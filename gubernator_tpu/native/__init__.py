"""Native (C++) runtime components, loaded via ctypes.

The compute path is JAX/XLA; the host runtime around it — here, the
key→slot table that front-ends every device tick — is C++ (built by the
Makefile in this directory).  Import degrades gracefully: when the shared
library is absent and can't be built, callers fall back to the pure-Python
SlotMap.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
from typing import List, Optional

import numpy as np

log = logging.getLogger("gubernator.native")

_DIR = os.path.dirname(__file__)
_SO = os.path.join(_DIR, "libguber_slotmap.so")
_lib: Optional[ctypes.CDLL] = None
_build_attempted = False


def _try_build() -> None:
    global _build_attempted
    if _build_attempted:
        return
    _build_attempted = True
    try:
        # guber: allow-G001(one-shot memoized toolchain build at first use - every later hot-path call hits the cached .so) # guber: allow-G007(same one-shot build - serialized behind _build_attempted, a cold-start cost, never steady-state)
        subprocess.run(
            ["make", "-C", _DIR, "-s"],
            check=True,
            capture_output=True,
            timeout=120,
        )
    except Exception as e:  # no toolchain / read-only install: fall back
        log.debug("native slotmap build failed: %s", e)


def load_library() -> Optional[ctypes.CDLL]:
    """The slotmap shared library, building it on first use if needed."""
    global _lib
    if _lib is not None:
        return _lib
    if not os.path.exists(_SO):
        _try_build()
    if not os.path.exists(_SO):
        return None
    lib = ctypes.CDLL(_SO)
    lib.guber_slotmap_new.restype = ctypes.c_void_p
    lib.guber_slotmap_new.argtypes = [ctypes.c_int64]
    lib.guber_slotmap_free.argtypes = [ctypes.c_void_p]
    lib.guber_slotmap_get.restype = ctypes.c_int64
    lib.guber_slotmap_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64]
    lib.guber_slotmap_assign.restype = ctypes.c_int64
    lib.guber_slotmap_assign.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64]
    lib.guber_slotmap_release.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.guber_slotmap_size.restype = ctypes.c_int64
    lib.guber_slotmap_size.argtypes = [ctypes.c_void_p]
    lib.guber_slotmap_key_of.restype = ctypes.c_int64
    lib.guber_slotmap_key_of.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_char_p, ctypes.c_int64,
    ]
    lib.guber_slotmap_resolve_batch.argtypes = [
        ctypes.c_void_p,
        ctypes.c_char_p,
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
        ctypes.c_int64,
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS"),
    ]
    lib.guber_slotmap_mapped.argtypes = [
        ctypes.c_void_p,
        np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS"),
    ]
    lib.guber_slotmap_release_batch.argtypes = [
        ctypes.c_void_p,
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
        ctypes.c_int64,
    ]
    lib.guber_slotmap_keys_batch.restype = ctypes.c_int64
    lib.guber_slotmap_keys_batch.argtypes = [
        ctypes.c_void_p,
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
        ctypes.c_int64,
        ctypes.c_char_p,
        ctypes.c_int64,
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
    ]
    lib.guber_slotmap_assign_batch.argtypes = [
        ctypes.c_void_p,
        ctypes.c_char_p,
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
        ctypes.c_int64,
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
    ]
    try:  # a stale prebuilt library may predate this symbol
        lib.guber_crc32_batch.argtypes = [
            ctypes.c_char_p,
            np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
            ctypes.c_int64,
            np.ctypeslib.ndpointer(np.uint32, flags="C_CONTIGUOUS"),
        ]
    except AttributeError:
        log.debug("native library lacks guber_crc32_batch; rebuild to get it")
    _lib = lib
    return lib


def as_char_p(blob):
    """A ``c_char_p``-compatible view of any bytes-like blob, copy-free
    for writable buffers (numpy views into a shared-memory slab, byte-
    arrays).  The native calls index strictly by (blob, offsets), so the
    missing NUL terminator of a raw buffer is irrelevant.  Read-only
    non-bytes buffers (rare: memoryview of bytes) fall back to one copy."""
    if isinstance(blob, (bytes, ctypes.Array)):
        return blob
    mv = memoryview(blob).cast("B")
    if mv.readonly:
        return mv.tobytes()
    return ctypes.cast(
        (ctypes.c_char * mv.nbytes).from_buffer(mv), ctypes.c_char_p
    )


def crc32_batch(blob, offsets: np.ndarray) -> np.ndarray:
    """zlib-compatible CRC-32 of every key in a packed (blob, offsets)
    pair — the mesh engine's vectorized key→shard router.  Falls back to
    a zlib loop when the native library is unavailable."""
    n = len(offsets) - 1
    lib = load_library()
    if lib is None or not hasattr(lib, "guber_crc32_batch"):
        import zlib

        mv = memoryview(blob)
        return np.fromiter(
            (zlib.crc32(mv[offsets[i]:offsets[i + 1]]) for i in range(n)),
            np.uint32, count=n,
        )
    offsets = np.ascontiguousarray(offsets, np.int64)
    out = np.empty(n, np.uint32)
    lib.guber_crc32_batch(as_char_p(blob), offsets, n, out)
    return out


class NativeSlotMap:
    """ctypes wrapper mirroring ops.engine.SlotMap, plus batch resolve."""

    def __init__(self, capacity: int):
        lib = load_library()
        if lib is None:
            raise RuntimeError("native slotmap library unavailable")
        self._lib = lib
        self.capacity = int(capacity)
        self._h = lib.guber_slotmap_new(self.capacity)
        self._keybuf = ctypes.create_string_buffer(4096)

    def __del__(self):
        h = getattr(self, "_h", None)
        if h:
            self._lib.guber_slotmap_free(h)
            self._h = None

    def __len__(self) -> int:
        return self._lib.guber_slotmap_size(self._h)

    def get(self, key: str) -> Optional[int]:
        b = key.encode()
        s = self._lib.guber_slotmap_get(self._h, b, len(b))
        return None if s < 0 else s

    def assign(self, key: str) -> Optional[int]:
        b = key.encode()
        s = self._lib.guber_slotmap_assign(self._h, b, len(b))
        return None if s < 0 else s

    def release(self, slot: int) -> None:
        self._lib.guber_slotmap_release(self._h, slot)

    def key_of(self, slot: int) -> Optional[str]:
        n = self._lib.guber_slotmap_key_of(
            self._h, slot, self._keybuf, len(self._keybuf)
        )
        return None if n < 0 else self._keybuf.raw[:n].decode()

    def mapped_mask(self) -> np.ndarray:
        """Boolean array over slots: True where a key is assigned."""
        out = np.empty(self.capacity, np.uint8)
        self._lib.guber_slotmap_mapped(self._h, out)
        return out.astype(bool)

    def resolve_batch(self, keys: List[bytes]):
        """(slots, known) for a batch of keys in one native call; slot -1
        means the table is full for that key."""
        from gubernator_tpu.ops.reqcols import pack_blob

        return self.resolve_blob(*pack_blob(keys))

    def resolve_blob(self, blob, offsets: np.ndarray):
        """resolve_batch on pre-packed (blob, offsets) — the columnar hot
        path's native call: no per-key Python at all.  ``blob`` may be any
        bytes-like buffer (a numpy view into a shared-memory slab included);
        non-bytes writable buffers are passed without copying."""
        n = len(offsets) - 1
        offsets = np.ascontiguousarray(offsets, np.int64)
        slots = np.empty(n, np.int64)
        known = np.empty(n, np.uint8)
        self._lib.guber_slotmap_resolve_batch(
            self._h, as_char_p(blob), offsets, n, slots, known
        )
        return slots, known

    def release_batch(self, slots: np.ndarray) -> None:
        """Release a batch of slots in one native call."""
        slots = np.ascontiguousarray(slots, np.int64)
        self._lib.guber_slotmap_release_batch(self._h, slots, len(slots))

    def keys_blob(self, slots: np.ndarray) -> tuple[bytes, np.ndarray]:
        """Keys of a batch of slots as one (blob, offsets) pair — the
        columnar snapshot format; unassigned slots span zero bytes."""
        slots = np.ascontiguousarray(slots, np.int64)
        n = len(slots)
        offsets = np.zeros(n + 1, np.int64)
        cap = max(4096, n * 64)
        while True:
            buf = ctypes.create_string_buffer(cap)
            need = self._lib.guber_slotmap_keys_batch(
                self._h, slots, n, buf, cap, offsets
            )
            if need <= cap:
                break
            cap = int(need)
        return buf.raw[: offsets[n]], offsets

    def keys_batch(self, slots: np.ndarray) -> List[bytes]:
        """Keys of a batch of slots (b"" for unassigned) in one native call."""
        blob, offsets = self.keys_blob(slots)
        mv = memoryview(blob)  # slice without copying the whole buffer
        return [bytes(mv[offsets[i] : offsets[i + 1]]) for i in range(len(slots))]

    def assign_blob(self, blob, offsets: np.ndarray) -> np.ndarray:
        """Assign keys packed as (blob, offsets); -1 = table full."""
        n = len(offsets) - 1
        offsets = np.ascontiguousarray(offsets, np.int64)
        out = np.empty(n, np.int64)
        self._lib.guber_slotmap_assign_batch(
            self._h, as_char_p(blob), offsets, n, out
        )
        return out

    def assign_batch(self, keys: List[bytes]) -> np.ndarray:
        """Assign a batch of keys in one native call; -1 = table full."""
        from gubernator_tpu.ops.reqcols import pack_blob

        return self.assign_blob(*pack_blob(keys))
