// Native key→slot table: the host-side hot path of the tick engine.
//
// The engine's device kernel is fast; what bounds end-to-end throughput is
// the per-request host work of resolving string keys to table slots (the
// role the reference's Go map + worker hash routing plays, lrucache.go /
// workers.go:180-184).  This is that path in C++: an open-addressing hash
// table (fnv1a, linear probing, tombstones) over a fixed slot arena, with a
// batch API so one C call resolves a whole tick's keys.
//
// Exposed as a plain C ABI for ctypes (no pybind11 in the image).

#include <cstdint>
#include <cstring>
#include <string>
#include <vector>

namespace {

constexpr int64_t kEmpty = -1;
constexpr int64_t kTomb = -2;

inline uint64_t fnv1a(const char* data, int64_t len) {
  uint64_t h = 0xcbf29ce484222325ULL;
  for (int64_t i = 0; i < len; ++i) {
    h ^= static_cast<unsigned char>(data[i]);
    h *= 0x100000001b3ULL;
  }
  return h;
}

inline uint64_t next_pow2(uint64_t v) {
  v--;
  v |= v >> 1; v |= v >> 2; v |= v >> 4;
  v |= v >> 8; v |= v >> 16; v |= v >> 32;
  return v + 1;
}

struct SlotMap {
  int64_t capacity;              // number of slots
  uint64_t mask;                 // hash table size - 1 (pow2, ≥ 2*capacity)
  std::vector<int64_t> table;    // hash bucket → slot | kEmpty | kTomb
  std::vector<uint64_t> hashes;  // per-bucket cached hash (valid when slot ≥ 0)
  std::vector<std::string> keys; // per-slot key (empty = unassigned)
  std::vector<int64_t> free_list;
  int64_t count = 0;
  int64_t tombs = 0;

  explicit SlotMap(int64_t cap) : capacity(cap) {
    uint64_t tsize = next_pow2(static_cast<uint64_t>(cap) * 2 + 16);
    mask = tsize - 1;
    table.assign(tsize, kEmpty);
    hashes.assign(tsize, 0);
    keys.resize(cap);
    free_list.reserve(cap);
    for (int64_t s = cap - 1; s >= 0; --s) free_list.push_back(s);
  }

  // Find the bucket holding key, or the first insertable bucket.
  // Returns (bucket, found).
  std::pair<uint64_t, bool> probe(const char* key, int64_t len,
                                  uint64_t h) const {
    uint64_t idx = h & mask;
    uint64_t first_tomb = UINT64_MAX;
    for (;;) {
      int64_t s = table[idx];
      if (s == kEmpty) {
        return {first_tomb != UINT64_MAX ? first_tomb : idx, false};
      }
      if (s == kTomb) {
        if (first_tomb == UINT64_MAX) first_tomb = idx;
      } else if (hashes[idx] == h &&
                 keys[s].size() == static_cast<size_t>(len) &&
                 std::memcmp(keys[s].data(), key, len) == 0) {
        return {idx, true};
      }
      idx = (idx + 1) & mask;
    }
  }

  void maybe_rehash() {
    // Tombstone buildup degrades probes; rebuild in place when they
    // outnumber live entries.
    if (tombs < static_cast<int64_t>(mask / 4)) return;
    std::fill(table.begin(), table.end(), kEmpty);
    tombs = 0;
    for (int64_t s = 0; s < capacity; ++s) {
      if (keys[s].empty()) continue;
      uint64_t h = fnv1a(keys[s].data(), keys[s].size());
      uint64_t idx = h & mask;
      while (table[idx] >= 0) idx = (idx + 1) & mask;
      table[idx] = s;
      hashes[idx] = h;
    }
  }

  int64_t get(const char* key, int64_t len) const {
    auto [idx, found] = probe(key, len, fnv1a(key, len));
    return found ? table[idx] : -1;
  }

  int64_t assign(const char* key, int64_t len) {
    uint64_t h = fnv1a(key, len);
    auto [idx, found] = probe(key, len, h);
    if (found) return table[idx];
    if (free_list.empty()) return -1;
    int64_t s = free_list.back();
    free_list.pop_back();
    if (table[idx] == kTomb) --tombs;
    table[idx] = s;
    hashes[idx] = h;
    keys[s].assign(key, len);
    ++count;
    return s;
  }

  void release(int64_t slot) {
    if (slot < 0 || slot >= capacity || keys[slot].empty()) return;
    uint64_t h = fnv1a(keys[slot].data(), keys[slot].size());
    auto [idx, found] = probe(keys[slot].data(), keys[slot].size(), h);
    if (found) {
      table[idx] = kTomb;
      ++tombs;
    }
    keys[slot].clear();
    free_list.push_back(slot);
    --count;
    maybe_rehash();
  }
};

}  // namespace

extern "C" {

void* guber_slotmap_new(int64_t capacity) { return new SlotMap(capacity); }

void guber_slotmap_free(void* p) { delete static_cast<SlotMap*>(p); }

int64_t guber_slotmap_get(void* p, const char* key, int64_t len) {
  return static_cast<SlotMap*>(p)->get(key, len);
}

int64_t guber_slotmap_assign(void* p, const char* key, int64_t len) {
  return static_cast<SlotMap*>(p)->assign(key, len);
}

void guber_slotmap_release(void* p, int64_t slot) {
  static_cast<SlotMap*>(p)->release(slot);
}

int64_t guber_slotmap_size(void* p) { return static_cast<SlotMap*>(p)->count; }

// Copy slot's key into buf (≤ buflen bytes); returns key length or -1.
int64_t guber_slotmap_key_of(void* p, int64_t slot, char* buf, int64_t buflen) {
  auto* m = static_cast<SlotMap*>(p);
  if (slot < 0 || slot >= m->capacity || m->keys[slot].empty()) return -1;
  const std::string& k = m->keys[slot];
  int64_t n = static_cast<int64_t>(k.size());
  if (n > buflen) return -1;
  std::memcpy(buf, k.data(), n);
  return n;
}

// Batch resolve: keys arrive as one concatenated blob with n+1 offsets.
// out_slots[i] = slot (or -1 when the table is full); out_known[i] = 1 when
// the key already had a mapping.  One call per tick replaces n dict lookups.
void guber_slotmap_resolve_batch(void* p, const char* blob,
                                 const int64_t* offsets, int64_t n,
                                 int64_t* out_slots, uint8_t* out_known) {
  auto* m = static_cast<SlotMap*>(p);
  for (int64_t i = 0; i < n; ++i) {
    const char* key = blob + offsets[i];
    int64_t len = offsets[i + 1] - offsets[i];
    int64_t existing = m->get(key, len);
    if (existing >= 0) {
      out_slots[i] = existing;
      out_known[i] = 1;
    } else {
      out_slots[i] = m->assign(key, len);
      out_known[i] = 0;
    }
  }
}

// Fill out[slot] = 1 for every slot that currently has a key (the engine's
// reclaim scan wants the live-slot mask as one array).
void guber_slotmap_mapped(void* p, uint8_t* out) {
  auto* m = static_cast<SlotMap*>(p);
  for (int64_t s = 0; s < m->capacity; ++s) out[s] = !m->keys[s].empty();
}

// Release a batch of slots in one call (reclaim's victim free list; the
// per-slot ctypes round trip dominates at 10M-slot scale otherwise).
void guber_slotmap_release_batch(void* p, const int64_t* slots, int64_t n) {
  auto* m = static_cast<SlotMap*>(p);
  for (int64_t i = 0; i < n; ++i) m->release(slots[i]);
}

// Copy the keys of n slots into one concatenated blob + n+1 offsets
// (snapshot export).  Returns total bytes required; when that exceeds
// blob_cap nothing is written and the caller retries with a bigger buffer.
// Unassigned slots contribute zero-length spans.
int64_t guber_slotmap_keys_batch(void* p, const int64_t* slots, int64_t n,
                                 char* blob, int64_t blob_cap,
                                 int64_t* offsets) {
  auto* m = static_cast<SlotMap*>(p);
  int64_t total = 0;
  for (int64_t i = 0; i < n; ++i) {
    int64_t s = slots[i];
    if (s >= 0 && s < m->capacity) total += m->keys[s].size();
  }
  if (total > blob_cap) return total;
  int64_t off = 0;
  for (int64_t i = 0; i < n; ++i) {
    offsets[i] = off;
    int64_t s = slots[i];
    if (s >= 0 && s < m->capacity && !m->keys[s].empty()) {
      std::memcpy(blob + off, m->keys[s].data(), m->keys[s].size());
      off += m->keys[s].size();
    }
  }
  offsets[n] = off;
  return total;
}

// Assign a batch of keys (snapshot restore); out_slots[i] = slot or -1 when
// the table is full.
void guber_slotmap_assign_batch(void* p, const char* blob,
                                const int64_t* offsets, int64_t n,
                                int64_t* out_slots) {
  auto* m = static_cast<SlotMap*>(p);
  for (int64_t i = 0; i < n; ++i) {
    out_slots[i] = m->assign(blob + offsets[i], offsets[i + 1] - offsets[i]);
  }
}

// CRC-32 (ISO-HDLC: poly 0xEDB88320, init/xorout 0xFFFFFFFF) over each key
// of a packed blob — bit-identical to Python's zlib.crc32, which the mesh
// engine's key->shard router is defined by.  One call replaces a
// per-key Python loop on the columnar submit path.
static uint32_t crc32_table[256];
static bool crc32_init_done = [] {
  for (uint32_t i = 0; i < 256; ++i) {
    uint32_t c = i;
    for (int k = 0; k < 8; ++k) c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
    crc32_table[i] = c;
  }
  return true;
}();

void guber_crc32_batch(const char* blob, const int64_t* offsets, int64_t n,
                       uint32_t* out) {
  for (int64_t i = 0; i < n; ++i) {
    uint32_t c = 0xFFFFFFFFu;
    for (int64_t j = offsets[i]; j < offsets[i + 1]; ++j) {
      c = crc32_table[(c ^ static_cast<uint8_t>(blob[j])) & 0xFFu] ^ (c >> 8);
    }
    out[i] = c ^ 0xFFFFFFFFu;
  }
}

}  // extern "C"
