// Native protobuf wire codec for the serving hot path.
//
// The gRPC edge's cost is NOT the device tick (~0.1 ms for 4K requests)
// but the per-request Python: materializing 1000 pb message objects and
// walking their attributes costs ~1.5 ms per batch, and building the
// response objects another ~1.4 ms (scripts/service_profile.py).  This
// codec parses the serialized GetRateLimitsReq straight into int64
// columns + a packed key blob (the engine's ReqColumns layout,
// ops/reqcols.py) and the GetRateLimitsResp wire bytes straight from the
// (5, n) response matrix — no message objects on either side.
//
// Wire contract (gubernator.proto; field numbers preserved from the
// reference's python/gubernator/gubernator.proto):
//
//   GetRateLimitsReq:  1 repeated RateLimitReq (len-delimited)
//   RateLimitReq:      1 name (string), 2 unique_key (string),
//                      3 hits, 4 limit, 5 duration (varint int64),
//                      6 algorithm, 7 behavior (varint enum),
//                      8 burst (varint int64), 9 metadata (map),
//                      10 created_at (optional varint int64)
//   GetRateLimitsResp: 1 repeated RateLimitResp (len-delimited)
//   RateLimitResp:     1 status (varint enum), 2 limit, 3 remaining,
//                      4 reset_time (varint int64), 5 error (string),
//                      6 metadata (map)
//
// Unknown fields are skipped by wire type (forward compatibility, the
// same guarantee protobuf gives).  Malformed input returns a negative
// count and the caller falls back to the protobuf library parser.

#include <cstdint>
#include <cstring>

namespace {

struct Reader {
  const uint8_t* p;
  const uint8_t* end;
  bool ok = true;

  uint64_t varint() {
    uint64_t v = 0;
    int shift = 0;
    while (p < end && shift < 64) {
      uint8_t b = *p++;
      v |= static_cast<uint64_t>(b & 0x7F) << shift;
      if (!(b & 0x80)) return v;
      shift += 7;
    }
    ok = false;
    return 0;
  }

  // Skip one field of the given wire type; groups (3/4) and unknown
  // types are malformed here.
  void skip(uint32_t wt) {
    switch (wt) {
      case 0: varint(); break;
      case 1: if (end - p < 8) ok = false; else p += 8; break;
      case 2: {
        uint64_t n = varint();
        if (!ok || static_cast<uint64_t>(end - p) < n) { ok = false; break; }
        p += n;
        break;
      }
      case 5: if (end - p < 4) ok = false; else p += 4; break;
      default: ok = false;
    }
  }
};

struct Writer {
  uint8_t* p;
  uint8_t* end;
  bool ok = true;

  void varint(uint64_t v) {
    while (true) {
      if (p >= end) { ok = false; return; }
      if (v < 0x80) { *p++ = static_cast<uint8_t>(v); return; }
      *p++ = static_cast<uint8_t>(v) | 0x80;
      v >>= 7;
    }
  }

  void bytes(const uint8_t* src, int64_t n) {
    if (end - p < n) { ok = false; return; }
    std::memcpy(p, src, n);
    p += n;
  }
};

inline int varint_size(uint64_t v) {
  int n = 1;
  while (v >= 0x80) { v >>= 7; ++n; }
  return n;
}

}  // namespace

extern "C" {

// Flag bits in out_flags.
enum : uint8_t {
  kNameEmpty = 1,
  kKeyEmpty = 2,
  kHasMetadata = 4,
  kHasCreatedAt = 8,
};

// Count the repeated field-1 submessages of a GetRateLimitsReq /
// GetRateLimitsResp (identical outer shape).  Returns -1 on malformed
// input.
int64_t guber_wire_count(const uint8_t* buf, int64_t len) {
  Reader r{buf, buf + len};
  int64_t n = 0;
  while (r.p < r.end) {
    uint64_t tag = r.varint();
    if (!r.ok) return -1;
    if (tag == ((1u << 3) | 2)) {
      uint64_t sz = r.varint();
      if (!r.ok || static_cast<uint64_t>(r.end - r.p) < sz) return -1;
      r.p += sz;
      ++n;
    } else {
      r.skip(tag & 7);
      if (!r.ok) return -1;
    }
  }
  return n;
}

// Parse a serialized GetRateLimitsReq into columns.
//
//   key_blob   caller buffer of at least len + n bytes ("name_unique");
//   key_off    (n+1) int64 offsets into key_blob;
//   name_len   n int64: byte length of the name part of each key (the
//              '_' splitter position — lets an encoder reconstruct the
//              two wire fields from the packed key);
//   cols       7 arrays of n int64: hits, limit, duration, algorithm,
//              behavior, burst, created_at (created_at left as-is where
//              absent — caller pre-fills the sentinel);
//   out_flags  n uint8 of kNameEmpty/kKeyEmpty/kHasMetadata/kHasCreatedAt.
//
// Returns the number of requests parsed (== guber_wire_count) or -1 on
// malformed input.  Metadata contents are NOT decoded (the caller routes
// metadata-bearing batches to the object path, which re-parses with
// protobuf); only presence is recorded.
int64_t guber_parse_req(const uint8_t* buf, int64_t len,
                        uint8_t* key_blob, int64_t key_cap,
                        int64_t* key_off, int64_t* name_len_out,
                        int64_t* hits, int64_t* limit, int64_t* duration,
                        int64_t* algorithm, int64_t* behavior,
                        int64_t* burst, int64_t* created_at,
                        uint8_t* out_flags) {
  Reader outer{buf, buf + len};
  int64_t n = 0;
  int64_t blob_at = 0;
  key_off[0] = 0;
  while (outer.p < outer.end) {
    uint64_t tag = outer.varint();
    if (!outer.ok) return -1;
    if (tag != ((1u << 3) | 2)) {
      outer.skip(tag & 7);
      if (!outer.ok) return -1;
      continue;
    }
    uint64_t sz = outer.varint();
    if (!outer.ok || static_cast<uint64_t>(outer.end - outer.p) < sz)
      return -1;
    Reader r{outer.p, outer.p + sz};
    outer.p += sz;

    const uint8_t* name_p = nullptr;
    int64_t name_n = 0;
    const uint8_t* key_p = nullptr;
    int64_t key_n = 0;
    uint8_t flags = 0;
    while (r.p < r.end) {
      uint64_t t = r.varint();
      if (!r.ok) return -1;
      uint32_t field = static_cast<uint32_t>(t >> 3);
      uint32_t wt = t & 7;
      if (wt == 2 && (field == 1 || field == 2 || field == 9)) {
        uint64_t fn = r.varint();
        if (!r.ok || static_cast<uint64_t>(r.end - r.p) < fn) return -1;
        if (field == 1) { name_p = r.p; name_n = fn; }
        else if (field == 2) { key_p = r.p; key_n = fn; }
        else flags |= kHasMetadata;
        r.p += fn;
      } else if (wt == 0 && field >= 3 && field <= 10 && field != 9) {
        uint64_t v = r.varint();
        if (!r.ok) return -1;
        int64_t sv = static_cast<int64_t>(v);
        switch (field) {
          case 3: hits[n] = sv; break;
          case 4: limit[n] = sv; break;
          case 5: duration[n] = sv; break;
          case 6: algorithm[n] = sv; break;
          case 7: behavior[n] = sv; break;
          case 8: burst[n] = sv; break;
          case 10: created_at[n] = sv; flags |= kHasCreatedAt; break;
        }
      } else {
        r.skip(wt);
        if (!r.ok) return -1;
      }
    }
    if (name_n == 0) flags |= kNameEmpty;
    if (key_n == 0) flags |= kKeyEmpty;
    name_len_out[n] = name_n;
    if (!(flags & (kNameEmpty | kKeyEmpty))) {
      if (blob_at + name_n + 1 + key_n > key_cap) return -1;
      std::memcpy(key_blob + blob_at, name_p, name_n);
      blob_at += name_n;
      key_blob[blob_at++] = '_';
      std::memcpy(key_blob + blob_at, key_p, key_n);
      blob_at += key_n;
    }
    out_flags[n] = flags;
    ++n;
    key_off[n] = blob_at;
  }
  return n;
}

// Parse a serialized GetRateLimitsResp (or GetPeerRateLimitsResp — same
// shape, field 1 repeated RateLimitResp) into a (5, n) column block:
// status, limit, remaining, reset_time, and a has-error flag (1 when the
// item carries a non-empty error string or metadata — the caller
// re-parses those rare items with protobuf for the strings).
// Returns n or -1 on malformed input.
int64_t guber_parse_resp(const uint8_t* buf, int64_t len,
                         int64_t* status, int64_t* limit,
                         int64_t* remaining, int64_t* reset_time,
                         uint8_t* special) {
  Reader outer{buf, buf + len};
  int64_t n = 0;
  while (outer.p < outer.end) {
    uint64_t tag = outer.varint();
    if (!outer.ok) return -1;
    if (tag != ((1u << 3) | 2)) {
      outer.skip(tag & 7);
      if (!outer.ok) return -1;
      continue;
    }
    uint64_t sz = outer.varint();
    if (!outer.ok || static_cast<uint64_t>(outer.end - outer.p) < sz)
      return -1;
    Reader r{outer.p, outer.p + sz};
    outer.p += sz;
    status[n] = limit[n] = remaining[n] = reset_time[n] = 0;
    special[n] = 0;
    while (r.p < r.end) {
      uint64_t t = r.varint();
      if (!r.ok) return -1;
      uint32_t field = static_cast<uint32_t>(t >> 3);
      uint32_t wt = t & 7;
      if (wt == 0 && field >= 1 && field <= 4) {
        uint64_t v = r.varint();
        if (!r.ok) return -1;
        int64_t sv = static_cast<int64_t>(v);
        switch (field) {
          case 1: status[n] = sv; break;
          case 2: limit[n] = sv; break;
          case 3: remaining[n] = sv; break;
          case 4: reset_time[n] = sv; break;
        }
      } else if (wt == 2 && (field == 5 || field == 6)) {
        uint64_t fn = r.varint();
        if (!r.ok || static_cast<uint64_t>(r.end - r.p) < fn) return -1;
        if (fn > 0) special[n] = 1;
        r.p += fn;
      } else {
        r.skip(wt);
        if (!r.ok) return -1;
      }
    }
    ++n;
  }
  return n;
}

// Serialize a GetRateLimitsReq (or GetPeerRateLimitsReq — same shape)
// from columns.  Key blob carries "name_unique" per request with the
// SPLIT position given separately (name_len[i]); proto3 zero-valued
// scalar fields are omitted; created_at is written when has_created[i]
// (optional presence).  Returns bytes written, or -needed when the
// buffer is too small (caller retries with a bigger one), or -1 on
// internal error.
int64_t guber_encode_req(const uint8_t* key_blob, const int64_t* key_off,
                         const int64_t* name_len,
                         const int64_t* hits, const int64_t* limit,
                         const int64_t* duration, const int64_t* algorithm,
                         const int64_t* behavior, const int64_t* burst,
                         const int64_t* created_at,
                         const uint8_t* has_created,
                         int64_t n, uint8_t* out, int64_t out_cap) {
  // Sizing pass.
  int64_t total = 0;
  for (int64_t i = 0; i < n; ++i) {
    int64_t nm = name_len[i];
    int64_t uk = key_off[i + 1] - key_off[i] - nm - 1;
    if (uk < 0) return -1;
    int64_t sz = 0;
    if (nm) sz += 1 + varint_size(nm) + nm;
    if (uk) sz += 1 + varint_size(uk) + uk;
    if (hits[i]) sz += 1 + varint_size(static_cast<uint64_t>(hits[i]));
    if (limit[i]) sz += 1 + varint_size(static_cast<uint64_t>(limit[i]));
    if (duration[i])
      sz += 1 + varint_size(static_cast<uint64_t>(duration[i]));
    if (algorithm[i])
      sz += 1 + varint_size(static_cast<uint64_t>(algorithm[i]));
    if (behavior[i])
      sz += 1 + varint_size(static_cast<uint64_t>(behavior[i]));
    if (burst[i]) sz += 1 + varint_size(static_cast<uint64_t>(burst[i]));
    if (has_created[i])
      sz += 1 + varint_size(static_cast<uint64_t>(created_at[i]));
    total += 1 + varint_size(sz) + sz;
  }
  if (total > out_cap) return -total;

  Writer w{out, out + out_cap};
  for (int64_t i = 0; i < n; ++i) {
    int64_t nm = name_len[i];
    int64_t uk = key_off[i + 1] - key_off[i] - nm - 1;
    const uint8_t* base = key_blob + key_off[i];
    int64_t sz = 0;
    if (nm) sz += 1 + varint_size(nm) + nm;
    if (uk) sz += 1 + varint_size(uk) + uk;
    if (hits[i]) sz += 1 + varint_size(static_cast<uint64_t>(hits[i]));
    if (limit[i]) sz += 1 + varint_size(static_cast<uint64_t>(limit[i]));
    if (duration[i])
      sz += 1 + varint_size(static_cast<uint64_t>(duration[i]));
    if (algorithm[i])
      sz += 1 + varint_size(static_cast<uint64_t>(algorithm[i]));
    if (behavior[i])
      sz += 1 + varint_size(static_cast<uint64_t>(behavior[i]));
    if (burst[i]) sz += 1 + varint_size(static_cast<uint64_t>(burst[i]));
    if (has_created[i])
      sz += 1 + varint_size(static_cast<uint64_t>(created_at[i]));

    w.varint((1u << 3) | 2);
    w.varint(sz);
    if (nm) { w.varint((1u << 3) | 2); w.varint(nm); w.bytes(base, nm); }
    if (uk) {
      w.varint((2u << 3) | 2);
      w.varint(uk);
      w.bytes(base + nm + 1, uk);
    }
    if (hits[i]) {
      w.varint((3u << 3) | 0);
      w.varint(static_cast<uint64_t>(hits[i]));
    }
    if (limit[i]) {
      w.varint((4u << 3) | 0);
      w.varint(static_cast<uint64_t>(limit[i]));
    }
    if (duration[i]) {
      w.varint((5u << 3) | 0);
      w.varint(static_cast<uint64_t>(duration[i]));
    }
    if (algorithm[i]) {
      w.varint((6u << 3) | 0);
      w.varint(static_cast<uint64_t>(algorithm[i]));
    }
    if (behavior[i]) {
      w.varint((7u << 3) | 0);
      w.varint(static_cast<uint64_t>(behavior[i]));
    }
    if (burst[i]) {
      w.varint((8u << 3) | 0);
      w.varint(static_cast<uint64_t>(burst[i]));
    }
    if (has_created[i]) {
      w.varint((10u << 3) | 0);
      w.varint(static_cast<uint64_t>(created_at[i]));
    }
    if (!w.ok) return -1;
  }
  return w.p - out;
}

// Serialize a GetRateLimitsResp from the engine's (5, n) response
// matrix rows (status, limit, remaining, reset_time; row 4 over_limit is
// not a wire field).  Proto3 zero-omission matches the protobuf library
// byte for byte for items with no error/metadata.  Returns bytes
// written or -needed when out_cap is too small.
int64_t guber_encode_resp(const int64_t* status, const int64_t* limit,
                          const int64_t* remaining,
                          const int64_t* reset_time,
                          int64_t n, uint8_t* out, int64_t out_cap) {
  int64_t total = 0;
  for (int64_t i = 0; i < n; ++i) {
    int64_t sz = 0;
    if (status[i]) sz += 1 + varint_size(static_cast<uint64_t>(status[i]));
    if (limit[i]) sz += 1 + varint_size(static_cast<uint64_t>(limit[i]));
    if (remaining[i])
      sz += 1 + varint_size(static_cast<uint64_t>(remaining[i]));
    if (reset_time[i])
      sz += 1 + varint_size(static_cast<uint64_t>(reset_time[i]));
    total += 1 + varint_size(sz) + sz;
  }
  if (total > out_cap) return -total;
  Writer w{out, out + out_cap};
  for (int64_t i = 0; i < n; ++i) {
    int64_t sz = 0;
    if (status[i]) sz += 1 + varint_size(static_cast<uint64_t>(status[i]));
    if (limit[i]) sz += 1 + varint_size(static_cast<uint64_t>(limit[i]));
    if (remaining[i])
      sz += 1 + varint_size(static_cast<uint64_t>(remaining[i]));
    if (reset_time[i])
      sz += 1 + varint_size(static_cast<uint64_t>(reset_time[i]));
    w.varint((1u << 3) | 2);
    w.varint(sz);
    if (status[i]) {
      w.varint((1u << 3) | 0);
      w.varint(static_cast<uint64_t>(status[i]));
    }
    if (limit[i]) {
      w.varint((2u << 3) | 0);
      w.varint(static_cast<uint64_t>(limit[i]));
    }
    if (remaining[i]) {
      w.varint((3u << 3) | 0);
      w.varint(static_cast<uint64_t>(remaining[i]));
    }
    if (reset_time[i]) {
      w.varint((4u << 3) | 0);
      w.varint(static_cast<uint64_t>(reset_time[i]));
    }
    if (!w.ok) return -1;
  }
  return w.p - out;
}

}  // extern "C"
