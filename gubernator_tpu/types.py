"""Core wire-level types: algorithms, behaviors, status, request/response.

Mirrors the reference protobuf contract (``gubernator.proto:56-203``):
``Algorithm{TOKEN_BUCKET=0, LEAKY_BUCKET=1}``, ``Behavior`` bitflags,
``Status{UNDER_LIMIT=0, OVER_LIMIT=1}``, ``RateLimitReq`` / ``RateLimitResp``
fields (snake_case JSON names are preserved by the gateway layer).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Dict, Optional


class Algorithm(enum.IntEnum):
    TOKEN_BUCKET = 0
    LEAKY_BUCKET = 1
    # Algorithm-zoo extensions (gubernator_tpu/algos/): same SoA table,
    # same dispatch, selected per-lane by this column.
    SLIDING_WINDOW = 2
    GCRA = 3
    CONCURRENCY = 4


# Highest wire-valid Algorithm value; anything outside [0, ALGORITHM_MAX]
# is rejected at the edge with INVALID_ARGUMENT (never silently treated
# as token-bucket by the select tree).
ALGORITHM_MAX = max(Algorithm)


class Behavior(enum.IntFlag):
    """Bitflags controlling per-request behavior (gubernator.proto:63-135).

    BATCHING is the zero value (default); flags combine with ``|``.
    """

    BATCHING = 0
    NO_BATCHING = 1
    GLOBAL = 2
    DURATION_IS_GREGORIAN = 4
    RESET_REMAINING = 8
    MULTI_REGION = 16
    DRAIN_OVER_LIMIT = 32


class Status(enum.IntEnum):
    UNDER_LIMIT = 0
    OVER_LIMIT = 1


# Gregorian interval selectors carried in `duration` when
# DURATION_IS_GREGORIAN is set (reference interval.go:74-81).
GREGORIAN_MINUTES = 0
GREGORIAN_HOURS = 1
GREGORIAN_DAYS = 2
GREGORIAN_WEEKS = 3
GREGORIAN_MONTHS = 4
GREGORIAN_YEARS = 5

# Hard cap on items per GetRateLimits call (reference gubernator.go:39-40).
MAX_BATCH_SIZE = 1000


@dataclass
class RateLimitRequest:
    """One rate-limit check (reference RateLimitReq, gubernator.proto:137-183)."""

    name: str = ""
    unique_key: str = ""
    hits: int = 0
    limit: int = 0
    duration: int = 0  # milliseconds (or Gregorian selector)
    algorithm: int = Algorithm.TOKEN_BUCKET
    behavior: int = Behavior.BATCHING
    burst: int = 0
    metadata: Dict[str, str] = field(default_factory=dict)
    created_at: Optional[int] = None  # epoch ms; stamped by server when None
    # Absolute local-monotonic admission deadline (seconds), stamped at
    # the serving edge (docs/overload.md).  Never serialized: the wire
    # carries the relative budget via guber-deadline-ms metadata.
    deadline: Optional[float] = None

    def hash_key(self) -> str:
        """The cluster-sharding key: ``name_uniquekey`` (reference client.go:39-41)."""
        return self.name + "_" + self.unique_key


@dataclass
class RateLimitResponse:
    """Result of one rate-limit check (reference RateLimitResp)."""

    status: int = Status.UNDER_LIMIT
    limit: int = 0
    remaining: int = 0
    reset_time: int = 0
    error: str = ""
    metadata: Dict[str, str] = field(default_factory=dict)


@dataclass
class GlobalUpdate:
    """Owner-pushed authoritative GLOBAL bucket state
    (reference UpdatePeerGlobal, peers.proto:52-72)."""

    key: str
    status: "RateLimitResponse"
    algorithm: int = Algorithm.TOKEN_BUCKET
    duration: int = 0
    created_at: int = 0


@dataclass(frozen=True)
class PeerInfo:
    """One cluster member (reference config.go:161-175)."""

    grpc_address: str = ""
    http_address: str = ""
    datacenter: str = ""
    is_owner: bool = False  # set only on the local instance's own entry

    def hash_key(self) -> str:
        """Ring identity of the peer (reference HashKey() = GRPCAddress)."""
        return self.grpc_address


@dataclass
class HealthCheckResponse:
    status: str = "healthy"
    message: str = ""
    peer_count: int = 0


def has_behavior(behavior: int, flag: int) -> bool:
    """Bitflag test (reference gubernator.go:776-781).

    Like the reference's ``b & flag != 0``: always False for the zero-valued
    BATCHING flag — batching is decided by the *absence* of NO_BATCHING.
    """
    return bool(behavior & flag)


def set_behavior(behavior: int, flag: int, on: bool) -> int:
    """Bitflag set/clear (reference gubernator.go:783-788)."""
    return (behavior | flag) if on else (behavior & ~flag)
