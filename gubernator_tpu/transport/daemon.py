"""Daemon: gRPC server + HTTP-JSON gateway + metrics + lifecycle.

The transport shell (reference ``daemon.go``): one grpc.aio server exposing
``V1`` and ``PeersV1``, an aiohttp JSON gateway mirroring grpc-gateway's
snake_case marshaling (``daemon.go:245-261``), ``/metrics`` in Prometheus
text format, an optional plaintext status listener when mTLS is on
(``daemon.go:305-334``), TLS/mTLS incl. AutoTLS, discovery-pool wiring
(``daemon.go:208-243``), and ``wait_for_connect`` readiness
(``daemon.go:451-488``).
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import List, Optional, Sequence

import grpc
import grpc.aio
from aiohttp import web
from google.protobuf import json_format

from gubernator_tpu.admission import (
    DEADLINE_METADATA_KEY,
    deadline_from_header,
)
from gubernator_tpu.config import DaemonConfig, env_knob
from gubernator_tpu.ops.reqcols import IngestOverloadError
from gubernator_tpu.pb import gubernator_pb2 as pb
from gubernator_tpu.pb import peers_pb2 as peers_pb
from gubernator_tpu.resilience.supervisor import spawn_supervised
from gubernator_tpu.service.instance import (
    BatchTooLargeError,
    InstanceConfig,
    V1Instance,
)
from gubernator_tpu.transport import convert, fastwire
from gubernator_tpu.transport.grpc_api import V1Stub, peers_handler, v1_handler
from gubernator_tpu.transport.tlsutil import TLSBundle, setup_tls
from gubernator_tpu.types import GlobalUpdate, PeerInfo
from gubernator_tpu.utils import flightrec, tracing
from gubernator_tpu.utils.metrics import Metrics

log = logging.getLogger("gubernator.daemon")

MAX_RECV_BYTES = 1024 * 1024  # 1 MiB, daemon.go:120-126


class _StatsInterceptor(grpc.aio.ServerInterceptor):
    """Per-RPC count/duration metrics (reference grpc_stats.go:41-121)."""

    def __init__(self, metrics: Metrics):
        self.metrics = metrics

    async def intercept_service(self, continuation, handler_call_details):
        handler = await continuation(handler_call_details)
        if handler is None or handler.unary_unary is None:
            return handler
        method = handler_call_details.method
        inner = handler.unary_unary
        metrics = self.metrics

        async def wrapped(request, context):
            t0 = time.perf_counter()
            failed = False
            try:
                return await inner(request, context)
            except Exception:
                failed = True
                raise
            finally:
                dt = time.perf_counter() - t0
                metrics.grpc_request_duration.labels(method=method).observe(dt)
                # Histogram family with log-spaced buckets: the Summary
                # above keeps reference-catalog parity; the histogram is
                # what per-method p99 dashboards and exemplar linkage
                # read (docs/observability.md).
                metrics.grpc_duration_hist.labels(method=method).observe(dt)
                metrics.grpc_request_counts.labels(
                    status="failed" if failed else "success", method=method
                ).inc()

        return grpc.unary_unary_rpc_method_handler(
            wrapped,
            request_deserializer=handler.request_deserializer,
            response_serializer=handler.response_serializer,
        )


class _TraceInterceptor(grpc.aio.ServerInterceptor):
    """Server span per RPC, continuing a caller's trace when the gRPC
    request metadata carries a W3C ``traceparent`` header (the reference's
    otelgrpc server stats handler, daemon.go:125)."""

    async def intercept_service(self, continuation, handler_call_details):
        handler = await continuation(handler_call_details)
        if handler is None or handler.unary_unary is None:
            return handler
        if not tracing.enabled():  # per-RPC check: dynamic enable still works
            return handler
        method = handler_call_details.method
        parent = tracing.extract(
            {k: v for k, v in (handler_call_details.invocation_metadata or ())
             if isinstance(v, str)}
        )
        inner = handler.unary_unary

        async def wrapped(request, context):
            with tracing.maybe_span(f"grpc.recv{method.replace('/', '.')}",
                                    parent=parent):
                return await inner(request, context)

        return grpc.unary_unary_rpc_method_handler(
            wrapped,
            request_deserializer=handler.request_deserializer,
            response_serializer=handler.response_serializer,
        )


async def _parse_pb(msg_type, raw: bytes, context):
    """Protobuf-parse raw request bytes; malformed input aborts with
    INVALID_ARGUMENT (the status a deserializer failure produced before
    the pass-through deserializers moved parsing into the servicers —
    without this, DecodeError would surface as UNKNOWN plus a server
    traceback per bad request)."""
    try:
        return msg_type.FromString(raw)
    except Exception as e:
        await context.abort(
            grpc.StatusCode.INVALID_ARGUMENT,
            f"failed to parse {msg_type.DESCRIPTOR.name}: {e}",
        )


def _item_responses(mat, errs):
    """Fallback per-item pb responses when a columnar batch carried
    per-item engine errors (rare; carries strings)."""
    status, limit, remaining, reset = (mat[r].tolist() for r in range(4))
    return [
        pb.RateLimitResp(error=errs[i])
        if i in errs
        else pb.RateLimitResp(
            status=status[i],
            limit=limit[i],
            remaining=remaining[i],
            reset_time=reset[i],
        )
        for i in range(len(status))
    ]


def _edge_deadline(context, default_timeout: float):
    """The absolute local admission deadline for one inbound RPC
    (docs/overload.md): an explicit ``guber-deadline-ms`` budget header
    wins (peer hops propagate remaining budget this way, clock-skew
    free), else the caller's own gRPC deadline, else the
    GUBER_REQUEST_TIMEOUT default.  None (never shed) only when the
    default is 0."""
    now = time.monotonic()
    value = None
    try:
        for k, v in context.invocation_metadata() or ():
            if k == DEADLINE_METADATA_KEY:
                value = v
                break
    except Exception:
        pass
    d = deadline_from_header(value, now)
    if d is not None:
        return d
    try:
        rem = context.time_remaining()
    except Exception:
        rem = None
    if rem is not None:
        return now + rem
    if default_timeout > 0:
        return now + default_timeout
    return None


def _sync_arena_metrics(arena, metrics) -> None:
    """Mirror the arena's plain-int fallback counter into the
    gubernator_tpu_arena_fallbacks family (delta sync, the tick loop's
    engine-counter pattern)."""
    if arena is None or metrics is None:
        return
    synced = getattr(arena, "_synced_fallbacks", 0)
    if arena.metric_fallbacks > synced:
        metrics.arena_fallbacks.inc(arena.metric_fallbacks - synced)
        arena._synced_fallbacks = arena.metric_fallbacks


async def _raw_columns_edge(raw, context, gate_ok, tick, msg_type,
                            arena=None, deadline=None, metrics=None):
    """The shared raw-bytes fast path of both rate-limit edges: native
    wire parse → columns → device tick → native wire encode, with no
    protobuf objects.  Returns ``(result, msg)``: ``result`` is the
    response (bytes, or a per-item response list for the error
    fallback) or None when the batch needs the object path; ``msg`` is
    the protobuf message if one was already parsed along the way (so
    the caller's object path doesn't parse twice).

    ``arena`` (the instance's ingest ColumnArena) makes the decode land
    in a preallocated slab — zero per-batch allocation.  The tick loop
    releases the slab after packing; batches that bail to the object
    path release it here."""
    msg = None
    if gate_ok:
        # Flight-recorder transport edges: per-batch decode/encode CPU
        # (folded into window records — see utils/flightrec.py).
        fr = flightrec.get()
        t0 = time.perf_counter() if fr is not None else 0.0
        try:
            parsed = fastwire.parse_req(raw, arena)
        except IngestOverloadError as e:
            # Bounded ingest (docs/overload.md): arena exhaustion past
            # the fallback budget is backpressure, not an allocation —
            # answer retriable RESOURCE_EXHAUSTED so clients back off.
            if metrics is not None:
                metrics.admission_shed.labels(reason="backpressure").inc()
            await context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED, str(e))
        _sync_arena_metrics(arena, metrics)
        if fr is not None:
            fr.edge("decode", time.perf_counter() - t0)
        if parsed is None:  # codec unavailable or malformed bytes
            msg = await _parse_pb(msg_type, raw, context)
            parsed = convert.columns_from_pb(msg.requests)
        cols, errors, special = parsed
        if not special and not errors:
            try:
                mat, errs = await tick(cols, deadline=deadline)
            except BatchTooLargeError as e:
                cols.release()  # rejected before the tick loop saw it
                await context.abort(grpc.StatusCode.OUT_OF_RANGE, str(e))
            if not errs:
                # Native wire encoding straight from the matrix; the
                # method's pass-through serializer ships bytes as-is.
                t1 = time.perf_counter() if fr is not None else 0.0
                out = fastwire.encode_resp(mat)
                if fr is not None:
                    fr.edge("encode", time.perf_counter() - t1)
                return out, msg
            return _item_responses(mat, errs), msg
        cols.release()  # object path re-parses; the slab is dead weight
    return None, msg


class V1Servicer:
    """pb ↔ dataclass edge for the public service.

    ``GetRateLimits`` receives the RAW request bytes (the method handler
    registers a pass-through deserializer, transport/grpc_api.py): the
    hot path never materializes protobuf message objects — native wire
    parse (transport/fastwire.py) → columns → device tick → native wire
    encode.  The object-routing path (clustered / GLOBAL / metadata /
    per-item errors / codec unavailable) parses with protobuf as before.
    """

    def __init__(self, instance: V1Instance):
        self.instance = instance

    def _default_budget(self) -> float:
        return self.instance.tick_loop.admission.request_timeout

    async def GetRateLimits(self, raw: bytes, context):
        deadline = _edge_deadline(context, self._default_budget())
        fast, msg = await _raw_columns_edge(
            raw, context,
            self.instance.columns_fast_path_ok(),
            self.instance.get_rate_limits_columns,
            pb.GetRateLimitsReq,
            arena=self.instance.ingest_arena,
            deadline=deadline,
            metrics=self.instance.metrics,
        )
        if fast is not None:
            if isinstance(fast, bytes):
                return fast
            return pb.GetRateLimitsResp(responses=fast)
        if msg is None:
            msg = await _parse_pb(pb.GetRateLimitsReq, raw, context)
        reqs = convert.reqs_from_pb(msg.requests)
        for r in reqs:
            r.deadline = deadline
        try:
            out = await self.instance.get_rate_limits(reqs)
        except BatchTooLargeError as e:
            await context.abort(grpc.StatusCode.OUT_OF_RANGE, str(e))
        return pb.GetRateLimitsResp(responses=convert.resps_to_pb(out))

    async def HealthCheck(self, request, context):
        h = self.instance.health_check()
        return pb.HealthCheckResp(
            status=h.status, message=h.message, peer_count=h.peer_count
        )

    async def LeaseGrant(self, raw: bytes, context):
        """Quota-lease grant edge (docs/leases.md): raw frame in, raw
        frame out — lease traffic never touches protobuf."""
        specs = fastwire.parse_lease_grant_req(raw)
        if specs is None:
            await context.abort(
                grpc.StatusCode.INVALID_ARGUMENT,
                "malformed LeaseGrant frame")
        tokens = await self.instance.lease_grant(specs)
        return fastwire.encode_lease_grant_resp(tokens)

    async def LeaseSync(self, raw: bytes, context):
        """Quota-lease reconcile edge: consumed counts in, acks out."""
        syncs = fastwire.parse_lease_sync_req(raw)
        if syncs is None:
            await context.abort(
                grpc.StatusCode.INVALID_ARGUMENT,
                "malformed LeaseSync frame")
        acks = await self.instance.lease_sync(syncs)
        return fastwire.encode_lease_sync_resp(acks)

    async def FederationSync(self, raw: bytes, context):
        """Inter-region envelope edge (docs/federation.md): GFE1 frame
        in, GFA1 ack out.  A node without federation enabled rejects the
        RPC — the sender's breaker treats it like any dead peer."""
        env = fastwire.parse_federation_envelope(raw)
        if env is None:
            await context.abort(
                grpc.StatusCode.INVALID_ARGUMENT,
                "malformed FederationSync frame")
        if self.instance.federation is None:
            await context.abort(
                grpc.StatusCode.FAILED_PRECONDITION,
                "federation is not enabled on this node")
        ack = await self.instance.federation.receive(env)
        return fastwire.encode_federation_ack(ack)


class PeersServicer:
    """pb ↔ dataclass edge for the peer service.

    ``GetPeerRateLimits`` receives RAW bytes like the public edge
    (pass-through deserializer): GetPeerRateLimitsReq shares
    GetRateLimitsReq's wire shape (field 1, repeated RateLimitReq), so
    the native codec parses it directly; GLOBAL/metadata/error batches
    fall back to the object path (trace extraction and owner-side
    GLOBAL queueing need request objects)."""

    def __init__(self, instance: V1Instance):
        self.instance = instance

    def _default_budget(self) -> float:
        return self.instance.tick_loop.admission.request_timeout

    async def GetPeerRateLimits(self, raw: bytes, context):
        deadline = _edge_deadline(context, self._default_budget())
        fast, msg = await _raw_columns_edge(
            raw, context,
            self.instance.peer_columns_fast_path_ok(),
            self.instance.get_peer_rate_limits_columns,
            peers_pb.GetPeerRateLimitsReq,
            arena=self.instance.ingest_arena,
            deadline=deadline,
            metrics=self.instance.metrics,
        )
        if fast is not None:
            if isinstance(fast, bytes):
                # Same wire shape as GetRateLimitsResp (field 1,
                # repeated RateLimitResp) — bytes ship as-is.
                return fast
            return peers_pb.GetPeerRateLimitsResp(rate_limits=fast)
        if msg is None:
            msg = await _parse_pb(peers_pb.GetPeerRateLimitsReq, raw, context)
        reqs = convert.reqs_from_pb(msg.requests)
        for r in reqs:
            r.deadline = deadline
        try:
            out = await self.instance.get_peer_rate_limits(reqs)
        except BatchTooLargeError as e:
            await context.abort(grpc.StatusCode.OUT_OF_RANGE, str(e))
        return peers_pb.GetPeerRateLimitsResp(rate_limits=convert.resps_to_pb(out))

    async def UpdatePeerGlobals(self, request, context):
        updates = [
            GlobalUpdate(
                key=g.key,
                status=convert.resp_from_pb(g.status),
                algorithm=int(g.algorithm),
                duration=g.duration,
                created_at=g.created_at,
            )
            for g in request.globals
        ]
        await self.instance.update_peer_globals(updates)
        return peers_pb.UpdatePeerGlobalsResp()


class Daemon:
    """One running node: instance + listeners + discovery."""

    def __init__(self, conf: DaemonConfig, engine=None, global_mesh=None,
                 global_mesh_node: int = 0):
        self.conf = conf
        self.metrics = Metrics()
        # Optional OS / runtime collectors (daemon.go:276-287).
        self.metrics.register_flag_collectors(conf.metric_flags)
        self.instance: Optional[V1Instance] = None
        self._engine = engine
        self._global_mesh = global_mesh
        self._global_mesh_node = global_mesh_node
        self._grpc_server: Optional[grpc.aio.Server] = None
        self._http_runner: Optional[web.AppRunner] = None
        self._status_runner: Optional[web.AppRunner] = None
        self._pool = None
        self.tls: Optional[TLSBundle] = None
        self.peer_info: List[PeerInfo] = []
        # Readiness is distinct from liveness (docs/persistence.md):
        # /readyz is 503 until the startup restore completed and flips
        # back to 503 the moment graceful drain begins, so orchestrators
        # stop routing new traffic while /healthz (liveness + breaker
        # quorum) stays truthful about the process itself.
        self._ready = False
        self._draining = False
        # /debug introspection surface (docs/observability.md): enabling
        # it also installs the flight recorder and an in-memory trace
        # exporter so /debug/pipeline and /debug/traces have data.  The
        # slow-window watchdog installs the recorder even without the
        # endpoints (its dumps go to the log + slow_windows counter).
        self._debug_enabled = bool(
            env_knob("GUBER_DEBUG_ENDPOINTS", 0, parse=int))
        self._slow_window_ms = env_knob(
            "GUBER_SLOW_WINDOW_MS", 0.0, parse=float)
        self._flight_recorder: Optional[flightrec.FlightRecorder] = None
        self._debug_exporter: Optional[tracing.InMemoryExporter] = None
        self._watchdog_task: Optional[asyncio.Task] = None
        self._profiling = False

    # ------------------------------------------------------------------
    @property
    def advertise_address(self) -> str:
        return self.conf.advertise_address or self.conf.grpc_listen_address

    async def start(self) -> None:
        """Bring up instance, gRPC, gateway, discovery (daemon.go:83-366)."""
        # guber: allow-G002(startup-only TLS material read - runs once before any listener accepts traffic)
        self.tls = setup_tls(self.conf.tls)
        options = [("grpc.max_receive_message_length", MAX_RECV_BYTES)]
        if self.conf.grpc_max_conn_age_sec > 0:
            # Reference parity (daemon.go:128-133): default is infinity;
            # when set, age AND grace both apply so long-lived streams on
            # aged connections are force-closed too.
            age_ms = self.conf.grpc_max_conn_age_sec * 1000
            options.append(("grpc.max_connection_age_ms", age_ms))
            options.append(("grpc.max_connection_age_grace_ms", age_ms))
        server = grpc.aio.server(
            interceptors=[_StatsInterceptor(self.metrics), _TraceInterceptor()],
            options=options,
        )
        if self.tls is not None:
            port = server.add_secure_port(
                self.conf.grpc_listen_address, self.tls.server_credentials()
            )
        else:
            port = server.add_insecure_port(self.conf.grpc_listen_address)
        if port == 0:
            raise RuntimeError(
                f"failed to bind gRPC listener {self.conf.grpc_listen_address}"
            )
        # Rewrite :0 binds to the allocated port so peers/tests can dial it.
        host = self.conf.grpc_listen_address.rsplit(":", 1)[0]
        self.conf.grpc_listen_address = f"{host}:{port}"

        if self._debug_enabled or self._slow_window_ms > 0:
            windows = env_knob(
                "GUBER_FLIGHT_RECORDER_WINDOWS", 256, parse=int)
            rec = flightrec.FlightRecorder(
                windows=max(2, windows),
                slow_threshold_s=self._slow_window_ms / 1e3,
            )
            rec.observer = self._observe_stage
            flightrec.install(rec)
            self._flight_recorder = rec
            self._watchdog_task = spawn_supervised(
                self._watchdog_loop,
                name="flight_watchdog",
                should_restart=lambda: not self._draining,
                metrics=self.metrics,
                loop_label="flight_watchdog",
            )
        if self._debug_enabled:
            self._debug_exporter = tracing.InMemoryExporter()
            tracing.add_exporter(self._debug_exporter)

        # Gateway comes up BEFORE the instance: a snapshot restore can
        # take seconds, and readiness probes must get a real 503 from
        # /readyz during it (not connection-refused ambiguity).
        await self._start_gateway()

        # The instance needs the *bound* address so set_peers can recognize
        # this node's own entry and mark it owner — create it only now.
        iconf = InstanceConfig.from_config(
            self.conf.config,
            advertise_address=self.advertise_address,
            metrics=self.metrics,
            peer_credentials=(
                self.tls.channel_credentials() if self.tls else None
            ),
        )
        iconf.data_center = self.conf.data_center or self.conf.config.data_center
        if self._global_mesh is not None:
            iconf.global_mesh = self._global_mesh
            iconf.global_mesh_node = self._global_mesh_node
        self.instance = await V1Instance.create(iconf, engine=self._engine)
        self._start_edge_plane()
        server.add_generic_rpc_handlers(
            (
                v1_handler(V1Servicer(self.instance)),
                peers_handler(PeersServicer(self.instance)),
            )
        )
        await server.start()
        self._grpc_server = server
        self._ready = True

        await self._start_discovery()
        log.info(
            "gubernator-tpu daemon up: grpc=%s http=%s",
            self.conf.grpc_listen_address,
            self.conf.http_listen_address,
        )

    def _start_edge_plane(self) -> None:
        """GUBER_EDGE_WORKERS > 0: bring up the shared-memory ingest
        plane (docs/edge.md) — N decode worker processes, each exposing
        a Unix-socket fastwire endpoint and feeding the tick loop
        through its own shm slab ring.  At 0 (the default) nothing is
        constructed: the serving path is byte-identical to the
        single-process daemon and no shm segment ever exists."""
        conf = self.conf.config
        if conf.edge_workers <= 0:
            return
        from gubernator_tpu.service.instance import MAX_BATCH_SIZE
        from gubernator_tpu.edge import EdgeConfig, EdgePlane

        plane = EdgePlane(
            self.instance.tick_loop,
            EdgeConfig(
                workers=conf.edge_workers,
                slabs=conf.edge_shm_slabs,
                ring_depth=conf.edge_ring_depth,
                max_batch=MAX_BATCH_SIZE,
                mode="socket",
            ),
            metrics=self.metrics,
        )
        plane.start()
        self.instance.attach_edge_plane(plane)
        log.info("edge ingest sockets: %s", ", ".join(plane.socket_paths()))

    # ------------------------------------------------------------------
    # HTTP gateway (grpc-gateway JSON + /metrics, daemon.go:245-292)
    # ------------------------------------------------------------------
    def _gateway_app(self, include_metrics: bool = True) -> web.Application:
        app = web.Application(client_max_size=MAX_RECV_BYTES)
        app.router.add_post("/v1/GetRateLimits", self._h_get_rate_limits)
        app.router.add_get("/v1/HealthCheck", self._h_health_check)
        app.router.add_get("/healthz", self._h_health_check)
        app.router.add_get("/readyz", self._h_readyz)
        if include_metrics:
            app.router.add_get("/metrics", self._h_metrics)
        if self._debug_enabled:
            self._add_debug_routes(app)
        return app

    def _add_debug_routes(self, app: web.Application) -> None:
        app.router.add_get("/debug/pipeline", self._h_debug_pipeline)
        app.router.add_get("/debug/traces", self._h_debug_traces)
        app.router.add_get("/debug/state", self._h_debug_state)
        app.router.add_get("/debug/profile", self._h_debug_profile)
        app.router.add_post("/debug/reshard", self._h_debug_reshard)
        app.router.add_get("/debug/autoscaler", self._h_debug_autoscaler)

    async def _start_gateway(self) -> None:
        if not self.conf.http_listen_address:
            return
        app = self._gateway_app()
        runner = web.AppRunner(app, access_log=None)
        await runner.setup()
        host, _, port = self.conf.http_listen_address.rpartition(":")
        ssl_ctx = self.tls.server_ssl_context() if self.tls else None
        site = web.TCPSite(runner, host or "localhost", int(port), ssl_context=ssl_ctx)
        await site.start()
        self._http_runner = runner
        # Rewrite :0 binds to the allocated port.
        socks = site._server.sockets if site._server is not None else []
        if int(port) == 0 and socks:
            self.conf.http_listen_address = (
                f"{host or 'localhost'}:{socks[0].getsockname()[1]}"
            )
        # Optional plaintext status listener for health probes behind mTLS
        # (daemon.go:305-334).
        if self.conf.http_status_listen_address:
            sapp = web.Application()
            sapp.router.add_get("/v1/HealthCheck", self._h_health_check)
            sapp.router.add_get("/healthz", self._h_health_check)
            sapp.router.add_get("/readyz", self._h_readyz)
            sapp.router.add_get("/metrics", self._h_metrics)
            if self._debug_enabled:
                self._add_debug_routes(sapp)
            srunner = web.AppRunner(sapp, access_log=None)
            await srunner.setup()
            shost, _, sport = self.conf.http_status_listen_address.rpartition(":")
            await web.TCPSite(srunner, shost or "localhost", int(sport)).start()
            self._status_runner = srunner

    async def _h_get_rate_limits(self, request: web.Request) -> web.Response:
        """JSON gateway with snake_case field names (UseProtoNames parity,
        daemon.go:251-261)."""
        if self.instance is None:
            return web.json_response(
                {"error": "starting up", "code": 14}, status=503
            )
        try:
            body = await request.read()
            msg = json_format.Parse(body, pb.GetRateLimitsReq())
        except json_format.ParseError as e:
            return web.json_response({"error": str(e), "code": 3}, status=400)
        try:
            parent = tracing.extract(
                {k.lower(): v for k, v in request.headers.items()}
            )
            with tracing.maybe_span("http.recv./v1/GetRateLimits",
                                    parent=parent):
                out = await self.instance.get_rate_limits(
                    convert.reqs_from_pb(msg.requests)
                )
        except BatchTooLargeError as e:
            return web.json_response({"error": str(e), "code": 11}, status=400)
        resp = pb.GetRateLimitsResp(responses=convert.resps_to_pb(out))
        return web.json_response(
            json_format.MessageToDict(
                resp,
                preserving_proto_field_name=True,
                always_print_fields_with_no_presence=True,
            )
        )

    async def _h_readyz(self, request: web.Request) -> web.Response:
        """Readiness, split from liveness: 503 before the startup restore
        completes and for the whole graceful drain, 200 only while the
        daemon wants new traffic.  /healthz keeps the breaker-majority
        liveness semantics (docs/resilience.md)."""
        ok = self._ready and not self._draining
        body = {
            "ready": ok,
            "draining": self._draining,
        }
        if self.instance is not None and self.instance.restore_stats:
            body["restore"] = self.instance.restore_stats
        return web.json_response(body, status=200 if ok else 503)

    async def _h_health_check(self, request: web.Request) -> web.Response:
        if self.instance is None:
            return web.json_response(
                {"status": "unhealthy", "message": "starting up",
                 "peer_count": 0}, status=503
            )
        h = self.instance.health_check()
        msg = pb.HealthCheckResp(
            status=h.status, message=h.message, peer_count=h.peer_count
        )
        body = json_format.MessageToDict(
            msg,
            preserving_proto_field_name=True,
            always_print_fields_with_no_presence=True,
        )
        # Tier occupancy rides the health JSON as extra keys (the proto
        # message is unchanged — wire-compatible clients ignore them).
        body["occupancy"] = self.instance.occupancy()
        # Unhealthy (e.g. a majority of peers behind open circuit
        # breakers) maps to 503 so plain HTTP probes — k8s liveness,
        # LB health checks — rotate the node without parsing JSON.
        return web.json_response(
            body, status=200 if h.status == "healthy" else 503
        )

    async def _h_metrics(self, request: web.Request) -> web.Response:
        if self.instance is None:
            return web.Response(
                body=self.metrics.expose(), content_type="text/plain"
            )
        eng = self.instance.engine
        self.metrics.cache_size.set(eng.cache_size())
        if hasattr(eng, "hot_occupancy"):
            self.metrics.hot_occupancy.set(eng.hot_occupancy())
        if hasattr(eng, "cold_size"):
            self.metrics.cold_size.set(eng.cold_size())
        return web.Response(
            body=self.metrics.expose(), content_type="text/plain"
        )

    # ------------------------------------------------------------------
    # /debug introspection surface (docs/observability.md)
    # ------------------------------------------------------------------
    def _observe_stage(self, stage: str, seconds: float) -> None:
        """Flight-recorder observer: per-stage latency histogram."""
        self.metrics.stage_duration.labels(stage=stage).observe(seconds)

    async def _watchdog_loop(self) -> None:
        """Drain slow-window records parked by FlightRecorder.finish().

        finish() runs on the dispatch hot path so it only does the float
        compare and a bounded-deque append; everything observable — the
        slow_windows counter, the log dump — happens here off the hot
        path, under the supervisor like every other background loop."""
        while not self._draining:
            rec = self._flight_recorder
            if rec is not None:
                for dump in rec.drain_slow():
                    self.metrics.slow_windows.inc()
                    log.warning(
                        "slow window %d: total=%.1fms width=%d depth=%d "
                        "stages_ms=%s",
                        dump["window"], dump["total_ms"], dump["width"],
                        dump["queue_depth"],
                        {s: v for s, v in dump["stages_ms"].items() if v},
                    )
            await asyncio.sleep(0.25)

    async def _h_debug_pipeline(self, request: web.Request) -> web.Response:
        rec = self._flight_recorder
        if rec is None:
            return web.json_response(
                {"error": "flight recorder not installed"}, status=404
            )
        try:
            limit = int(request.query.get("limit", "64"))
        except ValueError:
            return web.json_response({"error": "bad limit"}, status=400)
        return web.json_response({
            "windows": rec.recent(max(1, limit)),
            "stage_percentiles": rec.stage_percentiles(),
            "slow_windows": rec.slow_total,
        })

    @staticmethod
    def _span_dict(span: tracing.Span) -> dict:
        attrs = {
            k: v if isinstance(v, (str, int, float, bool, type(None)))
            else repr(v)
            for k, v in span.attributes.items()
        }
        return {
            "name": span.name,
            "trace_id": span.trace_id,
            "span_id": span.span_id,
            "parent_span_id": span.parent_span_id,
            "start_ns": span.start_ns,
            "duration_ms": round(span.duration_ms, 4),
            "attributes": attrs,
            "error": span.error,
        }

    async def _h_debug_traces(self, request: web.Request) -> web.Response:
        exp = self._debug_exporter
        if exp is None:
            return web.json_response(
                {"error": "trace exporter not installed"}, status=404
            )
        trace_id = request.query.get("trace_id")
        name = request.query.get("name")
        try:
            limit = int(request.query.get("limit", "128"))
        except ValueError:
            return web.json_response({"error": "bad limit"}, status=400)
        if trace_id:
            spans = exp.by_trace(trace_id)
        elif name:
            spans = exp.by_name(name)
        else:
            with exp._lock:
                spans = list(exp.spans)
        spans = spans[-max(1, limit):]
        return web.json_response({
            "tracing_enabled": tracing.enabled(),
            "count": len(spans),
            "spans": [self._span_dict(s) for s in spans],
        })

    async def _h_debug_state(self, request: web.Request) -> web.Response:
        if self.instance is None:
            return web.json_response({"error": "starting up"}, status=503)
        inst = self.instance
        eng = inst.engine
        body: dict = {
            "ready": self._ready,
            "draining": self._draining,
            "occupancy": inst.occupancy(),
            "restore": inst.restore_stats,
        }
        arena = inst.ingest_arena
        if arena is not None:
            body["ingest_arena"] = {
                "slabs": arena.n_slabs,
                "in_use": arena.in_use(),
                "leases": arena.metric_leases,
                "misses": arena.metric_misses,
            }
        if inst.edge_plane is not None:
            body["edge"] = inst.edge_plane.debug_state()
        engine_tel: dict = {}
        if hasattr(eng, "h2d_overlap_ratio"):
            engine_tel["h2d_windows"] = eng.metric_h2d_windows
            engine_tel["h2d_overlap_ratio"] = round(
                eng.h2d_overlap_ratio(), 4)
        staging = getattr(eng, "_staging", None)
        if staging is not None and hasattr(staging, "telemetry"):
            engine_tel["staging_ring"] = staging.telemetry()
        if engine_tel:
            body["engine"] = engine_tel
        body["breakers"] = {
            p.info.grpc_address: p.breaker.state.name
            for p in inst.local_picker.peers()
        }
        gm = inst.global_mgr
        body["redelivery"] = {
            "hits": len(gm._hits),
            "updates": len(gm._updates),
            "owned": len(gm._owned),
        }
        writer = getattr(inst, "_snapshot_writer", None)
        if writer is not None:
            body["snapshot"] = {
                "generation": writer.store.generation,
                "delta_writes": writer.metric_delta_writes,
                "base_writes": writer.metric_base_writes,
                "write_failures": writer.metric_write_failures,
            }
        body["reshard"] = inst.reshard_status()
        if inst.autoscaler is not None:
            scaler_state = inst.autoscaler.debug_state()
            scaler_state.pop("decisions", None)  # the ring lives at
            body["autoscaler"] = scaler_state    # /debug/autoscaler
        return web.json_response(body)

    async def _h_debug_autoscaler(self, request: web.Request) -> web.Response:
        """Autoscaler introspection (docs/autoscaling.md): config,
        streaks, and the bounded decision ring — the dry-run rollout
        reads this until the decisions look right."""
        if self.instance is None:
            return web.json_response({"error": "starting up"}, status=503)
        scaler = self.instance.autoscaler
        if scaler is None:
            return web.json_response(
                {"error": "autoscaler disabled (GUBER_AUTOSCALE_ENABLED)"},
                status=404,
            )
        return web.json_response(scaler.debug_state())

    async def _h_debug_reshard(self, request: web.Request) -> web.Response:
        """Admin trigger (docs/resharding.md): POST {"shards": m} runs
        one n→m transition and answers its outcome dict.  409 when a
        transition is already running (the coordinator's busy dict is
        the single source of truth — the autoscaler consults the same
        lock, so the two can never double-freeze); 400 on a bad target.
        The debug plane is operator-only (GUBER_DEBUG_ENDPOINTS), same
        trust level as /debug/profile."""
        if self.instance is None:
            return web.json_response({"error": "starting up"}, status=503)
        try:
            doc = await request.json()
            shards = int(doc["shards"])
        except (ValueError, KeyError, TypeError):
            return web.json_response(
                {"error": "body must be JSON {\"shards\": <int>}"},
                status=400,
            )
        from gubernator_tpu.parallel.reshard import ReshardError

        try:
            result = await self.instance.reshard(shards)
        except ReshardError as e:
            return web.json_response({"error": str(e)}, status=400)
        if result.get("result") == "busy":
            return web.json_response(result, status=409)
        return web.json_response(result)

    async def _h_debug_profile(self, request: web.Request) -> web.Response:
        try:
            seconds = float(request.query.get("seconds", "2"))
        except ValueError:
            return web.json_response({"error": "bad seconds"}, status=400)
        if not 0 < seconds <= 30:
            return web.json_response(
                {"error": "seconds must be in (0, 30]"}, status=400
            )
        if self._profiling:
            return web.json_response(
                {"error": "capture already running"}, status=409
            )
        self._profiling = True
        try:
            import tempfile

            import jax

            out_dir = tempfile.mkdtemp(prefix="guber-profile-")
            jax.profiler.start_trace(out_dir)
            try:
                await asyncio.sleep(seconds)
            finally:
                jax.profiler.stop_trace()
            return web.json_response(
                {"trace_dir": out_dir, "seconds": seconds}
            )
        except Exception as exc:  # profiler may be busy / unavailable
            return web.json_response(
                {"error": f"{type(exc).__name__}: {exc}"}, status=500
            )
        finally:
            self._profiling = False

    # ------------------------------------------------------------------
    # Discovery (daemon.go:208-243)
    # ------------------------------------------------------------------
    async def _start_discovery(self) -> None:
        kind = self.conf.peer_discovery_type
        if kind == "none":
            self.set_peers([self._self_info()])
            return
        from gubernator_tpu import discovery

        info = self._self_info()
        if kind == "dns":
            self._pool = discovery.DNSPool(
                fqdn=self.conf.dns_fqdn,
                grpc_port=int(self.conf.grpc_listen_address.rsplit(":", 1)[1]),
                http_port=int(self.conf.http_listen_address.rsplit(":", 1)[1])
                if self.conf.http_listen_address
                else 0,
                on_update=self.set_peers,
            )
        elif kind == "etcd":
            self._pool = discovery.EtcdPool(
                endpoints=self.conf.etcd_endpoints,
                key_prefix=self.conf.etcd_key_prefix,
                info=info,
                on_update=self.set_peers,
            )
        elif kind == "k8s":
            self._pool = discovery.K8sPool(
                namespace=self.conf.k8s_namespace,
                selector=self.conf.k8s_endpoints_selector,
                pod_ip=self.conf.k8s_pod_ip,
                pod_port=self.conf.k8s_pod_port,
                mechanism=self.conf.k8s_watch_mechanism,
                on_update=self.set_peers,
            )
        elif kind == "member-list":
            self._pool = discovery.MemberlistPool(
                bind_address=self.conf.memberlist_address,
                known_nodes=self.conf.memberlist_known_nodes,
                info=info,
                on_update=self.set_peers,
            )
        else:
            raise ValueError(f"unknown peer discovery type {kind!r}")
        await self._pool.start()

    def _self_info(self) -> PeerInfo:
        return PeerInfo(
            grpc_address=self.advertise_address,
            http_address=self.conf.http_listen_address,
            datacenter=self.conf.data_center,
        )

    # ------------------------------------------------------------------
    def set_peers(self, peers: Sequence[PeerInfo]) -> None:
        """Install cluster membership; marks our own entry (daemon.go:399-409)."""
        self.peer_info = list(peers)
        self.instance.set_peers(self.peer_info)

    def client(self) -> "DaemonClient":
        """A client dialing this daemon (reference Daemon.Client, :433-447)."""
        creds = self.tls.channel_credentials() if self.tls else None
        return DaemonClient(self.conf.grpc_listen_address, credentials=creds)

    async def wait_for_connect(self, timeout: float = 10.0) -> None:
        """Readiness: block until the gRPC listener answers HealthCheck."""
        client = self.client()
        deadline = time.monotonic() + timeout
        while True:
            try:
                await client.health_check()
                await client.close()
                return
            except Exception:
                if time.monotonic() > deadline:
                    await client.close()
                    raise
                await asyncio.sleep(0.05)

    async def close(self) -> None:
        """Graceful shutdown (daemon.go:369-396): flip readiness to 503
        first (orchestrators stop routing), then drain — discovery off,
        GLOBAL buffers flushed under the bounded deadline and the final
        base snapshot written inside instance.close — then listeners."""
        self._draining = True
        if self._watchdog_task is not None:
            self._watchdog_task.cancel()
            try:
                await self._watchdog_task
            except (asyncio.CancelledError, Exception):
                pass
            self._watchdog_task = None
        if self._debug_exporter is not None:
            tracing.remove_exporter(self._debug_exporter)
            self._debug_exporter = None
        if (self._flight_recorder is not None
                and flightrec.get() is self._flight_recorder):
            # Only drop the module-global slot if it is still ours — an
            # in-process test cluster shares it across daemons.
            flightrec.uninstall()
        self._flight_recorder = None
        if self._pool is not None:
            await self._pool.close()
        if self.instance is not None:
            await self.instance.close()
        if self._grpc_server is not None:
            await self._grpc_server.stop(grace=1.0)
        if self._http_runner is not None:
            await self._http_runner.cleanup()
        if self._status_runner is not None:
            await self._status_runner.cleanup()


class DaemonClient:
    """Thin async client for the public V1 API (reference client.go)."""

    def __init__(
        self,
        address: str,
        credentials: Optional[grpc.ChannelCredentials] = None,
    ):
        if credentials is not None:
            self.channel = grpc.aio.secure_channel(address, credentials)
        else:
            self.channel = grpc.aio.insecure_channel(address)
        self.stub = V1Stub(self.channel)
        # Raw-bytes method for the columnar client path: the native
        # codec produces/consumes the wire bytes; grpc just ships them.
        self._raw_get_rate_limits = self.channel.unary_unary(
            "/pb.gubernator.V1/GetRateLimits",
            request_serializer=lambda b: b,
            response_deserializer=lambda b: b,
        )

    async def get_rate_limits(self, reqs, timeout: float = 5.0,
                              budget_ms: int = None):
        """``budget_ms`` (optional) rides the ``guber-deadline-ms``
        metadata key so the server's admission plane sheds work this
        caller will no longer wait for (docs/overload.md)."""
        msg = pb.GetRateLimitsReq(requests=[convert.req_to_pb(r) for r in reqs])
        hdrs: dict = {}
        tracing.inject(hdrs)
        if budget_ms is not None:
            hdrs[DEADLINE_METADATA_KEY] = str(max(0, int(budget_ms)))
        out = await self.stub.GetRateLimits(
            msg, timeout=timeout, metadata=tuple(hdrs.items()) or None
        )
        return [convert.resp_from_pb(r) for r in out.responses]

    async def get_rate_limits_columns(self, cols, timeout: float = 5.0,
                                      budget_ms: int = None):
        """Columnar client fast path: a :class:`ReqColumns` batch (with
        ``name_len``) → native wire encode → raw gRPC → native wire
        decode → ((4, n) status/limit/remaining/reset_time matrix,
        {index: error string}).  Raises RuntimeError when the native
        codec is unavailable — callers keep the object API then."""
        import numpy as np

        raw = fastwire.encode_req(cols)
        if raw is None:
            raise RuntimeError(
                "native wire codec unavailable (build native/ or use "
                "get_rate_limits)"
            )
        hdrs: dict = {}
        tracing.inject(hdrs)
        if budget_ms is not None:
            hdrs[DEADLINE_METADATA_KEY] = str(max(0, int(budget_ms)))
        out = await self._raw_get_rate_limits(
            raw, timeout=timeout, metadata=tuple(hdrs.items()) or None
        )
        parsed = fastwire.parse_resp(out)
        if parsed is None:  # pragma: no cover - encode side proved lib ok
            raise RuntimeError("native wire codec failed to parse response")
        mat, special = parsed
        errors = {}
        if special.any():
            msg = pb.GetRateLimitsResp.FromString(out)
            for i in np.flatnonzero(special):
                if msg.responses[i].error:
                    errors[int(i)] = msg.responses[i].error
        return mat, errors

    async def health_check(self, timeout: float = 5.0):
        return await self.stub.HealthCheck(pb.HealthCheckReq(), timeout=timeout)

    async def lease_grant(self, specs, timeout: float = 5.0):
        """Request quota leases (docs/leases.md): [LeaseSpec] →
        [Optional[LeaseToken]] (None = server declined; fall back to
        per-request decisions)."""
        hdrs: dict = {}
        tracing.inject(hdrs)
        out = await self.stub.LeaseGrant(
            fastwire.encode_lease_grant_req(specs), timeout=timeout,
            metadata=tuple(hdrs.items()) or None,
        )
        tokens = fastwire.parse_lease_grant_resp(out)
        if tokens is None:
            raise RuntimeError("malformed LeaseGrant response frame")
        return tokens

    async def lease_sync(self, syncs, timeout: float = 5.0):
        """Report lease consumption: [LeaseSync] → [LeaseSyncAck]."""
        hdrs: dict = {}
        tracing.inject(hdrs)
        out = await self.stub.LeaseSync(
            fastwire.encode_lease_sync_req(syncs), timeout=timeout,
            metadata=tuple(hdrs.items()) or None,
        )
        acks = fastwire.parse_lease_sync_resp(out)
        if acks is None:
            raise RuntimeError("malformed LeaseSync response frame")
        return acks

    async def close(self) -> None:
        await self.channel.close()


async def spawn_daemon(conf: DaemonConfig, engine=None) -> Daemon:
    """Start a daemon and wait for readiness (reference SpawnDaemon,
    daemon.go:73-81)."""
    d = Daemon(conf, engine=engine)
    await d.start()
    await d.wait_for_connect()
    return d
