"""Vectorized protobuf wire encoding for the hot response path.

Building 1000 ``RateLimitResp`` message objects and serializing them
costs ~3 ms of single-core Python per batch (the serving path's largest
CPU component after request conversion); this module emits the
identical wire bytes straight from the engine's (5, n) response matrix
with numpy — ~50x less per-batch CPU.  The gRPC handler returns these
bytes through a pass-through serializer (transport/daemon.py), so the
client sees a byte-identical GetRateLimitsResp.

Wire layout (proto/gubernator.proto):

  GetRateLimitsResp: field 1, repeated RateLimitResp (len-delimited)
  RateLimitResp:     1 status (varint enum), 2 limit, 3 remaining,
                     4 reset_time (varint int64), 5 error (string),
                     6 metadata (map, unused on the fast path)

Negative int64s encode as 10-byte two's-complement varints, exactly as
protobuf requires (remaining can go negative under DRAIN semantics).
Per-item-error responses fall back to message objects host-side (they
are rare and carry strings); this encoder covers the all-ok fast path.
"""

from __future__ import annotations

import numpy as np

# Key tags (field << 3 | wire_type): varints are type 0, strings type 2.
_TAG_STATUS = (1 << 3) | 0
_TAG_LIMIT = (2 << 3) | 0
_TAG_REMAINING = (3 << 3) | 0
_TAG_RESET = (4 << 3) | 0
_TAG_RESPONSES = (1 << 3) | 2


def _varint_len(u: np.ndarray) -> np.ndarray:
    """Encoded byte count of each uint64 (1..10)."""
    # bit_length via log2 on float is unsafe past 2^53; use a comparison
    # ladder (9 compares, vectorized).
    n = np.ones(u.shape, np.int64)
    for k in range(1, 10):
        n += (u >= (np.uint64(1) << np.uint64(7 * k))).astype(np.int64)
    return n


def _write_varints(buf: np.ndarray, pos: np.ndarray, u: np.ndarray,
                   lens: np.ndarray) -> None:
    """Scatter each value's varint bytes at buf[pos[i]:pos[i]+lens[i]]."""
    max_len = int(lens.max()) if len(lens) else 0
    for k in range(max_len):
        sel = lens > k
        if not sel.any():
            break
        byte = (u[sel] >> np.uint64(7 * k)) & np.uint64(0x7F)
        cont = (lens[sel] > k + 1)
        buf[pos[sel] + k] = (byte | (cont.astype(np.uint64) << np.uint64(7))
                             ).astype(np.uint8)


def encode_get_rate_limits_resp(mat: np.ndarray) -> bytes:
    """(5, n) int64 response matrix (rows: status, limit, remaining,
    reset_time, over_limit) → serialized ``GetRateLimitsResp`` bytes.
    Matches message-object serialization byte-for-byte for responses
    with no error and no metadata (proto3 omits zero-valued scalars)."""
    n = mat.shape[1]
    if n == 0:
        return b""
    status = mat[0].astype(np.uint64)
    vals = mat[1:4].astype(np.uint64)  # limit, remaining, reset (2's comp)

    # Per-field encoded sizes; proto3 skips fields whose value is 0.
    sl = np.where(status != 0, 1 + _varint_len(status), 0)
    field_lens = np.where(vals != 0, 1 + _varint_len(vals), 0)  # (3, n)
    msg_lens = sl + field_lens.sum(axis=0)          # RateLimitResp bytes
    hdr_lens = 1 + _varint_len(msg_lens.astype(np.uint64))
    total = int((msg_lens + hdr_lens).sum())
    buf = np.empty(total, np.uint8)

    starts = np.zeros(n, np.int64)
    np.cumsum(msg_lens + hdr_lens, out=starts)
    starts -= msg_lens + hdr_lens                    # exclusive prefix sum

    # Submessage headers: tag byte + length varint.
    buf[starts] = _TAG_RESPONSES
    _write_varints(buf, starts + 1, msg_lens.astype(np.uint64),
                   hdr_lens - 1)

    pos = starts + hdr_lens
    for tag, u, ln in (
        (_TAG_STATUS, status, sl),
        (_TAG_LIMIT, vals[0], field_lens[0]),
        (_TAG_REMAINING, vals[1], field_lens[1]),
        (_TAG_RESET, vals[2], field_lens[2]),
    ):
        present = ln > 0
        buf[pos[present]] = tag
        _write_varints(buf, (pos + 1)[present], u[present],
                       (ln - 1)[present])
        pos = pos + ln
    return buf.tobytes()
