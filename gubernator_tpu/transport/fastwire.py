"""Native wire codec bindings: serialized pb ⇄ columns with no message
objects.

The serving path's CPU cost is per-request Python object churn
(~3-4.7 ms per 1000-item batch measured through protobuf message
objects, bench.py service rung); the C++ codec
(:file:`native/wirecodec.cc`) parses ``GetRateLimitsReq`` bytes straight
into :class:`~gubernator_tpu.ops.reqcols.ReqColumns` and emits
``GetRateLimitsResp`` bytes straight from the engine's (5, n) response
matrix — tens of microseconds per batch.  Every entry point degrades
gracefully: ``None`` (or the numpy fallback) when the shared library is
unavailable or the input needs the object path.

Request-side semantics match :func:`transport.convert.columns_from_pb`
exactly (empty-name/key per-item errors, metadata/GLOBAL → special,
``created_at`` 0-or-absent → server stamps now); response encoding is
byte-identical to protobuf for items without error/metadata — proven
against the protobuf library in tests/test_fastwire.py.
"""

from __future__ import annotations

import ctypes
from typing import Dict, Optional, Tuple

import numpy as np

from gubernator_tpu import native as native_mod
from gubernator_tpu.algos import algorithm_error, invalid_algorithm_mask
from gubernator_tpu.ops.reqcols import (
    CREATED_UNSET,
    ColumnArena,
    IngestOverloadError,
    ReqColumns,
)
from gubernator_tpu.types import Behavior
from gubernator_tpu.utils.hotpath import hot_path

_I64 = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
_U8 = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")

# out_flags bits (wirecodec.cc).
_NAME_EMPTY = 1
_KEY_EMPTY = 2
_HAS_METADATA = 4
_HAS_CREATED = 8

# Behaviors that force the object-routing path: GLOBAL (owner routing +
# reconcile queues) and MULTI_REGION (federation validation — the edge
# must reject it per-item when federation is off, which the columns
# fast path cannot express).
_SPECIAL_BEHAVIOR = int(Behavior.GLOBAL) | int(Behavior.MULTI_REGION)

_lib = None
_load_attempted = False


def load() -> Optional[ctypes.CDLL]:
    """The wire codec library (built alongside the slotmap; None when the
    toolchain/build is unavailable — callers fall back to protobuf)."""
    global _lib, _load_attempted
    if _lib is not None or _load_attempted:
        return _lib
    _load_attempted = True
    import os

    so = os.path.join(os.path.dirname(native_mod.__file__), "libguber_wire.so")
    if not os.path.exists(so):
        native_mod._try_build()
    if not os.path.exists(so):
        return None
    lib = ctypes.CDLL(so)
    lib.guber_wire_count.restype = ctypes.c_int64
    lib.guber_wire_count.argtypes = [ctypes.c_char_p, ctypes.c_int64]
    lib.guber_parse_req.restype = ctypes.c_int64
    lib.guber_parse_req.argtypes = [
        ctypes.c_char_p, ctypes.c_int64,
        _U8, ctypes.c_int64, _I64, _I64,
        _I64, _I64, _I64, _I64, _I64, _I64, _I64, _U8,
    ]
    lib.guber_parse_resp.restype = ctypes.c_int64
    lib.guber_parse_resp.argtypes = [
        ctypes.c_char_p, ctypes.c_int64,
        _I64, _I64, _I64, _I64, _U8,
    ]
    lib.guber_encode_req.restype = ctypes.c_int64
    lib.guber_encode_req.argtypes = [
        ctypes.c_char_p, _I64, _I64,
        _I64, _I64, _I64, _I64, _I64, _I64, _I64, _U8,
        ctypes.c_int64, _U8, ctypes.c_int64,
    ]
    lib.guber_encode_resp.restype = ctypes.c_int64
    lib.guber_encode_resp.argtypes = [
        _I64, _I64, _I64, _I64,
        ctypes.c_int64, _U8, ctypes.c_int64,
    ]
    _lib = lib
    return lib


@hot_path
def parse_req(
    data: bytes, arena: Optional[ColumnArena] = None,
) -> Optional[Tuple[ReqColumns, Dict[int, str], bool]]:
    """Serialized ``GetRateLimitsReq`` → (cols, per-item errors, special).

    ``special`` is True when any item carries GLOBAL or MULTI_REGION
    behavior or metadata (those route through the object path, which
    re-parses with protobuf — the codec records metadata *presence*
    only).  Returns None when the
    native library is unavailable or the bytes are malformed (caller
    falls back to ``pb.GetRateLimitsReq.FromString``).

    With ``arena`` (ops.reqcols.ColumnArena) the decode lands in a
    preallocated slab and the returned columns — key blob included —
    are views into it: zero per-window allocation and zero copies
    (the native slotmap resolves the blob view in place).  The
    caller owns the lease: ``cols.release()`` once the engine has
    packed the batch (an unreleased lease just falls back to plain
    allocation when the arena runs dry, never corrupts).  Oversized
    batches silently skip the arena."""
    lib = load()
    if lib is None:
        return None
    ln = len(data)
    n = lib.guber_wire_count(data, ln)
    if n < 0:
        return None
    if n == 0:
        return ReqColumns.empty(), {}, False
    blob_cap = ln + n
    lease = arena.lease(n, blob_cap) if arena is not None else None
    if lease is not None:
        ints = lease.ints
        blob = lease.blob
        flags_full = lease.flags
    else:
        # Bounded fallback (docs/overload.md): a size miss (batch wider
        # than any slab) always plain-allocates, but busy-slab
        # exhaustion spends the arena's per-window fallback budget —
        # past it, the edge sheds instead of growing the heap.
        if (arena is not None and arena.fits(n, blob_cap)
                and not arena.try_fallback()):
            raise IngestOverloadError(
                "ingest arena exhausted and fallback budget spent")
        blob = np.empty(blob_cap, np.uint8)
        # One zeroed block for all int64 outputs (native writes only the
        # fields present on the wire; proto3 absents must read 0): a
        # single memset beats ten allocations at serving batch rates.
        ints = np.zeros((9, n + 1), np.int64)
        flags_full = np.zeros(n, np.uint8)
    off = ints[8, : n + 1]
    name_len, hits, limit, duration, algorithm, behavior, burst, created = (
        ints[i, :n] for i in range(8)
    )
    flags = flags_full[:n]
    got = lib.guber_parse_req(
        data, ln, blob, len(blob), off, name_len,
        hits, limit, duration, algorithm, behavior, burst, created, flags,
    )
    if got != n:
        if lease is not None:
            lease.release()
        return None
    # created_at: absent OR explicit 0 → "server stamps now"
    # (convert.columns_from_pb parity).
    created[created == 0] = CREATED_UNSET
    errors: Dict[int, str] = {}
    # guber: allow-G001(flags is host numpy, never a device value)
    if bool((flags & (_NAME_EMPTY | _KEY_EMPTY)).any()):
        for i in np.flatnonzero(flags & (_NAME_EMPTY | _KEY_EMPTY)):
            errors[int(i)] = (
                "field 'unique_key' cannot be empty"
                if flags[i] & _KEY_EMPTY
                else "field 'namespace' cannot be empty"
            )
    # Out-of-range algorithm values must fail loudly here: the kernels'
    # branchless per-lane dispatch would otherwise silently run an
    # unknown enum as a token bucket (algos/__init__.py).
    bad_algo = invalid_algorithm_mask(algorithm)
    # guber: allow-G001(algorithm is host numpy, never a device value)
    if bool(bad_algo.any()):
        for i in np.flatnonzero(bad_algo):
            errors.setdefault(int(i), algorithm_error(algorithm[i]))
    # guber: allow-G001(flags/behavior are host numpy, never device)
    special = bool((flags & _HAS_METADATA).any()) or bool(
        (behavior & _SPECIAL_BEHAVIOR).any()
    )
    # The key blob stays a view into the decode buffer — the last copy
    # on the decode path is gone.  Arena-backed batches alias the slab
    # (valid until cols.release(), same lifetime as the other columns);
    # the plain-allocation branch aliases the freshly-built buffer the
    # columns already own.
    cols = ReqColumns(
        blob[: off[n]], off, hits, limit, duration,
        algorithm, behavior, created, burst, name_len=name_len,
        lease=lease,
    )
    return cols, errors, special


def parse_resp(data: bytes) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Serialized ``GetRateLimitsResp`` / ``GetPeerRateLimitsResp`` →
    ((4, n) int64 matrix of status/limit/remaining/reset_time, (n,) bool
    mask of items that carry an error string or metadata — re-parse those
    with protobuf for the strings).  None when unavailable/malformed."""
    lib = load()
    if lib is None:
        return None
    ln = len(data)
    n = lib.guber_wire_count(data, ln)
    if n < 0:
        return None
    mat = np.zeros((4, max(n, 1)), np.int64)
    special = np.zeros(max(n, 1), np.uint8)
    if n:
        got = lib.guber_parse_resp(
            data, ln, mat[0], mat[1], mat[2], mat[3], special
        )
        if got != n:
            return None
    return mat[:, :n], special[:n].astype(bool)


def encode_req(cols: ReqColumns, tag_peer: bool = False) -> Optional[bytes]:
    """Columns → serialized ``GetRateLimitsReq`` bytes (the identical
    outer shape serves ``GetPeerRateLimitsReq``; ``tag_peer`` is accepted
    for call-site clarity only).  Requires ``cols.name_len``; returns
    None when it (or the library) is missing — callers fall back to
    message objects."""
    n = len(cols)
    if n == 0:
        return b""
    lib = load()
    if lib is None or cols.name_len is None:
        return None
    has_created = (cols.created_at != CREATED_UNSET).astype(np.uint8)
    off = np.ascontiguousarray(cols.key_offsets, np.int64)
    name_len = np.ascontiguousarray(cols.name_len, np.int64)
    cap = int(off[n]) + 16 * n + 128
    while True:
        out = np.empty(cap, np.uint8)
        wrote = lib.guber_encode_req(
            native_mod.as_char_p(cols.key_blob), off, name_len,
            np.ascontiguousarray(cols.hits, np.int64),
            np.ascontiguousarray(cols.limit, np.int64),
            np.ascontiguousarray(cols.duration, np.int64),
            np.ascontiguousarray(cols.algorithm, np.int64),
            np.ascontiguousarray(cols.behavior, np.int64),
            np.ascontiguousarray(cols.burst, np.int64),
            np.ascontiguousarray(cols.created_at, np.int64),
            has_created, n, out, cap,
        )
        if wrote >= 0:
            return out[:wrote].tobytes()
        if wrote == -1:
            return None
        cap = -wrote


@hot_path
def encode_resp(mat: np.ndarray) -> bytes:
    """(5, n) response matrix → serialized ``GetRateLimitsResp`` bytes.
    Native when available, else the vectorized numpy encoder
    (:func:`transport.wire.encode_get_rate_limits_resp`) — identical
    bytes either way."""
    lib = load()
    if lib is None:
        from gubernator_tpu.transport.wire import encode_get_rate_limits_resp

        return encode_get_rate_limits_resp(mat)
    n = mat.shape[1]
    if n == 0:
        return b""
    rows = [np.ascontiguousarray(mat[r], np.int64) for r in range(4)]
    # Worst case per item: 44 B payload (4 fields x (1 tag + 10 B
    # varint)) + 2 B item header (1 B tag + 1 B length varint, since
    # payload <= 44 < 128) = 46 B.  The old 44 B/item budget under-sized
    # adversarial matrices (four 10-byte-varint fields) and leaned on
    # the retry below.
    cap = 8 + 46 * n
    out = np.empty(cap, np.uint8)
    wrote = lib.guber_encode_resp(rows[0], rows[1], rows[2], rows[3],
                                  n, out, cap)
    if wrote < 0:  # cap math above cannot under-size; belt and braces
        cap = -wrote if wrote < -1 else cap * 2
        out = np.empty(cap, np.uint8)
        wrote = lib.guber_encode_resp(rows[0], rows[1], rows[2], rows[3],
                                      n, out, cap)
        if wrote < 0:
            from gubernator_tpu.transport.wire import (
                encode_get_rate_limits_resp,
            )

            return encode_get_rate_limits_resp(mat)
    return out[:wrote].tobytes()


# ----------------------------------------------------------------------
# Quota-lease frames (docs/leases.md).
#
# Lease traffic happens at lease EDGES (grant, expiry, exhaustion,
# release) — orders of magnitude rarer than decisions — so these frames
# are pure-Python struct codecs, not native: the codec cost is
# irrelevant, while the native library must stay optional.  All frames
# are little-endian with a 4-byte magic + u32 count header; parsers
# return None on a magic/length mismatch (callers treat that exactly
# like a malformed protobuf: reject the RPC).

import struct as _struct

# Request frames are v2: they carry the leaseholder identity (the
# server accounts per-holder slices — docs/leases.md).  Parsers still
# accept the v1 frames (no holder field → the shared "" identity) so a
# not-yet-upgraded client keeps working against a v2 server.
_LEASE_GRANT_REQ_MAGIC = b"GLR2"
_LEASE_GRANT_REQ_MAGIC_V1 = b"GLR1"
_LEASE_GRANT_RESP_MAGIC = b"GLT1"
_LEASE_SYNC_REQ_MAGIC = b"GSY2"
_LEASE_SYNC_REQ_MAGIC_V1 = b"GSY1"
_LEASE_SYNC_RESP_MAGIC = b"GSA1"


def _pack_str(s: str) -> bytes:
    b = s.encode()
    return _struct.pack("<H", len(b)) + b


def _unpack_str(data: bytes, off: int):
    (ln,) = _struct.unpack_from("<H", data, off)
    off += 2
    return data[off : off + ln].decode(), off + ln


def encode_lease_grant_req(specs) -> bytes:
    """[LeaseSpec] → LeaseGrant request frame."""
    parts = [_LEASE_GRANT_REQ_MAGIC, _struct.pack("<I", len(specs))]
    for s in specs:
        parts.append(_struct.pack(
            "<qqqqq", s.limit, s.duration, s.algorithm, s.burst, s.want))
        parts.append(_pack_str(s.name))
        parts.append(_pack_str(s.key))
        parts.append(_pack_str(s.holder))
    return b"".join(parts)


def parse_lease_grant_req(data: bytes):
    """LeaseGrant request frame → [LeaseSpec] (None when malformed)."""
    from gubernator_tpu.leases.protocol import LeaseSpec

    try:
        magic = data[:4]
        if magic not in (_LEASE_GRANT_REQ_MAGIC,
                         _LEASE_GRANT_REQ_MAGIC_V1):
            return None
        v1 = magic == _LEASE_GRANT_REQ_MAGIC_V1
        (n,) = _struct.unpack_from("<I", data, 4)
        off = 8
        out = []
        for _ in range(n):
            limit, duration, algo, burst, want = _struct.unpack_from(
                "<qqqqq", data, off)
            off += 40
            name, off = _unpack_str(data, off)
            key, off = _unpack_str(data, off)
            holder = ""
            if not v1:
                holder, off = _unpack_str(data, off)
            out.append(LeaseSpec(
                name=name, key=key, limit=limit, duration=duration,
                algorithm=algo, burst=burst, want=want, holder=holder))
        return out if off == len(data) else None
    except (_struct.error, IndexError, UnicodeDecodeError):
        return None


def encode_lease_grant_resp(tokens) -> bytes:
    """[Optional[LeaseToken]] → LeaseGrant response frame (a None slot
    is an explicit declined marker: the bucket was too hot to delegate
    and the client must fall back to per-request decisions)."""
    parts = [_LEASE_GRANT_RESP_MAGIC, _struct.pack("<I", len(tokens))]
    for t in tokens:
        if t is None:
            parts.append(b"\x00")
            continue
        parts.append(b"\x01")
        parts.append(_struct.pack("<qqq", t.budget, t.expires_ms,
                                  t.generation))
        parts.append(_pack_str(t.name))
        parts.append(_pack_str(t.key))
        parts.append(_struct.pack("<H", len(t.signature)))
        parts.append(t.signature)
    return b"".join(parts)


def parse_lease_grant_resp(data: bytes):
    """LeaseGrant response frame → [Optional[LeaseToken]]."""
    from gubernator_tpu.leases.protocol import LeaseToken

    try:
        if data[:4] != _LEASE_GRANT_RESP_MAGIC:
            return None
        (n,) = _struct.unpack_from("<I", data, 4)
        off = 8
        out = []
        for _ in range(n):
            present = data[off]
            off += 1
            if not present:
                out.append(None)
                continue
            budget, expires_ms, gen = _struct.unpack_from("<qqq", data, off)
            off += 24
            name, off = _unpack_str(data, off)
            key, off = _unpack_str(data, off)
            (siglen,) = _struct.unpack_from("<H", data, off)
            off += 2
            sig = data[off : off + siglen]
            off += siglen
            out.append(LeaseToken(
                name=name, key=key, budget=budget, expires_ms=expires_ms,
                generation=gen, signature=sig))
        return out if off == len(data) else None
    except (_struct.error, IndexError, UnicodeDecodeError):
        return None


def encode_lease_sync_req(syncs) -> bytes:
    """[LeaseSync] → LeaseSync request frame."""
    parts = [_LEASE_SYNC_REQ_MAGIC, _struct.pack("<I", len(syncs))]
    for s in syncs:
        parts.append(_struct.pack(
            "<qqB", s.consumed, s.generation, 1 if s.release else 0))
        parts.append(_pack_str(s.name))
        parts.append(_pack_str(s.key))
        parts.append(_pack_str(s.holder))
    return b"".join(parts)


def parse_lease_sync_req(data: bytes):
    """LeaseSync request frame → [LeaseSync]."""
    from gubernator_tpu.leases.protocol import LeaseSync

    try:
        magic = data[:4]
        if magic not in (_LEASE_SYNC_REQ_MAGIC,
                         _LEASE_SYNC_REQ_MAGIC_V1):
            return None
        v1 = magic == _LEASE_SYNC_REQ_MAGIC_V1
        (n,) = _struct.unpack_from("<I", data, 4)
        off = 8
        out = []
        for _ in range(n):
            consumed, gen, release = _struct.unpack_from("<qqB", data, off)
            off += 17
            name, off = _unpack_str(data, off)
            key, off = _unpack_str(data, off)
            holder = ""
            if not v1:
                holder, off = _unpack_str(data, off)
            out.append(LeaseSync(
                name=name, key=key, consumed=consumed, generation=gen,
                release=bool(release), holder=holder))
        return out if off == len(data) else None
    except (_struct.error, IndexError, UnicodeDecodeError):
        return None


def encode_lease_sync_resp(acks) -> bytes:
    """[LeaseSyncAck] → LeaseSync response frame."""
    parts = [_LEASE_SYNC_RESP_MAGIC, _struct.pack("<I", len(acks))]
    for a in acks:
        parts.append(_struct.pack(
            "<Bqqq", 1 if a.accepted else 0, a.generation,
            a.credited, a.charged))
    return b"".join(parts)


def parse_lease_sync_resp(data: bytes):
    """LeaseSync response frame → [LeaseSyncAck]."""
    from gubernator_tpu.leases.protocol import LeaseSyncAck

    try:
        if data[:4] != _LEASE_SYNC_RESP_MAGIC:
            return None
        (n,) = _struct.unpack_from("<I", data, 4)
        off = 8
        out = []
        for _ in range(n):
            accepted, gen, credited, charged = _struct.unpack_from(
                "<Bqqq", data, off)
            off += 25
            out.append(LeaseSyncAck(
                accepted=bool(accepted), generation=gen,
                credited=credited, charged=charged))
        return out if off == len(data) else None
    except (_struct.error, IndexError, UnicodeDecodeError):
        return None


# ----------------------------------------------------------------------
# Multi-region federation frames (docs/federation.md).
#
# Envelope exchange happens once per GUBER_FEDERATION_INTERVAL per remote
# region — WAN cadence, not decision cadence — so like the lease frames
# these are pure-Python struct codecs.  The version rides the magic
# (GFE1/GFA1): a receiver that doesn't recognize the magic rejects the
# RPC, which the sender's breaker/redelivery path treats like any other
# failure — a mixed-version fleet degrades to intra-region-only instead
# of corrupting state.

_FED_ENVELOPE_MAGIC = b"GFE1"
_FED_ACK_MAGIC = b"GFA1"


def encode_federation_envelope(env) -> bytes:
    """FederationEnvelope → GFE1 frame."""
    parts = [
        _FED_ENVELOPE_MAGIC,
        _struct.pack("<q", env.seq),
        _pack_str(env.origin),
        _pack_str(env.region),
        _pack_str(env.epoch),
        _struct.pack("<I", len(env.records)),
    ]
    for rec in env.records:
        parts.append(_struct.pack(
            "<qqqqqqq", rec.hits, rec.limit, rec.duration, rec.algorithm,
            rec.behavior, rec.burst, rec.created_at))
        parts.append(_pack_str(rec.name))
        parts.append(_pack_str(rec.unique_key))
    return b"".join(parts)


def parse_federation_envelope(data: bytes):
    """GFE1 frame → FederationEnvelope (None when malformed)."""
    from gubernator_tpu.federation.envelope import (
        FederationEnvelope,
        FederationRecord,
    )

    try:
        if data[:4] != _FED_ENVELOPE_MAGIC:
            return None
        (seq,) = _struct.unpack_from("<q", data, 4)
        off = 12
        origin, off = _unpack_str(data, off)
        region, off = _unpack_str(data, off)
        epoch, off = _unpack_str(data, off)
        (n,) = _struct.unpack_from("<I", data, off)
        off += 4
        records = []
        for _ in range(n):
            hits, limit, duration, algo, behavior, burst, created = (
                _struct.unpack_from("<qqqqqqq", data, off))
            off += 56
            name, off = _unpack_str(data, off)
            key, off = _unpack_str(data, off)
            records.append(FederationRecord(
                name=name, unique_key=key, hits=hits, limit=limit,
                duration=duration, algorithm=algo, behavior=behavior,
                burst=burst, created_at=created))
        env = FederationEnvelope(
            origin=origin, region=region, epoch=epoch, seq=seq,
            records=records)
        return env if off == len(data) else None
    except (_struct.error, IndexError, UnicodeDecodeError):
        return None


def encode_federation_ack(ack) -> bytes:
    """FederationAck → GFA1 frame."""
    return b"".join([
        _FED_ACK_MAGIC,
        _struct.pack("<qq", ack.seq, ack.applied),
        _pack_str(ack.origin),
    ])


def parse_federation_ack(data: bytes):
    """GFA1 frame → FederationAck (None when malformed)."""
    from gubernator_tpu.federation.envelope import FederationAck

    try:
        if data[:4] != _FED_ACK_MAGIC:
            return None
        seq, applied = _struct.unpack_from("<qq", data, 4)
        off = 20
        origin, off = _unpack_str(data, off)
        ack = FederationAck(origin=origin, seq=seq, applied=applied)
        return ack if off == len(data) else None
    except (_struct.error, IndexError, UnicodeDecodeError):
        return None
