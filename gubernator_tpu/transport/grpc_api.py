"""Hand-written gRPC service/client bindings for the V1 and PeersV1 services.

The environment ships ``protoc`` (messages) but not the grpc codegen
plugin, so the service plumbing the plugin would emit — method handlers on
the server side, unary-unary stubs on the client side — is written here
directly against the public ``grpc`` API.  Method paths match the
reference's generated code (``/pb.gubernator.V1/GetRateLimits`` etc.,
reference gubernator_grpc.pb.go / peers_grpc.pb.go) so reference clients
and servers interoperate on the wire.
"""

from __future__ import annotations

import grpc

from gubernator_tpu.pb import gubernator_pb2 as pb
from gubernator_tpu.pb import peers_pb2 as peers_pb

V1_SERVICE = "pb.gubernator.V1"
PEERS_SERVICE = "pb.gubernator.PeersV1"


def v1_handler(servicer) -> grpc.GenericRpcHandler:
    """Generic handler for the public V1 service.

    ``servicer`` provides async (or sync) methods ``GetRateLimits(req,
    context)`` and ``HealthCheck(req, context)`` over pb messages.
    """
    return grpc.method_handlers_generic_handler(
        V1_SERVICE,
        {
            "GetRateLimits": grpc.unary_unary_rpc_method_handler(
                servicer.GetRateLimits,
                # Pass-through BOTH ways: the servicer parses the raw
                # bytes with the native codec (transport/fastwire.py)
                # and the fast path hands back already-encoded
                # GetRateLimitsResp bytes; object responses (errors/
                # metadata) still serialize normally.
                request_deserializer=lambda b: b,
                response_serializer=lambda m: (
                    m if isinstance(m, bytes) else m.SerializeToString()
                ),
            ),
            "HealthCheck": grpc.unary_unary_rpc_method_handler(
                servicer.HealthCheck,
                request_deserializer=pb.HealthCheckReq.FromString,
                response_serializer=pb.HealthCheckResp.SerializeToString,
            ),
            # Quota-lease methods (docs/leases.md): pass-through bytes
            # both ways — the servicer runs the lease frame codecs
            # (transport/fastwire.py), no pb messages involved.  Only
            # registered when the servicer implements leases, so older
            # daemons keep exporting exactly the reference surface.
            **({
                "LeaseGrant": grpc.unary_unary_rpc_method_handler(
                    servicer.LeaseGrant,
                    request_deserializer=lambda b: b,
                    response_serializer=lambda m: m,
                ),
                "LeaseSync": grpc.unary_unary_rpc_method_handler(
                    servicer.LeaseSync,
                    request_deserializer=lambda b: b,
                    response_serializer=lambda m: m,
                ),
            } if hasattr(servicer, "LeaseGrant") else {}),
            # Federation envelope exchange (docs/federation.md): raw
            # GFE1/GFA1 frames, registered only when the servicer wires
            # a FederationManager.
            **({
                "FederationSync": grpc.unary_unary_rpc_method_handler(
                    servicer.FederationSync,
                    request_deserializer=lambda b: b,
                    response_serializer=lambda m: m,
                ),
            } if hasattr(servicer, "FederationSync") else {}),
        },
    )


def peers_handler(servicer) -> grpc.GenericRpcHandler:
    """Generic handler for the peer-to-peer PeersV1 service."""
    return grpc.method_handlers_generic_handler(
        PEERS_SERVICE,
        {
            "GetPeerRateLimits": grpc.unary_unary_rpc_method_handler(
                servicer.GetPeerRateLimits,
                # Pass-through both ways, like V1.GetRateLimits: the
                # servicer runs the native codec on the raw bytes.
                request_deserializer=lambda b: b,
                response_serializer=lambda m: (
                    m if isinstance(m, bytes) else m.SerializeToString()
                ),
            ),
            "UpdatePeerGlobals": grpc.unary_unary_rpc_method_handler(
                servicer.UpdatePeerGlobals,
                request_deserializer=peers_pb.UpdatePeerGlobalsReq.FromString,
                response_serializer=peers_pb.UpdatePeerGlobalsResp.SerializeToString,
            ),
        },
    )


class V1Stub:
    """Client stub for the public service (works with sync or aio channels)."""

    def __init__(self, channel):
        self.GetRateLimits = channel.unary_unary(
            f"/{V1_SERVICE}/GetRateLimits",
            request_serializer=pb.GetRateLimitsReq.SerializeToString,
            response_deserializer=pb.GetRateLimitsResp.FromString,
        )
        self.HealthCheck = channel.unary_unary(
            f"/{V1_SERVICE}/HealthCheck",
            request_serializer=pb.HealthCheckReq.SerializeToString,
            response_deserializer=pb.HealthCheckResp.FromString,
        )
        # Raw-bytes lease methods (frame codecs in transport/fastwire.py).
        self.LeaseGrant = channel.unary_unary(
            f"/{V1_SERVICE}/LeaseGrant",
            request_serializer=lambda b: b,
            response_deserializer=lambda b: b,
        )
        self.LeaseSync = channel.unary_unary(
            f"/{V1_SERVICE}/LeaseSync",
            request_serializer=lambda b: b,
            response_deserializer=lambda b: b,
        )
        self.FederationSync = channel.unary_unary(
            f"/{V1_SERVICE}/FederationSync",
            request_serializer=lambda b: b,
            response_deserializer=lambda b: b,
        )


class PeersV1Stub:
    """Client stub for the peer service."""

    def __init__(self, channel):
        self.GetPeerRateLimits = channel.unary_unary(
            f"/{PEERS_SERVICE}/GetPeerRateLimits",
            request_serializer=peers_pb.GetPeerRateLimitsReq.SerializeToString,
            response_deserializer=peers_pb.GetPeerRateLimitsResp.FromString,
        )
        self.UpdatePeerGlobals = channel.unary_unary(
            f"/{PEERS_SERVICE}/UpdatePeerGlobals",
            request_serializer=peers_pb.UpdatePeerGlobalsReq.SerializeToString,
            response_deserializer=peers_pb.UpdatePeerGlobalsResp.FromString,
        )
