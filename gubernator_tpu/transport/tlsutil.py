"""TLS setup: file-based certs, AutoTLS self-signing, client-auth modes.

Re-creates the reference's TLS surface (``tls.go``): load CA/cert/key from
files, or — with ``auto_tls`` — generate a throwaway CA and a per-host
server certificate with SANs for localhost + discovered interface addresses
(``tls.go:293,390``).  Client-auth modes mirror ``config.go:368-373``.

Produces both ``grpc`` credentials (server + channel) and an ``ssl`` context
for the HTTPS gateway.
"""

from __future__ import annotations

import datetime
import ipaddress
import socket
import ssl
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

import grpc

# ``cryptography`` is only needed for AutoTLS self-signing; file-based
# certs and plaintext daemons must work without it (slim containers omit
# it), so the import is gated, not required at module load.
try:
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import rsa
    from cryptography.x509.oid import NameOID

    HAVE_CRYPTO = True
except ImportError:  # pragma: no cover - depends on container build
    x509 = hashes = serialization = rsa = NameOID = None
    HAVE_CRYPTO = False

from gubernator_tpu.config import TLSSettings

CLIENT_AUTH_MODES = {
    "": False,
    "request": False,
    "verify-if-given": False,
    "require": True,
    "require-and-verify": True,
}


@dataclass
class TLSBundle:
    """Everything the daemon needs: PEM blobs + derived credential objects."""

    ca_pem: bytes = b""
    cert_pem: bytes = b""
    key_pem: bytes = b""
    client_cert_pem: bytes = b""
    client_key_pem: bytes = b""
    client_auth_ca_pem: bytes = b""
    settings: TLSSettings = field(default_factory=TLSSettings)

    # ------------------------------------------------------------------
    def server_credentials(self) -> grpc.ServerCredentials:
        require = CLIENT_AUTH_MODES.get(self.settings.client_auth, False)
        root = self.client_auth_ca_pem or self.ca_pem
        return grpc.ssl_server_credentials(
            [(self.key_pem, self.cert_pem)],
            root_certificates=root if self.settings.client_auth else None,
            require_client_auth=require,
        )

    def channel_credentials(self) -> grpc.ChannelCredentials:
        cert = self.client_cert_pem or self.cert_pem
        key = self.client_key_pem or self.key_pem
        return grpc.ssl_channel_credentials(
            root_certificates=self.ca_pem or None,
            private_key=key or None,
            certificate_chain=cert or None,
        )

    def server_ssl_context(self) -> ssl.SSLContext:
        """SSL context for the HTTPS gateway listener."""
        import tempfile

        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        if self.settings.min_version == "1.3":
            ctx.minimum_version = ssl.TLSVersion.TLSv1_3
        else:
            ctx.minimum_version = ssl.TLSVersion.TLSv1_2
        with tempfile.NamedTemporaryFile(suffix=".pem") as cf, \
                tempfile.NamedTemporaryFile(suffix=".pem") as kf:
            cf.write(self.cert_pem)
            cf.flush()
            kf.write(self.key_pem)
            kf.flush()
            ctx.load_cert_chain(cf.name, kf.name)
        if self.settings.client_auth:
            ctx.verify_mode = (
                ssl.CERT_REQUIRED
                if CLIENT_AUTH_MODES.get(self.settings.client_auth, False)
                else ssl.CERT_OPTIONAL
            )
            import tempfile as _tf

            with _tf.NamedTemporaryFile(suffix=".pem") as caf:
                caf.write(self.client_auth_ca_pem or self.ca_pem)
                caf.flush()
                ctx.load_verify_locations(caf.name)
        return ctx

    def client_ssl_context(self) -> ssl.SSLContext:
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
        if self.settings.insecure_skip_verify:
            ctx.check_hostname = False
            ctx.verify_mode = ssl.CERT_NONE
        elif self.ca_pem:
            import tempfile

            with tempfile.NamedTemporaryFile(suffix=".pem") as caf:
                caf.write(self.ca_pem)
                caf.flush()
                ctx.load_verify_locations(caf.name)
        if self.client_cert_pem and self.client_key_pem:
            import tempfile

            with tempfile.NamedTemporaryFile(suffix=".pem") as cf, \
                    tempfile.NamedTemporaryFile(suffix=".pem") as kf:
                cf.write(self.client_cert_pem)
                cf.flush()
                kf.write(self.client_key_pem)
                kf.flush()
                ctx.load_cert_chain(cf.name, kf.name)
        return ctx


def _discover_san_addresses() -> Tuple[List[str], List[str]]:
    """DNS names + IPs for the AutoTLS server cert (tls.go SAN discovery via
    net.go:86 interface scan)."""
    names = ["localhost", socket.gethostname()]
    ips = ["127.0.0.1", "::1"]
    try:
        for info in socket.getaddrinfo(socket.gethostname(), None):
            addr = info[4][0]
            if addr not in ips:
                ips.append(addr)
    except OSError:
        pass
    return names, ips


def _gen_key() -> rsa.RSAPrivateKey:
    return rsa.generate_private_key(public_exponent=65537, key_size=2048)


def _key_pem(key) -> bytes:
    return key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.TraditionalOpenSSL,
        serialization.NoEncryption(),
    )


def generate_self_ca() -> Tuple[bytes, bytes, x509.Certificate, rsa.RSAPrivateKey]:
    """Throwaway CA for AutoTLS (tls.go:390 selfCA)."""
    key = _gen_key()
    name = x509.Name(
        [x509.NameAttribute(NameOID.COMMON_NAME, "gubernator-tpu auto CA")]
    )
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (
        x509.CertificateBuilder()
        .subject_name(name)
        .issuer_name(name)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(days=365))
        .add_extension(x509.BasicConstraints(ca=True, path_length=None), critical=True)
        .add_extension(
            x509.KeyUsage(
                digital_signature=True, key_cert_sign=True, crl_sign=True,
                content_commitment=False, key_encipherment=False,
                data_encipherment=False, key_agreement=False,
                encipher_only=False, decipher_only=False,
            ),
            critical=True,
        )
        .sign(key, hashes.SHA256())
    )
    return cert.public_bytes(serialization.Encoding.PEM), _key_pem(key), cert, key


def generate_cert(
    ca_cert: x509.Certificate,
    ca_key: rsa.RSAPrivateKey,
    *,
    client: bool = False,
    common_name: str = "",
    extra_dns: Tuple[str, ...] = (),
) -> Tuple[bytes, bytes]:
    """Server (or client) certificate signed by the auto CA, SANs covering
    localhost + discovered interface addresses (tls.go:293) plus any
    ``extra_dns`` names (compose/k8s service names)."""
    key = _gen_key()
    names, ips = _discover_san_addresses()
    names = list(names) + [n for n in extra_dns if n not in names]
    cn = common_name or (names[1] if len(names) > 1 else "localhost")
    san: List[x509.GeneralName] = [x509.DNSName(n) for n in names]
    for ip in ips:
        try:
            san.append(x509.IPAddress(ipaddress.ip_address(ip)))
        except ValueError:
            pass
    usage = (
        [x509.ExtendedKeyUsageOID.CLIENT_AUTH]
        if client
        else [x509.ExtendedKeyUsageOID.SERVER_AUTH, x509.ExtendedKeyUsageOID.CLIENT_AUTH]
    )
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (
        x509.CertificateBuilder()
        .subject_name(x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, cn)]))
        .issuer_name(ca_cert.subject)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(days=365))
        .add_extension(x509.SubjectAlternativeName(san), critical=False)
        .add_extension(x509.ExtendedKeyUsage(usage), critical=False)
        .sign(ca_key, hashes.SHA256())
    )
    return cert.public_bytes(serialization.Encoding.PEM), _key_pem(key)


def _read(path: str) -> bytes:
    with open(path, "rb") as f:
        return f.read()


def setup_tls(settings: Optional[TLSSettings]) -> Optional[TLSBundle]:
    """Build the TLS bundle from settings (reference SetupTLS, tls.go:140):
    files when given, AutoTLS generation otherwise; returns None when TLS is
    disabled."""
    if settings is None or not settings.enabled:
        return None
    b = TLSBundle(settings=settings)
    if settings.ca_file:
        b.ca_pem = _read(settings.ca_file)
    if settings.cert_file:
        b.cert_pem = _read(settings.cert_file)
    if settings.key_file:
        b.key_pem = _read(settings.key_file)
    if settings.client_auth_ca_file:
        b.client_auth_ca_pem = _read(settings.client_auth_ca_file)
    if settings.client_auth_cert_file:
        b.client_cert_pem = _read(settings.client_auth_cert_file)
    if settings.client_auth_key_file:
        b.client_key_pem = _read(settings.client_auth_key_file)

    if settings.auto_tls and not (b.cert_pem and b.key_pem):
        if not HAVE_CRYPTO:
            raise RuntimeError(
                "AutoTLS needs the 'cryptography' package; install it or "
                "point GUBER_TLS_CERT/GUBER_TLS_KEY at existing files"
            )
        if settings.ca_file and settings.ca_key_file:
            ca_pem, ca_key_pem = b.ca_pem, _read(settings.ca_key_file)
            ca_cert = x509.load_pem_x509_certificate(ca_pem)
            ca_key = serialization.load_pem_private_key(ca_key_pem, None)
        else:
            ca_pem, _ca_key_pem, ca_cert, ca_key = generate_self_ca()
            b.ca_pem = ca_pem
        b.cert_pem, b.key_pem = generate_cert(ca_cert, ca_key)
        if settings.client_auth:
            b.client_cert_pem, b.client_key_pem = generate_cert(
                ca_cert, ca_key, client=True
            )
            if not b.client_auth_ca_pem:
                b.client_auth_ca_pem = b.ca_pem
    return b


def main(argv=None) -> int:
    """Cert-dir generator for the compose/k8s TLS deployments:

        python -m gubernator_tpu.transport.tlsutil gen <dir> [dns-name ...]

    Writes ``ca.pem``, ``ca.key``, ``gubernator.pem``, ``gubernator.key``
    — the file names docker-compose-tls.yaml mounts (the reference ships
    pre-generated equivalents in contrib/certs)."""
    import argparse
    import os
    import sys

    p = argparse.ArgumentParser(description="gubernator-tpu cert generator")
    p.add_argument("command", choices=["gen"])
    p.add_argument("dir")
    p.add_argument("dns", nargs="*",
                   help="extra SAN dns names (e.g. compose service names)")
    args = p.parse_args(argv)

    if not HAVE_CRYPTO:
        print("the cert generator needs the 'cryptography' package",
              file=sys.stderr)
        return 2
    ca_pem, ca_key_pem, ca_cert, ca_key = generate_self_ca()
    cert_pem, key_pem = generate_cert(
        ca_cert, ca_key, extra_dns=tuple(args.dns)
    )
    os.makedirs(args.dir, exist_ok=True)
    for fname, data in (
        ("ca.pem", ca_pem),
        ("ca.key", ca_key_pem),
        ("gubernator.pem", cert_pem),
        ("gubernator.key", key_pem),
    ):
        path = os.path.join(args.dir, fname)
        mode = 0o600 if fname.endswith(".key") else 0o644
        fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, mode)
        with os.fdopen(fd, "wb") as f:
            f.write(data)
        os.chmod(path, mode)  # O_CREAT mode is ignored for existing files
        print(f"wrote {path}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
