"""Transport layer: gRPC bindings, JSON gateway, daemon shell."""
