"""Conversions between wire protos and the engine's dataclasses.

The engine layer (ops/, service) works with plain dataclasses
(:mod:`gubernator_tpu.types`) so it has no protobuf dependency; the
transport edge converts.  `created_at` uses proto3 `optional` presence —
absence means "server stamps now" (reference gubernator.proto:172-182).
"""

from __future__ import annotations

from typing import Iterable, List

from gubernator_tpu.pb import gubernator_pb2 as pb
from gubernator_tpu.types import RateLimitRequest, RateLimitResponse


def req_from_pb(m: pb.RateLimitReq) -> RateLimitRequest:
    return RateLimitRequest(
        name=m.name,
        unique_key=m.unique_key,
        hits=m.hits,
        limit=m.limit,
        duration=m.duration,
        algorithm=int(m.algorithm),
        behavior=int(m.behavior),
        burst=m.burst,
        metadata=dict(m.metadata),
        created_at=m.created_at if m.HasField("created_at") else None,
    )


def req_to_pb(r: RateLimitRequest) -> pb.RateLimitReq:
    m = pb.RateLimitReq(
        name=r.name,
        unique_key=r.unique_key,
        hits=r.hits,
        limit=r.limit,
        duration=r.duration,
        algorithm=r.algorithm,
        behavior=r.behavior,
        burst=r.burst,
    )
    for k, v in r.metadata.items():
        m.metadata[k] = v
    if r.created_at is not None:
        m.created_at = r.created_at
    return m


def resp_from_pb(m: pb.RateLimitResp) -> RateLimitResponse:
    return RateLimitResponse(
        status=int(m.status),
        limit=m.limit,
        remaining=m.remaining,
        reset_time=m.reset_time,
        error=m.error,
        metadata=dict(m.metadata),
    )


def resp_to_pb(r: RateLimitResponse) -> pb.RateLimitResp:
    m = pb.RateLimitResp(
        status=r.status,
        limit=r.limit,
        remaining=r.remaining,
        reset_time=r.reset_time,
        error=r.error,
    )
    for k, v in r.metadata.items():
        m.metadata[k] = v
    return m


def reqs_from_pb(ms: Iterable[pb.RateLimitReq]) -> List[RateLimitRequest]:
    return [req_from_pb(m) for m in ms]


def resps_to_pb(rs: Iterable[RateLimitResponse]) -> List[pb.RateLimitResp]:
    return [resp_to_pb(r) for r in rs]
