"""Conversions between wire protos and the engine's dataclasses.

The engine layer (ops/, service) works with plain dataclasses
(:mod:`gubernator_tpu.types`) so it has no protobuf dependency; the
transport edge converts.  `created_at` uses proto3 `optional` presence —
absence means "server stamps now" (reference gubernator.proto:172-182).
"""

from __future__ import annotations

from typing import Iterable, List

from gubernator_tpu.pb import gubernator_pb2 as pb
from gubernator_tpu.types import RateLimitRequest, RateLimitResponse


def req_from_pb(m: pb.RateLimitReq) -> RateLimitRequest:
    return RateLimitRequest(
        name=m.name,
        unique_key=m.unique_key,
        hits=m.hits,
        limit=m.limit,
        duration=m.duration,
        algorithm=int(m.algorithm),
        behavior=int(m.behavior),
        burst=m.burst,
        metadata=dict(m.metadata),
        created_at=m.created_at if m.HasField("created_at") else None,
    )


def req_to_pb(r: RateLimitRequest) -> pb.RateLimitReq:
    m = pb.RateLimitReq(
        name=r.name,
        unique_key=r.unique_key,
        hits=r.hits,
        limit=r.limit,
        duration=r.duration,
        algorithm=r.algorithm,
        behavior=r.behavior,
        burst=r.burst,
    )
    for k, v in r.metadata.items():
        m.metadata[k] = v
    if r.created_at is not None:
        m.created_at = r.created_at
    return m


def resp_from_pb(m: pb.RateLimitResp) -> RateLimitResponse:
    return RateLimitResponse(
        status=int(m.status),
        limit=m.limit,
        remaining=m.remaining,
        reset_time=m.reset_time,
        error=m.error,
        metadata=dict(m.metadata),
    )


def resp_to_pb(r: RateLimitResponse) -> pb.RateLimitResp:
    m = pb.RateLimitResp(
        status=r.status,
        limit=r.limit,
        remaining=r.remaining,
        reset_time=r.reset_time,
        error=r.error,
    )
    for k, v in r.metadata.items():
        m.metadata[k] = v
    return m


def reqs_from_pb(ms: Iterable[pb.RateLimitReq]) -> List[RateLimitRequest]:
    return [req_from_pb(m) for m in ms]


def columns_from_pb(ms) -> tuple:
    """Parse a repeated RateLimitReq straight into a columnar batch —
    the wire→device fast path with no per-request dataclasses.

    Returns ``(cols, errors, special)``: per-item validation errors
    (empty name/unique_key, the reference's error-in-item convention,
    gubernator.go:208-216) and ``special`` = True when any item carries
    GLOBAL or MULTI_REGION behavior or metadata (trace context) — those
    need the object-routing path.  ``created_at == 0`` means "server stamps now"
    (matching V1Instance's object path, gubernator.go:218-220).
    """
    import numpy as np

    from gubernator_tpu.algos import algorithm_error, invalid_algorithm_mask
    from gubernator_tpu.ops.reqcols import CREATED_UNSET, ReqColumns, pack_blob
    from gubernator_tpu.types import Behavior

    n = len(ms)
    if n == 0:
        return ReqColumns.empty(), {}, False
    SPECIAL = int(Behavior.GLOBAL) | int(Behavior.MULTI_REGION)
    keys: List[bytes] = [b""] * n
    hits = [0] * n
    limit = [0] * n
    duration = [0] * n
    algorithm = [0] * n
    behavior = [0] * n
    created = [0] * n
    burst = [0] * n
    errors = {}
    special = False
    for i, m in enumerate(ms):
        uk = m.unique_key
        nm = m.name
        if uk == "":
            errors[i] = "field 'unique_key' cannot be empty"
        elif nm == "":
            errors[i] = "field 'namespace' cannot be empty"
        else:
            if invalid_algorithm_mask(int(m.algorithm)):
                # Unknown enum values must NOT fall through the kernels'
                # branchless dispatch as token-bucket (algos/__init__.py).
                errors[i] = algorithm_error(m.algorithm)
            # The key is well-formed even when the algorithm is not —
            # keep it in the blob (fastwire.parse_req parity; batches
            # with errors never reach the columns tick path).
            keys[i] = (nm + "_" + uk).encode()
        hits[i] = m.hits
        limit[i] = m.limit
        duration[i] = m.duration
        algorithm[i] = m.algorithm
        b = behavior[i] = m.behavior
        created[i] = m.created_at or CREATED_UNSET
        burst[i] = m.burst
        if (b & SPECIAL) or m.metadata:
            special = True
    a = lambda v: np.asarray(v, np.int64)  # noqa: E731
    blob, offsets = pack_blob(keys)
    return (
        ReqColumns(
            blob, offsets, a(hits), a(limit), a(duration), a(algorithm),
            a(behavior), a(created), a(burst),
        ),
        errors,
        special,
    )


def resps_to_pb(rs: Iterable[RateLimitResponse]) -> List[pb.RateLimitResp]:
    return [resp_to_pb(r) for r in rs]
