"""Generated protobuf stubs (see scripts/genproto.sh)."""

from . import gubernator_pb2, peers_pb2  # noqa: F401
