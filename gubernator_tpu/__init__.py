"""gubernator_tpu — a TPU-native distributed rate-limiting framework.

A from-scratch re-design of the capabilities of Gubernator
(github.com/gubernator-io/gubernator, reference layout surveyed in
/root/repo/SURVEY.md) for TPU hardware:

* The per-key bucket arithmetic (reference ``algorithms.go``) becomes a
  branch-free, vectorized state transition over struct-of-arrays bucket
  state resident in HBM (:mod:`gubernator_tpu.ops.buckets`).
* The goroutine-per-request worker pool (reference ``workers.go``) becomes
  a tick-batched device step: requests accumulate on the host and are
  flushed to the TPU once per tick (:mod:`gubernator_tpu.ops.engine`).
* The GLOBAL behavior's hit-aggregation / broadcast fabric (reference
  ``global.go``) becomes collectives (``psum`` / ``all_gather``) over a
  ``jax.sharding.Mesh`` (:mod:`gubernator_tpu.parallel.global_sync`).
* The gRPC/HTTP API surface, consistent-hash peering, behaviors, config
  and observability match the reference's wire contract.

Importing this package does NOT import jax: the device bootstrap (x64
mode + compile cache, required before any device use) lives in
:mod:`gubernator_tpu.jaxinit`, which every jax-using module imports
before ``import jax``.  That keeps device-free entry points — the
container healthcheck probe, config parsing, and the static-analysis
CLI (``python -m gubernator_tpu.analysis``) — free of the multi-second
jax import and of the toolchain dependency entirely.
"""

from gubernator_tpu.types import (
    Algorithm,
    Behavior,
    Status,
    RateLimitRequest,
    RateLimitResponse,
)

from gubernator_tpu.version import VERSION as __version__


def configure_compile_cache(environ=None) -> None:
    """Re-apply the compile-cache knob (see jaxinit.configure_compile_cache;
    kept here because setup_daemon_config and operator code call it via the
    package root).  Imports jax — only call on a device-serving path."""
    from gubernator_tpu import jaxinit

    jaxinit.configure_compile_cache(environ)


__all__ = [
    "Algorithm",
    "Behavior",
    "Status",
    "RateLimitRequest",
    "RateLimitResponse",
    "configure_compile_cache",
    "__version__",
]
