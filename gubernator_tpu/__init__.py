"""gubernator_tpu — a TPU-native distributed rate-limiting framework.

A from-scratch re-design of the capabilities of Gubernator
(github.com/gubernator-io/gubernator, reference layout surveyed in
/root/repo/SURVEY.md) for TPU hardware:

* The per-key bucket arithmetic (reference ``algorithms.go``) becomes a
  branch-free, vectorized state transition over struct-of-arrays bucket
  state resident in HBM (:mod:`gubernator_tpu.ops.buckets`).
* The goroutine-per-request worker pool (reference ``workers.go``) becomes
  a tick-batched device step: requests accumulate on the host and are
  flushed to the TPU once per tick (:mod:`gubernator_tpu.ops.engine`).
* The GLOBAL behavior's hit-aggregation / broadcast fabric (reference
  ``global.go``) becomes collectives (``psum`` / ``all_gather``) over a
  ``jax.sharding.Mesh`` (:mod:`gubernator_tpu.parallel.global_sync`).
* The gRPC/HTTP API surface, consistent-hash peering, behaviors, config
  and observability match the reference's wire contract.

64-bit mode is required: the wire contract is int64 milliseconds /
int64 hits-limits, and leaky-bucket remaining is float64.
"""

import jax

jax.config.update("jax_enable_x64", True)

from gubernator_tpu.types import (  # noqa: E402
    Algorithm,
    Behavior,
    Status,
    RateLimitRequest,
    RateLimitResponse,
)

__version__ = "0.2.0"

__all__ = [
    "Algorithm",
    "Behavior",
    "Status",
    "RateLimitRequest",
    "RateLimitResponse",
    "__version__",
]
