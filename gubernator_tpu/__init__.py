"""gubernator_tpu — a TPU-native distributed rate-limiting framework.

A from-scratch re-design of the capabilities of Gubernator
(github.com/gubernator-io/gubernator, reference layout surveyed in
/root/repo/SURVEY.md) for TPU hardware:

* The per-key bucket arithmetic (reference ``algorithms.go``) becomes a
  branch-free, vectorized state transition over struct-of-arrays bucket
  state resident in HBM (:mod:`gubernator_tpu.ops.buckets`).
* The goroutine-per-request worker pool (reference ``workers.go``) becomes
  a tick-batched device step: requests accumulate on the host and are
  flushed to the TPU once per tick (:mod:`gubernator_tpu.ops.engine`).
* The GLOBAL behavior's hit-aggregation / broadcast fabric (reference
  ``global.go``) becomes collectives (``psum`` / ``all_gather``) over a
  ``jax.sharding.Mesh`` (:mod:`gubernator_tpu.parallel.global_sync`).
* The gRPC/HTTP API surface, consistent-hash peering, behaviors, config
  and observability match the reference's wire contract.

64-bit mode is required: the wire contract is int64 milliseconds /
int64 hits-limits, and leaky-bucket remaining is float64.
"""

import os

import jax

jax.config.update("jax_enable_x64", True)

def configure_compile_cache(environ=None) -> None:
    """Persistent XLA compilation cache, on by default: tick-program
    compiles cost tens of seconds on TPU toolchains and recur on every
    daemon restart otherwise (measured 30s -> 8.5s cold start cached).

    ``GUBER_COMPILE_CACHE_DIR=off`` disables; any other value overrides
    the location; an explicit ``JAX_COMPILATION_CACHE_DIR`` always wins.
    Runs at import AND again from ``setup_daemon_config`` so the knob
    also works from a ``-config`` file (which loads into the environment
    after import)."""
    env = os.environ if environ is None else environ
    cache_dir = env.get("GUBER_COMPILE_CACHE_DIR", "")
    if cache_dir.lower() in ("off", "0", "false"):
        jax.config.update("jax_compilation_cache_dir", None)
        return
    if env.get("JAX_COMPILATION_CACHE_DIR"):
        # jax bound this option at import time; a -config file loads the
        # env var after import, so re-apply it explicitly.
        jax.config.update(
            "jax_compilation_cache_dir", env["JAX_COMPILATION_CACHE_DIR"]
        )
        return
    cache_dir = cache_dir or os.path.join(
        os.path.expanduser("~"), ".cache", "gubernator-tpu", "xla"
    )
    try:
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
    except OSError:  # unwritable home: run uncached
        pass


configure_compile_cache()

from gubernator_tpu.types import (  # noqa: E402
    Algorithm,
    Behavior,
    Status,
    RateLimitRequest,
    RateLimitResponse,
)

from gubernator_tpu.version import VERSION as __version__

__all__ = [
    "Algorithm",
    "Behavior",
    "Status",
    "RateLimitRequest",
    "RateLimitResponse",
    "__version__",
]
