"""Columnar request batches: the zero-object host hot path.

The round-2 profile put the end-to-end engine at ~10µs of host work per
request — nearly all of it constructing and walking per-item Python
objects (dataclass attribute reads, ``hash_key()`` string building, list
comprehensions) against a device kernel that does the actual decision in
~4ns.  The reference has the same shape of cost in Go (per-request
structs, channel hops, ``gubernator.go:272-294``) but Go's per-item
constant is ~30x smaller, so it can afford it; Python cannot.

This module is the fix: a request batch as a *struct of arrays* —
one contiguous key blob + int64 numpy columns — that flows from the
transport edge to the device with no per-request Python in between:

    wire bytes → (parse) → ReqColumns → native slotmap resolve (blob in,
    slots out) → vectorized matrix pack → device tick → (5, B) response
    matrix → wire bytes

Dataclass `RateLimitRequest` remains the API-edge type (tests, SDK,
Store hooks); :meth:`ReqColumns.from_requests` bridges.  The engine's
``process()`` keeps its object contract and routes through this path.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import List, Optional, Sequence

import numpy as np

from gubernator_tpu.types import RateLimitRequest
from gubernator_tpu.utils.hotpath import hot_path
from gubernator_tpu.utils import sanitize

# `created_at` sentinel: proto3 optional presence maps to "server stamps
# now" (gubernator.proto:172-182).  0 is a legal (if silly) client value,
# so absence is encoded as -1.
CREATED_UNSET = -1

_EMPTY_I64 = np.empty(0, np.int64)


@dataclass
class ReqColumns:
    """One request batch as columns (see module docstring).

    ``key_blob``/``key_offsets`` hold the concatenated *hash keys*
    (``name + "_" + unique_key``, reference client.go:39-41): offsets are
    (n+1,) int64 with ``key j = blob[offsets[j]:offsets[j+1]]``, exactly
    the native slotmap's batch-resolve wire format (slotmap.cc
    guber_slotmap_resolve_batch).  The blob may be ``bytes`` or any
    bytes-like buffer — arena-backed batches carry a zero-copy numpy
    view into the decode slab (shared-memory slabs included); every
    consumer (native resolve, concat, per-key error paths) accepts the
    buffer form.

    ``refs`` optionally carries the originating request objects for the
    paths that genuinely need them (Store read/write-through hooks take a
    ``RateLimitRequest``); the hot path never touches it.
    """

    key_blob: "bytes | np.ndarray | memoryview"
    key_offsets: np.ndarray   # (n+1,) int64
    hits: np.ndarray          # all remaining columns: (n,) int64
    limit: np.ndarray
    duration: np.ndarray
    algorithm: np.ndarray
    behavior: np.ndarray
    created_at: np.ndarray    # CREATED_UNSET where the server stamps now
    burst: np.ndarray
    refs: Optional[Sequence[RateLimitRequest]] = None
    # Byte length of the *name* part of each packed key (the '_' split
    # position) — lets the wire codec re-emit the two proto string
    # fields from the packed key without re-splitting.  Optional: only
    # the transport paths that re-encode need it.
    name_len: Optional[np.ndarray] = None
    # Arena-backed batches (fastwire.parse_req decoding into a
    # ColumnArena slab) carry their lease here; the serving edge calls
    # :meth:`release` once the tick has consumed the columns so the slab
    # recycles.  Plain batches carry None and release() is a no-op.
    lease: Optional["ArenaLease"] = field(default=None, repr=False)

    def __len__(self) -> int:
        return len(self.hits)

    def release(self) -> None:
        """Return the backing arena slab (idempotent; no-op when the
        batch owns its arrays).  After release the column views may be
        overwritten by a later window — callers release only once the
        engine has packed the batch into its own request matrix."""
        lease, self.lease = self.lease, None
        if lease is not None:
            lease.release()

    def key_bytes(self, j: int) -> bytes:
        o = self.key_offsets
        b = self.key_blob[o[j] : o[j + 1]]
        # Buffer-backed blobs (arena/shm views) slice to a view; the
        # error/retry paths that call this expect real bytes.
        return b if type(b) is bytes else bytes(b)

    @classmethod
    def empty(cls) -> "ReqColumns":
        return cls(
            b"", np.zeros(1, np.int64), _EMPTY_I64, _EMPTY_I64, _EMPTY_I64,
            _EMPTY_I64, _EMPTY_I64, _EMPTY_I64, _EMPTY_I64,
        )

    @classmethod
    def from_requests(
        cls, requests: Sequence[RateLimitRequest], keep_refs: bool = False
    ) -> "ReqColumns":
        """Bridge from the dataclass API (one attribute pass, no copies
        beyond the columns themselves)."""
        n = len(requests)
        if n == 0:
            return cls.empty()
        names = [r.name for r in requests]
        blob, offsets = key_blob_from_parts(
            names, [r.unique_key for r in requests]
        )
        name_len = np.fromiter(
            (len(nm.encode()) for nm in names), np.int64, count=n
        )
        hits, limit, duration, algo, behav, created, burst = zip(*(
            (
                r.hits, r.limit, r.duration, int(r.algorithm),
                int(r.behavior),
                CREATED_UNSET if r.created_at is None else r.created_at,
                r.burst,
            )
            for r in requests
        ))
        a = lambda v: np.asarray(v, np.int64)  # noqa: E731
        return cls(
            blob, offsets, a(hits), a(limit), a(duration),
            a(algo), a(behav), a(created), a(burst),
            refs=requests if keep_refs else None,
            name_len=name_len,
        )

    def slice_chunk(self, s: int, e: int) -> "ReqColumns":
        """Contiguous sub-batch [s, e) — numpy views plus one blob slice
        (chunking by the engine's max_batch)."""
        o = self.key_offsets
        return ReqColumns(
            self.key_blob[o[s] : o[e]],
            o[s : e + 1] - o[s],
            self.hits[s:e], self.limit[s:e], self.duration[s:e],
            self.algorithm[s:e], self.behavior[s:e],
            self.created_at[s:e], self.burst[s:e],
            refs=None if self.refs is None else self.refs[s:e],
            name_len=None if self.name_len is None else self.name_len[s:e],
        )

    @classmethod
    def concat(cls, parts: List["ReqColumns"]) -> "ReqColumns":
        """Merge batches (the tick loop coalescing several waiters into
        one tick).  Refs survive only if every part carries them."""
        if len(parts) == 1:
            return parts[0]
        if not parts:
            return cls.empty()
        sizes = [len(p) for p in parts]
        offsets = np.zeros(sum(sizes) + 1, np.int64)
        base = 0
        at = 1
        for p, sz in zip(parts, sizes):
            offsets[at : at + sz] = p.key_offsets[1:] + base
            base += p.key_offsets[-1]
            at += sz
        cat = lambda f: np.concatenate([getattr(p, f) for p in parts])  # noqa: E731
        refs: Optional[list] = []
        for p in parts:
            if p.refs is None:
                refs = None
                break
            refs.extend(p.refs)
        name_len = (
            cat("name_len")
            if all(p.name_len is not None for p in parts) else None
        )
        return cls(
            b"".join(p.key_blob for p in parts), offsets,
            cat("hits"), cat("limit"), cat("duration"), cat("algorithm"),
            cat("behavior"), cat("created_at"), cat("burst"), refs=refs,
            name_len=name_len,
        )


def pack_blob(keys: Sequence[bytes]) -> tuple[bytes, np.ndarray]:
    """Concatenate keys into the (blob, (n+1,) int64 offsets) wire format
    every blob consumer here expects (native slotmap, snapshots)."""
    offsets = np.zeros(len(keys) + 1, np.int64)
    np.cumsum([len(k) for k in keys], out=offsets[1:])
    return b"".join(keys), offsets


def compact_blob(
    blob: bytes, offsets: np.ndarray, keep: np.ndarray
) -> tuple[bytes, np.ndarray]:
    """Filter a (blob, offsets) key pack down to the keep-masked rows,
    fully vectorized (snapshot restore drops expired rows without a
    per-key Python loop)."""
    arr = np.frombuffer(blob, np.uint8)
    lens = np.diff(offsets)
    starts = offsets[:-1][keep]
    ls = lens[keep]
    cum = np.zeros(len(ls) + 1, np.int64)
    np.cumsum(ls, out=cum[1:])
    pos = (
        np.arange(int(cum[-1]), dtype=np.int64)
        - np.repeat(cum[:-1], ls)
        + np.repeat(starts, ls)
    )
    return arr[pos].tobytes(), cum


def key_blob_from_parts(
    names: Sequence[str], unique_keys: Sequence[str]
) -> tuple[bytes, np.ndarray]:
    """Build (blob, offsets) for ``name_uniquekey`` hash keys from parallel
    name/key sequences (transport parse path)."""
    return pack_blob(
        [(nm + "_" + uk).encode() for nm, uk in zip(names, unique_keys)]
    )


# ----------------------------------------------------------------------
# Ingest column arena: preallocated per-window decode slabs
# ----------------------------------------------------------------------
class IngestOverloadError(RuntimeError):
    """Every arena slab is busy AND the per-window plain-allocation
    fallback budget (GUBER_INGEST_FALLBACK_LIMIT) is spent.  The ingest
    edge answers this as backpressure — a retriable RESOURCE_EXHAUSTED
    shed — instead of letting overload grow the heap unboundedly
    (docs/overload.md)."""


class ArenaLease:
    """One leased slab of a :class:`ColumnArena` (views handed to the
    decoder plus the release token).  Thread-safe release; idempotent."""

    __slots__ = ("arena", "index", "ints", "flags", "blob")

    def __init__(self, arena: "ColumnArena", index: int,
                 ints: np.ndarray, flags: np.ndarray, blob: np.ndarray):
        self.arena = arena
        self.index = index
        self.ints = ints
        self.flags = flags
        self.blob = blob

    def release(self) -> None:
        arena, self.arena = self.arena, None
        if arena is not None:
            arena._release(self.index)


class ColumnArena:
    """Reusable, capacity-bounded decode slabs for the wire→columns edge.

    The serving fast path (transport/fastwire.parse_req) used to
    allocate a fresh ``(9, n+1)`` int64 block, a flags vector, and a
    key-blob staging buffer per request batch — at serving batch rates
    the allocator (and the page-zeroing behind ``np.zeros``) is a
    measurable slice of the 0.15 ms/batch serve CPU.  The arena
    preallocates ``slabs`` fixed-size buffer sets once and leases them
    per window; a leased slab's numpy views become the
    :class:`ReqColumns` columns directly (zero copies besides the key
    blob's bytes materialization, which the native slotmap requires).

    Bounded by construction: a batch wider than ``max_batch`` (or a key
    blob larger than the slab), or a lease request while every slab is
    busy (more concurrent in-flight windows than ``slabs``), returns
    None and the caller falls back to plain allocation — the arena is a
    fast path, never a correctness constraint.  ``slabs`` should cover
    the tick pipeline depth plus decode concurrency
    (GUBER_INGEST_ARENA_SLABS; see docs/tpu-performance.md).
    """

    # Key-blob staging bytes per request row.  parse_req needs
    # len(data) + n staging bytes for a batch of n; hash keys in the
    # wild run tens of bytes, and oversized batches just fall back.
    BLOB_PER_ROW = 128

    def __init__(self, max_batch: int, slabs: int = 8,
                 fallback_limit: int = 32):
        self.max_batch = int(max_batch)
        self.n_slabs = max(1, int(slabs))
        self.blob_cap = self.max_batch * self.BLOB_PER_ROW
        self._ints = np.zeros(
            (self.n_slabs, 9, self.max_batch + 1), np.int64)
        self._flags = np.zeros((self.n_slabs, self.max_batch), np.uint8)
        self._blob = np.empty((self.n_slabs, self.blob_cap), np.uint8)
        self._busy = [False] * self.n_slabs
        self._next = 0
        self._lock = sanitize.lock("ColumnArena._lock")
        # Busy-slab plain-allocation fallback budget, per window: the
        # counter resets whenever a slab recycles (a window completed),
        # so sustained exhaustion — not a transient burst — is what
        # exhausts the budget and triggers shed (docs/overload.md).
        self.fallback_limit = max(0, int(fallback_limit))
        self._window_fallbacks = 0
        # Telemetry: misses (all slabs busy / batch too big) say whether
        # the bound is sized to the deployment's concurrency;
        # fallbacks count the budgeted plain allocations taken while
        # every slab was busy (gubernator_tpu_arena_fallbacks).
        self.metric_leases = 0
        self.metric_misses = 0
        self.metric_fallbacks = 0

    @hot_path
    def lease(self, n: int, blob_cap: int) -> Optional[ArenaLease]:
        """A slab for an ``n``-row decode needing ``blob_cap`` staging
        bytes, or None (caller allocates).  The returned views are
        already zeroed where the decoder requires zeros (proto3 absent
        fields must read 0)."""
        if n > self.max_batch or blob_cap > self.blob_cap:
            self.metric_misses += 1
            return None
        with self._lock:
            idx = -1
            for k in range(self.n_slabs):
                j = (self._next + k) % self.n_slabs
                if not self._busy[j]:
                    idx = j
                    break
            if idx < 0:
                self.metric_misses += 1
                return None
            self._busy[idx] = True
            self._next = (idx + 1) % self.n_slabs
            self.metric_leases += 1
        ints = self._ints[idx]
        # Zero only the region this decode reads/writes, not the slab:
        # the decoder writes only fields present on the wire.
        ints[:, : n + 1] = 0
        flags = self._flags[idx]
        flags[:n] = 0
        return ArenaLease(self, idx, ints, flags, self._blob[idx])

    def fits(self, n: int, blob_cap: int) -> bool:
        """Whether an ``n``-row decode could EVER lease here — False is a
        size miss (plain allocation is the only option and stays
        uncapped); True with a failed lease is busy-slab exhaustion,
        which is what the fallback budget governs."""
        return n <= self.max_batch and blob_cap <= self.blob_cap

    @hot_path
    def try_fallback(self) -> bool:
        """Spend one unit of the per-window plain-allocation budget.
        False means the budget is gone: the caller sheds with
        :class:`IngestOverloadError` semantics instead of allocating."""
        with self._lock:
            if self._window_fallbacks >= self.fallback_limit:
                return False
            self._window_fallbacks += 1
            self.metric_fallbacks += 1
            return True

    def _release(self, index: int) -> None:
        with self._lock:
            self._busy[index] = False
            self._window_fallbacks = 0

    def in_use(self) -> int:
        with self._lock:
            return sum(self._busy)
