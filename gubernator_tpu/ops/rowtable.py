"""Row-major bucket table with Pallas per-row DMA gather/scatter.

The column layout (buckets.py) bounds a tick by ~40 random single-word
HBM accesses per decision (24 stored columns gathered + scattered), which
measures ~100-200M words/s on a v5e chip — a hard ~3M decisions/s/chip
ceiling regardless of batch size.  This module stores the whole bucket
row contiguously — one (capacity+1, 128) int32 array, 512 B per slot —
and moves it with one DMA per row from a Pallas kernel (a pipelined ring
of async copies, K in flight, 4 issued per loop step).  Measured on v5e:
~3-25 ns/row scatter and ~25-50 ns/row gather, capacity-independent —
about 6-8x the column layout's gather+scatter cost at 32k-request ticks.

Layout (int32 words within a row; 24 used, the rest spare):
  word 0        algorithm
  words 1-2     limit        (int64 as lo,hi — same bitcast as buckets.py)
  words 3-4     remaining
  words 5-7     remaining_f  (float64 as 3-way Dekker float32 split)
  words 8-9     duration
  words 10-11   created_at
  words 12-13   updated_at
  words 14-15   burst
  word 16       status
  words 17-18   expire_at
  word 19       in_use
  words 20-21   tat          (GCRA theoretical arrival time)
  words 22-23   prev_count   (sliding-window previous-window count)

Row ``capacity`` is a guard row: masked scatter lanes aim there (the row
equivalent of the column path's ``mode="drop"`` sentinel), and gathers of
padding slots read its garbage — callers mask those lanes out, exactly as
they do for the column path's zero-fill.

Why 128 words: Mosaic requires HBM<->VMEM DMA slices to be 128-element
aligned in the lane dimension, so 512 B is the minimum int32 row.  The
5x space cost vs the 24 used words is the price of one-DMA rows; engines
fall back to the column layout for tables too big to afford it (see
engine.make_layout_choice).

On non-TPU backends the kernels run in Pallas interpret mode (slow, but
semantically identical) so the row engine is testable on the CPU mesh.
"""

from __future__ import annotations

import functools
import logging
import os
from typing import NamedTuple

import gubernator_tpu.jaxinit  # noqa: F401  (x64 + compile cache before jax use)
import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from gubernator_tpu.utils import jaxcompat
from gubernator_tpu.ops.buckets import (
    STATE_DTYPES,
    BucketState,
    to_logical,
    to_stored,
)

ROW_W = 128     # int32 words per row (Mosaic lane-alignment minimum)
# DMA pipeline shape (env-overridable for per-platform tuning): ring
# depth bounds outstanding copies — gathers are HBM-read-latency bound,
# so deeper rings hide more latency — and the unroll sets how many
# copies each scalar-loop step issues (the scalar loop is the issue-rate
# limiter).
def _env_pow2(env, name: str, default: int, lo: int, hi: int) -> int:
    """Clamped power-of-two env knob: a malformed or out-of-range value
    falls back to the default with a warning (a 0-deep ring would
    deadlock the first tick waiting on DMAs that were never started)."""
    raw = env.get(name, "")
    if raw == "":
        return default
    try:
        v = int(raw)
    except ValueError:
        v = -1
    if v < lo or v > hi or v & (v - 1):
        logging.getLogger("gubernator_tpu").warning(
            "%s=%r is not a power of two in [%d, %d]; using %d",
            name, raw, lo, hi, default,
        )
        return default
    return v


def refresh_dma_tuning(environ=None) -> None:
    """(Re-)read the DMA pipeline knobs.  Runs at import AND again from
    ``setup_daemon_config`` so the knobs also work from a ``-config``
    file, which loads into the env copy after import (the
    configure_compile_cache pattern, gubernator_tpu/__init__.py).

    The kernels bake DMA_RING/DMA_UNROLL in at trace time and the jitted
    wrappers are cached by (capacity, layout) only — once any kernel has
    been traced, a change here could not take effect for those programs
    and two engines in one process would silently disagree.  So a
    post-trace change is *refused* (loudly): refresh must precede the
    first engine construction."""
    global DMA_RING, DMA_UNROLL
    env = os.environ if environ is None else environ
    ring = _env_pow2(env, "GUBER_TPU_DMA_RING", 32, 8, 256)
    unroll = _env_pow2(env, "GUBER_TPU_DMA_UNROLL", 4, 1, 16)
    if _KERNELS_TRACED and (ring, unroll) != (DMA_RING, DMA_UNROLL):
        logging.getLogger("gubernator_tpu").warning(
            "DMA tuning change (ring %d->%d, unroll %d->%d) ignored: row "
            "kernels were already traced with the old values; set "
            "GUBER_TPU_DMA_* before the first engine is constructed",
            DMA_RING, ring, DMA_UNROLL, unroll,
        )
        return
    DMA_RING, DMA_UNROLL = ring, unroll


_KERNELS_TRACED = False
refresh_dma_tuning()

# The kernels stage the whole (B, ROW_W) batch block in VMEM; Mosaic's
# default scoped-vmem budget rejects a 64k-row tick (gather out-block +
# scatter in-block, 32 MB each), so raise it — v5e has 128 MB of VMEM.
# (CompilerParams is TPUCompilerParams on jax < 0.5-era pallas builds.)
_COMPILER_PARAMS = jaxcompat.pallas_tpu_compiler_params(
    vmem_limit_bytes=100 * 1024 * 1024)


def _field_words(field: str) -> int:
    from gubernator_tpu.ops.buckets import _FLOAT, _WIDE

    if field in _WIDE:
        return 2
    if field in _FLOAT:
        return 3
    return 1


# word offset of each logical field within a row, in STATE_DTYPES order
FIELD_OFFSETS = {}
_o = 0
for _f in STATE_DTYPES:
    FIELD_OFFSETS[_f] = _o
    _o += _field_words(_f)
ROW_USED = _o  # 24
assert ROW_USED <= ROW_W


class RowState(NamedTuple):
    """Device bucket table in row layout (+1 guard row)."""

    table: jnp.ndarray  # (capacity + 1, ROW_W) int32

    @property
    def capacity(self) -> int:
        return self.table.shape[0] - 1

    @classmethod
    def zeros(cls, n: int) -> "RowState":
        return cls(table=jnp.zeros((n + 1, ROW_W), jnp.int32))


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


# ----------------------------------------------------------------------
# Pallas kernels: one DMA per row, pipelined K-deep
# ----------------------------------------------------------------------
def _ring_loop(body_start, b: int):
    """Issue ``b`` DMAs through a ring of DMA_RING semaphores, DMA_UNROLL
    per scalar-loop step (the scalar loop, not the DMA engine, is the
    issue-rate limiter — unrolling measured ~10x on v5e)."""
    u = DMA_UNROLL if b % DMA_UNROLL == 0 and b >= 2 * DMA_RING else 1

    def body(g, _):
        for k in range(u):
            j = g * u + k

            @pl.when(j >= DMA_RING)
            def _(j=j):
                body_start(j - DMA_RING).wait()

            body_start(j).start()
        return 0

    lax.fori_loop(0, b // u, body, 0)

    def drain(j, _):
        body_start(j).wait()
        return 0

    lax.fori_loop(max(0, b - DMA_RING), b, drain, 0)


def _scatter_kernel(slots_ref, rows_ref, table_ref, out_ref, sems):
    b = rows_ref.shape[0]

    def start(j):
        return pltpu.make_async_copy(
            rows_ref.at[pl.ds(j, 1), :],
            out_ref.at[pl.ds(slots_ref[j], 1), :],
            sems.at[lax.rem(j, DMA_RING)],
        )

    _ring_loop(start, b)


def _gather_kernel(slots_ref, table_ref, out_ref, sems):
    b = out_ref.shape[0]

    def start(j):
        return pltpu.make_async_copy(
            table_ref.at[pl.ds(slots_ref[j], 1), :],
            out_ref.at[pl.ds(j, 1), :],
            sems.at[lax.rem(j, DMA_RING)],
        )

    _ring_loop(start, b)


def scatter_rows(table: jnp.ndarray, slots: jnp.ndarray,
                 rows: jnp.ndarray) -> jnp.ndarray:
    """Write ``rows[j]`` to ``table[slots[j]]`` for every j (row DMAs).

    ``slots`` must be int32 in [0, capacity]; duplicate *real* slots are
    a data race (callers scatter at most one row per slot — tick head
    rows, install/restore/evict dedup'd slots); duplicates of the guard
    row ``capacity`` are harmless (its content is never read as data).
    """
    global _KERNELS_TRACED
    _KERNELS_TRACED = True
    b, w = rows.shape
    cap1 = table.shape[0]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(1,),
        in_specs=[
            pl.BlockSpec((b, w), lambda t, *_: (0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[pltpu.SemaphoreType.DMA((DMA_RING,))],
    )
    with jaxcompat.enable_x64(False):
        return pl.pallas_call(
            _scatter_kernel,
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((cap1, w), jnp.int32),
            input_output_aliases={2: 0},
            compiler_params=_COMPILER_PARAMS,
            interpret=_interpret(),
        )(slots, rows, table)


def gather_rows(table: jnp.ndarray, slots: jnp.ndarray) -> jnp.ndarray:
    """Read ``table[slots[j]]`` into a (B, ROW_W) matrix (row DMAs)."""
    global _KERNELS_TRACED
    _KERNELS_TRACED = True
    b = slots.shape[0]
    w = table.shape[1]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(1,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec((b, w), lambda t, *_: (0, 0)),
        scratch_shapes=[pltpu.SemaphoreType.DMA((DMA_RING,))],
    )
    with jaxcompat.enable_x64(False):
        return pl.pallas_call(
            _gather_kernel,
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((b, w), jnp.int32),
            compiler_params=_COMPILER_PARAMS,
            interpret=_interpret(),
        )(slots, table)


_INTERPRET_OK = None


def interpret_supported() -> bool:
    """True when this toolchain can run the row kernels here: always on
    real TPU (Mosaic), and on other backends only when the Pallas
    interpreter of the installed jax can lower them (some versions choke
    on the DMA-ring loops, e.g. mixed-dtype index adds on the 0.4.x
    line).  Serving engines on non-TPU backends prefer the column layout
    anyway (engine.make_layout_choice); row-layout tests skip when this
    is False instead of failing on an emulation gap."""
    global _INTERPRET_OK
    if _INTERPRET_OK is None:
        if not _interpret():
            _INTERPRET_OK = True
        else:
            try:
                st = RowState.zeros(8)
                jax.jit(row_gather_state).lower(
                    st, jnp.zeros(4, jnp.int32)
                ).compile()
                _INTERPRET_OK = True
            except Exception:
                _INTERPRET_OK = False
    return _INTERPRET_OK


# ----------------------------------------------------------------------
# Row matrix <-> logical columns
# ----------------------------------------------------------------------
def matrix_to_logical(m: jnp.ndarray) -> BucketState:
    """(B, ROW_W) int32 row matrix -> logical per-request columns."""
    def col(f):
        o = FIELD_OFFSETS[f]
        n = _field_words(f)
        if n == 1:
            raw = m[:, o]
            return to_logical(raw, f) if STATE_DTYPES[f] != jnp.bool_ \
                else raw != 0
        return to_logical(tuple(m[:, o + k] for k in range(n)), f)

    return BucketState(**{f: col(f) for f in STATE_DTYPES})


def logical_to_matrix(rows: BucketState) -> jnp.ndarray:
    """Logical per-request columns -> (B, ROW_W) int32 row matrix."""
    cols = []
    for f in STATE_DTYPES:
        stored = to_stored(getattr(rows, f), f)
        if isinstance(stored, tuple):
            cols.extend(p.astype(jnp.int32) for p in stored)
        else:
            cols.append(stored.astype(jnp.int32))
    b = cols[0].shape[0]
    mat = jnp.stack(cols, axis=1)  # (B, ROW_USED)
    return jnp.concatenate(
        [mat, jnp.zeros((b, ROW_W - ROW_USED), jnp.int32)], axis=1
    )


# ----------------------------------------------------------------------
# BucketState-helper equivalents over RowState
# ----------------------------------------------------------------------
def row_gather_state(state: RowState, idx: jnp.ndarray) -> BucketState:
    """Gather logical rows at ``idx``.  Out-of-range/padding indices clamp
    to the guard row and read garbage — callers mask those lanes (the
    column path's fill-with-zeros contract, weakened to "don't read").
    Unlike ``buckets.gather_state`` there is deliberately no ``fill``
    option: zero-filling would cost a second masked pass per lane, and
    every caller already ignores padding rows."""
    cap = state.capacity
    slots = jnp.clip(idx, 0, cap).astype(jnp.int32)
    return matrix_to_logical(gather_rows(state.table, slots))


def row_scatter_state(state: RowState, idx: jnp.ndarray,
                      rows: BucketState) -> RowState:
    """Scatter logical rows; indices ≥ capacity land in the guard row."""
    cap = state.capacity
    slots = jnp.clip(idx, 0, cap).astype(jnp.int32)
    return RowState(
        table=scatter_rows(state.table, slots, logical_to_matrix(rows))
    )


def row_evict(state: RowState, slots: jnp.ndarray) -> RowState:
    """Zero whole rows (in_use=0 plus all state) for evicted slots."""
    cap = state.capacity
    s32 = jnp.clip(slots, 0, cap).astype(jnp.int32)
    zeros = jnp.zeros((s32.shape[0], ROW_W), jnp.int32)
    return RowState(table=scatter_rows(state.table, s32, zeros))


@functools.lru_cache(maxsize=None)
def _jitted_row_dead_scan():
    """Row-layout TTL sweep: strided column reads + packbits (one pass
    over the table; the engine ships capacity/8 bytes D2H)."""

    def scan(table, now):
        o = FIELD_OFFSETS["expire_at"]
        in_use = table[:-1, FIELD_OFFSETS["in_use"]] != 0
        exp = to_logical((table[:-1, o], table[:-1, o + 1]), "expire_at")
        dead = (~in_use) | (exp < now)
        return jnp.packbits(dead, bitorder="little")

    return jax.jit(scan)


def row_device_dead_bits(state: RowState, now: int):
    """Dispatch the dead-slot scan; returns the device packed bitmask (see
    engine.device_dead_bits for the dispatch/materialize split)."""
    return _jitted_row_dead_scan()(state.table, jnp.int64(now))


def row_device_dead_mask(state: RowState, now: int, capacity: int) -> np.ndarray:
    # guber: allow-G001(the deliberate reclaim D2H, row-layout twin of unpack_dead_bits - at most once per reclaim round, never per tick)
    bits = np.asarray(row_device_dead_bits(state, now))
    return np.unpackbits(bits, count=capacity, bitorder="little").astype(bool)


@functools.lru_cache(maxsize=None)
def _jitted_export_columns():
    """Slice the stored columns out of the row table on device, so a
    snapshot D2H moves ROW_USED words/slot, not ROW_W (5 GB -> 840 MB at
    10M slots)."""

    def export(table):
        return tuple(table[:-1, k] for k in range(ROW_USED))

    return jax.jit(export)


def row_host_columns(state: RowState) -> BucketState:
    """Fetch the table and rebuild a host-side stored-layout BucketState
    (np columns), for the export/items paths shared with the column
    engines."""
    cols = [np.asarray(c) for c in _jitted_export_columns()(state.table)]

    def stored(f):
        o = FIELD_OFFSETS[f]
        n = _field_words(f)
        if n == 1:
            c = cols[o]
            return c.astype(bool) if STATE_DTYPES[f] == jnp.bool_ else c
        return tuple(cols[o + k] for k in range(n))

    return BucketState(**{f: stored(f) for f in STATE_DTYPES})


def host_columns_from_rows(rows: np.ndarray) -> BucketState:
    """Host-side stored-layout BucketState from an (N, ROW_W) matrix of
    *data* rows (guard rows already dropped) — the mesh engine's export
    path, where the sharded table is fetched whole."""

    def stored(f):
        o = FIELD_OFFSETS[f]
        n = _field_words(f)
        if n == 1:
            c = np.ascontiguousarray(rows[:, o])
            return c.astype(bool) if STATE_DTYPES[f] == jnp.bool_ else c
        return tuple(np.ascontiguousarray(rows[:, o + k]) for k in range(n))

    return BucketState(**{f: stored(f) for f in STATE_DTYPES})
