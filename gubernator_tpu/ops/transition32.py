"""Parts-native bucket transition: the full token/leaky decision tree in
pure int32/float32 ops.

Semantically this is :func:`gubernator_tpu.ops.buckets.bucket_transition`
(itself the vectorized form of the reference's ``tokenBucket()`` /
``leakyBucket()``, algorithms.go:37-493, every branch and quirk in the
same precedence) restated over the *storage* representation — i64 fields
as (lo, hi) int32 pairs (:mod:`gubernator_tpu.ops.i64pair`), the leaky
``remaining`` float64 as its Dekker triple-f32 split
(:mod:`gubernator_tpu.ops.tfloat`).  Running on the parts directly:

* removes ``jax_enable_x64`` from the tick entirely (XLA's generic
  64-bit emulation and the bitcast-heavy row<->logical conversion were
  ~30% of a 32K tick), and
* makes the transition compilable *inside* a Mosaic/Pallas kernel,
  where it can overlap the per-row DMA streams (the fused tick).

Every function here is shape-polymorphic and elementwise, so the same
code serves (B,) XLA columns and (1, C) Pallas blocks.
"""

from __future__ import annotations

from typing import NamedTuple

import gubernator_tpu.jaxinit  # noqa: F401  (x64 + compile cache before jax use)
import jax.numpy as jnp
from jax import lax

from gubernator_tpu.algos import ZOO_MIN
from gubernator_tpu.algos import table as zoo_table
from gubernator_tpu.ops import i64pair as p64
from gubernator_tpu.ops import tfloat as tf
from gubernator_tpu.ops.i64pair import I64
from gubernator_tpu.ops.tfloat import T3
from gubernator_tpu.types import Algorithm, Behavior, Status

I32 = jnp.int32
F32 = jnp.float32


class PState(NamedTuple):
    """Per-request gathered bucket state, storage parts (cf. BucketState)."""

    algorithm: jnp.ndarray   # i32
    limit: I64
    remaining: I64
    remaining_f: T3
    duration: I64
    created_at: I64
    updated_at: I64
    burst: I64
    status: jnp.ndarray      # i32
    expire_at: I64
    in_use: jnp.ndarray      # bool
    tat: I64                 # GCRA theoretical arrival time
    prev_count: I64          # sliding-window previous-window count


class PReq(NamedTuple):
    """Request batch, storage parts (cf. ReqBatch)."""

    slot: jnp.ndarray        # i32
    known: jnp.ndarray       # bool
    hits: I64
    limit: I64
    duration: I64
    algorithm: jnp.ndarray   # i32
    behavior: jnp.ndarray    # i32
    created_at: I64
    burst: I64
    greg_exp: I64
    greg_dur: I64
    valid: jnp.ndarray       # bool


class PResp(NamedTuple):
    """Responses, storage parts (compact wire: limit echoed host-side)."""

    status: jnp.ndarray      # i32
    remaining: I64
    reset_time: I64
    over_limit: jnp.ndarray  # bool


def transition32(now: I64, s: PState, r: PReq) -> tuple[PState, PResp]:
    """Mirror of ``bucket_transition`` on parts — same branch structure,
    same precedence, same quirks; see buckets.py for the line-by-line
    reference mapping.  Comments here mark only parts-specific moves."""
    UNDER = jnp.int32(Status.UNDER_LIMIT)
    OVER = jnp.int32(Status.OVER_LIMIT)

    shape = jnp.shape(r.slot)
    zero = p64.const(0, r.slot)
    one = p64.const(1, r.slot)
    zero_t = tf.zeros_like(r.slot)

    reset_b = (r.behavior & jnp.int32(Behavior.RESET_REMAINING)) != 0
    drain_b = (r.behavior & jnp.int32(Behavior.DRAIN_OVER_LIMIT)) != 0
    greg_b = (r.behavior & jnp.int32(Behavior.DURATION_IS_GREGORIAN)) != 0

    exists = r.known & s.in_use & p64.le(now, s.expire_at)
    is_token = r.algorithm == jnp.int32(Algorithm.TOKEN_BUCKET)
    algo_match = s.algorithm == r.algorithm

    h = r.hits
    h_query = p64.is_zero(h)
    h_pos = p64.gt(h, zero)
    safe_limit_t = tf.from_pair(p64.select(p64.is_zero(r.limit), one, r.limit))

    # ------------------------------------------------------------------
    # TOKEN BUCKET
    # ------------------------------------------------------------------
    tok_reset = exists & reset_b
    tok_exist = exists & ~reset_b & algo_match

    t_rem0 = p64.select(
        p64.ne(s.limit, r.limit),
        p64.max_(p64.add(s.remaining, p64.sub(r.limit, s.limit)), zero),
        s.remaining,
    )
    rl_status = s.status
    rl_rem_base = t_rem0
    dur_changed = p64.ne(s.duration, r.duration)
    expire_cand = p64.select(
        greg_b, r.greg_exp, p64.add(s.created_at, r.duration))
    renew = p64.le(expire_cand, r.created_at)
    expire_new = p64.select(
        renew, p64.add(r.created_at, r.duration), expire_cand)
    t_created = p64.select(dur_changed & renew, r.created_at, s.created_at)
    t_rem1 = p64.select(dur_changed & renew, r.limit, t_rem0)
    t_expire = p64.select(dur_changed, expire_new, s.expire_at)
    rl_reset = p64.select(dur_changed, expire_new, s.expire_at)

    t_query = h_query
    t_at_zero = ~t_query & p64.is_zero(rl_rem_base) & h_pos
    t_exact = ~t_query & ~t_at_zero & p64.eq(t_rem1, h)
    t_over = ~t_query & ~t_at_zero & ~t_exact & p64.gt(h, t_rem1)
    t_dec = ~t_query & ~t_at_zero & ~t_exact & ~t_over

    te_rem = p64.select(
        t_exact,
        zero,
        p64.select(
            t_over,
            p64.select(drain_b, zero, t_rem1),
            p64.select(t_dec, p64.sub(t_rem1, h), t_rem1),
        ),
    )
    te_status = jnp.where(t_at_zero, OVER, s.status)
    te_resp_status = jnp.where(t_at_zero | t_over, OVER, rl_status)
    te_resp_rem = p64.select(
        t_exact,
        zero,
        p64.select(
            t_over,
            p64.select(drain_b, zero, rl_rem_base),
            p64.select(t_dec, p64.sub(t_rem1, h), rl_rem_base),
        ),
    )

    tn_expire = p64.select(
        greg_b, r.greg_exp, p64.add(r.created_at, r.duration))
    tn_over = p64.gt(h, r.limit)
    tn_rem = p64.select(tn_over, r.limit, p64.sub(r.limit, h))
    tn_resp_status = jnp.where(tn_over, OVER, UNDER)

    # ------------------------------------------------------------------
    # LEAKY BUCKET
    # ------------------------------------------------------------------
    burst = p64.select(p64.is_zero(r.burst), r.limit, r.burst)
    leak_exist = exists & algo_match

    b_rem0 = tf.select(reset_b, tf.from_pair(burst), s.remaining_f)
    burst_changed = p64.ne(s.burst, burst)
    b_rem1 = tf.select(
        burst_changed & p64.gt(burst, tf.floor_to_pair(b_rem0)),
        tf.from_pair(burst),
        b_rem0,
    )
    rate = tf.div(
        tf.from_pair(p64.select(greg_b, r.greg_dur, r.duration)),
        safe_limit_t,
    )
    duration_eff = p64.select(greg_b, p64.sub(r.greg_exp, now), r.duration)
    elapsed = p64.sub(r.created_at, s.updated_at)
    rate_zero = (rate.hi == 0) & (rate.mid == 0) & (rate.lo == 0)
    one_t = tf.from_f32(jnp.ones(shape, F32))
    leak = tf.div(tf.from_pair(elapsed), tf.select(rate_zero, one_t, rate))
    # int64(leak) > 0  <=>  leak >= 1 (negatives truncate toward zero)
    leaked = tf.ge(leak, one_t)
    b_rem2 = tf.select(leaked, tf.add(b_rem1, leak), b_rem1)
    b_upd = p64.select(leaked, r.created_at, s.updated_at)
    # int64(b_rem2) > burst  <=>  b_rem2 >= burst + 1 (b_rem2, burst >= 0)
    b_rem3 = tf.select(
        tf.ge_pair(b_rem2, p64.add(burst, one)), tf.from_pair(burst), b_rem2)

    rem_i = tf.floor_to_pair(b_rem3)
    # Go converts the float rate with int64(rate) — trunc toward zero,
    # which differs from floor when a negative duration makes the rate
    # negative (algorithms.go:336,377).
    rate_i = tf.trunc_to_pair(rate)
    l_at_zero = p64.is_zero(rem_i) & h_pos
    l_exact = ~l_at_zero & p64.eq(rem_i, h)
    l_over = ~l_at_zero & ~l_exact & p64.gt(h, rem_i)
    l_query = ~l_at_zero & ~l_exact & ~l_over & h_query
    l_dec = ~l_at_zero & ~l_exact & ~l_over & ~l_query

    le_remf = tf.select(
        l_exact,
        zero_t,
        tf.select(
            l_over,
            tf.select(drain_b, zero_t, b_rem3),
            tf.select(l_dec, tf.sub(b_rem3, tf.from_pair(h)), b_rem3),
        ),
    )
    le_resp_status = jnp.where(l_at_zero | l_over, OVER, UNDER)
    # trunc(b_rem3 - h) == floor(b_rem3) - h: h integral, result >= 0
    le_resp_rem = p64.select(
        l_exact,
        zero,
        p64.select(
            l_over,
            p64.select(drain_b, zero, rem_i),
            p64.select(l_dec, p64.sub(rem_i, h), rem_i),
        ),
    )
    le_reset_rem = p64.select(l_over, rem_i, le_resp_rem)
    le_resp_reset = p64.add(
        r.created_at, p64.mul(p64.sub(r.limit, le_reset_rem), rate_i))
    le_expire = p64.select(
        ~h_query, p64.add(r.created_at, duration_eff), s.expire_at)

    ln_rate_i = tf.trunc_to_pair(
        tf.div(tf.from_pair(r.duration), safe_limit_t))
    ln_duration = p64.select(greg_b, p64.sub(r.greg_exp, now), r.duration)
    ln_over = p64.gt(h, burst)
    ln_remf = tf.select(
        ln_over, zero_t, tf.from_pair(p64.sub(burst, h)))
    ln_resp_rem = p64.select(ln_over, zero, p64.sub(burst, h))
    ln_resp_reset = p64.add(
        r.created_at, p64.mul(p64.sub(r.limit, ln_resp_rem), ln_rate_i))
    ln_resp_status = jnp.where(ln_over, OVER, UNDER)
    ln_expire = p64.add(r.created_at, ln_duration)

    # ------------------------------------------------------------------
    # ALGORITHM ZOO (gubernator_tpu/algos): the same policy table the
    # x64 oracle folds in, instantiated on the parts backend.
    # ------------------------------------------------------------------
    is_zoo = r.algorithm >= jnp.int32(ZOO_MIN)
    zs, zr = zoo_table.zoo_transitions(
        zoo_table.PartsOps, s, r, exists, reset_b, drain_b)

    def z64(zoo_v, legacy_v):
        return p64.select(is_zoo, zoo_v, legacy_v)

    def z32(zoo_v, legacy_v):
        return jnp.where(is_zoo, zoo_v, legacy_v)

    # ------------------------------------------------------------------
    # Select per-request outcome (token-reset / token-exist / token-new /
    # leaky-exist / leaky-new)
    # ------------------------------------------------------------------
    def sel32(tr, te, tn, le, ln):
        tok = jnp.where(tok_reset, tr, jnp.where(tok_exist, te, tn))
        lk = jnp.where(leak_exist, le, ln)
        return jnp.where(is_token, tok, lk)

    def sel64(tr, te, tn, le, ln):
        tok = p64.select(tok_reset, tr, p64.select(tok_exist, te, tn))
        lk = p64.select(leak_exist, le, ln)
        return p64.select(is_token, tok, lk)

    def selt(tr, te, tn, le, ln):
        tok = tf.select(tok_reset, tr, tf.select(tok_exist, te, tn))
        lk = tf.select(leak_exist, le, ln)
        return tf.select(is_token, tok, lk)

    # 0/1 int32 lanes, not bool: Mosaic cannot lower selects between
    # bool vectors (i8->i1 truncation); the != 0 at the end emits a
    # plain compare instead.
    true_ = jnp.ones(shape, I32)
    false_ = jnp.zeros(shape, I32)

    new_state = PState(
        algorithm=z32(
            r.algorithm,
            jnp.where(
                is_token,
                jnp.int32(Algorithm.TOKEN_BUCKET),
                jnp.int32(Algorithm.LEAKY_BUCKET),
            )),
        limit=r.limit,
        remaining=z64(
            zs.remaining,
            sel64(zero, te_rem, tn_rem, s.remaining, s.remaining)),
        remaining_f=tf.select(
            is_zoo, zero_t,
            selt(zero_t, s.remaining_f, s.remaining_f, le_remf, ln_remf)),
        duration=z64(
            r.duration,
            sel64(zero, r.duration, r.duration, r.duration, ln_duration)),
        created_at=z64(
            zs.created_at,
            sel64(zero, t_created, r.created_at, s.created_at,
                  s.created_at)),
        updated_at=z64(
            r.created_at,
            sel64(zero, s.updated_at, s.updated_at, b_upd, r.created_at)),
        burst=z64(r.burst, sel64(zero, s.burst, s.burst, burst, burst)),
        status=z32(
            zs.status,
            sel32(jnp.zeros(shape, I32), te_status, UNDER, s.status,
                  UNDER)),
        expire_at=z64(
            zs.expire_at,
            sel64(zero, t_expire, tn_expire, le_expire, ln_expire)),
        in_use=z32(true_, sel32(false_, true_, true_, true_, true_)) != 0,
        tat=z64(zs.tat, zero),
        prev_count=z64(zs.prev_count, zero),
    )

    resp = PResp(
        status=z32(
            zr.status,
            sel32(jnp.full(shape, UNDER), te_resp_status, tn_resp_status,
                  le_resp_status, ln_resp_status)),
        remaining=z64(
            zr.remaining,
            sel64(r.limit, te_resp_rem, tn_rem, le_resp_rem,
                  ln_resp_rem)),
        reset_time=z64(
            zr.reset_time,
            sel64(zero, rl_reset, tn_expire, le_resp_reset,
                  ln_resp_reset)),
        over_limit=z32(
            zr.over_limit,
            sel32(
                false_,
                (t_at_zero | t_over).astype(I32),
                tn_over.astype(I32),
                (l_at_zero | l_over).astype(I32),
                ln_over.astype(I32),
            )) != 0,
    )
    return new_state, resp


# ----------------------------------------------------------------------
# Wire / table adapters
# ----------------------------------------------------------------------
def preq_from_compact(m32: jnp.ndarray) -> PReq:
    """(19, B) compact int32 request matrix → PReq (no 64-bit ops;
    device-side inverse of pack_request_matrix32)."""
    from gubernator_tpu.ops.engine import REQ32_INDEX

    def wide(name):
        i = REQ32_INDEX[name]
        return I64(m32[i], m32[i + 1])

    return PReq(
        slot=m32[REQ32_INDEX["slot"]],
        known=m32[REQ32_INDEX["known"]] != 0,
        hits=wide("hits"),
        limit=wide("limit"),
        duration=wide("duration"),
        algorithm=m32[REQ32_INDEX["algorithm"]],
        behavior=m32[REQ32_INDEX["behavior"]],
        created_at=wide("created_at"),
        burst=wide("burst"),
        greg_exp=wide("greg_exp"),
        greg_dur=wide("greg_dur"),
        valid=m32[REQ32_INDEX["valid"]] != 0,
    )


def presp_to_compact(resp: PResp) -> jnp.ndarray:
    """PResp → (6, B) compact int32 response matrix (same row order as
    pack_resp_compact: status, over, rem lo/hi, reset lo/hi)."""
    return jnp.stack([
        resp.status,
        resp.over_limit.astype(I32),
        resp.remaining.lo,
        resp.remaining.hi,
        resp.reset_time.lo,
        resp.reset_time.hi,
    ])


def _f32(x):
    return lax.bitcast_convert_type(x, F32)


def _i32(x):
    return lax.bitcast_convert_type(x, I32)


def pstate_from_matrix(m: jnp.ndarray) -> PState:
    """(B, ROW_W) gathered row matrix → PState (int32 slices + f32
    bitcasts only — replaces matrix_to_logical's x64 conversion)."""
    from gubernator_tpu.ops.rowtable import FIELD_OFFSETS as O

    def pair(f):
        return I64(m[..., O[f]], m[..., O[f] + 1])

    fo = O["remaining_f"]
    return PState(
        algorithm=m[..., O["algorithm"]],
        limit=pair("limit"),
        remaining=pair("remaining"),
        remaining_f=T3(
            _f32(m[..., fo]), _f32(m[..., fo + 1]), _f32(m[..., fo + 2])),
        duration=pair("duration"),
        created_at=pair("created_at"),
        updated_at=pair("updated_at"),
        burst=pair("burst"),
        status=m[..., O["status"]],
        expire_at=pair("expire_at"),
        in_use=m[..., O["in_use"]] != 0,
        tat=pair("tat"),
        prev_count=pair("prev_count"),
    )


def pstate_to_matrix(s: PState) -> jnp.ndarray:
    """PState → (B, ROW_W) row matrix (inverse of pstate_from_matrix;
    spare words zero, like logical_to_matrix)."""
    from gubernator_tpu.ops.rowtable import ROW_W

    cols = [
        s.algorithm,
        s.limit.lo, s.limit.hi,
        s.remaining.lo, s.remaining.hi,
        _i32(s.remaining_f.hi), _i32(s.remaining_f.mid),
        _i32(s.remaining_f.lo),
        s.duration.lo, s.duration.hi,
        s.created_at.lo, s.created_at.hi,
        s.updated_at.lo, s.updated_at.hi,
        s.burst.lo, s.burst.hi,
        s.status,
        s.expire_at.lo, s.expire_at.hi,
        s.in_use.astype(I32),
        s.tat.lo, s.tat.hi,
        s.prev_count.lo, s.prev_count.hi,
    ]
    mat = jnp.stack(cols, axis=-1)
    b = mat.shape[:-1]
    return jnp.concatenate(
        [mat, jnp.zeros(b + (ROW_W - len(cols),), I32)], axis=-1)


def pstate_gather_columns(state, idx: jnp.ndarray) -> PState:
    """Gather a PState from a stored-layout column-table BucketState
    (tuples of i32 part columns) without any 64-bit conversion."""

    def pair(f):
        lo, hi = getattr(state, f)
        return I64(lo[idx], hi[idx])

    fh, fm, fl = state.remaining_f
    return PState(
        algorithm=state.algorithm[idx],
        limit=pair("limit"),
        remaining=pair("remaining"),
        remaining_f=T3(_f32(fh[idx]), _f32(fm[idx]), _f32(fl[idx])),
        duration=pair("duration"),
        created_at=pair("created_at"),
        updated_at=pair("updated_at"),
        burst=pair("burst"),
        status=state.status[idx],
        expire_at=pair("expire_at"),
        in_use=state.in_use[idx],
        tat=pair("tat"),
        prev_count=pair("prev_count"),
    )


def pstate_scatter_columns(state, idx: jnp.ndarray, rows: PState):
    """Scatter a PState back into a stored-layout column BucketState
    (drop mode, like scatter_state)."""

    def put(col, vals):
        return col.at[idx].set(vals, mode="drop")

    return state._replace(
        algorithm=put(state.algorithm, rows.algorithm),
        limit=(put(state.limit[0], rows.limit.lo),
               put(state.limit[1], rows.limit.hi)),
        remaining=(put(state.remaining[0], rows.remaining.lo),
                   put(state.remaining[1], rows.remaining.hi)),
        remaining_f=(
            put(state.remaining_f[0], _i32(rows.remaining_f.hi)),
            put(state.remaining_f[1], _i32(rows.remaining_f.mid)),
            put(state.remaining_f[2], _i32(rows.remaining_f.lo)),
        ),
        duration=(put(state.duration[0], rows.duration.lo),
                  put(state.duration[1], rows.duration.hi)),
        created_at=(put(state.created_at[0], rows.created_at.lo),
                    put(state.created_at[1], rows.created_at.hi)),
        updated_at=(put(state.updated_at[0], rows.updated_at.lo),
                    put(state.updated_at[1], rows.updated_at.hi)),
        burst=(put(state.burst[0], rows.burst.lo),
               put(state.burst[1], rows.burst.hi)),
        status=put(state.status, rows.status),
        expire_at=(put(state.expire_at[0], rows.expire_at.lo),
                   put(state.expire_at[1], rows.expire_at.hi)),
        in_use=put(state.in_use, rows.in_use),
        tat=(put(state.tat[0], rows.tat.lo),
             put(state.tat[1], rows.tat.hi)),
        prev_count=(put(state.prev_count[0], rows.prev_count.lo),
                    put(state.prev_count[1], rows.prev_count.hi)),
    )


# ----------------------------------------------------------------------
# Grouped ("scatter-add") tick: closed-form duplicate fold on parts
# ----------------------------------------------------------------------
# The BASELINE north star names hot-key scatter-add: Zipf traffic puts
# many identical requests on one key per window, and the device should
# tick each hot slot ONCE, not once per duplicate.  The host dedups the
# slot-sorted batch (engine._build_group_plan), the kernel transitions
# each unique head and folds the group's followers closed-form into the
# table row (merged_fold32 — the parts mirror of engine._merged_formulas,
# same math, same quirks), and a second elementwise program reconstructs
# every member's response from the head outputs (expand32).  The fold is
# rank-arithmetic only, so a k-deep hot key costs the same HBM traffic
# as a unique key.

class MergedHead(NamedTuple):
    """Per-head extras the expansion needs, alongside the head's own
    compact response."""

    base: I64        # post-head integer remaining (token R0 / trunc F0)
    q: I64           # base // hits (the last under-limit rank)
    rate_i: I64      # floor(duration / limit) — leaky reset slope
    s0: jnp.ndarray  # post-head stored status (pre-fold), i32
    expire: I64      # post-head expire_at


def merged_fold32(now: I64, new_s: PState, r: PReq, count: jnp.ndarray
                  ) -> tuple[PState, MergedHead]:
    """Fold ``count - 1`` identical followers into the head's
    post-transition row (engine._merged_formulas semantics: the i <= q
    steps decrement, the rest are over-limit; stored token status flips
    on an at-zero step; leaky remaining_f zeroes exactly on an
    exact-remainder or drain step).  ``count == 1`` is the identity, so
    unique slots ride the same program.

    Host contract (engine._build_group_plan): every member of a
    count > 1 group is identical to its head, hits > 0, known, and free
    of RESET_REMAINING / Gregorian behaviors.
    """
    OVER = jnp.int32(Status.OVER_LIMIT)
    zero = p64.const(0, r.slot)
    one = p64.const(1, r.slot)

    is_tok = r.algorithm == jnp.int32(Algorithm.TOKEN_BUCKET)
    h = p64.select(p64.gt(r.hits, zero), r.hits, one)  # div-safe
    f0_floor = tf.floor_to_pair(new_s.remaining_f)
    base = p64.select(is_tok, new_s.remaining, f0_floor)
    base_pos = p64.select(p64.is_neg(base), zero, base)  # div domain
    q = p64.div_floor_pos(base_pos, h)
    li = p64.from_i32(count - 1)
    alive = p64.le(now, new_s.expire_at)
    # Closed-form fold is only valid for the token/leaky pair; the host
    # group planner never groups zoo lanes (engine gates eligibility on
    # algorithm <= LEAKY_BUCKET), this mask is defense in depth.
    legacy = r.algorithm <= jnp.int32(Algorithm.LEAKY_BUCKET)
    fold = (count > 1) & alive & r.valid & legacy

    qh = p64.mul(q, h)
    residue = p64.sub(base, qh)          # base - q*h, >= 0
    divisible = p64.is_zero(residue)
    drain = (r.behavior & jnp.int32(Behavior.DRAIN_OVER_LIMIT)) != 0
    l_under = p64.le(li, q)
    rem_over = p64.select(drain, zero, residue)
    rem_last = p64.select(l_under, p64.sub(base, p64.mul(li, h)), rem_over)
    # i32 lanes through the select: Mosaic cannot lower selects between
    # bool vectors (see transition32's sel32 note).
    at_zero_last = jnp.where(
        divisible,
        p64.gt(li, q).astype(I32),
        (drain & p64.gt(li, p64.add(q, one))).astype(I32),
    ) != 0
    status_last = jnp.where(at_zero_last, OVER, new_s.status)

    zero_t = tf.zeros_like(r.slot)
    zero_f = (
        (p64.ge(q, one) & divisible & p64.ge(li, q))
        | (p64.gt(base, zero) & drain & p64.gt(li, q))
    )
    li_capped = p64.min_(li, q)
    remf_last = tf.select(
        zero_f,
        zero_t,
        tf.sub(new_s.remaining_f, tf.from_pair(p64.mul(li_capped, h))),
    )

    safe_limit = p64.select(p64.is_zero(r.limit), one, r.limit)
    rate_i = p64.div_floor_pos(
        p64.select(p64.is_neg(r.duration), zero, r.duration), safe_limit)

    folded = new_s._replace(
        remaining=p64.select(fold & is_tok, rem_last, new_s.remaining),
        status=jnp.where(fold & is_tok, status_last, new_s.status),
        remaining_f=tf.select(
            fold & ~is_tok, remf_last, new_s.remaining_f),
    )
    head = MergedHead(
        base=base, q=q, rate_i=rate_i, s0=new_s.status,
        expire=new_s.expire_at,
    )
    return folded, head


def _expand_members(head6, base, q, rate_i, s0, expire, h, limit,
                    created, algorithm, behavior, rank) -> tuple:
    """The follower-response derivation shared by both expansion layouts
    (engine._merged_formulas response rules): ``head6`` is the head's own
    compact response (taken verbatim at rank 0), the rest are the head
    fold outputs / uniform request params broadcast per member."""
    OVER = jnp.int32(Status.OVER_LIMIT)
    UNDER = jnp.int32(Status.UNDER_LIMIT)
    zero = p64.const(0, rank)
    one = p64.const(1, rank)
    is_tok = algorithm == jnp.int32(Algorithm.TOKEN_BUCKET)
    drain = (behavior & jnp.int32(Behavior.DRAIN_OVER_LIMIT)) != 0
    h = p64.select(p64.gt(h, zero), h, one)

    i = p64.from_i32(rank)
    under = p64.le(i, q)
    residue = p64.sub(base, p64.mul(q, h))
    rem_over = p64.select(drain, zero, residue)
    rem_resp = p64.select(under, p64.sub(base, p64.mul(i, h)), rem_over)
    status = jnp.where(under, jnp.where(is_tok, s0, UNDER), OVER)
    over = ~under
    reset_rem = p64.select(
        under,
        rem_resp,
        p64.select(drain & p64.gt(i, p64.add(q, one)), zero, residue),
    )
    leaky_reset = p64.add(
        created, p64.mul(p64.sub(limit, reset_rem), rate_i))
    reset = p64.select(is_tok, expire, leaky_reset)

    is_head = rank == 0
    return (
        jnp.where(is_head, head6[0], status),
        jnp.where(is_head, head6[1], over.astype(I32)),
        jnp.where(is_head, head6[2], rem_resp.lo),
        jnp.where(is_head, head6[3], rem_resp.hi),
        jnp.where(is_head, head6[4], reset.lo),
        jnp.where(is_head, head6[5], reset.hi),
    )


def expand32_rows(
    mh_rows: tuple,        # 15 (U,) rows of the merged-program output
    mhead: jnp.ndarray,    # (19, U) head request matrix (uniform params)
    uidx: jnp.ndarray,     # (B,) i32 → head column of each member
    rank: jnp.ndarray,     # (B,) i32 rank within the duplicate group
) -> tuple:
    """Per-member responses for a grouped tick → the six compact rows,
    unstacked (see _expand_members).  rank-0 members take the head's own
    response verbatim; padding members (uidx pointing at a padded head
    column) produce unspecified values, exactly like the plain tick's
    padding lanes.  Rows stay unstacked so chained callers on the CPU
    backend avoid the concatenate-fusion pathology
    (tick32.make_tick32_rows_fn)."""
    from gubernator_tpu.ops.engine import REQ32_INDEX

    g = [row[uidx] for row in mh_rows]   # 15 (B,) head rows per member
    req = mhead[:, uidx]                 # (19, B)

    def rpair(name):
        k = REQ32_INDEX[name]
        return I64(req[k], req[k + 1])

    return _expand_members(
        g[:6],
        base=I64(g[6], g[7]), q=I64(g[8], g[9]),
        rate_i=I64(g[10], g[11]), s0=g[12], expire=I64(g[13], g[14]),
        h=rpair("hits"), limit=rpair("limit"),
        created=rpair("created_at"),
        algorithm=req[REQ32_INDEX["algorithm"]],
        behavior=req[REQ32_INDEX["behavior"]],
        rank=rank,
    )


# Row order of the row-major merged output (fused kernel): compact resp,
# MergedHead extras, then the (uniform) request params the expansion
# needs — one 96 B row gather per member instead of 15+ lane gathers.
MERGED24_ROWS = 24  # 23 used + 1 spare (matches the kernel's TW transpose)


def merged24_rows(resp: PResp, head: MergedHead, r: PReq) -> tuple:
    """The 23 used rows of the row-major merged output, in order."""
    return (
        resp.status,
        resp.over_limit.astype(I32),
        resp.remaining.lo, resp.remaining.hi,
        resp.reset_time.lo, resp.reset_time.hi,
        head.base.lo, head.base.hi,
        head.q.lo, head.q.hi,
        head.rate_i.lo, head.rate_i.hi,
        head.s0,
        head.expire.lo, head.expire.hi,
        r.hits.lo, r.hits.hi,
        r.limit.lo, r.limit.hi,
        r.created_at.lo, r.created_at.hi,
        r.algorithm,
        r.behavior,
    )


def expand32_rowmajor(resp24: jnp.ndarray, uidx: jnp.ndarray,
                      rank: jnp.ndarray) -> tuple:
    """Per-member responses from the row-major (U, 24) merged output →
    six compact rows, unstacked (see _expand_members).  One whole-row
    gather per member — the TPU-fast layout (chained-differential probe:
    95 µs vs 3.6 ms for 32K members against lane-dimension gathers)."""
    g = resp24[uidx]                     # (B, 24)

    def cpair(k):
        return I64(g[:, k], g[:, k + 1])

    return _expand_members(
        tuple(g[:, k] for k in range(6)),
        base=cpair(6), q=cpair(8), rate_i=cpair(10), s0=g[:, 12],
        expire=cpair(13), h=cpair(15), limit=cpair(17),
        created=cpair(19), algorithm=g[:, 21], behavior=g[:, 22],
        rank=rank,
    )
