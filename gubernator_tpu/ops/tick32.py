"""The parts-native tick program: compact i32 requests in, compact i32
responses out, no 64-bit ops anywhere in the hot path.

This is the unique-slot fast program: the host sorts every batch by slot
(engine._build_cols) and knows whether duplicates exist; batches with at
most one request per slot — the overwhelming production shape and the
bench worst case — dispatch here, duplicate-bearing batches take the
merge-capable program (engine.make_tick_fn).  Keeping the two as
separate host-dispatched programs (instead of a traced lax.cond) lets
this one stay pure int32/float32, which is what allows it to run inside
a Mosaic kernel at all (Mosaic refuses jax_enable_x64 programs) and
removes XLA's emulated-64-bit overhead from the XLA fallback.

Layouts:
* ``row`` — Pallas per-row DMA gather/scatter around a parts transition
  (fused kernel lands behind this same factory).
* ``columns`` — direct i32 part-column gathers/scatters (the 100M-slot
  regime, where the row table doesn't fit).

Reference semantics: algorithms.go:37-493 via ops/transition32.py.
"""

from __future__ import annotations

import functools

import gubernator_tpu.jaxinit  # noqa: F401  (x64 + compile cache before jax use)
import jax
import jax.numpy as jnp

from gubernator_tpu.ops import i64pair as p64
from gubernator_tpu.types import Algorithm, Behavior
from gubernator_tpu.ops.transition32 import (
    preq_from_compact,
    pstate_from_matrix,
    pstate_gather_columns,
    pstate_scatter_columns,
    pstate_to_matrix,
    transition32,
)

I32 = jnp.int32


def now_to_pair(now: jnp.ndarray) -> p64.I64:
    """Scalar int64 ``now`` → (lo, hi) i32 pair (scalar arithmetic only —
    this toolchain's X64 rewriter has no 64-bit bitcasts)."""
    hi = (now >> 32).astype(I32)
    lo_u = now & jnp.int64(0xFFFFFFFF)
    lo = jnp.where(
        lo_u >= jnp.int64(1 << 31), lo_u - jnp.int64(1 << 32), lo_u
    ).astype(I32)
    return p64.I64(lo, hi)


def _resolve_fused(fused: bool | None) -> bool:
    """Default: fused Pallas on real TPU, unfused XLA elsewhere.  On CPU
    the fused kernel only exists in interpret mode (a Python-stepped DMA
    loop — seconds per tick), so the 8-device test mesh would crawl;
    GUBER_TPU_FUSED_TICK=0/1 still forces either path on any backend
    (tests/test_fusedtick.py covers fused-vs-unfused parity in interpret
    mode explicitly).  Read through the config registry at engine
    construction (not per tick — the resolved choice is baked into the
    jitted program cache key)."""
    from gubernator_tpu.config import env_knob

    if fused is not None:
        return fused
    env = env_knob("GUBER_TPU_FUSED_TICK")
    if env is not None:
        return env != "0"
    return jax.default_backend() == "tpu"


def _resp_rows(resp) -> tuple:
    """PResp → the six compact response rows, unstacked (same order as
    presp_to_compact: status, over, rem lo/hi, reset lo/hi)."""
    return (
        resp.status,
        resp.over_limit.astype(I32),
        resp.remaining.lo,
        resp.remaining.hi,
        resp.reset_time.lo,
        resp.reset_time.hi,
    )


def make_tick32_rows_fn(capacity: int, layout: str = "columns"):
    """The XLA (non-Pallas) tick program, response as SIX SEPARATE row
    vectors rather than one stacked (6, B) matrix.

    The split exists because stacking is poison on the CPU backend:
    XLA:CPU emits a concatenate-rooted fusion over this very deep
    elementwise graph by recursively re-evaluating each operand's
    expression tree per output element (no memoization across the
    diamond-shaped reuse in the i64-pair/triple-f32 arithmetic), which
    turns a ~10 µs tick into ~0.2 s *per batch element* — a 64-wide tick
    took 12 s on the 8-device test mesh.  Returning the rows as separate
    program outputs keeps every fusion root single-output, which XLA
    emits as one memoized loop.  TPU's emitter doesn't have the
    pathology, but the two-program composition costs only a dispatch.
    """

    if layout == "row":
        from gubernator_tpu.ops.rowtable import gather_rows, scatter_rows

        def tick(state, m32, now):
            r = preq_from_compact(m32)
            slots = jnp.clip(r.slot, 0, capacity)
            mat = gather_rows(state.table, slots)
            s = pstate_from_matrix(mat)
            new_g, resp = transition32(now_to_pair(now), s, r)
            scat = jnp.where(r.valid, slots, jnp.int32(capacity))
            table = scatter_rows(state.table, scat, pstate_to_matrix(new_g))
            return state._replace(table=table), _resp_rows(resp)

    else:

        def tick(state, m32, now):
            r = preq_from_compact(m32)
            slots = jnp.clip(r.slot, 0, capacity - 1)
            s = pstate_gather_columns(state, slots)
            new_g, resp = transition32(now_to_pair(now), s, r)
            # unclipped slot: padding rows (slot == capacity) drop
            scat = jnp.where(r.valid, r.slot, jnp.int32(capacity))
            state = pstate_scatter_columns(state, scat, new_g)
            return state, _resp_rows(resp)

    return tick


def make_tick32_fn(capacity: int, layout: str = "columns",
                   fused: bool | None = None):
    """Build (state, m32, now) → (state, resp6) for unique-slot batches.

    Contract (matches make_tick_fn's compact in/out so TickHandle code is
    shared): ``m32`` is the (19, B) compact request matrix, slot-sorted,
    padding/error rows carrying slot == capacity; at most one valid
    request per real slot.  ``resp6`` is the (6, B) compact response
    matrix; rows past the live count are unspecified.

    This single-program form is for callers that need one traceable
    function (bench chains it inside a fori_loop on TPU).  Engines should
    use :func:`jitted_tick32`, which splits the response stack into a
    second program — see make_tick32_rows_fn for why.
    """

    if layout == "row" and _resolve_fused(fused):
        from gubernator_tpu.ops.fusedtick import make_fused_tick_fn

        return make_fused_tick_fn(capacity)

    rows_fn = make_tick32_rows_fn(capacity, layout)

    def tick(state, m32, now):
        state, rows = rows_fn(state, m32, now)
        return state, jnp.stack(rows)

    return tick


@functools.lru_cache(maxsize=None)
def _jitted_stack6():
    return jax.jit(lambda rows: jnp.stack(rows))


@functools.lru_cache(maxsize=None)
def jitted_tick32(capacity: int, layout: str = "columns",
                  fused: bool | None = None):
    """Engine entry: two-program composition (tick rows + stack) so the
    CPU backend never sees a concatenate-rooted mega-fusion (see
    make_tick32_rows_fn).  The fused Pallas row kernel packs its response
    in-kernel and stays a single program."""
    if layout == "row" and _resolve_fused(fused):
        from gubernator_tpu.ops.fusedtick import make_fused_tick_fn

        return jax.jit(make_fused_tick_fn(capacity), donate_argnums=(0,))

    inner = jax.jit(
        make_tick32_rows_fn(capacity, layout), donate_argnums=(0,))
    stack = _jitted_stack6()

    def tick(state, m32, now):
        state, rows = inner(state, m32, now)
        return state, stack(rows)

    return tick


# ----------------------------------------------------------------------
# Grouped ("scatter-add") tick: unique heads + closed-form fold
# ----------------------------------------------------------------------
def make_merged_tick32_rows_fn(capacity: int, layout: str = "columns"):
    """(state, mhead (19, U) i32, count (U,) i32, now) → (state, 15-row
    tuple): the unique-head tick with the duplicate-group fold applied to
    the table row (transition32.merged_fold32) and the head extras the
    expansion program needs.  Same unstacked-rows discipline as
    make_tick32_rows_fn (XLA:CPU concat-fusion pathology)."""
    from gubernator_tpu.ops.transition32 import merged_fold32

    def rows_of(now, s, r, count, new_g, resp):
        folded, head = merged_fold32(now, new_g, r, count)
        return folded, (
            resp.status,
            resp.over_limit.astype(I32),
            resp.remaining.lo, resp.remaining.hi,
            resp.reset_time.lo, resp.reset_time.hi,
            head.base.lo, head.base.hi,
            head.q.lo, head.q.hi,
            head.rate_i.lo, head.rate_i.hi,
            head.s0,
            head.expire.lo, head.expire.hi,
        )

    if layout == "row":
        from gubernator_tpu.ops.rowtable import gather_rows, scatter_rows

        def tick(state, mhead, count, now):
            r = preq_from_compact(mhead)
            slots = jnp.clip(r.slot, 0, capacity)
            mat = gather_rows(state.table, slots)
            s = pstate_from_matrix(mat)
            np_ = now_to_pair(now)
            new_g, resp = transition32(np_, s, r)
            folded, rows = rows_of(np_, s, r, count, new_g, resp)
            scat = jnp.where(r.valid, slots, jnp.int32(capacity))
            table = scatter_rows(
                state.table, scat, pstate_to_matrix(folded))
            return state._replace(table=table), rows

    else:

        def tick(state, mhead, count, now):
            r = preq_from_compact(mhead)
            slots = jnp.clip(r.slot, 0, capacity - 1)
            s = pstate_gather_columns(state, slots)
            np_ = now_to_pair(now)
            new_g, resp = transition32(np_, s, r)
            folded, rows = rows_of(np_, s, r, count, new_g, resp)
            scat = jnp.where(r.valid, r.slot, jnp.int32(capacity))
            state = pstate_scatter_columns(state, scat, folded)
            return state, rows

    return tick


# ----------------------------------------------------------------------
# Layered tick: host-planned unit layers through the narrow merged core
# ----------------------------------------------------------------------
def _expand_sorted(flat15, m32, uidx, rank):
    """Member responses from a flattened unit-layer journal: head values
    gathered per member from ``flat15[:, uidx]``; request params come
    from each member's OWN compact columns (within a unit all members
    are identical to the head by construction, so no head-param gather
    is needed).  Returns the six compact rows, unstacked."""
    from gubernator_tpu.ops.engine import REQ32_INDEX
    from gubernator_tpu.ops.transition32 import _expand_members

    g = [row[uidx] for row in flat15]

    def rpair(name):
        k = REQ32_INDEX[name]
        return p64.I64(m32[k], m32[k + 1])

    return _expand_members(
        g[:6],
        base=p64.I64(g[6], g[7]), q=p64.I64(g[8], g[9]),
        rate_i=p64.I64(g[10], g[11]), s0=g[12],
        expire=p64.I64(g[13], g[14]),
        h=rpair("hits"), limit=rpair("limit"),
        created=rpair("created_at"),
        algorithm=m32[REQ32_INDEX["algorithm"]],
        behavior=m32[REQ32_INDEX["behavior"]],
        rank=rank,
    )


@functools.lru_cache(maxsize=None)
def jitted_layered_pipeline(capacity: int, layout: str, w0: int,
                            k_layers: int, layer_width: int = 512,
                            fused: bool | None = None):
    """Engine entry for mixed-duplicate batches with a host layer plan
    (engine.build_layer_plan): (state, mh0, cnt0, mhk, cntk, m32, uidx,
    rank, now) → (state, (6, B) compact responses).

    Layer 0 (every segment's first unit, up to ``w0`` heads) and then
    ``k_layers - 1`` narrow layers each run the merged tick — gather,
    transition, closed-form count-fold, scatter — CHAINED THROUGH THE
    TABLE (layer k+1's gather reads layer k's scatter), so a segment's
    units apply in exact batch order at one narrow tick per layer
    instead of one full-width gather/scatter round per unit.  One
    elementwise expansion then derives every member's response from its
    unit's journal row.  The fused Pallas kernel serves the layers on
    the row layout (real TPU); the XLA merged core serves columns/CPU.
    """
    if layout == "row" and _resolve_fused(fused):
        from gubernator_tpu.ops.fusedtick import make_fused_merged_tick_fn
        from gubernator_tpu.ops.transition32 import expand32_rowmajor

        tick0 = make_fused_merged_tick_fn(capacity, chunk=min(2048, w0))
        tickk = make_fused_merged_tick_fn(
            capacity, chunk=min(2048, layer_width))

        def run_inner(state, mh0, cnt0, mhk, cntk, m32, uidx, rank, now):
            state, r24_0 = tick0(state, mh0, cnt0, now)   # (W0, 24)

            def layer(k, carry):
                st, J = carry
                st, r24 = tickk(st, mhk[k], cntk[k], now)
                return st, jax.lax.dynamic_update_slice(
                    J, r24[None], (k, 0, 0))

            J0 = jnp.zeros((max(k_layers - 1, 1), layer_width, 24), I32)
            state, J = jax.lax.fori_loop(
                0, k_layers - 1, layer, (state, J0))
            flat24 = jnp.concatenate(
                [r24_0, J.reshape(-1, 24)], axis=0)
            return state, jnp.stack(
                expand32_rowmajor(flat24, uidx, rank))

        return jax.jit(run_inner, donate_argnums=(0,))

    core = make_merged_tick32_rows_fn(capacity, layout)

    def run_inner(state, mh0, cnt0, mhk, cntk, m32, uidx, rank, now):
        state, rows0 = core(state, mh0, cnt0, now)

        def layer(k, carry):
            state, J = carry
            state, rows = core(state, mhk[k], cntk[k], now)
            # Journal as FIFTEEN separate carries: stacking the deep
            # parts graphs inside the loop would hand XLA:CPU a
            # concatenate-rooted mega-fusion (make_tick32_rows_fn).
            J = tuple(
                jax.lax.dynamic_update_slice(a, r[None], (k, 0))
                for a, r in zip(J, rows)
            )
            return state, J

        J0 = tuple(
            jnp.zeros((max(k_layers - 1, 1), layer_width), I32)
            for _ in range(15)
        )
        state, J = jax.lax.fori_loop(0, k_layers - 1, layer, (state, J0))
        flat15 = [
            jnp.concatenate([r0, a.reshape(-1)])
            for r0, a in zip(rows0, J)
        ]
        return state, jnp.stack(_expand_sorted(flat15, m32, uidx, rank))

    return jax.jit(run_inner, donate_argnums=(0,))


# ----------------------------------------------------------------------
# Sorted mixed-duplicate tick: chained unit rounds, parts-native
# ----------------------------------------------------------------------
def make_sorted_tick32_rows_fn(capacity: int, layout: str = "columns",
                               unit_unroll: int = 8):
    """The mixed-duplicate program, parts-native: (state, m32 (19, B)
    slot-sorted compact requests, now) → (state, 6-row compact response
    tuple), preserving exact per-slot request order.

    Structure (the engine.make_tick_fn tick_sorted contract, restated in
    int32/f32 parts so no XLA 64-bit emulation rides the mixed-herd
    path):

    * a *unit* is a maximal run of identical fold-eligible duplicates
      (engine._sorted_merge_plan); uniform groups are one unit, groups
      broken by RESET/Gregorian/query/parameter-change rows are several;
    * each round gathers once, then applies up to ``unit_unroll`` units
      per slot IN REGISTERS — head transition (transition32), follower
      fold (merged_fold32 + _expand_members, the grouped program's own
      closed forms), then forward-propagates the folded row state so the
      next unit's head chains without a scatter/gather round trip — and
      scatters once, from each slot's last applied head;
    * cost: ceil(units / unit_unroll) gather+scatter rounds, with
      sequential unit transitions amortized onto cheap elementwise work
      (the Go reference serializes the same traffic per key,
      workers.go:190-258; here the chain rides the VPU).
    """
    from gubernator_tpu.ops.transition32 import (
        _expand_members, merged_fold32)

    if layout == "row":
        from gubernator_tpu.ops.rowtable import gather_rows, scatter_rows

        def gather_mat(state, slots):
            return gather_rows(state.table, slots)

        def scatter_mat(state, scat, mat):
            return state._replace(
                table=scatter_rows(state.table, scat, mat))
    else:

        def gather_mat(state, slots):
            return pstate_to_matrix(pstate_gather_columns(state, slots))

        def scatter_mat(state, scat, mat):
            return pstate_scatter_columns(
                state, scat, pstate_from_matrix(mat))

    def tick(state, m32, now):
        from gubernator_tpu.ops.engine import (
            REQ32_INDEX as R,
            _seg_max_all,
            _seg_min_all,
        )

        b = m32.shape[1]
        idx = jnp.arange(b, dtype=I32)
        rq = preq_from_compact(m32)
        np_ = now_to_pair(now)
        slot = rq.slot
        slots_clip = jnp.clip(slot, 0, capacity - 1)
        key = jnp.where(rq.valid, slot, jnp.int32(capacity))
        is_start = jnp.concatenate(
            [jnp.ones((1,), jnp.bool_), key[1:] != key[:-1]])

        # Unit plan (engine._sorted_merge_plan on the compact matrix):
        # "equals its predecessor" chains to "equals its head" within a
        # contiguous run.
        PARAM_ROWS = (
            R["algorithm"], R["behavior"],
            R["hits"], R["hits"] + 1,
            R["limit"], R["limit"] + 1,
            R["duration"], R["duration"] + 1,
            R["created_at"], R["created_at"] + 1,
            R["burst"], R["burst"] + 1,
            R["greg_exp"], R["greg_exp"] + 1,
            R["greg_dur"], R["greg_dur"] + 1,
        )
        eqp = jnp.ones(b - 1, jnp.bool_)
        for row in PARAM_ROWS:
            eqp = eqp & (m32[row, 1:] == m32[row, :-1])
        same_as_prev = is_start | jnp.concatenate(
            [jnp.ones((1,), jnp.bool_), eqp])
        NO_MERGE = jnp.int32(
            int(Behavior.RESET_REMAINING)
            | int(Behavior.DURATION_IS_GREGORIAN))
        hits_pos = p64.gt(rq.hits, p64.const(0, slot))
        # Closed-form duplicate folds exist only for token/leaky; zoo
        # lanes (algorithm >= 2) stay size-1 units and transition
        # sequentially within the same dispatch.
        legacy_alg = rq.algorithm <= jnp.int32(Algorithm.LEAKY_BUCKET)
        ok = (
            rq.valid & same_as_prev & hits_pos
            & ((rq.behavior & NO_MERGE) == 0)
            & (rq.known | is_start)
            & legacy_alg
        )
        unit_start = is_start | ~ok
        nxt = jnp.where(unit_start, idx, jnp.int32(b))
        sfx = jax.lax.associative_scan(jnp.minimum, nxt[::-1])[::-1]
        unit_end = jnp.concatenate(
            [sfx[1:], jnp.full((1,), b, jnp.int32)])

        resp0 = tuple(jnp.zeros(b, I32) for _ in range(6))
        bmax = jnp.int32(b - 1)

        def sub_step(applied, g_mat, resp, cur_head, last_head):
            """One unit per slot, no scans: ``cur_head[i]`` points at the
            head row the row's segment processes this sub-step (every
            row of a segment shares the value), so all head→member data
            flow is B-indexed gathers.  Rows whose pointer has walked
            into a following segment are harmless: a head is always the
            lowest-indexed live row of its unit, so the ``i > h`` fold
            guard never matches across segments."""
            cand = ~applied
            head = cand & (idx == cur_head)
            s = pstate_from_matrix(g_mat)
            new_s, r_out = transition32(np_, s, rq)
            cnt = jnp.where(head, unit_end - idx, jnp.int32(1))
            folded, mh = merged_fold32(np_, new_s, rq, cnt)
            head6 = _resp_rows(r_out)
            folded_mat = pstate_to_matrix(folded)

            h = cur_head  # (B,) row index of my segment's current head
            def hv(a):
                return a[h]

            hpos = h
            uend = hv(unit_end)
            base = p64.I64(hv(mh.base.lo), hv(mh.base.hi))
            q = p64.I64(hv(mh.q.lo), hv(mh.q.hi))
            rate_i = p64.I64(hv(mh.rate_i.lo), hv(mh.rate_i.hi))
            s0 = hv(mh.s0)
            expire = p64.I64(hv(mh.expire.lo), hv(mh.expire.hi))
            head6_p = tuple(hv(r6) for r6 in head6)
            head_live = hv(head)  # my segment fired a head this sub-step

            rank = idx - hpos
            alive = p64.le(np_, expire)
            fold = (cand & ok & head_live & alive
                    & (rank > 0) & (idx < uend))
            member6 = _expand_members(
                head6_p, base=base, q=q, rate_i=rate_i, s0=s0,
                expire=expire, h=rq.hits, limit=rq.limit,
                created=rq.created_at, algorithm=rq.algorithm,
                behavior=rq.behavior, rank=rank,
            )
            upd = head | fold
            resp = tuple(
                jnp.where(upd, mv, rv) for rv, mv in zip(resp, member6)
            )
            # Chain: every row's working state becomes its segment
            # head's unit-final state (only rows that head the NEXT
            # sub-step consume it, so over-sharing is free and simple).
            g_mat = jnp.where(
                head_live[:, None], folded_mat[h], g_mat)
            applied = applied | head | fold
            last_head = jnp.where(head, idx, last_head)
            # Advance the pointer: a live fold consumed the whole unit
            # (next head = unit end); a dead head consumed only itself.
            nxt_h = jnp.where(
                head_live,
                jnp.minimum(
                    jnp.where(alive, uend, hpos + 1), bmax),
                cur_head,
            )
            return applied, g_mat, resp, nxt_h, last_head

        def round_body(carry):
            applied, state, resp = carry
            g_mat = gather_mat(state, slots_clip)
            cand0 = ~applied
            # One segmented min per ROUND seeds the head pointers; the
            # sub-steps advance them with gathers only.
            first_cand = _seg_min_all(
                is_start, jnp.where(cand0, idx, jnp.int32(b)))
            cur_head = jnp.minimum(first_cand, bmax)
            sc = (applied, g_mat, resp, cur_head, jnp.full(b, -1, I32))
            sc = jax.lax.fori_loop(
                0, max(1, unit_unroll),
                lambda _k, c: jax.lax.cond(
                    jnp.all(c[0]), lambda cc: cc,
                    lambda cc: sub_step(*cc), c,
                ),
                sc,
            )
            applied, g_mat, resp, cur_head, last_head = sc
            seg_last = _seg_max_all(is_start, last_head)
            scat_src = (last_head >= 0) & (last_head == seg_last)
            scat = jnp.where(scat_src, slot, jnp.int32(capacity))
            state = scatter_mat(state, scat, g_mat)
            return applied, state, resp

        applied0 = ~rq.valid
        _, state, resp = jax.lax.while_loop(
            lambda c: ~jnp.all(c[0]), round_body,
            (applied0, state, resp0),
        )
        return state, resp

    return tick


@functools.lru_cache(maxsize=None)
def jitted_sorted_tick32(capacity: int, layout: str = "columns",
                         unit_unroll: int = 8):
    """Engine entry for mixed-duplicate batches: two-program composition
    (rows + stack), like jitted_tick32."""
    inner = jax.jit(
        make_sorted_tick32_rows_fn(capacity, layout, unit_unroll),
        donate_argnums=(0,))
    stack = _jitted_stack6()

    def tick(state, m32, now):
        state, rows = inner(state, m32, now)
        return state, stack(rows)

    return tick


@functools.lru_cache(maxsize=None)
def jitted_merged_pipeline(capacity: int, layout: str = "columns",
                           fused: bool | None = None):
    """Engine entry for grouped batches: (state, mhead, count, uidx,
    rank, now) → (state, (6, B) compact responses).  Composes the merged
    tick with the member expansion, hiding the format split: the fused
    Pallas kernel emits the row-major (U, 24) block (one whole-row
    gather per member — the TPU-fast layout), the XLA fallback emits
    unstacked rows (the CPU-safe layout)."""
    if layout == "row" and _resolve_fused(fused):
        from gubernator_tpu.ops.fusedtick import make_fused_merged_tick_fn
        from gubernator_tpu.ops.transition32 import expand32_rowmajor

        tick = jax.jit(
            make_fused_merged_tick_fn(capacity), donate_argnums=(0,))
        expand = jax.jit(lambda r24, uidx, rank: jnp.stack(
            expand32_rowmajor(r24, uidx, rank)))

        def run(state, mhead, count, uidx, rank, now):
            state, r24 = tick(state, mhead, count, now)
            return state, expand(r24, uidx, rank)

        return run

    from gubernator_tpu.ops.transition32 import expand32_rows

    inner = jax.jit(
        make_merged_tick32_rows_fn(capacity, layout), donate_argnums=(0,))
    expand = jax.jit(expand32_rows)
    stack = _jitted_stack6()

    def run(state, mhead, count, uidx, rank, now):
        state, rows = inner(state, mhead, count, now)
        return state, stack(expand(tuple(rows), mhead, uidx, rank))

    return run
