"""The parts-native tick program: compact i32 requests in, compact i32
responses out, no 64-bit ops anywhere in the hot path.

This is the unique-slot fast program: the host sorts every batch by slot
(engine._build_cols) and knows whether duplicates exist; batches with at
most one request per slot — the overwhelming production shape and the
bench worst case — dispatch here, duplicate-bearing batches take the
merge-capable program (engine.make_tick_fn).  Keeping the two as
separate host-dispatched programs (instead of a traced lax.cond) lets
this one stay pure int32/float32, which is what allows it to run inside
a Mosaic kernel at all (Mosaic refuses jax_enable_x64 programs) and
removes XLA's emulated-64-bit overhead from the XLA fallback.

Layouts:
* ``row`` — Pallas per-row DMA gather/scatter around a parts transition
  (fused kernel lands behind this same factory).
* ``columns`` — direct i32 part-column gathers/scatters (the 100M-slot
  regime, where the row table doesn't fit).

Reference semantics: algorithms.go:37-493 via ops/transition32.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from gubernator_tpu.ops import i64pair as p64
from gubernator_tpu.ops.transition32 import (
    preq_from_compact,
    presp_to_compact,
    pstate_from_matrix,
    pstate_gather_columns,
    pstate_scatter_columns,
    pstate_to_matrix,
    transition32,
)

I32 = jnp.int32


def now_to_pair(now: jnp.ndarray) -> p64.I64:
    """Scalar int64 ``now`` → (lo, hi) i32 pair (scalar arithmetic only —
    this toolchain's X64 rewriter has no 64-bit bitcasts)."""
    hi = (now >> 32).astype(I32)
    lo_u = now & jnp.int64(0xFFFFFFFF)
    lo = jnp.where(
        lo_u >= jnp.int64(1 << 31), lo_u - jnp.int64(1 << 32), lo_u
    ).astype(I32)
    return p64.I64(lo, hi)


def make_tick32_fn(capacity: int, layout: str = "columns",
                   fused: bool | None = None):
    """Build (state, m32, now) → (state, resp6) for unique-slot batches.

    Contract (matches make_tick_fn's compact in/out so TickHandle code is
    shared): ``m32`` is the (19, B) compact request matrix, slot-sorted,
    padding/error rows carrying slot == capacity; at most one valid
    request per real slot.  ``resp6`` is the (6, B) compact response
    matrix; rows past the live count are unspecified.
    """

    if layout == "row":
        import os

        if fused is None:
            fused = os.environ.get("GUBER_TPU_FUSED_TICK", "1") != "0"
        if fused:
            from gubernator_tpu.ops.fusedtick import make_fused_tick_fn

            return make_fused_tick_fn(capacity)

        from gubernator_tpu.ops.rowtable import gather_rows, scatter_rows

        def tick(state, m32, now):
            r = preq_from_compact(m32)
            slots = jnp.clip(r.slot, 0, capacity)
            mat = gather_rows(state.table, slots)
            s = pstate_from_matrix(mat)
            new_g, resp = transition32(now_to_pair(now), s, r)
            scat = jnp.where(r.valid, slots, jnp.int32(capacity))
            table = scatter_rows(state.table, scat, pstate_to_matrix(new_g))
            return state._replace(table=table), presp_to_compact(resp)

    else:

        def tick(state, m32, now):
            r = preq_from_compact(m32)
            slots = jnp.clip(r.slot, 0, capacity - 1)
            s = pstate_gather_columns(state, slots)
            new_g, resp = transition32(now_to_pair(now), s, r)
            # unclipped slot: padding rows (slot == capacity) drop
            scat = jnp.where(r.valid, r.slot, jnp.int32(capacity))
            state = pstate_scatter_columns(state, scat, new_g)
            return state, presp_to_compact(resp)

    return tick


@functools.lru_cache(maxsize=None)
def jitted_tick32(capacity: int, layout: str = "columns",
                  fused: bool | None = None):
    return jax.jit(
        make_tick32_fn(capacity, layout, fused=fused), donate_argnums=(0,))
