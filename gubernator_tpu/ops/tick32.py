"""The parts-native tick program: compact i32 requests in, compact i32
responses out, no 64-bit ops anywhere in the hot path.

This is the unique-slot fast program: the host sorts every batch by slot
(engine._build_cols) and knows whether duplicates exist; batches with at
most one request per slot — the overwhelming production shape and the
bench worst case — dispatch here, duplicate-bearing batches take the
merge-capable program (engine.make_tick_fn).  Keeping the two as
separate host-dispatched programs (instead of a traced lax.cond) lets
this one stay pure int32/float32, which is what allows it to run inside
a Mosaic kernel at all (Mosaic refuses jax_enable_x64 programs) and
removes XLA's emulated-64-bit overhead from the XLA fallback.

Layouts:
* ``row`` — Pallas per-row DMA gather/scatter around a parts transition
  (fused kernel lands behind this same factory).
* ``columns`` — direct i32 part-column gathers/scatters (the 100M-slot
  regime, where the row table doesn't fit).

Reference semantics: algorithms.go:37-493 via ops/transition32.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from gubernator_tpu.ops import i64pair as p64
from gubernator_tpu.ops.transition32 import (
    preq_from_compact,
    presp_to_compact,
    pstate_from_matrix,
    pstate_gather_columns,
    pstate_scatter_columns,
    pstate_to_matrix,
    transition32,
)

I32 = jnp.int32


def now_to_pair(now: jnp.ndarray) -> p64.I64:
    """Scalar int64 ``now`` → (lo, hi) i32 pair (scalar arithmetic only —
    this toolchain's X64 rewriter has no 64-bit bitcasts)."""
    hi = (now >> 32).astype(I32)
    lo_u = now & jnp.int64(0xFFFFFFFF)
    lo = jnp.where(
        lo_u >= jnp.int64(1 << 31), lo_u - jnp.int64(1 << 32), lo_u
    ).astype(I32)
    return p64.I64(lo, hi)


def _resolve_fused(fused: bool | None) -> bool:
    """Default: fused Pallas on real TPU, unfused XLA elsewhere.  On CPU
    the fused kernel only exists in interpret mode (a Python-stepped DMA
    loop — seconds per tick), so the 8-device test mesh would crawl;
    GUBER_TPU_FUSED_TICK=0/1 still forces either path on any backend
    (tests/test_fusedtick.py covers fused-vs-unfused parity in interpret
    mode explicitly)."""
    import os

    if fused is not None:
        return fused
    env = os.environ.get("GUBER_TPU_FUSED_TICK")
    if env is not None:
        return env != "0"
    return jax.default_backend() == "tpu"


def _resp_rows(resp) -> tuple:
    """PResp → the six compact response rows, unstacked (same order as
    presp_to_compact: status, over, rem lo/hi, reset lo/hi)."""
    return (
        resp.status,
        resp.over_limit.astype(I32),
        resp.remaining.lo,
        resp.remaining.hi,
        resp.reset_time.lo,
        resp.reset_time.hi,
    )


def make_tick32_rows_fn(capacity: int, layout: str = "columns"):
    """The XLA (non-Pallas) tick program, response as SIX SEPARATE row
    vectors rather than one stacked (6, B) matrix.

    The split exists because stacking is poison on the CPU backend:
    XLA:CPU emits a concatenate-rooted fusion over this very deep
    elementwise graph by recursively re-evaluating each operand's
    expression tree per output element (no memoization across the
    diamond-shaped reuse in the i64-pair/triple-f32 arithmetic), which
    turns a ~10 µs tick into ~0.2 s *per batch element* — a 64-wide tick
    took 12 s on the 8-device test mesh.  Returning the rows as separate
    program outputs keeps every fusion root single-output, which XLA
    emits as one memoized loop.  TPU's emitter doesn't have the
    pathology, but the two-program composition costs only a dispatch.
    """

    if layout == "row":
        from gubernator_tpu.ops.rowtable import gather_rows, scatter_rows

        def tick(state, m32, now):
            r = preq_from_compact(m32)
            slots = jnp.clip(r.slot, 0, capacity)
            mat = gather_rows(state.table, slots)
            s = pstate_from_matrix(mat)
            new_g, resp = transition32(now_to_pair(now), s, r)
            scat = jnp.where(r.valid, slots, jnp.int32(capacity))
            table = scatter_rows(state.table, scat, pstate_to_matrix(new_g))
            return state._replace(table=table), _resp_rows(resp)

    else:

        def tick(state, m32, now):
            r = preq_from_compact(m32)
            slots = jnp.clip(r.slot, 0, capacity - 1)
            s = pstate_gather_columns(state, slots)
            new_g, resp = transition32(now_to_pair(now), s, r)
            # unclipped slot: padding rows (slot == capacity) drop
            scat = jnp.where(r.valid, r.slot, jnp.int32(capacity))
            state = pstate_scatter_columns(state, scat, new_g)
            return state, _resp_rows(resp)

    return tick


def make_tick32_fn(capacity: int, layout: str = "columns",
                   fused: bool | None = None):
    """Build (state, m32, now) → (state, resp6) for unique-slot batches.

    Contract (matches make_tick_fn's compact in/out so TickHandle code is
    shared): ``m32`` is the (19, B) compact request matrix, slot-sorted,
    padding/error rows carrying slot == capacity; at most one valid
    request per real slot.  ``resp6`` is the (6, B) compact response
    matrix; rows past the live count are unspecified.

    This single-program form is for callers that need one traceable
    function (bench chains it inside a fori_loop on TPU).  Engines should
    use :func:`jitted_tick32`, which splits the response stack into a
    second program — see make_tick32_rows_fn for why.
    """

    if layout == "row" and _resolve_fused(fused):
        from gubernator_tpu.ops.fusedtick import make_fused_tick_fn

        return make_fused_tick_fn(capacity)

    rows_fn = make_tick32_rows_fn(capacity, layout)

    def tick(state, m32, now):
        state, rows = rows_fn(state, m32, now)
        return state, jnp.stack(rows)

    return tick


@functools.lru_cache(maxsize=None)
def _jitted_stack6():
    return jax.jit(lambda rows: jnp.stack(rows))


@functools.lru_cache(maxsize=None)
def jitted_tick32(capacity: int, layout: str = "columns",
                  fused: bool | None = None):
    """Engine entry: two-program composition (tick rows + stack) so the
    CPU backend never sees a concatenate-rooted mega-fusion (see
    make_tick32_rows_fn).  The fused Pallas row kernel packs its response
    in-kernel and stays a single program."""
    if layout == "row" and _resolve_fused(fused):
        from gubernator_tpu.ops.fusedtick import make_fused_tick_fn

        return jax.jit(make_fused_tick_fn(capacity), donate_argnums=(0,))

    inner = jax.jit(
        make_tick32_rows_fn(capacity, layout), donate_argnums=(0,))
    stack = _jitted_stack6()

    def tick(state, m32, now):
        state, rows = inner(state, m32, now)
        return state, stack(rows)

    return tick


# ----------------------------------------------------------------------
# Grouped ("scatter-add") tick: unique heads + closed-form fold
# ----------------------------------------------------------------------
def make_merged_tick32_rows_fn(capacity: int, layout: str = "columns"):
    """(state, mhead (19, U) i32, count (U,) i32, now) → (state, 15-row
    tuple): the unique-head tick with the duplicate-group fold applied to
    the table row (transition32.merged_fold32) and the head extras the
    expansion program needs.  Same unstacked-rows discipline as
    make_tick32_rows_fn (XLA:CPU concat-fusion pathology)."""
    from gubernator_tpu.ops.transition32 import merged_fold32

    def rows_of(now, s, r, count, new_g, resp):
        folded, head = merged_fold32(now, new_g, r, count)
        return folded, (
            resp.status,
            resp.over_limit.astype(I32),
            resp.remaining.lo, resp.remaining.hi,
            resp.reset_time.lo, resp.reset_time.hi,
            head.base.lo, head.base.hi,
            head.q.lo, head.q.hi,
            head.rate_i.lo, head.rate_i.hi,
            head.s0,
            head.expire.lo, head.expire.hi,
        )

    if layout == "row":
        from gubernator_tpu.ops.rowtable import gather_rows, scatter_rows

        def tick(state, mhead, count, now):
            r = preq_from_compact(mhead)
            slots = jnp.clip(r.slot, 0, capacity)
            mat = gather_rows(state.table, slots)
            s = pstate_from_matrix(mat)
            np_ = now_to_pair(now)
            new_g, resp = transition32(np_, s, r)
            folded, rows = rows_of(np_, s, r, count, new_g, resp)
            scat = jnp.where(r.valid, slots, jnp.int32(capacity))
            table = scatter_rows(
                state.table, scat, pstate_to_matrix(folded))
            return state._replace(table=table), rows

    else:

        def tick(state, mhead, count, now):
            r = preq_from_compact(mhead)
            slots = jnp.clip(r.slot, 0, capacity - 1)
            s = pstate_gather_columns(state, slots)
            np_ = now_to_pair(now)
            new_g, resp = transition32(np_, s, r)
            folded, rows = rows_of(np_, s, r, count, new_g, resp)
            scat = jnp.where(r.valid, r.slot, jnp.int32(capacity))
            state = pstate_scatter_columns(state, scat, folded)
            return state, rows

    return tick


@functools.lru_cache(maxsize=None)
def jitted_merged_pipeline(capacity: int, layout: str = "columns",
                           fused: bool | None = None):
    """Engine entry for grouped batches: (state, mhead, count, uidx,
    rank, now) → (state, (6, B) compact responses).  Composes the merged
    tick with the member expansion, hiding the format split: the fused
    Pallas kernel emits the row-major (U, 24) block (one whole-row
    gather per member — the TPU-fast layout), the XLA fallback emits
    unstacked rows (the CPU-safe layout)."""
    if layout == "row" and _resolve_fused(fused):
        from gubernator_tpu.ops.fusedtick import make_fused_merged_tick_fn
        from gubernator_tpu.ops.transition32 import expand32_rowmajor

        tick = jax.jit(
            make_fused_merged_tick_fn(capacity), donate_argnums=(0,))
        expand = jax.jit(lambda r24, uidx, rank: jnp.stack(
            expand32_rowmajor(r24, uidx, rank)))

        def run(state, mhead, count, uidx, rank, now):
            state, r24 = tick(state, mhead, count, now)
            return state, expand(r24, uidx, rank)

        return run

    from gubernator_tpu.ops.transition32 import expand32_rows

    inner = jax.jit(
        make_merged_tick32_rows_fn(capacity, layout), donate_argnums=(0,))
    expand = jax.jit(expand32_rows)
    stack = _jitted_stack6()

    def run(state, mhead, count, uidx, rank, now):
        state, rows = inner(state, mhead, count, now)
        return state, stack(expand(tuple(rows), mhead, uidx, rank))

    return run
