"""Tick-batched rate-limit engine: the TPU replacement for the worker pool.

The reference shards its key space over N single-goroutine workers with
private cache shards and routes each request through channels
(``workers.go:19-37,125-147``).  Here the whole table is one device-resident
struct-of-arrays (:class:`gubernator_tpu.ops.buckets.BucketState`) and a
*tick* applies an entire batch of requests in one fused XLA program:

    gather slots → branch-free transition → scatter back

**Sequential semantics for duplicate keys.**  Go serializes same-key requests
via worker ownership; a batch may contain several hits on one key and each
must observe the state left by the previous one.  We reproduce this exactly:
requests are ranked by arrival order *within* their slot (a stable sort by
slot + a segmented iota), and a ``lax.while_loop`` applies one "rank round"
at a time — round *k* touches at most one request per slot, so gathers and
scatters never conflict.  Batches with all-unique keys run exactly one round.

**Host/device split.**  The host owns the key→slot mapping (strings never
reach the device), stamps wall-clock time, resolves Gregorian calendar math,
and reclaims slots (TTL first, then LRU by last-touched tick — mirroring the
expired-on-read eviction + evict-oldest of ``lrucache.go:88-149``).  The
device owns all bucket arithmetic.
"""

from __future__ import annotations

import functools
import threading
import time
from typing import Dict, List, Optional, Sequence

import gubernator_tpu.jaxinit  # noqa: F401  (x64 + compile cache before jax use)
import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from gubernator_tpu.ops.buckets import (
    BucketState,
    ReqBatch,
    RespBatch,
    bucket_transition,
    gather_state,
    np_logical,
    to_logical,
    scatter_state,
)
from gubernator_tpu.ops import rowtable
from gubernator_tpu.ops.reqcols import CREATED_UNSET, ReqColumns, compact_blob
from gubernator_tpu.ops.rowtable import RowState
from gubernator_tpu.types import (
    Algorithm,
    Behavior,
    GlobalUpdate,
    RateLimitRequest,
    RateLimitResponse,
    Status,
    has_behavior,
)
from gubernator_tpu.utils import flightrec, timeutil, tracing
from gubernator_tpu.utils.hotpath import hot_path
from gubernator_tpu.utils import sanitize


# Table storage layouts (see rowtable.py for the row design rationale):
#   "columns" — tuple-of-int32-columns SoA; XLA gathers/scatters.  The
#               CPU/mesh default, and the fallback for huge tables.
#   "row"     — (capacity+1, 128)-word rows moved by Pallas per-row DMA.
#               ~6-8x faster ticks on TPU, 512 B/slot.
ROW_LAYOUT_MAX_BYTES = 6 << 30  # beyond this, fall back to columns


def make_layout_choice(layout: str, capacity: int, device,
                       max_batch: int = 0) -> str:
    """Resolve an engine ``table_layout`` setting ("auto"/"row"/"columns").

    ``max_batch`` participates because the row kernels stage the whole
    request block in VMEM (512 B/row): widths past 64k rows don't fit
    alongside the double-buffered pipeline, so auto falls back."""
    if layout == "auto":
        row_bytes = (capacity + 1) * rowtable.ROW_W * 4
        return (
            "row"
            if device.platform == "tpu"
            and row_bytes <= ROW_LAYOUT_MAX_BYTES
            and pad_pow2(max_batch or 1) <= EVICT_CHUNK
            else "columns"
        )
    if layout not in ("row", "columns"):
        raise ValueError(f"unknown table layout {layout!r}")
    return layout


def _layout_ops(layout: str):
    """(zeros, gather, scatter) for a storage layout."""
    if layout == "row":
        return (
            RowState.zeros,
            rowtable.row_gather_state,
            rowtable.row_scatter_state,
        )
    return (
        BucketState.zeros,
        gather_state,
        scatter_state,
    )


def _slot_segments(slot: jnp.ndarray, valid: jnp.ndarray, capacity: int):
    """Per-request segment info for requests sharing a slot.

    Stable-sorts by slot (invalid rows pushed past ``capacity``), computes a
    segmented iota over equal-slot runs, and scatters everything back to
    request order.  O(B log B), no table-sized buffers.  Returns
    ``(rank, group_size, head_idx, seg_id)``: arrival rank within the slot
    group, the group's member count, the original index of the group's
    first request, and a dense segment id usable as a B-bounded scatter
    target for segmented reductions.
    """
    # int32 sort key: capacity < 2^31 always (slots are i32); a 64-bit
    # key doubles the on-device sort cost for nothing.
    sort_key = jnp.where(valid, slot, capacity).astype(jnp.int32)
    order = jnp.argsort(sort_key, stable=True)
    return _segments_from_sorted(sort_key[order], order)


def _segments_from_sorted(sorted_key: jnp.ndarray, order: jnp.ndarray):
    """Segment info from an already-sorted key column (see
    :func:`_slot_segments`; the tick sorts once for duplicate detection
    and reuses the result here)."""
    b = sorted_key.shape[0]
    idx = jnp.arange(b, dtype=jnp.int32)
    is_start = jnp.concatenate(
        [jnp.ones((1,), jnp.bool_), sorted_key[1:] != sorted_key[:-1]]
    )
    seg_start = lax.associative_scan(jnp.maximum, jnp.where(is_start, idx, 0))
    rank_sorted = idx - seg_start
    seg_id_sorted = jnp.cumsum(is_start.astype(jnp.int32)) - 1
    sizes = jnp.zeros(b, jnp.int32).at[seg_id_sorted].add(1)
    inv = jnp.zeros(b, jnp.int32).at[order].set(idx)  # request → sorted pos
    rank = rank_sorted[inv]
    seg_id = seg_id_sorted[inv]
    group_size = sizes[seg_id]
    head_idx = order[seg_start][inv]
    return rank, group_size, head_idx, seg_id


def _rank_within_slot(slot: jnp.ndarray, valid: jnp.ndarray, capacity: int):
    """Arrival rank of each request among requests sharing its slot."""
    return _slot_segments(slot, valid, capacity)[0]


def pad_pow2(n: int) -> int:
    """Next power of two ≥ n: variable-width scatter batches (install/evict)
    quantize to a few shapes so jit doesn't recompile per width."""
    return 1 << max(0, (int(n) - 1)).bit_length()


# Row layout of the packed request matrix (one H2D transfer per tick instead
# of 12 — device-transfer latency dominates small ticks, especially over a
# tunneled device).
REQ_ROWS = (
    "slot", "known", "hits", "limit", "duration", "algorithm", "behavior",
    "created_at", "burst", "greg_exp", "greg_dur", "valid",
)
REQ_ROW_INDEX = {name: i for i, name in enumerate(REQ_ROWS)}


def pack_request_matrix(
    m: np.ndarray,
    sel,
    requests,
    slots,
    known,
    now: int,
    *,
    nodes=None,
    behav=None,
    greg=None,
) -> None:
    """Vectorized fill of the packed LEGACY int64 request matrix: one
    attribute pass over ``requests`` plus one fancy-indexed numpy write
    per row.  Remaining user: the GLOBAL mesh engine (global_mesh.py) —
    the single-chip and sharded tick engines moved to the compact int32
    wire format (:func:`pack_request_matrix32` / REQ32 layout).

    ``m`` is (len(REQ_ROWS), B), or (N, len(REQ_ROWS), B) with ``nodes``
    giving the leading-axis index per request.  ``behav`` optionally
    passes precomputed int behaviors (IntFlag conversion is a measured
    host hotspot).  ``greg`` is (greg_exp, greg_dur) per request, or None
    when the caller already wrote those rows."""
    if len(requests) == 0:
        return
    R = REQ_ROW_INDEX

    def put(row, vals):
        if nodes is None:
            m[R[row], sel] = vals
        else:
            m[nodes, R[row], sel] = vals

    if behav is None:
        behav = [int(r.behavior) for r in requests]
    hits, limit, duration, algo, created, burst = zip(*(
        (r.hits, r.limit, r.duration, int(r.algorithm),
         r.created_at if r.created_at is not None else now, r.burst)
        for r in requests
    ))
    put("slot", slots)
    put("known", known)
    put("hits", hits)
    put("limit", limit)
    put("duration", duration)
    put("algorithm", algo)
    put("behavior", behav)
    put("created_at", created)
    put("burst", burst)
    if greg is not None:
        put("greg_exp", greg[0])
        put("greg_dur", greg[1])
    put("valid", 1)


def resolve_gregorian(r: "RateLimitRequest", now: int) -> tuple[int, int]:
    """Host-side Gregorian resolution for one request: (greg_exp, greg_dur).

    Returns (0, 0) when DURATION_IS_GREGORIAN is unset; raises
    :class:`gubernator_tpu.utils.timeutil.GregorianError` on a bad selector
    (callers surface it in the per-item ``error`` field, the reference's
    error-in-item convention, gubernator.go:208-216).
    """
    if not has_behavior(r.behavior, Behavior.DURATION_IS_GREGORIAN):
        return 0, 0
    return (
        timeutil.gregorian_expiration(now, r.duration),
        timeutil.gregorian_duration(now, r.duration),
    )


def unpack_reqs(packed: jnp.ndarray) -> ReqBatch:
    """(12, B) int64 matrix → ReqBatch (device-side, inside jit)."""
    f = dict(zip(REQ_ROWS, packed))
    return ReqBatch(
        slot=f["slot"].astype(jnp.int32),
        known=f["known"].astype(jnp.bool_),
        hits=f["hits"],
        limit=f["limit"],
        duration=f["duration"],
        algorithm=f["algorithm"].astype(jnp.int32),
        behavior=f["behavior"].astype(jnp.int32),
        created_at=f["created_at"],
        burst=f["burst"],
        greg_exp=f["greg_exp"],
        greg_dur=f["greg_dur"],
        valid=f["valid"].astype(jnp.bool_),
    )


# Compact int32 request wire format: narrow fields ride one i32 row each,
# 8-byte fields ride (lo, hi) i32 pairs — 76 B/request over the link
# instead of the legacy int64 matrix's 96 (the engine's H2D is a top cost
# both over remote links and on PCIe hosts at high tick rates).
REQ32_NARROW = ("slot", "known", "algorithm", "behavior", "valid")
REQ32_WIDE = (
    "hits", "limit", "duration", "created_at", "burst",
    "greg_exp", "greg_dur",
)
REQ32_INDEX = {name: i for i, name in enumerate(REQ32_NARROW)}
for _j, _name in enumerate(REQ32_WIDE):
    REQ32_INDEX[_name] = len(REQ32_NARROW) + 2 * _j  # the lo row; hi = +1
REQ32_ROWS = len(REQ32_NARROW) + 2 * len(REQ32_WIDE)  # 19


def split_i64(v: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """int64 → (lo, hi) int32 pair — THE host-side definition of the
    compact wire format's wide encoding (device inverse:
    unpack_reqs_compact; host inverse: join_i32_pair)."""
    return (
        (v & 0xFFFFFFFF).astype(np.uint32).view(np.int32),
        (v >> 32).astype(np.int32),
    )


@hot_path
def pack_wide_rows(m32: np.ndarray, name: str, values, ix) -> None:
    """Host-side write of an int64 column as its (lo, hi) i32 pair.
    Runs per tick on the dispatch thread (every @hot_path packer funnels
    through it) — marked so G001 visits it directly."""
    # guber: allow-G001(host-side wire packing - values is a host list or np column, asarray is the cheap staging copy, never a device sync)
    lo, hi = split_i64(np.asarray(values, np.int64))
    r = REQ32_INDEX[name]
    m32[r, ix] = lo
    m32[r + 1, ix] = hi


def pack_request_matrix32(
    m32: np.ndarray,
    sel,
    requests,
    slots,
    known,
    now: int,
    *,
    nodes=None,
    greg=None,
) -> None:
    """Compact-format counterpart of :func:`pack_request_matrix`: fill a
    (REQ32_ROWS, B) — or (N, REQ32_ROWS, B) with ``nodes`` — int32 matrix
    from request objects.  One attribute pass + one vectorized write per
    row (wide fields as lo/hi pairs)."""
    if len(requests) == 0:
        return
    R = REQ32_INDEX

    def put(row, vals):
        if nodes is None:
            m32[R[row], sel] = vals
        else:
            m32[nodes, R[row], sel] = vals

    def put_wide(name, vals):
        if nodes is None:
            pack_wide_rows(m32, name, vals, sel)
            return
        v = np.asarray(vals, np.int64)
        lo, hi = split_i64(v)
        r = REQ32_INDEX[name]
        m32[nodes, r, sel] = lo
        m32[nodes, r + 1, sel] = hi

    behav, hits, limit, duration, algo, created, burst = zip(*(
        (int(r.behavior), r.hits, r.limit, r.duration, int(r.algorithm),
         r.created_at if r.created_at is not None else now, r.burst)
        for r in requests
    ))
    put("slot", slots)
    put("known", known)
    put("algorithm", algo)
    put("behavior", behav)
    put("valid", 1)
    put_wide("hits", hits)
    put_wide("limit", limit)
    put_wide("duration", duration)
    put_wide("created_at", created)
    put_wide("burst", burst)
    if greg is not None:
        put_wide("greg_exp", greg[0])
        put_wide("greg_dur", greg[1])


@hot_path
def pack_cols_req32(m32: np.ndarray, cols, slots, known, now: int, ix) -> None:
    """Shard-aware columnar REQ32 fill: write one resolved batch's
    request columns into a staging slab — the ONE definition of how a
    ``ReqColumns`` batch becomes compact wire rows, shared by the
    single-chip engine (``TickEngine._build_cols``) and the sharded
    mesh engine's flat routed packer.

    ``ix`` selects the packed lanes (a slice for the contiguous
    no-error batch, a fancy index when shed/error rows are skipped).
    ``slots`` may be LOCAL (single-chip) or GLOBAL (mesh-routed) — the
    packer doesn't care, which is what makes it shard-aware: ownership
    is a property of the slot value, not of the wire format."""
    R = REQ32_INDEX
    m32[R["slot"], ix] = slots
    m32[R["known"], ix] = known
    m32[R["algorithm"], ix] = cols.algorithm[ix]
    m32[R["behavior"], ix] = cols.behavior[ix]
    m32[R["valid"], ix] = 1
    pack_wide_rows(m32, "hits", cols.hits[ix], ix)
    pack_wide_rows(m32, "limit", cols.limit[ix], ix)
    pack_wide_rows(m32, "duration", cols.duration[ix], ix)
    ca = cols.created_at[ix]
    pack_wide_rows(
        m32, "created_at", np.where(ca != CREATED_UNSET, ca, now), ix
    )
    pack_wide_rows(m32, "burst", cols.burst[ix], ix)


def sort_packed_by_slot(m32: np.ndarray, n: int, capacity: int):
    """Stable in-place sort of a packed REQ32 batch's live lanes by the
    slot row (same-slot requests keep arrival order — the duplicate-
    sequencing contract) and duplicate detection against ``capacity``'s
    padding sentinel.  Returns ``(inv, has_dups)``: the request→sorted-
    lane permutation (responses un-permute through it) and whether any
    live slot repeats (routes the batch to the merge-capable program)."""
    R = REQ32_INDEX
    order = np.argsort(m32[R["slot"], :n], kind="stable")
    m32[:, :n] = m32[:, :n][:, order]
    inv = np.empty(n, np.int64)
    inv[order] = np.arange(n)
    sl = m32[R["slot"], :n]
    has_dups = bool(  # guber: allow-G001(m32 is host numpy, never device)
        ((sl[1:] == sl[:-1]) & (sl[1:] < capacity)).any()
    )
    return inv, has_dups


class StagingRing:
    """Reusable host staging slabs for async H2D request uploads — the
    double-buffered pipeline contract (docs/tpu-performance.md round 6)
    factored out of ``TickEngine`` so the sharded mesh engine shares one
    implementation: a slab recycles only once the tick handle that
    consumed it has resolved (until then jax may still read the host
    buffer for the in-flight copy), and when every slab is in flight
    the lease falls back to a fresh allocation rather than corrupting
    one.  Callers hold their engine lock around lease()/retire() (ring
    state is unsynchronized).

    ``width`` picks between the two slab regimes:

    * ``width=None`` (single-chip ``TickEngine``): slabs materialize
      lazily per leased width — the engine quantizes batch sizes to a
      small width ladder, so the dict stays a handful of entries.
    * ``width=B`` (sharded mesh engine): ONE ring of ``(rows, B)``
      slabs preallocated up front.  The ragged dispatch always leases
      the full batch capacity — extent offsets, not slab shape, carry
      the per-window size — so there is exactly one slab shape, one
      H2D signature, one traced program."""

    __slots__ = ("rows", "sentinel", "depth", "_stage", "_next", "_leased",
                 "metric_leases", "metric_fallback_allocs")

    def __init__(self, rows: int, sentinel: int, depth: int,
                 width: Optional[int] = None):
        self.rows = int(rows)
        self.sentinel = int(sentinel)
        self.depth = int(depth)
        self._stage: Dict[int, list] = {}   # width -> [[matrix, handle]]
        self._next: Dict[int, int] = {}
        if width is not None:
            w = int(width)
            self._stage[w] = [
                [np.empty((self.rows, w), np.int32), None]
                for _ in range(self.depth)
            ]
            self._next[w] = 0
        self._leased: Optional[list] = None
        # Plain-int telemetry (caller holds the engine lock): total
        # leases and how many missed the ring entirely (every slab
        # in flight → fresh allocation) — surfaced by /debug/state.
        self.metric_leases = 0
        self.metric_fallback_allocs = 0

    def lease(self, b: int) -> np.ndarray:
        """A zeroed (rows, b) slab with the slot row pre-set to the
        padding sentinel (padding lanes scatter out of bounds)."""
        ring = self._stage.get(b)
        if ring is None:
            ring = self._stage[b] = [
                [np.empty((self.rows, b), np.int32), None]
                for _ in range(self.depth)
            ]
            self._next[b] = 0
        slot = None
        start = self._next[b]
        for k in range(len(ring)):
            cand = ring[(start + k) % len(ring)]
            h = cand[1]
            if h is None or h._done is not None:
                slot = cand
                self._next[b] = (start + k + 1) % len(ring)
                break
        self.metric_leases += 1
        if slot is None:
            # Every slab still feeds an unresolved window (caller is
            # pipelining deeper than the ring): plain allocation.
            m = np.empty((self.rows, b), np.int32)
            self._leased = None
            self.metric_fallback_allocs += 1
        else:
            slot[1] = None
            m = slot[0]
            self._leased = slot
        m.fill(0)
        m[REQ32_INDEX["slot"]] = self.sentinel
        return m

    def telemetry(self) -> dict:
        """Snapshot for /debug/state: ring shape, per-width slab counts
        and how many slabs are currently bound to unresolved handles."""
        widths = {}
        for w, ring in self._stage.items():
            in_flight = sum(
                1 for _, h in ring if h is not None and h._done is None
            )
            widths[int(w)] = {"slabs": len(ring), "in_flight": in_flight}
        return {
            "depth": self.depth,
            "leases": self.metric_leases,
            "fallback_allocs": self.metric_fallback_allocs,
            "widths": widths,
        }

    def retire(self, handle) -> None:
        """Bind the most recent lease to the tick handle consuming it
        (the slab recycles when that handle resolves); ``None`` frees
        the slab immediately — the dispatch never uploaded it."""
        if self._leased is not None:
            self._leased[1] = handle
            self._leased = None


@hot_path
def join_i32_pair(lo: np.ndarray, hi: np.ndarray) -> np.ndarray:
    """Host-side (lo, hi) int32 pair → int64 (the compact wire format's
    inverse; two's complement preserved for negatives).  Per-tick on the
    dispatch thread (group/layer plan builds) — G001 visits it directly."""
    return (
        # guber: allow-G001(host-side wire unpacking - inputs are host i32 rows, asarray is a view, never a device sync)
        (np.asarray(hi).astype(np.int64) << 32)
        # guber: allow-G001(host-side wire unpacking - same as the hi row above)
        | np.asarray(lo).astype(np.uint32).astype(np.int64)
    )


def unpack_reqs_compact(m32: jnp.ndarray) -> ReqBatch:
    """(19, B) int32 matrix → ReqBatch (device-side, inside jit)."""

    def wide(name):
        r = REQ32_INDEX[name]
        lo = m32[r].astype(jnp.uint32).astype(jnp.int64)
        return (m32[r + 1].astype(jnp.int64) << 32) | lo

    return ReqBatch(
        slot=m32[REQ32_INDEX["slot"]],
        known=m32[REQ32_INDEX["known"]].astype(jnp.bool_),
        hits=wide("hits"),
        limit=wide("limit"),
        duration=wide("duration"),
        algorithm=m32[REQ32_INDEX["algorithm"]],
        behavior=m32[REQ32_INDEX["behavior"]],
        created_at=wide("created_at"),
        burst=wide("burst"),
        greg_exp=wide("greg_exp"),
        greg_dur=wide("greg_dur"),
        valid=m32[REQ32_INDEX["valid"]].astype(jnp.bool_),
    )


def pack_resp(resp: RespBatch) -> jnp.ndarray:
    """RespBatch → (5, B) int64 matrix (one D2H transfer)."""
    return jnp.stack(
        [
            resp.status.astype(jnp.int64),
            resp.limit,
            resp.remaining,
            resp.reset_time,
            resp.over_limit.astype(jnp.int64),
        ]
    )


def pack_resp_compact(resp: RespBatch) -> jnp.ndarray:
    """RespBatch → (6, B) **int32** matrix: status, over_limit, and the
    lo/hi halves of remaining and reset_time.

    The response ``limit`` is always an echo of the request's limit
    (reference algorithms.go returns rl.Limit after the limit-delta rules
    update stored state to it), so the host reconstructs it from the
    request columns instead of shipping 8 more bytes per decision — 24
    B/decision instead of 40 over the link (TickHandle._finish rebuilds
    the public (5, B) int64 contract)."""

    def split(v):
        return (
            (v & jnp.int64(0xFFFFFFFF)).astype(jnp.int32),
            (v >> 32).astype(jnp.int32),
        )

    rl, rh = split(resp.remaining)
    tl, th = split(resp.reset_time)
    return jnp.stack(
        [resp.status, resp.over_limit.astype(jnp.int32), rl, rh, tl, th]
    )


def unpack_resp_compact(raw: np.ndarray, limit_req: np.ndarray) -> np.ndarray:
    """Host inverse of :func:`pack_resp_compact`: (6, n) int32 in request
    order + the request-order limit column → the (5, n) int64 response
    matrix.  Values at per-item-error indices are unspecified (callers
    overwrite those with error responses)."""
    n = raw.shape[1]
    out = np.empty((5, n), np.int64)
    out[0] = raw[0]
    out[1] = limit_req[:n]
    out[2] = join_i32_pair(raw[2], raw[3])
    out[3] = join_i32_pair(raw[4], raw[5])
    out[4] = raw[1]
    return out


def group_upad(b: int, u: int = 0) -> int:
    """The grouped plan's quantized head width for a batch width ``b``:
    hard floor at max(256, b/4) so serving traffic compiles a handful of
    (Upad, B) merged/expansion programs, not one per traffic shape."""
    return pad_pow2(max(u, 256, b // 4))


def _param_rows_equal_prev(m: np.ndarray, nl: int) -> np.ndarray:
    """(nl,) bool: row i carries identical request parameters to row
    i-1 (the 17 REQ32 parameter rows both duplicate planners fold on —
    ONE definition so the grouped and layered plans can never disagree
    on unit boundaries)."""
    R = REQ32_INDEX
    rows = (
        R["algorithm"], R["behavior"],
        R["hits"], R["hits"] + 1,
        R["limit"], R["limit"] + 1,
        R["duration"], R["duration"] + 1,
        R["created_at"], R["created_at"] + 1,
        R["burst"], R["burst"] + 1,
        R["greg_exp"], R["greg_exp"] + 1,
        R["greg_dur"], R["greg_dur"] + 1,
    )
    eq = np.ones(nl, bool)
    for r in rows:
        eq[1:] &= m[r, 1:nl] == m[r, : nl - 1]
    return eq


def build_group_plan(m: np.ndarray, n: int, capacity: int, now: int,
                     min_dup_frac: float = 1 / 8):
    """Host-side grouped-tick plan for a slot-sorted compact batch (the
    BASELINE north star's hot-key scatter-add): duplicate groups collapse
    to one device row each when every follower is identical to its head,
    known, hits > 0, free of RESET_REMAINING / Gregorian behaviors, and
    under a head that provably comes out alive — the same eligibility the
    device-side fold uses (:func:`_apply_merged_followers` ``ok``).
    Returns ``(mhead (19, Upad), count (Upad,), uidx (B,), rank (B,),
    u)`` — ``u`` the live head count — or None when any group is
    ineligible (those batches keep the sequential rank-round program,
    whose per-unit rounds handle mixed groups) or when fewer than
    ``min_dup_frac`` of the live rows are followers: a near-unique batch
    saves almost no device rows while the grouped path's (U, 24) head
    block costs ~4x the compact response's D2H bytes, so shallow
    duplication stays on the sequential program.  The savings check runs
    before the O(n·rows) eligibility sweep and the plan allocations.

    ``uidx``/``rank`` address the expansion program
    (transition32.expand32_rows): member i's response derives from head column
    ``uidx[i]`` at rank ``rank[i]``.  Error lanes keep their real group
    head (they share its slot run) and lanes past ``n`` point at column
    ``upad - 1`` — which aliases the last real head when ``u == upad``.
    Both are harmless: their response values are unspecified and are
    sliced/masked downstream exactly like the plain tick's padding
    lanes."""
    R = REQ32_INDEX
    b = m.shape[1]
    s = m[R["slot"], :n]
    live = s < capacity
    if n == 0 or not live.any():
        return None
    is_start = np.empty(n, bool)
    is_start[0] = True
    np.not_equal(s[1:], s[:-1], out=is_start[1:])
    # Row savings count LIVE followers only: error/padding lanes share
    # slot == capacity and would otherwise masquerade as one huge
    # "duplicate group".
    dup_rows = int(np.count_nonzero(~is_start & live))
    if dup_rows < max(1, int(min_dup_frac * int(np.count_nonzero(live)))):
        return None
    starts = np.flatnonzero(is_start)
    gid = np.cumsum(is_start) - 1
    rank = np.arange(n, dtype=np.int32) - starts[gid].astype(np.int32)

    eq_prev = _param_rows_equal_prev(m, n)
    hits_pos = join_i32_pair(m[R["hits"], :n], m[R["hits"] + 1, :n]) > 0
    known = m[R["known"], :n] != 0
    no_merge = int(Behavior.RESET_REMAINING | Behavior.DURATION_IS_GREGORIAN)
    beh_ok = (m[R["behavior"], :n] & no_merge) == 0
    # The fold requires the head row to come out ALIVE (post-transition
    # expire_at >= now) — a dead head sends the x64 path's followers to
    # fresh-install rank rounds, which the closed form cannot express.
    # duration > 0 plus created_at >= now guarantees it for every
    # reachable head branch (new: expire = created+duration > now;
    # exists: expire_cand > created >= now); groups that fail (negative
    # durations, client-backdated duplicates) keep the sequential
    # program.
    dur = join_i32_pair(m[R["duration"], :n], m[R["duration"] + 1, :n])
    created = join_i32_pair(
        m[R["created_at"], :n], m[R["created_at"] + 1, :n])
    alive_ok = (dur > 0) & (created >= now)
    # Closed-form folds exist only for token/leaky; zoo duplicates
    # (algorithm >= 2) keep the sequential program's size-1 units.
    alg_ok = m[R["algorithm"], :n] <= int(Algorithm.LEAKY_BUCKET)
    follower = ~is_start & live
    if np.any(follower
              & ~(eq_prev & known & hits_pos & beh_ok & alive_ok & alg_ok)):
        return None

    u = len(starts)
    upad = group_upad(b, u)
    mhead = np.empty((REQ32_ROWS, upad), np.int32)
    mhead[:, :u] = m[:, starts]
    mhead[:, u:] = 0
    mhead[R["slot"], u:] = capacity  # padding heads aim at the guard row
    count = np.ones(upad, np.int32)
    sizes = np.diff(np.append(starts, n)).astype(np.int32)
    count[:u] = sizes
    uidx = np.full(b, upad - 1, np.int32)
    uidx[:n] = gid
    rank_b = np.zeros(b, np.int32)
    rank_b[:n] = rank
    return mhead, count, uidx, rank_b, u


def build_layer_plan(m: np.ndarray, n: int, capacity: int, now: int,
                     layer_width: int = 512, max_layers: int = 32,
                     min_dup_frac: float = 1 / 8):
    """Host-side UNIT-LAYER plan for mixed/ineligible duplicate batches —
    the general case :func:`build_group_plan` declines (groups broken by
    RESET/parameter-change/query rows).

    A *unit* is a maximal run of identical fold-eligible duplicates
    (the same definition the sequential program uses,
    :func:`_sorted_merge_plan`); layer ``k`` collects the k-th unit of
    every slot segment.  Each layer then ticks through the NARROW merged
    program (one head row + count per unit, closed-form fold), chained
    through the table — layer k+1's gather sees layer k's scatter — and
    a single elementwise expansion maps every member's response from its
    unit's journal row.  Cost: K narrow ticks instead of one full
    gather/scatter round per unit, where K = max units per segment.

    Returns ``(mh0 (19, W0), cnt0 (W0,), mhk (K-1, 19, LW), cntk
    (K-1, LW), uidx (B,), rank (B,), k_pad)`` or None when the batch is
    ineligible: a count>1 unit whose head is not provably alive
    (build_group_plan's alive_ok argument), more than ``max_layers``
    units on one segment, a non-first layer wider than ``layer_width``
    (adversarial shapes keep the sequential program, which is always
    correct), or fewer than ``min_dup_frac`` of the live rows being
    duplicates — a near-unique batch gains nothing here, and sending it
    through would compile wide (w0 ≈ B) layered shapes that warmup
    never prepared (the sequential program those batches keep IS
    warmed).  ``uidx`` addresses the flattened journal (layer-0 block
    first, then the K-1 narrow blocks); padding/error lanes are left at
    position 0 — a real unit's journal row — and their response values
    are unspecified, masked/sliced downstream exactly like the plain
    tick's padding lanes."""
    R = REQ32_INDEX
    b = m.shape[1]
    s = m[R["slot"], :n]
    live = s < capacity
    nl = int(np.count_nonzero(live))
    if nl == 0:
        return None
    # Error rows carry slot == capacity and sort to the tail: the live
    # prefix is contiguous.
    s = s[:nl]
    is_start = np.empty(nl, bool)
    is_start[0] = True
    np.not_equal(s[1:], s[:-1], out=is_start[1:])
    dup_rows = int(np.count_nonzero(~is_start))
    if dup_rows < max(1, int(min_dup_frac * nl)):
        return None

    eq_prev = _param_rows_equal_prev(m, nl)
    NO_MERGE = int(Behavior.RESET_REMAINING | Behavior.DURATION_IS_GREGORIAN)
    hits_pos = join_i32_pair(m[R["hits"], :nl], m[R["hits"] + 1, :nl]) > 0
    ok = (
        (is_start | eq_prev)
        & hits_pos
        & ((m[R["behavior"], :nl] & NO_MERGE) == 0)
        & ((m[R["known"], :nl] != 0) | is_start)
        # zoo lanes have no closed-form fold: size-1 units only
        & (m[R["algorithm"], :nl] <= int(Algorithm.LEAKY_BUCKET))
    )
    unit_start = is_start | ~ok
    heads = np.flatnonzero(unit_start)
    u = len(heads)
    sizes = np.diff(np.append(heads, nl)).astype(np.int32)

    # Unit ordinal within its segment.
    seg_of_unit = (np.cumsum(is_start) - 1)[heads]
    first_unit_of_seg = np.full(seg_of_unit[-1] + 1, u, np.int64)
    unit_idx = np.arange(u)
    np.minimum.at(first_unit_of_seg, seg_of_unit, unit_idx)
    ord_ = (unit_idx - first_unit_of_seg[seg_of_unit]).astype(np.int64)
    k_layers = int(ord_.max()) + 1
    if k_layers > max_layers:
        return None
    if k_layers > 1:
        wide = np.bincount(ord_[ord_ >= 1])
        if len(wide) and wide.max() > layer_width:
            return None
    # Fold-eligible heads (count>1) must come out alive: duration > 0
    # plus created_at >= now guarantees it on every reachable branch
    # (see build_group_plan's alive_ok derivation).
    multi = sizes > 1
    if multi.any():
        hr = heads[multi]
        dur = join_i32_pair(m[R["duration"], :nl][hr],
                            m[R["duration"] + 1, :nl][hr])
        created = join_i32_pair(m[R["created_at"], :nl][hr],
                                m[R["created_at"] + 1, :nl][hr])
        if not ((dur > 0) & (created >= now)).all():
            return None

    w0_n = int(np.count_nonzero(ord_ == 0))
    w0 = group_upad(b, w0_n)
    # Quantize the layer count so serving traffic compiles a handful of
    # shapes, padding with all-padding layers (slot=capacity heads).
    # Multiples of 4 past 4 (not pow2): each padding layer costs a real
    # narrow tick, and pow2 rounding at k=17 would run 15 dead layers.
    if k_layers <= 2:
        k_pad = 2
    elif k_layers <= 4:
        k_pad = 4
    else:
        k_pad = -(-k_layers // 4) * 4
    k_pad = min(k_pad, max_layers)

    def head_block(unit_sel, width):
        mh = np.zeros((REQ32_ROWS, width), np.int32)
        mh[R["slot"]] = capacity
        cnt = np.ones(width, np.int32)
        k = len(unit_sel)
        mh[:, :k] = m[:, :nl][:, heads[unit_sel]]
        cnt[:k] = sizes[unit_sel]
        return mh, cnt

    # Per-unit flat journal position, layer-0 block first.
    pos_of_unit = np.empty(u, np.int64)
    lay0 = np.flatnonzero(ord_ == 0)
    pos_of_unit[lay0] = np.arange(len(lay0))
    mh0, cnt0 = head_block(lay0, w0)
    mhk = np.zeros((k_pad - 1, REQ32_ROWS, layer_width), np.int32)
    mhk[:, R["slot"], :] = capacity
    cntk = np.ones((k_pad - 1, layer_width), np.int32)
    for k in range(1, k_layers):
        sel = np.flatnonzero(ord_ == k)
        pos_of_unit[sel] = w0 + (k - 1) * layer_width + np.arange(len(sel))
        mhk[k - 1], cntk[k - 1] = head_block(sel, layer_width)

    gid_unit = np.cumsum(unit_start) - 1        # row → unit
    uidx = np.zeros(b, np.int64)
    uidx[:nl] = pos_of_unit[gid_unit]
    rank = np.zeros(b, np.int32)
    rank[:nl] = np.arange(nl, dtype=np.int32) - heads[gid_unit].astype(np.int32)
    return (mh0, cnt0, mhk, cntk, uidx.astype(np.int32), rank,
            k_pad)


def masked_over_limit(resp_mat: np.ndarray, errors) -> int:
    """Over-limit count from a public (5, n) response matrix with the
    per-item-error lanes zeroed first — their values are unspecified in
    the device response (on the row layout they gather guard-row
    garbage; see unpack_resp_compact)."""
    over = resp_mat[4]
    if errors:
        over = over.copy()
        over[list(errors)] = 0
    return int(over.sum())


def _apply_merged_followers(
    new_g: BucketState,
    resp: RespBatch,
    reqs: ReqBatch,
    now: jnp.ndarray,
    rank: jnp.ndarray,
    group_size: jnp.ndarray,
    head_idx: jnp.ndarray,
    seg_id: jnp.ndarray,
):
    """Closed-form application of duplicate-key followers (token + leaky).

    Runs against ``new_g`` — the per-request rows of the heads' round-0
    transition output (``new_g[head_idx]`` is each request's post-head slot
    state), so the whole merge needs no table gather and no second scatter:
    the head's scatter row carries the group-final values.  For a slot
    group whose members are *identical* requests (hits>0, no
    RESET_REMAINING/Gregorian), the sequential fold the rank rounds would
    perform has a closed form in the member's rank ``i`` against the
    post-head state.  Let ``base`` be the post-head integer remaining —
    ``remaining`` for token buckets, ``trunc(remaining_f)`` for leaky
    (algorithms.go:383-387 works on the truncated value) — and
    ``q = base // h``:

        i <= q  → UNDER, remaining base - i·h
                  (token echoes stored status S0, leaky reports UNDER)
        i >  q  → OVER_LIMIT, remaining = drain ? 0 : base - q·h
                  (divisible base makes base - q·h == 0, unifying the
                  exact-remainder → at-zero and over-ask cases)

    matching algorithms.go:157-198 (token) and :389-430 (leaky) exactly:
    the ``i <= q`` steps are the dec/exact branches, ``i > q`` is over-ask
    until remaining hits zero and the already-at-zero branch afterwards.
    Leaky followers never drip: the head either advanced ``updated_at`` to
    ``created_at`` (follower elapsed = 0) or left it where a same-instant
    drip already truncated to zero tokens (algorithms.go:361-367), so the
    follower's drip is zero too.

    Stored token status only flips to OVER on an at-zero step
    (algorithms.go:162-169), first at rank ``q+1`` when h divides base, at
    ``q+2`` under DRAIN_OVER_LIMIT, never otherwise; leaky has no persisted
    status.  Leaky ``remaining_f`` keeps its fractional part through
    integer decrements but is *exactly zeroed* by an exact-remainder step
    (:392-397) or a drain step (:414-417).  The group-final state is
    evaluated at the last member's rank (``group_size - 1``) and written
    into the HEAD's scatter row; expire/created/duration are untouched
    (token hits never renew; leaky followers re-bump the same expiration
    the head wrote; a uniform group can't change limit or duration after
    its head).

    Returns ``(rows, resp, merged)``: the head rows of ``new_g`` with the
    group-final remaining/status/remaining_f folded in, per-request
    responses, and the follower rows handled here (excluded from the rank
    rounds).
    """
    b = reqs.slot.shape[0]
    NO_MERGE = jnp.int32(
        Behavior.RESET_REMAINING | Behavior.DURATION_IS_GREGORIAN
    )

    def hd(a):
        return a[head_idx]

    same_as_head = (
        (reqs.hits == hd(reqs.hits))
        & (reqs.limit == hd(reqs.limit))
        & (reqs.duration == hd(reqs.duration))
        & (reqs.behavior == hd(reqs.behavior))
        & (reqs.created_at == hd(reqs.created_at))
        & (reqs.burst == hd(reqs.burst))
        & (reqs.algorithm == hd(reqs.algorithm))
    )
    # Followers must take the exists path (known & in_use & now<=expire);
    # heads are exempt from the known check (their round-0 transition
    # handles the new-item case and leaves in_use set).
    ok = (
        reqs.valid
        & same_as_head
        & (reqs.hits > 0)
        & ((reqs.behavior & NO_MERGE) == 0)
        & (reqs.known | (rank == 0))
        # zoo lanes (algorithm >= 2) have no closed-form fold
        & (reqs.algorithm <= jnp.int32(Algorithm.LEAKY_BUCKET))
    )
    # A group merges only if every valid member is mergeable: one bad row
    # (different hits/limit/..., RESET, query) sends the whole group to the
    # rank rounds so cross-member interactions stay sequential.
    bad_per_seg = jnp.zeros(b, jnp.int32).at[seg_id].add(
        (reqs.valid & ~ok).astype(jnp.int32)
    )
    group_ok = bad_per_seg[seg_id] == 0

    # Post-head state of the group's slot, read straight from the heads'
    # transition output (identical to a table gather after the head
    # scatter, minus the gather).
    return _merged_formulas(
        new_g, resp, reqs, now, rank, group_size - 1,
        fold_mask=group_ok & ok & (rank > 0),
        head_mask=group_ok & ok & (rank == 0) & (group_size > 1),
        R0=hd(new_g.remaining), F0=hd(new_g.remaining_f),
        S0=hd(new_g.status), E=hd(new_g.expire_at),
    )


def _merged_formulas(new_g, resp, reqs, now, rank, last_rank, fold_mask,
                     head_mask, R0, F0, S0, E):
    """The closed-form follower fold shared by the gather-based (unsorted)
    group merge and the scan-based (sorted-input) unit merge; see
    :func:`_apply_merged_followers` for the math.  ``R0/F0/S0/E`` are the
    fold head's post-transition remaining/remaining_f/status/expire_at
    broadcast to every member; ``rank`` is the member's distance from
    that head, ``last_rank`` the distance of the fold window's last
    member.  ``fold_mask``/``head_mask`` select the members folding /
    the heads absorbing a window (both are further gated on the head
    state being alive here)."""
    TOKEN = jnp.int32(Algorithm.TOKEN_BUCKET)
    UNDER = jnp.int32(Status.UNDER_LIMIT)
    OVER = jnp.int32(Status.OVER_LIMIT)
    is_tok = reqs.algorithm == TOKEN
    N0 = F0.astype(jnp.int64)  # Go float64→int64 truncation
    alive = now <= E

    merged = fold_mask & alive

    h = jnp.where(reqs.hits > 0, reqs.hits, jnp.int64(1))  # div-safe
    i = rank.astype(jnp.int64)
    base = jnp.where(is_tok, R0, N0)
    q = base // h
    drain = (reqs.behavior & Behavior.DRAIN_OVER_LIMIT) != 0
    under = i <= q
    rem_over = jnp.where(drain, jnp.int64(0), base - q * h)
    rem_resp = jnp.where(under, base - i * h, rem_over)
    # Leaky reset_time tracks the would-be post-step remaining: the over-ask
    # branch reports it from the *pre*-step value, the at-zero rows that
    # follow a drain report zero (algorithms.go:400-430).
    safe_limit = jnp.where(reqs.limit == 0, jnp.int64(1), reqs.limit)
    rate_i = (reqs.duration.astype(jnp.float64) / safe_limit.astype(jnp.float64)).astype(jnp.int64)
    reset_rem = jnp.where(
        under, rem_resp, jnp.where(drain & (i > q + 1), jnp.int64(0), base - q * h)
    )
    leaky_reset = reqs.created_at + (reqs.limit - reset_rem) * rate_i
    resp = RespBatch(
        status=jnp.where(
            merged,
            jnp.where(under, jnp.where(is_tok, S0, UNDER), OVER),
            resp.status,
        ),
        limit=jnp.where(merged, reqs.limit, resp.limit),
        remaining=jnp.where(merged, rem_resp, resp.remaining),
        reset_time=jnp.where(
            merged, jnp.where(is_tok, E, leaky_reset), resp.reset_time
        ),
        over_limit=jnp.where(merged, ~under, resp.over_limit),
    )

    # Window-final state, evaluated at the LAST member's rank and folded
    # into the head's scatter row (one scatter for head + whole window).
    li = last_rank.astype(jnp.int64)
    l_under = li <= q
    rem_last = jnp.where(l_under, base - li * h, rem_over)
    divisible = base - q * h == 0
    # Token: stored status flips OVER once an at-zero step occurred.
    at_zero_last = jnp.where(divisible, li > q, drain & (li > q + 1))
    status_last = jnp.where(at_zero_last, OVER, S0)
    # Leaky: the float remaining keeps its fraction through decrements but
    # collapses to exactly 0.0 after an exact-remainder step (q ≥ 1,
    # divisible, reached) or a drain step (base > 0, passed rank q).
    zero_f = ((q >= 1) & divisible & (li >= q)) | ((base > 0) & drain & (li > q))
    remf_last = jnp.where(
        zero_f,
        jnp.float64(0.0),
        F0 - (jnp.minimum(li, q) * h).astype(jnp.float64),
    )
    head_ovr = head_mask & alive
    rows = new_g._replace(
        remaining=jnp.where(head_ovr & is_tok, rem_last, new_g.remaining),
        status=jnp.where(head_ovr & is_tok, status_last, new_g.status),
        remaining_f=jnp.where(
            head_ovr & ~is_tok, remf_last, new_g.remaining_f
        ),
    )
    return rows, resp, merged


def _seg_propagate(is_start, vals):
    """Broadcast each segment head's values to every member (segmented
    inclusive scan; the classic (flag, value) combine — associative)."""
    def combine(a, b):
        fa, va = a[0], a[1:]
        fb, vb = b[0], b[1:]
        return (fa | fb,) + tuple(
            jnp.where(fb, y, x) for x, y in zip(va, vb)
        )

    out = lax.associative_scan(combine, (is_start,) + tuple(vals))
    return out[1:]


def _seg_min_all(is_start, val):
    """Per-row minimum of ``val`` over the row's whole segment, without
    scatters: a forward segmented min covers [start..i], a backward one
    covers [i..end]."""
    def combine(a, b):
        fa, va = a
        fb, vb = b
        return fa | fb, jnp.where(fb, vb, jnp.minimum(va, vb))

    fwd = lax.associative_scan(combine, (is_start, val))[1]
    last = jnp.concatenate([is_start[1:], jnp.ones((1,), jnp.bool_)])
    bwd = lax.associative_scan(
        combine, (last[::-1], val[::-1])
    )[1][::-1]
    return jnp.minimum(fwd, bwd)


def _seg_max_all(is_start, val):
    """Per-row maximum of ``val`` over the row's whole segment (mirror of
    :func:`_seg_min_all`)."""
    def combine(a, b):
        fa, va = a
        fb, vb = b
        return fa | fb, jnp.where(fb, vb, jnp.maximum(va, vb))

    fwd = lax.associative_scan(combine, (is_start, val))[1]
    last = jnp.concatenate([is_start[1:], jnp.ones((1,), jnp.bool_)])
    bwd = lax.associative_scan(
        combine, (last[::-1], val[::-1])
    )[1][::-1]
    return jnp.maximum(fwd, bwd)


def _sorted_merge_plan(reqs: ReqBatch, is_start: jnp.ndarray):
    """Static fold structure for a slot-sorted batch: the ``ok``
    fold-eligibility predicate and the end index of each row's *unit*
    (maximal contiguous run of identical fold-eligible requests).

    Units are the granularity of the sorted tick's rounds: a uniform
    duplicate group is one unit (one round — the thundering-herd fast
    path), and a group broken by RESET/Gregorian/query/parameter-change
    rows costs one round per unit, NOT one per duplicate (round-3's 6.5 s
    adversarial corner: a ~700-deep hot key interleaved with RESET rows
    degenerated to ~700 gather+scatter rounds)."""
    NO_MERGE = jnp.int32(
        Behavior.RESET_REMAINING | Behavior.DURATION_IS_GREGORIAN
    )
    b = reqs.slot.shape[0]
    idx = jnp.arange(b, dtype=jnp.int32)

    def eq_prev(a):
        return jnp.concatenate(
            [jnp.ones((1,), jnp.bool_), a[1:] == a[:-1]]
        )

    # "Equals its predecessor" chains to "equals its head" within a
    # contiguous run, so run membership is a neighbor compare.
    same_as_prev = is_start | (
        eq_prev(reqs.hits)
        & eq_prev(reqs.limit)
        & eq_prev(reqs.duration)
        & eq_prev(reqs.behavior)
        & eq_prev(reqs.created_at)
        & eq_prev(reqs.burst)
        & eq_prev(reqs.algorithm)
    )
    ok = (
        reqs.valid
        & same_as_prev
        & (reqs.hits > 0)
        & ((reqs.behavior & NO_MERGE) == 0)
        # group heads are exempt from the known check (their transition
        # handles the new-item case); group-rank==0 IS is_start
        & (reqs.known | is_start)
        # zoo lanes (algorithm >= 2) have no closed-form fold
        & (reqs.algorithm <= jnp.int32(Algorithm.LEAKY_BUCKET))
    )
    unit_start = is_start | ~ok
    nxt = jnp.where(unit_start, idx, jnp.int32(b))
    sfx = lax.associative_scan(jnp.minimum, nxt[::-1])[::-1]
    unit_end = jnp.concatenate([sfx[1:], jnp.full((1,), b, jnp.int32)])
    return ok, unit_end


def make_tick_fn(capacity: int, merge_uniform: bool = True,
                 layout: str = "columns", sorted_input: bool = False,
                 compact_resp: bool = False, compact_req: bool = False,
                 unit_unroll: int = 8):
    """Build the jittable tick: (state, reqs, now) → (state, responses).

    Pure function of its inputs (no clocks, no host state) so the driver can
    compile-check it and shard it.

    **Thundering-herd fast path** (``merge_uniform``): a batch full of
    duplicates of one hot key is the reference's headline scenario
    (docs/architecture.md, benchmark_test.go:122-147).  Naive rank rounds
    cost one full gather+scatter per duplicate.  When every request in a
    slot group is *identical* (same hits/limit/duration/algorithm/behavior/
    created_at/burst, hits>0, token or leaky bucket, no RESET/Gregorian)
    the sequential fold over the group has a closed form in the member's
    rank: the group head runs the normal transition (handling new-item/
    renewal/limit-delta/drip), every follower's response is prefix
    arithmetic on the head's post-state, and only the last member scatters
    the final state.  Duplicate cost collapses from O(dups) rounds to O(1);
    mixed groups fall back to rank rounds bounded by the *non-merged* ranks
    only.
    """

    _, _gather, _scatter = _layout_ops(layout)

    def tick_sorted(state, reqs: ReqBatch, now: jnp.ndarray, resp0):
        """Sorted-input tick: unit rounds.

        Contract: the host packed the batch sorted by slot with
        invalid/padding rows (slot=capacity) at the end, so every slot
        group is a contiguous run and all segment math is neighbor
        compares + scans — no device sort, no B-sized gathers/scatters
        anywhere in the merge path.

        Each round applies, per slot, the FIRST not-yet-applied request
        as that slot's head (full transition) and closed-form-folds the
        rest of the head's *unit* — its maximal run of identical
        fold-eligible duplicates (:func:`_sorted_merge_plan`) — so a
        uniform duplicate group costs one round (the thundering-herd
        fast path) and a group interleaved with RESET/query/Gregorian or
        parameter-change rows costs one round per unit, never one per
        duplicate.  Heads whose post-state is already expired fold
        nothing; their followers simply head later rounds, preserving
        exact per-slot sequencing (reference workers.go:19-37 serializes
        per key; algorithms.go is the per-request bar)."""
        b = reqs.slot.shape[0]
        sorted_key = jnp.where(
            reqs.valid, reqs.slot, capacity
        ).astype(jnp.int32)
        is_start = jnp.concatenate(
            [jnp.ones((1,), jnp.bool_), sorted_key[1:] != sorted_key[:-1]]
        )
        has_dups = jnp.any((~is_start[1:]) & reqs.valid[1:])

        def unique_branch(_):
            gathered = _gather(state, reqs.slot)
            new_g, r_out = bucket_transition(now, gathered, reqs)
            resp = jax.tree.map(
                lambda old, new: jnp.where(reqs.valid, new, old),
                resp0, r_out,
            )
            scat = jnp.where(reqs.valid, reqs.slot, capacity)
            return _scatter(state, scat, new_g), resp

        def dup_branch(_):
            idx = jnp.arange(b, dtype=jnp.int32)
            ok, unit_end = _sorted_merge_plan(reqs, is_start)

            def cond(carry):
                return ~jnp.all(carry[0])

            def sub_step(applied, g, resp, last_head):
                """Apply, per slot, the first unapplied unit (head
                transition + closed-form fold) entirely in registers:
                ``g`` holds each row's view of its slot's CURRENT state,
                updated by forward propagation — no gather or scatter per
                unit (those happen once per round, in ``body``)."""
                cand = ~applied
                headpos = _seg_min_all(
                    is_start, jnp.where(cand, idx, jnp.int32(b))
                )
                head = cand & (idx == headpos)
                new_g, r_out = bucket_transition(now, g, reqs)
                resp = jax.tree.map(
                    lambda old, new: jnp.where(head, new, old), resp, r_out
                )
                # Broadcast the head's post-transition values (and its
                # position / unit end) forward over its group; rows
                # before the head are already applied and masked out.
                R0, F0, S0, E, hpos, uend = _seg_propagate(
                    is_start | head,
                    (new_g.remaining, new_g.remaining_f, new_g.status,
                     new_g.expire_at, idx, unit_end),
                )
                fold_rank = idx - hpos
                fold = cand & ok & (fold_rank > 0) & (idx < uend)
                rows, resp, merged = _merged_formulas(
                    new_g, resp, reqs, now, fold_rank, uend - 1 - hpos,
                    fold_mask=fold,
                    head_mask=head & (uend - hpos > 1),
                    R0=R0, F0=F0, S0=S0, E=E,
                )
                # Chain units in-register: broadcast the head's
                # unit-final row state forward over its segment so the
                # next sub-step's head (the following unit of the same
                # slot) transitions from post-unit state.  The
                # propagated ``head`` flag distinguishes spans whose
                # nearest boundary is a live head from spans headed by a
                # stale segment start (those keep their state).
                prop = _seg_propagate(is_start | head, (head,) + tuple(rows))
                from_head = prop[0]
                g = jax.tree.map(
                    lambda cur, pv: jnp.where(from_head, pv, cur),
                    g, type(rows)(*prop[1:]),
                )
                applied = applied | head | merged
                last_head = jnp.where(head, idx, last_head)
                return applied, g, resp, last_head

            def body(carry):
                applied, st, resp = carry
                g = _gather(st, reqs.slot)
                sc = (applied, g, resp, jnp.full(b, -1, jnp.int32))
                # unit_unroll units per slot per ROUND: one gather and
                # one scatter amortize over up to that many sequential
                # units (parameter-change/RESET-broken groups cost
                # ceil(units / unit_unroll) rounds, not one round per
                # unit).  A fori_loop (not a Python unroll) keeps the
                # compiled graph one sub_step big, and its cond skips
                # finished sub-steps so a batch whose units are
                # exhausted early (the uniform-herd one-unit case) pays
                # for one.
                sc = lax.fori_loop(
                    0, max(1, unit_unroll),
                    lambda _k, c: lax.cond(
                        jnp.all(c[0]), lambda cc: cc,
                        lambda cc: sub_step(*cc), c,
                    ),
                    sc,
                )
                applied, g, resp, last_head = sc
                # One scatter per slot, from its LAST applied head this
                # round — that row's ``g`` carries the slot's final
                # chained state (heads are boundary rows of the final
                # propagation, so their own values survive in ``g``).
                seg_last = _seg_max_all(is_start, last_head)
                scat_src = (last_head >= 0) & (last_head == seg_last)
                scat = jnp.where(scat_src, reqs.slot, capacity)
                st = _scatter(st, scat, g)
                return applied, st, resp

            _, st, resp = lax.while_loop(
                cond, body, (~reqs.valid, state, resp0)
            )
            return st, resp

        return lax.cond(has_dups, dup_branch, unique_branch, None)

    def tick(state, reqs: ReqBatch, now: jnp.ndarray):
        b = reqs.slot.shape[0]

        resp0 = RespBatch(
            status=jnp.zeros(b, jnp.int32),
            limit=jnp.zeros(b, jnp.int64),
            remaining=jnp.zeros(b, jnp.int64),
            reset_time=jnp.zeros(b, jnp.int64),
            over_limit=jnp.zeros(b, jnp.bool_),
        )

        if merge_uniform and sorted_input:
            return tick_sorted(state, reqs, now, resp0)

        def round_step(st, resp, active):
            gathered = _gather(st, reqs.slot)
            new_g, r_out = bucket_transition(now, gathered, reqs)
            # Scatter only this round's rows; inactive rows aim out of
            # bounds and are dropped (guard row for the row layout).
            scat = jnp.where(active, reqs.slot, capacity)
            st = _scatter(st, scat, new_g)
            resp = jax.tree.map(
                lambda old, new: jnp.where(active, new, old), resp, r_out
            )
            return st, resp

        # Round 0: every group head takes the full transition (new item,
        # renewal, limit delta, RESET — all head-only concerns).  With the
        # merge fast path the heads' scatter rows already carry the whole
        # group's final state, so head + followers cost ONE scatter.
        gathered = _gather(state, reqs.slot)
        new_g, r_out = bucket_transition(now, gathered, reqs)

        if merge_uniform:
            # The duplicate-group machinery costs ~2x the rest of a tick
            # — and an all-unique batch needs none of it.  Detect
            # duplicates once, then lax.cond so unique batches skip
            # straight to "every row is its own head".
            def unique_branch(_):
                resp = jax.tree.map(
                    lambda old, new: jnp.where(reqs.valid, new, old),
                    resp0, r_out,
                )
                return new_g, resp, reqs.valid, jnp.zeros(b, jnp.int32)

            sort_key = jnp.where(
                reqs.valid, reqs.slot, capacity
            ).astype(jnp.int32)
            order = jnp.argsort(sort_key, stable=True)
            sorted_key = sort_key[order]
            has_dups = jnp.any(
                (sorted_key[1:] == sorted_key[:-1])
                & (sorted_key[1:] < jnp.int32(capacity))
            )

            def dup_branch(_):
                rank, group_size, head_idx, seg_id = (
                    _segments_from_sorted(sorted_key, order)
                )
                heads = reqs.valid & (rank == 0)
                resp = jax.tree.map(
                    lambda old, new: jnp.where(heads, new, old),
                    resp0, r_out,
                )
                rows, resp, merged = _apply_merged_followers(
                    new_g, resp, reqs, now,
                    rank, group_size, head_idx, seg_id,
                )
                return rows, resp, merged, rank

            rows, resp, merged, rank = lax.cond(
                has_dups, dup_branch, unique_branch, None
            )
        else:
            rank = _rank_within_slot(reqs.slot, reqs.valid, capacity)
            heads0 = reqs.valid & (rank == 0)
            resp = jax.tree.map(
                lambda old, new: jnp.where(heads0, new, old), resp0, r_out
            )
            rows = new_g
            merged = jnp.zeros(b, jnp.bool_)

        heads = reqs.valid & (rank == 0)
        scat = jnp.where(heads, reqs.slot, capacity)
        state = _scatter(state, scat, rows)

        # Rank rounds for whatever didn't merge (mixed-parameter groups,
        # RESET/Gregorian flows, queries): round k applies at most one
        # request per slot.
        pending = reqs.valid & ~merged
        n_rounds = jnp.max(jnp.where(pending, rank, 0)) + 1

        def cond(carry):
            k, _, _ = carry
            return k < n_rounds

        def body(carry):
            k, st, resp = carry
            st, resp = round_step(st, resp, pending & (rank == k))
            return k + 1, st, resp

        _, state, resp = lax.while_loop(cond, body, (jnp.int32(1), state, resp))
        return state, resp

    def tick_packed(state, packed: jnp.ndarray, now: jnp.ndarray):
        reqs = (
            unpack_reqs_compact(packed)
            if compact_req
            else unpack_reqs(packed)
        )
        state, resp = tick(state, reqs, now)
        return state, (
            pack_resp_compact(resp) if compact_resp else pack_resp(resp)
        )

    tick_packed.unpacked = tick
    return tick_packed


def make_install_fn(layout: str = "columns"):
    """Jitted scatter installing owner-pushed GLOBAL state into the table.

    Mirrors the reference's ``UpdatePeerGlobals`` install
    (gubernator.go:425-459): ExpireAt comes from the pushed ``reset_time``;
    token buckets install {status, limit, duration, remaining,
    created_at=now}; leaky buckets install {remaining_f, limit, duration,
    burst=limit, updated_at=now}.  ``cols`` rows: slot, algorithm, limit,
    remaining, status, duration, reset_time, valid.
    """

    _, _gather, _scatter = _layout_ops(layout)

    def install(state, cols: jnp.ndarray, now: jnp.ndarray):
        slot, algo, limit, remaining, status, duration, reset_time, valid = cols
        # Every integer-count algorithm (token bucket and the whole zoo)
        # installs remaining into the int column; only leaky buckets route
        # it through remaining_f.  A pushed zoo bucket restarts its
        # window/TAT locally (tat/prev_count zero) — the counter value is
        # the authoritative part of an owner push, the phase is not.
        is_leaky = algo == jnp.int64(int(Algorithm.LEAKY_BUCKET))
        # Invalid rows aim one past the table and drop.  The sentinel must
        # stay < 2^31: GSPMD partitions the scatter with int32 index math,
        # and a 2^40 sentinel truncates to slot 0 on a sharded table.
        scat = jnp.where(valid != 0, slot, jnp.int64(state.capacity))

        zero = jnp.zeros_like(limit)
        rows = BucketState(
            algorithm=algo.astype(jnp.int32),
            limit=limit,
            remaining=jnp.where(is_leaky, jnp.int64(0), remaining),
            remaining_f=jnp.where(
                is_leaky, remaining.astype(jnp.float64), jnp.float64(0.0)
            ),
            duration=duration,
            created_at=jnp.where(is_leaky, jnp.int64(0), now),
            updated_at=jnp.where(is_leaky, now, jnp.int64(0)),
            burst=jnp.where(is_leaky, limit, jnp.int64(0)),
            status=status.astype(jnp.int32),
            expire_at=reset_time,
            in_use=valid != 0,
            tat=zero,
            prev_count=zero,
        )
        return _scatter(state, scat, rows)

    return install


# Field order for full-state restore/readback matrices (Store hooks).
ITEM_INT_ROWS = (
    "slot", "algorithm", "limit", "remaining", "duration", "created_at",
    "updated_at", "burst", "status", "expire_at", "tat", "prev_count",
    "valid",
)


def make_restore_fn(layout: str = "columns"):
    """Jitted scatter installing *full* item state — the read-through path
    (Store.Get on cache miss, reference algorithms.go:45-51) and the
    Loader.Load restore.  ``ints`` is (13, B) int64 per ITEM_INT_ROWS;
    ``floats`` is (B,) float64 (leaky ``remaining_f``)."""

    _, _gather, _scatter = _layout_ops(layout)

    def restore(state, ints: jnp.ndarray, floats: jnp.ndarray):
        f = dict(zip(ITEM_INT_ROWS, ints))
        # Sentinel must stay < 2^31 (see make_install_fn).
        scat = jnp.where(f["valid"] != 0, f["slot"], jnp.int64(state.capacity))

        rows = BucketState(
            algorithm=f["algorithm"].astype(jnp.int32),
            limit=f["limit"],
            remaining=f["remaining"],
            remaining_f=floats,
            duration=f["duration"],
            created_at=f["created_at"],
            updated_at=f["updated_at"],
            burst=f["burst"],
            status=f["status"].astype(jnp.int32),
            expire_at=f["expire_at"],
            in_use=f["valid"] != 0,
            tat=f["tat"],
            prev_count=f["prev_count"],
        )
        return _scatter(state, scat, rows)

    return restore


def make_readback_fn(layout: str = "columns"):
    """Jitted gather of full item state at given slots — the write-through
    path (Store.OnChange after every mutation, algorithms.go:149-153).
    Returns ((12, B) int64, (B,) float64).  Out-of-range (padding) slots
    read zeros on the column layout and guard-row garbage on the row
    layout — callers must not read rows past their real batch."""

    _, _gather, _scatter = _layout_ops(layout)

    def readback(state, slots: jnp.ndarray):
        # Column layout zero-fills out-of-range slots; the row layout has
        # no fill option (guard-row garbage instead) — callers never read
        # past their real batch, so both contracts are safe here.
        rows = (
            _gather(state, slots)
            if layout == "row"
            else _gather(state, slots, fill=True)
        )
        ints = jnp.stack(
            [
                rows.algorithm.astype(jnp.int64),
                rows.limit,
                rows.remaining,
                rows.duration,
                rows.created_at,
                rows.updated_at,
                rows.burst,
                rows.status.astype(jnp.int64),
                rows.expire_at,
                rows.tat,
                rows.prev_count,
                rows.in_use.astype(jnp.int64),
            ]
        )
        return ints, rows.remaining_f

    return readback


READBACK_ROWS = (
    "algorithm", "limit", "remaining", "duration", "created_at",
    "updated_at", "burst", "status", "expire_at", "tat", "prev_count",
    "in_use",
)


# Columnar snapshot schema: every stored bucket field as a (live,) array
# plus the key blob/offsets pair.  The Loader v2 wire format.
SNAP_FIELDS = (
    "algorithm", "limit", "remaining", "remaining_f", "duration",
    "created_at", "updated_at", "burst", "status", "expire_at",
)


# Cooperative quota-lease columns (docs/leases.md), parallel to the SoA
# table and exported as EXTRA snapshot keys (np.savez carries them
# transparently) so outstanding delegations survive a restore.  Kept out
# of SNAP_FIELDS proper: the slim-transfer probe/select schema, the item
# dict shape, and the cold tier's column contract all iterate
# SNAP_FIELDS, and a pre-lease snapshot must keep loading (absent keys
# restore as all-zeros = no outstanding delegation, which is the safe
# reading: clients re-grant).
LEASE_SNAP_FIELDS = ("lease_budget", "lease_expire", "lease_gen")


# Algorithm-zoo state columns (docs/algorithms.md): GCRA's theoretical
# arrival time and the sliding window's previous-window count.  Like the
# lease columns these are EXTRA snapshot keys so pre-zoo snapshots keep
# loading (absent keys restore as zeros — a fresh window/TAT, which is
# the safe reading).  Unlike the lease columns they live IN the device
# table, so they ride the slim-transfer probe/select path via SNAP_WIDE.
ZOO_SNAP_FIELDS = ("tat", "prev_count")


# Wide (int64) snapshot fields, in SNAP_FIELDS order, minus the narrow
# algorithm/status columns — the unit of the slim-transfer schema below.
# The zoo columns append after the legacy seven (word offsets 20-23).
SNAP_WIDE = (
    "limit", "remaining", "duration", "created_at", "updated_at",
    "burst", "expire_at", "tat", "prev_count",
)
SNAP_CHUNK = 1 << 21  # live rows per export D2H chunk (~44-64 MB each)


@functools.lru_cache(maxsize=None)
def _jitted_snap_wide(layout: str):
    """(state, slots (w,) i32) → (ROW_USED, w) i32 stored-word matrix of
    the gathered slots — the device-side staging buffer the probe/select
    programs slice.  Padding slots must point at a REAL row (the caller
    pads with the chunk's first slot) so the probe's range statistics
    aren't polluted by guard-row zeros."""
    from gubernator_tpu.ops.buckets import STATE_DTYPES

    if layout == "row":

        def f(state, slots):
            return state.table[slots, : rowtable.ROW_USED].T

    else:

        def f(state, slots):
            rows = []
            for name in STATE_DTYPES:
                col = getattr(state, name)
                for p in col if isinstance(col, tuple) else (col,):
                    c = p[slots]
                    rows.append(
                        c if c.dtype == jnp.int32 else c.astype(jnp.int32)
                    )
            return jnp.stack(rows)

    return jax.jit(f)


@functools.lru_cache(maxsize=None)
def _jitted_snap_probe():
    """(ROW_USED, w) words → (len(SNAP_WIDE), 3) i32 per-field stats:
    [all hi words are the lo word's sign extension, min hi, max hi].
    The export uses them to pick, per chunk, which hi columns need to
    cross the link at all (verdict r3 #7: the int64 columns were the
    bytes inflating a ~0.9 GB / 110 s 10M export)."""
    O = rowtable.FIELD_OFFSETS

    def f(m):
        out = []
        for name in SNAP_WIDE:
            lo, hi = m[O[name]], m[O[name] + 1]
            out.append(
                jnp.stack([
                    jnp.all(hi == (lo >> 31)).astype(jnp.int32),
                    jnp.min(hi),
                    jnp.max(hi),
                ])
            )
        return jnp.stack(out)

    return jax.jit(f)


@functools.lru_cache(maxsize=None)
def _jitted_snap_select(hi_mask: tuple):
    """(ROW_USED, w) words → (W, w) transfer matrix: the SNAP_WIDE lo
    words, the hi words the chunk's probe proved necessary, the 3
    remaining_f parts, and one packed algorithm|status|in_use word."""
    O = rowtable.FIELD_OFFSETS

    def f(m):
        rows = [m[O[name]] for name in SNAP_WIDE]
        rows += [
            m[O[name] + 1]
            for name, keep in zip(SNAP_WIDE, hi_mask) if keep
        ]
        fo = O["remaining_f"]
        rows += [m[fo], m[fo + 1], m[fo + 2]]
        rows.append(
            (m[O["algorithm"]] & 0xFF)
            | ((m[O["status"]] & 0xFF) << 8)
            | ((m[O["in_use"]] & 1) << 16)
        )
        return jnp.stack(rows)

    return jax.jit(f)


def _snap_decode(part, k, probe, hi_mask, sel_np):
    """One transfer chunk → (kept_slots, {snap_field: column}) with dead
    (in_use=0) rows dropped.  Inverse of _jitted_snap_select + probe."""
    mat = sel_np[:, :k]
    r = len(SNAP_WIDE)
    his = {}
    for name, keep in zip(SNAP_WIDE, hi_mask):
        if keep:
            his[name] = mat[r]
            r += 1
    f32 = mat[r : r + 3]
    packed = mat[r + 3]
    alive = ((packed >> 16) & 1).astype(bool)
    cols: dict = {}
    for i, name in enumerate(SNAP_WIDE):
        lo = mat[i]
        if name in his:
            hi = his[name].astype(np.int64)
        else:
            all_se, hmin, _ = probe[i]
            if all_se:
                cols[name] = lo.astype(np.int64)[alive]
                continue
            hi = np.int64(hmin)  # probe proved the hi word constant
        cols[name] = (
            (hi << 32) | lo.view(np.uint32).astype(np.int64)
        )[alive]
    cols["remaining_f"] = sum(
        w.view(np.float32).astype(np.float64) for w in f32
    )[alive]
    cols["algorithm"] = (packed & 0xFF).astype(np.int64)[alive]
    cols["status"] = ((packed >> 8) & 0xFF).astype(np.int64)[alive]
    return part[alive], cols


def snapshot_from_items(items: Sequence[dict]) -> dict:
    """Loader-contract item dicts → columnar snapshot (the inverse of
    :func:`items_from_snapshot`; the one place the dict→columns
    conversion lives)."""
    from gubernator_tpu.ops.reqcols import pack_blob

    blob, offsets = pack_blob([it["key"].encode() for it in items])
    snap: dict = {"key_blob": blob, "key_offsets": offsets}
    for f in SNAP_FIELDS:
        dt = np.float64 if f == "remaining_f" else np.int64
        snap[f] = np.asarray([it[f] for it in items], dt)
    # Zoo columns default to zero for legacy items (pre-zoo Loader
    # sources never mention them).
    for f in ZOO_SNAP_FIELDS:
        snap[f] = np.asarray([it.get(f, 0) for it in items], np.int64)
    return snap


def items_from_snapshot(snap: dict) -> List[dict]:
    """Columnar snapshot → Loader-contract item dicts (the dict API edge;
    per-item Python lives only here)."""
    offsets = snap["key_offsets"]
    blob = snap["key_blob"]
    n = len(offsets) - 1
    fields = SNAP_FIELDS + ZOO_SNAP_FIELDS
    cols = {
        f: snap[f].tolist() if f in snap else [0] * n for f in fields
    }
    keys = [
        bytes(blob[offsets[j] : offsets[j + 1]]).decode() for j in range(n)
    ]
    return [
        {"key": keys[j], **{f: cols[f][j] for f in fields}}
        for j in range(n)
    ]


def items_from_columns(keys: List[bytes], st, live: np.ndarray) -> List[dict]:
    """Build Loader-contract item dicts for the live slots of a (host) state.

    Shared by both engines' ``export_items``: one vectorized slice per
    column, then the (unavoidable, dict-shaped) per-item build.
    """
    from gubernator_tpu.ops.buckets import slice_field

    cols = {
        name: np_logical(slice_field(getattr(st, name), live), name)
        for name in (
            "algorithm", "limit", "remaining", "remaining_f", "duration",
            "created_at", "updated_at", "burst", "status", "expire_at",
            "tat", "prev_count",
        )
    }
    return [
        {
            "key": keys[j].decode(),
            "algorithm": int(cols["algorithm"][j]),
            "limit": int(cols["limit"][j]),
            "remaining": int(cols["remaining"][j]),
            "remaining_f": float(cols["remaining_f"][j]),
            "duration": int(cols["duration"][j]),
            "created_at": int(cols["created_at"][j]),
            "updated_at": int(cols["updated_at"][j]),
            "burst": int(cols["burst"][j]),
            "status": int(cols["status"][j]),
            "expire_at": int(cols["expire_at"][j]),
            "tat": int(cols["tat"][j]),
            "prev_count": int(cols["prev_count"][j]),
        }
        for j in range(len(live))
    ]


def make_evict_fn(layout: str = "columns"):
    """Jitted slot eviction: zero a batch of slots (LRU reclamation).

    Both layouts zero the WHOLE row, not just ``in_use``: an evicted item
    is removed in the reference (lrucache.go:138-149), and stale
    don't-care fields would otherwise leak into the next tenant's
    snapshot when the slot is reborn under the other algorithm."""

    if layout == "row":
        return rowtable.row_evict

    def evict(state: BucketState, slots: jnp.ndarray) -> BucketState:
        # Zero the whole row, not just in_use: an evicted item is REMOVED
        # in the reference (lrucache.go:138-149), and leaving stale
        # don't-care fields behind leaks them into the next tenant's
        # snapshot when the slot is reborn under the other algorithm
        # (found by the row/column fuzz parity suite).
        zeros = BucketState.zeros_logical(slots.shape[0])
        return scatter_state(state, slots, zeros)

    return evict


@functools.lru_cache(maxsize=None)
def _jitted_tick(capacity: int, layout: str = "columns",
                 sorted_input: bool = False, compact_resp: bool = False,
                 compact_req: bool = False):
    """Shared jitted tick per capacity: engines pass state explicitly, so an
    in-process multi-daemon cluster (the reference's test topology,
    cluster/cluster.go) compiles the kernel once, not once per daemon."""
    return jax.jit(
        make_tick_fn(capacity, layout=layout, sorted_input=sorted_input,
                     compact_resp=compact_resp, compact_req=compact_req),
        donate_argnums=(0,),
    )


@functools.lru_cache(maxsize=None)
def _jitted_evict(layout: str = "columns"):
    return jax.jit(make_evict_fn(layout), donate_argnums=(0,))


@functools.lru_cache(maxsize=None)
def _jitted_install(layout: str = "columns"):
    return jax.jit(make_install_fn(layout), donate_argnums=(0,))


@functools.lru_cache(maxsize=None)
def _jitted_restore(layout: str = "columns"):
    return jax.jit(make_restore_fn(layout), donate_argnums=(0,))


@functools.lru_cache(maxsize=None)
def _jitted_readback(layout: str = "columns"):
    return jax.jit(make_readback_fn(layout))


@functools.lru_cache(maxsize=None)
def _jitted_lease_apply(is_set: bool):
    """One lease-column window as a single scatter over the three lease
    columns (docs/leases.md).  ``is_set`` picks grant semantics (install
    the authoritative outstanding/expiry/generation triple) vs reconcile
    deltas (budget += delta clamped at zero; expiry/generation only move
    forward).  Padding lanes carry slot == capacity, which ``mode="drop"``
    discards on device — no host-side masking pass."""

    def f(budget_col, expire_col, gen_col, slots, budgets, expires, gens):
        if is_set:
            budget_col = budget_col.at[slots].set(budgets, mode="drop")
            expire_col = expire_col.at[slots].set(expires, mode="drop")
            gen_col = gen_col.at[slots].set(gens, mode="drop")
        else:
            budget_col = jnp.maximum(
                budget_col.at[slots].add(budgets, mode="drop"), 0
            )
            expire_col = expire_col.at[slots].max(expires, mode="drop")
            gen_col = gen_col.at[slots].max(gens, mode="drop")
        return budget_col, expire_col, gen_col

    return jax.jit(f, donate_argnums=(0, 1, 2))


class SlotMap:
    """Host-side key→slot table (the stand-in for ``lrucache.go``'s map).

    Python-dict based; the C++ native version (gubernator_tpu/native) slots in
    behind the same interface for the 10M+ key regime.
    """

    def __init__(self, capacity: int):
        self.capacity = capacity
        self._map: Dict[str, int] = {}
        self._keys: List[Optional[str]] = [None] * capacity
        self._free: List[int] = list(range(capacity - 1, -1, -1))

    def __len__(self) -> int:
        return len(self._map)

    def get(self, key: str) -> Optional[int]:
        return self._map.get(key)

    def assign(self, key: str) -> Optional[int]:
        """Return the slot for key, allocating if new; None if table full."""
        s = self._map.get(key)
        if s is not None:
            return s
        if not self._free:
            return None
        s = self._free.pop()
        self._map[key] = s
        self._keys[s] = key
        return s

    def release(self, slot: int) -> None:
        key = self._keys[slot]
        if key is not None:
            del self._map[key]
            self._keys[slot] = None
            self._free.append(slot)

    def key_of(self, slot: int) -> Optional[str]:
        return self._keys[slot]

    def mapped_mask(self) -> np.ndarray:
        """Boolean array over slots: True where a key is assigned."""
        return np.fromiter(
            (k is not None for k in self._keys), np.bool_, count=self.capacity
        )

    def resolve_batch(self, keys: List[bytes]):
        """(slots, known) for a batch of keys; slot -1 = table full.
        Interface-compatible with NativeSlotMap.resolve_batch."""
        n = len(keys)
        slots = np.empty(n, np.int64)
        known = np.empty(n, np.uint8)
        get = self._map.get
        for j in range(n):
            k = keys[j].decode()
            s = get(k)
            if s is not None:
                slots[j] = s
                known[j] = 1
            else:
                s = self.assign(k)
                slots[j] = -1 if s is None else s
                known[j] = 0
        return slots, known

    def resolve_blob(self, blob, offsets: np.ndarray):
        """(slots, known) for keys packed as one blob + offsets (the
        columnar hot-path format; NativeSlotMap resolves this with zero
        per-key Python).  ``blob`` may be any bytes-like buffer — slices
        are coerced to bytes for the per-key decode."""
        mv = memoryview(blob)
        return self.resolve_batch(
            [bytes(mv[offsets[j] : offsets[j + 1]]) for j in range(len(offsets) - 1)]
        )

    def release_batch(self, slots: np.ndarray) -> None:
        for s in slots:
            self.release(int(s))

    def keys_batch(self, slots: np.ndarray) -> List[bytes]:
        return [
            (k.encode() if (k := self._keys[int(s)]) is not None else b"")
            for s in slots
        ]

    def keys_blob(self, slots: np.ndarray) -> tuple[bytes, np.ndarray]:
        """Keys of a batch of slots as one (blob, offsets) pair (the
        columnar snapshot format; NativeSlotMap does this natively)."""
        from gubernator_tpu.ops.reqcols import pack_blob

        return pack_blob(self.keys_batch(slots))

    def assign_blob(self, blob: bytes, offsets: np.ndarray) -> np.ndarray:
        return self.assign_batch(
            [blob[offsets[j] : offsets[j + 1]] for j in range(len(offsets) - 1)]
        )

    def assign_batch(self, keys: List[bytes]) -> np.ndarray:
        out = np.empty(len(keys), np.int64)
        for j, k in enumerate(keys):
            s = self.assign(k.decode())
            out[j] = -1 if s is None else s
        return out


@functools.lru_cache(maxsize=None)
def _jitted_dead_scan():
    """Device-side TTL sweep: ``~in_use | expired`` packed to a bitmask so
    the per-reclaim D2H is capacity/8 bytes, not the 9 bytes/slot the old
    host sweep copied (seconds of stall at 10M slots over a tunneled
    device)."""

    def scan(in_use, exp_lo, exp_hi, now):
        exp = to_logical((exp_lo, exp_hi), "expire_at")
        dead = (~in_use) | (exp < now)
        return jnp.packbits(dead, bitorder="little")

    return jax.jit(scan)


def device_dead_bits(in_use, expire_field, now: int):
    """Dispatch the dead-slot scan; returns the *device* packed bitmask
    (callers materialize with :func:`unpack_dead_bits`).  Split from
    :func:`device_dead_mask` so the background reclaimer can dispatch
    under the engine lock (the state buffers are donated by the next tick)
    but pay the D2H wait outside it."""
    lo, hi = expire_field
    return _jitted_dead_scan()(in_use, lo, hi, jnp.int64(now))


def unpack_dead_bits(bits, capacity: int) -> np.ndarray:
    return np.unpackbits(
        # guber: allow-G001(the deliberate reclaim D2H - materializing the packed dead bitmask is this helper's whole job; callers pay it off-lock, at most once per reclaim round, never per tick)
        np.asarray(bits), count=capacity, bitorder="little"
    ).astype(bool)


def device_dead_mask(in_use, expire_field, now: int, capacity: int) -> np.ndarray:
    """Host bool mask of device-dead slots (unused or TTL-expired), computed
    on device and shipped as a packed bitmask."""
    return unpack_dead_bits(device_dead_bits(in_use, expire_field, now), capacity)


def select_reclaim_victims(
    mapped: np.ndarray,
    dead_dev: np.ndarray,
    last_access: np.ndarray,
    tick_count: int,
    want: int,
) -> tuple[np.ndarray, np.ndarray]:
    """TTL-then-LRU victim selection over a table (or a shard slice of one).

    The one reclaim policy shared by all engines (expired-on-read eviction +
    evict-oldest of lrucache.go:88-149): returns ``(expired, lru_victims)``
    as local slot indices.  ``dead_dev`` is the device's view of dead slots
    (:func:`device_dead_mask`).  Expired slots release host-side with no
    device work; LRU victims must *also* be device-evicted (their ``in_use``
    is still set, and stale state must not resurrect if the slot is reused).

    ``mapped`` must already exclude host-pending slots (assigned but not
    yet written by a tick); slots touched this tick are excluded here —
    both look dead on device but are live.
    """
    mapped = mapped & (last_access != tick_count)
    dead = mapped & dead_dev
    freed = np.flatnonzero(dead)
    none = np.empty(0, np.int64)
    if len(freed) >= want:
        return freed, none
    live = np.flatnonzero(mapped & ~dead)
    n = min(want - len(freed), len(live))
    if n <= 0:
        return freed, none
    if n >= len(live):
        return freed, live
    # argpartition, not argsort: O(live) — a full sort of a 10M-slot table
    # costs seconds per reclaim for ordering we don't need.
    return freed, live[np.argpartition(last_access[live], n - 1)[:n]]


EVICT_CHUNK = 1 << 16
RESTORE_CHUNK = 1 << 15  # bounds the per-call VMEM row staging (16 MB)


def evict_chunked(evict_fn, state, victims: np.ndarray, capacity: int):
    """Apply a device evict scatter in width-capped chunks.

    Padding the whole batch to ``pad_pow2(len(victims))`` would compile an
    unbounded program width — including a ~1M-wide one on the first
    big-table reclaim (tens of seconds of jit on a slow toolchain).
    Capping at EVICT_CHUNK bounds compiles to the log2(EVICT_CHUNK) small
    widths, each cheap to build and shared via jit's shape cache."""
    for start in range(0, len(victims), EVICT_CHUNK):
        part = victims[start : start + EVICT_CHUNK]
        w = min(EVICT_CHUNK, pad_pow2(len(part)))
        padded = np.full(w, capacity, np.int32)
        padded[: len(part)] = part
        state = evict_fn(state, jnp.asarray(padded))
    return state


def make_slot_map(capacity: int):
    """Native C++ slotmap when the shared library is available (built by
    gubernator_tpu/native/Makefile), pure-Python fallback otherwise."""
    try:
        from gubernator_tpu.native import NativeSlotMap

        return NativeSlotMap(capacity)
    except Exception:
        return SlotMap(capacity)


class TickHandle:
    """One dispatched tick: device work is queued, host readback deferred.

    ``result()`` materializes the (5, n) response matrix in request order
    (rows: status, limit, remaining, reset_time, over_limit) and runs the
    deferred per-tick bookkeeping (over-limit metric, Store write-through).
    Idempotent; safe to call from a different thread than the dispatcher.
    """

    __slots__ = ("_engine", "_resp", "_n", "_inv", "errors", "_refs",
                 "_slots_req", "_limit_req", "_done", "_flock")

    def __init__(self, engine, resp, n, inv, errors, refs, slots_req,
                 limit_req=None):
        self._engine = engine
        self._resp = resp
        self._n = n
        self._inv = inv
        self.errors = errors
        self._refs = refs
        self._slots_req = slots_req
        # Request-order limit column: the compact device response omits
        # the limit echo (pack_resp_compact); reconstruction needs it.
        # COPIED — the caller may reuse/rewrite its ReqColumns buffers
        # between submit and resolve (the pipelining pattern), and this
        # column is read at resolve time.
        self._limit_req = (
            None if limit_req is None
            # guber: allow-G001(host column snapshot - limit_req is a host array; the copy is the pipelining contract, not a device sync)
            else np.array(limit_req[:n], np.int64, copy=True)
        )
        self._done: Optional[np.ndarray] = None
        self._flock = sanitize.lock("TickHandle._flock")

    def _finish(self, raw: np.ndarray) -> None:
        """Complete from an already-materialized device response matrix:
        (6, W) int32 compact (TickEngine's format — it compiles its tick
        with compact_resp=True and always passes limit_req) or the
        (5, W) int64 legacy layout used by engines that don't."""
        with self._flock:
            if self._done is not None:
                return
            # The [:, inv] un-permutes the slot-sorted batch.
            rm = raw[:, : self._n][:, self._inv]
            if self._limit_req is not None:  # compact → public (5, n) int64
                rm = unpack_resp_compact(rm, self._limit_req)
            eng = self._engine
            with eng._lock:
                # This window is resolved: it no longer holds its H2D
                # staging slab, and later windows' uploads stop counting
                # it as overlap (see TickEngine.metric_h2d_overlapped).
                eng._inflight = max(0, eng._inflight - 1)
                eng.metric_over_limit += masked_over_limit(rm, self.errors)
                if eng.store is not None:
                    eng._write_through(
                        self._refs, self._slots_req, self._n, self.errors
                    )
            self._resp = None  # release the device buffer reference
            self._done = rm

    def result(self) -> tuple[np.ndarray, Dict[int, str]]:
        if self._done is None:
            self._finish(np.asarray(self._resp))
        return self._done, self.errors


def resolve_ticks(handles: Sequence[TickHandle]) -> None:
    """Materialize many dispatched ticks' responses in as few D2H
    transfers as possible: same-shape response buffers are stacked on
    device (a cheap async op) and fetched in ONE host transfer.

    Per-transfer latency is the throughput ceiling when the device is far
    away (measured here: ~3 ms to dispatch a tick, ~130 ms for EACH
    response transfer over the tunneled device — so resolving K ticks
    together is a ~K× throughput lever; on local PCIe/ICI it merely saves
    K-1 small syscalls)."""
    todo = [h for h in handles if h._done is None]
    if len(todo) <= 1:
        for h in todo:
            h.result()
        return
    groups: Dict[tuple, List[TickHandle]] = {}
    for h in todo:
        groups.setdefault(tuple(h._resp.shape), []).append(h)
    for hs in groups.values():
        if len(hs) == 1:
            hs[0].result()
            continue
        stacked = np.asarray(jnp.stack([h._resp for h in hs]))
        for k, h in enumerate(hs):
            h._finish(stacked[k])


class SubmittedBatch:
    """A dispatched object-level batch (one or more chunked ticks); the
    tick loop resolves it off the dispatch thread."""

    __slots__ = ("_handles", "_spans", "_n")

    def __init__(self, handles, spans, n):
        self._handles = handles
        self._spans = spans
        self._n = n

    def handles(self) -> List[TickHandle]:
        return self._handles

    def matrix(self) -> tuple[np.ndarray, Dict[int, str]]:
        """(5, n) response matrix in request order + per-item errors
        (the columnar result shape; responses() wraps it in dataclasses)."""
        resolve_ticks(self._handles)  # one D2H for all chunks
        out = np.empty((5, self._n), np.int64)
        errors: Dict[int, str] = {}
        for h, (s, e) in zip(self._handles, self._spans):
            rm, errs = h.result()
            out[:, s:e] = rm
            for i, msg in errs.items():
                errors[s + i] = msg
        return out, errors

    def responses(self) -> List[RateLimitResponse]:
        resolve_ticks(self._handles)  # one D2H for all chunks
        out: List[Optional[RateLimitResponse]] = [None] * self._n
        for h, (s, e) in zip(self._handles, self._spans):
            rm, errors = h.result()
            status, limit, remaining, reset = (rm[r].tolist() for r in range(4))
            for i in range(e - s):
                out[s + i] = (
                    RateLimitResponse(error=errors[i])
                    if i in errors
                    else RateLimitResponse(
                        status=status[i],
                        limit=limit[i],
                        remaining=remaining[i],
                        reset_time=reset[i],
                    )
                )
        return out  # type: ignore[return-value]


class TickEngine:
    """Owns the device state table and applies request batches tick by tick.

    Thread-safe: the service layer calls :meth:`process` from its tick loop;
    loaders/metrics may snapshot concurrently.
    """

    def __init__(
        self,
        capacity: int = 1 << 16,
        max_batch: int = 4096,
        device: Optional[jax.Device] = None,
        store=None,
        table_layout: str = "auto",
        bg_reclaim: Optional[bool] = None,
        cold_capacity: int = 0,
        ssd=None,
    ):
        self.capacity = int(capacity)
        self.max_batch = int(max_batch)
        # Optional write/read-through Store (reference store.go:49-65).
        # Write-through costs one extra D2H readback of touched slots per
        # tick; read-through one extra scatter when misses hit the store.
        self.store = store
        # Tiered bucket state (docs/tiering.md): a host-side cold store
        # LRU victims demote into (readback-then-evict) and misses
        # promote out of, so bucket continuity survives hot↔cold cycling
        # — without it, eviction zeroes the row and a key cycling back
        # in restarts with a full budget.  0 = disabled (strict
        # evict-destroys semantics, the reference's lrucache.go:138-149).
        self.cold = None
        if cold_capacity > 0:
            from gubernator_tpu.tiering import ColdStore

            self.cold = ColdStore(int(cold_capacity), store=store)
        # Third tier (docs/tiering.md): an SsdStore absorbing the cold
        # tier's overflow.  It interposes as the cold tier's write-behind
        # sink — the engine-level Store keeps its write/read-through
        # roles — and the miss path gains one batched hop (hot miss →
        # cold miss → SSD take_batch) whose hits merge into the SAME
        # one-scatter-per-tick restore as cold hits.
        self.ssd = ssd
        if ssd is not None:
            if self.cold is None:
                raise ValueError(
                    "SSD tier requires a cold tier (cold_capacity > 0): "
                    "the SSD store only ever holds cold-tier overflow"
                )
            self.cold.store = ssd
        self.device = device or jax.devices()[0]
        self.layout = make_layout_choice(
            table_layout, self.capacity, self.device, self.max_batch
        )
        zeros, _, _ = _layout_ops(self.layout)
        with jax.default_device(self.device):
            self.state = jax.tree.map(jnp.asarray, zeros(self.capacity))
        # Mixed/ineligible duplicate batches run the parts-native chained
        # unit-round program (tick32.make_sorted_tick32_rows_fn): exact
        # per-slot order, ceil(units/8) gather+scatter rounds, no XLA
        # 64-bit emulation.  GUBER_TPU_SORTED32=0 falls back to the x64
        # oracle program (engine.make_tick_fn), which stays the parity
        # reference in tests.  Registry read, once per engine — never
        # per tick.
        from gubernator_tpu.config import env_knob

        from gubernator_tpu.ops.tick32 import (
            jitted_merged_pipeline,
            jitted_sorted_tick32,
            jitted_tick32,
        )

        if env_knob("GUBER_TPU_SORTED32") == "0":
            self._tick = _jitted_tick(self.capacity, self.layout,
                                      sorted_input=True, compact_resp=True,
                                      compact_req=True)
        else:
            self._tick = jitted_sorted_tick32(self.capacity, self.layout)

        # Note on request-buffer donation: the (19, B) request matrix
        # has no same-shape program output, and XLA's input-output
        # aliasing is exact-shape, so donating it buys nothing (jax
        # warns "donated buffers were not usable").  The double-buffered
        # H2D contract is therefore: donated STATE buffers + the host
        # staging ring + async upload — each window's upload rides
        # under the previous window's tick, and the request buffer is
        # simply dropped when its tick completes.
        self._tick32 = jitted_tick32(self.capacity, self.layout)
        # Grouped batches (uniform duplicate groups — Zipf/hot-key
        # traffic) tick each unique head once with a closed-form follower
        # fold, then expand per-member responses elementwise: the
        # scatter-add architecture from BASELINE.json.  Serving-scale
        # engines warm it per width (see _warmup); small test-cluster
        # engines compile lazily on the first grouped batch.
        self._tick32m = jitted_merged_pipeline(self.capacity, self.layout)
        # Tick widths: one narrow program for typical service batches
        # (≤ the reference's 1000-item batch limit) plus the full width.
        # Singleton for small engines so test clusters don't pay an extra
        # compile per daemon.
        mb = pad_pow2(self.max_batch)
        self._widths = (
            (mb,) if mb < 2048 else tuple(sorted({max(1024, mb // 4), mb}))
        )
        self._evict = _jitted_evict(self.layout)
        self._install = _jitted_install(self.layout)
        self._restore = _jitted_restore(self.layout)
        self._readback = _jitted_readback(self.layout)
        # Double-buffered H2D staging (docs/tpu-performance.md): the
        # packed request matrix for each window is built in a reusable
        # host slab and uploaded with an *async* host→device copy, so window
        # N+1's transfer rides the link while window N's tick still
        # runs on device.  The ring holds 2x the tick pipeline depth of
        # slabs per program width: a slab recycles only once the tick
        # that consumed it has resolved (its H2D is then provably
        # complete — jax may read the host buffer until the transfer
        # finishes), and when every slab is still in flight the lease
        # falls back to a fresh allocation rather than corrupting one.
        try:
            _depth = max(1, env_knob(
                "GUBER_TICK_PIPELINE_DEPTH", 4, parse=int))
        except ValueError:
            _depth = 4
        self._stage_depth = 2 * _depth + 1
        self._staging = StagingRing(
            REQ32_ROWS, self.capacity, self._stage_depth
        )
        # H2D overlap telemetry: a window counts as overlapped when its
        # upload was dispatched while at least one earlier window was
        # still unresolved — the pipelined steady state.  The bench
        # ladder exports overlapped/windows as h2d_overlap_ratio and
        # the CI gate holds it (scripts/check_bench_regression.py).
        self._inflight = 0
        self.metric_h2d_windows = 0
        self.metric_h2d_overlapped = 0
        self.slots = make_slot_map(self.capacity)
        self._last_access = np.zeros(self.capacity, np.int64)
        # Slots mutated since the last export — the incremental snapshot's
        # working set (export_columns(dirty_only=True)).  Marked at the
        # three mutation sites (tick, GLOBAL install, snapshot restore);
        # cleared by any export.  The reference's Store OnChange trickles
        # per-request updates continuously (store.go:49-65); here the
        # delta accumulates host-side and drains on the export cadence.
        self._dirty = np.zeros(self.capacity, bool)
        # Slots assigned host-side but not yet written by a device tick; the
        # device's in_use lags for these, so reclamation must not treat them
        # as dead (or two live keys could share a slot within one tick).
        self._pending: set = set()
        self._tick_count = 0
        self._lock = sanitize.rlock("TickEngine._lock")
        # Background reclaim (SURVEY §7 "reclaim off the serving path"):
        # when free slots dip under the low watermark AND the batch had
        # misses, a reclaimer thread runs TTL-then-LRU victim selection on
        # snapshots outside the lock, so a full 10M-slot table doesn't put
        # an argpartition + dead-scan D2H on the p99 of a serving tick.
        # Auto-enabled for big tables only: small tables keep the strict
        # evict-at-capacity semantics (reference lrucache.go:138-149) that
        # the behavior suite pins, and the sync fallback still guarantees
        # progress when the reclaimer is behind.
        self._bg_reclaim = (
            bg_reclaim if bg_reclaim is not None else self.capacity >= (1 << 18)
        )
        self._reclaim_low = min(
            self.capacity // 8, max(2 * self.max_batch, self.capacity // 64)
        )
        self._reclaim_evt = threading.Event()
        self._reclaim_closed = False
        self._reclaim_thread: Optional[threading.Thread] = None
        # Request-time clock: the max `now` any tick has seen.  Background
        # reclaim judges TTL expiry against THIS, not the wall clock —
        # callers may drive synthetic time (tests, replay harnesses).
        self._last_now = 0
        # Metrics mirrors (lrucache.go:48-59, gubernator.go:60-111 families).
        self.metric_hits = 0
        self.metric_misses = 0
        self.metric_over_limit = 0
        self.metric_unexpired_evictions = 0
        self.metric_layered_ticks = 0
        # Tiering telemetry: cold lookups that hit on the miss path,
        # batched restore scatters the promote path dispatched (and the
        # ticks that needed one — their ratio must stay 1.0: promotion
        # is one scatter per tick, never per key), readback dispatches
        # the demote path ran, and reclaim rounds that had LRU victims
        # (readbacks happen ONLY inside those).  Shed counts requests
        # answered with a per-item table-full error instead of a raise.
        self.metric_cold_hits = 0
        self.metric_promotions = 0
        self.metric_promote_dispatches = 0
        self.metric_promote_ticks = 0
        self.metric_demote_readbacks = 0
        self.metric_evict_reclaims = 0
        self.metric_shed_requests = 0
        # SSD-tier exact-work telemetry: lookups counts take_batch
        # calls (≤ 1 per tick that still had misses after the cold hop
        # — their ratio is the bench's ssd_promote_batches_per_miss_tick
        # gate), and tick_path_reads is the structural proof that no
        # SSD read ever lands inside the tick-dispatch block (must stay
        # 0; scripts/check_bench_regression.py pins it).
        self.metric_ssd_hits = 0
        self.metric_ssd_lookups = 0
        self.metric_ssd_miss_ticks = 0
        self.metric_ssd_tick_path_reads = 0
        # Cooperative quota-lease columns (docs/leases.md): per-slot
        # outstanding delegated budget, lease expiry (epoch ms), and
        # generation — device-resident so grant/renew/reconcile land as
        # ONE batched scatter per window (lease_window; the exact-work
        # dispatch counter below proves one dispatch per window) and so
        # delegations survive a snapshot round-trip (LEASE_SNAP_FIELDS).
        # Nomenclature: StagingRing "leases" are H2D slab reservations;
        # everything lease_* on the engine is quota leases.
        self._lease_budget = jnp.zeros(self.capacity, jnp.int64)
        self._lease_expire = jnp.zeros(self.capacity, jnp.int64)
        self._lease_gen = jnp.zeros(self.capacity, jnp.int32)
        self.metric_lease_dispatches = 0
        self.metric_lease_windows = 0
        self.metric_lease_ops = 0
        self._warmup()

    def _warmup(self) -> None:
        """Compile the tick/install programs now (first compile is seconds;
        it must land at startup, not on the first live request's deadline).
        An all-padding batch leaves the zeroed state untouched.

        The response matrix is materialized host-side too: the first D2H of
        a given buffer shape pays a setup cost on tunneled devices (~1.5s
        measured) — unwarmed, that lands on the first live request, blows
        the 500ms peer batch_timeout, and triggers forward retries that
        double-count hits."""
        warm_sequential = jax.default_backend() == "tpu"
        for w in self._widths:
            m = np.zeros((REQ32_ROWS, w), np.int32)
            m[REQ32_INDEX["slot"]] = self.capacity
            if warm_sequential:
                # The sequential chained-unit program only serves
                # adversarial duplicate shapes; like the layered warmup
                # below, eager-compiling it is a serving chip's live-
                # deadline concern — on the CPU backend (tests, the fast
                # CI gate) most engines never tick it and lazy is the
                # right trade.
                self.state, resp = self._tick(
                    self.state, jnp.asarray(m), jnp.int64(0)
                )
                np.asarray(resp)
            self.state, resp = self._tick32(
                self.state, jnp.asarray(m), jnp.int64(0)
            )
            np.asarray(resp)
        # Warm the grouped (scatter-add) pipeline at each width's floor
        # head shape (group_upad — the shape every sub-quantum hot-key
        # window hits) so the first grouped batch doesn't pay the
        # compile on a live deadline.  Deeper head widths stay lazy.
        # Gated to serving-scale engines: test-cluster engines (small
        # capacity, usually no duplicate traffic) skip the extra
        # compiles.
        if self.capacity >= (1 << 14):
            for w in self._widths:
                upad = group_upad(w)
                mh = np.zeros((REQ32_ROWS, upad), np.int32)
                mh[REQ32_INDEX["slot"]] = self.capacity
                self.state, resp = self._tick32m(
                    self.state, jnp.asarray(mh),
                    jnp.ones(upad, np.int32),
                    jnp.full(w, upad - 1, np.int32),
                    jnp.zeros(w, np.int32),
                    jnp.int64(0),
                )
                np.asarray(resp)
        if self.capacity >= (1 << 16) and jax.default_backend() == "tpu":
            # Warm the layered pipeline's most common shape (w0 at the
            # narrow width's floor, 2 layers — what a typical mixed-herd
            # serving batch plans to) so the first live one doesn't pay
            # the compile; deeper/wider shapes stay lazy, as do
            # mid-sized engines (in-process test clusters default to
            # 50k-slot tables and rarely see mixed-duplicate traffic —
            # their first such batch compiles then).  TPU-only: the
            # live-deadline concern is a serving chip's; on the CPU
            # backend (tests, the fast CI gate) the same compile costs
            # minutes per engine and lazy is the right trade.
            from gubernator_tpu.ops.tick32 import jitted_layered_pipeline

            w = self._widths[0]
            w0 = group_upad(w)
            mh0 = np.zeros((REQ32_ROWS, w0), np.int32)
            mh0[REQ32_INDEX["slot"]] = self.capacity
            mhk = np.zeros((1, REQ32_ROWS, 512), np.int32)
            mhk[:, REQ32_INDEX["slot"], :] = self.capacity
            m32 = np.zeros((REQ32_ROWS, w), np.int32)
            m32[REQ32_INDEX["slot"]] = self.capacity
            fn = jitted_layered_pipeline(self.capacity, self.layout, w0, 2)
            self.state, resp = fn(
                self.state, jnp.asarray(mh0), jnp.ones(w0, np.int32),
                jnp.asarray(mhk), jnp.ones((1, 512), np.int32),
                jnp.asarray(m32), jnp.zeros(w, np.int32),
                jnp.zeros(w, np.int32), jnp.int64(0),
            )
            np.asarray(resp)
        cols = np.zeros((8, 1), np.int64)  # valid=0 row: install is a no-op
        self.state = self._install(self.state, jnp.asarray(cols), jnp.int64(0))
        # Compile the reclaim dead-scan now too: its first invocation
        # otherwise jits a capacity-wide program on the serving path, right
        # when the table first fills (tens of seconds on slow toolchains).
        self._dead_mask(0)
        jax.block_until_ready(self.state)

    def _dead_bits(self, now: int):
        """Dispatch the device dead-slot scan (packed bitmask, on device)."""
        if self.layout == "row":
            return rowtable.row_device_dead_bits(self.state, now)
        return device_dead_bits(self.state.in_use, self.state.expire_at, now)

    def _dead_mask(self, now: int) -> np.ndarray:
        return unpack_dead_bits(self._dead_bits(now), self.capacity)

    # ------------------------------------------------------------------
    # Host-side request preparation
    # ------------------------------------------------------------------
    def _resolve_slot(self, key: str, now: int) -> tuple[int, bool]:
        known = self.slots.get(key) is not None
        slot = self.slots.assign(key)
        if slot is None:
            self._reclaim(now)
            slot = self.slots.assign(key)
            if slot is None:
                raise RuntimeError("rate-limit table full; eviction failed")
        if not known:
            self._pending.add(slot)
        if known:
            self.metric_hits += 1
        else:
            self.metric_misses += 1
        return slot, known

    def _reclaim(self, now: int, want: Optional[int] = None) -> None:
        """Free expired slots; fall back to LRU eviction (lrucache.go:115-149).

        LRU victims take the readback-then-evict path: their rows are
        pulled D2H *before* the evict scatter and demoted into the cold
        tier (when one is configured), so unexpired bucket state survives
        eviction instead of evaporating (docs/tiering.md)."""
        mapped = self.slots.mapped_mask()
        if self._pending:
            mapped[np.fromiter(self._pending, np.int64)] = False
        freed, victims = select_reclaim_victims(
            mapped,
            self._dead_mask(now),
            self._last_access,
            self._tick_count,
            want or max(1, self.capacity // 16),
        )
        self.slots.release_batch(freed)
        if len(victims) == 0:
            if self.cold is not None:
                self.cold.expire(now)
            return
        self.metric_unexpired_evictions += len(victims)
        finish = self._demote_dispatch(victims, now)
        self.slots.release_batch(victims)
        self.state = evict_chunked(self._evict, self.state, victims, self.capacity)
        finish()
        if self.cold is not None:
            self.cold.expire(now)

    def _demote_dispatch(self, victims: np.ndarray, now: int):
        """Readback-then-evict, dispatch half: queue the D2H readback of
        the victim rows *before* the caller's evict scatter (same device
        stream — program order guarantees the readback observes pre-evict
        state) and capture the victims' keys before the slot map releases
        them.  Returns a finish closure that materializes the readback
        (the D2H wait), lands live rows in the cold tier, and fires
        ``Store.remove`` for rows leaving the tiered cache entirely — the
        documented remove-on-eviction contract (store.py) the old blind
        zeroing never honored.  The background reclaimer runs the closure
        outside the engine lock; the sync path runs it inline.

        Called only from reclaim rounds that selected LRU victims — a
        reclaim-free tick never pays a readback."""
        self.metric_evict_reclaims += 1
        if self.cold is None and self.store is None:
            return lambda: None
        keys = self.slots.keys_batch(victims)
        if self.cold is None:
            # No cold tier: eviction is terminal — honor Store.remove
            # (store.py: "remove on eviction") without any device work.
            def finish_remove():
                for k in keys:
                    if k:
                        # guber: allow-g009(Store.remove is the pluggable Store contract's thread-safe entry point; the engine calls it but never rebinds self.store after __init__)
                        self.store.remove(k.decode())

            return finish_remove
        pending = []
        for start in range(0, len(victims), RESTORE_CHUNK):
            part = victims[start : start + RESTORE_CHUNK]
            padded = np.full(pad_pow2(len(part)), self.capacity, np.int64)
            padded[: len(part)] = part
            self.metric_demote_readbacks += 1
            pending.append(
                (len(part), self._readback(self.state, jnp.asarray(padded)))
            )

        def finish():
            off = 0
            for k_n, (ints, floats) in pending:
                im = np.asarray(ints)[:, :k_n]
                fl = np.asarray(floats)[:k_n]
                part_keys = keys[off : off + k_n]
                off += k_n
                f = dict(zip(READBACK_ROWS, im))
                # Rows dead on device (never ticked, or TTL-expired) are
                # not demoted — resurrecting them would hand the next
                # tenant stale state; they leave the cache entirely.
                live = (f["in_use"] != 0) & (f["expire_at"] >= now)
                sel = np.flatnonzero(live)
                if len(sel):
                    cols = {
                        name: f[name][sel]
                        for name in READBACK_ROWS
                        if name != "in_use"
                    }
                    cols["remaining_f"] = fl[sel]
                    self.cold.put_columns(
                        [part_keys[int(j)] for j in sel], cols, now
                    )
                if self.store is not None:
                    for j in np.flatnonzero(~live):
                        k = part_keys[int(j)]
                        if k:
                            self.store.remove(k.decode())

        return finish

    # ------------------------------------------------------------------
    # Background reclaim
    # ------------------------------------------------------------------
    def _maybe_trigger_reclaim(self) -> None:
        """Wake the reclaimer when free slots dip under the watermark.
        Called under the lock from the build path, only when the batch had
        misses — a full table under pure-hit traffic must NOT evict (the
        reference evicts on insert pressure only, lrucache.go:88-103)."""
        if not self._bg_reclaim or self._reclaim_closed:
            return
        if self.capacity - len(self.slots) >= self._reclaim_low:
            return
        if self._reclaim_thread is None:  # lazy: most engines never need it
            self._reclaim_thread = threading.Thread(
                target=self._reclaim_loop, daemon=True, name="guber-reclaim"
            )
            self._reclaim_thread.start()
        self._reclaim_evt.set()

    def _reclaim_loop(self) -> None:
        import logging

        while True:
            self._reclaim_evt.wait()
            self._reclaim_evt.clear()
            if self._reclaim_closed:
                return
            try:
                self._reclaim_background()
            except Exception:
                logging.getLogger("gubernator.engine").exception(
                    "background reclaim failed"
                )

    def _reclaim_background(self) -> None:
        """One reclaim round with the expensive work off the lock.

        Phase 1 (lock): *dispatch* the device dead-scan — must happen under
        the lock because the next tick donates the state buffers.  Expiry
        is judged against the engine's request-time clock (``_last_now``),
        NOT the host wall clock: callers may drive synthetic time (tests,
        replay), and the reference's expiry is always relative to request
        ``CreatedAt`` (algorithms.go:46-57).
        Phase 2 (no lock): materialize the dead bitmask (D2H wait).
        Phase 3 (lock): snapshot mapped/pending/last_access.
        Phase 4 (no lock): TTL-then-LRU victim selection (argpartition over
        the table — the cost that used to spike serving p99).
        Phase 5 (lock): revalidate — drop any candidate touched since the
        snapshot (later builds stamp tick_count > snap under the lock) —
        then release slots and dispatch the evict scatter (async).
        """
        with self._lock:
            # Size the round to the watermark deficit (target: 2x the low
            # watermark free, capped at the sync quantum) — the trigger
            # may have been satisfied already by an earlier round.
            free = self.capacity - len(self.slots)
            want = min(self.capacity // 16, 2 * self._reclaim_low - free)
            if want <= 0 or self._last_now == 0:
                return
            # snap is taken HERE, before the scan is dispatched: the dead
            # bitmask is stale for anything that ticks during the D2H
            # wait, and the phase-5 `la <= snap` filter must therefore
            # drop every slot touched at tick > snap — a bucket revived
            # mid-wait must not be freed on the strength of the old scan.
            snap = self._tick_count
            bits = self._dead_bits(self._last_now)
        dead = unpack_dead_bits(bits, self.capacity)
        with self._lock:
            mapped = self.slots.mapped_mask()
            if self._pending:
                mapped[np.fromiter(self._pending, np.int64)] = False
            la = self._last_access.copy()
        freed, victims = select_reclaim_victims(mapped, dead, la, snap, want)
        finish = None
        with self._lock:
            freed = freed[self._last_access[freed] <= snap]
            victims = victims[self._last_access[victims] <= snap]
            self.slots.release_batch(freed)
            if len(victims):
                self.metric_unexpired_evictions += len(victims)
                # Dispatch the demote readback BEFORE the evict scatter
                # (device program order = pre-evict state) but run the
                # D2H wait + cold-tier insert outside the lock.
                finish = self._demote_dispatch(victims, self._last_now)
                self.slots.release_batch(victims)
                # guber: allow-g009(every post-start touch holds _lock; the unguarded peers are _warmup, which runs in __init__ before the reclaim thread exists)
                self.state = evict_chunked(
                    self._evict, self.state, victims, self.capacity
                )
        if finish is not None:
            finish()
        if self.cold is not None:
            self.cold.expire(self._last_now)

    def close(self) -> None:
        """Stop the background reclaimer.  Engines are otherwise GC-safe
        (the thread is a daemon and lazily started); services close via
        V1Instance.close."""
        self._reclaim_closed = True
        self._reclaim_evt.set()
        t = self._reclaim_thread
        if t is not None:
            t.join(timeout=5)
        # The engine owns its SSD tier's writer thread: drain + stop it
        # so staged demote batches reach disk before the process exits.
        if self.ssd is not None:
            self.ssd.close()

    @hot_path
    def _lease_matrix(self, b: int) -> np.ndarray:
        """A zeroed (REQ32_ROWS, b) staging slab from the per-width ring
        (slot row pre-set to the padding sentinel) — see
        :class:`StagingRing` for the recycle contract.  Called under the
        engine lock (ring state is unsynchronized)."""
        fr = flightrec.get()
        if fr is None:
            return self._staging.lease(b)
        t0 = time.perf_counter()
        m = self._staging.lease(b)
        fr.note(fr.active(), "lease", time.perf_counter() - t0)
        return m

    @hot_path
    def _build_cols(self, cols: ReqColumns, now: int):
        """Resolve keys to slots and pack the padded (12, B) request matrix
        from a columnar batch — zero per-request Python on the no-error,
        no-store path: one native blob resolve + a dozen vectorized numpy
        writes + one argsort.

        A single int64 matrix means one H2D transfer per tick; per-transfer
        latency dominates small ticks.
        """
        n = len(cols)
        if n > self.max_batch:
            raise ValueError(f"batch of {n} exceeds engine max {self.max_batch}")
        # Width quantization: a tick's device cost scales with the padded
        # width (scatter lanes), so small batches use the narrow program
        # instead of paying for max_batch lanes of padding.  Both widths
        # are compiled at warmup.
        b = next(w for w in self._widths if w >= n)
        m = self._lease_matrix(b)
        R = REQ32_INDEX
        errors: Dict[int, str] = {}

        # Gregorian resolution (host-side calendar math) — only requests
        # carrying the flag pay for it; failures become per-item errors.
        GREG = int(Behavior.DURATION_IS_GREGORIAN)
        greg = cols.behavior & GREG
        if greg.any():
            for i in np.flatnonzero(greg):
                try:
                    d = int(cols.duration[i])
                    pack_wide_rows(
                        m, "greg_exp", timeutil.gregorian_expiration(now, d), i
                    )
                    pack_wide_rows(
                        m, "greg_dur", timeutil.gregorian_duration(now, d), i
                    )
                except timeutil.GregorianError as exc:
                    errors[int(i)] = str(exc)

        # One native call resolves every key to a slot (the reference does a
        # per-key map lookup inside each worker goroutine; here it's a batch
        # against the C++ open-addressing table, fed the key blob directly).
        if errors:
            # guber: allow-G001(builds a host index list, never device)
            sel = np.array([i for i in range(n) if i not in errors], np.int64)
            if len(sel) == 0:
                return m, n, errors, np.arange(n, dtype=np.int64), False
            slots, known = self.slots.resolve_batch(
                [cols.key_bytes(int(i)) for i in sel]
            )
        else:
            sel = None  # the whole batch, contiguous
            slots, known = self.slots.resolve_blob(
                cols.key_blob, cols.key_offsets
            )
        if (slots < 0).any():
            # Stamp the already-resolved rows live *before* reclaiming:
            # fresh misses look unused on device and known slots carry a
            # stale _last_access, so an unstamped reclaim could release
            # slots resolved microseconds ago and hand them to the retried
            # keys — two keys sharing one bucket within the same tick.
            ok = slots >= 0
            self._last_access[slots[ok]] = self._tick_count
            self._pending.update(slots[ok & (known == 0)].tolist())
            # Free at least as many slots as this batch still needs — the
            # capacity//16 default can be smaller than one batch's misses,
            # which would fail the retry with room still reclaimable.
            needed = int((~ok).sum())
            self._reclaim(now, want=max(needed, self.capacity // 16))
            retry = np.flatnonzero(slots < 0)
            retry_src = retry if sel is None else sel[retry]
            s2, k2 = self.slots.resolve_batch(
                [cols.key_bytes(int(j)) for j in retry_src]
            )
            slots[retry] = s2
            known[retry] = k2
            if (slots < 0).any():
                # Graceful degradation: a truly full table (reclaim freed
                # nothing — e.g. every slot is pending in this very batch)
                # sheds the unplaceable items with per-item errors instead
                # of failing the whole batch (the reference's
                # error-in-item convention, gubernator.go:208-216); the
                # rest of the batch is still served.
                shed = np.flatnonzero(slots < 0)
                shed_src = shed if sel is None else sel[shed]
                for j in shed_src:
                    errors[int(j)] = "rate-limit table full; eviction failed"
                self.metric_shed_requests += len(shed)
                keep = slots >= 0
                sel = (
                    np.flatnonzero(keep)
                    if sel is None
                    # guber: allow-G001(sel is host numpy, never device)
                    else np.asarray(sel)[keep]
                ).astype(np.int64)
                slots = slots[keep]
                known = known[keep]
                if len(slots) == 0:
                    return m, n, errors, np.arange(n, dtype=np.int64), False
        self._last_access[slots] = self._tick_count
        miss = known == 0
        self._pending.update(slots[miss].tolist())
        n_miss = int(miss.sum())
        self.metric_hits += len(miss) - n_miss
        self.metric_misses += n_miss
        if n_miss:
            # Insert pressure near a full table: reclaim in the background
            # so the dead-scan/argpartition never lands on a serving tick.
            self._maybe_trigger_reclaim()

        if self.cold is not None and miss.any():
            miss = self._promote_misses(cols, sel, slots, known, miss, now)

        if self.store is not None and miss.any():
            if cols.refs is None:
                raise ValueError(
                    "Store read-through needs request objects; build the "
                    "batch with ReqColumns.from_requests(..., keep_refs=True)"
                )
            rt_sel = np.arange(n, dtype=np.int64) if sel is None else sel
            self._read_through(cols.refs, rt_sel, slots, known, miss)

        # Vectorized pack: plain slices on the (typical) no-error batch,
        # fancy-indexed writes when error rows must be skipped.  Narrow
        # fields write one i32 row; 8-byte fields write (lo, hi) pairs
        # (pack_wide_rows) — the compact wire format unpack_reqs_compact
        # reads on device.
        ix = slice(0, n) if sel is None else sel
        pack_cols_req32(m, cols, slots, known, now, ix)
        # Sort the batch by slot (stable: same-slot requests keep arrival
        # order, the duplicate-sequencing contract).  The tick's
        # sorted-input path then does all segment math with neighbor
        # compares + scans — a host argsort here is ~100x cheaper than
        # the device-side gathers/scatters it replaces.  Error rows
        # (slot=capacity) sort to the end with the padding; sorted
        # neighbors then reveal duplicate slots for free (unique batches
        # dispatch to the parts-native program, duplicate-bearing ones
        # to the merge-capable program).
        inv, has_dups = sort_packed_by_slot(m, n, self.capacity)
        return m, n, errors, inv, has_dups

    @hot_path
    def _promote_misses(
        self, cols: ReqColumns, sel, slots, known, miss, now: int
    ) -> np.ndarray:
        """Consult the cold tier for this batch's misses and batch-reinstall
        the hits via ONE restore scatter before the tick runs — the
        promote half of the tiering flow (docs/tiering.md).  Promotion is
        a move: the cold tier drops its copy, the device row becomes the
        owner, and the request proceeds as a *known* slot so the bucket
        keeps its consumed budget (no fresh-bucket bypass).  Returns the
        updated miss mask (read-through only sees what stayed cold-miss).

        Duplicate keys in one batch resolve to one miss row (the slot
        map marks later occurrences known), so hit rows map to unique
        slots and the single scatter has no write conflicts.

        With an SSD tier attached, keys that also miss cold take one
        more hop — ONE batched ``take_batch`` against the slab store per
        tick (never per key; the bench gates the ratio) — and its hits
        merge into the same scatter, so the promote dispatch count is
        unchanged by the third tier.  The SSD read seconds are recorded
        as the flight recorder's "ssd" stage and subtracted from "pack"
        (which brackets all of _build_cols), keeping the tick/pack
        stages clean of SSD I/O by construction."""
        midx = np.flatnonzero(miss)
        # guber: allow-G001(sel is host numpy, never device)
        src = midx if sel is None else np.asarray(sel)[midx]
        keys = [cols.key_bytes(int(j)) for j in src]
        pos, ccols = self.cold.take(keys, now)
        self.metric_cold_hits += len(pos)
        if self.ssd is not None and len(pos) < len(midx):
            cold_hit = np.zeros(len(midx), bool)
            if len(pos):
                cold_hit[pos] = True
            rem = np.flatnonzero(~cold_hit)
            fr = flightrec.get()
            t0 = time.perf_counter() if fr is not None else 0.0
            spos, scols = self.ssd.take_batch(
                [keys[int(j)] for j in rem], now
            )
            if fr is not None:
                dt = time.perf_counter() - t0
                wid = fr.active()
                fr.note(wid, "ssd", dt)
                fr.note(wid, "pack", -dt)
            self.metric_ssd_lookups += 1
            self.metric_ssd_miss_ticks += 1
            if len(spos):
                self.metric_ssd_hits += len(spos)
                srows = rem[spos]
                if len(pos):
                    pos = np.concatenate([pos, srows])
                    ccols = {
                        f: np.concatenate([ccols[f], scols[f]])
                        for f in scols
                    }
                else:
                    pos, ccols = srows, scols
        if len(pos) == 0:
            return miss
        hit_rows = midx[pos]
        known[hit_rows] = 1
        hit_slots = slots[hit_rows]
        # The restore lands the device rows right here, so these slots
        # are live (in_use set) before the tick — no longer pending.
        self._pending.difference_update(int(s) for s in hit_slots)
        self._dirty[hit_slots] = True
        # One batched scatter for the whole tick's promotions (chunked
        # only past RESTORE_CHUNK, which a ≤max_batch tick never is).
        for start in range(0, len(hit_rows), RESTORE_CHUNK):
            part = slice(start, start + RESTORE_CHUNK)
            k = len(hit_slots[part])
            w = pad_pow2(k)
            ints = np.zeros((len(ITEM_INT_ROWS), w), np.int64)
            floats = np.zeros(w, np.float64)
            ints[0, :k] = hit_slots[part]
            for r, name in enumerate(ITEM_INT_ROWS[1:-1], start=1):
                ints[r, :k] = ccols[name][part]
            ints[-1, :k] = 1  # valid
            floats[:k] = ccols["remaining_f"][part]
            self.state = self._restore(
                self.state, jnp.asarray(ints), jnp.asarray(floats)
            )
            self.metric_promote_dispatches += 1
        self.metric_promote_ticks += 1
        self.metric_promotions += len(hit_rows)
        return known == 0

    def _read_through(self, requests, sel, slots, known, miss) -> None:
        """Store.Get for cache misses (algorithms.go:45-51): install the
        persisted items so the kernel sees existing buckets."""
        restore_rows: List[tuple] = []
        restored: set = set()
        for j in np.flatnonzero(miss):
            slot = int(slots[j])
            if slot in restored:
                known[j] = 1
                continue
            item = self.store.get(requests[sel[j]])
            if item is None:
                continue
            restored.add(slot)
            known[j] = 1
            self._pending.discard(slot)
            restore_rows.append(
                (
                    (slot, item["algorithm"], item["limit"], item["remaining"],
                     item["duration"], item["created_at"], item["updated_at"],
                     item["burst"], item["status"], item["expire_at"],
                     item.get("tat", 0), item.get("prev_count", 0), 1),
                    item.get("remaining_f", 0.0),
                )
            )
        if restore_rows:
            w = pad_pow2(len(restore_rows))
            ints = np.zeros((len(ITEM_INT_ROWS), w), np.int64)
            floats = np.zeros(w, np.float64)
            for j, (row, rf) in enumerate(restore_rows):
                ints[:, j] = row
                floats[j] = rf
            self.state = self._restore(
                self.state, jnp.asarray(ints), jnp.asarray(floats)
            )

    # ------------------------------------------------------------------
    # The tick
    # ------------------------------------------------------------------
    @hot_path
    def submit_columns(
        self, cols: ReqColumns, now: Optional[int] = None
    ) -> "TickHandle":
        """Build + dispatch one tick (≤ max_batch rows) and return a handle.

        Device work (H2D, tick, response buffer) is *queued*, not awaited —
        the caller materializes via :meth:`TickHandle.result`, so host
        packing of the next tick overlaps device execution of this one
        (the double-buffering SURVEY §7 calls for; the round-2 engine
        serialized pack → dispatch → blocking D2H and paid the sum).

        With a Store attached the handle is resolved before return (the
        write-through readback must observe exactly this tick's state, so
        no later tick may be dispatched first).
        """
        with self._lock:
            now = now if now is not None else timeutil.now_ms()
            self._last_now = max(self._last_now, now)
            self._tick_count += 1
            # Flight-recorder stage notes (docs/observability.md): "pack"
            # covers slot resolve + matrix fill + argsort (the lease is
            # also broken out inside _lease_matrix); "h2d" the queued
            # device dispatch below.
            fr = flightrec.get()
            t_pack = time.perf_counter() if fr is not None else 0.0
            packed, n, errors, inv, has_dups = self._build_cols(cols, now)
            if fr is not None:
                fr.note(fr.active(), "pack", time.perf_counter() - t_pack)
            dev_m = None
            # Named range in XProf captures (utils/tracing.py): device
            # tick vs host packing shows up separated in the profile.
            plan = (
                build_group_plan(packed, n, self.capacity, now)
                if has_dups else None
            )
            t_h2d = time.perf_counter() if fr is not None else 0.0
            # Structural tick-path evidence: any SSD lookup issued while
            # the tick-dispatch block below runs would land in this
            # delta.  _build_cols (the only legitimate lookup site) has
            # already returned, so the counter stays 0 by construction —
            # and the bench gate keeps it that way.
            ssd_reads0 = (
                self.ssd.metric_lookup_calls if self.ssd is not None else 0
            )
            with tracing.profile_annotation("guber.tick"):
                if plan is not None:
                    # Grouped tick: unique heads through the parts
                    # program (fold on device), member responses from
                    # the elementwise expansion — a k-deep hot key costs
                    # one row of HBM traffic, not k.
                    mhead, count, uidx, rank, _ = plan
                    self.state, resp = self._tick32m(
                        self.state, jnp.asarray(mhead),
                        jnp.asarray(count), jnp.asarray(uidx),
                        jnp.asarray(rank), jnp.int64(now),
                    )
                elif has_dups:
                    # Layered dispatch is gated to serving-scale engines
                    # (same threshold as the grouped warmup): each
                    # (w0, k_pad) shape is a real XLA compile, and small
                    # test-cluster engines churning capacities would pay
                    # a compile storm for batches the sequential program
                    # already handles in a round or two.
                    lplan = (
                        build_layer_plan(packed, n, self.capacity, now)
                        if self.capacity >= (1 << 14) else None
                    )
                    if lplan is not None:
                        # Mixed groups with a host layer plan: one
                        # narrow merged tick per unit layer, chained
                        # through the table (tick32.
                        # jitted_layered_pipeline) — K narrow ticks
                        # instead of one full round per unit.
                        from gubernator_tpu.ops.tick32 import (
                            jitted_layered_pipeline,
                        )

                        mh0, cnt0, mhk, cntk, uidx, rank, kpad = lplan
                        self.metric_layered_ticks += 1
                        fn = jitted_layered_pipeline(
                            self.capacity, self.layout, mh0.shape[1], kpad
                        )
                        dev_m = jnp.asarray(packed)
                        self.state, resp = fn(
                            self.state, jnp.asarray(mh0),
                            jnp.asarray(cnt0), jnp.asarray(mhk),
                            jnp.asarray(cntk), dev_m,
                            jnp.asarray(uidx), jnp.asarray(rank),
                            jnp.int64(now),
                        )
                    else:
                        # Adversarial shapes (over-deep/over-wide unit
                        # structure, unprovable head liveness): the
                        # sequential chained-unit program is always
                        # correct.
                        dev_m = jnp.asarray(packed)
                        self.state, resp = self._tick(
                            self.state, dev_m, jnp.int64(now)
                        )
                else:
                    # The common serving shape: the upload is an ASYNC
                    # host→device copy (jnp.asarray of a numpy buffer
                    # queues the transfer and returns; jax may read the
                    # host slab until it completes — the staging ring
                    # above guarantees it stays stable), so this
                    # window's H2D overlaps the previous window's
                    # still-running tick.  Deliberately asarray, not a
                    # committed device_put: a committed sharding is a
                    # new jit signature and re-traces every warmed
                    # program once per width (measured ~0.6 s each on
                    # the CPU suite).
                    dev_m = jnp.asarray(packed)
                    self.state, resp = self._tick32(
                        self.state, dev_m, jnp.int64(now)
                    )
            if fr is not None:
                fr.note(fr.active(), "h2d", time.perf_counter() - t_h2d)
            if self.ssd is not None:
                self.metric_ssd_tick_path_reads += (
                    self.ssd.metric_lookup_calls - ssd_reads0
                )
            self._pending.clear()
            tick_slots = packed[REQ32_INDEX["slot"], :n]
            # Dirty marking feeds export_columns(dirty_only=True); pure
            # queries — hits == 0 on a known slot, no RESET_REMAINING —
            # read bucket state without moving it, so marking them would
            # inflate deltas under read-heavy traffic (advisor finding).
            # Unknown slots always mark (the tick creates the row), as
            # does RESET (removal/refill).  A leaky-bucket query can
            # refill tokens on device, but the refill is derived from
            # (updated_at, now) and recomputes identically after a
            # baseline+delta restore, so skipping it loses nothing.
            hr = REQ32_INDEX["hits"]
            mutating = (
                (packed[hr, :n] != 0)
                | (packed[hr + 1, :n] != 0)
                | (packed[REQ32_INDEX["known"], :n] == 0)
                | ((packed[REQ32_INDEX["behavior"], :n]
                    & int(Behavior.RESET_REMAINING)) != 0)
            )
            mut_slots = tick_slots[mutating & (tick_slots < self.capacity)]
            if len(mut_slots):
                self._dirty[mut_slots] = True
            slots_req = (
                packed[REQ32_INDEX["slot"], :n][inv].astype(np.int64)
                if self.store is not None
                else None
            )
            handle = TickHandle(
                self, resp, n, inv, errors, cols.refs, slots_req,
                limit_req=cols.limit,
            )
            # Overlap telemetry + slab retirement: this window's upload
            # was dispatched while `_inflight` earlier windows were
            # still unresolved (their ticks run while our bytes move).
            self.metric_h2d_windows += 1
            if self._inflight > 0:
                self.metric_h2d_overlapped += 1
            self._inflight += 1
            # The slab recycles once this tick resolves; grouped ticks
            # never uploaded it (dev_m is None) and free it for the very
            # next lease.
            self._staging.retire(handle if dev_m is not None else None)
            if self.store is not None:
                handle.result()
            return handle

    @hot_path
    def submit_cols(
        self, cols: ReqColumns, now: Optional[int] = None
    ) -> SubmittedBatch:
        """Dispatch a columnar batch of any width without awaiting the
        device (chunked into max_batch ticks; chunk k+1 packs while chunk
        k executes).  Resolve via ``.matrix()`` / ``.responses()``."""
        n = len(cols)
        now = now if now is not None else timeutil.now_ms()
        spans = [
            (s, min(s + self.max_batch, n))
            for s in range(0, n, self.max_batch)
        ]
        handles = [
            self.submit_columns(
                cols if len(spans) == 1 else cols.slice_chunk(s, e), now
            )
            for s, e in spans
        ]
        return SubmittedBatch(handles, spans, n)

    def process_columns(
        self, cols: ReqColumns, now: Optional[int] = None
    ) -> tuple[np.ndarray, Dict[int, str]]:
        """Apply a columnar batch; returns the (5, n) response matrix in
        request order (rows: status, limit, remaining, reset_time,
        over_limit) plus per-item errors."""
        if len(cols) == 0:
            return np.zeros((5, 0), np.int64), {}
        return self.submit_cols(cols, now).matrix()

    @hot_path
    def submit(
        self, requests: Sequence[RateLimitRequest], now: Optional[int] = None
    ) -> SubmittedBatch:
        """Dispatch an object-level batch without awaiting the device: the
        tick loop's pipelining hook (resolve via ``.responses()`` on a
        reader thread while this thread packs the next window)."""
        return self.submit_cols(
            ReqColumns.from_requests(
                requests, keep_refs=self.store is not None
            ),
            now,
        )

    def process(
        self, requests: Sequence[RateLimitRequest], now: Optional[int] = None
    ) -> List[RateLimitResponse]:
        """Apply a batch of requests; returns responses in request order
        (the dataclass API edge over the columnar path)."""
        if not requests:
            return []
        return self.submit(requests, now).responses()

    def _write_through(
        self, requests: Sequence[RateLimitRequest], slots: np.ndarray,
        n: int, errors: Dict[int, str],
    ) -> None:
        """Store.OnChange with each touched slot's post-tick state
        (write-through, algorithms.go:149-153).  A slot cleared by the tick
        (RESET_REMAINING removal) maps to Store.remove instead, matching the
        reference's remove-on-reset (algorithms.go:78-90).  ``slots`` is in
        request order (process() un-permutes the sorted batch)."""
        # Pad to a power of two so this per-tick hot path compiles a handful
        # of widths, not one per batch size; padding slots aim out of range
        # (zero-fill on columns, guard-row garbage on rows) and rows past n
        # are never read host-side.
        padded = np.full(pad_pow2(max(1, n)), self.capacity, np.int64)
        padded[:n] = slots
        ints, floats = self._readback(self.state, jnp.asarray(padded))
        ints = np.asarray(ints)
        floats = np.asarray(floats)
        seen: set = set()
        for i in range(n):
            if i in errors:
                continue
            slot = int(slots[i])
            if slot in seen:
                continue  # duplicate key in batch: one OnChange, final state
            seen.add(slot)
            key = self.slots.key_of(slot)
            if key is None:
                continue
            f = dict(zip(READBACK_ROWS, ints[:, i]))
            if not f["in_use"]:
                self.store.remove(key)
                continue
            self.store.on_change(
                requests[i],
                {
                    "key": key,
                    "algorithm": int(f["algorithm"]),
                    "limit": int(f["limit"]),
                    "remaining": int(f["remaining"]),
                    "remaining_f": float(floats[i]),
                    "duration": int(f["duration"]),
                    "created_at": int(f["created_at"]),
                    "updated_at": int(f["updated_at"]),
                    "burst": int(f["burst"]),
                    "status": int(f["status"]),
                    "expire_at": int(f["expire_at"]),
                    "tat": int(f["tat"]),
                    "prev_count": int(f["prev_count"]),
                },
            )

    def install_globals(
        self, updates: Sequence[GlobalUpdate], now: Optional[int] = None
    ) -> None:
        """Install owner-pushed GLOBAL state (UpdatePeerGlobals receive path,
        gubernator.go:425-459).  Writes land on device immediately (no tick),
        so installed slots are live the moment this returns."""
        if not updates:
            return
        with self._lock:
            now = now if now is not None else timeutil.now_ms()
            # New logical tick: without this, slots touched by the *previous*
            # tick still satisfy the "touched this tick" reclaim guard and
            # LRU eviction can't free anything.
            self._tick_count += 1
            # Dict keyed by slot: duplicate keys in one push dedup to the
            # LAST update (install order), which the row layout requires —
            # two concurrent row DMAs to one slot are a data race
            # (rowtable.scatter_rows) — and the column path's sequential
            # scatter resolved the same way.
            by_slot: Dict[int, tuple] = {}
            for u in updates:
                try:
                    slot, _ = self._resolve_slot(u.key, now)
                except RuntimeError:
                    continue  # table full; drop (the next broadcast retries)
                self._last_access[slot] = self._tick_count
                self._pending.discard(slot)  # device write happens right here
                by_slot[slot] = (
                    slot, u.algorithm, u.status.limit, u.status.remaining,
                    u.status.status, u.duration, u.status.reset_time, 1,
                )
            if not by_slot:
                return
            self._dirty[list(by_slot)] = True
            rows = list(by_slot.values())
            # Width-chunked like load_items: the row layout stages the
            # batch in VMEM, so one huge push must not compile one huge
            # program.
            for start in range(0, len(rows), RESTORE_CHUNK):
                part = rows[start : start + RESTORE_CHUNK]
                cols = np.zeros((8, pad_pow2(len(part))), np.int64)
                cols[:, : len(part)] = np.array(part, np.int64).T
                self.state = self._install(
                    self.state, jnp.asarray(cols), jnp.int64(now)
                )

    # ------------------------------------------------------------------
    # Snapshot / restore (Loader.Load/Save analog, workers.go:329-534)
    # ------------------------------------------------------------------
    @hot_path
    def lease_window(
        self,
        keys: Sequence[bytes],
        budgets: Sequence[int],
        expires: Sequence[int],
        gens: Sequence[int],
        is_set: bool = True,
    ) -> int:
        """Apply one window of quota-lease column mutations as ONE
        batched device scatter (docs/leases.md).

        ``is_set=True`` installs authoritative (outstanding, expiry,
        generation) triples — the grant/sync commit path; ``False``
        applies reconcile deltas (budget += delta clamped ≥ 0,
        expiry/generation monotone).  Keys not resident in the hot table
        are skipped — the LeaseManager's host records stay authoritative
        and re-mirror on the next window that finds the slot.  Returns
        the number of column updates applied; exactly one device
        dispatch regardless (metric_lease_dispatches/windows is the
        exact-work invariant the lease tests pin at 1.0)."""
        n = len(keys)
        if n == 0:
            return 0
        with self._lock:
            get = self.slots.get
            slots = np.full(n, self.capacity, np.int64)
            for j in range(n):
                s = get(keys[j].decode())
                if s is not None:
                    slots[j] = s
            live = slots < self.capacity
            w = pad_pow2(n)
            slot_pad = np.full(w, self.capacity, np.int64)
            slot_pad[:n] = slots
            bud = np.zeros(w, np.int64)
            bud[:n] = budgets
            exp = np.zeros(w, np.int64)
            exp[:n] = expires
            gen = np.zeros(w, np.int32)
            gen[:n] = gens
            fn = _jitted_lease_apply(is_set)
            self._lease_budget, self._lease_expire, self._lease_gen = fn(
                self._lease_budget, self._lease_expire, self._lease_gen,
                jnp.asarray(slot_pad), jnp.asarray(bud), jnp.asarray(exp),
                jnp.asarray(gen),
            )
            self.metric_lease_dispatches += 1
            self.metric_lease_windows += 1
            applied = int(live.sum())
            self.metric_lease_ops += applied
            self._dirty[slots[live]] = True
            return applied

    def lease_columns(self, keys: Sequence[bytes]):
        """Host readback of the lease columns for a batch of keys:
        (budget, expire_ms, generation) int64/int64/int32 arrays, zeros
        for non-resident keys.  Diagnostics/tests only — the serving
        path never reads these back."""
        n = len(keys)
        with self._lock:
            get = self.slots.get
            slots = np.full(n, -1, np.int64)
            for j in range(n):
                s = get(keys[j].decode())
                if s is not None:
                    slots[j] = s
            live = slots >= 0
            bud = np.zeros(n, np.int64)
            exp = np.zeros(n, np.int64)
            gen = np.zeros(n, np.int32)
            if live.any():
                idx = jnp.asarray(slots[live])
                bud[live] = np.asarray(self._lease_budget[idx])
                exp[live] = np.asarray(self._lease_expire[idx])
                gen[live] = np.asarray(self._lease_gen[idx])
            return bud, exp, gen

    def export_columns(self, dirty_only: bool = False) -> dict:
        """Bulk snapshot: numpy columns + one key blob (the Loader v2
        format; see SNAP_FIELDS).  The reference streams items through a
        channel (store.go:69-78); the columnar analog of that stream is
        arrays.

        Transfer discipline (verdict r3 #7): only LIVE slots cross the
        link, as int32 words, and only the words a per-chunk device probe
        proves necessary — hi words that are sign extensions of their lo
        (values < 2^31: limits, remainings, sub-25-day durations) are
        dropped, constant hi words (epoch-ms columns inside one ~50-day
        window) become one host scalar, and algorithm/status/in_use pack
        into a single word.  Typical cost: 44 B/item instead of the full
        table's 80 B/slot.  Chunks pipeline: while chunk i drains over
        the link, chunk i+1's gather/probe runs on device.
        ``last_export_stats`` records what actually crossed.

        ``dirty_only=True`` exports only the slots mutated since the
        previous export (any kind): the incremental path — a delta moves
        bytes proportional to the touched working set, not the table
        (the reference's Store OnChange design trickles the same way,
        store.go:49-65).  Deltas are ordinary (smaller) snapshots:
        ``load_columns`` applies them as upserts, so delta files append
        to a full baseline.  Removals are only partially reproduced:
        TTL-expired rows fall out at load time via the expire_at filter
        (like the reference's persisted-but-expired items), but an
        unexpired LRU *eviction* is not represented — a baseline+delta
        restore can resurrect keys the source engine evicted to make
        room.  That matches upsert-trickle semantics (the reference's
        OnChange stream carries no deletions either, store.go:49-65);
        restores needing eviction fidelity should take a full export.
        Every export (full or delta) resets the dirty set."""
        with self._lock:
            mask = self.slots.mapped_mask()
            if dirty_only:
                mask &= self._dirty
            mapped = np.flatnonzero(mask)
            self._dirty[:] = False
            n = len(mapped)
            empty = {
                "key_blob": b"",
                "key_offsets": np.zeros(1, np.int64),
                **{
                    f: np.zeros(
                        0, np.float64 if f == "remaining_f" else np.int64
                    )
                    for f in SNAP_FIELDS
                },
                **{f: np.zeros(0, np.int64) for f in ZOO_SNAP_FIELDS},
                **{f: np.zeros(0, np.int64) for f in LEASE_SNAP_FIELDS},
            }
            if n == 0:
                self.last_export_stats = {
                    "d2h_bytes": 0, "items": 0, "partial": dirty_only}
                return self._export_with_cold(empty, dirty_only)
            w = SNAP_CHUNK if n > SNAP_CHUNK else pad_pow2(n)
            wide_fn = _jitted_snap_wide(self.layout)
            probe_fn = _jitted_snap_probe()
            d2h = 0
            parts: List[np.ndarray] = []
            chunks: List[dict] = []
            prev = None
            for start in range(0, n, w):
                part = mapped[start : start + w]
                k = len(part)
                slots_pad = np.full(w, part[0], np.int32)
                slots_pad[:k] = part
                wide = wide_fn(self.state, jnp.asarray(slots_pad))
                probe = np.asarray(probe_fn(wide))
                hi_mask = tuple(
                    not (bool(probe[i, 0]) or probe[i, 1] == probe[i, 2])
                    for i in range(len(SNAP_WIDE))
                )
                sel = _jitted_snap_select(hi_mask)(wide)
                del wide
                d2h += probe.nbytes + int(np.prod(sel.shape)) * 4
                if prev is not None:
                    p, cols = _snap_decode(
                        prev[0], prev[1], prev[2], prev[3],
                        np.asarray(prev[4]),
                    )
                    parts.append(p)
                    chunks.append(cols)
                prev = (part, k, probe, hi_mask, sel)
            p, cols = _snap_decode(
                prev[0], prev[1], prev[2], prev[3], np.asarray(prev[4])
            )
            parts.append(p)
            chunks.append(cols)
            live = np.concatenate(parts)
            if len(live) == 0:
                self.last_export_stats = {
                    "d2h_bytes": d2h, "items": 0, "partial": dirty_only}
                return self._export_with_cold(empty, dirty_only)
            blob, offsets = self.slots.keys_blob(live)
            snap: dict = {"key_blob": blob, "key_offsets": offsets}
            # The zoo columns decode from the same chunks (they sit in
            # SNAP_WIDE) and export as extra keys beside SNAP_FIELDS.
            for name in SNAP_FIELDS + ZOO_SNAP_FIELDS:
                snap[name] = np.concatenate([c[name] for c in chunks])
            # Lease columns ride as extra snapshot keys gathered at the
            # same live slots (order-aligned with the key blob).  One
            # device gather per column per export, not per chunk: the
            # lease columns are narrow (24 B/slot total), so the slim
            # probe/select machinery isn't worth threading them through.
            lidx = jnp.asarray(live)
            snap["lease_budget"] = np.array(self._lease_budget[lidx])
            snap["lease_expire"] = np.array(self._lease_expire[lidx])
            snap["lease_gen"] = np.array(
                self._lease_gen[lidx], dtype=np.int64)
            self.last_export_stats = {
                "d2h_bytes": d2h,
                "items": len(live),
                "bytes_per_item": round(d2h / max(len(live), 1), 1),
                "partial": dirty_only,
            }
            return self._export_with_cold(snap, dirty_only)

    def _export_with_cold(self, snap: dict, dirty_only: bool) -> dict:
        """Append the cold tier's (dirty) entries to a columnar snapshot:
        demoted state is still cached state and must survive a Loader
        save/restore cycle (docs/tiering.md).  Hot and cold are disjoint
        by construction (promotion is a move), so the merge is a plain
        concatenation — no dedup pass."""
        if self.cold is None:
            return snap
        ckeys, ccols = self.cold.export_columns(dirty_only)
        if not ckeys:
            return snap
        from gubernator_tpu.ops.reqcols import pack_blob

        blob2, offs2 = pack_blob(ckeys)
        off1 = np.asarray(snap["key_offsets"], np.int64)
        base = int(off1[-1]) if len(off1) else 0
        snap["key_blob"] = bytes(snap["key_blob"]) + blob2
        snap["key_offsets"] = np.concatenate([off1, offs2[1:] + base])
        for f in SNAP_FIELDS + ZOO_SNAP_FIELDS:
            # The cold tier stores the zoo columns too (COLD_FIELDS),
            # so demoted zoo state survives the round trip.
            snap[f] = np.concatenate([np.asarray(snap[f]), ccols[f]])
        for f in LEASE_SNAP_FIELDS:
            # Cold rows hold no delegation (demotion targets idle slots;
            # leases live on hot, recently-granted keys): zero-pad so the
            # lease columns stay aligned with the merged key blob.
            if f in snap:
                snap[f] = np.concatenate([
                    np.asarray(snap[f]),
                    np.zeros(len(ckeys), np.int64),
                ])
        self.last_export_stats["items"] = (
            self.last_export_stats.get("items", 0) + len(ckeys)
        )
        self.last_export_stats["cold_items"] = len(ckeys)
        return snap

    def export_items(self) -> List[dict]:
        """Drain live bucket state to host dicts (the dict-shaped Loader
        API edge over :meth:`export_columns`)."""
        return items_from_snapshot(self.export_columns())

    def load_columns(self, snap: dict, now: Optional[int] = None) -> None:
        """Bulk restore from a columnar snapshot (see export_columns).

        Expired rows are dropped with a vectorized blob compaction; one
        native blob-assign maps every key; duplicate keys dedup to their
        LAST occurrence (install order — the row layout's one-DMA-per-slot
        contract); the data lands in RESTORE_CHUNK-wide jitted scatters.
        """
        with self._lock:
            now = now if now is not None else timeutil.now_ms()
            self._last_now = max(self._last_now, now)
            self._tick_count += 1  # see install_globals: unblock LRU reclaim
            offsets = np.asarray(snap["key_offsets"], np.int64)
            n = len(offsets) - 1
            if n == 0:
                return
            cols = {f: np.asarray(snap[f]) for f in SNAP_FIELDS}
            # Pre-zoo snapshots lack the zoo state columns: restore them
            # as zeros — a fresh window/TAT, the safe reading (see
            # ZOO_SNAP_FIELDS).
            for f in ZOO_SNAP_FIELDS:
                cols[f] = (
                    np.asarray(snap[f]) if f in snap
                    else np.zeros(n, np.int64)
                )
            # Pre-lease snapshots simply lack the lease keys: restore
            # them as no-delegation (zeros) rather than failing.
            has_lease = all(f in snap for f in LEASE_SNAP_FIELDS)
            if has_lease:
                for f in LEASE_SNAP_FIELDS:
                    cols[f] = np.asarray(snap[f])
            blob = snap["key_blob"]
            keep = cols["expire_at"] >= now
            if not keep.all():
                blob, offsets = compact_blob(blob, offsets, keep)
                cols = {f: c[keep] for f, c in cols.items()}
                n = int(keep.sum())
                if n == 0:
                    return
            shortfall = len(self.slots) + n - self.capacity
            if shortfall > 0:
                self._reclaim(now, want=shortfall)
            slots = self.slots.assign_blob(blob, offsets)
            if self.cold is not None and (slots < 0).any():
                # Full table: the overflow tail lands in the cold tier
                # instead of being dropped — a restore bigger than the
                # device table keeps the whole working set (the miss
                # path promotes rows back as traffic touches them).
                over = np.flatnonzero(slots < 0)
                offsets = np.asarray(offsets, np.int64)
                self.cold.put_columns(
                    [bytes(blob[offsets[j] : offsets[j + 1]]) for j in over],
                    {f: cols[f][over] for f in SNAP_FIELDS + ZOO_SNAP_FIELDS},
                    now,
                )
            sel = np.flatnonzero(slots >= 0)  # full table: drop the tail
            if len(sel) == 0:
                return
            # Last-wins dedup by slot (same key → same slot): reverse +
            # first-unique keeps each slot's final occurrence.
            s = slots[sel]
            _, ridx = np.unique(s[::-1], return_index=True)
            sel = sel[len(s) - 1 - ridx]
            self._last_access[slots[sel]] = self._tick_count
            self._dirty[slots[sel]] = True
            # Chunked like evict_chunked: one restore per RESTORE_CHUNK
            # keeps the compiled width bounded — the row layout stages
            # the batch in VMEM (512 B/row), so a multi-million-item
            # snapshot in one call would not even compile.
            for start in range(0, len(sel), RESTORE_CHUNK):
                part = sel[start : start + RESTORE_CHUNK]
                k = len(part)
                w = pad_pow2(k)
                ints = np.zeros((len(ITEM_INT_ROWS), w), np.int64)
                floats = np.zeros(w, np.float64)
                ints[0, :k] = slots[part]
                for r, name in enumerate(ITEM_INT_ROWS[1:-1], start=1):
                    ints[r, :k] = cols[name][part]
                ints[-1, :k] = 1  # valid
                floats[:k] = cols["remaining_f"][part]
                self.state = self._restore(
                    self.state, jnp.asarray(ints), jnp.asarray(floats)
                )
            if has_lease:
                # Restore the lease columns with one host read-modify-
                # write + push per column: restores are rare (startup,
                # failover) and the columns are narrow, so clarity beats
                # a fourth jitted scatter here.
                tgt = slots[sel]
                lb = np.array(self._lease_budget)
                le = np.array(self._lease_expire)
                lg = np.array(self._lease_gen)
                lb[tgt] = cols["lease_budget"][sel]
                le[tgt] = cols["lease_expire"][sel]
                lg[tgt] = cols["lease_gen"][sel]
                self._lease_budget = jnp.asarray(lb)
                self._lease_expire = jnp.asarray(le)
                self._lease_gen = jnp.asarray(lg)

    def load_items(self, items: Sequence[dict], now: Optional[int] = None) -> None:
        """Install snapshot items into the table (the dict-shaped Loader
        API edge: one pass builds the columnar snapshot, then
        :meth:`load_columns` does the real work)."""
        items = list(items)
        if not items:
            return
        self.load_columns(snapshot_from_items(items), now=now)

    def cache_size(self) -> int:
        return len(self.slots)

    def cold_size(self) -> int:
        """Entries currently held by the cold tier (0 when tiering is
        disabled) — the occupancy gauge's second axis."""
        return 0 if self.cold is None else len(self.cold)

    def hot_occupancy(self) -> float:
        """Fraction of device slots holding a mapped key (0.0–1.0)."""
        return len(self.slots) / self.capacity if self.capacity else 0.0

    def h2d_overlap_ratio(self) -> float:
        """Fraction of windows whose request upload was dispatched while
        an earlier window's tick was still unresolved — 0.0 for fully
        serial submission, →1.0 when the pipeline keeps the H2D of
        window N+1 riding under window N's device tick (the
        double-buffered steady state the bench ladder gates)."""
        return self.metric_h2d_overlapped / max(1, self.metric_h2d_windows)
