"""Ragged device-side window walking: each shard consumes only its own
``[offset, offset + count)`` extent of the flat slot-sorted batch.

The routed mesh path (PR 7) compacted the replicated flat (19, B)
request matrix into a padded (19, local_width) block per shard
(partition.route_block) and fell back to a host-blocked packer whenever
a window's per-shard skew exceeded ``local_width`` — but Zipf-skewed
traffic is the *normal* case at scale, so the fast path degraded exactly
when load concentrated.  Ragged Paged Attention (PAPERS.md, arXiv
2604.15464) shows the TPU-native shape: keep the flat matrix, add a
per-block row-count vector, and iterate ragged extents directly.

The flat matrix is already slot-sorted by GLOBAL slot
(engine.sort_packed_by_slot), and ownership is ``slot //
local_capacity`` — so each shard's rows form one CONTIGUOUS extent of
the batch, and the host (which computed the per-shard counts during
resolve) ships a cumulative ``offsets`` vector alongside the matrix.
No compaction, no padding lanes, no skew fallback: every per-shard
width is served by ONE fixed-shape program per batch capacity.

Three entry points, all sharing the extent/masking arithmetic:

* :func:`choose_tile` — the static tile width the XLA walker strides
  the extent with (~B/n, 64-lane quantized).
* :func:`ragged_walk` — the XLA extent walker wrapped around any
  single-chip tile tick (the merge-capable x64 program, or the unfused
  int32 parts program on CPU): a ``fori_loop`` over the extent's
  dynamic tile count, each tile clamped into the batch and masked so
  out-of-extent lanes become guard rows (slot = local_capacity,
  valid = 0), responses merged read-modify-write into a zeroed flat
  buffer so the cross-shard gather stays one exact ``psum``.
* :func:`make_fused_ragged_tick_fn` — the Pallas kernel (row layout):
  fusedtick's gather-DMA → in-register transition → scatter-DMA ring,
  with the chunk count now a *runtime* scalar (prefetched alongside the
  slots) so one compiled program serves every extent length.  Tail
  chunks clamp into the batch and aim their masked lanes' DMAs at the
  guard row; the response buffer zero-fills first, then each chunk
  merges its live lanes in place.

Masking guarantees (why clamped tiles are safe): a clamped tile
re-reads lanes the previous tile already served, but those lanes are
masked to guard rows — the tick scatters them at ``local_capacity``
(dropped / guard garbage by contract) and the response merge keeps the
previously-written value, so no lane is double-applied.  A duplicate
run split across two tiles is two *sequential* ticks of the same slot
(the state carry between tiles), which is exactly the merge program's
sequential-application semantics.

Reference semantics bar: algorithms.go:37-493 (via transition32).
"""

from __future__ import annotations

import functools

import gubernator_tpu.jaxinit  # noqa: F401  (x64 + compile cache before jax use)
import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from gubernator_tpu.ops.engine import REQ32_INDEX, REQ32_ROWS
from gubernator_tpu.ops.fusedtick import (
    TW,
    _VMEM,
    _preq_from_rows,
    _pstate_from_T,
    _pstate_to_T,
    _transpose_bwd,
    _transpose_fwd,
)
from gubernator_tpu.ops.i64pair import I64
from gubernator_tpu.ops.rowtable import ROW_W, _interpret
from gubernator_tpu.ops.transition32 import transition32
from gubernator_tpu.utils import jaxcompat

I32 = jnp.int32


def choose_tile(b: int, n_shards: int) -> int:
    """Static tile width for :func:`ragged_walk`: ~B/n so the per-shard
    tile work matches the balanced load, 64-lane quantized (VPU lane
    width), floored at 64 and capped at the batch.  Skewed extents just
    run more iterations of the same tile — no retrace, no fallback."""
    tile = max(64, -(-int(b) // max(1, int(n_shards))))
    tile = -(-tile // 64) * 64
    return min(tile, int(b))


def ragged_walk(tick_tile, state, m, start, count, lo, local_capacity,
                tile, resp_zeros):
    """Walk one shard's ``[start, start + count)`` extent of the flat
    slot-sorted (19, B) matrix in ``tile``-wide steps (traced; runs per
    shard inside the mesh engine's ``shard_map`` programs).

    ``tick_tile(state, blk)`` is any single-chip tick closure over a
    (19, tile) LOCAL block; ``resp_zeros`` is the zeroed flat response
    pytree the tile responses merge into (a (6, B) matrix, or the
    unfused path's tuple of six (B,) rows).  Tiles near the batch edge
    clamp their base into ``[0, B - tile]`` and mask the re-read lanes:
    masked lanes become guard rows on the way in (slot =
    ``local_capacity``, valid = 0) and keep the already-merged value on
    the way out, so the returned buffer is exact on the extent and zero
    elsewhere — summing the per-shard buffers (one ``psum``) is the
    whole response gather."""
    R = REQ32_INDEX
    nrows, b = m.shape
    tile = min(int(tile), b)
    one_t = jnp.asarray(tile, count.dtype)
    n_tiles = (count + (one_t - 1)) // one_t
    lanes0 = jnp.arange(tile, dtype=jnp.int32)

    def body(t, carry):
        state, out = carry
        a = (start + t * tile).astype(jnp.int32)
        actual = jnp.clip(a, 0, b - tile)
        sl = lax.dynamic_slice(m, (jnp.int32(0), actual), (nrows, tile))
        lane = actual + lanes0
        live = (lane >= a) & (lane < (start + count).astype(jnp.int32))
        blk = sl.at[R["slot"]].set(
            jnp.where(
                live, sl[R["slot"]] - jnp.asarray(lo, sl.dtype),
                jnp.asarray(local_capacity, sl.dtype),
            )
        )
        blk = blk.at[R["valid"]].set(
            (live & (sl[R["valid"]] != 0)).astype(sl.dtype)
        )
        state, resp = tick_tile(state, blk)

        def merge(buf, r):
            r = r.astype(buf.dtype)
            if buf.ndim == 1:
                cur = lax.dynamic_slice(buf, (actual,), (tile,))
                return lax.dynamic_update_slice(
                    buf, jnp.where(live, r, cur), (actual,)
                )
            cur = lax.dynamic_slice(
                buf, (jnp.int32(0), actual), (buf.shape[0], tile)
            )
            return lax.dynamic_update_slice(
                buf, jnp.where(live[None, :], r, cur),
                (jnp.int32(0), actual),
            )

        out = jax.tree.map(merge, out, resp)
        return state, out

    return lax.fori_loop(0, n_tiles, body, (state, resp_zeros))


def make_fused_ragged_tick_fn(capacity: int, chunk: int | None = None):
    """(state: RowState, m32 (19, B) i32, start, count, lo, now)
    → (state, resp (6, B)).

    The ragged fused tick: fusedtick's double-buffered DMA ring, chunk
    count now ``ceil(count / C)`` at RUNTIME — ``(start, count, lo)``
    prefetch to SMEM beside the slot row, so ONE compiled program
    serves every extent length of a given batch capacity.  Unique-slot,
    slot-sorted extents on the row layout (duplicate-bearing windows
    take the merge-capable XLA walker); the response lanes outside the
    extent are exact zeros, ready for the cross-shard ``psum``.
    ``chunk`` as in make_fused_tick_fn."""

    def tick(state, m32, start, count, lo, now):
        b = m32.shape[1]
        c = min(chunk or 2048, b)
        slots = m32[REQ32_INDEX["slot"]]
        from gubernator_tpu.ops.tick32 import now_to_pair

        np_ = now_to_pair(now)
        now2 = jnp.stack([np_.lo, np_.hi])
        ext = jnp.stack([
            jnp.asarray(start, I32),
            jnp.asarray(count, I32),
            jnp.asarray(lo, I32),
        ])

        kernel = functools.partial(
            _ragged_kernel, capacity=capacity, C=c, B=b)
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,  # slots, now2, ext
            grid=(1,),
            in_specs=[
                pl.BlockSpec((REQ32_ROWS, b), lambda t, *_: (0, 0)),
                pl.BlockSpec(memory_space=pl.ANY),  # table (HBM)
            ],
            out_specs=[
                pl.BlockSpec(memory_space=pl.ANY),  # table out (aliased)
                pl.BlockSpec((6, b), lambda t, *_: (0, 0)),
            ],
            scratch_shapes=[
                pltpu.VMEM((2, c, ROW_W), I32),  # read buffers
                pltpu.VMEM((2, c, ROW_W), I32),  # write buffers
                pltpu.SemaphoreType.DMA((2,)),   # read sems (per buffer)
                pltpu.SemaphoreType.DMA((2,)),   # write sems (per buffer)
            ],
        )
        with jaxcompat.enable_x64(False):
            table, resp = pl.pallas_call(
                kernel,
                grid_spec=grid_spec,
                out_shape=[
                    jax.ShapeDtypeStruct((capacity + 1, ROW_W), I32),
                    jax.ShapeDtypeStruct((6, b), I32),
                ],
                input_output_aliases={4: 0},  # table input -> table output
                compiler_params=_VMEM,
                interpret=_interpret(),
            )(slots, now2, ext, m32, state.table)
        return state._replace(table=table), resp

    return tick


def _ragged_kernel(slots_ref, now_ref, ext_ref, m32_ref, table_ref,
                   tout_ref, resp_ref, rbuf, wbuf, rsem, wsem, *,
                   capacity, C, B):
    start = ext_ref[0]
    count = ext_ref[1]
    lo = ext_ref[2]
    cap_i = jnp.int32(capacity)
    # Runtime chunk count, rounded UP to even so the double-buffered
    # pair loop keeps its static buffer parity (fusedtick's read/write
    # interleave); an odd extent pays one phantom chunk whose lanes are
    # all masked (guard-row DMAs, merged-out responses).  count == 0
    # (warmup / idle shard) skips the pipeline entirely.
    nc_live = (count + jnp.int32(C - 1)) // jnp.int32(C)
    nc = nc_live + lax.rem(nc_live, jnp.int32(2))
    U = 8 if C % 8 == 0 else 1

    def chunk_base(c):
        """(intended base, clamped base) of chunk ``c``: tail chunks
        slide back into the batch and mask the re-read lanes."""
        a = start + jnp.int32(c) * C
        return a, jnp.clip(a, 0, jnp.int32(B - C))

    def lslot(c, j):
        # Rebasing is clipped defensively: a host extent bug must never
        # aim a DMA outside the (capacity + 1)-row table.
        a, actual = chunk_base(c)
        idx = actual + j
        live = (idx >= a) & (idx < start + count)
        return jnp.where(
            live, jnp.clip(slots_ref[idx] - lo, 0, cap_i), cap_i)

    def read_copy(c, buf, j):
        return pltpu.make_async_copy(
            tout_ref.at[pl.ds(lslot(c, j), 1), :],
            rbuf.at[buf, pl.ds(j, 1), :],
            rsem.at[buf],
        )

    def write_copy(c, buf, j):
        return pltpu.make_async_copy(
            wbuf.at[buf, pl.ds(j, 1), :],
            tout_ref.at[pl.ds(lslot(c, j), 1), :],
            wsem.at[buf],
        )

    def _loop(fn):
        def body(g, _):
            for k in range(U):
                fn(g * U + k)
            return 0

        lax.fori_loop(0, C // U, body, 0)

    def issue_reads(c, buf):
        _loop(lambda j: read_copy(c, buf, j).start())

    def wait_reads(c, buf):
        # One aggregate wait per chunk (see fusedtick._kernel).
        pltpu.make_async_copy(
            rbuf.at[buf], rbuf.at[buf], rsem.at[buf]).wait()

    def issue_writes(c, buf):
        _loop(lambda j: write_copy(c, buf, j).start())

    def wait_writes(c, buf):
        pltpu.make_async_copy(
            wbuf.at[buf], wbuf.at[buf], wsem.at[buf]).wait()

    def compute_store(c, buf):
        """Transition chunk ``c`` from rbuf[buf] into wbuf[buf], merging
        the live lanes' responses into resp_ref in place."""
        a, actual = chunk_base(c)
        T = _transpose_fwd(rbuf[buf, :, :TW])
        s = _pstate_from_T(T)
        lane = actual + lax.broadcasted_iota(I32, (1, C), 1)
        live = (lane >= a) & (lane < start + count)
        mr = m32_ref[:REQ32_ROWS, pl.ds(actual, C)]
        r = _preq_from_rows(mr)
        # Masked lanes ride the pipeline as guard rows: valid = 0 keeps
        # their transition inert and their scatter aims the guard.
        r = r._replace(valid=r.valid & live)
        now_pair = I64(
            jnp.full((1, C), now_ref[0], I32),
            jnp.full((1, C), now_ref[1], I32),
        )
        new_state, resp = transition32(now_pair, s, r)
        # Write-buffer store FIRST (see fusedtick.compute_store).
        out = _transpose_bwd(_pstate_to_T(new_state))  # (C, TW)
        wbuf[buf, :, :TW] = out
        rows = jnp.concatenate([
            resp.status,
            resp.over_limit.astype(I32),
            resp.remaining.lo,
            resp.remaining.hi,
            resp.reset_time.lo,
            resp.reset_time.hi,
        ], axis=0)
        cur = resp_ref[:, pl.ds(actual, C)]
        resp_ref[:, pl.ds(actual, C)] = jnp.where(live, rows, cur)

    # The flat response must be exact zeros off this shard's extent
    # (the cross-shard gather is a psum); chunks then merge their live
    # lanes read-modify-write.
    resp_ref[:, :] = jnp.zeros((6, B), I32)
    # Spare words of the write rows are zero for the whole kernel (rows
    # scatter whole-width; eviction/installs expect zeroed spares).
    wbuf[0, :, TW:] = jnp.zeros((C, ROW_W - TW), I32)
    wbuf[1, :, TW:] = jnp.zeros((C, ROW_W - TW), I32)

    # nc is even by construction: 0 (empty extent — whole pipeline
    # skipped) or >= 2, so the pair loop never needs an nc == 1 special
    # case the way the static-shape kernel does.
    @pl.when(nc > 0)
    def _():
        issue_reads(0, 0)
        issue_reads(1, 1)

        def pair_body(c2, _):
            for buf in (0, 1):
                c = 2 * c2 + buf
                wait_reads(c, buf)

                @pl.when(c >= 2)
                def _(c=c, buf=buf):
                    wait_writes(c - 2, buf)

                compute_store(c, buf)

                # Reads ahead of writes (see fusedtick.pair_body).
                @pl.when(c + 2 < nc)
                def _(c=c, buf=buf):
                    issue_reads(c + 2, buf)

                issue_writes(c, buf)

            return 0

        lax.fori_loop(0, nc // 2, pair_body, 0)
        wait_writes(nc - 2, 0)
        wait_writes(nc - 1, 1)
