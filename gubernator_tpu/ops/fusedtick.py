"""The fused tick: gather-DMA → in-register transition → scatter-DMA in
ONE Pallas kernel.

Round 3's tick was three serialized passes over HBM (row gather ~750 us,
XLA middle ~690 us of extracts + emulated-64-bit transition, scatter
~410 us at 32K: docs/tpu-performance.md).  This kernel streams the batch
through VMEM in double-buffered chunks so the transition and the write
stream hide under the read stream, which is the hardware floor (~23 ns
per random 512 B row read on v5e, flat across ring depth / unroll /
semaphore-array count — scripts/gather_microbench*.py):

  reads(chunk c+2) ──┐ issued while
  compute(chunk c)   ├─ writes(chunk c-1..c) drain
  responses(chunk c) ┘

Three parts-specific moves make the in-kernel transition possible/cheap:

* the transition itself is pure int32/f32 (ops/transition32.py) — Mosaic
  cannot compile 64-bit programs at all;
* row⇄column layout conversion rides the MXU: a (C, 32) int32 block is
  split into exact 16-bit halves, transposed by one-hot f32 matmuls
  (precision HIGHEST keeps them exact), and recombined — replacing the
  strided-slice extracts that cost ~390 us/tick in XLA;
* responses pack to the compact (6, B) int32 wire format in-kernel, so
  the program's outputs are exactly the bytes the host wants.

Contract (same as ops/tick32.make_tick32_fn): slot-sorted unique-slot
batches, padding rows at slot == capacity, row-layout tables only.
Duplicate-bearing batches take the merge-capable XLA program instead
(host dispatch in engine.submit_columns).

Reference semantics bar: algorithms.go:37-493 (via transition32).
"""

from __future__ import annotations

import functools

import gubernator_tpu.jaxinit  # noqa: F401  (x64 + compile cache before jax use)
import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from gubernator_tpu.ops.engine import REQ32_INDEX, REQ32_ROWS
from gubernator_tpu.ops.i64pair import I64
from gubernator_tpu.ops.rowtable import ROW_W, _interpret
from gubernator_tpu.ops.tfloat import T3
from gubernator_tpu.utils import jaxcompat
from gubernator_tpu.ops.transition32 import (
    PReq,
    PState,
    transition32,
)

I32 = jnp.int32
F32 = jnp.float32

# 24 table words ride the MXU transpose: ROW_USED (24 — 20 legacy words
# plus the zoo's tat/prev_count pairs), already a multiple of 8
# sublanes.  The transposed block is (TW, C).
TW = 24
_VMEM = jaxcompat.pallas_tpu_compiler_params(
    vmem_limit_bytes=100 * 1024 * 1024)


def _eye(n):
    return (
        lax.broadcasted_iota(I32, (n, n), 0)
        == lax.broadcasted_iota(I32, (n, n), 1)
    ).astype(F32)


def _transpose_fwd(block):
    """(C, TW) int32 → (TW, C) via exact one-hot MXU matmuls."""
    lo = (block & jnp.int32(0xFFFF)).astype(F32)
    hi = ((block >> 16) & jnp.int32(0xFFFF)).astype(F32)
    dn = (((1,), (1,)), ((), ()))
    loT = lax.dot_general(_eye(TW), lo, dn, precision=lax.Precision.HIGHEST,
                          preferred_element_type=F32)
    hiT = lax.dot_general(_eye(TW), hi, dn, precision=lax.Precision.HIGHEST,
                          preferred_element_type=F32)
    return (hiT.astype(I32) << 16) | loT.astype(I32)


def _transpose_bwd(blockT):
    """(TW, C) int32 → (C, TW), same construction."""
    lo = (blockT & jnp.int32(0xFFFF)).astype(F32)
    hi = ((blockT >> 16) & jnp.int32(0xFFFF)).astype(F32)
    dn = (((0,), (0,)), ((), ()))
    loT = lax.dot_general(lo, _eye(TW), dn, precision=lax.Precision.HIGHEST,
                          preferred_element_type=F32)
    hiT = lax.dot_general(hi, _eye(TW), dn, precision=lax.Precision.HIGHEST,
                          preferred_element_type=F32)
    return (hiT.astype(I32) << 16) | loT.astype(I32)


def _bc_f32(x):
    return lax.bitcast_convert_type(x, F32)


def _bc_i32(x):
    return lax.bitcast_convert_type(x, I32)


def _pstate_from_T(T):
    """Rows of the transposed (TW, C) block → PState of (1, C) leaves.
    Word offsets are rowtable.FIELD_OFFSETS (the row layout)."""
    from gubernator_tpu.ops.rowtable import FIELD_OFFSETS as O

    def row(k):
        return T[k:k + 1, :]

    def pair(f):
        return I64(row(O[f]), row(O[f] + 1))

    fo = O["remaining_f"]
    return PState(
        algorithm=row(O["algorithm"]),
        limit=pair("limit"),
        remaining=pair("remaining"),
        remaining_f=T3(_bc_f32(row(fo)), _bc_f32(row(fo + 1)),
                       _bc_f32(row(fo + 2))),
        duration=pair("duration"),
        created_at=pair("created_at"),
        updated_at=pair("updated_at"),
        burst=pair("burst"),
        status=row(O["status"]),
        expire_at=pair("expire_at"),
        in_use=row(O["in_use"]) != 0,
        tat=pair("tat"),
        prev_count=pair("prev_count"),
    )


def _pstate_to_T(s: PState):
    """PState of (1, C) leaves → (TW, C) transposed block (spare rows 0)."""
    rows = [
        s.algorithm,
        s.limit.lo, s.limit.hi,
        s.remaining.lo, s.remaining.hi,
        _bc_i32(s.remaining_f.hi), _bc_i32(s.remaining_f.mid),
        _bc_i32(s.remaining_f.lo),
        s.duration.lo, s.duration.hi,
        s.created_at.lo, s.created_at.hi,
        s.updated_at.lo, s.updated_at.hi,
        s.burst.lo, s.burst.hi,
        s.status,
        s.expire_at.lo, s.expire_at.hi,
        s.in_use.astype(I32),
        s.tat.lo, s.tat.hi,
        s.prev_count.lo, s.prev_count.hi,
    ]
    c = rows[0].shape[1]
    if len(rows) < TW:
        pad = jnp.zeros((TW - len(rows), c), I32)
        rows = rows + [pad]
    return jnp.concatenate(rows, axis=0)


def _preq_from_rows(mr):
    """(19, C) request slice → PReq of (1, C) leaves."""

    def row(name):
        k = REQ32_INDEX[name]
        return mr[k:k + 1, :]

    def pair(name):
        k = REQ32_INDEX[name]
        return I64(mr[k:k + 1, :], mr[k + 1:k + 2, :])

    return PReq(
        slot=row("slot"),
        known=row("known") != 0,
        hits=pair("hits"),
        limit=pair("limit"),
        duration=pair("duration"),
        algorithm=row("algorithm"),
        behavior=row("behavior"),
        created_at=pair("created_at"),
        burst=pair("burst"),
        greg_exp=pair("greg_exp"),
        greg_dur=pair("greg_dur"),
        valid=row("valid") != 0,
    )


def make_fused_tick_fn(capacity: int, chunk: int | None = None):
    """(state: RowState, m32 (19, B) i32, now i64) → (state, resp (6, B)).

    Unique-slot, slot-sorted batches on the row layout; see module doc.
    ``chunk`` overrides the VMEM chunk rows (default 2048, the measured
    sweet spot on v5e; tests use small chunks to exercise the
    double-buffered path cheaply in interpret mode)."""

    def tick(state, m32, now):
        b = m32.shape[1]
        c = min(chunk or 2048, b)
        nc = b // c
        assert b % c == 0 and (nc == 1 or nc % 2 == 0), (b, c)
        slots = m32[REQ32_INDEX["slot"]]
        from gubernator_tpu.ops.tick32 import now_to_pair

        np_ = now_to_pair(now)
        now2 = jnp.stack([np_.lo, np_.hi])

        kernel = functools.partial(_kernel, capacity=capacity, C=c, nc=nc)
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,  # slots, now2
            grid=(1,),
            in_specs=[
                pl.BlockSpec((REQ32_ROWS, b), lambda t, *_: (0, 0)),
                pl.BlockSpec(memory_space=pl.ANY),  # table (HBM)
            ],
            out_specs=[
                pl.BlockSpec(memory_space=pl.ANY),  # table out (aliased)
                pl.BlockSpec((6, b), lambda t, *_: (0, 0)),
            ],
            scratch_shapes=[
                pltpu.VMEM((2, c, ROW_W), I32),  # read buffers
                pltpu.VMEM((2, c, ROW_W), I32),  # write buffers
                pltpu.SemaphoreType.DMA((2,)),   # read sems (per buffer)
                pltpu.SemaphoreType.DMA((2,)),   # write sems (per buffer)
            ],
        )
        with jaxcompat.enable_x64(False):
            table, resp = pl.pallas_call(
                kernel,
                grid_spec=grid_spec,
                out_shape=[
                    jax.ShapeDtypeStruct((capacity + 1, ROW_W), I32),
                    jax.ShapeDtypeStruct((6, b), I32),
                ],
                input_output_aliases={3: 0},  # table input -> table output
                compiler_params=_VMEM,
                interpret=_interpret(),
            )(slots, now2, m32, state.table)
        return state._replace(table=table), resp

    return tick


def _kernel(slots_ref, now_ref, m32_ref, table_ref, tout_ref, resp_ref,
            rbuf, wbuf, rsem, wsem, *, capacity, C, nc, merged=False):
    cap_i = jnp.int32(capacity)

    # The scalar core's DMA work is the kernel's second wall (~23 ns per
    # read descriptor): slots are trusted in [0, capacity] (the host
    # packs them; engine._build_cols), waits are ONE bulk semaphore_wait
    # per chunk instead of C descriptor re-creations, and the issue
    # loops are manually 8-wide (Mosaic only supports unroll=1/full in
    # lax loops).
    del cap_i
    # 8-wide measured best on v5e (4: ~5% slower; 16: ~50% slower).
    U = 8 if C % 8 == 0 else 1

    def read_copy(c, buf, j):
        return pltpu.make_async_copy(
            tout_ref.at[pl.ds(slots_ref[c * C + j], 1), :],
            rbuf.at[buf, pl.ds(j, 1), :],
            rsem.at[buf],
        )

    def write_copy(c, buf, j):
        return pltpu.make_async_copy(
            wbuf.at[buf, pl.ds(j, 1), :],
            tout_ref.at[pl.ds(slots_ref[c * C + j], 1), :],
            wsem.at[buf],
        )

    def _loop(fn):
        def body(g, _):
            for k in range(U):
                fn(g * U + k)
            return 0

        lax.fori_loop(0, C // U, body, 0)

    def issue_reads(c, buf):
        _loop(lambda j: read_copy(c, buf, j).start())

    def wait_reads(c, buf):
        # One aggregate wait for the whole chunk: DMA semaphores count
        # bytes, and the wait amount comes from the descriptor's dst
        # size — a (C, ROW_W) self-copy descriptor waits exactly the sum
        # of the C row copies without C descriptor re-creations.
        pltpu.make_async_copy(
            rbuf.at[buf], rbuf.at[buf], rsem.at[buf]).wait()

    def issue_writes(c, buf):
        _loop(lambda j: write_copy(c, buf, j).start())

    def wait_writes(c, buf):
        pltpu.make_async_copy(
            wbuf.at[buf], wbuf.at[buf], wsem.at[buf]).wait()

    def compute_store(c, buf):
        """Transition chunk ``c`` from rbuf[buf] into wbuf[buf] + resp."""
        base = c * C
        T = _transpose_fwd(rbuf[buf, :, :TW])
        s = _pstate_from_T(T)
        mr = m32_ref[:REQ32_ROWS, pl.ds(base, C)]
        r = _preq_from_rows(mr)
        now_pair = I64(
            jnp.full((1, C), now_ref[0], I32),
            jnp.full((1, C), now_ref[1], I32),
        )
        new_state, resp = transition32(now_pair, s, r)
        if merged:
            from gubernator_tpu.ops.transition32 import (
                MERGED24_ROWS,
                merged24_rows,
                merged_fold32,
            )

            cnt = m32_ref[REQ32_ROWS:REQ32_ROWS + 1, pl.ds(base, C)]
            new_state, head = merged_fold32(now_pair, new_state, r, cnt)
        # The write-buffer store comes FIRST: pair_body issues the row
        # scatters right after compute_store returns, and filling wbuf
        # before the response packing keeps the write DMAs from waiting
        # on VPU work they don't depend on.
        out = _transpose_bwd(_pstate_to_T(new_state))  # (C, TW)
        wbuf[buf, :, :TW] = out
        if merged:
            rows = list(merged24_rows(resp, head, r))
            rows += [jnp.zeros((1, C), I32)] * (MERGED24_ROWS - len(rows))
            # Row-major output via the same exact one-hot MXU transpose
            # the table rows use (TW == MERGED24_ROWS == 24).
            respT = _transpose_bwd(jnp.concatenate(rows, axis=0))
            resp_ref[pl.ds(base, C), :] = respT
        else:
            rows = [
                resp.status,
                resp.over_limit.astype(I32),
                resp.remaining.lo,
                resp.remaining.hi,
                resp.reset_time.lo,
                resp.reset_time.hi,
            ]
            resp_ref[:, pl.ds(base, C)] = jnp.concatenate(rows, axis=0)

    # Spare words of the write rows are zero for the whole kernel (rows
    # scatter whole-width; eviction/installs expect zeroed spares).
    wbuf[0, :, TW:] = jnp.zeros((C, ROW_W - TW), I32)
    wbuf[1, :, TW:] = jnp.zeros((C, ROW_W - TW), I32)

    issue_reads(0, 0)

    if nc == 1:
        wait_reads(0, 0)
        compute_store(0, 0)
        issue_writes(0, 0)
        wait_writes(0, 0)
        return

    issue_reads(1, 1)

    def pair_body(c2, _):
        for buf in (0, 1):
            c = 2 * c2 + buf
            wait_reads(c, buf)

            @pl.when(c2 > 0)
            def _(c=c, buf=buf):
                wait_writes(c - 2, buf)

            compute_store(c, buf)

            # Reads ahead of writes: the DMA queue serves descriptors in
            # order and the read stream is the critical path — feeding
            # chunk c's writes first would stall chunk c+2's reads
            # behind ~C write descriptors every chunk.
            @pl.when(c + 2 < nc)
            def _(c=c, buf=buf):
                issue_reads(c + 2, buf)

            issue_writes(c, buf)

        return 0

    lax.fori_loop(0, nc // 2, pair_body, 0)
    wait_writes(nc - 2, 0)
    wait_writes(nc - 1, 1)


def make_fused_merged_tick_fn(capacity: int, chunk: int | None = None):
    """Grouped variant of the fused tick: same DMA pipeline, with the
    closed-form duplicate fold (transition32.merged_fold32) applied
    in-register before the scatter.  ``count`` rides as a 20th
    request-matrix row so the kernel reads it from VMEM like any other
    request field.

    Output format is ROW-MAJOR ``(U, 24)`` (transition32.MERGED24 row
    order: compact resp + MergedHead extras + the request params the
    expansion needs) — the per-member expansion gathers whole 96 B rows
    by head index, which the TPU executes ~40x faster than 15 separate
    lane-dimension gathers (chained-differential probe: 95 µs vs 3.6 ms
    for 32K members).  The transpose into row-major rides the same
    one-hot MXU blocks as the table rows."""
    from gubernator_tpu.ops.transition32 import MERGED24_ROWS

    def tick(state, mhead, count, now):
        b = mhead.shape[1]
        c = min(chunk or 2048, b)
        nc = b // c
        assert b % c == 0 and (nc == 1 or nc % 2 == 0), (b, c)
        slots = mhead[REQ32_INDEX["slot"]]
        from gubernator_tpu.ops.tick32 import now_to_pair

        np_ = now_to_pair(now)
        now2 = jnp.stack([np_.lo, np_.hi])
        m20 = jnp.concatenate([mhead, count[None].astype(I32)], axis=0)

        kernel = functools.partial(
            _kernel, capacity=capacity, C=c, nc=nc, merged=True)
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,  # slots, now2
            grid=(1,),
            in_specs=[
                pl.BlockSpec((REQ32_ROWS + 1, b), lambda t, *_: (0, 0)),
                pl.BlockSpec(memory_space=pl.ANY),  # table (HBM)
            ],
            out_specs=[
                pl.BlockSpec(memory_space=pl.ANY),  # table out (aliased)
                pl.BlockSpec((b, MERGED24_ROWS), lambda t, *_: (0, 0)),
            ],
            scratch_shapes=[
                pltpu.VMEM((2, c, ROW_W), I32),
                pltpu.VMEM((2, c, ROW_W), I32),
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.DMA((2,)),
            ],
        )
        with jaxcompat.enable_x64(False):
            table, resp = pl.pallas_call(
                kernel,
                grid_spec=grid_spec,
                out_shape=[
                    jax.ShapeDtypeStruct((capacity + 1, ROW_W), I32),
                    jax.ShapeDtypeStruct((b, MERGED24_ROWS), I32),
                ],
                input_output_aliases={3: 0},
                compiler_params=_VMEM,
                interpret=_interpret(),
            )(slots, now2, m20, state.table)
        return state._replace(table=table), resp

    return tick
