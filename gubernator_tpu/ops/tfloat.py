"""Triple-float32 arithmetic — f64-class precision in pure f32/i32 ops.

The leaky bucket's ``remaining`` is a float64 in the reference
(store.go:29-35) and is stored on device as an exact three-way Dekker
float32 split (ops/buckets.py STATE_DTYPES).  On TPU there is no native
f64 — XLA's X64 rewriter emulates it (float32-pair class precision) and
Mosaic cannot compile under ``jax_enable_x64`` at all.  This module does
the drip arithmetic *directly on the stored (hi, mid, lo) triple*:
three non-overlapping f32 parts carry up to ~72 mantissa bits, at or
above both IEEE f64 (53) and XLA's own TPU emulation, in ops Mosaic can
compile (f32 add/sub/mul/div/floor + i32 logic).

All functions are shape-polymorphic and elementwise.  Error-free
transforms (two_sum / two_prod via Dekker splitting — no FMA required)
keep results exact when they are representable, which covers the golden
suites' integral rates and drips; accumulated drip fractions carry
~70-bit precision, the same equivalence class the previous x64 path
provided on TPU silicon.

Domain: finite values, |x| < 2^63 for integer interop (the rate
limiter's envelope — the reference itself stores token counts in f64,
so anything beyond 2^53 is already approximate upstream).
"""

from __future__ import annotations

from typing import NamedTuple

import gubernator_tpu.jaxinit  # noqa: F401  (x64 + compile cache before jax use)
import jax.numpy as jnp
import numpy as np

from gubernator_tpu.ops import i64pair as p64

F32 = jnp.float32
I32 = jnp.int32

# numpy scalars so kernels using these ops stay closed (see i64pair.py)
_P24 = np.float32(1 << 24)
_P32 = np.float32(2.0**32)
_PM32 = np.float32(2.0**-32)
_P48 = np.float32(2.0**48)
_P16 = np.float32(1 << 16)
_SPLIT = np.float32((1 << 12) + 1)  # Dekker split constant for f32


class T3(NamedTuple):
    """Non-overlapping (hi, mid, lo) float32 triple."""

    hi: jnp.ndarray
    mid: jnp.ndarray
    lo: jnp.ndarray


def _two_sum(a, b):
    s = a + b
    bb = s - a
    err = (a - (s - bb)) + (b - bb)
    return s, err


def _two_prod(a, b):
    """Exact product: p + e == a*b (Dekker split, no FMA)."""
    p = a * b
    ah = (a * _SPLIT) - ((a * _SPLIT) - a)
    al = a - ah
    bh = (b * _SPLIT) - ((b * _SPLIT) - b)
    bl = b - bh
    e = ((ah * bh - p) + ah * bl + al * bh) + al * bl
    return p, e


def renorm(x0, x1, x2) -> T3:
    """Two bubble passes of two_sum: parts come out ordered and
    (to within an ulp) non-overlapping — enough headroom at 72 bits."""
    x0, x1 = _two_sum(x0, x1)
    x1, x2 = _two_sum(x1, x2)
    x0, x1 = _two_sum(x0, x1)
    x1, x2 = _two_sum(x1, x2)
    return T3(x0, x1, x2)


def zeros_like(x) -> T3:
    z = jnp.zeros(jnp.shape(x), F32)
    return T3(z, z, z)


def from_f32(x) -> T3:
    x = jnp.asarray(x, F32)
    z = jnp.zeros_like(x)
    return T3(x, z, z)


def select(c, a: T3, b: T3) -> T3:
    return T3(jnp.where(c, a.hi, b.hi), jnp.where(c, a.mid, b.mid),
              jnp.where(c, a.lo, b.lo))


def neg(a: T3) -> T3:
    return T3(-a.hi, -a.mid, -a.lo)


def add(a: T3, b: T3) -> T3:
    s0, e0 = _two_sum(a.hi, b.hi)
    s1, e1 = _two_sum(a.mid, b.mid)
    s1b, e0b = _two_sum(s1, e0)
    s2 = a.lo + b.lo + e1 + e0b
    return renorm(s0, s1b, s2)


def sub(a: T3, b: T3) -> T3:
    return add(a, neg(b))


def mul_f(a: T3, f) -> T3:
    """Triple times plain f32."""
    p0, e0 = _two_prod(a.hi, f)
    p1, e1 = _two_prod(a.mid, f)
    m, em = _two_sum(e0, p1)
    return renorm(p0, m, em + e1 + a.lo * f)


def div(a: T3, b: T3) -> T3:
    """a / b to ~70 bits: leading-part quotient + two residual
    corrections.  Exact when the quotient is exactly representable
    (integral rates like 30000/10) because the final residual is zero."""
    q0 = a.hi / b.hi
    r1 = sub(a, mul_f(b, q0))
    q1 = r1.hi / b.hi
    r2 = sub(r1, mul_f(b, q1))
    q2 = r2.hi / b.hi
    return renorm(q0, q1, q2)


def from_pair(v: p64.I64) -> T3:
    """Exact i64 pair -> triple (24-bit chunk decomposition)."""
    c2 = p64.shr(v, 48).lo                       # signed top chunk
    c1 = p64.shr(v, 24).lo & jnp.int32(0xFFFFFF)  # unsigned middle
    c0 = v.lo & jnp.int32(0xFFFFFF)               # unsigned low
    return renorm(
        c2.astype(F32) * _P48,
        c1.astype(F32) * _P24,
        c0.astype(F32),
    )


def _part_int_frac(x):
    """Per-part (floor as exact f32 integer, fraction in [0,1))."""
    big = jnp.abs(x) >= _P24          # f32 >= 2^24 is already an integer
    fl = jnp.where(big, x, jnp.floor(x))
    fr = jnp.where(big, jnp.float32(0), x - jnp.floor(x))
    return fl, fr


def _f32int_to_pair(fx) -> p64.I64:
    """Exact-integer f32 (|fx| < 2^63) -> i64 pair.  Decomposes the
    magnitude (whose sub-2^32 suffix is always representable) and negates
    in pair arithmetic — decomposing a negative directly would need
    2^32-|fx| low words that don't fit a 24-bit mantissa."""
    s = fx < 0
    a = jnp.abs(fx)
    h = jnp.floor(a * _PM32)           # high word as f32 integer, >= 0
    l = a - h * _P32                   # in [0, 2^32), <= 24 sig bits, exact
    lh = jnp.floor(l / _P16)           # [0, 2^16)
    ll = l - lh * _P16                 # [0, 2^16)
    lo = ll.astype(I32) | (lh.astype(I32) << 16)
    mag = p64.I64(lo, h.astype(I32))
    return p64.select(s, p64.neg(mag), mag)


def floor_to_pair(t: T3) -> p64.I64:
    """floor(t) as an i64 pair.  floor == trunc for the engine's
    non-negative uses (remaining, rates); negative inputs floor.

    The per-part fraction sum can misround by one when a part sits
    within half an f32 ulp of an integer (e.g. mid = -1e-8 gives a
    1 - 1e-8 fraction that rounds to 1.0), so the candidate is
    re-verified against ``t`` with the ~70-bit triple compares and
    nudged — floor and the compare ops then agree by construction."""
    f0, r0 = _part_int_frac(t.hi)
    f1, r1 = _part_int_frac(t.mid)
    f2, r2 = _part_int_frac(t.lo)
    total = p64.add(p64.add(_f32int_to_pair(f0), _f32int_to_pair(f1)),
                    _f32int_to_pair(f2))
    fr = r0 + r1 + r2                  # [0, 3)
    cand = p64.add(total, p64.from_i32(jnp.floor(fr).astype(I32)))
    # Correct a +-1 error: want cand <= t < cand + 1.
    d = sub(t, from_pair(cand))
    one = p64.const(1, t.hi)
    cand = p64.select(ge_zero(d), cand, p64.sub(cand, one))
    too_low = ge_zero(sub(d, from_f32(jnp.float32(1.0))))
    return p64.select(too_low, p64.add(cand, one), cand)


def trunc_to_pair(t: T3) -> p64.I64:
    """trunc(t) toward zero as an i64 pair — Go's ``int64(float64)``
    conversion (algorithms.go:377 ``int64(rate)``).  Equal to floor for
    t >= 0; one above floor for negative non-integers (a negative leaky
    rate from a negative duration is the one engine input where the two
    differ)."""
    fl = floor_to_pair(t)
    neg_frac = ~ge_zero(t) & gt_zero(sub(t, from_pair(fl)))
    return p64.select(neg_frac, p64.add(fl, p64.const(1, t.hi)), fl)


def ge_zero(t: T3):
    """t >= 0 for a renormalized triple (sign of leading nonzero part)."""
    return (t.hi > 0) | (
        (t.hi == 0) & ((t.mid > 0) | ((t.mid == 0) & (t.lo >= 0)))
    )


def gt_zero(t: T3):
    return (t.hi > 0) | (
        (t.hi == 0) & ((t.mid > 0) | ((t.mid == 0) & (t.lo > 0)))
    )


def ge(a: T3, b: T3):
    return ge_zero(sub(a, b))


def gt(a: T3, b: T3):
    return gt_zero(sub(a, b))


def ge_pair(t: T3, v: p64.I64):
    return ge(t, from_pair(v))


def gt_pair(t: T3, v: p64.I64):
    return gt(t, from_pair(v))


def to_np(t: T3):
    """Host-side: triple -> numpy float64 (tests / exports)."""
    import numpy as np

    return (np.asarray(t.hi).astype(np.float64)
            + np.asarray(t.mid).astype(np.float64)
            + np.asarray(t.lo).astype(np.float64))


def from_np(v):
    """Host-side: numpy float64 -> exact Dekker-split triple (tests)."""
    import numpy as np

    v = np.asarray(v, np.float64)
    hi = v.astype(np.float32)
    r1 = v - hi.astype(np.float64)
    mid = r1.astype(np.float32)
    lo = (r1 - mid.astype(np.float64)).astype(np.float32)
    return T3(jnp.asarray(hi), jnp.asarray(mid), jnp.asarray(lo))
