"""int64 arithmetic on (lo, hi) int32 pairs — TPU-native 64-bit math.

TPU v5e has no native 64-bit integer unit: XLA's X64 rewriter emulates
every i64 op with i32 pairs *generically*, and (worse) Mosaic refuses to
compile Pallas kernels at all under ``jax_enable_x64``.  The tick's
wire formats already store every 64-bit field as explicit (lo, hi) i32
columns (ops/buckets.py STATE_DTYPES); this module supplies arithmetic
directly on that representation so the whole bucket transition can run
in pure int32 — inside a Pallas kernel or in plain XLA — with bit-exact
two's-complement i64 semantics (adds/subs/muls wrap exactly like Go's
int64, reference algorithms.go:37-493).

Representation: ``(lo, hi)`` int32 arrays of any (matching) shape; ``lo``
holds the unsigned low 32 bits (bit pattern in an int32), ``hi`` the
signed high word.  All functions are shape-polymorphic and elementwise.
"""

from __future__ import annotations

from typing import NamedTuple

import gubernator_tpu.jaxinit  # noqa: F401  (x64 + compile cache before jax use)
import jax.numpy as jnp
import numpy as np

I32 = jnp.int32

# numpy scalars (not jnp): they embed as literals, so kernels built from
# these ops stay closed (Pallas rejects captured device constants).
_SIGN = np.int32(-0x80000000)  # 0x80000000 bit pattern
_M16 = np.int32(0xFFFF)


class I64(NamedTuple):
    """(lo, hi) int32 pair holding one int64 per element."""

    lo: jnp.ndarray
    hi: jnp.ndarray


def from_i32(x) -> I64:
    """Sign-extend an int32 array to a pair."""
    x = jnp.asarray(x, I32)
    return I64(x, x >> 31)


def const(v: int, like) -> I64:
    """Broadcast a Python int constant to the shape of ``like`` (an array)."""
    shape = jnp.shape(like)
    lo = jnp.full(shape, _lo32(v), I32)
    hi = jnp.full(shape, _hi32(v), I32)
    return I64(lo, hi)


def _lo32(v: int) -> int:
    u = v & 0xFFFFFFFF
    return u - 0x100000000 if u >= 0x80000000 else u


def _hi32(v: int) -> int:
    u = (v >> 32) & 0xFFFFFFFF
    return u - 0x100000000 if u >= 0x80000000 else u


def _ult(a, b):
    """Unsigned 32-bit a < b on int32 bit patterns (sign-bias trick)."""
    return (a ^ _SIGN) < (b ^ _SIGN)


def add(a: I64, b: I64) -> I64:
    lo = a.lo + b.lo
    carry = _ult(lo, a.lo).astype(I32)
    return I64(lo, a.hi + b.hi + carry)


def sub(a: I64, b: I64) -> I64:
    lo = a.lo - b.lo
    borrow = _ult(a.lo, lo).astype(I32)
    return I64(lo, a.hi - b.hi - borrow)


def neg(a: I64) -> I64:
    return sub(const(0, a.lo), a)


def eq(a: I64, b: I64):
    return (a.lo == b.lo) & (a.hi == b.hi)


def ne(a: I64, b: I64):
    return (a.lo != b.lo) | (a.hi != b.hi)


def lt(a: I64, b: I64):
    return (a.hi < b.hi) | ((a.hi == b.hi) & _ult(a.lo, b.lo))


def le(a: I64, b: I64):
    return (a.hi < b.hi) | ((a.hi == b.hi) & ~_ult(b.lo, a.lo))


def gt(a: I64, b: I64):
    return lt(b, a)


def ge(a: I64, b: I64):
    return le(b, a)


def is_zero(a: I64):
    return (a.lo == 0) & (a.hi == 0)


def is_neg(a: I64):
    return a.hi < 0


def select(c, a: I64, b: I64) -> I64:
    return I64(jnp.where(c, a.lo, b.lo), jnp.where(c, a.hi, b.hi))


def max_(a: I64, b: I64) -> I64:
    return select(lt(a, b), b, a)


def min_(a: I64, b: I64) -> I64:
    return select(lt(a, b), a, b)


def _umul32(a, b):
    """Unsigned 32x32 -> 64 multiply on int32 bit patterns, via 16-bit
    limbs (TPU has no widening multiply)."""
    a0 = a & _M16
    a1 = (a >> 16) & _M16
    b0 = b & _M16
    b1 = (b >> 16) & _M16
    p00 = a0 * b0            # < 2^32, exact as bit pattern
    p01 = a0 * b1            # < 2^32
    p10 = a1 * b0            # < 2^32
    p11 = a1 * b1            # < 2^32
    # lo = p00 + ((p01 + p10) << 16), tracking carries into hi.
    mid = (p01 & _M16) + (p10 & _M16) + ((p00 >> 16) & _M16)
    lo = (p00 & _M16) | (mid << 16)
    hi = p11 + ((p01 >> 16) & _M16) + ((p10 >> 16) & _M16) \
        + ((mid >> 16) & _M16)
    return lo, hi


def mul(a: I64, b: I64) -> I64:
    """Wrapping i64 multiply (Go int64 overflow semantics)."""
    lo, hi = _umul32(a.lo, b.lo)
    hi = hi + a.lo * b.hi + a.hi * b.lo  # wrapping i32 muls feed high word
    return I64(lo, hi)


def shr(a: I64, n: int) -> I64:
    """Arithmetic shift right by a static 0 <= n < 64."""
    if n == 0:
        return a
    if n < 32:
        lo = ((a.lo >> n) & ((1 << (32 - n)) - 1)) | (a.hi << (32 - n))
        return I64(lo, a.hi >> n)
    return I64(a.hi >> (n - 32), a.hi >> 31)


def to_np(a: I64):
    """Host-side: pair -> numpy int64 (for tests)."""
    import numpy as np

    lo = np.asarray(a.lo).astype(np.int64) & 0xFFFFFFFF
    hi = np.asarray(a.hi).astype(np.int64)
    return (hi << 32) | lo


def from_np(v):
    """Host-side: numpy int64 -> pair (for tests)."""
    import numpy as np

    v = np.asarray(v, np.int64)
    lo = (v & 0xFFFFFFFF).astype(np.uint32).view(np.int32)
    hi = (v >> 32).astype(np.int32)
    return I64(jnp.asarray(lo), jnp.asarray(hi))


def div_floor_pos(a: I64, b: I64) -> I64:
    """``a // b`` for ``a >= 0, b > 0`` (the group-fold quotient: rate
    limits' remaining and hits are non-negative by the time a fold runs).

    No 64-bit divide exists on TPU, so the candidate quotient comes from
    triple-f32 division (~70-bit, ops/tfloat.py) and is then corrected in
    exact pair arithmetic: the remainder ``a - q*b`` decides ±1 steps.
    Two correction rounds cover the triple's worst-case rounding (the
    candidate is within one of the true quotient; a second round guards
    the floor-vs-compare edge at exact multiples).  Differentially tested
    against the x64 oracle in tests/test_parts_math.py."""
    from gubernator_tpu.ops import tfloat as tf

    q = tf.floor_to_pair(tf.div(tf.from_pair(a), tf.from_pair(b)))
    q = select(is_neg(q), I64(jnp.zeros_like(q.lo), jnp.zeros_like(q.hi)), q)
    for _ in range(2):
        r = sub(a, mul(q, b))
        q = select(is_neg(r), sub(q, from_i32(jnp.ones_like(q.lo))), q)
        q = select(ge(r, b), add(q, from_i32(jnp.ones_like(q.lo))), q)
    return q
