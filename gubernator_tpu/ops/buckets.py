"""Vectorized token/leaky bucket state transitions.

This is the TPU-native replacement for the reference's per-key, per-goroutine
``tokenBucket()`` / ``leakyBucket()`` (``algorithms.go:37-257`` and
``:260-493``): instead of branchy scalar code run once per request, the full
decision tree is expressed as a branch-free ``jnp.where`` chain evaluated for
a whole batch of requests at once.  All ~20 distinct outcomes (new item,
expired item, algorithm switch, limit delta, duration change + renewal,
Hits==0 status query, exact remainder, over-ask with/without
DRAIN_OVER_LIMIT, negative hits, RESET_REMAINING) are reproduced with the
*same precedence* as the reference, including its quirks:

* On a duration-change renewal the response `remaining` reflects the
  pre-renewal value while the stored state is refilled (algorithms.go:134-147
  assembles `rl` before the renew mutates `t`).
* `OVER_LIMIT` is only *persisted* into token-bucket state on the
  "already at zero" branch (algorithms.go:162-169); the over-ask branch
  returns OVER_LIMIT without persisting it.
* Negative hits *add* tokens with no upper clamp for token bucket
  (TestTokenBucketNegativeHits semantics).
* A leaky-bucket Hits==0 query that lands on an integer-zero remaining
  truncates away the fractional remainder (the `int64(b.Remaining) == r.Hits`
  branch precedes the Hits==0 early return, algorithms.go:398-403).
* Leaky new items compute `rate` from the *raw* duration even when
  DURATION_IS_GREGORIAN rewrites the stored duration (algorithms.go:437-450).

State is struct-of-arrays (one array per field over table slots) so the
transition maps onto the VPU as pure elementwise math after a gather, and
scatters back afterwards — see :mod:`gubernator_tpu.ops.engine`.

Time is an explicit input: `now` (the tick's wall clock, used for cache
expiry and Gregorian math like the reference's `clock.Now()`) and the
per-request `created_at` (client-suppliable, gubernator.proto:172-182).
Gregorian expirations/durations are resolved host-side
(:mod:`gubernator_tpu.utils.timeutil`) and passed per request, because
calendar math doesn't belong on the MXU/VPU.
"""

from __future__ import annotations

from typing import NamedTuple

import gubernator_tpu.jaxinit  # noqa: F401  (x64 + compile cache before jax use)
import jax.numpy as jnp
import numpy as np
from jax import lax

from gubernator_tpu.algos import ZOO_MIN
from gubernator_tpu.algos import table as zoo_table
from gubernator_tpu.types import Algorithm, Behavior, Status

I64 = jnp.int64
I32 = jnp.int32
F64 = jnp.float64


# Logical dtype of each BucketState field.  8-byte fields are STORED as
# multiple 1-D int32 columns and converted to/from the logical dtype at
# gather/scatter boundaries: on TPU, scatter vectorizes ONLY for 1-D
# 4-byte element arrays — 8-byte elements and 2-D row scatters fall back
# to a serialized path (measured 20×/12× slower per element on v5e) — and
# scatter is the entire cost of a tick.
#
# - int64 → (lo, hi) int32 pair (supported ``bitcast_convert_type``).
# - float64 → an exact three-way Dekker split (hi/mid/lo float32 with
#   non-overlapping mantissas, 3×24 ≥ 53 bits) bitcast to 3 int32 columns:
#   this TPU toolchain's X64 rewriter implements no 64-bit bitcasts at
#   all, so the float must be decomposed arithmetically.  The split is
#   bit-exact while the residual parts stay in float32 range — i.e. for
#   values whose lowest mantissa bit is ≥ 2^-149 (≈ |v| ≥ 2^-97, or any
#   v with ≤ 48 significant bits down there; ~2^-74 where subnormals are
#   flushed).  A leaky-bucket remaining is a count of whole tokens minus
#   drips with lowest bits ≥ 2^-52 — nowhere near the floor.
STATE_DTYPES = {
    "algorithm": I32,    # Algorithm of the stored item
    "limit": I64,
    "remaining": I64,    # token-bucket remaining
    "remaining_f": F64,  # leaky-bucket remaining (float64 like Go)
    "duration": I64,     # ms (raw request duration; leaky items store the effective one)
    "created_at": I64,   # epoch ms (token bucket CreatedAt)
    "updated_at": I64,   # epoch ms (leaky bucket UpdatedAt)
    "burst": I64,        # (leaky)
    "status": I32,       # persisted Status (token bucket only)
    "expire_at": I64,    # epoch ms (CacheItem.ExpireAt)
    "in_use": jnp.bool_,  # slot holds a live item
    # Algorithm-zoo columns (gubernator_tpu/algos/): zero for token/leaky.
    "tat": I64,          # GCRA theoretical arrival time (epoch ms)
    "prev_count": I64,   # sliding-window previous-window count
}

_WIDE = frozenset(k for k, dt in STATE_DTYPES.items() if dt == I64)
_FLOAT = frozenset(k for k, dt in STATE_DTYPES.items() if dt == F64)
F32 = jnp.float32


def _split_f64(a: jnp.ndarray):
    """Exact 3-way float32 split of a float64 (non-overlapping parts)."""
    a = a.astype(F64)
    hi = a.astype(F32)
    r1 = a - hi.astype(F64)
    mid = r1.astype(F32)
    r2 = r1 - mid.astype(F64)
    lo = r2.astype(F32)
    return hi, mid, lo


def to_stored(a: jnp.ndarray, field: str):
    """Logical column → storage columns (tuple of int32 for 8-byte fields)."""
    if field in _WIDE:
        b = lax.bitcast_convert_type(a.astype(I64), I32)
        return (b[..., 0], b[..., 1])
    if field in _FLOAT:
        return tuple(
            lax.bitcast_convert_type(p, I32) for p in _split_f64(a)
        )
    return a.astype(STATE_DTYPES[field])


def to_logical(a, field: str) -> jnp.ndarray:
    """Storage columns → logical column (device-side, cheap elementwise)."""
    if field in _WIDE:
        lo, hi = a
        return lax.bitcast_convert_type(jnp.stack([lo, hi], axis=-1), I64)
    if field in _FLOAT:
        hi, mid, lo = (
            lax.bitcast_convert_type(p, F32).astype(F64) for p in a
        )
        return hi + mid + lo
    return a


def np_logical(a, field: str) -> np.ndarray:
    """Host-side storage → logical values (accepts device or np columns)."""
    if field in _WIDE:
        lo, hi = (np.asarray(p) for p in a)
        return (hi.astype(np.int64) << 32) | lo.view(np.uint32).astype(np.int64)
    if field in _FLOAT:
        hi, mid, lo = (
            np.asarray(p).view(np.float32).astype(np.float64) for p in a
        )
        return hi + mid + lo
    return np.asarray(a)


def _map_field(a, fn):
    if isinstance(a, tuple):
        return tuple(fn(p) for p in a)
    return fn(a)


def slice_field(a, sl):
    """Slice one stored field (array or tuple of part columns)."""
    return _map_field(a, lambda p: p[sl])


class BucketState(NamedTuple):
    """SoA bucket state; each field is a column (or tuple of storage
    columns) over table slots.

    Unifies the reference's ``TokenBucketItem`` (store.go:37-43),
    ``LeakyBucketItem`` (store.go:29-35) and ``CacheItem`` (cache.go:29-41).

    Two representations share this type (mirroring how the kernels use it):

    - **stored**: the table; 8-byte fields as tuples of 1-D int32 columns
      (see :data:`STATE_DTYPES`) so scatters take TPU's fast path.
    - **logical**: per-request gathers / full-table views with the logical
      dtypes, as produced by :func:`gather_state` / :func:`logical_view` —
      what :func:`bucket_transition` computes on.
    """

    algorithm: jnp.ndarray
    limit: jnp.ndarray
    remaining: jnp.ndarray
    remaining_f: jnp.ndarray
    duration: jnp.ndarray
    created_at: jnp.ndarray
    updated_at: jnp.ndarray
    burst: jnp.ndarray
    status: jnp.ndarray
    expire_at: jnp.ndarray
    in_use: jnp.ndarray
    tat: jnp.ndarray
    prev_count: jnp.ndarray

    @classmethod
    def zeros(cls, n: int) -> "BucketState":
        """Stored-layout all-zeros table."""
        def z(f):
            if f in _WIDE:
                return (jnp.zeros(n, I32), jnp.zeros(n, I32))
            if f in _FLOAT:
                # Three DISTINCT buffers: donation rejects aliased args.
                return tuple(jnp.zeros(n, I32) for _ in range(3))
            return jnp.zeros(n, STATE_DTYPES[f])

        return cls(**{f: z(f) for f in STATE_DTYPES})

    @property
    def capacity(self) -> int:
        return self.algorithm.shape[0]

    @classmethod
    def zeros_logical(cls, n: int) -> "BucketState":
        """Logical-dtype all-zero rows (an absent item's state — what a
        new slot reads and what eviction writes back)."""
        def z(f):
            if f in _WIDE:
                return jnp.zeros(n, I64)
            if f in _FLOAT:
                return jnp.zeros(n, F64)
            return jnp.zeros(n, STATE_DTYPES[f])

        return cls(**{f: z(f) for f in STATE_DTYPES})


def logical_view(state: BucketState) -> BucketState:
    """Full-table logical columns (elementwise bitcast; no data movement)."""
    return BucketState(**{
        f: to_logical(getattr(state, f), f) for f in STATE_DTYPES
    })


def stored_view(state: BucketState) -> BucketState:
    """Logical full-table columns → storage layout (inverse of
    :func:`logical_view`)."""
    return BucketState(**{
        f: to_stored(getattr(state, f), f) for f in STATE_DTYPES
    })


def gather_field(state: BucketState, field: str, idx: jnp.ndarray,
                 fill: bool = False) -> jnp.ndarray:
    """Gather one logical column at ``idx`` from a stored-layout table."""
    def g(a):
        if fill:
            return a.at[idx].get(mode="fill", fill_value=0)
        return a[idx]

    return to_logical(_map_field(getattr(state, field), g), field)


def gather_state(state: BucketState, idx: jnp.ndarray,
                 fill: bool = False) -> BucketState:
    """Gather logical rows at ``idx`` from a stored-layout table.

    ``fill=True`` reads zeros for out-of-range indices (readback paths);
    the default promises in-bounds indices (tick hot path).
    """
    return BucketState(**{
        f: gather_field(state, f, idx, fill=fill) for f in STATE_DTYPES
    })


def _put_field(stored, field: str, idx, values, **at_kwargs):
    """Scatter one logical column into one stored field's column(s)."""
    vals = to_stored(values, field)
    if isinstance(stored, tuple):
        return tuple(
            s.at[idx].set(v, **at_kwargs) for s, v in zip(stored, vals)
        )
    return stored.at[idx].set(vals, **at_kwargs)


def scatter_state(state: BucketState, idx: jnp.ndarray,
                  rows: BucketState) -> BucketState:
    """Scatter logical rows back into a stored-layout table; out-of-range
    indices drop (the rank-round masking convention)."""
    return BucketState(**{
        f: _put_field(getattr(state, f), f, idx, getattr(rows, f), mode="drop")
        for f in STATE_DTYPES
    })


def scatter_field(state: BucketState, field: str, idx: jnp.ndarray,
                  values: jnp.ndarray) -> BucketState:
    """Scatter one logical column into the stored table (drop mode)."""
    return state._replace(**{
        field: _put_field(getattr(state, field), field, idx, values, mode="drop")
    })


def set_slot(state: BucketState, slot: int, **fields) -> BucketState:
    """Write logical field values into one slot of a stored-layout table
    (test/debug convenience)."""
    return state._replace(**{
        name: _put_field(getattr(state, name), name, slot, jnp.asarray(val))
        for name, val in fields.items()
    })


def get_slot(state: BucketState, field: str, slot: int):
    """Read one logical field value from a stored-layout table (host)."""
    return np_logical(getattr(state, field), field)[slot]


class ReqBatch(NamedTuple):
    """One batch of rate-limit requests, already resolved to table slots."""

    slot: jnp.ndarray       # i32: table slot index (engine-assigned)
    known: jnp.ndarray      # bool: slot had an existing key→slot mapping
    hits: jnp.ndarray       # i64
    limit: jnp.ndarray      # i64
    duration: jnp.ndarray   # i64
    algorithm: jnp.ndarray  # i32
    behavior: jnp.ndarray   # i32 bitflags
    created_at: jnp.ndarray  # i64 epoch ms
    burst: jnp.ndarray      # i64
    greg_exp: jnp.ndarray   # i64: host-resolved GregorianExpiration (0 if unused)
    greg_dur: jnp.ndarray   # i64: host-resolved GregorianDuration (0 if unused)
    valid: jnp.ndarray      # bool: padding mask

    @classmethod
    def zeros(cls, n: int) -> "ReqBatch":
        return cls(
            slot=jnp.zeros(n, I32),
            known=jnp.zeros(n, jnp.bool_),
            hits=jnp.zeros(n, I64),
            limit=jnp.zeros(n, I64),
            duration=jnp.zeros(n, I64),
            algorithm=jnp.zeros(n, I32),
            behavior=jnp.zeros(n, I32),
            created_at=jnp.zeros(n, I64),
            burst=jnp.zeros(n, I64),
            greg_exp=jnp.zeros(n, I64),
            greg_dur=jnp.zeros(n, I64),
            valid=jnp.zeros(n, jnp.bool_),
        )


class RespBatch(NamedTuple):
    """Per-request results (reference ``RateLimitResp``)."""

    status: jnp.ndarray     # i32
    limit: jnp.ndarray      # i64
    remaining: jnp.ndarray  # i64
    reset_time: jnp.ndarray  # i64
    over_limit: jnp.ndarray  # bool: metricOverLimitCounter signal


def _trunc_i64(x: jnp.ndarray) -> jnp.ndarray:
    """float64 → int64 with C/Go truncation-toward-zero semantics."""
    return x.astype(I64)


def bucket_transition(
    now: jnp.ndarray, s: BucketState, r: ReqBatch
) -> tuple[BucketState, RespBatch]:
    """Apply one batch of requests to their (gathered) bucket states.

    Elementwise over the batch: ``s`` holds per-request gathers of the state
    table, the returned state is scattered back by the engine.  Assumes at
    most one request per slot (the engine's rank-rounds guarantee this).
    """
    UNDER = jnp.int32(Status.UNDER_LIMIT)
    OVER = jnp.int32(Status.OVER_LIMIT)

    reset_b = (r.behavior & Behavior.RESET_REMAINING) != 0
    drain_b = (r.behavior & Behavior.DRAIN_OVER_LIMIT) != 0
    greg_b = (r.behavior & Behavior.DURATION_IS_GREGORIAN) != 0

    # Cache-read existence: item present and not expired (cache.go:43-57,
    # lrucache.go:111-128 treat now > ExpireAt as a miss + eviction).
    exists = r.known & s.in_use & (now <= s.expire_at)
    is_token = r.algorithm == jnp.int32(Algorithm.TOKEN_BUCKET)
    algo_match = s.algorithm == r.algorithm

    h = r.hits
    # Guard against limit == 0 division (service-level validation rejects it;
    # the kernel must still be total).
    safe_limit_f = jnp.where(r.limit == 0, jnp.int64(1), r.limit).astype(F64)

    # ------------------------------------------------------------------
    # TOKEN BUCKET (algorithms.go:37-257)
    # ------------------------------------------------------------------
    # Branch T_RESET: RESET_REMAINING on an existing item removes it and
    # reports a full bucket (algorithms.go:78-90). Checked before the
    # algorithm-switch test, so it applies even if the stored item is leaky.
    tok_reset = exists & reset_b

    # Branch T_EXIST: normal existing token bucket.
    tok_exist = exists & ~reset_b & algo_match

    # Limit delta: remaining += newLimit - oldLimit, clamp ≥ 0 (:106-113).
    t_rem0 = jnp.where(
        s.limit != r.limit,
        jnp.maximum(s.remaining + (r.limit - s.limit), 0),
        s.remaining,
    )
    # Response snapshot taken *before* any duration-change renewal (:115-120).
    rl_status = s.status
    rl_rem_base = t_rem0
    # Duration change (:123-147).
    dur_changed = s.duration != r.duration
    expire_cand = jnp.where(greg_b, r.greg_exp, s.created_at + r.duration)
    renew = expire_cand <= r.created_at
    expire_new = jnp.where(renew, r.created_at + r.duration, expire_cand)
    t_created = jnp.where(dur_changed & renew, r.created_at, s.created_at)
    t_rem1 = jnp.where(dur_changed & renew, r.limit, t_rem0)
    t_expire = jnp.where(dur_changed, expire_new, s.expire_at)
    rl_reset = jnp.where(dur_changed, expire_new, s.expire_at)

    # Outcome precedence (:157-198): query > already-at-zero > exact
    # remainder > over-ask > decrement.
    t_query = h == 0
    t_at_zero = ~t_query & (rl_rem_base == 0) & (h > 0)
    t_exact = ~t_query & ~t_at_zero & (t_rem1 == h)
    t_over = ~t_query & ~t_at_zero & ~t_exact & (h > t_rem1)
    t_dec = ~t_query & ~t_at_zero & ~t_exact & ~t_over

    te_rem = jnp.where(
        t_exact,
        jnp.int64(0),
        jnp.where(
            t_over,
            jnp.where(drain_b, jnp.int64(0), t_rem1),
            jnp.where(t_dec, t_rem1 - h, t_rem1),
        ),
    )
    te_status = jnp.where(t_at_zero, OVER, s.status)
    te_resp_status = jnp.where(t_at_zero | t_over, OVER, rl_status)
    te_resp_rem = jnp.where(
        t_exact,
        jnp.int64(0),
        jnp.where(
            t_over,
            jnp.where(drain_b, jnp.int64(0), rl_rem_base),
            jnp.where(t_dec, t_rem1 - h, rl_rem_base),
        ),
    )

    # Branch T_NEW: no usable item → tokenBucketNewItem (:206-257).
    tn_expire = jnp.where(greg_b, r.greg_exp, r.created_at + r.duration)
    tn_over = h > r.limit
    tn_rem = jnp.where(tn_over, r.limit, r.limit - h)
    tn_resp_status = jnp.where(tn_over, OVER, UNDER)

    # ------------------------------------------------------------------
    # LEAKY BUCKET (algorithms.go:260-493)
    # ------------------------------------------------------------------
    burst = jnp.where(r.burst == 0, r.limit, r.burst)  # default Burst=Limit (:264-266)

    leak_exist = exists & algo_match  # for leaky requests; reset handled inline

    # RESET_REMAINING refills to burst and *continues* (:320-322).
    b_rem0 = jnp.where(reset_b, burst.astype(F64), s.remaining_f)
    # Burst change (:325-330).
    burst_changed = s.burst != burst
    b_rem1 = jnp.where(
        burst_changed & (burst > _trunc_i64(b_rem0)), burst.astype(F64), b_rem0
    )
    # Rate: ms per token. Gregorian uses the whole calendar interval (:336-354).
    rate = jnp.where(greg_b, r.greg_dur.astype(F64), r.duration.astype(F64)) / safe_limit_f
    duration_eff = jnp.where(greg_b, r.greg_exp - now, r.duration)
    # Leak whole tokens only (:361-367), clamp to burst (:369-371).
    elapsed = r.created_at - s.updated_at
    leak = elapsed.astype(F64) / jnp.where(rate == 0, jnp.float64(1), rate)
    leaked = _trunc_i64(leak) > 0
    b_rem2 = jnp.where(leaked, b_rem1 + leak, b_rem1)
    b_upd = jnp.where(leaked, r.created_at, s.updated_at)
    b_rem3 = jnp.where(_trunc_i64(b_rem2) > burst, burst.astype(F64), b_rem2)

    rem_i = _trunc_i64(b_rem3)
    rate_i = _trunc_i64(rate)
    # Outcome precedence (:389-430): at-zero > exact remainder > over-ask >
    # query > decrement.  (Note: exact-remainder precedes the Hits==0 check.)
    l_at_zero = (rem_i == 0) & (h > 0)
    l_exact = ~l_at_zero & (rem_i == h)
    l_over = ~l_at_zero & ~l_exact & (h > rem_i)
    l_query = ~l_at_zero & ~l_exact & ~l_over & (h == 0)
    l_dec = ~l_at_zero & ~l_exact & ~l_over & ~l_query

    le_remf = jnp.where(
        l_exact,
        jnp.float64(0.0),
        jnp.where(
            l_over,
            jnp.where(drain_b, jnp.float64(0.0), b_rem3),
            jnp.where(l_dec, b_rem3 - h.astype(F64), b_rem3),
        ),
    )
    le_resp_status = jnp.where(l_at_zero | l_over, OVER, UNDER)
    le_resp_rem = jnp.where(
        l_exact,
        jnp.int64(0),
        jnp.where(
            l_over,
            jnp.where(drain_b, jnp.int64(0), rem_i),
            jnp.where(l_dec, _trunc_i64(b_rem3 - h.astype(F64)), rem_i),
        ),
    )
    # Over-ask keeps the reset_time computed from the pre-drain remaining
    # (the drain branch at :414-417 zeroes Remaining but not ResetTime).
    le_reset_rem = jnp.where(l_over, rem_i, le_resp_rem)
    le_resp_reset = r.created_at + (r.limit - le_reset_rem) * rate_i
    # Hits != 0 bumps the cache expiration (:356-358).
    le_expire = jnp.where(h != 0, r.created_at + duration_eff, s.expire_at)

    # Leaky new item (:437-493). `rate` from the raw duration (quirk).
    ln_rate_i = _trunc_i64(r.duration.astype(F64) / safe_limit_f)
    ln_duration = jnp.where(greg_b, r.greg_exp - now, r.duration)
    ln_over = h > burst
    ln_remf = jnp.where(ln_over, jnp.float64(0.0), (burst - h).astype(F64))
    ln_resp_rem = jnp.where(ln_over, jnp.int64(0), burst - h)
    ln_resp_reset = r.created_at + (r.limit - ln_resp_rem) * ln_rate_i
    ln_resp_status = jnp.where(ln_over, OVER, UNDER)
    ln_expire = r.created_at + ln_duration

    # ------------------------------------------------------------------
    # ALGORITHM ZOO (gubernator_tpu/algos): sliding-window / GCRA /
    # concurrency lanes, computed branchlessly for every lane and folded
    # by r.algorithm.  Legacy lanes keep the two-way select below.
    # ------------------------------------------------------------------
    is_zoo = r.algorithm >= jnp.int32(ZOO_MIN)
    zs, zr = zoo_table.zoo_transitions(
        zoo_table.X64Ops, s, r, exists, reset_b, drain_b)

    def zsel(zoo_v, legacy_v):
        return jnp.where(is_zoo, zoo_v, legacy_v)

    # ------------------------------------------------------------------
    # Select per-request outcome
    # ------------------------------------------------------------------
    tok_new = is_token & ~tok_reset & ~tok_exist  # miss OR stored-algo mismatch
    leak_new = ~is_token & ~leak_exist

    def sel(tr, te, tn, le, ln):
        """Select by branch: token-reset / token-exist / token-new /
        leaky-exist / leaky-new."""
        tok = jnp.where(tok_reset, tr, jnp.where(tok_exist, te, tn))
        lk = jnp.where(leak_exist, le, ln)
        return jnp.where(is_token, tok, lk)

    zero64 = jnp.zeros_like(r.hits)
    new_state = BucketState(
        algorithm=zsel(
            r.algorithm,
            jnp.where(is_token, jnp.int32(Algorithm.TOKEN_BUCKET),
                      jnp.int32(Algorithm.LEAKY_BUCKET)),
        ),
        limit=r.limit,
        remaining=zsel(
            zs.remaining,
            sel(zero64, te_rem, tn_rem, s.remaining, s.remaining)),
        remaining_f=zsel(
            jnp.zeros_like(s.remaining_f),
            sel(s.remaining_f * 0, s.remaining_f, s.remaining_f, le_remf,
                ln_remf)),
        duration=zsel(
            r.duration,
            sel(zero64, r.duration, r.duration, r.duration, ln_duration)),
        created_at=zsel(
            zs.created_at,
            sel(zero64, t_created, r.created_at, s.created_at,
                s.created_at)),
        updated_at=zsel(
            r.created_at,
            sel(zero64, s.updated_at, s.updated_at, b_upd, r.created_at)),
        burst=zsel(r.burst, sel(zero64, s.burst, s.burst, burst, burst)),
        status=zsel(
            zs.status,
            sel(jnp.zeros_like(s.status), te_status, UNDER, s.status,
                UNDER)),
        expire_at=zsel(
            zs.expire_at,
            sel(zero64, t_expire, tn_expire, le_expire, ln_expire)),
        in_use=zsel(
            jnp.ones_like(s.in_use),
            sel(jnp.zeros_like(s.in_use), s.in_use | True, s.in_use | True,
                s.in_use | True, s.in_use | True)),
        tat=zsel(zs.tat, zero64),
        prev_count=zsel(zs.prev_count, zero64),
    )

    resp = RespBatch(
        status=zsel(
            zr.status,
            sel(UNDER * jnp.ones_like(s.status), te_resp_status,
                tn_resp_status, le_resp_status, ln_resp_status)),
        limit=r.limit,
        remaining=zsel(
            zr.remaining,
            sel(r.limit, te_resp_rem, tn_rem, le_resp_rem, ln_resp_rem)),
        reset_time=zsel(
            zr.reset_time,
            sel(zero64, rl_reset, tn_expire, le_resp_reset,
                ln_resp_reset)),
        over_limit=zsel(
            zr.over_limit != 0,
            sel(
                jnp.zeros_like(exists),
                t_at_zero | t_over,
                tn_over,
                l_at_zero | l_over,
                ln_over,
            )),
    )
    return new_state, resp
