"""Vectorized token/leaky bucket state transitions.

This is the TPU-native replacement for the reference's per-key, per-goroutine
``tokenBucket()`` / ``leakyBucket()`` (``algorithms.go:37-257`` and
``:260-493``): instead of branchy scalar code run once per request, the full
decision tree is expressed as a branch-free ``jnp.where`` chain evaluated for
a whole batch of requests at once.  All ~20 distinct outcomes (new item,
expired item, algorithm switch, limit delta, duration change + renewal,
Hits==0 status query, exact remainder, over-ask with/without
DRAIN_OVER_LIMIT, negative hits, RESET_REMAINING) are reproduced with the
*same precedence* as the reference, including its quirks:

* On a duration-change renewal the response `remaining` reflects the
  pre-renewal value while the stored state is refilled (algorithms.go:134-147
  assembles `rl` before the renew mutates `t`).
* `OVER_LIMIT` is only *persisted* into token-bucket state on the
  "already at zero" branch (algorithms.go:162-169); the over-ask branch
  returns OVER_LIMIT without persisting it.
* Negative hits *add* tokens with no upper clamp for token bucket
  (TestTokenBucketNegativeHits semantics).
* A leaky-bucket Hits==0 query that lands on an integer-zero remaining
  truncates away the fractional remainder (the `int64(b.Remaining) == r.Hits`
  branch precedes the Hits==0 early return, algorithms.go:398-403).
* Leaky new items compute `rate` from the *raw* duration even when
  DURATION_IS_GREGORIAN rewrites the stored duration (algorithms.go:437-450).

State is struct-of-arrays (one array per field over table slots) so the
transition maps onto the VPU as pure elementwise math after a gather, and
scatters back afterwards — see :mod:`gubernator_tpu.ops.engine`.

Time is an explicit input: `now` (the tick's wall clock, used for cache
expiry and Gregorian math like the reference's `clock.Now()`) and the
per-request `created_at` (client-suppliable, gubernator.proto:172-182).
Gregorian expirations/durations are resolved host-side
(:mod:`gubernator_tpu.utils.timeutil`) and passed per request, because
calendar math doesn't belong on the MXU/VPU.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

from gubernator_tpu.types import Algorithm, Behavior, Status

I64 = jnp.int64
I32 = jnp.int32
F64 = jnp.float64


class BucketState(NamedTuple):
    """SoA bucket state; each field is an array over table slots (or a gather
    of them).  Unifies the reference's ``TokenBucketItem`` (store.go:37-43),
    ``LeakyBucketItem`` (store.go:29-35) and ``CacheItem`` (cache.go:29-41).
    """

    algorithm: jnp.ndarray  # i32: Algorithm of the stored item
    limit: jnp.ndarray      # i64
    remaining: jnp.ndarray  # i64: token-bucket remaining
    remaining_f: jnp.ndarray  # f64: leaky-bucket remaining (float64 like Go)
    duration: jnp.ndarray   # i64 ms (raw request duration; leaky new items store the effective one)
    created_at: jnp.ndarray  # i64 epoch ms (token bucket CreatedAt)
    updated_at: jnp.ndarray  # i64 epoch ms (leaky bucket UpdatedAt)
    burst: jnp.ndarray      # i64 (leaky)
    status: jnp.ndarray     # i32: persisted Status (token bucket only)
    expire_at: jnp.ndarray  # i64 epoch ms (CacheItem.ExpireAt)
    in_use: jnp.ndarray     # bool: slot holds a live item

    @classmethod
    def zeros(cls, n: int) -> "BucketState":
        return cls(
            algorithm=jnp.zeros(n, I32),
            limit=jnp.zeros(n, I64),
            remaining=jnp.zeros(n, I64),
            remaining_f=jnp.zeros(n, F64),
            duration=jnp.zeros(n, I64),
            created_at=jnp.zeros(n, I64),
            updated_at=jnp.zeros(n, I64),
            burst=jnp.zeros(n, I64),
            status=jnp.zeros(n, I32),
            expire_at=jnp.zeros(n, I64),
            in_use=jnp.zeros(n, jnp.bool_),
        )


class ReqBatch(NamedTuple):
    """One batch of rate-limit requests, already resolved to table slots."""

    slot: jnp.ndarray       # i32: table slot index (engine-assigned)
    known: jnp.ndarray      # bool: slot had an existing key→slot mapping
    hits: jnp.ndarray       # i64
    limit: jnp.ndarray      # i64
    duration: jnp.ndarray   # i64
    algorithm: jnp.ndarray  # i32
    behavior: jnp.ndarray   # i32 bitflags
    created_at: jnp.ndarray  # i64 epoch ms
    burst: jnp.ndarray      # i64
    greg_exp: jnp.ndarray   # i64: host-resolved GregorianExpiration (0 if unused)
    greg_dur: jnp.ndarray   # i64: host-resolved GregorianDuration (0 if unused)
    valid: jnp.ndarray      # bool: padding mask

    @classmethod
    def zeros(cls, n: int) -> "ReqBatch":
        return cls(
            slot=jnp.zeros(n, I32),
            known=jnp.zeros(n, jnp.bool_),
            hits=jnp.zeros(n, I64),
            limit=jnp.zeros(n, I64),
            duration=jnp.zeros(n, I64),
            algorithm=jnp.zeros(n, I32),
            behavior=jnp.zeros(n, I32),
            created_at=jnp.zeros(n, I64),
            burst=jnp.zeros(n, I64),
            greg_exp=jnp.zeros(n, I64),
            greg_dur=jnp.zeros(n, I64),
            valid=jnp.zeros(n, jnp.bool_),
        )


class RespBatch(NamedTuple):
    """Per-request results (reference ``RateLimitResp``)."""

    status: jnp.ndarray     # i32
    limit: jnp.ndarray      # i64
    remaining: jnp.ndarray  # i64
    reset_time: jnp.ndarray  # i64
    over_limit: jnp.ndarray  # bool: metricOverLimitCounter signal


def _trunc_i64(x: jnp.ndarray) -> jnp.ndarray:
    """float64 → int64 with C/Go truncation-toward-zero semantics."""
    return x.astype(I64)


def bucket_transition(
    now: jnp.ndarray, s: BucketState, r: ReqBatch
) -> tuple[BucketState, RespBatch]:
    """Apply one batch of requests to their (gathered) bucket states.

    Elementwise over the batch: ``s`` holds per-request gathers of the state
    table, the returned state is scattered back by the engine.  Assumes at
    most one request per slot (the engine's rank-rounds guarantee this).
    """
    UNDER = jnp.int32(Status.UNDER_LIMIT)
    OVER = jnp.int32(Status.OVER_LIMIT)

    reset_b = (r.behavior & Behavior.RESET_REMAINING) != 0
    drain_b = (r.behavior & Behavior.DRAIN_OVER_LIMIT) != 0
    greg_b = (r.behavior & Behavior.DURATION_IS_GREGORIAN) != 0

    # Cache-read existence: item present and not expired (cache.go:43-57,
    # lrucache.go:111-128 treat now > ExpireAt as a miss + eviction).
    exists = r.known & s.in_use & (now <= s.expire_at)
    is_token = r.algorithm == jnp.int32(Algorithm.TOKEN_BUCKET)
    algo_match = s.algorithm == r.algorithm

    h = r.hits
    # Guard against limit == 0 division (service-level validation rejects it;
    # the kernel must still be total).
    safe_limit_f = jnp.where(r.limit == 0, jnp.int64(1), r.limit).astype(F64)

    # ------------------------------------------------------------------
    # TOKEN BUCKET (algorithms.go:37-257)
    # ------------------------------------------------------------------
    # Branch T_RESET: RESET_REMAINING on an existing item removes it and
    # reports a full bucket (algorithms.go:78-90). Checked before the
    # algorithm-switch test, so it applies even if the stored item is leaky.
    tok_reset = exists & reset_b

    # Branch T_EXIST: normal existing token bucket.
    tok_exist = exists & ~reset_b & algo_match

    # Limit delta: remaining += newLimit - oldLimit, clamp ≥ 0 (:106-113).
    t_rem0 = jnp.where(
        s.limit != r.limit,
        jnp.maximum(s.remaining + (r.limit - s.limit), 0),
        s.remaining,
    )
    # Response snapshot taken *before* any duration-change renewal (:115-120).
    rl_status = s.status
    rl_rem_base = t_rem0
    # Duration change (:123-147).
    dur_changed = s.duration != r.duration
    expire_cand = jnp.where(greg_b, r.greg_exp, s.created_at + r.duration)
    renew = expire_cand <= r.created_at
    expire_new = jnp.where(renew, r.created_at + r.duration, expire_cand)
    t_created = jnp.where(dur_changed & renew, r.created_at, s.created_at)
    t_rem1 = jnp.where(dur_changed & renew, r.limit, t_rem0)
    t_expire = jnp.where(dur_changed, expire_new, s.expire_at)
    rl_reset = jnp.where(dur_changed, expire_new, s.expire_at)

    # Outcome precedence (:157-198): query > already-at-zero > exact
    # remainder > over-ask > decrement.
    t_query = h == 0
    t_at_zero = ~t_query & (rl_rem_base == 0) & (h > 0)
    t_exact = ~t_query & ~t_at_zero & (t_rem1 == h)
    t_over = ~t_query & ~t_at_zero & ~t_exact & (h > t_rem1)
    t_dec = ~t_query & ~t_at_zero & ~t_exact & ~t_over

    te_rem = jnp.where(
        t_exact,
        jnp.int64(0),
        jnp.where(
            t_over,
            jnp.where(drain_b, jnp.int64(0), t_rem1),
            jnp.where(t_dec, t_rem1 - h, t_rem1),
        ),
    )
    te_status = jnp.where(t_at_zero, OVER, s.status)
    te_resp_status = jnp.where(t_at_zero | t_over, OVER, rl_status)
    te_resp_rem = jnp.where(
        t_exact,
        jnp.int64(0),
        jnp.where(
            t_over,
            jnp.where(drain_b, jnp.int64(0), rl_rem_base),
            jnp.where(t_dec, t_rem1 - h, rl_rem_base),
        ),
    )

    # Branch T_NEW: no usable item → tokenBucketNewItem (:206-257).
    tn_expire = jnp.where(greg_b, r.greg_exp, r.created_at + r.duration)
    tn_over = h > r.limit
    tn_rem = jnp.where(tn_over, r.limit, r.limit - h)
    tn_resp_status = jnp.where(tn_over, OVER, UNDER)

    # ------------------------------------------------------------------
    # LEAKY BUCKET (algorithms.go:260-493)
    # ------------------------------------------------------------------
    burst = jnp.where(r.burst == 0, r.limit, r.burst)  # default Burst=Limit (:264-266)

    leak_exist = exists & algo_match  # for leaky requests; reset handled inline

    # RESET_REMAINING refills to burst and *continues* (:320-322).
    b_rem0 = jnp.where(reset_b, burst.astype(F64), s.remaining_f)
    # Burst change (:325-330).
    burst_changed = s.burst != burst
    b_rem1 = jnp.where(
        burst_changed & (burst > _trunc_i64(b_rem0)), burst.astype(F64), b_rem0
    )
    # Rate: ms per token. Gregorian uses the whole calendar interval (:336-354).
    rate = jnp.where(greg_b, r.greg_dur.astype(F64), r.duration.astype(F64)) / safe_limit_f
    duration_eff = jnp.where(greg_b, r.greg_exp - now, r.duration)
    # Leak whole tokens only (:361-367), clamp to burst (:369-371).
    elapsed = r.created_at - s.updated_at
    leak = elapsed.astype(F64) / jnp.where(rate == 0, jnp.float64(1), rate)
    leaked = _trunc_i64(leak) > 0
    b_rem2 = jnp.where(leaked, b_rem1 + leak, b_rem1)
    b_upd = jnp.where(leaked, r.created_at, s.updated_at)
    b_rem3 = jnp.where(_trunc_i64(b_rem2) > burst, burst.astype(F64), b_rem2)

    rem_i = _trunc_i64(b_rem3)
    rate_i = _trunc_i64(rate)
    # Outcome precedence (:389-430): at-zero > exact remainder > over-ask >
    # query > decrement.  (Note: exact-remainder precedes the Hits==0 check.)
    l_at_zero = (rem_i == 0) & (h > 0)
    l_exact = ~l_at_zero & (rem_i == h)
    l_over = ~l_at_zero & ~l_exact & (h > rem_i)
    l_query = ~l_at_zero & ~l_exact & ~l_over & (h == 0)
    l_dec = ~l_at_zero & ~l_exact & ~l_over & ~l_query

    le_remf = jnp.where(
        l_exact,
        jnp.float64(0.0),
        jnp.where(
            l_over,
            jnp.where(drain_b, jnp.float64(0.0), b_rem3),
            jnp.where(l_dec, b_rem3 - h.astype(F64), b_rem3),
        ),
    )
    le_resp_status = jnp.where(l_at_zero | l_over, OVER, UNDER)
    le_resp_rem = jnp.where(
        l_exact,
        jnp.int64(0),
        jnp.where(
            l_over,
            jnp.where(drain_b, jnp.int64(0), rem_i),
            jnp.where(l_dec, _trunc_i64(b_rem3 - h.astype(F64)), rem_i),
        ),
    )
    # Over-ask keeps the reset_time computed from the pre-drain remaining
    # (the drain branch at :414-417 zeroes Remaining but not ResetTime).
    le_reset_rem = jnp.where(l_over, rem_i, le_resp_rem)
    le_resp_reset = r.created_at + (r.limit - le_reset_rem) * rate_i
    # Hits != 0 bumps the cache expiration (:356-358).
    le_expire = jnp.where(h != 0, r.created_at + duration_eff, s.expire_at)

    # Leaky new item (:437-493). `rate` from the raw duration (quirk).
    ln_rate_i = _trunc_i64(r.duration.astype(F64) / safe_limit_f)
    ln_duration = jnp.where(greg_b, r.greg_exp - now, r.duration)
    ln_over = h > burst
    ln_remf = jnp.where(ln_over, jnp.float64(0.0), (burst - h).astype(F64))
    ln_resp_rem = jnp.where(ln_over, jnp.int64(0), burst - h)
    ln_resp_reset = r.created_at + (r.limit - ln_resp_rem) * ln_rate_i
    ln_resp_status = jnp.where(ln_over, OVER, UNDER)
    ln_expire = r.created_at + ln_duration

    # ------------------------------------------------------------------
    # Select per-request outcome
    # ------------------------------------------------------------------
    tok_new = is_token & ~tok_reset & ~tok_exist  # miss OR stored-algo mismatch
    leak_new = ~is_token & ~leak_exist

    def sel(tr, te, tn, le, ln):
        """Select by branch: token-reset / token-exist / token-new /
        leaky-exist / leaky-new."""
        tok = jnp.where(tok_reset, tr, jnp.where(tok_exist, te, tn))
        lk = jnp.where(leak_exist, le, ln)
        return jnp.where(is_token, tok, lk)

    zero64 = jnp.zeros_like(r.hits)
    new_state = BucketState(
        algorithm=jnp.where(is_token, jnp.int32(Algorithm.TOKEN_BUCKET),
                            jnp.int32(Algorithm.LEAKY_BUCKET)),
        limit=r.limit,
        remaining=sel(zero64, te_rem, tn_rem, s.remaining, s.remaining),
        remaining_f=sel(s.remaining_f * 0, s.remaining_f, s.remaining_f, le_remf, ln_remf),
        duration=sel(zero64, r.duration, r.duration, r.duration, ln_duration),
        created_at=sel(zero64, t_created, r.created_at, s.created_at, s.created_at),
        updated_at=sel(zero64, s.updated_at, s.updated_at, b_upd, r.created_at),
        burst=sel(zero64, s.burst, s.burst, burst, burst),
        status=sel(jnp.zeros_like(s.status), te_status, UNDER, s.status, UNDER),
        expire_at=sel(zero64, t_expire, tn_expire, le_expire, ln_expire),
        in_use=sel(jnp.zeros_like(s.in_use), s.in_use | True, s.in_use | True,
                   s.in_use | True, s.in_use | True),
    )

    resp = RespBatch(
        status=sel(UNDER * jnp.ones_like(s.status), te_resp_status,
                   tn_resp_status, le_resp_status, ln_resp_status),
        limit=r.limit,
        remaining=sel(r.limit, te_resp_rem, tn_rem, le_resp_rem, ln_resp_rem),
        reset_time=sel(zero64, rl_reset, tn_expire, le_resp_reset, ln_resp_reset),
        over_limit=sel(
            jnp.zeros_like(exists),
            t_at_zero | t_over,
            tn_over,
            l_at_zero | l_over,
            ln_over,
        ),
    )
    return new_state, resp
