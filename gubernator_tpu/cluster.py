"""In-process multi-daemon cluster for tests and local development.

The reference proves "multi-node" behavior without a real cluster by
booting N daemons in one process on loopback with statically injected peer
lists (``cluster/cluster.go:123-189``); this is the same harness for the
TPU build: real gRPC over loopback, real consistent hashing, real
batching/broadcast loops — the engines all share one device.

Ownership introspection helpers (``FindOwningDaemon``,
``ListNonOwningDaemons``, ``cluster/cluster.go:81-110``) let tests target
the exact peer that owns a key.
"""

from __future__ import annotations

import asyncio
import random
from typing import Dict, List, Optional, Sequence

from gubernator_tpu.config import BehaviorConfig, Config, DaemonConfig
from gubernator_tpu.transport.daemon import Daemon
from gubernator_tpu.types import PeerInfo


def _daemon_config(
    datacenter: str = "",
    behaviors: Optional[BehaviorConfig] = None,
    cache_size: int = 4096,
    resilience=None,
    fault_injector=None,
    federation: bool = False,
    federation_interval: float = 0.05,
) -> DaemonConfig:
    conf = DaemonConfig(
        grpc_listen_address="127.0.0.1:0",
        http_listen_address="",  # gateway off by default; tests opt in
        peer_discovery_type="none",
        data_center=datacenter,
    )
    conf.config = Config(
        behaviors=behaviors or BehaviorConfig(),
        cache_size=cache_size,
        data_center=datacenter,
        federation_enabled=federation,
        federation_interval=federation_interval,
    )
    if resilience is not None:
        conf.config.resilience = resilience
    conf.config.fault_injector = fault_injector
    return conf


class Cluster:
    """N in-process daemons with a static, fully-connected peer list."""

    def __init__(self):
        self.daemons: List[Daemon] = []
        self.peers: List[PeerInfo] = []

    # ------------------------------------------------------------------
    @classmethod
    async def start(
        cls,
        n: int,
        datacenters: Optional[Sequence[str]] = None,
        behaviors: Optional[BehaviorConfig] = None,
        cache_size: int = 4096,
        http_gateway: bool = False,
        global_mesh: bool = False,
        resilience=None,
        fault_injector=None,
        federation: bool = False,
        federation_interval: float = 0.05,
    ) -> "Cluster":
        """Boot ``n`` daemons (dc layout via ``datacenters``, one entry per
        daemon) and wire them into one cluster (cluster.go:123-189).

        ``global_mesh=True`` models mesh-resident peers: all daemons share
        one MeshGlobalEngine (one device per daemon) so GLOBAL limits
        reconcile via collectives instead of the gRPC loops.

        ``resilience``/``fault_injector`` thread the fault-tolerant peer
        path's knobs and the chaos hook into every daemon (the injector is
        shared, so one schedule partitions a peer cluster-wide).

        ``federation=True`` enables the inter-region envelope exchange
        (docs/federation.md) on daemons with a datacenter, at the fast
        test cadence ``federation_interval``.
        """
        c = cls()
        datacenters = list(datacenters or [""] * n)
        assert len(datacenters) == n
        mesh_engine = None
        if global_mesh:
            from gubernator_tpu.parallel.global_mesh import (
                MeshGlobalEngine,
                make_global_mesh,
            )

            sync_ms = int((behaviors or BehaviorConfig()).global_sync_wait * 500)
            mesh_engine = MeshGlobalEngine(
                mesh=make_global_mesh(n),
                capacity=min(cache_size, 1 << 16),
                min_reconcile_ms=sync_ms,
            )
        for idx, dc in enumerate(datacenters):
            conf = _daemon_config(dc, behaviors, cache_size,
                                  resilience, fault_injector,
                                  federation and bool(dc),
                                  federation_interval)
            if http_gateway:
                conf.http_listen_address = "127.0.0.1:0"
            d = Daemon(conf, global_mesh=mesh_engine, global_mesh_node=idx)
            await d.start()
            c.daemons.append(d)
        c.peers = [
            PeerInfo(
                grpc_address=d.conf.grpc_listen_address,
                http_address=d.conf.http_listen_address,
                datacenter=d.conf.data_center,
            )
            for d in c.daemons
        ]
        for d in c.daemons:
            d.set_peers(c.peers)
        for d in c.daemons:
            await d.wait_for_connect()
        return c

    async def stop(self) -> None:
        for d in self.daemons:
            await d.close()
        self.daemons = []

    # ------------------------------------------------------------------
    # Ownership introspection (cluster/cluster.go:81-110)
    # ------------------------------------------------------------------
    def find_owning_daemon(self, name: str, key: str) -> Daemon:
        """The daemon whose instance owns ``name_key``."""
        d0 = self.daemons[0]
        owner = d0.instance.get_peer(name + "_" + key)
        addr = owner.info.grpc_address
        for d in self.daemons:
            if d.conf.grpc_listen_address == addr:
                return d
        raise RuntimeError(f"no daemon listening on {addr}")

    def find_owning_daemon_in_region(
        self, name: str, key: str, datacenter: str
    ) -> Daemon:
        """The daemon owning ``name_key`` on ``datacenter``'s own ring.
        Resolution must go through a daemon IN that region — each local
        picker only contains its own datacenter's members."""
        d0 = self.get_random_peer(datacenter)
        owner = d0.instance.get_peer(name + "_" + key)
        addr = d0.conf.grpc_listen_address if owner is None \
            else owner.info.grpc_address
        for d in self.daemons:
            if d.conf.grpc_listen_address == addr:
                return d
        raise RuntimeError(f"no daemon listening on {addr}")

    def list_non_owning_daemons(self, name: str, key: str) -> List[Daemon]:
        owner = self.find_owning_daemon(name, key)
        return [d for d in self.daemons if d is not owner]

    def get_random_peer(self, datacenter: str = "") -> Daemon:
        pool = [
            d for d in self.daemons if d.conf.data_center == datacenter
        ]
        return random.choice(pool)

    def addresses(self) -> List[str]:
        return [d.conf.grpc_listen_address for d in self.daemons]

    async def restart(self, idx: int) -> Daemon:
        """Stop and re-start one daemon on its old port (cluster.go:139-148)."""
        old = self.daemons[idx]
        addr = old.conf.grpc_listen_address
        await old.close()
        conf = _daemon_config(
            old.conf.data_center,
            old.conf.config.behaviors,
            old.conf.config.cache_size,
            old.conf.config.resilience,
            old.conf.config.fault_injector,
            old.conf.config.federation_enabled,
            old.conf.config.federation_interval,
        )
        conf.grpc_listen_address = addr
        d = Daemon(conf)
        await d.start()
        d.set_peers(self.peers)
        await d.wait_for_connect()
        self.daemons[idx] = d
        return d

    # Metrics oracle: scrape one daemon's registry value
    # (the reference scrapes /metrics; same idea, in-process).
    def metric_value(self, idx: int, name: str, labels: Dict[str, str] = None):
        return self.daemons[idx].metrics.sample(name, labels)

    async def wait_for_metric(
        self,
        idx: int,
        name: str,
        minimum: float = 1.0,
        labels: Dict[str, str] = None,
        timeout: float = 5.0,
    ) -> float:
        """Poll one daemon's registry until ``name`` reaches ``minimum``
        — the metrics-as-oracle pattern the reference's distributed tests
        use instead of sleeps (functional_test.go:2184-2276)."""
        deadline = asyncio.get_running_loop().time() + timeout
        while True:
            v = self.metric_value(idx, name, labels)
            if v >= minimum:
                return v
            if asyncio.get_running_loop().time() > deadline:
                raise AssertionError(
                    f"metric {name}{labels or ''} on daemon {idx} stuck at"
                    f" {v}, wanted >= {minimum}"
                )
            await asyncio.sleep(0.01)

    async def wait_for_broadcast(
        self, idx: int, count: float = 1.0, timeout: float = 5.0
    ) -> float:
        """Wait until daemon ``idx`` (a GLOBAL owner) has completed
        ``count`` peer broadcasts (functional_test.go:2184 waitForBroadcast)."""
        return await self.wait_for_metric(
            idx, "gubernator_broadcast_duration_count", count, timeout=timeout
        )

    async def wait_for_update(
        self, idx: int, count: float = 1.0, timeout: float = 5.0
    ) -> float:
        """Wait until daemon ``idx`` (a non-owner) has flushed ``count``
        GLOBAL hit batches to the owner (functional_test.go:2230
        waitForUpdate; ours counts send flushes via global_send_duration)."""
        return await self.wait_for_metric(
            idx, "gubernator_global_send_duration_count", count, timeout=timeout
        )
