"""etcd-based discovery over etcd's v3 HTTP/JSON gateway.

Functional equivalent of the reference's ``etcd.go``: register this node
under ``<prefix><grpc_address>`` with a 30s lease kept alive in the
background, re-register if the lease is lost (etcd.go:221-315), watch the
prefix for membership changes (polled here instead of a gRPC watch stream —
the python etcd3 client isn't in the image, so this speaks the JSON gateway
with aiohttp), and delete + revoke on close.
"""

from __future__ import annotations

import asyncio
import base64
import json
import logging
from typing import Callable, List, Optional, Sequence

import aiohttp

from gubernator_tpu.types import PeerInfo

log = logging.getLogger("gubernator.etcd")

LEASE_TTL_S = 30  # etcd.go:31-36 etcdLeaseTTL


def _b64(s: str) -> str:
    return base64.b64encode(s.encode()).decode()


class EtcdPool:
    def __init__(
        self,
        endpoints: Sequence[str],
        key_prefix: str,
        info: PeerInfo,
        on_update: Callable[[List[PeerInfo]], None],
        poll_interval: float = 2.0,
        username: str = "",
        password: str = "",
    ):
        self.base = self._base_url(endpoints)
        self.key_prefix = key_prefix
        self.info = info
        self.on_update = on_update
        self.poll_interval = poll_interval
        self.auth = (username, password) if username else None
        self._lease_id: Optional[int] = None
        self._session: Optional[aiohttp.ClientSession] = None
        self._tasks: List[asyncio.Task] = []
        self._last: Optional[List[PeerInfo]] = None

    @staticmethod
    def _base_url(endpoints: Sequence[str]) -> str:
        ep = endpoints[0] if endpoints else "localhost:2379"
        if not ep.startswith("http"):
            ep = f"http://{ep}"
        return ep.rstrip("/")

    async def _post(self, path: str, payload: dict) -> dict:
        async with self._session.post(
            f"{self.base}{path}", json=payload
        ) as resp:
            resp.raise_for_status()
            return await resp.json()

    # ------------------------------------------------------------------
    async def _register(self) -> None:
        """Grant a lease and put our PeerInfo under it (etcd.go:233-259)."""
        out = await self._post("/v3/lease/grant", {"TTL": LEASE_TTL_S, "ID": 0})
        self._lease_id = int(out["ID"])
        key = self.key_prefix + self.info.grpc_address
        value = json.dumps(
            {
                "grpc_address": self.info.grpc_address,
                "http_address": self.info.http_address,
                "datacenter": self.info.datacenter,
            }
        )
        await self._post(
            "/v3/kv/put",
            {"key": _b64(key), "value": _b64(value), "lease": self._lease_id},
        )

    async def _keepalive_loop(self) -> None:
        """Refresh the lease; re-register from scratch when it's lost."""
        while True:
            await asyncio.sleep(LEASE_TTL_S / 3)
            try:
                out = await self._post(
                    "/v3/lease/keepalive", {"ID": self._lease_id}
                )
                ttl = int(out.get("result", {}).get("TTL", 0))
                if ttl <= 0:
                    raise RuntimeError("lease expired")
            except Exception as e:
                log.warning("etcd keepalive lost (%s); re-registering", e)
                try:
                    await self._register()
                except Exception as e2:
                    log.error("etcd re-register failed: %s", e2)

    async def _watch_loop(self) -> None:
        """Poll the prefix and emit membership changes (etcd.go:109-219)."""
        range_end = self.key_prefix[:-1] + chr(ord(self.key_prefix[-1]) + 1)
        while True:
            try:
                out = await self._post(
                    "/v3/kv/range",
                    {"key": _b64(self.key_prefix), "range_end": _b64(range_end)},
                )
                peers = []
                for kv in out.get("kvs", []):
                    try:
                        v = json.loads(base64.b64decode(kv["value"]))
                        peers.append(
                            PeerInfo(
                                grpc_address=v.get("grpc_address", ""),
                                http_address=v.get("http_address", ""),
                                datacenter=v.get("datacenter", ""),
                            )
                        )
                    except (ValueError, KeyError):
                        continue
                peers.sort(key=lambda p: p.grpc_address)
                if peers != self._last:
                    self._last = peers
                    self.on_update(list(peers))
            except Exception as e:
                log.warning("etcd range failed: %s", e)
            await asyncio.sleep(self.poll_interval)

    # ------------------------------------------------------------------
    async def start(self) -> None:
        self._session = aiohttp.ClientSession(
            auth=aiohttp.BasicAuth(*self.auth) if self.auth else None
        )
        await self._register()
        self._tasks = [
            asyncio.create_task(self._keepalive_loop(), name="etcd-keepalive"),
            asyncio.create_task(self._watch_loop(), name="etcd-watch"),
        ]

    async def close(self) -> None:
        for t in self._tasks:
            t.cancel()
        await asyncio.gather(*self._tasks, return_exceptions=True)
        try:
            key = self.key_prefix + self.info.grpc_address
            await self._post("/v3/kv/deleterange", {"key": _b64(key)})
            if self._lease_id:
                await self._post("/v3/lease/revoke", {"ID": self._lease_id})
        except Exception:
            pass
        if self._session is not None:
            await self._session.close()
