"""Gossip membership: the memberlist-equivalent discovery pool.

The reference rides hashicorp/memberlist (SWIM gossip over UDP/TCP,
``memberlist.go``).  That exact wire protocol isn't reproducible without
the library, so this is a self-contained **push-pull gossip** with the
same observable contract: nodes join via ``known_nodes``, carry their
``PeerInfo`` as node metadata (memberlist.go:126-151), learn the full
membership transitively, detect dead peers via failed probes, and emit
``on_update`` on every membership change.

Protocol: JSON-over-TCP.  Each round (1s) a node picks a random peer and
exchanges full state — a map ``addr → {info, incarnation, alive}``.  Entries
merge by highest incarnation; a node always re-asserts itself with a higher
incarnation if someone claims it dead (SWIM refutation).  A peer unreachable
for ``suspect_after`` consecutive probes is marked dead and pruned after it
gossips around.
"""

from __future__ import annotations

import asyncio
import json
import logging
import random
import time
from typing import Callable, Dict, List, Optional, Sequence

from gubernator_tpu.types import PeerInfo

log = logging.getLogger("gubernator.gossip")


class MemberlistPool:
    def __init__(
        self,
        bind_address: str,
        known_nodes: Sequence[str],
        info: PeerInfo,
        on_update: Callable[[List[PeerInfo]], None],
        gossip_interval: float = 1.0,
        suspect_after: int = 3,
    ):
        if not bind_address:
            raise ValueError(
                "GUBER_MEMBERLIST_ADDRESS is required for member-list discovery"
            )
        self.bind_address = bind_address
        self.known_nodes = [n for n in known_nodes if n and n != bind_address]
        self.info = info
        self.on_update = on_update
        self.gossip_interval = gossip_interval
        self.suspect_after = suspect_after
        # addr (gossip address) → member record
        self._members: Dict[str, dict] = {
            bind_address: {
                "info": self._info_dict(info),
                "incarnation": int(time.time() * 1000),
                "alive": True,
            }
        }
        self._fails: Dict[str, int] = {}
        self._server: Optional[asyncio.AbstractServer] = None
        self._task: Optional[asyncio.Task] = None
        self._last_emitted: Optional[List[PeerInfo]] = None

    @staticmethod
    def _info_dict(info: PeerInfo) -> dict:
        return {
            "grpc_address": info.grpc_address,
            "http_address": info.http_address,
            "datacenter": info.datacenter,
        }

    # ------------------------------------------------------------------
    # State merge
    # ------------------------------------------------------------------
    def _merge(self, remote: Dict[str, dict]) -> None:
        changed = False
        for addr, rec in remote.items():
            if addr == self.bind_address:
                # Refute any claim that we are dead (SWIM refutation).
                if not rec.get("alive", True):
                    mine = self._members[addr]
                    if rec.get("incarnation", 0) >= mine["incarnation"]:
                        mine["incarnation"] = rec["incarnation"] + 1
                        changed = True
                continue
            mine = self._members.get(addr)
            if mine is None or rec.get("incarnation", 0) > mine["incarnation"]:
                self._members[addr] = dict(rec)
                changed = True
            elif (
                rec.get("incarnation", 0) == mine["incarnation"]
                and not rec.get("alive", True)
                and mine["alive"]
            ):
                mine["alive"] = False  # dead beats alive at equal incarnation
                changed = True
        if changed:
            self._emit()

    def _emit(self) -> None:
        peers = sorted(
            (
                PeerInfo(**rec["info"])
                for rec in self._members.values()
                if rec.get("alive", True)
            ),
            key=lambda p: p.grpc_address,
        )
        if peers != self._last_emitted:
            self._last_emitted = peers
            self.on_update(list(peers))

    # ------------------------------------------------------------------
    # Wire
    # ------------------------------------------------------------------
    async def _handle(self, reader, writer) -> None:
        try:
            line = await asyncio.wait_for(reader.readline(), 5.0)
            remote = json.loads(line)
            self._merge(remote.get("members", {}))
            writer.write(
                (json.dumps({"members": self._members}) + "\n").encode()
            )
            await writer.drain()
        except (asyncio.TimeoutError, OSError, ValueError):
            pass
        finally:
            writer.close()

    async def _push_pull(self, addr: str) -> bool:
        host, _, port = addr.rpartition(":")
        try:
            reader, writer = await asyncio.wait_for(
                asyncio.open_connection(host, int(port)), 2.0
            )
            writer.write(
                (json.dumps({"members": self._members}) + "\n").encode()
            )
            await writer.drain()
            line = await asyncio.wait_for(reader.readline(), 5.0)
            self._merge(json.loads(line).get("members", {}))
            writer.close()
            return True
        except (OSError, ValueError, asyncio.TimeoutError):
            return False

    async def _gossip_loop(self) -> None:
        while True:
            await asyncio.sleep(self.gossip_interval)
            candidates = [
                a
                for a, rec in self._members.items()
                if a != self.bind_address and rec.get("alive", True)
            ]
            # Keep trying seeds until we've met someone.
            if not candidates and self.known_nodes:
                candidates = list(self.known_nodes)
            if not candidates:
                continue
            addr = random.choice(candidates)
            ok = await self._push_pull(addr)
            if ok:
                self._fails.pop(addr, None)
            else:
                n = self._fails.get(addr, 0) + 1
                self._fails[addr] = n
                rec = self._members.get(addr)
                if rec is not None and rec["alive"] and n >= self.suspect_after:
                    rec["alive"] = False
                    rec["incarnation"] = rec.get("incarnation", 0)
                    log.info("gossip: marking %s dead after %d failed probes", addr, n)
                    self._emit()

    # ------------------------------------------------------------------
    async def start(self) -> None:
        host, _, port = self.bind_address.rpartition(":")
        self._server = await asyncio.start_server(
            self._handle, host or "0.0.0.0", int(port)
        )
        # Initial join (memberlist.go:126-151): push-pull every seed once.
        for seed in self.known_nodes:
            await self._push_pull(seed)
        self._task = asyncio.create_task(self._gossip_loop(), name="gossip")
        self._emit()

    async def close(self) -> None:
        """Leave: mark ourselves dead and gossip it once."""
        me = self._members[self.bind_address]
        me["alive"] = False
        me["incarnation"] += 1
        for addr, rec in list(self._members.items()):
            if addr != self.bind_address and rec.get("alive", True):
                await self._push_pull(addr)
                break
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
