"""Static peer list: the no-discovery pool (reference cluster tests inject
peers statically via SetPeers, cluster/cluster.go:151-189)."""

from __future__ import annotations

from typing import Callable, List, Sequence

from gubernator_tpu.types import PeerInfo


class StaticPool:
    def __init__(
        self,
        peers: Sequence[PeerInfo],
        on_update: Callable[[List[PeerInfo]], None],
    ):
        self.peers = list(peers)
        self.on_update = on_update

    async def start(self) -> None:
        self.on_update(list(self.peers))

    async def close(self) -> None:
        pass
