"""Kubernetes discovery: poll Endpoints or Pods via the API server.

Functional equivalent of the reference's ``kubernetes.go``: watch ready
addresses behind a label selector, mechanism switchable between
``endpoints`` and ``pods`` (kubernetes.go:45-63,101-110), peers built from
address + ``pod_port``, self detected via ``pod_ip``.  Speaks the k8s REST
API directly with aiohttp using in-cluster credentials (service-account
token + CA), so no kubernetes client package is required.
"""

from __future__ import annotations

import asyncio
import logging
import os
import ssl
from typing import Callable, List, Optional

import aiohttp

from gubernator_tpu.types import PeerInfo

log = logging.getLogger("gubernator.k8s")

SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


class K8sPool:
    def __init__(
        self,
        namespace: str,
        selector: str,
        pod_ip: str,
        pod_port: str,
        on_update: Callable[[List[PeerInfo]], None],
        mechanism: str = "endpoints",
        poll_interval: float = 5.0,
        api_server: str = "",
        datacenter: str = "",
    ):
        if mechanism not in ("endpoints", "pods"):
            raise ValueError(
                "GUBER_K8S_WATCH_MECHANISM must be 'endpoints' or 'pods'"
            )
        self.namespace = namespace or "default"
        self.selector = selector
        self.pod_ip = pod_ip
        self.pod_port = pod_port
        self.on_update = on_update
        self.mechanism = mechanism
        self.poll_interval = poll_interval
        self.datacenter = datacenter
        host = api_server or (
            f"https://{os.environ.get('KUBERNETES_SERVICE_HOST', 'kubernetes.default.svc')}"
            f":{os.environ.get('KUBERNETES_SERVICE_PORT', '443')}"
        )
        self.base = host.rstrip("/")
        self._session: Optional[aiohttp.ClientSession] = None
        self._task: Optional[asyncio.Task] = None
        self._last: Optional[List[PeerInfo]] = None

    def _make_session(self) -> aiohttp.ClientSession:
        headers = {}
        token_path = os.path.join(SA_DIR, "token")
        if os.path.exists(token_path):
            with open(token_path) as f:
                headers["Authorization"] = f"Bearer {f.read().strip()}"
        ca_path = os.path.join(SA_DIR, "ca.crt")
        if os.path.exists(ca_path):
            ctx = ssl.create_default_context(cafile=ca_path)
        else:
            ctx = ssl.create_default_context()
        return aiohttp.ClientSession(
            headers=headers, connector=aiohttp.TCPConnector(ssl=ctx)
        )

    async def _list_addresses(self) -> List[str]:
        if self.mechanism == "endpoints":
            url = (
                f"{self.base}/api/v1/namespaces/{self.namespace}/endpoints"
                f"?labelSelector={self.selector}"
            )
            async with self._session.get(url) as resp:
                resp.raise_for_status()
                out = await resp.json()
            addrs: List[str] = []
            for item in out.get("items", []):
                for subset in item.get("subsets", []) or []:
                    for addr in subset.get("addresses", []) or []:
                        if addr.get("ip"):
                            addrs.append(addr["ip"])
            return addrs
        url = (
            f"{self.base}/api/v1/namespaces/{self.namespace}/pods"
            f"?labelSelector={self.selector}"
        )
        async with self._session.get(url) as resp:
            resp.raise_for_status()
            out = await resp.json()
        addrs = []
        for pod in out.get("items", []):
            status = pod.get("status", {})
            if status.get("phase") != "Running":
                continue
            conds = {
                c.get("type"): c.get("status")
                for c in status.get("conditions", []) or []
            }
            if conds.get("Ready") == "True" and status.get("podIP"):
                addrs.append(status["podIP"])
        return addrs

    async def _loop(self) -> None:
        while True:
            try:
                ips = sorted(set(await self._list_addresses()))
                peers = [
                    PeerInfo(
                        grpc_address=f"{ip}:{self.pod_port}",
                        datacenter=self.datacenter,
                    )
                    for ip in ips
                ]
                if peers != self._last:
                    self._last = peers
                    self.on_update(list(peers))
            except Exception as e:
                log.warning("k8s discovery poll failed: %s", e)
            await asyncio.sleep(self.poll_interval)

    async def start(self) -> None:
        # guber: allow-G002(startup-only session build - reads the service-account token once before the poll loop exists)
        self._session = self._make_session()
        self._task = asyncio.create_task(self._loop(), name="k8s-discovery")

    async def close(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
        if self._session is not None:
            await self._session.close()
