"""DNS-based discovery: poll A/AAAA records of an FQDN.

Functional equivalent of the reference's ``dns.go`` (miekg/dns raw
queries + TTL-driven repoll, dns.go:130-214): resolve the FQDN, map each
address to ``ip:grpc_port`` / ``ip:http_port`` peers, re-poll on an
interval, and emit ``on_update`` when membership changes.  Uses the
system resolver (stdlib) instead of raw DNS packets — record TTLs aren't
visible that way, so the poll interval is fixed (the reference also floors
its delay to ~1s and caps it at 300s).
"""

from __future__ import annotations

import asyncio
import logging
import socket
from typing import Callable, List, Optional

from gubernator_tpu.types import PeerInfo

log = logging.getLogger("gubernator.dns")


class DNSPool:
    def __init__(
        self,
        fqdn: str,
        grpc_port: int,
        http_port: int,
        on_update: Callable[[List[PeerInfo]], None],
        poll_interval: float = 15.0,
        datacenter: str = "",
    ):
        if not fqdn:
            raise ValueError("GUBER_DNS_FQDN is required for dns discovery")
        self.fqdn = fqdn
        self.grpc_port = grpc_port
        self.http_port = http_port
        self.on_update = on_update
        self.poll_interval = poll_interval
        self.datacenter = datacenter
        self._task: Optional[asyncio.Task] = None
        self._last: Optional[List[PeerInfo]] = None

    async def _resolve(self) -> List[PeerInfo]:
        loop = asyncio.get_running_loop()
        infos = await loop.getaddrinfo(
            self.fqdn, None, type=socket.SOCK_STREAM
        )
        peers = {}
        for family, _, _, _, sockaddr in infos:
            ip = sockaddr[0]
            host = f"[{ip}]" if family == socket.AF_INET6 else ip
            peers[ip] = PeerInfo(
                grpc_address=f"{host}:{self.grpc_port}",
                http_address=f"{host}:{self.http_port}" if self.http_port else "",
                datacenter=self.datacenter,
            )
        return sorted(peers.values(), key=lambda p: p.grpc_address)

    async def _loop(self) -> None:
        while True:
            try:
                peers = await self._resolve()
                if peers != self._last:
                    self._last = peers
                    self.on_update(list(peers))
            except OSError as e:
                log.warning("dns lookup of %s failed: %s", self.fqdn, e)
            await asyncio.sleep(self.poll_interval)

    async def start(self) -> None:
        self._task = asyncio.create_task(self._loop(), name="dns-discovery")

    async def close(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
