"""Peer discovery pools: membership sources emitting ``on_update([PeerInfo])``.

Each pool mirrors one of the reference's discovery backends (``etcd.go``,
``memberlist.go``, ``kubernetes.go``, ``dns.go``): it watches some
membership source and calls ``on_update`` with the full peer list on every
change (the reference's ``UpdateFunc`` contract, config.go:177).  All pools
expose ``await start()`` / ``await close()`` (reference ``PoolInterface``,
etcd.go:38-40).
"""

from gubernator_tpu.discovery.static import StaticPool  # noqa: F401
from gubernator_tpu.discovery.dnspool import DNSPool  # noqa: F401
from gubernator_tpu.discovery.etcdpool import EtcdPool  # noqa: F401
from gubernator_tpu.discovery.k8spool import K8sPool  # noqa: F401
from gubernator_tpu.discovery.gossip import MemberlistPool  # noqa: F401
