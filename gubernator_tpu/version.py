"""Single source for the release version.

The reference stamps ``Version`` into its binary via ldflags and keeps a
``version`` file + packaging metadata in sync, checked by
``contrib/check-version.sh`` — the analogs here are this module, the
repo-root ``version`` file, ``pyproject.toml``, and our
``contrib/check-version.sh``.
"""

VERSION = "0.2.0"


def banner() -> str:
    """The startup identification line (cmd/gubernator/main.go:53)."""
    import platform

    return (
        f"gubernator-tpu {VERSION} "
        f"(python {platform.python_version()}/{platform.machine()})"
    )
