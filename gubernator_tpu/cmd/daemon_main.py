"""Server entry point: ``python -m gubernator_tpu.cmd.daemon_main``.

The reference's ``cmd/gubernator/main.go``: two flags (``-config``,
``-debug``), env-first configuration, SIGTERM/SIGINT graceful shutdown.
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import signal
import sys

from gubernator_tpu.config import setup_daemon_config
from gubernator_tpu.transport.daemon import spawn_daemon


async def run(config_file: str) -> None:
    # guber: allow-G002(startup-only config read - the loop serves nothing until this returns)
    conf = setup_daemon_config(config_file)
    level = getattr(logging, conf.log_level.upper(), logging.INFO)
    if conf.log_format == "json":
        logging.basicConfig(
            level=level,
            format='{"time":"%(asctime)s","level":"%(levelname)s",'
            '"logger":"%(name)s","message":"%(message)s"}',
        )
    else:
        logging.basicConfig(level=level)
    from gubernator_tpu.version import banner

    logging.getLogger("gubernator").info("%s", banner())
    daemon = await spawn_daemon(conf)
    print("Ready", flush=True)  # readiness marker (client tests wait on it)

    stop = asyncio.Event()
    loop = asyncio.get_running_loop()

    def _on_signal(signame: str) -> None:
        logging.getLogger("gubernator").info(
            "received %s: draining (readiness -> 503, flushing GLOBAL "
            "buffers, final snapshot)", signame,
        )
        stop.set()

    for sig in (signal.SIGTERM, signal.SIGINT):
        loop.add_signal_handler(sig, _on_signal, sig.name)
    await stop.wait()
    # Graceful drain (docs/persistence.md): close flips /readyz to 503,
    # flushes the GLOBAL hit/broadcast/redelivery buffers under the
    # GUBER_DRAIN_TIMEOUT budget, writes the final base snapshot, then
    # stops the listeners — a drained exit loses zero accounting.
    await daemon.close()
    logging.getLogger("gubernator").info("drain complete; exiting")


def main(argv=None) -> int:
    from gubernator_tpu.version import banner

    p = argparse.ArgumentParser(description="gubernator-tpu rate-limit daemon")
    p.add_argument("-config", "--config", default="", help="path to a key=value config file")
    p.add_argument("-debug", "--debug", action="store_true", help="debug logging")
    p.add_argument("-version", "--version", action="version", version=banner())
    args = p.parse_args(argv)
    if args.debug:
        import os

        os.environ["GUBER_LOG_LEVEL"] = "debug"
    try:
        asyncio.run(run(args.config))
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
