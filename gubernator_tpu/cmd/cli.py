"""Synthetic load generator: ``python -m gubernator_tpu.cmd.cli``.

The reference's ``cmd/gubernator-cli/main.go``: generate a pool of random
token-bucket limits and fire them at a server with bounded concurrency,
reporting throughput and over-limit counts.
"""

from __future__ import annotations

import argparse
import asyncio
import random
import string
import sys
import time

from gubernator_tpu.transport.daemon import DaemonClient
from gubernator_tpu.types import Algorithm, RateLimitRequest, Status


def _rand_key(n: int = 10) -> str:
    return "".join(random.choice(string.ascii_lowercase) for _ in range(n))


async def run(args) -> None:
    limits = [
        RateLimitRequest(
            name=f"gubernator-cli-{i}",
            unique_key=_rand_key(),
            hits=1,
            limit=random.randint(1, 100),
            duration=random.randint(1000, 60_000),
            algorithm=Algorithm.TOKEN_BUCKET,
        )
        for i in range(args.limits)
    ]
    client = DaemonClient(args.address)
    sem = asyncio.Semaphore(args.concurrency)
    stats = {"ok": 0, "over": 0, "err": 0}

    async def one(i: int):
        async with sem:
            r = random.choice(limits)
            try:
                out = await client.get_rate_limits([r], timeout=args.timeout)
            except Exception:
                stats["err"] += 1
                return
            if out[0].error:
                stats["err"] += 1
            elif out[0].status == Status.OVER_LIMIT:
                stats["over"] += 1
            else:
                stats["ok"] += 1

    t0 = time.perf_counter()
    await asyncio.gather(*(one(i) for i in range(args.requests)))
    dt = time.perf_counter() - t0
    await client.close()
    print(
        f"{args.requests} requests in {dt:.2f}s "
        f"({args.requests / dt:,.0f} req/s) — "
        f"ok={stats['ok']} over_limit={stats['over']} errors={stats['err']}"
    )


def main(argv=None) -> int:
    from gubernator_tpu.version import banner

    p = argparse.ArgumentParser(description="gubernator-tpu load generator")
    p.add_argument("--version", action="version", version=banner())
    p.add_argument("--address", default="localhost:81")
    p.add_argument("--limits", type=int, default=2000,
                   help="number of distinct random rate limits")
    p.add_argument("--requests", type=int, default=10_000)
    p.add_argument("--concurrency", type=int, default=128)
    p.add_argument("--timeout", type=float, default=5.0)
    args = p.parse_args(argv)
    asyncio.run(run(args))
    return 0


if __name__ == "__main__":
    sys.exit(main())
