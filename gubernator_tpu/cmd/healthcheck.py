"""Container healthcheck probe: ``python -m gubernator_tpu.cmd.healthcheck``.

The reference's ``cmd/healthcheck/main.go``: GET /v1/HealthCheck on the
local daemon, exit 2 unless it reports healthy — suitable as a container
HEALTHCHECK command.
"""

from __future__ import annotations

import json
import os
import sys
import urllib.error
import urllib.request


def main(argv=None) -> int:
    # Prefer the no-mTLS status listener when configured: under
    # GUBER_TLS_CLIENT_AUTH the main gateway rejects cleartext probes,
    # which is exactly what GUBER_STATUS_HTTP_ADDRESS exists for.
    addr = os.environ.get("GUBER_STATUS_HTTP_ADDRESS") or os.environ.get(
        "GUBER_HTTP_ADDRESS", "localhost:80"
    )
    url = f"http://{addr}/v1/HealthCheck"
    try:
        with urllib.request.urlopen(url, timeout=5) as resp:
            body = json.loads(resp.read())
    except urllib.error.HTTPError as e:
        # The daemon answers 503 with the health JSON body when unhealthy
        # (e.g. a majority of peers behind open circuit breakers) —
        # surface its message instead of the bare HTTP error.
        try:
            body = json.loads(e.read())
        except Exception:
            print(f"healthcheck failed: {e}", file=sys.stderr)
            return 2
    except Exception as e:
        print(f"healthcheck failed: {e}", file=sys.stderr)
        return 2
    if body.get("status") != "healthy":
        print(f"unhealthy: {body.get('message', '')}", file=sys.stderr)
        return 2
    print("healthy")
    return 0


if __name__ == "__main__":
    sys.exit(main())
