"""Container healthcheck probe: ``python -m gubernator_tpu.cmd.healthcheck``.

The reference's ``cmd/healthcheck/main.go``: GET /v1/HealthCheck on the
local daemon, exit 2 unless it reports healthy — suitable as a container
HEALTHCHECK command.

``--ready`` probes /readyz instead (readiness, not liveness): exit 2
while the daemon is still restoring its snapshot or graceful-draining —
the flag a k8s readinessProbe exec command should use so traffic routes
only to nodes that want it (docs/persistence.md).
"""

from __future__ import annotations

import json
import sys
import urllib.error
import urllib.request

from gubernator_tpu.config import env_knob


def main(argv=None) -> int:
    # Manual flag scan, not argparse: the probe is also called in-process
    # (tests, embedding) where sys.argv belongs to someone else and must
    # not be *parsed* — but the console-script entry point passes no
    # argv, so the literal flag is still honored from the command line.
    ready_probe = "--ready" in (sys.argv[1:] if argv is None else argv)

    # Prefer the no-mTLS status listener when configured: under
    # GUBER_TLS_CLIENT_AUTH the main gateway rejects cleartext probes,
    # which is exactly what GUBER_STATUS_HTTP_ADDRESS exists for.
    # Registry reads (config.env_knob) — no jax import rides along:
    # the package root and config are device-free by design.
    addr = env_knob("GUBER_STATUS_HTTP_ADDRESS") or env_knob(
        "GUBER_HTTP_ADDRESS", "localhost:80"
    )
    path = "/readyz" if ready_probe else "/v1/HealthCheck"
    url = f"http://{addr}{path}"
    try:
        with urllib.request.urlopen(url, timeout=5) as resp:
            body = json.loads(resp.read())
    except urllib.error.HTTPError as e:
        # The daemon answers 503 with a JSON body when unhealthy (e.g. a
        # majority of peers behind open circuit breakers) or not ready
        # (restoring / draining) — surface its message, not the bare
        # HTTP error.
        try:
            body = json.loads(e.read())
        except Exception:
            print(f"healthcheck failed: {e}", file=sys.stderr)
            return 2
    except Exception as e:
        print(f"healthcheck failed: {e}", file=sys.stderr)
        return 2
    if ready_probe:
        if not body.get("ready"):
            state = "draining" if body.get("draining") else "starting"
            print(f"not ready: {state}", file=sys.stderr)
            return 2
        print("ready")
        return 0
    if body.get("status") != "healthy":
        print(f"unhealthy: {body.get('message', '')}", file=sys.stderr)
        return 2
    print("healthy")
    return 0


if __name__ == "__main__":
    sys.exit(main())
