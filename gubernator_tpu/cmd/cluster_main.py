"""Local development cluster: ``python -m gubernator_tpu.cmd.cluster_main``.

The reference's ``cmd/gubernator-cluster/main.go``: a 6-instance in-process
cluster on fixed localhost ports for client development; prints "Ready"
once all instances answer health checks.
"""

from __future__ import annotations

import asyncio
import signal
import sys

from gubernator_tpu.config import BehaviorConfig, Config, DaemonConfig
from gubernator_tpu.transport.daemon import Daemon
from gubernator_tpu.types import PeerInfo

GRPC_PORTS = range(9990, 9996)  # reference uses :9990-:9995


async def run() -> None:
    daemons = []
    for port in GRPC_PORTS:
        conf = DaemonConfig(
            grpc_listen_address=f"127.0.0.1:{port}",
            http_listen_address=f"127.0.0.1:{port + 100}",
            peer_discovery_type="none",
        )
        conf.config = Config(behaviors=BehaviorConfig(), cache_size=50_000)
        d = Daemon(conf)
        await d.start()
        daemons.append(d)
    peers = [
        PeerInfo(
            grpc_address=d.conf.grpc_listen_address,
            http_address=d.conf.http_listen_address,
        )
        for d in daemons
    ]
    for d in daemons:
        d.set_peers(peers)
    for d in daemons:
        await d.wait_for_connect()
    print("Ready", flush=True)

    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGTERM, signal.SIGINT):
        loop.add_signal_handler(sig, stop.set)
    await stop.wait()
    for d in daemons:
        await d.close()


def main() -> int:
    try:
        asyncio.run(run())
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
