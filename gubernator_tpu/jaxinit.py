"""jax bootstrap: x64 mode + the persistent compile cache.

Importing this module configures jax for the whole process; every module
that imports jax MUST import :mod:`gubernator_tpu.jaxinit` first (the
convention that replaced doing this work in the package ``__init__`` —
which made ``import gubernator_tpu`` pull jax into processes that never
touch a device: the container healthcheck probe, config parsing, and the
static-analysis CLI, none of which should pay a multi-second jax import
or require the toolchain at all).

64-bit mode is required: the wire contract is int64 milliseconds /
int64 hits-limits, and leaky-bucket remaining is float64.
"""

from __future__ import annotations

import os

import jax

jax.config.update("jax_enable_x64", True)


def configure_compile_cache(environ=None) -> None:
    """Persistent XLA compilation cache, on by default: tick-program
    compiles cost tens of seconds on TPU toolchains and recur on every
    daemon restart otherwise (measured 30s -> 8.5s cold start cached).

    ``GUBER_COMPILE_CACHE_DIR=off`` disables; any other value overrides
    the location; an explicit ``JAX_COMPILATION_CACHE_DIR`` always wins.
    Runs at import AND again from ``setup_daemon_config`` so the knob
    also works from a ``-config`` file (which loads into the environment
    after import)."""
    env = os.environ if environ is None else environ
    cache_dir = env.get("GUBER_COMPILE_CACHE_DIR", "")
    if cache_dir.lower() in ("off", "0", "false"):
        jax.config.update("jax_compilation_cache_dir", None)
        return
    if env.get("JAX_COMPILATION_CACHE_DIR"):
        # jax bound this option at import time; a -config file loads the
        # env var after import, so re-apply it explicitly.
        jax.config.update(
            "jax_compilation_cache_dir", env["JAX_COMPILATION_CACHE_DIR"]
        )
        return
    cache_dir = cache_dir or os.path.join(
        os.path.expanduser("~"), ".cache", "gubernator-tpu", "xla"
    )
    try:
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
    except OSError:  # unwritable home: run uncached
        return
    # jax's default floor (1s) only caches the big tick programs; the
    # long tail of sub-second helper compiles (packers, scans, installs)
    # recurs on every process start and dominates single-core cold
    # starts — cache everything.
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)


configure_compile_cache()
