"""Configuration: library Config + daemon config, env-var driven.

Mirrors the reference's three-level hierarchy (``config.go:49-252``):
``BehaviorConfig`` (batching/global cadences) inside ``Config`` (library
instance) inside ``DaemonConfig`` (transport + discovery + TLS), with the
same defaults (``config.go:126-141``) and the same env-first setup path
(``SetupDaemonConfig``, ``config.go:270-479``): every knob is a ``GUBER_*``
environment variable, and an optional ``key=value`` config file is loaded
*into* the environment before reading (``config.go:635-658``).

TPU-specific additions live in :class:`Config` and are prefixed
``GUBER_TPU_`` (table capacity per device, tick batch size, mesh shards) —
they replace the reference's worker-count knob (workers are goroutines
there; here the "workers" are table shards on the device mesh).
"""

from __future__ import annotations

import logging
import os
import random
import socket
import string
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from gubernator_tpu.resilience import ResilienceConfig
from gubernator_tpu.types import MAX_BATCH_SIZE, PeerInfo

log = logging.getLogger("gubernator")

# Selector for which discovery pool the daemon runs
# (reference daemon.go:208-243 switch).
DISCOVERY_TYPES = ("member-list", "etcd", "dns", "k8s", "none")


# ----------------------------------------------------------------------
# The env-var registry: THE single source of truth for the supported
# ``GUBER_*`` surface.  guberlint rule G004 (gubernator_tpu/analysis)
# enforces that every GUBER_* name mentioned anywhere in the package is
# a key here, that every key is documented in example.conf (and vice
# versa), and that no module reads os.environ for a GUBER_* knob
# directly — module-level fast-path reads go through :func:`env_knob`.
# ----------------------------------------------------------------------
ENV_REGISTRY: Dict[str, str] = {
    "GUBER_ADVERTISE_ADDRESS": "address peers use to reach this node",
    "GUBER_AUTOSCALE_COOLDOWN_DOWN": "autoscaler: quiet period before a scale-down",
    "GUBER_AUTOSCALE_COOLDOWN_UP": "autoscaler: quiet period before a scale-up",
    "GUBER_AUTOSCALE_DRY_RUN": "autoscaler: record decisions without acting",
    "GUBER_AUTOSCALE_ENABLED": "telemetry-driven shard autoscaler on/off",
    "GUBER_AUTOSCALE_HYSTERESIS": "autoscaler: scale-down band = target p99 × this",
    "GUBER_AUTOSCALE_INTERVAL": "autoscaler: signal sampling cadence",
    "GUBER_AUTOSCALE_MAX_PER_HOUR": "autoscaler: rolling-hour transition cap",
    "GUBER_AUTOSCALE_MAX_SHARDS": "autoscaler: shard-count ceiling",
    "GUBER_AUTOSCALE_MIN_SHARDS": "autoscaler: shard-count floor",
    "GUBER_AUTOSCALE_OCCUPANCY_LOW": "autoscaler: scale-down occupancy threshold",
    "GUBER_AUTOSCALE_QUEUE_HIGH": "autoscaler: scale-up queue-depth high-water",
    "GUBER_AUTOSCALE_TARGET_P99_MS": "autoscaler: scale-up window p99 threshold",
    "GUBER_AUTOSCALE_WINDOWS": "autoscaler: consecutive windows before acting",
    "GUBER_BATCH_LIMIT": "max requests per forwarded peer batch",
    "GUBER_BATCH_TIMEOUT": "deadline for a forwarded peer batch",
    "GUBER_BATCH_WAIT": "batch accumulation window (the tick wait)",
    "GUBER_BREAKER_ENABLED": "per-peer circuit breakers on/off",
    "GUBER_BREAKER_FAILURE_THRESHOLD": "failure fraction that opens a breaker",
    "GUBER_BREAKER_HALF_OPEN_PROBES": "probe RPCs allowed half-open",
    "GUBER_BREAKER_MIN_REQUESTS": "min window samples before tripping",
    "GUBER_BREAKER_OPEN_CAP": "max open duration (backoff cap)",
    "GUBER_BREAKER_OPEN_FOR": "initial open duration",
    "GUBER_BREAKER_WINDOW": "sliding failure window length",
    "GUBER_CACHE_SIZE": "device bucket-table capacity (slots)",
    "GUBER_COLD_CACHE_SIZE": "host-side cold-tier entry budget (0 = off)",
    "GUBER_COMPILE_CACHE_DIR": "persistent XLA compile cache dir / 'off'",
    "GUBER_DATA_CENTER": "datacenter name for region-aware picking",
    "GUBER_DEBUG_ENDPOINTS": "serve /debug/* introspection endpoints (0/1)",
    "GUBER_DISABLE_BATCHING": "disable peer-forwarding batches",
    "GUBER_DNS_FQDN": "dns discovery: name to resolve for peers",
    "GUBER_DRAIN_TIMEOUT": "graceful-shutdown GLOBAL flush budget",
    "GUBER_EDGE_RING_DEPTH": "edge plane: response slots per worker",
    "GUBER_EDGE_SHM_SLABS": "edge plane: request slabs per worker",
    "GUBER_EDGE_WORKERS": "edge decode worker processes (0 = off)",
    "GUBER_ETCD_DIAL_TIMEOUT": "etcd discovery: dial timeout",
    "GUBER_ETCD_ENDPOINTS": "etcd discovery: endpoints (comma list)",
    "GUBER_ETCD_KEY_PREFIX": "etcd discovery: peer key prefix",
    "GUBER_ETCD_PASSWORD": "etcd discovery: password",
    "GUBER_ETCD_USER": "etcd discovery: username",
    "GUBER_FAULT_DELAY": "fault injection: added per-RPC latency",
    "GUBER_FAULT_DROP_RATE": "fault injection: DEADLINE_EXCEEDED rate",
    "GUBER_FAULT_ERROR_RATE": "fault injection: UNAVAILABLE rate",
    "GUBER_FAULT_PARTITION": "fault injection: 100% UNAVAILABLE",
    "GUBER_FAULT_PEERS": "fault injection: target peers or '*'",
    "GUBER_FAULT_SEED": "fault injection: RNG seed",
    "GUBER_FEDERATION_BATCH_LIMIT": "max envelope records per federation flush",
    "GUBER_FEDERATION_ENABLED": "multi-region federation exchange on/off",
    "GUBER_FEDERATION_INTERVAL": "inter-region envelope exchange cadence",
    "GUBER_FEDERATION_TIMEOUT": "deadline for federation envelope RPCs",
    "GUBER_FLIGHT_RECORDER_WINDOWS": "flight-recorder ring size (window records)",
    "GUBER_FORCE_GLOBAL": "force GLOBAL behavior on every request",
    "GUBER_FORWARD_BACKOFF_BASE": "forward-retry backoff base",
    "GUBER_FORWARD_BACKOFF_CAP": "forward-retry backoff cap",
    "GUBER_FORWARD_MAX_ATTEMPTS": "forward-retry attempt budget",
    "GUBER_GLOBAL_BATCH_LIMIT": "max records per GLOBAL flush batch",
    "GUBER_GLOBAL_SYNC_WAIT": "GLOBAL reconcile cadence",
    "GUBER_GLOBAL_TIMEOUT": "deadline for GLOBAL RPCs",
    "GUBER_GRPC_ADDRESS": "gRPC listen address",
    "GUBER_GRPC_MAX_CONN_AGE_SEC": "max gRPC client connection age (0 = inf)",
    "GUBER_HTTP_ADDRESS": "HTTP/JSON gateway listen address",
    "GUBER_INGEST_ARENA_SLABS": "preallocated wire-decode column slabs (0 = off)",
    "GUBER_INGEST_FALLBACK_LIMIT": "arena-miss plain allocations per window before shed",
    "GUBER_INSTANCE_ID": "unique instance id for logs/tracing",
    "GUBER_K8S_ENDPOINTS_SELECTOR": "k8s discovery: endpoints selector",
    "GUBER_K8S_NAMESPACE": "k8s discovery: namespace",
    "GUBER_K8S_POD_IP": "k8s discovery: this pod's IP",
    "GUBER_K8S_POD_PORT": "k8s discovery: this pod's port",
    "GUBER_K8S_WATCH_MECHANISM": "k8s discovery: 'endpoints' or 'pods'",
    "GUBER_LEASE_BUDGET_FRACTION": "limit fraction delegated per lease grant",
    "GUBER_LEASE_CREDIT_BACK": "credit unused lease budget back on release (0/1)",
    "GUBER_LEASE_ENABLED": "cooperative quota-lease tier on/off",
    "GUBER_LEASE_MAX_BUDGET": "hard cap on admissions per lease grant",
    "GUBER_LEASE_OFFLINE_GRACE": "client lease extension window when owner unreachable",
    "GUBER_LEASE_SECRET": "shared HMAC lease-signing secret ('' = per-process)",
    "GUBER_LEASE_TTL": "lease validity window (duration)",
    "GUBER_LOG_FORMAT": "log format: text or json",
    "GUBER_LOG_LEVEL": "log level: debug/info/warning/error",
    "GUBER_MEMBERLIST_ADDRESS": "member-list discovery: bind address",
    "GUBER_MEMBERLIST_ADVERTISE_ADDRESS": "member-list: advertise address",
    "GUBER_MEMBERLIST_KNOWN_NODES": "member-list: seed nodes (comma list)",
    "GUBER_MESH_LOCAL_WIDTH": "DEPRECATED routed-path width (warns; no-op)",
    "GUBER_MESH_ROUTING": "sharded-table key routing: auto/device",
    "GUBER_METRIC_FLAGS": "optional collectors: os,golang",
    "GUBER_PEER_DISCOVERY_TYPE": "discovery pool: member-list/etcd/dns/k8s/none",
    "GUBER_PEER_PICKER": "peer picker implementation",
    "GUBER_PEER_PICKER_HASH": "picker hash: fnv1 or fnv1a",
    "GUBER_PEER_TIMEOUT_FLOOR": "min peer RPC timeout under deadline propagation",
    "GUBER_PENDING_LIMIT": "bounded admission queue cap in requests (0 = auto)",
    "GUBER_REDELIVERY_LIMIT": "GLOBAL redelivery buffer cap",
    "GUBER_REPLICATED_HASH_REPLICAS": "consistent-hash virtual replicas",
    "GUBER_REQUEST_TIMEOUT": "default per-request deadline budget",
    "GUBER_RESHARD_FREEZE_TIMEOUT": "reshard drain budget before abort",
    "GUBER_RESHARD_VERIFY": "audit the table after each reshard cutover",
    "GUBER_RESOLV_CONF": "dns discovery: resolv.conf path",
    "GUBER_SANITIZERS": "runtime lock-order/SPSC sanitizers (tests only)",
    "GUBER_SHED_POLICY": "overload shed answers: fail-open/fail-closed",
    "GUBER_SLOW_WINDOW_MS": "slow-window watchdog threshold in ms (0 = off)",
    "GUBER_SNAPSHOT_DELTAS_PER_BASE": "delta records per base compaction",
    "GUBER_SNAPSHOT_DIR": "crash-safe snapshot directory ('' = off)",
    "GUBER_SNAPSHOT_INTERVAL": "delta snapshot cadence (seconds)",
    "GUBER_SSD_CAPACITY_BYTES": "SSD-tier slab byte budget",
    "GUBER_SSD_COMPACT_RATIO": "slab garbage fraction that triggers compaction",
    "GUBER_SSD_DIR": "SSD-tier slab directory ('' = off)",
    "GUBER_SSD_QUEUE_DEPTH": "SSD writer queue depth (demote batches)",
    "GUBER_STATUS_HTTP_ADDRESS": "no-mTLS health/metrics listener",
    "GUBER_TARGET_P99_MS": "AIMD limiter window-p99 target in ms (0 = off)",
    "GUBER_TICK_PIPELINE_DEPTH": "dispatched-unresolved tick windows in flight",
    "GUBER_TLS_AUTO": "self-signed server TLS",
    "GUBER_TLS_CA": "TLS CA cert file",
    "GUBER_TLS_CA_KEY": "TLS CA key file (auto-signs server certs)",
    "GUBER_TLS_CERT": "TLS server cert file",
    "GUBER_TLS_CLIENT_AUTH": "client-cert policy for mTLS",
    "GUBER_TLS_CLIENT_AUTH_CA_CERT": "CA bundle validating client certs",
    "GUBER_TLS_CLIENT_AUTH_CERT": "client cert for peer dials",
    "GUBER_TLS_CLIENT_AUTH_KEY": "client key for peer dials",
    "GUBER_TLS_CLIENT_AUTH_SERVER_NAME": "expected server name on dials",
    "GUBER_TLS_INSECURE_SKIP_VERIFY": "skip peer cert verification (dev only)",
    "GUBER_TLS_KEY": "TLS server key file",
    "GUBER_TLS_MIN_VERSION": "minimum TLS version",
    "GUBER_TPU_BG_RECLAIM": "background reclaim: auto/on/off",
    "GUBER_TPU_DMA_RING": "row-kernel DMA ring slots (pow2)",
    "GUBER_TPU_DMA_UNROLL": "row-kernel DMA issue unroll (pow2)",
    "GUBER_TPU_FUSED_TICK": "force fused Pallas tick on/off (default: auto)",
    "GUBER_TPU_GLOBAL_MESH_CAPACITY": "GLOBAL mesh slot capacity",
    "GUBER_TPU_GLOBAL_MESH_NODE": "this node's mesh index (-1 = auto)",
    "GUBER_TPU_GLOBAL_MESH_NODES": "GLOBAL mesh size (0 = gRPC loops only)",
    "GUBER_TPU_MAX_BATCH": "request columns per device tick",
    "GUBER_TPU_MESH_SHARDS": "table shards on the device mesh",
    "GUBER_TPU_PLATFORM": "force jax platform (e.g. cpu)",
    "GUBER_TPU_SORTED32": "0 = x64 oracle tick for duplicate batches",
    "GUBER_TPU_TABLE_LAYOUT": "bucket-table layout: auto/columns/row",
}


def env_knob(name: str, default=None, parse: Optional[Callable] = None,
             environ: Optional[Dict[str, str]] = None):
    """Registered read of one ``GUBER_*`` knob from the environment.

    The blessed accessor for module-level fast-path reads outside
    :func:`setup_daemon_config` (feature toggles resolved at engine
    construction, the healthcheck probe's listener address): the name
    must be a key of :data:`ENV_REGISTRY` — an unregistered read raises
    at import/construction time instead of silently growing the env
    surface — and ``parse`` failures carry the var name.  Unset or
    empty returns ``default`` unparsed."""
    if name not in ENV_REGISTRY:
        raise KeyError(
            f"{name} is not registered in config.ENV_REGISTRY; add it "
            "there (and to example.conf) first"
        )
    env = os.environ if environ is None else environ
    v = env.get(name, "")
    if v == "":
        return default
    if parse is None:
        return v
    try:
        return parse(v)
    except ValueError as e:
        raise ValueError(f"{name}: {e}") from None


def _ms(v: float) -> float:
    return v / 1000.0


# Reference BatchLimit default (config.go:126-128).  Single source for the
# field default, the explicit-set detection, and the env reader default —
# they must agree or batch_limit_set desyncs.
DEFAULT_BATCH_LIMIT = 1000


@dataclass
class BehaviorConfig:
    """Batching and GLOBAL cadence knobs (reference config.go:49-70).

    Durations are seconds (floats) host-side; wire values remain ms.
    """

    # Client→owner forwarding batches.
    batch_timeout: float = 0.5       # BatchTimeout 500ms
    batch_wait: float = 500e-6       # BatchWait 500µs (the tick)
    batch_limit: int = DEFAULT_BATCH_LIMIT   # BatchLimit
    # True when the operator set GUBER_BATCH_LIMIT (or a caller assigned
    # batch_limit explicitly).  The tick window honors an explicit cap —
    # even one equal to the reference default — and otherwise widens to
    # tpu_max_batch (service/instance.py window_limit).
    batch_limit_set: bool = False

    disable_batching: bool = False

    # GLOBAL behavior reconciliation.
    global_timeout: float = 0.5      # GlobalTimeout 500ms
    global_sync_wait: float = 0.1    # GlobalSyncWait 100ms
    global_batch_limit: int = 1000   # GlobalBatchLimit
    global_peer_requests_concurrency: int = 100

    force_global: bool = False

    def __post_init__(self) -> None:
        # Programmatic construction with a tuned batch_limit counts as
        # explicit, so such callers keep their cap without knowing about
        # the flag; only "left at the default" widens the tick window.
        if self.batch_limit != DEFAULT_BATCH_LIMIT:
            self.batch_limit_set = True


@dataclass
class Config:
    """Library-level instance config (reference config.go:73-123)."""

    behaviors: BehaviorConfig = field(default_factory=BehaviorConfig)
    cache_size: int = 50_000         # default table capacity (config.go:139)
    data_center: str = ""
    local_picker_hash: str = "fnv1"  # GUBER_PEER_PICKER_HASH
    replicas: int = 512              # GUBER_REPLICATED_HASH_REPLICAS
    instance_id: str = ""

    # --- TPU engine knobs (new surface; no reference analog) ---
    tpu_max_batch: int = 4096        # request columns per device tick
    tpu_mesh_shards: int = 0         # 0 = single-chip TickEngine; N = mesh
    # Sharded-table key routing (parallel/mesh_engine.py): "device" (the
    # "auto" default) ships one flat slot-sorted batch plus ragged
    # extent offsets and each shard walks only its own extent on
    # device.  The legacy "host" blocked packer is retired (the ragged
    # path has no per-shard width to overflow).  GUBER_MESH_ROUTING
    mesh_routing: str = "auto"
    # DEPRECATED: per-shard lanes of the retired device-routed local
    # block.  The ragged dispatch has no width knob; a non-zero value
    # only emits a one-time deprecation warning.  GUBER_MESH_LOCAL_WIDTH
    mesh_local_width: int = 0
    tpu_platform: str = ""           # force jax platform ("cpu" for tests)
    # Bucket-table storage: "auto" picks the Pallas row layout on TPU for
    # tables it fits (ops/rowtable.py), "columns"/"row" force one.
    tpu_table_layout: str = "auto"   # GUBER_TPU_TABLE_LAYOUT
    # Background reclamation (TTL sweep + LRU selection on a reclaimer
    # thread instead of the serving path): "auto" enables it for tables
    # >= 2^18 slots; "on"/"off" force.  GUBER_TPU_BG_RECLAIM
    tpu_bg_reclaim: str = "auto"
    # Tiered bucket state (docs/tiering.md): entry budget of the
    # host-side cold store LRU victims demote into and misses promote
    # from.  0 disables tiering (eviction destroys bucket state, the
    # reference's strict LRU semantics).  GUBER_COLD_CACHE_SIZE
    cold_cache_size: int = 0
    # SSD third tier (docs/tiering.md): when GUBER_SSD_DIR names a
    # directory, an append-only mmap slab store absorbs the cold tier's
    # overflow — billions of keys under bounded RAM, with the SSD hop
    # provably off the tick path.  Requires cold_cache_size > 0 (the
    # SSD tier only ever holds cold-tier overflow).  Empty = off.
    ssd_dir: str = ""
    ssd_capacity_bytes: int = 1 << 30   # GUBER_SSD_CAPACITY_BYTES
    ssd_compact_ratio: float = 0.5      # GUBER_SSD_COMPACT_RATIO
    ssd_queue_depth: int = 8            # GUBER_SSD_QUEUE_DEPTH
    # GLOBAL reconciliation over the device mesh (collectives data plane,
    # parallel/global_mesh.py): N logical peer-nodes; 0 = gRPC loops only.
    # Node index -1 = auto (jax.process_index(), the multi-host identity).
    tpu_global_mesh_nodes: int = 0
    tpu_global_mesh_node: int = -1
    tpu_global_mesh_capacity: int = 1 << 16

    # Crash-safe bucket-state persistence (docs/persistence.md): when
    # GUBER_SNAPSHOT_DIR names a directory, a supervised background loop
    # appends CRC'd dirty-delta snapshots every GUBER_SNAPSHOT_INTERVAL
    # and compacts them into a fresh base every
    # GUBER_SNAPSHOT_DELTAS_PER_BASE records; startup restores base +
    # deltas before serving.  Empty = persistence off (the seed
    # behavior: restart is amnesia unless a Loader is wired).
    snapshot_dir: str = ""
    snapshot_interval: float = 5.0
    snapshot_deltas_per_base: int = 64
    # Graceful-drain budget (seconds): bounds the final GLOBAL
    # hit/broadcast/redelivery flush inside GlobalManager.close so a
    # dead peer can't wedge shutdown.  GUBER_DRAIN_TIMEOUT
    drain_timeout: float = 2.0

    # Elastic live resharding (docs/resharding.md): the bounded quiesce
    # budget before the cutover — a drain that misses it aborts the
    # transition (GUBER_RESHARD_FREEZE_TIMEOUT) — and whether the
    # post-cutover table is audited for loss/double-residency before
    # admission unfreezes (GUBER_RESHARD_VERIFY; the audit is a full
    # readback, so very large tables may opt out).
    reshard_freeze_timeout: float = 5.0
    reshard_verify: bool = True

    # Multi-process streaming edge (docs/edge.md): N decode worker
    # processes feeding the tick loop through shared-memory slab rings.
    # 0 keeps the in-process serving path byte-identical and never
    # creates a shm segment.  GUBER_EDGE_WORKERS /
    # GUBER_EDGE_SHM_SLABS / GUBER_EDGE_RING_DEPTH
    edge_workers: int = 0
    edge_shm_slabs: int = 8
    edge_ring_depth: int = 16

    # Multi-region GLOBAL federation (docs/federation.md): when enabled,
    # owner-side GLOBAL state changes additionally fan out as bounded-
    # staleness envelopes to the owning peer in every *other* datacenter
    # (region_picker), batched per federation_interval and shipped over
    # the resilience breaker/backoff/redelivery path.  Requires
    # data_center to be set — regions are keyed by it.
    # GUBER_FEDERATION_* / GUBER_DATA_CENTER.
    federation_enabled: bool = False
    federation_interval: float = 1.0
    federation_batch_limit: int = 1000
    federation_timeout: float = 1.0

    # Guardrailed shard autoscaler (docs/autoscaling.md): a supervised
    # controller samples the admission/latency/occupancy telemetry every
    # autoscale_interval and drives live reshard transitions through
    # hysteresis bands, per-direction cooldowns, and a rolling-hour flap
    # cap.  Off by default; when enabled it starts in dry-run (decisions
    # recorded at /debug/autoscaler, nothing actuated) until
    # GUBER_AUTOSCALE_DRY_RUN is explicitly turned off.
    # GUBER_AUTOSCALE_*.
    autoscale_enabled: bool = False
    autoscale_interval: float = 10.0
    autoscale_windows: int = 3
    autoscale_target_p99_ms: float = 5.0
    autoscale_queue_high: int = 1000
    autoscale_hysteresis: float = 0.5
    autoscale_occupancy_low: float = 0.3
    autoscale_min_shards: int = 1
    autoscale_max_shards: int = 8
    autoscale_cooldown_up: float = 60.0
    autoscale_cooldown_down: float = 300.0
    autoscale_max_per_hour: int = 4
    autoscale_dry_run: bool = True

    # Fault-tolerant peer path (docs/resilience.md): per-peer circuit
    # breakers, forward-retry backoff, and the GLOBAL redelivery buffer.
    # GUBER_BREAKER_* / GUBER_FORWARD_* / GUBER_REDELIVERY_LIMIT.
    resilience: ResilienceConfig = field(default_factory=ResilienceConfig)
    # Fault-injection hook (chaos tests / game-days): a FaultInjector the
    # peer clients consult before every RPC.  GUBER_FAULT_* builds one at
    # daemon setup; tests install theirs directly.
    fault_injector: Optional[object] = None

    # Optional persistence hooks (reference store.go).
    loader: Optional[object] = None
    store: Optional[object] = None

    def set_defaults(self) -> None:
        if not self.instance_id:
            self.instance_id = _random_instance_id()
        if self.cache_size <= 0:
            self.cache_size = 50_000


# GLOBAL-mesh reconcile envelope (parallel/global_mesh.py module doc):
# every reconcile all-gathers O(capacity * n_nodes) state and applies the
# transition to EVERY slot, every sync interval, independent of traffic.
# Past ~2^20 slots that dense pass stops fitting a 100 ms cadence (and at
# 2^24 a single step moves gigabytes over ICI), so the config surface
# warns at the documented soft bound and refuses the hard one instead of
# letting a typo configure an unserviceable mesh.
GLOBAL_MESH_CAPACITY_SOFT = 1 << 20
GLOBAL_MESH_CAPACITY_HARD = 1 << 24


def validate_global_mesh_capacity(capacity: int) -> None:
    if capacity > GLOBAL_MESH_CAPACITY_HARD:
        raise ValueError(
            f"GUBER_TPU_GLOBAL_MESH_CAPACITY={capacity} exceeds "
            f"{GLOBAL_MESH_CAPACITY_HARD} (2^24); the dense reconcile "
            "moves O(capacity * nodes) bytes over ICI every sync interval "
            "and cannot serve tables this large — GLOBAL limits are a "
            "small hot subset; shard the serving table instead "
            "(parallel/global_mesh.py scaling envelope)"
        )
    if capacity > GLOBAL_MESH_CAPACITY_SOFT:
        log.warning(
            "GUBER_TPU_GLOBAL_MESH_CAPACITY=%d is past the documented "
            "envelope (2^14-2^20): each reconcile densely rewrites every "
            "slot on every node — expect the sync cadence to stretch "
            "(parallel/global_mesh.py scaling envelope)", capacity,
        )


# Metric-collector flags (reference flags.go:20-23).  "os" registers a
# process collector (RSS, fds, CPU via /proc); "golang" — kept under the
# reference's name so GUBER_METRIC_FLAGS values carry over — registers the
# host-runtime collectors (here: Python GC + platform info, the analog of
# Go's GoCollector).
FLAG_OS_METRICS = 1 << 0
FLAG_RUNTIME_METRICS = 1 << 1


def parse_metric_flags(values: List[str]) -> int:
    """Comma-separated flag names → bitmask (reference flags.go:38-57:
    getEnvMetricFlags; invalid names are logged and ignored)."""
    flags = 0
    for f in values:
        f = f.strip().lower()
        if not f:
            continue
        if f == "os":
            flags |= FLAG_OS_METRICS
        elif f in ("golang", "python", "runtime"):
            flags |= FLAG_RUNTIME_METRICS
        else:
            log.error(
                "invalid flag '%s' for 'GUBER_METRIC_FLAGS' valid options"
                " are ['os', 'golang']", f,
            )
    return flags


@dataclass
class TLSSettings:
    """TLS file paths / modes (reference config.go:330-420 env surface)."""

    ca_file: str = ""
    ca_key_file: str = ""
    cert_file: str = ""
    key_file: str = ""
    auto_tls: bool = False
    client_auth: str = ""            # "", "request", "verify-if-given", "require", "require-and-verify"
    client_auth_ca_file: str = ""
    client_auth_cert_file: str = ""
    client_auth_key_file: str = ""
    client_auth_server_name: str = ""
    insecure_skip_verify: bool = False
    min_version: str = "1.3"

    @property
    def enabled(self) -> bool:
        return bool(
            self.auto_tls
            or self.cert_file
            or self.key_file
            or self.ca_file
        )


@dataclass
class DaemonConfig:
    """Daemon-level config (reference config.go:181-252)."""

    grpc_listen_address: str = "localhost:81"
    http_listen_address: str = "localhost:80"
    http_status_listen_address: str = ""   # optional no-mTLS health listener
    advertise_address: str = ""
    config: Config = field(default_factory=Config)
    peer_discovery_type: str = "none"
    data_center: str = ""
    log_level: str = "info"
    log_format: str = "text"
    metric_flags: int = 0
    # Max age of a gRPC client connection in seconds; 0 = infinity
    # (reference config.go:319 GRPCMaxConnectionAgeSeconds).
    grpc_max_conn_age_sec: int = 0

    # member-list discovery
    memberlist_address: str = ""
    memberlist_advertise_address: str = ""
    memberlist_known_nodes: List[str] = field(default_factory=list)

    # etcd discovery
    etcd_endpoints: List[str] = field(default_factory=list)
    etcd_key_prefix: str = "/gubernator-tpu/peers/"
    etcd_user: str = ""
    etcd_password: str = ""
    etcd_dial_timeout: float = 5.0

    # k8s discovery
    k8s_namespace: str = ""
    k8s_pod_ip: str = ""
    k8s_pod_port: str = ""
    k8s_endpoints_selector: str = ""
    k8s_watch_mechanism: str = "endpoints"

    # dns discovery
    dns_fqdn: str = ""
    dns_resolv_conf: str = "/etc/resolv.conf"

    tls: TLSSettings = field(default_factory=TLSSettings)

    def client_tls(self) -> Optional[TLSSettings]:
        return self.tls if self.tls.enabled else None


def _random_instance_id(n: int = 10) -> str:
    """Instance id fallback (reference config.go:678-694 tries env, docker
    cgroup, then random).  Hostname-seeded random keeps logs greppable."""
    alphabet = string.ascii_lowercase + string.digits
    return "".join(random.choice(alphabet) for _ in range(n))


def load_config_file(path: str, environ: Optional[Dict[str, str]] = None) -> None:
    """Load a ``key=value`` config file into the environment
    (reference config.go:635-658): later ``GUBER_*`` reads see the values,
    but real environment variables win."""
    env = environ if environ is not None else os.environ
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            if "=" not in line:
                raise ValueError(f"{path}:{lineno}: expected 'key=value', got {line!r}")
            k, _, v = line.partition("=")
            k, v = k.strip(), v.strip()
            if k and k not in env:
                env[k] = v


class EnvReader:
    """Typed ``GUBER_*`` reads with default fallbacks."""

    def __init__(self, environ: Optional[Dict[str, str]] = None):
        self.env = environ if environ is not None else os.environ

    def str_(self, name: str, default: str = "") -> str:
        v = self.env.get(name, "")
        return v if v != "" else default

    def has(self, name: str) -> bool:
        """True when the var is set non-empty (the readers above treat an
        empty string as unset)."""
        return self.env.get(name, "") != ""

    def int_(self, name: str, default: int = 0) -> int:
        v = self.env.get(name, "")
        if v == "":
            return default
        try:
            return int(v)
        except ValueError as e:
            raise ValueError(f"{name}: {e}") from None

    def float_seconds(self, name: str, default: float) -> float:
        """Duration env var; accepts Go-style suffixed values (``500ms``,
        ``30s``, ``1m``, ``100us``) or a plain float of seconds."""
        v = self.env.get(name, "")
        if v == "":
            return default
        return parse_duration(v)

    def bool_(self, name: str, default: bool = False) -> bool:
        v = self.env.get(name, "").lower()
        if v == "":
            return default
        return v in ("1", "true", "yes", "on")

    def list_(self, name: str, default: Optional[List[str]] = None) -> List[str]:
        v = self.env.get(name, "")
        if v == "":
            return list(default or [])
        return [x.strip() for x in v.split(",") if x.strip()]


_DUR_UNITS = [  # ordered: longest suffix first so "ms" wins over "s"
    ("ms", 1e-3), ("us", 1e-6), ("µs", 1e-6), ("ns", 1e-9),
    ("s", 1.0), ("m", 60.0), ("h", 3600.0),
]


def parse_duration(v: str) -> float:
    """Parse a Go-style duration string into seconds."""
    v = v.strip()
    for suffix, mult in _DUR_UNITS:
        if v.endswith(suffix):
            return float(v[: -len(suffix)]) * mult
    return float(v)


def setup_daemon_config(
    config_file: str = "",
    environ: Optional[Dict[str, str]] = None,
) -> DaemonConfig:
    """Build a DaemonConfig from env (+ optional config file), mirroring the
    reference's ``SetupDaemonConfig`` (config.go:270-479)."""
    env = dict(os.environ) if environ is None else dict(environ)
    if config_file:
        load_config_file(config_file, env)
    # Re-apply the compile-cache knob: a config file loads into the
    # environment after the import-time default was chosen.
    from gubernator_tpu import configure_compile_cache
    from gubernator_tpu.ops.rowtable import refresh_dma_tuning

    configure_compile_cache(env)
    refresh_dma_tuning(env)
    r = EnvReader(env)

    behaviors = BehaviorConfig(
        batch_timeout=r.float_seconds("GUBER_BATCH_TIMEOUT", 0.5),
        batch_wait=r.float_seconds("GUBER_BATCH_WAIT", 500e-6),
        batch_limit=r.int_("GUBER_BATCH_LIMIT", DEFAULT_BATCH_LIMIT),
        batch_limit_set=r.has("GUBER_BATCH_LIMIT"),
        disable_batching=r.bool_("GUBER_DISABLE_BATCHING"),
        global_timeout=r.float_seconds("GUBER_GLOBAL_TIMEOUT", 0.5),
        global_sync_wait=r.float_seconds("GUBER_GLOBAL_SYNC_WAIT", 0.1),
        global_batch_limit=r.int_("GUBER_GLOBAL_BATCH_LIMIT", 1000),
        force_global=r.bool_("GUBER_FORCE_GLOBAL"),
    )
    resilience = ResilienceConfig(
        breaker_enabled=r.bool_("GUBER_BREAKER_ENABLED", True),
        breaker_failure_threshold=float(
            r.str_("GUBER_BREAKER_FAILURE_THRESHOLD", "0.5")
        ),
        breaker_min_requests=r.int_("GUBER_BREAKER_MIN_REQUESTS", 5),
        breaker_window=r.float_seconds("GUBER_BREAKER_WINDOW", 10.0),
        breaker_open_for=r.float_seconds("GUBER_BREAKER_OPEN_FOR", 2.0),
        breaker_open_cap=r.float_seconds("GUBER_BREAKER_OPEN_CAP", 30.0),
        breaker_half_open_probes=r.int_("GUBER_BREAKER_HALF_OPEN_PROBES", 1),
        forward_max_attempts=r.int_("GUBER_FORWARD_MAX_ATTEMPTS", 5),
        forward_backoff_base=r.float_seconds(
            "GUBER_FORWARD_BACKOFF_BASE", 0.005
        ),
        forward_backoff_cap=r.float_seconds("GUBER_FORWARD_BACKOFF_CAP", 0.1),
        redelivery_limit=r.int_("GUBER_REDELIVERY_LIMIT", 10_000),
    )
    from gubernator_tpu.resilience import FaultInjector

    conf = Config(
        behaviors=behaviors,
        resilience=resilience,
        fault_injector=FaultInjector.from_env(r),
        cache_size=r.int_("GUBER_CACHE_SIZE", 50_000),
        cold_cache_size=r.int_("GUBER_COLD_CACHE_SIZE", 0),
        ssd_dir=r.str_("GUBER_SSD_DIR"),
        ssd_capacity_bytes=r.int_("GUBER_SSD_CAPACITY_BYTES", 1 << 30),
        ssd_compact_ratio=float(r.str_("GUBER_SSD_COMPACT_RATIO", "0.5")),
        ssd_queue_depth=r.int_("GUBER_SSD_QUEUE_DEPTH", 8),
        snapshot_dir=r.str_("GUBER_SNAPSHOT_DIR"),
        snapshot_interval=r.float_seconds("GUBER_SNAPSHOT_INTERVAL", 5.0),
        snapshot_deltas_per_base=r.int_(
            "GUBER_SNAPSHOT_DELTAS_PER_BASE", 64
        ),
        drain_timeout=r.float_seconds("GUBER_DRAIN_TIMEOUT", 2.0),
        reshard_freeze_timeout=r.float_seconds(
            "GUBER_RESHARD_FREEZE_TIMEOUT", 5.0),
        reshard_verify=r.bool_("GUBER_RESHARD_VERIFY", True),
        edge_workers=r.int_("GUBER_EDGE_WORKERS", 0),
        edge_shm_slabs=r.int_("GUBER_EDGE_SHM_SLABS", 8),
        edge_ring_depth=r.int_("GUBER_EDGE_RING_DEPTH", 16),
        data_center=r.str_("GUBER_DATA_CENTER"),
        federation_enabled=r.bool_("GUBER_FEDERATION_ENABLED"),
        federation_interval=r.float_seconds("GUBER_FEDERATION_INTERVAL", 1.0),
        federation_batch_limit=r.int_("GUBER_FEDERATION_BATCH_LIMIT", 1000),
        federation_timeout=r.float_seconds("GUBER_FEDERATION_TIMEOUT", 1.0),
        autoscale_enabled=r.bool_("GUBER_AUTOSCALE_ENABLED"),
        autoscale_interval=r.float_seconds("GUBER_AUTOSCALE_INTERVAL", 10.0),
        autoscale_windows=r.int_("GUBER_AUTOSCALE_WINDOWS", 3),
        autoscale_target_p99_ms=float(
            r.str_("GUBER_AUTOSCALE_TARGET_P99_MS", "5.0")),
        autoscale_queue_high=r.int_("GUBER_AUTOSCALE_QUEUE_HIGH", 1000),
        autoscale_hysteresis=float(
            r.str_("GUBER_AUTOSCALE_HYSTERESIS", "0.5")),
        autoscale_occupancy_low=float(
            r.str_("GUBER_AUTOSCALE_OCCUPANCY_LOW", "0.3")),
        autoscale_min_shards=r.int_("GUBER_AUTOSCALE_MIN_SHARDS", 1),
        autoscale_max_shards=r.int_("GUBER_AUTOSCALE_MAX_SHARDS", 8),
        autoscale_cooldown_up=r.float_seconds(
            "GUBER_AUTOSCALE_COOLDOWN_UP", 60.0),
        autoscale_cooldown_down=r.float_seconds(
            "GUBER_AUTOSCALE_COOLDOWN_DOWN", 300.0),
        autoscale_max_per_hour=r.int_("GUBER_AUTOSCALE_MAX_PER_HOUR", 4),
        autoscale_dry_run=r.bool_("GUBER_AUTOSCALE_DRY_RUN", True),
        local_picker_hash=r.str_("GUBER_PEER_PICKER_HASH", "fnv1"),
        replicas=r.int_("GUBER_REPLICATED_HASH_REPLICAS", 512),
        instance_id=r.str_("GUBER_INSTANCE_ID"),
        tpu_max_batch=r.int_("GUBER_TPU_MAX_BATCH", 4096),
        tpu_table_layout=r.str_("GUBER_TPU_TABLE_LAYOUT", "auto"),
        tpu_bg_reclaim=r.str_("GUBER_TPU_BG_RECLAIM", "auto"),
        tpu_mesh_shards=r.int_("GUBER_TPU_MESH_SHARDS", 0),
        mesh_routing=r.str_("GUBER_MESH_ROUTING", "auto"),
        mesh_local_width=r.int_("GUBER_MESH_LOCAL_WIDTH", 0),
        tpu_platform=r.str_("GUBER_TPU_PLATFORM"),
        tpu_global_mesh_nodes=r.int_("GUBER_TPU_GLOBAL_MESH_NODES", 0),
        tpu_global_mesh_node=r.int_("GUBER_TPU_GLOBAL_MESH_NODE", -1),
        tpu_global_mesh_capacity=r.int_(
            "GUBER_TPU_GLOBAL_MESH_CAPACITY", 1 << 16
        ),
    )
    conf.set_defaults()

    if conf.tpu_bg_reclaim not in ("auto", "on", "off"):
        raise ValueError(
            f"GUBER_TPU_BG_RECLAIM must be auto, on, or off; "
            f"got {conf.tpu_bg_reclaim!r}"
        )
    if conf.mesh_routing not in ("auto", "device"):
        raise ValueError(
            f"GUBER_MESH_ROUTING must be auto or device (the legacy "
            f"'host' blocked path is retired); got {conf.mesh_routing!r}"
        )
    if conf.mesh_local_width < 0:
        raise ValueError(
            f"GUBER_MESH_LOCAL_WIDTH must be >= 0; "
            f"got {conf.mesh_local_width}"
        )
    if conf.cold_cache_size < 0:
        raise ValueError(
            f"GUBER_COLD_CACHE_SIZE must be >= 0; got {conf.cold_cache_size}"
        )
    if conf.ssd_dir and conf.cold_cache_size <= 0:
        raise ValueError(
            "GUBER_SSD_DIR requires GUBER_COLD_CACHE_SIZE > 0: the SSD "
            "tier only ever holds cold-tier overflow"
        )
    if conf.ssd_dir and conf.tpu_mesh_shards > 1:
        # Hard error, not warn+disable: a silently absent third tier is
        # a robustness trap at reshard scale — the operator sized the
        # deployment around capacity the engine never had.
        raise ValueError(
            "GUBER_SSD_DIR is not supported by the sharded mesh engine "
            "(GUBER_TPU_MESH_SHARDS > 1): the SSD tier hangs off the "
            "single-chip cold store; unset one of the two"
        )
    if conf.reshard_freeze_timeout <= 0:
        raise ValueError(
            f"GUBER_RESHARD_FREEZE_TIMEOUT must be > 0; "
            f"got {conf.reshard_freeze_timeout}"
        )
    if conf.ssd_capacity_bytes <= 0:
        raise ValueError(
            f"GUBER_SSD_CAPACITY_BYTES must be > 0; "
            f"got {conf.ssd_capacity_bytes}"
        )
    if not 0.0 < conf.ssd_compact_ratio <= 1.0:
        raise ValueError(
            f"GUBER_SSD_COMPACT_RATIO must be in (0, 1]; "
            f"got {conf.ssd_compact_ratio}"
        )
    if conf.ssd_queue_depth < 1:
        raise ValueError(
            f"GUBER_SSD_QUEUE_DEPTH must be >= 1; got {conf.ssd_queue_depth}"
        )
    if conf.snapshot_interval <= 0:
        raise ValueError(
            f"GUBER_SNAPSHOT_INTERVAL must be > 0; "
            f"got {conf.snapshot_interval}"
        )
    if conf.snapshot_deltas_per_base < 1:
        raise ValueError(
            f"GUBER_SNAPSHOT_DELTAS_PER_BASE must be >= 1; "
            f"got {conf.snapshot_deltas_per_base}"
        )
    if conf.drain_timeout < 0:
        raise ValueError(
            f"GUBER_DRAIN_TIMEOUT must be >= 0; got {conf.drain_timeout}"
        )
    if conf.edge_workers < 0:
        raise ValueError(
            f"GUBER_EDGE_WORKERS must be >= 0; got {conf.edge_workers}"
        )
    if conf.edge_shm_slabs < 1:
        raise ValueError(
            f"GUBER_EDGE_SHM_SLABS must be >= 1; got {conf.edge_shm_slabs}"
        )
    if conf.edge_ring_depth < 1:
        raise ValueError(
            f"GUBER_EDGE_RING_DEPTH must be >= 1; got {conf.edge_ring_depth}"
        )
    if conf.federation_interval <= 0:
        raise ValueError(
            f"GUBER_FEDERATION_INTERVAL must be > 0; "
            f"got {conf.federation_interval}"
        )
    if not 1 <= conf.federation_batch_limit <= MAX_BATCH_SIZE:
        # The cap matters: the receiver applies envelopes through the
        # peer batch handler, which rejects batches over MAX_BATCH_SIZE
        # — a larger envelope would fail every apply and wedge its
        # channel in permanent redelivery.
        raise ValueError(
            f"GUBER_FEDERATION_BATCH_LIMIT must be in "
            f"[1, {MAX_BATCH_SIZE}]; got {conf.federation_batch_limit}"
        )
    if conf.federation_timeout <= 0:
        raise ValueError(
            f"GUBER_FEDERATION_TIMEOUT must be > 0; "
            f"got {conf.federation_timeout}"
        )
    if conf.federation_enabled and not conf.data_center:
        raise ValueError(
            "GUBER_FEDERATION_ENABLED requires GUBER_DATA_CENTER: regions "
            "are keyed by datacenter name and this node must know its own"
        )
    if conf.autoscale_interval <= 0:
        raise ValueError(
            f"GUBER_AUTOSCALE_INTERVAL must be > 0; "
            f"got {conf.autoscale_interval}"
        )
    if conf.autoscale_windows < 1:
        raise ValueError(
            f"GUBER_AUTOSCALE_WINDOWS must be >= 1; "
            f"got {conf.autoscale_windows}"
        )
    if conf.autoscale_target_p99_ms < 0:
        raise ValueError(
            f"GUBER_AUTOSCALE_TARGET_P99_MS must be >= 0 (0 disables the "
            f"latency signal); got {conf.autoscale_target_p99_ms}"
        )
    if conf.autoscale_queue_high < 1:
        raise ValueError(
            f"GUBER_AUTOSCALE_QUEUE_HIGH must be >= 1; "
            f"got {conf.autoscale_queue_high}"
        )
    if not 0.0 < conf.autoscale_hysteresis < 1.0:
        # Strict: hysteresis == 1 would make the scale-down latency band
        # touch the scale-up band and the controller could ping-pong on
        # a p99 sitting exactly at target.
        raise ValueError(
            f"GUBER_AUTOSCALE_HYSTERESIS must be in (0, 1) so the up and "
            f"down bands never overlap; got {conf.autoscale_hysteresis}"
        )
    if not 0.0 <= conf.autoscale_occupancy_low <= 1.0:
        raise ValueError(
            f"GUBER_AUTOSCALE_OCCUPANCY_LOW must be in [0, 1]; "
            f"got {conf.autoscale_occupancy_low}"
        )
    if conf.autoscale_min_shards < 1:
        raise ValueError(
            f"GUBER_AUTOSCALE_MIN_SHARDS must be >= 1; "
            f"got {conf.autoscale_min_shards}"
        )
    if conf.autoscale_max_shards < conf.autoscale_min_shards:
        raise ValueError(
            f"GUBER_AUTOSCALE_MAX_SHARDS must be >= "
            f"GUBER_AUTOSCALE_MIN_SHARDS; got "
            f"{conf.autoscale_max_shards} < {conf.autoscale_min_shards}"
        )
    if conf.autoscale_cooldown_up < 0 or conf.autoscale_cooldown_down < 0:
        raise ValueError(
            f"GUBER_AUTOSCALE_COOLDOWN_UP/_DOWN must be >= 0; got "
            f"{conf.autoscale_cooldown_up}/{conf.autoscale_cooldown_down}"
        )
    if conf.autoscale_max_per_hour < 1:
        raise ValueError(
            f"GUBER_AUTOSCALE_MAX_PER_HOUR must be >= 1; "
            f"got {conf.autoscale_max_per_hour}"
        )
    if not 0.0 < resilience.breaker_failure_threshold <= 1.0:
        raise ValueError(
            f"GUBER_BREAKER_FAILURE_THRESHOLD must be in (0, 1]; "
            f"got {resilience.breaker_failure_threshold}"
        )
    if resilience.forward_max_attempts < 0:
        raise ValueError(
            f"GUBER_FORWARD_MAX_ATTEMPTS must be >= 0; "
            f"got {resilience.forward_max_attempts}"
        )
    if resilience.redelivery_limit < 0:
        raise ValueError(
            f"GUBER_REDELIVERY_LIMIT must be >= 0; "
            f"got {resilience.redelivery_limit}"
        )
    validate_global_mesh_capacity(conf.tpu_global_mesh_capacity)
    if conf.local_picker_hash not in ("fnv1", "fnv1a"):
        raise ValueError(
            f"GUBER_PEER_PICKER_HASH is invalid; choose one of 'fnv1', 'fnv1a'"
        )
    picker_type = r.str_("GUBER_PEER_PICKER", "replicated-hash")
    if picker_type not in ("replicated-hash",):
        raise ValueError(
            "GUBER_PEER_PICKER is invalid; 'replicated-hash' is the only picker"
        )

    discovery = r.str_("GUBER_PEER_DISCOVERY_TYPE", "none")
    if discovery not in DISCOVERY_TYPES:
        raise ValueError(
            f"GUBER_PEER_DISCOVERY_TYPE is invalid; choose one of {DISCOVERY_TYPES}"
        )

    tls = TLSSettings(
        ca_file=r.str_("GUBER_TLS_CA"),
        ca_key_file=r.str_("GUBER_TLS_CA_KEY"),
        cert_file=r.str_("GUBER_TLS_CERT"),
        key_file=r.str_("GUBER_TLS_KEY"),
        auto_tls=r.bool_("GUBER_TLS_AUTO"),
        client_auth=r.str_("GUBER_TLS_CLIENT_AUTH"),
        client_auth_ca_file=r.str_("GUBER_TLS_CLIENT_AUTH_CA_CERT"),
        client_auth_cert_file=r.str_("GUBER_TLS_CLIENT_AUTH_CERT"),
        client_auth_key_file=r.str_("GUBER_TLS_CLIENT_AUTH_KEY"),
        client_auth_server_name=r.str_("GUBER_TLS_CLIENT_AUTH_SERVER_NAME"),
        insecure_skip_verify=r.bool_("GUBER_TLS_INSECURE_SKIP_VERIFY"),
        min_version=r.str_("GUBER_TLS_MIN_VERSION", "1.3"),
    )

    return DaemonConfig(
        grpc_listen_address=r.str_("GUBER_GRPC_ADDRESS", f"{local_host()}:81"),
        http_listen_address=r.str_("GUBER_HTTP_ADDRESS", f"{local_host()}:80"),
        http_status_listen_address=r.str_("GUBER_STATUS_HTTP_ADDRESS"),
        advertise_address=r.str_("GUBER_ADVERTISE_ADDRESS"),
        config=conf,
        peer_discovery_type=discovery,
        data_center=r.str_("GUBER_DATA_CENTER"),
        log_level=r.str_("GUBER_LOG_LEVEL", "info"),
        log_format=r.str_("GUBER_LOG_FORMAT", "text"),
        metric_flags=parse_metric_flags(r.list_("GUBER_METRIC_FLAGS")),
        grpc_max_conn_age_sec=r.int_("GUBER_GRPC_MAX_CONN_AGE_SEC", 0),
        memberlist_address=r.str_("GUBER_MEMBERLIST_ADDRESS"),
        memberlist_advertise_address=r.str_("GUBER_MEMBERLIST_ADVERTISE_ADDRESS"),
        memberlist_known_nodes=r.list_("GUBER_MEMBERLIST_KNOWN_NODES"),
        etcd_endpoints=r.list_("GUBER_ETCD_ENDPOINTS", ["localhost:2379"]),
        etcd_key_prefix=r.str_("GUBER_ETCD_KEY_PREFIX", "/gubernator-tpu/peers/"),
        etcd_user=r.str_("GUBER_ETCD_USER"),
        etcd_password=r.str_("GUBER_ETCD_PASSWORD"),
        etcd_dial_timeout=r.float_seconds("GUBER_ETCD_DIAL_TIMEOUT", 5.0),
        k8s_namespace=r.str_("GUBER_K8S_NAMESPACE", "default"),
        k8s_pod_ip=r.str_("GUBER_K8S_POD_IP"),
        k8s_pod_port=r.str_("GUBER_K8S_POD_PORT"),
        k8s_endpoints_selector=r.str_("GUBER_K8S_ENDPOINTS_SELECTOR"),
        k8s_watch_mechanism=r.str_("GUBER_K8S_WATCH_MECHANISM", "endpoints"),
        dns_fqdn=r.str_("GUBER_DNS_FQDN"),
        dns_resolv_conf=r.str_("GUBER_RESOLV_CONF", "/etc/resolv.conf"),
        tls=tls,
    )


def local_host() -> str:
    """Bind-address default: 'localhost' unless it doesn't resolve
    (reference config.go:498-511 platform dance)."""
    try:
        socket.getaddrinfo("localhost", None)
        return "localhost"
    except OSError:
        return "127.0.0.1"


# Callback type peers flow through: discovery → daemon → instance
# (reference config.go:177).
UpdateFunc = Callable[[List[PeerInfo]], None]
