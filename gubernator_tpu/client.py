"""Client convenience surface (reference client.go + python/gubernator).

The framework's full async client lives in
:class:`gubernator_tpu.transport.daemon.DaemonClient`; this module adds
the small helpers the reference ships for callers — duration constants,
millisecond-timestamp converters (client.go:70-86), ``sleep_until_reset``
(python/gubernator/__init__.py:14), peer/string randomizers
(client.go:89-105) — and a ``dial_v1`` that mirrors ``DialV1Server``
(client.go:44-65: optional TLS, tracing-instrumented channel).
"""

from __future__ import annotations

import asyncio
import random
import secrets
import string
import time
from typing import Sequence

from gubernator_tpu.types import PeerInfo
from gubernator_tpu.utils import timeutil

MILLISECOND = 1
SECOND = 1000 * MILLISECOND
MINUTE = 60 * SECOND


def to_timestamp(duration_s: float) -> int:
    """Seconds → the millisecond duration/reset_time unit of the API
    (client.go:70 ToTimeStamp, from Go's time.Duration)."""
    return int(duration_s * 1000)


def from_timestamp(ts_ms: int) -> float:
    """Unix-ms timestamp → seconds from now (client.go:76 FromTimeStamp);
    negative when ``ts_ms`` is in the future."""
    return (timeutil.now_ms() - ts_ms) / 1000.0


def from_unix_milliseconds(ts_ms: int) -> float:
    """Unix-ms timestamp → unix seconds (client.go:84)."""
    return ts_ms / 1000.0


def sleep_until_reset(reset_time_ms: int) -> None:
    """Block until a response's ``reset_time`` has passed
    (python/gubernator/__init__.py:14)."""
    delta = reset_time_ms - timeutil.now_ms()
    if delta > 0:
        time.sleep(delta / 1000.0)


async def asleep_until_reset(reset_time_ms: int) -> None:
    """Async variant of :func:`sleep_until_reset`."""
    delta = reset_time_ms - timeutil.now_ms()
    if delta > 0:
        await asyncio.sleep(delta / 1000.0)


def random_peer(peers: Sequence[PeerInfo]) -> PeerInfo:
    """A random peer from the list (client.go:89 RandomPeer)."""
    return random.choice(list(peers))


def random_string(n: int) -> str:
    """Random alphanumeric string of length ``n`` (client.go:97),
    crypto-sourced like the reference."""
    alphabet = string.digits + string.ascii_uppercase + string.ascii_lowercase
    return "".join(secrets.choice(alphabet) for _ in range(n))


def dial_v1(server: str, tls=None):
    """Connect to a daemon, returning the async client
    (reference DialV1Server, client.go:44-65).

    ``tls`` may be a :class:`gubernator_tpu.transport.tlsutil.TLSBundle`
    (client credentials derived from it) or ready-made
    ``grpc.ChannelCredentials``.
    """
    import grpc

    from gubernator_tpu.transport.daemon import DaemonClient

    if not server:
        raise ValueError("server is empty; must provide a server")
    creds = None
    if tls is not None:
        creds = (
            tls if isinstance(tls, grpc.ChannelCredentials)
            else tls.channel_credentials()
        )
    return DaemonClient(server, credentials=creds)


class LeaseSession:
    """Async driver over :class:`~gubernator_tpu.leases.LeaseCache`
    against a dialed daemon (docs/leases.md).

    While a signed lease holds budget, :meth:`admit` answers locally —
    zero server round trips; at the lease edges (grant, exhaustion,
    expiry) it runs one sync+grant round over the client's lease RPCs.
    ``admit`` returning None means the lease tier has no answer (server
    declined to delegate, or budget cap below the hits batch): fall back
    to ``client.get_rate_limits`` for an ordinary server decision.

    ``close()`` flushes unsynced consumption through the normal sync
    path, bounded and deadline-capped — see :meth:`LeaseCache.close`.
    """

    def __init__(self, client, *, verifier=None, want_budget: int = 0,
                 offline_grace_ms: int = 5_000,
                 max_offline_extensions: int = 3, clock=time.time,
                 holder_id: str = None):
        from gubernator_tpu.leases import LeaseCache

        self.client = client
        self.cache = LeaseCache(
            clock=clock, verifier=verifier, want_budget=want_budget,
            offline_grace_ms=offline_grace_ms,
            max_offline_extensions=max_offline_extensions,
            holder_id=holder_id,
        )

    async def admit(self, spec, hits: int = 1):
        """True/False = local lease verdict; None = no lease path, make
        an ordinary server request."""
        from gubernator_tpu.leases.cache import ADMIT, NEED_LEASE

        c = self.cache
        verdict = c.try_admit(spec, hits)
        if verdict == ADMIT:
            return True
        if verdict != NEED_LEASE:
            c.metric_local_denies += hits
            return False
        # One sync+grant round, then one retry (the cache's convenience
        # driver, inlined with awaits; RPC failure → bounded offline
        # extension instead of failing the caller).
        try:
            syncs = c.take_syncs()
            if syncs:
                c.note_synced(syncs, await self.client.lease_sync(syncs))
            tokens = await self.client.lease_grant([c.fill_want(spec)])
        except Exception:
            if c.extend_offline(spec):
                if c.try_admit(spec, hits) == ADMIT:
                    return True
                c.metric_local_denies += hits
                return False
            return None
        if not c.note_grant(spec, tokens[0] if tokens else None):
            return None
        verdict = c.try_admit(spec, hits)
        if verdict == ADMIT:
            return True
        if verdict == NEED_LEASE:
            return None
        c.metric_local_denies += hits
        return False

    async def close(self, deadline: float = None, attempts: int = 2) -> int:
        """Drain unsynced consumption through the server sync path;
        returns admissions left unsynced (also counted in the cache's
        ``metric_sync_lost``)."""
        c = self.cache
        if not c.mark_closed():
            return 0
        for _ in range(max(1, attempts)):
            if deadline is not None and c.now_ms() >= deadline * 1000:
                break
            syncs = c.take_syncs(release=True)
            if not syncs:
                break
            try:
                acks = await self.client.lease_sync(syncs)
            except Exception:
                continue
            c.note_synced(syncs, acks)
        return c.abandon_unsynced()

    def stats(self):
        return self.cache.stats()
