"""Client convenience surface (reference client.go + python/gubernator).

The framework's full async client lives in
:class:`gubernator_tpu.transport.daemon.DaemonClient`; this module adds
the small helpers the reference ships for callers — duration constants,
millisecond-timestamp converters (client.go:70-86), ``sleep_until_reset``
(python/gubernator/__init__.py:14), peer/string randomizers
(client.go:89-105) — and a ``dial_v1`` that mirrors ``DialV1Server``
(client.go:44-65: optional TLS, tracing-instrumented channel).
"""

from __future__ import annotations

import asyncio
import random
import secrets
import string
import time
from typing import Sequence

from gubernator_tpu.types import PeerInfo
from gubernator_tpu.utils import timeutil

MILLISECOND = 1
SECOND = 1000 * MILLISECOND
MINUTE = 60 * SECOND


def to_timestamp(duration_s: float) -> int:
    """Seconds → the millisecond duration/reset_time unit of the API
    (client.go:70 ToTimeStamp, from Go's time.Duration)."""
    return int(duration_s * 1000)


def from_timestamp(ts_ms: int) -> float:
    """Unix-ms timestamp → seconds from now (client.go:76 FromTimeStamp);
    negative when ``ts_ms`` is in the future."""
    return (timeutil.now_ms() - ts_ms) / 1000.0


def from_unix_milliseconds(ts_ms: int) -> float:
    """Unix-ms timestamp → unix seconds (client.go:84)."""
    return ts_ms / 1000.0


def sleep_until_reset(reset_time_ms: int) -> None:
    """Block until a response's ``reset_time`` has passed
    (python/gubernator/__init__.py:14)."""
    delta = reset_time_ms - timeutil.now_ms()
    if delta > 0:
        time.sleep(delta / 1000.0)


async def asleep_until_reset(reset_time_ms: int) -> None:
    """Async variant of :func:`sleep_until_reset`."""
    delta = reset_time_ms - timeutil.now_ms()
    if delta > 0:
        await asyncio.sleep(delta / 1000.0)


def random_peer(peers: Sequence[PeerInfo]) -> PeerInfo:
    """A random peer from the list (client.go:89 RandomPeer)."""
    return random.choice(list(peers))


def random_string(n: int) -> str:
    """Random alphanumeric string of length ``n`` (client.go:97),
    crypto-sourced like the reference."""
    alphabet = string.digits + string.ascii_uppercase + string.ascii_lowercase
    return "".join(secrets.choice(alphabet) for _ in range(n))


def dial_v1(server: str, tls=None):
    """Connect to a daemon, returning the async client
    (reference DialV1Server, client.go:44-65).

    ``tls`` may be a :class:`gubernator_tpu.transport.tlsutil.TLSBundle`
    (client credentials derived from it) or ready-made
    ``grpc.ChannelCredentials``.
    """
    import grpc

    from gubernator_tpu.transport.daemon import DaemonClient

    if not server:
        raise ValueError("server is empty; must provide a server")
    creds = None
    if tls is not None:
        creds = (
            tls if isinstance(tls, grpc.ChannelCredentials)
            else tls.channel_credentials()
        )
    return DaemonClient(server, credentials=creds)
