"""Adaptive overload control plane (docs/overload.md).

Three cooperating pieces thread through the serving path:

* :mod:`~gubernator_tpu.admission.deadline` — per-request deadline
  propagation: fastwire/gRPC edges stamp an absolute local deadline on
  arrival (wire carries the *relative* budget in ``guber-deadline-ms``
  metadata), the tick loop sheds already-expired work before packing,
  and :class:`~gubernator_tpu.service.peer_client.PeerClient` forwards
  the remaining budget as the RPC timeout.
* :mod:`~gubernator_tpu.admission.queue` — the bounded two-class
  pending queue (peer/GLOBAL reconcile traffic outranks client
  traffic) with deadline-ordered drop-oldest-expiring overflow.
* :mod:`~gubernator_tpu.admission.limiter` — the AIMD concurrency
  limiter that adjusts admitted window width against the measured
  window p99 vs. ``GUBER_TARGET_P99_MS``.

Shed answers are never silent: expired/shutdown sheds answer with a
retriable error status, overflow/limiter sheds answer with the
configured degradation policy (``GUBER_SHED_POLICY``) — fail-open
(UNDER_LIMIT, full remaining) or fail-closed (OVER_LIMIT, zero
remaining), mirroring DRAIN_OVER_LIMIT semantics.
"""

from __future__ import annotations

from dataclasses import dataclass

from gubernator_tpu.admission.deadline import (  # noqa: F401
    DEADLINE_METADATA_KEY,
    BudgetExhaustedError,
    batch_deadline,
    budget_header_value,
    deadline_from_header,
    remaining_budget,
)
from gubernator_tpu.admission.limiter import AimdLimiter  # noqa: F401
from gubernator_tpu.admission.queue import (  # noqa: F401
    CLASS_CLIENT,
    CLASS_PEER,
    AdmissionQueue,
    QueueItem,
)
from gubernator_tpu.config import env_knob, parse_duration

# Shed policies (GUBER_SHED_POLICY).  Fail-open answers UNDER_LIMIT with
# the full limit remaining (availability over enforcement: a shed caller
# proceeds as if admitted); fail-closed answers OVER_LIMIT with zero
# remaining (enforcement over availability: a shed caller is throttled).
POLICY_FAIL_OPEN = "fail-open"
POLICY_FAIL_CLOSED = "fail-closed"
SHED_POLICIES = (POLICY_FAIL_OPEN, POLICY_FAIL_CLOSED)

# Retriable shed messages: transported as per-item errors so callers can
# distinguish "shed, retry elsewhere / with a fresh budget" from a real
# rate-limit verdict.  Kept as prefix constants so tests and the bench
# rung can classify responses without string-matching free text.
SHED_EXPIRED_MSG = (
    "request shed: deadline expired before processing; retry with a "
    "fresh deadline"
)
SHED_SHUTDOWN_MSG = (
    "request shed: tick loop shutting down; retry against another peer"
)
SHED_BACKPRESSURE_MSG = (
    "request shed: ingest arena exhausted; retry after backoff"
)
SHED_RESHARD_MSG = (
    "request shed: shard transition in progress; retry after the "
    "cutover window"
)


@dataclass
class AdmissionConfig:
    """Resolved overload-control knobs (see docs/overload.md).

    ``request_timeout`` is the default per-request budget stamped at the
    serving edge when the caller supplied none; ``target_p99_ms`` == 0
    disables the AIMD limiter; ``pending_limit`` == 0 auto-sizes the
    bounded queue to 8x the window limit.
    """

    request_timeout: float = 30.0
    target_p99_ms: float = 0.0
    pending_limit: int = 0
    shed_policy: str = POLICY_FAIL_OPEN

    @classmethod
    def from_env(cls) -> "AdmissionConfig":
        try:
            timeout = env_knob(
                "GUBER_REQUEST_TIMEOUT", 30.0, parse=parse_duration)
        except ValueError:
            timeout = 30.0
        try:
            target = env_knob("GUBER_TARGET_P99_MS", 0.0, parse=float)
        except ValueError:
            target = 0.0
        try:
            pending = env_knob("GUBER_PENDING_LIMIT", 0, parse=int)
        except ValueError:
            pending = 0
        policy = env_knob("GUBER_SHED_POLICY", POLICY_FAIL_OPEN)
        if policy not in SHED_POLICIES:
            policy = POLICY_FAIL_OPEN
        return cls(
            request_timeout=max(0.0, float(timeout)),
            target_p99_ms=max(0.0, float(target)),
            pending_limit=max(0, int(pending)),
            shed_policy=policy,
        )

    def effective_pending_limit(self, window_limit: int) -> int:
        if self.pending_limit > 0:
            return self.pending_limit
        return max(1, 8 * int(window_limit))


def under_pressure(
    limiter: AimdLimiter,
    pending: int,
    pending_limit: int,
    batch_limit: int,
) -> bool:
    """Overload-degrade trigger for the lease tier (docs/leases.md):
    True when the AIMD limiter has backed off below the full window, or
    the pending queue has filled past half its bound.  Under pressure,
    lease grants degrade to cheap TTL extension of already-held budget
    (no decision, no device work) instead of full decisions — the lease
    analog of docs/overload.md's shed-before-pack discipline."""
    if limiter is not None and limiter.enabled:
        if limiter.window_limit < int(batch_limit):
            return True
    return pending >= max(1, int(pending_limit)) // 2
