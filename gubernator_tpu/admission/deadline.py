"""Deadline propagation helpers (docs/overload.md).

Deadlines are *absolute local monotonic* timestamps (seconds, same
clock as the component that stamped them).  They never cross a process
boundary directly — the wire carries the *relative* remaining budget in
milliseconds via the ``guber-deadline-ms`` gRPC metadata key, and the
receiving edge re-anchors it against its own clock.  That sidesteps
clock skew entirely: each hop only ever subtracts its own elapsed time
from the budget it was handed.
"""

from __future__ import annotations

from typing import Iterable, Optional

# gRPC metadata key carrying the caller's remaining budget in integer
# milliseconds.  Lowercase per gRPC metadata rules.
DEADLINE_METADATA_KEY = "guber-deadline-ms"


class BudgetExhaustedError(RuntimeError):
    """The caller's propagated deadline budget is already spent — the
    RPC (or retry) must not be attempted at all."""


def remaining_budget(deadline: Optional[float], now: float) -> Optional[float]:
    """Seconds left before ``deadline`` (None = unbounded budget)."""
    if deadline is None:
        return None
    return deadline - now


def budget_header_value(deadline: Optional[float], now: float) -> Optional[str]:
    """Render the remaining budget as a ``guber-deadline-ms`` metadata
    value, or None when there is no deadline to propagate.  A spent
    budget renders as ``"0"`` so the receiver sheds immediately instead
    of inheriting its own generous default."""
    if deadline is None:
        return None
    return str(max(0, int((deadline - now) * 1000.0)))


def deadline_from_header(value: Optional[str], now: float) -> Optional[float]:
    """Re-anchor a ``guber-deadline-ms`` metadata value against the
    local clock.  Malformed values are ignored (None) rather than
    rejected — a bad budget header must never fail an otherwise-valid
    request."""
    if value is None:
        return None
    try:
        ms = int(value)
    except (TypeError, ValueError):
        return None
    if ms < 0:
        return None
    return now + ms / 1000.0


def batch_deadline(reqs: Iterable) -> Optional[float]:
    """The effective deadline for a batch submission: the earliest
    per-request deadline present, or None when no request carries one.
    Shed granularity in the tick loop is the queued item, so a batch
    inherits its most urgent member's budget."""
    best: Optional[float] = None
    for r in reqs:
        d = getattr(r, "deadline", None)
        if d is None:
            continue
        if best is None or d < best:
            best = d
    return best
