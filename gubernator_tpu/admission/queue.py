"""Bounded two-class admission queue (docs/overload.md).

Replaces the tick loop's unbounded pending list.  Two strict priority
classes: peer/GLOBAL reconcile traffic (class 0) outranks client
traffic (class 1) — under overload the mesh keeps converging while
client work degrades first, matching the reference's GLOBAL behavior
guarantees.  Overflow policy is deadline-ordered drop-oldest-expiring:
the queued *client* item whose deadline is soonest is shed first (it is
the work most likely to expire unserved anyway); only an all-peer
backlog sheds peer work.  The queue never sheds down to empty to admit
an oversized item — a single item larger than the whole limit is still
admitted when the queue is empty, so the bound can never deadlock a
legal batch.

Not thread-safe by itself: the tick loop serializes access under its
own condition lock.
"""

from __future__ import annotations

from collections import deque
from typing import Deque, List, Optional, Tuple

from gubernator_tpu.utils.hotpath import hot_path

CLASS_PEER = 0
CLASS_CLIENT = 1


class QueueItem:
    """One queued submission: an object batch or a columnar batch plus
    its completion future, admission class, and absolute deadline."""

    __slots__ = ("kind", "payload", "n", "fut", "deadline", "klass", "seq")

    def __init__(self, kind, payload, n, fut, deadline=None,
                 klass=CLASS_CLIENT, seq=0):
        self.kind = kind
        self.payload = payload
        self.n = int(n)
        self.fut = fut
        self.deadline = deadline
        self.klass = klass
        self.seq = seq

    def expired(self, now: float) -> bool:
        return self.deadline is not None and now >= self.deadline


class AdmissionQueue:
    """Bounded (in *requests*, not items) two-class FIFO-per-class
    queue with deadline-ordered overflow shedding."""

    def __init__(self, limit: int):
        self.limit = max(1, int(limit))
        self._classes: Tuple[Deque[QueueItem], Deque[QueueItem]] = (
            deque(), deque())
        self._requests = 0
        self._seq = 0

    @property
    def requests(self) -> int:
        """Total queued requests across both classes."""
        return self._requests

    def __len__(self) -> int:
        return len(self._classes[0]) + len(self._classes[1])

    def __bool__(self) -> bool:
        return self._requests > 0 or len(self) > 0

    def snapshot(self) -> dict:
        """Cheap public view for the control plane — not ``@hot_path``
        (the autoscaler samples it off the tick path; the tick loop's
        condition serializes access)."""
        return {
            "requests": self._requests,
            "items": len(self),
            "limit": self.limit,
        }

    @hot_path
    def push(self, item: QueueItem) -> List[QueueItem]:
        """Admit ``item``, shedding queued work to stay under the bound.
        Returns the shed items (possibly including ``item`` itself when
        nothing lower-value can make room); the caller answers them."""
        self._seq += 1
        item.seq = self._seq
        shed: List[QueueItem] = []
        while self._requests > 0 and self._requests + item.n > self.limit:
            victim = self._pick_victim(item)
            if victim is None:
                # Nothing queued is lower-value than the incoming item:
                # shed the arrival itself.
                shed.append(item)
                return shed
            self._remove(victim)
            shed.append(victim)
        dq = self._classes[CLASS_PEER if item.klass == CLASS_PEER
                           else CLASS_CLIENT]
        dq.append(item)
        self._requests += item.n
        return shed

    def _pick_victim(self, incoming: QueueItem) -> Optional[QueueItem]:
        """Deadline-ordered drop-oldest-expiring: the queued client item
        with the soonest deadline (deadline-less items rank last within
        the class, oldest first).  Peer items are only victims when the
        incoming item is itself peer-class and no client work is queued
        — a client arrival never evicts reconcile traffic."""
        victim = self._soonest(self._classes[CLASS_CLIENT])
        if victim is not None:
            return victim
        if incoming.klass == CLASS_PEER:
            return self._soonest(self._classes[CLASS_PEER])
        return None

    @staticmethod
    def _soonest(dq: Deque[QueueItem]) -> Optional[QueueItem]:
        victim: Optional[QueueItem] = None
        for it in dq:
            if victim is None:
                victim = it
                continue
            vd = victim.deadline
            d = it.deadline
            if d is not None and (vd is None or d < vd):
                victim = it
        return victim

    def _remove(self, item: QueueItem) -> None:
        for dq in self._classes:
            try:
                dq.remove(item)
            except ValueError:
                continue
            self._requests -= item.n
            return

    @hot_path
    def pop_window(self, max_requests: int) -> List[QueueItem]:
        """Take the next serving window: peer class drains first, then
        client, up to ``max_requests`` — but always at least one item so
        an oversized batch cannot wedge the loop."""
        out: List[QueueItem] = []
        total = 0
        for dq in self._classes:
            while dq:
                item = dq[0]
                if out and total + item.n > max_requests:
                    return out
                dq.popleft()
                self._requests -= item.n
                out.append(item)
                total += item.n
        return out

    def drain(self) -> List[QueueItem]:
        """Remove and return everything queued (shutdown path)."""
        out: List[QueueItem] = []
        for dq in self._classes:
            out.extend(dq)
            dq.clear()
        self._requests = 0
        return out
