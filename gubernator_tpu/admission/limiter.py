"""AIMD concurrency limiter (docs/overload.md).

Classic additive-increase / multiplicative-decrease on the admitted
window width, driven by the measured per-window latency (the same
dispatch+resolve time the flight recorder attributes to a window, PR 8)
against ``GUBER_TARGET_P99_MS``.  Every ``adjust_every`` windows the
limiter computes the sample p99: at or under target, the window widens
by one additive step; over target, it shrinks multiplicatively — so the
system converges to max goodput instead of max queue.  A target of 0
disables the limiter entirely (the tick loop then admits its static
``batch_limit``), which is the default so unconfigured deployments and
tier-1 tests see byte-identical behavior.
"""

from __future__ import annotations

from typing import List

from gubernator_tpu.utils.hotpath import hot_path


class AimdLimiter:
    """Adjusts the admitted window width from observed window latency."""

    #: multiplicative back-off factor applied when p99 exceeds target.
    DECREASE = 0.8

    def __init__(
        self,
        target_p99_ms: float,
        max_limit: int,
        min_limit: int = 0,
        adjust_every: int = 16,
    ):
        self.target_p99_ms = float(target_p99_ms)
        self.enabled = self.target_p99_ms > 0.0
        self.max_limit = max(1, int(max_limit))
        self.min_limit = (
            max(1, int(min_limit)) if min_limit
            else max(1, self.max_limit // 32)
        )
        self.adjust_every = max(1, int(adjust_every))
        # Start wide open: back off only on evidence of saturation.
        self._limit = self.max_limit
        self._samples: List[float] = []
        self.metric_increases = 0
        self.metric_decreases = 0

    @property
    def window_limit(self) -> int:
        """Current admitted window width, in requests."""
        return self._limit

    def snapshot(self) -> dict:
        """Cheap public view for the control plane (autoscaler,
        /debug/autoscaler) — deliberately NOT ``@hot_path``: it runs on
        the controller's sampling cadence, never inside a tick."""
        return {
            "window_limit": self._limit,
            "enabled": self.enabled,
            "target_p99_ms": self.target_p99_ms,
            "max_limit": self.max_limit,
            "min_limit": self.min_limit,
            "increases": self.metric_increases,
            "decreases": self.metric_decreases,
        }

    @property
    def step(self) -> int:
        """Additive increase per adjustment, in requests."""
        return max(1, self.max_limit // 64)

    @hot_path
    def record(self, window_ms: float) -> None:
        """Feed one window's measured latency; adjusts the limit every
        ``adjust_every`` samples.  No-op when disabled."""
        if not self.enabled:
            return
        self._samples.append(window_ms)
        if len(self._samples) >= self.adjust_every:
            self._adjust()

    def _adjust(self) -> None:
        samples = sorted(self._samples)
        self._samples = []
        idx = min(len(samples) - 1, int(0.99 * len(samples)))
        p99 = samples[idx]
        if p99 <= self.target_p99_ms:
            nxt = min(self.max_limit, self._limit + self.step)
            if nxt > self._limit:
                self.metric_increases += 1
            self._limit = nxt
        else:
            nxt = max(self.min_limit, int(self._limit * self.DECREASE))
            if nxt < self._limit:
                self.metric_decreases += 1
            self._limit = nxt
