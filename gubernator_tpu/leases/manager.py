"""Server-side lease mint: batched grants, reconciles, revocation.

The manager turns lease traffic into the engine's native currency —
batched decisions and one on-device column window per call:

* **Grant** — delegating ``budget`` admissions IS a decision with
  ``hits=budget`` through the ordinary tick path (UNDER_LIMIT → the
  whole slice is charged up front and delegated; OVER_LIMIT → grant 0
  and the client falls back to per-request decisions).  Total
  admissions therefore never exceed server-side decisions plus granted
  budgets: the over-admission invariant is structural, not policed.
* **Reconcile** — a sync's unused budget flows back through the same
  decision path as *negative* hits (bucket_transition credits tokens
  for negative hits), so credit-back needs no new kernel either.
* **Per-holder slices** — several clients may hold leases on the same
  key concurrently, so a key's record carries one slice per leaseholder
  (LeaseSpec/LeaseSync.holder): a sync credits back only the syncing
  holder's unused slice, and cheap extension re-signs only the
  requesting holder's budget — no holder can ever consume or refund
  budget delegated to another.
* **Column accounting** — outstanding budget, lease expiry, and
  generation live as device columns parallel to the SoA table
  (engine.lease_window): one jitted scatter per grant/sync window, no
  per-key host dispatch, exported/restored with the snapshot.  Columns
  mirror the per-key aggregate across holders.

Under overload (tick_loop.under_pressure) grants degrade to *cheap
extension*: re-sign the requesting holder's held budget with a
pushed-out TTL — zero device work, zero decisions — so the lease tier
sheds load exactly when the admission plane most needs it to
(docs/overload.md).
"""

from __future__ import annotations

import asyncio
import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from gubernator_tpu.admission import CLASS_PEER
from gubernator_tpu.config import env_knob, parse_duration
from gubernator_tpu.leases.protocol import (
    LeaseSpec,
    LeaseSync,
    LeaseSyncAck,
    LeaseToken,
)
from gubernator_tpu.leases.signing import LeaseSigner
from gubernator_tpu.types import RateLimitRequest, Status
from gubernator_tpu.utils import sanitize

log = logging.getLogger("gubernator.leases")


@dataclass
class LeaseConfig:
    """GUBER_LEASE_* knob surface (config.ENV_REGISTRY; example.conf)."""

    enabled: bool = True
    ttl_ms: int = 5_000            # GUBER_LEASE_TTL
    budget_fraction: float = 0.1   # GUBER_LEASE_BUDGET_FRACTION
    max_budget: int = 10_000       # GUBER_LEASE_MAX_BUDGET
    credit_back: bool = True       # GUBER_LEASE_CREDIT_BACK
    secret: bytes = b""            # GUBER_LEASE_SECRET

    @classmethod
    def from_env(cls) -> "LeaseConfig":
        def knob(name, default, parse):
            try:
                return env_knob(name, default, parse=parse)
            except ValueError:
                return default

        return cls(
            enabled=bool(knob("GUBER_LEASE_ENABLED", 1, int)),
            ttl_ms=int(
                knob("GUBER_LEASE_TTL", 5.0, parse_duration) * 1000),
            budget_fraction=knob("GUBER_LEASE_BUDGET_FRACTION", 0.1, float),
            max_budget=knob("GUBER_LEASE_MAX_BUDGET", 10_000, int),
            credit_back=bool(knob("GUBER_LEASE_CREDIT_BACK", 1, int)),
            secret=str(knob("GUBER_LEASE_SECRET", "", str)).encode(),
        )


@dataclass
class _Slice:
    """One leaseholder's live delegation on one key."""

    outstanding: int           # granted, not-yet-reconciled budget
    expires_ms: int


@dataclass
class _Held:
    """Host record of one key's live delegations (the signing/authority
    source of truth; the device columns mirror the per-key aggregate for
    batch accounting and snapshot survival).  ``holders`` keys slices by
    leaseholder identity so reconciles and extensions only ever touch
    the syncing client's own budget."""

    generation: int
    limit: int
    duration: int
    algorithm: int
    holders: Dict[str, _Slice] = field(default_factory=dict)

    @property
    def outstanding(self) -> int:
        return sum(s.outstanding for s in self.holders.values())

    @property
    def expires_ms(self) -> int:
        return max((s.expires_ms for s in self.holders.values()), default=0)


class LeaseManager:
    """Mints, renews, reconciles, and revokes quota leases.

    ``tick_loop=None`` runs decisions synchronously through
    ``engine.process`` (grant_local/sync_local — benches and
    ManualClock tests); with a tick loop, grants/syncs ride the
    ordinary admission queue (syncs in the peer class).
    """

    def __init__(
        self,
        engine,
        tick_loop=None,
        config: Optional[LeaseConfig] = None,
        metrics=None,
        signer: Optional[LeaseSigner] = None,
        clock=time.time,
    ):
        self.engine = engine
        self.tick_loop = tick_loop
        self.config = config or LeaseConfig.from_env()
        self.metrics = metrics
        self.signer = signer or LeaseSigner(secret=self.config.secret)
        self._clock = clock
        self._held: Dict[Tuple[str, str], _Held] = {}
        # Per-key generation high-water mark, surviving record removal:
        # a release pops the record, but a recreated record must NOT
        # restart at generation 1 or a partitioned client holding a
        # token from the earlier incarnation could sync against the new
        # one.  Generations are monotonic per key for the manager's
        # lifetime (and per process restart the random HMAC secret /
        # fresh ed25519 key already invalidates old tokens).
        self._gen_floor: Dict[Tuple[str, str], int] = {}
        self._lock = sanitize.lock("LeaseManager._lock")
        # Plain-int counters (the tick-loop delta-sync pattern mirrors
        # engine counters; these sync straight into prometheus families
        # at increment time since lease traffic is not per-tick-window).
        self.metric_grants = 0
        self.metric_renewals = 0
        self.metric_revocations = 0
        self.metric_sync_loss = 0
        self.metric_sync_dropped = 0

    # ------------------------------------------------------------------
    # Public async surface (daemon path)
    # ------------------------------------------------------------------
    async def grant(
        self, specs: Sequence[LeaseSpec]
    ) -> List[Optional[LeaseToken]]:
        plan = self._plan_grants(specs)
        if plan.reqs:
            fut = self.tick_loop.submit(plan.reqs)
            responses = await asyncio.wrap_future(fut)
        else:
            responses = []
        return self._commit_grants(plan, responses)

    async def sync(
        self, syncs: Sequence[LeaseSync]
    ) -> List[LeaseSyncAck]:
        plan = self._plan_syncs(syncs)
        responses = []
        if plan.reqs:
            # Reconcile traffic rides the peer admission class: syncs
            # carry already-admitted consumption, so shedding them loses
            # accounting while shedding a client decision loses nothing.
            # _commit_syncs inspects the responses so that any shed or
            # unapplied reconcile is at least counted, never silent.
            fut = self.tick_loop.submit(plan.reqs, klass=CLASS_PEER)
            responses = await asyncio.wrap_future(fut)
        return self._commit_syncs(plan, responses)

    # ------------------------------------------------------------------
    # Synchronous surface (engine-only: benches, virtual-clock tests)
    # ------------------------------------------------------------------
    def grant_local(
        self, specs: Sequence[LeaseSpec], now_ms: Optional[int] = None
    ) -> List[Optional[LeaseToken]]:
        plan = self._plan_grants(specs, now_ms)
        responses = (
            self.engine.process(plan.reqs, now=now_ms) if plan.reqs else []
        )
        return self._commit_grants(plan, responses, now_ms)

    def sync_local(
        self, syncs: Sequence[LeaseSync], now_ms: Optional[int] = None
    ) -> List[LeaseSyncAck]:
        plan = self._plan_syncs(syncs, now_ms)
        responses = (
            self.engine.process(plan.reqs, now=now_ms) if plan.reqs else []
        )
        return self._commit_syncs(plan, responses, now_ms)

    # ------------------------------------------------------------------
    # Grant planning/commit
    # ------------------------------------------------------------------
    @dataclass
    class _GrantPlan:
        specs: List[LeaseSpec]
        reqs: List[RateLimitRequest]
        decide: List[int]          # spec index per request
        budgets: List[int]         # requested slice per request
        cheap: Dict[int, LeaseToken]   # spec index → extended token
        declined: Dict[int, None]      # spec index → lease tier off

    def _now_ms(self, now_ms: Optional[int] = None) -> int:
        return int(self._clock() * 1000) if now_ms is None else int(now_ms)

    def _budget_for(self, spec: LeaseSpec) -> int:
        cap = max(1, int(spec.limit * self.config.budget_fraction))
        cap = min(cap, self.config.max_budget, max(1, spec.limit))
        return min(spec.want, cap) if spec.want > 0 else cap

    def _plan_grants(self, specs, now_ms=None) -> "_GrantPlan":
        now = self._now_ms(now_ms)
        plan = self._GrantPlan(list(specs), [], [], [], {}, {})
        pressure = bool(
            self.tick_loop is not None
            and getattr(self.tick_loop, "under_pressure", lambda: False)()
        )
        with self._lock:
            for i, spec in enumerate(plan.specs):
                if not self.config.enabled:
                    plan.declined[i] = None
                    continue
                k = (spec.name, spec.key)
                rec = self._held.get(k)
                if rec is not None and (
                    rec.limit != spec.limit
                    or rec.duration != spec.duration
                ):
                    # Config changed: revoke the generation.  Every
                    # holder's outstanding stays charged until its sync
                    # reconciles it (a stale-generation sync is handled
                    # conservatively, never credited).
                    rec.generation += 1
                    rec.limit = spec.limit
                    rec.duration = spec.duration
                    rec.holders.clear()
                    self.metric_revocations += 1
                    if self.metrics is not None:
                        self.metrics.lease_revocations.inc()
                sl = (
                    rec.holders.get(spec.holder)
                    if rec is not None else None
                )
                if pressure and sl is not None and sl.outstanding > 0:
                    # Overload degrade (docs/overload.md): extend ONLY
                    # the requesting holder's held slice — no decision,
                    # no device work.  Another holder's budget is never
                    # re-minted here: with N holders on one key, each
                    # extension re-signs that client's own slice, so the
                    # sum of live token budgets never exceeds what was
                    # charged at grant time.
                    sl.expires_ms = now + self.config.ttl_ms
                    plan.cheap[i] = self.signer.mint(
                        spec.name, spec.key, sl.outstanding,
                        sl.expires_ms, rec.generation,
                    )
                    self.metric_renewals += 1
                    if self.metrics is not None:
                        self.metrics.lease_renewals.inc()
                    continue
                budget = self._budget_for(spec)
                plan.decide.append(i)
                plan.budgets.append(budget)
                plan.reqs.append(RateLimitRequest(
                    name=spec.name, unique_key=spec.key, hits=budget,
                    limit=spec.limit, duration=spec.duration,
                    algorithm=spec.algorithm, burst=spec.burst,
                ))
        return plan

    def _commit_grants(
        self, plan: "_GrantPlan", responses, now_ms=None
    ) -> List[Optional[LeaseToken]]:
        now = self._now_ms(now_ms)
        out: List[Optional[LeaseToken]] = [None] * len(plan.specs)
        granted_keys: List[bytes] = []
        granted_cols: List[Tuple[int, int, int]] = []
        with self._lock:
            for i, tok in plan.cheap.items():
                out[i] = tok
            for j, i in enumerate(plan.decide):
                spec = plan.specs[i]
                resp = responses[j]
                k = (spec.name, spec.key)
                rec = self._held.get(k)
                if resp.status != Status.UNDER_LIMIT or getattr(
                        resp, "error", ""):
                    # Bucket too hot to delegate, or the decision was
                    # shed with a retriable error (nothing was charged):
                    # no token — the client falls back to per-request
                    # decisions or retries the grant.
                    continue
                budget = plan.budgets[j]
                if rec is None:
                    # Recreated records continue from the per-key
                    # generation high-water mark, never restart at 1 —
                    # tokens from a released/revoked incarnation must
                    # stay stale forever.
                    rec = self._held[k] = _Held(
                        generation=self._gen_floor.get(k, 0) + 1,
                        limit=spec.limit, duration=spec.duration,
                        algorithm=spec.algorithm,
                    )
                sl = rec.holders.get(spec.holder)
                if sl is None:
                    sl = rec.holders[spec.holder] = _Slice(0, 0)
                sl.outstanding += budget
                sl.expires_ms = now + self.config.ttl_ms
                out[i] = self.signer.mint(
                    spec.name, spec.key, budget, sl.expires_ms,
                    rec.generation,
                )
                self.metric_grants += 1
                if self.metrics is not None:
                    self.metrics.lease_grants.inc()
                granted_keys.append(spec.full_key.encode())
                granted_cols.append(
                    (rec.outstanding, rec.expires_ms, rec.generation))
        self._apply_columns(granted_keys, granted_cols, is_set=True)
        return out

    # ------------------------------------------------------------------
    # Sync planning/commit
    # ------------------------------------------------------------------
    @dataclass
    class _SyncPlan:
        syncs: List[LeaseSync]
        reqs: List[RateLimitRequest]
        req_meta: List[Tuple[str, int]]   # ("credit"|"charge", amount)
        acks: List[LeaseSyncAck]
        col_keys: List[bytes]
        col_vals: List[Tuple[int, int, int]]

    def _plan_syncs(self, syncs, now_ms=None) -> "_SyncPlan":
        now = self._now_ms(now_ms)
        plan = self._SyncPlan(list(syncs), [], [], [], [], [])
        with self._lock:
            for s in plan.syncs:
                k = (s.name, s.key)
                rec = self._held.get(k)
                sl = rec.holders.get(s.holder) if rec is not None else None
                # A known key with a matching generation but no slice
                # for this holder is still stale: whatever this client
                # consumed was never delegated by the live record.
                stale = (
                    rec is None
                    or rec.generation != s.generation
                    or sl is None
                )
                consumed = max(s.consumed, 0)
                applied = 0 if stale else min(consumed, sl.outstanding)
                excess = consumed - applied
                credited = 0
                if not stale:
                    sl.outstanding -= applied
                    done = s.release or sl.expires_ms <= now
                    if done:
                        credited = (
                            sl.outstanding if self.config.credit_back else 0
                        )
                        unused = sl.outstanding
                        sl.outstanding = 0
                        # Only THIS holder's slice ends here — budget
                        # still delegated to other holders of the same
                        # key stays outstanding (their signed tokens
                        # remain live until their own sync/expiry).
                        rec.holders.pop(s.holder, None)
                        if s.release and not rec.holders:
                            self._held.pop(k, None)
                            self._gen_floor[k] = rec.generation
                        if credited > 0:
                            # Unused delegated budget flows back through
                            # the normal decision path: negative hits
                            # ADD tokens (ops/buckets.py) — no special
                            # kernel, full snapshot/GLOBAL semantics.
                            plan.reqs.append(RateLimitRequest(
                                name=s.name, unique_key=s.key,
                                hits=-credited,
                                limit=rec.limit, duration=rec.duration,
                                algorithm=rec.algorithm,
                            ))
                            plan.req_meta.append(("credit", credited))
                        elif unused:
                            pass  # credit-back disabled: stays charged
                charged = 0
                if excess > 0:
                    # Consumption beyond the grant (misbehaving or
                    # recovered client): count the over-admission, and
                    # force-charge it so the bucket reflects reality.
                    self.metric_sync_loss += excess
                    if self.metrics is not None:
                        self.metrics.lease_sync_loss.inc(excess)
                    if rec is not None:
                        # Stale generation ≠ unknown config: the record
                        # keeps the real (limit, duration), so the charge
                        # lands as an ordinary decision instead of a
                        # limit=0 config change that bucket_transition
                        # would clamp to the floor (ops/buckets.py).
                        plan.reqs.append(RateLimitRequest(
                            name=s.name, unique_key=s.key, hits=excess,
                            limit=rec.limit, duration=rec.duration,
                            algorithm=rec.algorithm,
                        ))
                        plan.req_meta.append(("charge", excess))
                        charged = excess
                    else:
                        # No config known for this key at all: a made-up
                        # limit would corrupt the bucket's config, so the
                        # excess is recorded as dropped accounting rather
                        # than charged.
                        self.metric_sync_dropped += excess
                        if self.metrics is not None:
                            self.metrics.lease_sync_dropped.inc(excess)
                if rec is not None:
                    ack_gen = rec.generation
                else:
                    ack_gen = max(
                        self._gen_floor.get(k, 0), s.generation) + 1
                plan.acks.append(LeaseSyncAck(
                    accepted=not stale,
                    generation=ack_gen,
                    credited=credited,
                    charged=charged,
                ))
                if not stale:
                    plan.col_keys.append(
                        f"{s.name}_{s.key}".encode())
                    plan.col_vals.append((
                        rec.outstanding, rec.expires_ms, rec.generation))
        return plan

    def _commit_syncs(self, plan: "_SyncPlan", responses=(),
                      now_ms=None) -> List[LeaseSyncAck]:
        # The host records were already mutated in _plan_syncs; if the
        # peer-class batch was shed (per-item retriable error) or a
        # force-charge bounced off the bucket floor (OVER_LIMIT consumes
        # nothing), the bucket never received the credit/charge.  That
        # drift cannot be rolled back safely — the ack may already be
        # promised — so it is counted and logged, never silent.
        dropped = 0
        for resp, (kind, amount) in zip(responses, plan.req_meta):
            if getattr(resp, "error", ""):
                dropped += amount
            elif kind == "charge" and resp.status != Status.UNDER_LIMIT:
                dropped += amount
        if dropped:
            self.metric_sync_dropped += dropped
            if self.metrics is not None:
                self.metrics.lease_sync_dropped.inc(dropped)
            log.warning(
                "lease reconcile lost %d admissions of bucket "
                "accounting (shed or unapplied credit/charge)", dropped)
        self._apply_columns(plan.col_keys, plan.col_vals, is_set=True)
        return plan.acks

    # ------------------------------------------------------------------
    # Device column window
    # ------------------------------------------------------------------
    def _apply_columns(self, keys: List[bytes],
                       vals: List[Tuple[int, int, int]],
                       is_set: bool) -> int:
        """One batched on-device lease-column update for this call's
        mutations — a single dispatch per window (engine.lease_window's
        exact-work counter proves it).  Engines without lease columns
        (the sharded mesh engine, for now) skip the mirror; the host
        records above stay authoritative either way."""
        if not keys or not hasattr(self.engine, "lease_window"):
            return 0
        budgets = [v[0] for v in vals]
        expires = [v[1] for v in vals]
        gens = [v[2] for v in vals]
        return self.engine.lease_window(
            keys, budgets, expires, gens, is_set=is_set
        )

    # ------------------------------------------------------------------
    def revoke(self, name: str, key: str) -> bool:
        """Explicit revocation: bump the generation so every holder's
        outstanding tokens die at their next sync/renewal."""
        with self._lock:
            rec = self._held.get((name, key))
            if rec is None:
                return False
            rec.generation += 1
            rec.holders.clear()
            self.metric_revocations += 1
            if self.metrics is not None:
                self.metrics.lease_revocations.inc()
            return True

    def verifier(self):
        return self.signer.verifier()

    def outstanding(self, name: str, key: str) -> int:
        with self._lock:
            rec = self._held.get((name, key))
            return rec.outstanding if rec else 0

    def stats(self) -> dict:
        with self._lock:
            return {
                "held": len(self._held),
                "holders": sum(
                    len(r.holders) for r in self._held.values()
                ),
                "grants": self.metric_grants,
                "renewals": self.metric_renewals,
                "revocations": self.metric_revocations,
                "sync_loss": self.metric_sync_loss,
                "sync_dropped": self.metric_sync_dropped,
                "outstanding_total": sum(
                    r.outstanding for r in self._held.values()
                ),
            }
