"""Client-side lease cache: answer rate-limit checks locally while a
signed lease holds budget.

The cache is the client half of the cooperative tier (docs/leases.md):
it admits from the lease's delegated budget with zero server round
trips, and talks to the server only at the lease *edges* — grant,
exhaustion, expiry, release.  Its one hard invariant is **never
over-admit**: the local admit count under a lease can never exceed the
granted budget, under any failure — offline extension stretches a
lease's *time*, never its budget, so a partitioned client degrades to
denials, not to free admissions.

The core is a synchronous state machine over an injectable clock
(ManualClock-compatible: a callable returning float seconds), driven
either by the convenience :meth:`admit` (plain callables — tests, sync
clients) or by async glue that speaks the same primitives
(client.LeaseSession).  Sync/grant callables may raise — including
:class:`~gubernator_tpu.resilience.BreakerOpenError` when the owner is
unreachable — and the cache answers from local state within the bounded
offline grace window instead of failing the caller.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, replace as _replace
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from gubernator_tpu.leases.protocol import (
    LeaseCacheStats,
    LeaseSpec,
    LeaseSync,
    LeaseSyncAck,
    LeaseToken,
)
from gubernator_tpu.utils import sanitize

# try_admit verdicts.
ADMIT = "admit"          # consumed from the local lease
DENY = "deny"            # lease live but budget exhausted and un-renewable
NEED_LEASE = "need_lease"  # caller should grant/renew (sync rides along)


@dataclass
class _Record:
    token: LeaseToken
    remaining: int           # unconsumed local budget
    unsynced: int            # consumed since the last successful sync
    extensions: int = 0      # offline grace extensions applied
    limit: int = 0           # config the lease was granted under —
    duration: int = 0        # a change here means revoke-and-regrant


class LeaseCache:
    """Per-client cache of held leases with local budget accounting."""

    def __init__(
        self,
        grant_fn: Optional[Callable[..., Sequence[Optional[LeaseToken]]]] = None,
        sync_fn: Optional[Callable[..., Sequence[LeaseSyncAck]]] = None,
        *,
        clock: Callable[[], float] = time.time,
        verifier=None,
        want_budget: int = 0,
        offline_grace_ms: int = 5_000,
        max_offline_extensions: int = 3,
        holder_id: Optional[str] = None,
    ):
        self._grant_fn = grant_fn
        self._sync_fn = sync_fn
        self._clock = clock
        self._verifier = verifier
        # Leaseholder identity: the server accounts each holder's slice
        # separately (several clients may lease the same key), so every
        # outgoing spec/sync carries this cache's id.  Random per cache
        # by default — two caches must never collide on one identity.
        self.holder_id = holder_id or os.urandom(8).hex()
        self.want_budget = int(want_budget)
        self.offline_grace_ms = int(offline_grace_ms)
        self.max_offline_extensions = int(max_offline_extensions)
        self._records: Dict[Tuple[str, str], _Record] = {}
        self._lock = sanitize.lock("LeaseCache._lock")
        self._closed = False
        self.metric_local_admits = 0
        self.metric_local_denies = 0
        self.metric_grants = 0
        self.metric_syncs = 0
        self.metric_offline_extensions = 0
        self.metric_sync_lost = 0

    # ------------------------------------------------------------------
    # State-machine primitives (lock-protected; async glue drives these)
    # ------------------------------------------------------------------
    def now_ms(self) -> int:
        return int(self._clock() * 1000)

    def try_admit(self, spec: LeaseSpec, hits: int = 1) -> str:
        """One local admission attempt.  ADMIT consumed ``hits`` from the
        lease; NEED_LEASE means the caller should run a grant round
        (collect :meth:`take_syncs` first) and retry; DENY is a local,
        budget-honest denial."""
        if self._closed:
            raise RuntimeError("lease cache is closed")
        k = (spec.name, spec.key)
        now = self.now_ms()
        with self._lock:
            rec = self._records.get(k)
            if rec is None:
                return NEED_LEASE
            if rec.limit != spec.limit or rec.duration != spec.duration:
                # Config changed under the lease: stop self-enforcing
                # against stale terms; the next grant round syncs what
                # was consumed and the server bumps the generation.
                return NEED_LEASE
            if rec.token.expires_ms <= now:
                return NEED_LEASE
            if rec.remaining >= hits:
                rec.remaining -= hits
                rec.unsynced += hits
                self.metric_local_admits += hits
                return ADMIT
            # Insufficient local budget: a grant round may top it up
            # (the driver denies if the retry still can't cover it).
            return NEED_LEASE

    def take_syncs(self, release: bool = False) -> List[LeaseSync]:
        """Snapshot every record's unsynced consumption as LeaseSync
        items (the consumed counts stay owned by the records until
        :meth:`note_synced` confirms delivery)."""
        out: List[LeaseSync] = []
        with self._lock:
            for (name, key), rec in self._records.items():
                if rec.unsynced > 0 or release:
                    out.append(LeaseSync(
                        name=name, key=key, consumed=rec.unsynced,
                        generation=rec.token.generation, release=release,
                        holder=self.holder_id,
                    ))
        return out

    def note_synced(self, syncs: Sequence[LeaseSync],
                    acks: Sequence[LeaseSyncAck]) -> None:
        """Confirm delivery: subtract the synced counts; a stale-
        generation ack drops the record (the lease was revoked)."""
        with self._lock:
            for s, a in zip(syncs, acks):
                rec = self._records.get((s.name, s.key))
                if rec is None:
                    continue
                rec.unsynced = max(0, rec.unsynced - s.consumed)
                self.metric_syncs += 1
                if not a.accepted or s.release:
                    self._records.pop((s.name, s.key), None)

    def note_grant(self, spec: LeaseSpec,
                   token: Optional[LeaseToken]) -> bool:
        """Install a grant-round result.  A None/zero-budget token means
        the server declined (bucket too hot to delegate) — the caller
        falls back to per-request server decisions.  Returns True when a
        usable lease is now held."""
        k = (spec.name, spec.key)
        with self._lock:
            if token is None or token.budget <= 0:
                self._records.pop(k, None)
                return False
            if self._verifier is not None and not self._verifier.verify(token):
                self._records.pop(k, None)
                return False
            old = self._records.get(k)
            carried = old.unsynced if old is not None else 0
            self._records[k] = _Record(
                token=token, remaining=token.budget, unsynced=carried,
                limit=spec.limit, duration=spec.duration,
            )
            self.metric_grants += 1
            return True

    def extend_offline(self, spec: LeaseSpec) -> bool:
        """The owner is unreachable (breaker open, RPC failure): push the
        held lease's expiry out by the offline grace window — bounded,
        time-only (remaining budget is NOT refreshed, so the no-over-
        admission invariant holds through any partition length).
        Returns False once the extension budget is spent."""
        k = (spec.name, spec.key)
        now = self.now_ms()
        with self._lock:
            rec = self._records.get(k)
            if rec is None or rec.extensions >= self.max_offline_extensions:
                return False
            rec.extensions += 1
            rec.token = rec.token.with_expiry(
                max(rec.token.expires_ms, now) + self.offline_grace_ms,
                rec.token.signature,
            )
            self.metric_offline_extensions += 1
            return True

    # ------------------------------------------------------------------
    # Convenience driver (sync callables; tests and sync clients)
    # ------------------------------------------------------------------
    def admit(self, spec: LeaseSpec, hits: int = 1) -> Optional[bool]:
        """Admit ``hits`` against ``spec`` locally.  True/False is a
        local verdict; None means "no lease path" — the caller should
        fall back to an ordinary server request (which is itself a
        correct, server-accounted decision)."""
        verdict = self.try_admit(spec, hits)
        if verdict == ADMIT:
            return True
        if verdict == DENY:
            self.metric_local_denies += hits
            return False
        # NEED_LEASE: one sync+grant round, then one retry.
        if self._grant_fn is None:
            return None
        syncs = self.take_syncs()
        try:
            if syncs and self._sync_fn is not None:
                self.note_synced(syncs, self._sync_fn(syncs))
            tokens = self._grant_fn([self.fill_want(spec)])
        except Exception:
            # Owner unreachable (BreakerOpenError, RPC failure): answer
            # from local state inside the bounded grace window.
            if self.extend_offline(spec):
                verdict = self.try_admit(spec, hits)
                if verdict == ADMIT:
                    return True
                self.metric_local_denies += hits
                return False
            return None
        held = self.note_grant(spec, tokens[0] if tokens else None)
        if not held:
            return None
        verdict = self.try_admit(spec, hits)
        if verdict == ADMIT:
            return True
        if verdict == DENY:
            self.metric_local_denies += hits
            return False
        # Fresh grant still can't cover ``hits`` (budget cap < batch):
        # not a lease-tier verdict — fall back to a server decision.
        return None

    def fill_want(self, spec: LeaseSpec) -> LeaseSpec:
        """Spec ready to send: this cache's budget ask and leaseholder
        identity filled in (the server accounts slices per holder)."""
        want = (
            self.want_budget if self.want_budget and not spec.want
            else spec.want
        )
        if want != spec.want or spec.holder != self.holder_id:
            return _replace(spec, want=want, holder=self.holder_id)
        return spec

    # ------------------------------------------------------------------
    # Shutdown drain
    # ------------------------------------------------------------------
    def mark_closed(self) -> bool:
        """Flip to closed; False when already closed (close is
        idempotent).  Split out so async drivers (client.LeaseSession)
        can run the same drain shape with awaited sync calls."""
        if self._closed:
            return False
        self._closed = True
        return True

    def abandon_unsynced(self) -> int:
        """Drop every record, counting still-unsynced consumption into
        ``metric_sync_lost`` — the drain's last resort, never silent."""
        lost = 0
        with self._lock:
            for rec in self._records.values():
                lost += rec.unsynced
            self._records.clear()
        self.metric_sync_lost += lost
        return lost

    def close(self, deadline: Optional[float] = None,
              attempts: int = 2) -> int:
        """Flush every unsynced consumed count through the normal sync
        path, bounded and deadline-capped (the PR 4 drain discipline):
        up to ``attempts`` tries, each abandoned once ``deadline`` (on
        this cache's clock, seconds) passes.  Consumption that could not
        be delivered is counted in ``metric_sync_lost`` — never silently
        dropped.  Returns the number of admissions left unsynced."""
        if not self.mark_closed():
            return 0
        for _ in range(max(1, attempts)):
            if deadline is not None and self._clock() >= deadline:
                break
            syncs = self.take_syncs(release=True)
            if not syncs:
                break
            try:
                acks = self._sync_fn(syncs) if self._sync_fn else None
            except Exception:
                continue
            if acks is None:
                break
            self.note_synced(syncs, acks)
        return self.abandon_unsynced()

    # ------------------------------------------------------------------
    def stats(self) -> LeaseCacheStats:
        with self._lock:
            return LeaseCacheStats(
                leases=len(self._records),
                local_admits=self.metric_local_admits,
                local_denies=self.metric_local_denies,
                grants=self.metric_grants,
                syncs=self.metric_syncs,
                offline_extensions=self.metric_offline_extensions,
                sync_lost=self.metric_sync_lost,
                unsynced_consumed=sum(
                    r.unsynced for r in self._records.values()
                ),
                details={
                    f"{n}_{k}": {
                        "remaining": r.remaining,
                        "unsynced": r.unsynced,
                        "expires_ms": r.token.expires_ms,
                        "generation": r.token.generation,
                    }
                    for (n, k), r in self._records.items()
                },
            )
