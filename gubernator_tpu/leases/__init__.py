"""Cooperative quota leases: signed TTL-bounded self-enforcement tier.

Server side: :class:`LeaseManager` mints signed leases and reconciles
consumption as batched engine work.  Client side: :class:`LeaseCache`
answers admissions locally while a lease holds budget.  See
docs/leases.md for the protocol and failure semantics.
"""

from gubernator_tpu.leases.cache import ADMIT, DENY, NEED_LEASE, LeaseCache
from gubernator_tpu.leases.manager import LeaseConfig, LeaseManager
from gubernator_tpu.leases.protocol import (
    LeaseCacheStats,
    LeaseSpec,
    LeaseSync,
    LeaseSyncAck,
    LeaseToken,
)
from gubernator_tpu.leases.signing import (
    HAVE_CRYPTO,
    LeaseSigner,
    LeaseVerifier,
    lease_payload,
)

__all__ = [
    "ADMIT",
    "DENY",
    "NEED_LEASE",
    "HAVE_CRYPTO",
    "LeaseCache",
    "LeaseCacheStats",
    "LeaseConfig",
    "LeaseManager",
    "LeaseSigner",
    "LeaseSpec",
    "LeaseSync",
    "LeaseSyncAck",
    "LeaseToken",
    "LeaseVerifier",
    "lease_payload",
]
