"""Lease token signing: Ed25519 when ``cryptography`` is present, stdlib
HMAC-SHA256 otherwise.

Mirrors tlsutil's soft-dependency posture (transport/tlsutil.py): slim
containers without the ``cryptography`` wheel must still mint and verify
leases, so the import is gated and the HMAC fallback is always available.
The two schemes differ in trust shape, not in protocol:

* ``ed25519`` — the minting node holds the private key; anyone holding
  the public key (clients, peers) can verify but not mint.
* ``hmac-sha256`` — one shared secret both mints and verifies
  (``GUBER_LEASE_SECRET``; unset = a random per-process secret, which
  confines verification to clients who received their tokens from this
  process — fine for single-node and loopback deployments).

The signed payload is a canonical length-prefixed encoding of
``(name, key, budget, expires_ms, generation)`` — every field that grants
authority is covered, so no field can be stretched after minting.
"""

from __future__ import annotations

import hashlib
import hmac as _hmac
import os
import struct

from gubernator_tpu.leases.protocol import LeaseToken

# Gated exactly like tlsutil.HAVE_CRYPTO: the fallback must exercise the
# same code paths the slim container will run.
try:
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PrivateKey,
        Ed25519PublicKey,
    )
    from cryptography.hazmat.primitives import serialization
    from cryptography.exceptions import InvalidSignature

    HAVE_CRYPTO = True
except ImportError:  # pragma: no cover - depends on container build
    Ed25519PrivateKey = Ed25519PublicKey = InvalidSignature = None
    serialization = None
    HAVE_CRYPTO = False

_PAYLOAD_MAGIC = b"guber-lease-v1"


def lease_payload(
    name: str, key: str, budget: int, expires_ms: int, generation: int
) -> bytes:
    """Canonical signed bytes for one lease (length-prefixed, so
    ``("ab","c")`` and ``("a","bc")`` never collide)."""
    nb = name.encode()
    kb = key.encode()
    return b"".join((
        _PAYLOAD_MAGIC,
        struct.pack("<I", len(nb)), nb,
        struct.pack("<I", len(kb)), kb,
        struct.pack("<qqq", budget, expires_ms, generation),
    ))


class LeaseVerifier:
    """Verify-only half of a signer: what a client needs (and all a
    client gets — a verifier can never mint)."""

    def __init__(self, scheme: str, material: bytes):
        self.scheme = scheme
        self._material = material
        self._pub = (
            Ed25519PublicKey.from_public_bytes(material)
            if scheme == "ed25519" else None
        )

    def verify(self, token: LeaseToken) -> bool:
        payload = lease_payload(
            token.name, token.key, token.budget,
            token.expires_ms, token.generation,
        )
        if self.scheme == "ed25519":
            try:
                self._pub.verify(token.signature, payload)
                return True
            except InvalidSignature:
                return False
        mac = _hmac.new(self._material, payload, hashlib.sha256).digest()
        return _hmac.compare_digest(mac, token.signature)


class LeaseSigner:
    """Mints (and verifies) lease signatures.

    ``secret`` pins HMAC with that shared secret (multi-node verify);
    ``force_hmac`` pins the stdlib path without a shared secret (tests,
    slim containers).  Otherwise Ed25519 when available.
    """

    def __init__(self, secret: bytes = b"", force_hmac: bool = False):
        if secret or force_hmac or not HAVE_CRYPTO:
            self.scheme = "hmac-sha256"
            self._secret = secret or os.urandom(32)
            self._priv = None
            self._pub_raw = b""
        else:
            self.scheme = "ed25519"
            self._secret = b""
            self._priv = Ed25519PrivateKey.generate()
            pub = self._priv.public_key()
            self._pub_raw = pub.public_bytes(
                serialization.Encoding.Raw,
                serialization.PublicFormat.Raw,
            )

    def sign(
        self, name: str, key: str, budget: int, expires_ms: int,
        generation: int,
    ) -> bytes:
        payload = lease_payload(name, key, budget, expires_ms, generation)
        if self.scheme == "ed25519":
            return self._priv.sign(payload)
        return _hmac.new(self._secret, payload, hashlib.sha256).digest()

    def mint(
        self, name: str, key: str, budget: int, expires_ms: int,
        generation: int,
    ) -> LeaseToken:
        return LeaseToken(
            name=name, key=key, budget=budget, expires_ms=expires_ms,
            generation=generation,
            signature=self.sign(name, key, budget, expires_ms, generation),
        )

    def verifier(self) -> LeaseVerifier:
        if self.scheme == "ed25519":
            return LeaseVerifier("ed25519", self._pub_raw)
        return LeaseVerifier("hmac-sha256", self._secret)

    def verify(self, token: LeaseToken) -> bool:
        return self.verifier().verify(token)
