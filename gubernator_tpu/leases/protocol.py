"""Lease protocol types: the nouns shared by the server mint, the client
cache, and the wire frames.

A *quota lease* delegates a slice of one rate limit's budget to a client
for a bounded TTL: the server charges the whole slice against the bucket
up front (one ordinary batched decision), signs ``(name, key, budget,
expiry, generation)``, and the client self-enforces locally — admitting
from the lease without any server round trip — until the lease expires,
exhausts, or is revoked, at which point it syncs the consumed count back
(docs/leases.md).

``generation`` is the revocation handle: the server bumps it whenever the
limit's configuration changes, and a sync carrying a stale generation is
reconciled conservatively (no credit-back) instead of trusted.

``holder`` is the leaseholder identity: several clients may hold leases
on the same key at once, so every spec and sync names the client it
belongs to and the server accounts each holder's delegated slice
separately — one holder's release or renewal can never credit back (or
re-mint) budget delegated to another.  :class:`~gubernator_tpu.leases
.cache.LeaseCache` stamps its own id automatically; callers driving the
manager directly pick any stable string (empty is a valid — shared —
identity).
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace as _replace


@dataclass(frozen=True)
class LeaseSpec:
    """A client's request for (or renewal of) one lease."""

    name: str
    key: str
    limit: int
    duration: int              # limit window, ms
    algorithm: int = 0         # types.Algorithm (0 = TOKEN_BUCKET)
    burst: int = 0
    want: int = 0              # requested budget; 0 = server default
    holder: str = ""           # leaseholder identity (per-client slice)

    @property
    def full_key(self) -> str:
        return f"{self.name}_{self.key}"


@dataclass(frozen=True)
class LeaseToken:
    """A signed, TTL-bounded delegation of ``budget`` admissions."""

    name: str
    key: str
    budget: int                # admissions delegated by this grant
    expires_ms: int            # epoch ms; self-enforcement ends here
    generation: int            # monotonic revocation counter
    signature: bytes = b""

    @property
    def full_key(self) -> str:
        return f"{self.name}_{self.key}"

    def with_expiry(self, expires_ms: int, signature: bytes) -> "LeaseToken":
        """A re-signed copy with a pushed-out expiry (the cheap-extension
        and offline-grace paths; budget and generation are unchanged)."""
        return _replace(self, expires_ms=expires_ms, signature=signature)


@dataclass(frozen=True)
class LeaseSync:
    """A client's report of lease consumption since its last sync."""

    name: str
    key: str
    consumed: int              # admissions consumed since the last sync
    generation: int            # generation of the lease consumed under
    release: bool = False      # True = lease is done; credit unused back
    holder: str = ""           # leaseholder identity (per-client slice)

    @property
    def full_key(self) -> str:
        return f"{self.name}_{self.key}"


@dataclass(frozen=True)
class LeaseSyncAck:
    """Server's answer to one LeaseSync item."""

    accepted: bool             # False = generation was stale (revoked)
    generation: int            # the server's current generation
    credited: int = 0          # unused budget credited back to the bucket
    charged: int = 0           # excess beyond grant force-charged


# Introspection/test helper: every record a cache holds, flattened.
@dataclass
class LeaseCacheStats:
    leases: int = 0
    local_admits: int = 0
    local_denies: int = 0
    grants: int = 0
    syncs: int = 0
    offline_extensions: int = 0
    sync_lost: int = 0
    unsynced_consumed: int = 0
    details: dict = field(default_factory=dict)
