"""Federation envelope types: the inter-region exchange unit.

An envelope carries per-key hit *deltas* from one origin node to the
owning peer of each key in one remote region, tagged with a per-channel
monotonic sequence number.  The merge discipline makes delivery safe
under every WAN failure mode the breaker path produces:

* **Commutative** — records are additive hit deltas; each (origin →
  target) channel numbers its envelopes independently, so envelopes from
  different origins apply in any interleaving and converge to the same
  totals.
* **Idempotent** — the receiver keeps the last applied sequence per
  channel (:class:`ReceiveLedger`); a redelivered envelope (``seq <=
  last``) is acked but not re-applied, so a retry after a lost ack (the
  one-way-partition case) never double-counts.  The channel identity is
  ``(origin, epoch)`` — the sender's advertise address *plus* a
  per-boot nonce — so a restarted sender (same address, seq back at 1)
  opens a fresh ledger entry instead of having its first envelopes
  swallowed as duplicates of the previous incarnation's sequences.

Exactly-once then falls out of the sender discipline in
:class:`~gubernator_tpu.federation.manager.FederationManager`: at most
one envelope is in flight per channel, a failed send retries the *same*
envelope (same seq, same records) while new deltas merge into the
pending buffer for ``seq + 1`` — no hit is ever dropped or applied
twice.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Tuple

from gubernator_tpu.types import Behavior, set_behavior


@dataclass
class FederationRecord:
    """One key's accumulated hit delta plus the limit config a remote
    region needs to create the bucket if it has never seen the key."""

    name: str = ""
    unique_key: str = ""
    hits: int = 0
    limit: int = 0
    duration: int = 0
    algorithm: int = 0
    behavior: int = 0
    burst: int = 0
    created_at: int = 0

    def hash_key(self) -> str:
        return self.name + "_" + self.unique_key

    def merge(self, other: "FederationRecord") -> None:
        """Fold another delta for the same key into this one: hits add
        (the commutative core), limit config takes the newer record's
        values (last-writer-wins, matching queue_update's dict
        overwrite), RESET_REMAINING ORs in like the intra-region hit
        aggregation."""
        self.hits += other.hits
        self.limit = other.limit
        self.duration = other.duration
        self.algorithm = other.algorithm
        if other.behavior & int(Behavior.RESET_REMAINING):
            self.behavior = set_behavior(
                self.behavior, Behavior.RESET_REMAINING, True)
        self.burst = other.burst
        self.created_at = other.created_at


@dataclass
class FederationEnvelope:
    """A batch of records on one (origin node → target peer) channel."""

    origin: str = ""   # sender's advertise address
    region: str = ""   # sender's datacenter (loop-prevention tag)
    epoch: str = ""    # sender's boot nonce; (origin, epoch) = channel id
    seq: int = 0       # per-channel monotonic sequence, starts at 1
    records: List[FederationRecord] = field(default_factory=list)


@dataclass
class FederationAck:
    """Receiver's reply: the highest sequence applied for the origin."""

    origin: str = ""
    seq: int = 0
    applied: int = 0   # records applied (0 for a duplicate no-op)


class ReceiveLedger:
    """Last-applied sequence per ``(origin, epoch)`` channel: the
    idempotency gate.

    The sender guarantees at most one outstanding envelope per channel
    and only advances ``seq`` after an ack, so on a healthy channel
    sequences arrive in order; ``seq <= last`` can only mean a
    redelivery of an envelope whose ack was lost — a no-op.

    Keying by epoch (the sender's per-boot nonce) is what makes that
    inference restart-safe: a rebooted sender reuses its advertise
    address but numbers a *fresh* stream from 1, and without the epoch
    every envelope of the new incarnation would compare ``<=`` the old
    ledger entry and be acked-but-dropped for as long as the previous
    uptime.  Dead epochs' entries are retained (one int per sender
    boot) so a straggler redelivery from the previous incarnation is
    still recognized as a duplicate."""

    def __init__(self):
        self._last: Dict[Tuple[str, str], int] = {}

    def seen(self, env: FederationEnvelope) -> bool:
        """True for a duplicate (ack ``seq`` again, apply nothing)."""
        return env.seq <= self._last.get((env.origin, env.epoch), 0)

    def mark(self, env: FederationEnvelope) -> None:
        """Record a successful apply.  Called *after* the apply lands, so
        an apply that fails mid-RPC leaves the sequence unmarked and the
        sender's retry of the same envelope is admitted, not dropped."""
        key = (env.origin, env.epoch)
        self._last[key] = max(env.seq, self._last.get(key, 0))

    def admit(self, env: FederationEnvelope) -> bool:
        """Check-and-mark in one step (the unit-fuzz convenience): True
        when the envelope is new, False for a duplicate."""
        if self.seen(env):
            return False
        self.mark(env)
        return True

    def last(self, origin: str, epoch: str = "") -> int:
        return self._last.get((origin, epoch), 0)


def merge_records(
    into: Dict[str, FederationRecord],
    records: List[FederationRecord],
    limit: int,
) -> Tuple[int, int]:
    """Fold ``records`` into the per-key map ``into``, bounded at
    ``limit`` *distinct keys* (merging bounds the key count, never the
    hits — an existing key always absorbs its delta, so a full buffer
    under sustained traffic still loses nothing for tracked keys).
    Returns (merged, dropped_new_keys)."""
    merged = dropped = 0
    for rec in records:
        k = rec.hash_key()
        prev = into.get(k)
        if prev is not None:
            prev.merge(rec)
            merged += 1
        elif len(into) < limit:
            into[k] = rec
            merged += 1
        else:
            dropped += 1
    return merged, dropped
