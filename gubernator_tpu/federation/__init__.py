"""Multi-region GLOBAL federation (docs/federation.md).

Partition-tolerant bounded-staleness reconcile between datacenters:
:class:`FederationManager` runs the async inter-region envelope
exchange over the resilience breaker/backoff/redelivery path;
:mod:`~gubernator_tpu.federation.envelope` defines the commutative,
idempotent merge unit it ships (``GFE1`` frames on the wire,
transport/fastwire.py).
"""

from __future__ import annotations

from gubernator_tpu.federation.envelope import (
    FederationAck,
    FederationEnvelope,
    FederationRecord,
    ReceiveLedger,
    merge_records,
)
from gubernator_tpu.federation.manager import FED_ORIGIN_KEY, FederationManager

__all__ = [
    "FED_ORIGIN_KEY",
    "FederationAck",
    "FederationEnvelope",
    "FederationManager",
    "FederationRecord",
    "ReceiveLedger",
    "merge_records",
]
