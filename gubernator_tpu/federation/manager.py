"""Inter-region bounded-staleness reconcile: the federation sender/receiver.

Two-level GLOBAL topology (docs/federation.md): intra-region stays the
existing GlobalManager psum-native reconcile untouched; this manager is
the *inter*-region half.  The intra-region owner of a GLOBAL key — the
one node in its region that sees every hit for it (non-owners forward
theirs through the hits loop) — feeds each owner-side state change here
(:meth:`queue`); deltas accumulate per remote region and per key, and a
supervised loop flushes them every ``GUBER_FEDERATION_INTERVAL`` as
:class:`~gubernator_tpu.federation.envelope.FederationEnvelope` frames
to the owning peer of each key in the remote region's own ring
(RegionPicker — the sender computes remote ownership locally because
every region runs the same hash).

No client request ever waits on a cross-region RPC: requests are
answered from region-local state (the PR 3 degraded-answer discipline
absorbs WAN latency/partitions), so region isolation degrades to
bounded local over-admission — at most ``federation_interval ×
local_rate`` hits drift per region — and never to an outage.

Delivery rides the PR 3 machinery: the target peer's circuit breaker
(one owning peer per region per flush, so the per-region breaker IS
that peer's breaker), decorrelated-jitter backoff between retries, and
a merge-on-requeue pending buffer bounded by ``GUBER_REDELIVERY_LIMIT``
distinct keys.  Exactly-once comes from the channel discipline: at most
one envelope is in flight per (this node → target peer) channel, a
failed send retries the *same* envelope (same seq, same records), and
new deltas merge into pending for the next seq — paired with the
receiver's :class:`~gubernator_tpu.federation.envelope.ReceiveLedger`
duplicate gate, a partition heals by replaying the buffer with zero
hit loss and zero double-counts.
"""

from __future__ import annotations

import asyncio
import logging
import secrets
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from gubernator_tpu.federation.envelope import (
    FederationAck,
    FederationEnvelope,
    FederationRecord,
    ReceiveLedger,
    merge_records,
)
from gubernator_tpu.resilience import (
    DecorrelatedJitterBackoff,
    spawn_supervised,
)
from gubernator_tpu.types import (
    MAX_BATCH_SIZE,
    Behavior,
    RateLimitRequest,
    set_behavior,
)

log = logging.getLogger("gubernator.federation")

# Metadata key stamped on federation-applied requests: the receive path
# submits them through the normal owner handler (which re-broadcasts
# intra-region), and GlobalManager's federation feed skips requests
# carrying it — without the tag, region A's hits applied in B would
# federate back to A (and to every third region the origin already
# reached directly), double-counting on each lap.
FED_ORIGIN_KEY = "fed-origin"


@dataclass
class _Channel:
    """One (this node → remote owning peer) envelope stream."""

    peer: object
    region: str
    seq: int = 0                    # last assigned sequence
    inflight: Optional[FederationEnvelope] = None
    inflight_since: float = 0.0
    failing: bool = False           # last send attempt failed
    sending: bool = False           # an RPC is awaiting right now
    orphaned: bool = False          # dropped from the ring mid-send
    next_try: float = 0.0
    backoff: DecorrelatedJitterBackoff = field(
        default=None)  # type: ignore[assignment]


class FederationManager:
    """Owns the inter-region exchange for one V1Instance."""

    def __init__(self, instance, metrics=None, clock=time.monotonic,
                 sleep=asyncio.sleep, epoch: str = ""):
        self.instance = instance
        self.metrics = metrics
        self._clock = clock
        self._sleep = sleep
        conf = instance.conf
        self.home = conf.data_center
        self.interval = conf.federation_interval
        self.batch_limit = conf.federation_batch_limit
        self.timeout = conf.federation_timeout
        self.resilience = conf.resilience
        # Boot nonce: receivers key their ReceiveLedger by (origin,
        # epoch), so a restart of this node (same advertise address,
        # seq back at 1) opens a fresh channel instead of having its
        # envelopes dropped as duplicates of the previous incarnation.
        self.epoch = epoch or secrets.token_hex(8)
        # region → key → accumulated delta (merge-on-requeue buffer).
        self._pending: Dict[str, Dict[str, FederationRecord]] = {}
        # region → enqueue time of the oldest un-flushed delta.
        self._pending_since: Dict[str, float] = {}
        # target grpc address → channel.
        self._channels: Dict[str, _Channel] = {}
        # Channels dropped by a ring update while their RPC was still
        # awaiting: the address is quarantined from _compact until the
        # RPC settles, so a peer that leaves and instantly rejoins can't
        # get a second concurrent envelope racing the orphaned one.
        self._orphans: Dict[str, _Channel] = {}
        # target grpc address → last assigned seq, retained across
        # channel drop/recreate (ring churn): the receiver's ledger for
        # this (origin, epoch) survives the churn, so a recreated
        # channel to the same address must continue the sequence, not
        # restart at 1 and be deduplicated into oblivion.
        self._seqs: Dict[str, int] = {}
        self.ledger = ReceiveLedger()
        # One apply at a time per origin channel: a redelivery racing a
        # still-running slow apply of the same envelope must wait and
        # then read the marked ledger (duplicate), not start a second
        # apply off the not-yet-marked one.
        self._apply_locks: Dict[str, asyncio.Lock] = {}
        # Reshard interlock: while True, no envelope is compacted or
        # sent (the ReshardCoordinator pauses sends for FREEZE→CUTOVER
        # so no envelope snapshots half-relayouted owner state; deltas
        # keep accumulating in _pending and flush after resume()).
        self._paused = False
        self._running = True
        self._task = spawn_supervised(
            self._flush_loop, name="federation-flush",
            should_restart=lambda: self._running,
            metrics=metrics, loop_label="federation_flush",
        )

    @property
    def origin(self) -> str:
        """This node's channel identity: its advertise address."""
        return self.instance.conf.advertise_address

    # ------------------------------------------------------------------
    # Sender side
    # ------------------------------------------------------------------
    def queue(self, req: RateLimitRequest) -> None:
        """Record one owner-side GLOBAL state change for every remote
        region.  Called from GlobalManager.queue_update — the one place
        every hit in this region funnels through exactly once."""
        if req.hits == 0:
            return
        if req.metadata.get(FED_ORIGIN_KEY):
            return  # applied FROM a peer region; never re-federate
        try:
            regions = self.instance.region_picker.regions()
        except Exception:
            return
        limit = self.resilience.redelivery_limit
        now = self._clock()
        dropped_total = 0
        for region in regions:
            if not region or region == self.home:
                continue
            pending = self._pending.setdefault(region, {})
            if not pending:
                self._pending_since[region] = now
            rec = FederationRecord(
                name=req.name, unique_key=req.unique_key, hits=req.hits,
                limit=req.limit, duration=req.duration,
                algorithm=int(req.algorithm), behavior=int(req.behavior),
                burst=req.burst, created_at=req.created_at or 0,
            )
            _, dropped = merge_records(pending, [rec], limit)
            dropped_total += dropped
        if dropped_total:
            # Never silent: a full pending buffer under a long partition
            # means this key's drift will NOT heal on rejoin.
            log.warning(
                "federation pending buffer full (GUBER_REDELIVERY_LIMIT"
                "=%d keys): dropped %d new-key records", limit,
                dropped_total,
            )

    async def _flush_loop(self) -> None:
        while self._running:
            await self._sleep(self.interval)
            if not self._running:
                return
            await self._flush_once()
            self._update_staleness()

    def pause(self) -> None:
        """Stop compacting/sending envelopes (reshard FREEZE→CUTOVER).
        Queued deltas keep merging into ``_pending``; nothing is lost.
        Called from the coordinator's executor thread — a plain bool
        flip read by the flush loop is the whole protocol (same shape
        as MeshGlobalEngine.pause_reconcile)."""
        self._paused = True

    def resume(self) -> None:
        """Re-enable envelope flushes after reshard commit/abort; the
        next flush tick drains everything accumulated under the pause."""
        self._paused = False

    async def _flush_once(self, force_retry: bool = False) -> None:
        """Compact pending deltas into envelopes on idle channels, then
        send every due envelope concurrently.  A pause() (reshard
        cutover in flight) gates the whole flush — including the
        force_retry final flush, which is safe because the coordinator's
        finally block always resumes before the instance closes."""
        if self._paused:
            return
        for region, pending in self._pending.items():
            if not pending:
                continue
            self._compact(region, pending)
            if not pending:
                self._pending_since.pop(region, None)
        now = self._clock()
        due = [
            ch for ch in self._channels.values()
            if ch.inflight is not None and (force_retry or now >= ch.next_try)
        ]
        if due:
            await asyncio.gather(*(self._send(ch) for ch in due))

    def _compact(self, region: str,
                 pending: Dict[str, FederationRecord]) -> None:
        """Route pending keys to their remote-region owners and build the
        next envelope on every channel without one in flight.  Keys whose
        channel is busy (or whose region has no reachable ring yet) stay
        pending — merge-on-requeue keeps accumulating their hits."""
        groups: Dict[str, tuple] = {}
        for key in pending:
            try:
                peer = self.instance.region_picker.get(key, region)
            except Exception:
                return  # no ring for the region yet; keep everything
            addr = peer.info.grpc_address
            if addr in groups:
                groups[addr][1].append(key)
            else:
                groups[addr] = (peer, [key])
        for addr, (peer, keys) in groups.items():
            if addr in self._orphans:
                continue  # quarantined until the orphaned RPC settles
            ch = self._channels.get(addr)
            if ch is None:
                rc = self.resilience
                ch = self._channels[addr] = _Channel(
                    peer=peer, region=region,
                    seq=self._seqs.get(addr, 0),
                    backoff=DecorrelatedJitterBackoff(
                        rc.forward_backoff_base, rc.forward_backoff_cap),
                )
            ch.peer = peer  # ring churn may swap the handle
            if ch.inflight is not None:
                continue
            take = keys[: self.batch_limit]
            ch.seq += 1
            self._seqs[addr] = ch.seq
            ch.inflight = FederationEnvelope(
                origin=self.origin, region=self.home, epoch=self.epoch,
                seq=ch.seq,
                records=[pending.pop(k) for k in take],
            )
            ch.inflight_since = self._clock()

    async def _send(self, ch: _Channel) -> None:
        env = ch.inflight
        ch.sending = True
        try:
            ack = await ch.peer.federation_sync(env, timeout=self.timeout)
        except asyncio.CancelledError:
            raise
        except Exception:
            # BreakerOpenError / AioRpcError / malformed-frame — all the
            # same to the channel: the envelope stays in flight and
            # retries with the SAME seq after a jittered backoff.  The
            # receiver's ledger makes the retry safe even when only the
            # ack was lost.
            self._send_failed(ch)
            return
        finally:
            ch.sending = False
        if ack.seq < env.seq:
            # A stale ack (buggy or mixed-version receiver) is a failed
            # delivery, not limbo: without backoff the envelope would
            # retry every interval with the channel reported healthy.
            self._send_failed(ch)
            return
        ch.inflight = None
        ch.inflight_since = 0.0
        ch.failing = False
        ch.next_try = 0.0
        ch.backoff.reset()
        if ch.orphaned:
            self._release_orphan(ch)
        if self.metrics is not None:
            self.metrics.federation_envelopes.labels(result="sent").inc()

    def _send_failed(self, ch: _Channel) -> None:
        if ch.orphaned:
            # The target left the ring while this RPC was awaiting; the
            # channel is already out of the table, so the decision the
            # reroute deferred lands here: the peer never applied the
            # envelope, requeue its records for the new owner.
            self._requeue_inflight(ch)
            self._release_orphan(ch)
            return
        ch.failing = True
        ch.next_try = self._clock() + ch.backoff.next()
        if self.metrics is not None:
            self.metrics.federation_redeliveries.inc()

    def on_ring_update(self) -> None:
        """Reroute after ``set_peers``: drop channels whose target
        address left its region's ring, requeueing any in-flight records
        into the pending buffer so the next compact rehashes them to the
        new owner.  Without this, an envelope pinned to a departed peer
        retries that dead address forever — its records never reach the
        key's new owner, and the channel's failing flag holds
        :meth:`is_degraded` true and the staleness gauge climbing for
        a peer that no longer exists."""
        pickers = self.instance.region_picker.pickers()
        for addr, ch in list(self._channels.items()):
            ring = pickers.get(ch.region)
            if ring is not None and ring.get_by_address(addr) is not None:
                continue
            del self._channels[addr]
            if ch.sending:
                # An RPC to the departed peer is awaiting right now — it
                # may yet succeed (graceful drain acks in flight), so
                # requeueing here could double-deliver.  Defer: _send's
                # completion either finishes the envelope (delivered,
                # nothing to do) or requeues on failure; until then the
                # address is quarantined from _compact.
                ch.orphaned = True
                self._orphans[addr] = ch
                continue
            self._requeue_inflight(ch)

    def _release_orphan(self, ch: _Channel) -> None:
        addr = getattr(ch.peer.info, "grpc_address", "")
        if self._orphans.get(addr) is ch:
            del self._orphans[addr]

    def _requeue_inflight(self, ch: _Channel) -> None:
        """Fold a dropped channel's in-flight records back into its
        region's pending buffer so the next compact rehashes them."""
        env = ch.inflight
        requeued_at = ch.inflight_since or self._clock()
        ch.inflight = None
        ch.inflight_since = 0.0
        if env is None or not env.records:
            return
        pending = self._pending.setdefault(ch.region, {})
        since = self._pending_since.get(ch.region)
        self._pending_since[ch.region] = (
            requeued_at if since is None else min(since, requeued_at))
        _, dropped = merge_records(
            pending, env.records, self.resilience.redelivery_limit)
        if dropped:
            log.warning(
                "federation reroute of %s (left the %s ring) dropped "
                "%d new-key records: pending buffer full",
                getattr(ch.peer.info, "grpc_address", "?"), ch.region,
                dropped,
            )

    def _update_staleness(self) -> None:
        """Export the worst-case cross-region drift age: the oldest delta
        not yet acked by its target region (pending or in flight)."""
        if self.metrics is None:
            return
        now = self._clock()
        oldest = None
        for ts in self._pending_since.values():
            oldest = ts if oldest is None else min(oldest, ts)
        for ch in self._channels.values():
            if ch.inflight is not None and ch.inflight_since:
                ts = ch.inflight_since
                oldest = ts if oldest is None else min(oldest, ts)
        self.metrics.federation_staleness.set(
            max(0.0, now - oldest) if oldest is not None else 0.0)

    def is_degraded(self) -> bool:
        """True while any remote region is unreachable (its channel's
        breaker is open or its last send failed): MULTI_REGION answers
        served now may over-admit up to the staleness budget."""
        for ch in self._channels.values():
            if ch.failing:
                return True
            breaker = getattr(ch.peer, "breaker", None)
            if breaker is not None and breaker.is_open():
                return True
        return False

    # ------------------------------------------------------------------
    # Receiver side
    # ------------------------------------------------------------------
    async def receive(self, env: FederationEnvelope) -> FederationAck:
        """Apply one envelope from a peer region and ack it.

        Duplicates (a redelivery whose ack was lost) are acked without
        re-applying; a failed apply leaves the ledger unmarked so the
        sender's retry of the same seq lands the records.

        Cancellation-shielded: the sender's RPC deadline cancels the
        transport handler, but an apply that already committed hits to
        the engine MUST still mark the ledger — cancelling between the
        two would turn every slow apply (e.g. a first-use JIT compile)
        into a double-count when the same envelope is redelivered."""
        return await asyncio.shield(self._receive_inner(env))

    async def _receive_inner(self, env: FederationEnvelope) -> FederationAck:
        lock = self._apply_locks.setdefault(env.origin, asyncio.Lock())
        async with lock:
            return await self._apply_locked(env)

    async def _apply_locked(self, env: FederationEnvelope) -> FederationAck:
        if self.ledger.seen(env):
            if self.metrics is not None:
                self.metrics.federation_envelopes.labels(
                    result="duplicate").inc()
            return FederationAck(origin=env.origin, seq=env.seq, applied=0)
        reqs: List[RateLimitRequest] = []
        for rec in env.records:
            reqs.append(RateLimitRequest(
                name=rec.name,
                unique_key=rec.unique_key,
                hits=rec.hits,
                limit=rec.limit,
                duration=rec.duration,
                algorithm=rec.algorithm,
                behavior=set_behavior(rec.behavior, Behavior.GLOBAL, True),
                burst=rec.burst,
                metadata={FED_ORIGIN_KEY: env.region},
                created_at=rec.created_at or None,
            ))
        # The owner-relay handler: forces DRAIN_OVER_LIMIT on GLOBAL
        # hits, applies to the local engine, and queues the intra-
        # region broadcast — the remote region's hits reach every
        # local peer through the existing machinery.  Chunked at
        # MAX_BATCH_SIZE: the handler rejects larger batches outright,
        # which would turn an oversized envelope (mixed-version or
        # misconfigured sender) into a poison message retried forever.
        for i in range(0, len(reqs), MAX_BATCH_SIZE):
            await self.instance.get_peer_rate_limits(
                reqs[i:i + MAX_BATCH_SIZE])
        self.ledger.mark(env)
        if self.metrics is not None:
            self.metrics.federation_envelopes.labels(result="applied").inc()
        return FederationAck(
            origin=env.origin, seq=env.seq, applied=len(reqs))

    # ------------------------------------------------------------------
    # Introspection / shutdown
    # ------------------------------------------------------------------
    def pending_keys(self) -> int:
        return sum(len(p) for p in self._pending.values())

    def inflight_envelopes(self) -> int:
        return sum(
            1 for ch in self._channels.values() if ch.inflight is not None)

    async def _final_flush(self) -> None:
        """Bounded drain rounds through the normal flush path, retrying
        immediately (no backoff waits — the caller's deadline is the
        budget)."""
        for _ in range(4):
            if not (self.pending_keys() or self.inflight_envelopes()):
                return
            await self._flush_once(force_retry=True)

    async def close(self, drain_timeout: float = 0.0) -> None:
        """Stop the flush loop, then (graceful-drain path) push what's
        still buffered under a bounded deadline."""
        self._running = False
        self._task.cancel()
        await asyncio.gather(self._task, return_exceptions=True)
        if drain_timeout > 0 and (
                self.pending_keys() or self.inflight_envelopes()):
            try:
                await asyncio.wait_for(self._final_flush(), drain_timeout)
            except asyncio.TimeoutError:
                log.warning(
                    "federation drain deadline (%.1fs) expired with %d "
                    "pending keys / %d in-flight envelopes",
                    drain_timeout, self.pending_keys(),
                    self.inflight_envelopes(),
                )
            except Exception:
                log.exception("federation drain flush failed")
        self._update_staleness()
