"""Inter-region bounded-staleness reconcile: the federation sender/receiver.

Two-level GLOBAL topology (docs/federation.md): intra-region stays the
existing GlobalManager psum-native reconcile untouched; this manager is
the *inter*-region half.  The intra-region owner of a GLOBAL key — the
one node in its region that sees every hit for it (non-owners forward
theirs through the hits loop) — feeds each owner-side state change here
(:meth:`queue`); deltas accumulate per remote region and per key, and a
supervised loop flushes them every ``GUBER_FEDERATION_INTERVAL`` as
:class:`~gubernator_tpu.federation.envelope.FederationEnvelope` frames
to the owning peer of each key in the remote region's own ring
(RegionPicker — the sender computes remote ownership locally because
every region runs the same hash).

No client request ever waits on a cross-region RPC: requests are
answered from region-local state (the PR 3 degraded-answer discipline
absorbs WAN latency/partitions), so region isolation degrades to
bounded local over-admission — at most ``federation_interval ×
local_rate`` hits drift per region — and never to an outage.

Delivery rides the PR 3 machinery: the target peer's circuit breaker
(one owning peer per region per flush, so the per-region breaker IS
that peer's breaker), decorrelated-jitter backoff between retries, and
a merge-on-requeue pending buffer bounded by ``GUBER_REDELIVERY_LIMIT``
distinct keys.  Exactly-once comes from the channel discipline: at most
one envelope is in flight per (this node → target peer) channel, a
failed send retries the *same* envelope (same seq, same records), and
new deltas merge into pending for the next seq — paired with the
receiver's :class:`~gubernator_tpu.federation.envelope.ReceiveLedger`
duplicate gate, a partition heals by replaying the buffer with zero
hit loss and zero double-counts.
"""

from __future__ import annotations

import asyncio
import logging
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from gubernator_tpu.federation.envelope import (
    FederationAck,
    FederationEnvelope,
    FederationRecord,
    ReceiveLedger,
    merge_records,
)
from gubernator_tpu.resilience import (
    DecorrelatedJitterBackoff,
    spawn_supervised,
)
from gubernator_tpu.types import Behavior, RateLimitRequest, set_behavior

log = logging.getLogger("gubernator.federation")

# Metadata key stamped on federation-applied requests: the receive path
# submits them through the normal owner handler (which re-broadcasts
# intra-region), and GlobalManager's federation feed skips requests
# carrying it — without the tag, region A's hits applied in B would
# federate back to A (and to every third region the origin already
# reached directly), double-counting on each lap.
FED_ORIGIN_KEY = "fed-origin"


@dataclass
class _Channel:
    """One (this node → remote owning peer) envelope stream."""

    peer: object
    region: str
    seq: int = 0                    # last assigned sequence
    inflight: Optional[FederationEnvelope] = None
    inflight_since: float = 0.0
    failing: bool = False           # last send attempt failed
    next_try: float = 0.0
    backoff: DecorrelatedJitterBackoff = field(
        default=None)  # type: ignore[assignment]


class FederationManager:
    """Owns the inter-region exchange for one V1Instance."""

    def __init__(self, instance, metrics=None, clock=time.monotonic,
                 sleep=asyncio.sleep):
        self.instance = instance
        self.metrics = metrics
        self._clock = clock
        self._sleep = sleep
        conf = instance.conf
        self.home = conf.data_center
        self.interval = conf.federation_interval
        self.batch_limit = conf.federation_batch_limit
        self.timeout = conf.federation_timeout
        self.resilience = conf.resilience
        # region → key → accumulated delta (merge-on-requeue buffer).
        self._pending: Dict[str, Dict[str, FederationRecord]] = {}
        # region → enqueue time of the oldest un-flushed delta.
        self._pending_since: Dict[str, float] = {}
        # target grpc address → channel.
        self._channels: Dict[str, _Channel] = {}
        self.ledger = ReceiveLedger()
        # One apply at a time per origin channel: a redelivery racing a
        # still-running slow apply of the same envelope must wait and
        # then read the marked ledger (duplicate), not start a second
        # apply off the not-yet-marked one.
        self._apply_locks: Dict[str, asyncio.Lock] = {}
        self._running = True
        self._task = spawn_supervised(
            self._flush_loop, name="federation-flush",
            should_restart=lambda: self._running,
            metrics=metrics, loop_label="federation_flush",
        )

    @property
    def origin(self) -> str:
        """This node's channel identity: its advertise address."""
        return self.instance.conf.advertise_address

    # ------------------------------------------------------------------
    # Sender side
    # ------------------------------------------------------------------
    def queue(self, req: RateLimitRequest) -> None:
        """Record one owner-side GLOBAL state change for every remote
        region.  Called from GlobalManager.queue_update — the one place
        every hit in this region funnels through exactly once."""
        if req.hits == 0:
            return
        if req.metadata.get(FED_ORIGIN_KEY):
            return  # applied FROM a peer region; never re-federate
        try:
            regions = self.instance.region_picker.regions()
        except Exception:
            return
        limit = self.resilience.redelivery_limit
        now = self._clock()
        dropped_total = 0
        for region in regions:
            if not region or region == self.home:
                continue
            pending = self._pending.setdefault(region, {})
            if not pending:
                self._pending_since[region] = now
            rec = FederationRecord(
                name=req.name, unique_key=req.unique_key, hits=req.hits,
                limit=req.limit, duration=req.duration,
                algorithm=int(req.algorithm), behavior=int(req.behavior),
                burst=req.burst, created_at=req.created_at or 0,
            )
            _, dropped = merge_records(pending, [rec], limit)
            dropped_total += dropped
        if dropped_total:
            # Never silent: a full pending buffer under a long partition
            # means this key's drift will NOT heal on rejoin.
            log.warning(
                "federation pending buffer full (GUBER_REDELIVERY_LIMIT"
                "=%d keys): dropped %d new-key records", limit,
                dropped_total,
            )

    async def _flush_loop(self) -> None:
        while self._running:
            await self._sleep(self.interval)
            if not self._running:
                return
            await self._flush_once()
            self._update_staleness()

    async def _flush_once(self, force_retry: bool = False) -> None:
        """Compact pending deltas into envelopes on idle channels, then
        send every due envelope concurrently."""
        for region, pending in self._pending.items():
            if not pending:
                continue
            self._compact(region, pending)
            if not pending:
                self._pending_since.pop(region, None)
        now = self._clock()
        due = [
            ch for ch in self._channels.values()
            if ch.inflight is not None and (force_retry or now >= ch.next_try)
        ]
        if due:
            await asyncio.gather(*(self._send(ch) for ch in due))

    def _compact(self, region: str,
                 pending: Dict[str, FederationRecord]) -> None:
        """Route pending keys to their remote-region owners and build the
        next envelope on every channel without one in flight.  Keys whose
        channel is busy (or whose region has no reachable ring yet) stay
        pending — merge-on-requeue keeps accumulating their hits."""
        groups: Dict[str, tuple] = {}
        for key in pending:
            try:
                peer = self.instance.region_picker.get(key, region)
            except Exception:
                return  # no ring for the region yet; keep everything
            addr = peer.info.grpc_address
            if addr in groups:
                groups[addr][1].append(key)
            else:
                groups[addr] = (peer, [key])
        for addr, (peer, keys) in groups.items():
            ch = self._channels.get(addr)
            if ch is None:
                rc = self.resilience
                ch = self._channels[addr] = _Channel(
                    peer=peer, region=region,
                    backoff=DecorrelatedJitterBackoff(
                        rc.forward_backoff_base, rc.forward_backoff_cap),
                )
            ch.peer = peer  # ring churn may swap the handle
            if ch.inflight is not None:
                continue
            take = keys[: self.batch_limit]
            ch.seq += 1
            ch.inflight = FederationEnvelope(
                origin=self.origin, region=self.home, seq=ch.seq,
                records=[pending.pop(k) for k in take],
            )
            ch.inflight_since = self._clock()

    async def _send(self, ch: _Channel) -> None:
        env = ch.inflight
        try:
            ack = await ch.peer.federation_sync(env, timeout=self.timeout)
        except asyncio.CancelledError:
            raise
        except Exception:
            # BreakerOpenError / AioRpcError / malformed-frame — all the
            # same to the channel: the envelope stays in flight and
            # retries with the SAME seq after a jittered backoff.  The
            # receiver's ledger makes the retry safe even when only the
            # ack was lost.
            ch.failing = True
            ch.next_try = self._clock() + ch.backoff.next()
            if self.metrics is not None:
                self.metrics.federation_redeliveries.inc()
            return
        if ack.seq >= env.seq:
            ch.inflight = None
            ch.inflight_since = 0.0
            ch.failing = False
            ch.next_try = 0.0
            ch.backoff.reset()
            if self.metrics is not None:
                self.metrics.federation_envelopes.labels(result="sent").inc()

    def _update_staleness(self) -> None:
        """Export the worst-case cross-region drift age: the oldest delta
        not yet acked by its target region (pending or in flight)."""
        if self.metrics is None:
            return
        now = self._clock()
        oldest = None
        for ts in self._pending_since.values():
            oldest = ts if oldest is None else min(oldest, ts)
        for ch in self._channels.values():
            if ch.inflight is not None and ch.inflight_since:
                ts = ch.inflight_since
                oldest = ts if oldest is None else min(oldest, ts)
        self.metrics.federation_staleness.set(
            max(0.0, now - oldest) if oldest is not None else 0.0)

    def is_degraded(self) -> bool:
        """True while any remote region is unreachable (its channel's
        breaker is open or its last send failed): MULTI_REGION answers
        served now may over-admit up to the staleness budget."""
        for ch in self._channels.values():
            if ch.failing:
                return True
            breaker = getattr(ch.peer, "breaker", None)
            if breaker is not None and breaker.is_open():
                return True
        return False

    # ------------------------------------------------------------------
    # Receiver side
    # ------------------------------------------------------------------
    async def receive(self, env: FederationEnvelope) -> FederationAck:
        """Apply one envelope from a peer region and ack it.

        Duplicates (a redelivery whose ack was lost) are acked without
        re-applying; a failed apply leaves the ledger unmarked so the
        sender's retry of the same seq lands the records.

        Cancellation-shielded: the sender's RPC deadline cancels the
        transport handler, but an apply that already committed hits to
        the engine MUST still mark the ledger — cancelling between the
        two would turn every slow apply (e.g. a first-use JIT compile)
        into a double-count when the same envelope is redelivered."""
        return await asyncio.shield(self._receive_inner(env))

    async def _receive_inner(self, env: FederationEnvelope) -> FederationAck:
        lock = self._apply_locks.setdefault(env.origin, asyncio.Lock())
        async with lock:
            return await self._apply_locked(env)

    async def _apply_locked(self, env: FederationEnvelope) -> FederationAck:
        if self.ledger.seen(env):
            if self.metrics is not None:
                self.metrics.federation_envelopes.labels(
                    result="duplicate").inc()
            return FederationAck(origin=env.origin, seq=env.seq, applied=0)
        reqs: List[RateLimitRequest] = []
        for rec in env.records:
            reqs.append(RateLimitRequest(
                name=rec.name,
                unique_key=rec.unique_key,
                hits=rec.hits,
                limit=rec.limit,
                duration=rec.duration,
                algorithm=rec.algorithm,
                behavior=set_behavior(rec.behavior, Behavior.GLOBAL, True),
                burst=rec.burst,
                metadata={FED_ORIGIN_KEY: env.region},
                created_at=rec.created_at or None,
            ))
        if reqs:
            # The owner-relay handler: forces DRAIN_OVER_LIMIT on GLOBAL
            # hits, applies to the local engine, and queues the intra-
            # region broadcast — the remote region's hits reach every
            # local peer through the existing machinery.
            await self.instance.get_peer_rate_limits(reqs)
        self.ledger.mark(env)
        if self.metrics is not None:
            self.metrics.federation_envelopes.labels(result="applied").inc()
        return FederationAck(
            origin=env.origin, seq=env.seq, applied=len(reqs))

    # ------------------------------------------------------------------
    # Introspection / shutdown
    # ------------------------------------------------------------------
    def pending_keys(self) -> int:
        return sum(len(p) for p in self._pending.values())

    def inflight_envelopes(self) -> int:
        return sum(
            1 for ch in self._channels.values() if ch.inflight is not None)

    async def _final_flush(self) -> None:
        """Bounded drain rounds through the normal flush path, retrying
        immediately (no backoff waits — the caller's deadline is the
        budget)."""
        for _ in range(4):
            if not (self.pending_keys() or self.inflight_envelopes()):
                return
            await self._flush_once(force_retry=True)

    async def close(self, drain_timeout: float = 0.0) -> None:
        """Stop the flush loop, then (graceful-drain path) push what's
        still buffered under a bounded deadline."""
        self._running = False
        self._task.cancel()
        await asyncio.gather(self._task, return_exceptions=True)
        if drain_timeout > 0 and (
                self.pending_keys() or self.inflight_envelopes()):
            try:
                await asyncio.wait_for(self._final_flush(), drain_timeout)
            except asyncio.TimeoutError:
                log.warning(
                    "federation drain deadline (%.1fs) expired with %d "
                    "pending keys / %d in-flight envelopes",
                    drain_timeout, self.pending_keys(),
                    self.inflight_envelopes(),
                )
            except Exception:
                log.exception("federation drain flush failed")
        self._update_staleness()
