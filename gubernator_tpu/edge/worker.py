"""Edge worker child process: fastwire decode into shared-memory slabs.

The worker owns the producer side of one segment's request ring and the
consumer side of its response ring (:mod:`gubernator_tpu.edge.shmring`).
It NEVER imports jax — the import chain is numpy + protobuf + the native
wire codec, so a child spawns in well under a second and its crash
surface is disjoint from the device runtime.

Two modes share the decode/publish/ack core:

* ``socket`` — the daemon-facing ingest surface: a Unix-domain listener
  speaking length-prefixed fastwire frames (4-byte LE length +
  serialized ``GetRateLimitsReq``; responses mirror the framing with
  ``GetRateLimitsResp`` bytes).  Many clients per worker; responses are
  routed back by publish order.
* ``drive`` — a self-generating loopback load source for bench.py's
  ``serve_multiproc`` rung and the chaos tests: pre-encodes frames once,
  then decode→publish→ack as fast as the rings allow, accounting every
  window through the shm counter block so the owner can check the
  exact-work invariants (parity / double-serve / dropped-ack) without
  trusting the worker's stdout.

Backpressure is per-producer by construction: a worker blocks on its own
ring (slab exhaustion) and its own response depth, never on another
worker's traffic.
"""

from __future__ import annotations

import os
import selectors
import signal
import socket
import struct
import time
from typing import Dict, Optional

import numpy as np

from gubernator_tpu.edge import shmring
from gubernator_tpu.edge.shmring import (
    CTRL_REQ_AT,
    CTRL_RESP_AT,
    C_BACKPRESSURE_WAITS,
    C_DECODE_BATCHES,
    C_DECODE_SECONDS,
    C_DOUBLE_SERVED,
    C_DRIVE_DONE,
    C_ERR_ROWS,
    C_HITS_ACKED,
    C_HITS_PUBLISHED,
    C_ROWS_ACKED,
    C_ROWS_DECODED,
    C_ROWS_PUBLISHED,
    C_SHED_LOCAL,
    C_WIN_ACKED,
    C_WIN_PUBLISHED,
    C_WIRE_BYTES_IN,
    C_WIRE_BYTES_OUT,
    CTRL_GENERATION,
    CTRL_GO,
    CTRL_READY,
    CTRL_STOP,
    CTRL_WORKER_PID,
    RESP_OK,
)
from gubernator_tpu.ops.reqcols import (
    CREATED_UNSET,
    IngestOverloadError,
    ReqColumns,
    key_blob_from_parts,
)
from gubernator_tpu.transport import fastwire

_LEN = struct.Struct("<I")

# The worker's local shed message mirrors the PR 9 admission-plane
# convention (retriable, names the stage) without importing the serving
# stack into the child.
SHED_EDGE_MSG = "request shed: edge worker slab ring exhausted (retriable)"
OVERSIZE_MSG = "batch exceeds the edge plane's max_batch; use the gRPC path"


class _WorkerSlabLease:
    """ArenaLease stand-in for ``fastwire.parse_req`` decoding into a
    ring slab.  Claiming never touches shm state (the slab stays FREE
    until publish), so release — parse-failure cleanup — is a no-op and
    the cursor simply reuses the slab."""

    __slots__ = ("ints", "flags", "blob", "index")

    def __init__(self, ints, flags, blob, index):
        self.ints = ints
        self.flags = flags
        self.blob = blob
        self.index = index

    def release(self) -> None:
        pass


class _WorkerArena:
    """Duck-typed ColumnArena over the request ring: ``parse_req`` leases
    the slab at the write cursor and decodes straight into shared memory.
    A busy ring raises IngestOverloadError through the normal
    fits/try_fallback protocol (the per-producer backpressure bound);
    oversized batches plain-allocate so the caller can reject them
    without publishing."""

    def __init__(self, seg: shmring.EdgeSegment, ring: shmring.RequestRing):
        self.seg = seg
        self.ring = ring
        self.max_batch = seg.max_batch
        self.blob_cap = seg.blob_cap
        self.last: Optional[_WorkerSlabLease] = None

    def lease(self, n: int, blob_cap: int) -> Optional[_WorkerSlabLease]:
        if n > self.max_batch or blob_cap > self.blob_cap:
            return None
        idx = self.ring.try_claim()
        if idx is None:
            return None
        ints = self.seg.req_ints[idx]
        ints[:, : n + 1] = 0
        flags = self.seg.req_flags[idx]
        flags[:n] = 0
        self.last = _WorkerSlabLease(ints, flags, self.seg.req_blob[idx], idx)
        return self.last

    def fits(self, n: int, blob_cap: int) -> bool:
        return n <= self.max_batch and blob_cap <= self.blob_cap

    def try_fallback(self) -> bool:
        return False  # busy ring = backpressure, never heap growth


class EdgeWorker:
    """One edge worker's event loop (child-process side)."""

    def __init__(self, seg: shmring.EdgeSegment, worker_id: int):
        self.seg = seg
        self.worker_id = worker_id
        self.req = shmring.RequestRing(seg)
        self.resp = shmring.ResponseRing(seg)
        # Respawn handoff: a fresh worker must publish where the owner
        # will read next, and read responses where the owner will write
        # next (the owner's cursors survive the crash; ours don't).
        self.req.write_at = int(seg.ctrl[CTRL_REQ_AT]) % seg.slabs
        self.resp.read_at = int(seg.ctrl[CTRL_RESP_AT]) % seg.depth
        self.arena = _WorkerArena(seg, self.req)
        self.counters = seg.counters
        self.generation = int(seg.ctrl[CTRL_GENERATION])
        self.next_seq = 1
        # seq → (hits copy, route) — hits survive slab reuse for the ack
        # accounting; route is the client connection (socket mode) or
        # None (drive mode).
        self.pending: Dict[int, tuple] = {}
        self.on_reply = None  # socket mode's routing callback
        self.stop = False
        seg.ctrl[CTRL_WORKER_PID] = os.getpid()
        signal.signal(signal.SIGTERM, self._on_term)

    def _on_term(self, *_):
        self.stop = True

    def detach(self) -> None:
        """Drop every shm view (rings, arena, counters) so the segment's
        mmap can close without a BufferError at exit."""
        self.req.detach()
        self.resp.detach()
        self.arena.last = None
        self.arena.seg = None
        self.arena.ring = None
        self.counters = None

    def should_stop(self) -> bool:
        return self.stop or int(self.seg.ctrl[CTRL_STOP]) != 0

    # -- decode/publish core -------------------------------------------
    def decode_publish(self, data: bytes, deadline_ns: int, route=None):
        """Parse one frame into the slab at the write cursor and publish
        it.  Returns (seq, None) on publish, (None, reply) when the
        frame must be answered locally (per-item errors, special
        routing, oversize), and raises IngestOverloadError on a full
        ring."""
        t0 = time.monotonic_ns()
        out = fastwire.parse_req(data, self.arena)
        if out is None:
            raise ValueError("malformed or non-decodable request frame")
        cols, errors, special = out
        n = len(cols)
        if cols.lease is None:
            # Oversized for the slab: never published, answered locally.
            cols.release()
            return None, _error_frame(n, OVERSIZE_MSG)
        if errors or special:
            # Per-item validation errors and GLOBAL/metadata routing need
            # the object path; the edge plane serves plain batches only
            # (docs/edge.md) — answer locally, slab stays unpublished.
            msg = errors or {i: OVERSIZE_MSG for i in range(n)}
            if special and not errors:
                msg = {
                    i: "edge plane serves plain batches only; "
                    "use the gRPC path for GLOBAL/metadata"
                    for i in range(n)
                }
            return None, _error_frame(n, None, per_item=msg)
        dt = time.monotonic_ns() - t0
        idx = self.arena.last.index
        seq = self.next_seq
        self.next_seq += 1
        hits = np.array(cols.hits)  # slab views die at release; copy
        self.pending[seq] = (hits, route)
        c = self.counters
        c[C_DECODE_SECONDS] += dt * 1e-9
        c[C_DECODE_BATCHES] += 1
        c[C_ROWS_DECODED] += n
        c[C_WIRE_BYTES_IN] += len(data) + _LEN.size
        c[C_WIN_PUBLISHED] += 1
        c[C_ROWS_PUBLISHED] += n
        c[C_HITS_PUBLISHED] += int(hits.sum())
        self.req.publish(
            idx, seq, n, int(cols.key_offsets[n]), deadline_ns, dt,
            self.generation,
        )
        return seq, None

    # -- ack side -------------------------------------------------------
    def consume_responses(self, on_reply=None) -> int:
        """Drain the response ring; per window, account and (socket
        mode) encode + route the reply.  Returns windows consumed."""
        if on_reply is None:
            on_reply = self.on_reply
        got = 0
        c = self.counters
        while True:
            r = self.resp.poll()
            if r is None:
                return got
            seqno, rows, mat, errc, errb, gen, status, idx = r
            if gen != self.generation:
                self.resp.free_slot(idx)
                continue
            entry = self.pending.pop(seqno, None)
            if entry is None:
                # The exact-work oracle: a response for a window already
                # answered (or never published) is a double-serve.
                c[C_DOUBLE_SERVED] += 1
                self.resp.free_slot(idx)
                continue
            hits, route = entry
            errors = shmring.decode_errors(errb, errc) if errc else {}
            c[C_WIN_ACKED] += 1
            c[C_ROWS_ACKED] += rows
            c[C_ERR_ROWS] += len(errors)
            if status == RESP_OK:
                ok = mat[0] == 0  # UNDER_LIMIT consumes; OVER_LIMIT doesn't
                if errors:
                    ok = ok.copy()
                    for i in errors:
                        ok[i] = False
                c[C_HITS_ACKED] += int(hits[: len(ok)][ok].sum())
            wire = _encode_reply(mat, errors)
            c[C_WIRE_BYTES_OUT] += len(wire) + _LEN.size
            self.resp.free_slot(idx)
            if on_reply is not None:
                on_reply(route, wire)
            got += 1

    # -- drive mode -----------------------------------------------------
    def run_drive(self, spec: dict) -> None:
        """Self-generating loopback load (see module docstring).

        spec: batch, windows (0 = until stop flag), keys, key_prefix,
        hits, limit, duration, frames, timeout_s.
        """
        batch = int(spec.get("batch", 512))
        target = int(spec.get("windows", 0))
        n_keys = int(spec.get("keys", 4096))
        prefix = spec.get("key_prefix", f"w{self.worker_id}_")
        hits = int(spec.get("hits", 1))
        limit = int(spec.get("limit", 1 << 40))
        duration = int(spec.get("duration", 3_600_000))
        n_frames = int(spec.get("frames", 16))
        timeout_ns = int(float(spec.get("timeout_s", 30.0)) * 1e9)
        rng = np.random.default_rng(1000 + self.worker_id)
        frames = []
        for _ in range(n_frames):
            ids = rng.integers(0, n_keys, batch)
            blob, off = key_blob_from_parts(
                ["edge"] * batch, [f"{prefix}{int(k)}" for k in ids]
            )
            z = np.zeros(batch, np.int64)
            cols = ReqColumns(
                blob, off, np.full(batch, hits, np.int64),
                np.full(batch, limit, np.int64),
                np.full(batch, duration, np.int64),
                z, z, np.full(batch, CREATED_UNSET, np.int64), z,
                name_len=np.full(batch, 4, np.int64),
            )
            data = fastwire.encode_req(cols)
            if data is None:
                raise RuntimeError("edge drive mode needs the native codec")
            frames.append(data)
        # Start barrier: spawn/import time must not pollute the owner's
        # throughput clock.
        self.seg.ctrl[CTRL_READY] = 1
        while not self.should_stop() and int(self.seg.ctrl[CTRL_GO]) == 0:
            time.sleep(0.0002)
        fi = 0
        depth = self.seg.depth
        c = self.counters
        published = 0
        while not self.should_stop() and (target == 0 or published < target):
            self.consume_responses()
            if len(self.pending) >= depth:
                c[C_BACKPRESSURE_WAITS] += 1
                time.sleep(0.00005)
                continue
            try:
                seq, _ = self.decode_publish(
                    frames[fi], time.monotonic_ns() + timeout_ns
                )
            except IngestOverloadError:
                c[C_BACKPRESSURE_WAITS] += 1
                time.sleep(0.00005)
                continue
            fi = (fi + 1) % n_frames
            published += 1
        # Final drain: every published window must come back (the
        # dropped-ack invariant) unless the owner is tearing us down.
        quiet_until = time.monotonic() + 5.0
        while self.pending and time.monotonic() < quiet_until:
            if self.consume_responses():
                quiet_until = time.monotonic() + 5.0
            if self.should_stop():
                break
            time.sleep(0.0002)
        c[C_DRIVE_DONE] = 1
        # Linger until told to stop so the counter block stays paired
        # with a live process for the owner's final sync.
        while not self.should_stop():
            time.sleep(0.002)

    # -- socket mode ----------------------------------------------------
    def run_socket(self, path: str, timeout_s: float = 30.0) -> None:
        """Unix-socket ingest: length-prefixed fastwire frames in,
        length-prefixed response frames out, responses in publish order
        per window."""
        sel = selectors.DefaultSelector()
        srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        try:
            os.unlink(path)
        except FileNotFoundError:
            pass
        srv.bind(path)
        srv.listen(64)
        srv.setblocking(False)
        sel.register(srv, selectors.EVENT_READ, None)
        conns: Dict[int, "_Conn"] = {}
        timeout_ns = int(timeout_s * 1e9)
        self.seg.ctrl[CTRL_READY] = 1

        def reply(route, wire):
            conn = conns.get(route)
            if conn is not None:
                conn.queue(_LEN.pack(len(wire)) + wire)

        self.on_reply = reply
        try:
            while not self.should_stop():
                self.consume_responses()
                for key, events in sel.select(timeout=0.0005):
                    if key.data is None:
                        try:
                            s, _ = srv.accept()
                        except OSError:
                            continue
                        s.setblocking(False)
                        conn = _Conn(s)
                        conns[conn.id] = conn
                        sel.register(s, selectors.EVENT_READ, conn)
                        continue
                    conn = key.data
                    if events & selectors.EVENT_READ:
                        if not conn.read():
                            self._drop_conn(sel, conns, conn)
                            continue
                        for frame in conn.frames():
                            self._serve_frame(conn, frame, timeout_ns)
                    if events & selectors.EVENT_WRITE:
                        conn.flush()
                for conn in list(conns.values()):
                    if conn.out and not conn.flush():
                        self._drop_conn(sel, conns, conn)
        finally:
            for conn in list(conns.values()):
                self._drop_conn(sel, conns, conn)
            sel.unregister(srv)
            srv.close()
            try:
                os.unlink(path)
            except FileNotFoundError:
                pass

    def _serve_frame(self, conn: "_Conn", frame: bytes,
                     timeout_ns: int) -> None:
        deadline = time.monotonic_ns() + timeout_ns
        # Bounded claim wait: the ring is this producer's own admission
        # bound, so a short spin then a retriable shed (the PR 9
        # convention) keeps one hot client from queueing unboundedly.
        for _ in range(40):
            if len(self.pending) >= self.seg.depth:
                # Outstanding bound: the response ring must always have a
                # free slot for a live worker's windows.
                self.counters[C_BACKPRESSURE_WAITS] += 1
                self.consume_responses()
                time.sleep(0.0002)
                continue
            try:
                seq, local = self.decode_publish(frame, deadline, conn.id)
            except IngestOverloadError:
                self.counters[C_BACKPRESSURE_WAITS] += 1
                self.consume_responses()
                time.sleep(0.0002)
                continue
            except ValueError:
                conn.queue(_LEN.pack(0))  # unparseable: empty response
                return
            if local is not None:
                conn.queue(_LEN.pack(len(local)) + local)
            return
        self.counters[C_SHED_LOCAL] += 1
        n = _frame_rows(frame)
        shed = _error_frame(n, SHED_EDGE_MSG)
        conn.queue(_LEN.pack(len(shed)) + shed)

    def _drop_conn(self, sel, conns, conn) -> None:
        conns.pop(conn.id, None)
        try:
            sel.unregister(conn.sock)
        except Exception:
            pass
        conn.sock.close()
        # Windows already published for this conn still complete; their
        # replies drop at routing (the conn is gone) but the accounting
        # in consume_responses still runs — never silently lost.


class _Conn:
    """One client connection's read/write buffers."""

    _next_id = 1

    def __init__(self, sock):
        self.sock = sock
        self.id = _Conn._next_id
        _Conn._next_id += 1
        self.buf = b""
        self.out = b""

    def read(self) -> bool:
        try:
            data = self.sock.recv(1 << 16)
        except BlockingIOError:
            return True
        except OSError:
            return False
        if not data:
            return False
        self.buf += data
        return True

    def frames(self):
        while len(self.buf) >= _LEN.size:
            (ln,) = _LEN.unpack_from(self.buf)
            if len(self.buf) < _LEN.size + ln:
                return
            frame = self.buf[_LEN.size : _LEN.size + ln]
            self.buf = self.buf[_LEN.size + ln :]
            yield frame

    def queue(self, data: bytes) -> None:
        self.out += data
        self.flush()

    def flush(self) -> bool:
        if not self.out:
            return True
        try:
            sent = self.sock.send(self.out)
            self.out = self.out[sent:]
            return True
        except BlockingIOError:
            return True
        except OSError:
            return False


class EdgeClient:
    """Minimal blocking client for the worker's Unix-socket framing
    (tests and operator smoke checks; production streaming clients speak
    the same four-byte little-endian length prefix)."""

    def __init__(self, path: str, timeout: float = 10.0):
        self.sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self.sock.settimeout(timeout)
        self.sock.connect(path)

    def call(self, req_bytes: bytes) -> bytes:
        self.sock.sendall(_LEN.pack(len(req_bytes)) + req_bytes)
        return self.recv()

    def send(self, req_bytes: bytes) -> None:
        self.sock.sendall(_LEN.pack(len(req_bytes)) + req_bytes)

    def recv(self) -> bytes:
        hdr = self._read(_LEN.size)
        (ln,) = _LEN.unpack(hdr)
        return self._read(ln) if ln else b""

    def _read(self, n: int) -> bytes:
        out = b""
        while len(out) < n:
            chunk = self.sock.recv(n - len(out))
            if not chunk:
                raise ConnectionError("edge socket closed mid-frame")
            out += chunk
        return out

    def close(self) -> None:
        self.sock.close()


def _frame_rows(frame: bytes) -> int:
    lib = fastwire.load()
    if lib is None:
        return 0
    n = lib.guber_wire_count(frame, len(frame))
    return max(0, int(n))


def _encode_reply(mat: np.ndarray, errors: dict) -> bytes:
    """Response matrix (+ per-item error strings) → wire bytes.  The
    no-error path is the native encoder (byte-identical to protobuf);
    error items take the pb object path, mirroring the daemon's
    fallback."""
    if not errors:
        return fastwire.encode_resp(np.ascontiguousarray(mat))
    from gubernator_tpu import pb

    status, limit, remaining, reset = (
        mat[r].tolist() for r in range(4)
    )
    return pb.GetRateLimitsResp(
        responses=[
            pb.RateLimitResp(error=errors[i])
            if i in errors
            else pb.RateLimitResp(
                status=status[i], limit=limit[i],
                remaining=remaining[i], reset_time=reset[i],
            )
            for i in range(mat.shape[1])
        ]
    ).SerializeToString()


def _error_frame(n: int, msg: Optional[str], per_item: dict = None) -> bytes:
    """A whole-batch (or per-item) error response, pb-encoded."""
    from gubernator_tpu import pb

    errs = per_item if per_item is not None else {i: msg for i in range(n)}
    return pb.GetRateLimitsResp(
        responses=[
            pb.RateLimitResp(error=errs.get(i, msg or "")) for i in range(n)
        ]
    ).SerializeToString()


def worker_main(seg_name: str, worker_id: int, max_batch: int, slabs: int,
                depth: int, mode: str, options: dict) -> None:
    """Spawn entry point (the supervisor's process target).  Attaches
    the segment untracked, then runs the mode loop until the stop flag
    or SIGTERM."""
    if fastwire.load() is None:
        raise RuntimeError(
            "edge worker needs the native wire codec (libguber_wire.so)"
        )
    seg = shmring.attach_segment(seg_name, max_batch, slabs, depth)
    w = None
    try:
        w = EdgeWorker(seg, worker_id)
        if mode == "drive":
            w.run_drive(options.get("drive", {}))
        elif mode == "socket":
            w.run_socket(
                options["socket_path"],
                timeout_s=float(options.get("timeout_s", 30.0)),
            )
        else:
            raise ValueError(f"unknown edge worker mode {mode!r}")
    finally:
        if w is not None:
            w.detach()
        seg.close()
