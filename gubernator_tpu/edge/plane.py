"""EdgePlane: the device-owner side of the shared-memory ingest plane.

Owns the worker processes, their shm segments, and two owner threads:

* the **drain** thread walks every worker's request ring, rebuilds each
  published slab as a zero-copy :class:`ReqColumns` view (key blob
  included — the native slotmap resolves it in place) and submits it to
  the tick loop; the attached :class:`ShmSlabLease` returns the slab to
  the worker when ``TickLoop._flush`` releases after pack, exactly the
  in-process arena timing.  Worker-stamped decode time folds into the
  flight recorder here, so ``/debug/pipeline`` and
  ``stage_duration{stage="decode"}`` show where decode really happened.
* the **supervisor** thread respawns dead workers: unconsumed published
  slabs are shed with the PR 9 retriable-shutdown accounting (never
  silently dropped), the segment generation is bumped so in-flight
  responses from the old life are discarded on arrival, and the ring
  cursors are handed to the fresh process through the control block.

Response fan-out rides the tick loop's future callbacks (resolver and
shed threads both complete futures; the per-worker lock serializes the
slot writes).  Exactly-once holds for ACKED windows: a window either
reaches its worker's response ring once, or is counted shed/dropped.
"""

from __future__ import annotations

import logging
import os
import secrets
import threading
import time
from dataclasses import dataclass, field
from functools import partial
from typing import Dict, List, Optional

import numpy as np

from gubernator_tpu.admission import CLASS_CLIENT, SHED_SHUTDOWN_MSG
from gubernator_tpu.edge import shmring
from gubernator_tpu.edge.shmring import (
    CTRL_GENERATION,
    CTRL_GO,
    CTRL_READY,
    CTRL_REQ_AT,
    CTRL_RESP_AT,
    CTRL_STOP,
    C_DRIVE_DONE,
    N_COUNTERS,
    PUBLISHED,
    RESP_OK,
    RS_STATE,
    EdgeSegment,
    ShmSlabLease,
)
from gubernator_tpu.ops.reqcols import ReqColumns
from gubernator_tpu.utils import flightrec
from gubernator_tpu.utils.hotpath import hot_path
from gubernator_tpu.utils import sanitize

log = logging.getLogger("gubernator.edge")


@dataclass
class EdgeConfig:
    """Shape of the edge plane (GUBER_EDGE_* knobs; docs/edge.md)."""

    workers: int = 0
    slabs: int = 8            # request slabs per worker (GUBER_EDGE_SHM_SLABS)
    ring_depth: int = 16      # response slots per worker (GUBER_EDGE_RING_DEPTH)
    max_batch: int = 1000
    mode: str = "socket"      # "socket" (daemon ingest) | "drive" (bench/chaos)
    socket_dir: Optional[str] = None
    drive: dict = field(default_factory=dict)
    timeout_s: float = 30.0

    def __post_init__(self):
        # A live worker bounds its outstanding windows to the response
        # depth; depth >= slabs keeps that bound from throttling below
        # the slab count.
        self.ring_depth = max(int(self.ring_depth), int(self.slabs))


class _WorkerHandle:
    """Owner-side state for one worker process."""

    def __init__(self, wid: int, seg: EdgeSegment):
        self.id = wid
        self.seg = seg
        self.ring = shmring.RequestRing(seg)
        self.resp = shmring.ResponseRing(seg)
        self.generation = 1
        # Reentrant: a tick-loop future can complete inline during
        # submit (shutdown shed), firing _on_done on the drain thread
        # while _drain_once still holds the lock.
        self.lock = sanitize.rlock("_WorkerHandle.lock")
        self.proc = None
        self.restarts = 0
        self.shed_rows = 0
        self.dropped_responses = 0
        self.in_flight = 0
        self.synced = np.zeros(N_COUNTERS, np.float64)
        self.socket_path: Optional[str] = None


class EdgePlane:
    """N worker processes + the owner drain/supervisor (module docstring)."""

    def __init__(self, tick_loop, config: EdgeConfig, metrics=None):
        from gubernator_tpu.transport import fastwire

        if config.workers <= 0:
            raise ValueError("EdgePlane needs workers >= 1; 0 disables the "
                             "plane (the caller must not construct it)")
        if fastwire.load() is None:
            raise RuntimeError(
                "edge plane needs the native wire codec (libguber_wire.so)"
            )
        self.tick_loop = tick_loop
        self.config = config
        self.metrics = metrics
        self.workers: List[_WorkerHandle] = []
        self._threads: List[threading.Thread] = []
        self._closing = False
        self._started = False
        self._token = secrets.token_hex(4)

    # -- lifecycle ------------------------------------------------------
    def start(self) -> None:
        cfg = self.config
        for wid in range(cfg.workers):
            seg = EdgeSegment(
                f"guber_edge_{os.getpid()}_{wid}_{self._token}",
                cfg.max_batch, cfg.slabs, cfg.ring_depth, create=True,
            )
            w = _WorkerHandle(wid, seg)
            if cfg.mode == "socket":
                w.socket_path = os.path.join(
                    cfg.socket_dir or "/tmp",
                    f"guber-edge-{os.getpid()}-{wid}-{self._token}.sock",
                )
            self.workers.append(w)
            self._spawn(w)
        self._started = True
        for name, target in (("edge_drain", self._drain_loop),
                             ("edge_supervisor", self._supervise_loop)):
            t = threading.Thread(target=target, name=name, daemon=True)
            t.start()
            self._threads.append(t)
        log.info(
            "edge plane up: %d workers, %d slabs x %d rows, mode=%s",
            cfg.workers, cfg.slabs, cfg.max_batch, cfg.mode,
        )

    def _spawn(self, w: _WorkerHandle) -> None:
        import multiprocessing as mp

        cfg = self.config
        options = {"timeout_s": cfg.timeout_s}
        if cfg.mode == "socket":
            options["socket_path"] = w.socket_path
        else:
            drive = dict(cfg.drive)
            drive.setdefault("key_prefix", f"w{w.id}_")
            options["drive"] = drive
        ctx = mp.get_context("spawn")  # the owner holds jax + threads: no fork
        from gubernator_tpu.edge.worker import worker_main

        w.proc = ctx.Process(
            target=worker_main,
            args=(w.seg.shm.name, w.id, cfg.max_batch, cfg.slabs,
                  cfg.ring_depth, cfg.mode, options),
            name=f"guber-edge-w{w.id}",
            daemon=True,
        )
        w.proc.start()

    def close(self, timeout: float = 10.0) -> None:
        """Stop workers, wait out in-flight windows, account every slab,
        then tear down the segments.  Called before TickLoop.close()."""
        if self._closing:
            return
        self._closing = True
        for w in self.workers:
            if hasattr(w.seg, "ctrl"):
                w.seg.ctrl[CTRL_STOP] = 1
        deadline = time.monotonic() + timeout
        for t in self._threads:
            t.join(timeout=max(0.1, deadline - time.monotonic()))
        for w in self.workers:
            p = w.proc
            if p is not None:
                p.join(timeout=max(0.1, min(2.0, deadline - time.monotonic())))
                if p.is_alive():
                    p.terminate()
                    p.join(timeout=1.0)
                if p.is_alive():
                    p.kill()
                    p.join(timeout=1.0)
        # Shed whatever was published but never drained — the retriable
        # shutdown accounting; nothing disappears silently.
        for w in self.workers:
            with w.lock:
                self._shed_unconsumed(w, reason="shutdown")
        # In-flight windows hold zero-copy views into the segments; wait
        # for their futures before unmapping.
        while (time.monotonic() < deadline
               and any(w.in_flight > 0 for w in self.workers)):
            time.sleep(0.005)
        self._sync_metrics()
        for w in self.workers:
            wedged = w.in_flight > 0
            if not wedged:
                w.ring.detach()
                w.resp.detach()
                w.seg.close()
            w.seg.unlink()
            if wedged:
                log.warning(
                    "edge worker %d: %d windows still in flight at close; "
                    "segment left mapped", w.id, w.in_flight,
                )

    # -- drain (owner hot path) -----------------------------------------
    def _drain_loop(self) -> None:
        idle_sleep = 0.0001
        while not self._closing:
            drained = 0
            for w in self.workers:
                with w.lock:
                    drained += self._drain_once(w)
            if drained:
                idle_sleep = 0.0001
            else:
                time.sleep(idle_sleep)
                idle_sleep = min(idle_sleep * 2, 0.002)

    @hot_path
    def _drain_once(self, w: _WorkerHandle) -> int:
        """Pop every published slab of one worker into the tick loop.
        Zero copies: the columns (key blob included) are views into the
        slab; the lease releases it after pack."""
        drained = 0
        seg = w.seg
        while True:
            item = w.ring.pop_published()
            if item is None:
                return drained
            idx, seqno, rows, blob_len, deadline_ns, decode_ns, gen = item
            if gen != w.generation or rows <= 0:
                w.ring.free(idx)  # pre-crash leftovers; supervisor counted
                continue
            ints = seg.req_ints[idx]
            cols = ReqColumns(
                seg.req_blob[idx][:blob_len],
                ints[8, : rows + 1],
                ints[1, :rows], ints[2, :rows], ints[3, :rows],
                ints[4, :rows], ints[5, :rows], ints[7, :rows],
                ints[6, :rows],
                name_len=ints[0, :rows],
                lease=ShmSlabLease(w.ring, idx),
            )
            fr = flightrec.get()
            if fr is not None:
                # The worker stamped decode begin/end around its parse;
                # fold the real decode cost into the window record (and,
                # through the observer, stage_duration{stage="decode"}).
                fr.edge("decode", decode_ns * 1e-9)
            w.in_flight += 1
            fut = self.tick_loop.submit_columns(
                cols, deadline_ns * 1e-9, CLASS_CLIENT
            )
            fut.add_done_callback(
                partial(self._on_done, w, seqno, rows, gen)
            )
            drained += 1

    def _on_done(self, w: _WorkerHandle, seqno: int, rows: int,
                 gen: int, fut) -> None:
        """Tick-loop future → response ring (runs on resolver/shed
        threads).  Stale-generation results — the window was in flight
        when its worker died — are dropped *with accounting*: the
        respawned life must never see them (double-serve)."""
        try:
            mat, errors = fut.result()
        except Exception:
            mat = np.zeros((5, rows), np.int64)
            errors = {i: SHED_SHUTDOWN_MSG for i in range(rows)}
        err_blob, err_count = shmring.encode_errors(errors)
        with w.lock:
            w.in_flight -= 1
            if gen != w.generation or self._closing:
                w.dropped_responses += 1
                return
            ok = w.resp.try_publish(
                seqno, rows, mat, err_blob, err_count, gen, RESP_OK
            )
            if not ok:
                w.dropped_responses += 1

    def _shed_unconsumed(self, w: _WorkerHandle, reason: str) -> int:
        """Count + free every published-but-undrained slab (crash and
        shutdown paths; caller holds w.lock).  Returns rows shed."""
        rows_shed = 0
        windows = 0
        while True:
            item = w.ring.pop_published()
            if item is None:
                break
            idx, _seq, rows, *_ = item
            rows_shed += max(0, rows)
            windows += 1
            w.ring.free(idx)
        if rows_shed and self.metrics is not None:
            # The PR 9 admission path's shed accounting: retriable, never
            # silent (docs/overload.md).
            self.metrics.admission_shed.labels(reason="shutdown").inc(rows_shed)
            self.metrics.edge_shed.labels(
                worker=str(w.id), reason=reason).inc(rows_shed)
        w.shed_rows += rows_shed
        if windows:
            log.warning(
                "edge worker %d: shed %d windows (%d rows), reason=%s",
                w.id, windows, rows_shed, reason,
            )
        return rows_shed

    # -- supervision -----------------------------------------------------
    def _supervise_loop(self) -> None:
        last_sync = 0.0
        while not self._closing:
            for w in self.workers:
                p = w.proc
                if p is not None and not p.is_alive() and not self._closing:
                    self._respawn(w)
            now = time.monotonic()
            if now - last_sync >= 0.25:
                self._sync_metrics()
                last_sync = now
            time.sleep(0.02)

    def _respawn(self, w: _WorkerHandle) -> None:
        """Crash recovery: shed in-flight slabs retriably, bump the
        generation (stale responses drop on arrival), hand the surviving
        cursors to the fresh process."""
        exitcode = w.proc.exitcode
        log.warning("edge worker %d died (exit %s); respawning", w.id, exitcode)
        with w.lock:
            w.generation += 1
            self._shed_unconsumed(w, reason="crash")
            # Unconsumed responses from the old life die with it.
            stale = int((w.seg.resp_hdr[:, RS_STATE] == PUBLISHED).sum())
            if stale:
                w.seg.resp_hdr[:, RS_STATE] = 0
                w.dropped_responses += stale
            ctrl = w.seg.ctrl
            ctrl[CTRL_GENERATION] = w.generation
            ctrl[CTRL_READY] = 0
            ctrl[CTRL_REQ_AT] = w.ring.read_at
            ctrl[CTRL_RESP_AT] = w.resp.write_at
            w.seg.counters[C_DRIVE_DONE] = 0
            w.restarts += 1
        if self.metrics is not None:
            self.metrics.edge_worker_restarts.labels(worker=str(w.id)).inc()
        self._spawn(w)

    # -- telemetry -------------------------------------------------------
    def _sync_metrics(self) -> None:
        """Fold the workers' shm counter blocks into the owner's
        Prometheus families (delta sync; each family carries the
        ``worker`` label so one hot worker is visible as itself)."""
        m = self.metrics
        if m is None:
            return
        C = shmring
        for w in self.workers:
            if not hasattr(w.seg, "counters"):
                continue
            cur = np.array(w.seg.counters)
            d = cur - w.synced
            w.synced = cur
            if (d <= 0).all():
                continue
            lbl = str(w.id)

            def inc(family, i):
                if d[i] > 0:
                    family.labels(worker=lbl).inc(d[i])

            inc(m.edge_decode_seconds, C.C_DECODE_SECONDS)
            inc(m.edge_windows, C.C_WIN_PUBLISHED)
            inc(m.edge_rows, C.C_ROWS_PUBLISHED)
            inc(m.edge_acked_windows, C.C_WIN_ACKED)
            inc(m.edge_backpressure_waits, C.C_BACKPRESSURE_WAITS)
            if d[C.C_SHED_LOCAL] > 0:
                m.edge_shed.labels(worker=lbl, reason="local").inc(
                    d[C.C_SHED_LOCAL]
                )

    # -- introspection ---------------------------------------------------
    def socket_paths(self) -> List[str]:
        return [w.socket_path for w in self.workers if w.socket_path]

    def counters(self, wid: int) -> np.ndarray:
        return np.array(self.workers[wid].seg.counters)

    def totals(self) -> Dict[str, float]:
        """Aggregate worker counters (bench invariants, /debug/state)."""
        agg = np.zeros(N_COUNTERS, np.float64)
        for w in self.workers:
            if hasattr(w.seg, "counters"):
                agg += np.array(w.seg.counters)
        return {
            "windows_published": float(agg[shmring.C_WIN_PUBLISHED]),
            "rows_published": float(agg[shmring.C_ROWS_PUBLISHED]),
            "hits_published": float(agg[shmring.C_HITS_PUBLISHED]),
            "windows_acked": float(agg[shmring.C_WIN_ACKED]),
            "rows_acked": float(agg[shmring.C_ROWS_ACKED]),
            "hits_acked": float(agg[shmring.C_HITS_ACKED]),
            "err_rows": float(agg[shmring.C_ERR_ROWS]),
            "double_served": float(agg[shmring.C_DOUBLE_SERVED]),
            "decode_seconds": float(agg[shmring.C_DECODE_SECONDS]),
            "backpressure_waits": float(agg[shmring.C_BACKPRESSURE_WAITS]),
            "shed_local": float(agg[shmring.C_SHED_LOCAL]),
            "shed_rows": float(sum(w.shed_rows for w in self.workers)),
            "dropped_responses": float(
                sum(w.dropped_responses for w in self.workers)
            ),
            "restarts": float(sum(w.restarts for w in self.workers)),
            "in_flight": float(sum(w.in_flight for w in self.workers)),
        }

    def debug_state(self) -> dict:
        return {
            "workers": self.config.workers,
            "slabs": self.config.slabs,
            "ring_depth": self.config.ring_depth,
            "mode": self.config.mode,
            "sockets": self.socket_paths(),
            "alive": [
                bool(w.proc is not None and w.proc.is_alive())
                for w in self.workers
            ],
            "generations": [w.generation for w in self.workers],
            "totals": self.totals(),
        }

    # -- drive-mode helpers (bench / chaos) ------------------------------
    def wait_ready(self, timeout: float = 30.0) -> bool:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if all(
                int(w.seg.ctrl[CTRL_READY]) == 1 for w in self.workers
            ):
                return True
            time.sleep(0.005)
        return False

    def go(self) -> None:
        for w in self.workers:
            w.seg.ctrl[CTRL_GO] = 1

    def wait_drive_done(self, timeout: float) -> bool:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if all(
                int(w.seg.counters[C_DRIVE_DONE]) == 1 for w in self.workers
            ):
                return True
            time.sleep(0.01)
        return False
