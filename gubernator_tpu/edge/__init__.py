"""Multi-process streaming edge: shared-memory slab ingest plane.

The device ticks ~50M decisions/s but wire decode/encode is Python under
the GIL — single-threaded, it caps *served* throughput two orders of
magnitude below the kernel (docs/tpu-performance.md).  This package is
the scaling seam: N edge worker **processes** decode fastwire streams
into columnar REQ32 slabs living in ``multiprocessing.shared_memory``;
the device-owner process drains the published slab windows straight into
the existing tick loop (the flat slot-sorted matrix already supports
multi-producer concat) and fans the response matrices back through
per-worker shm response rings.  No pickling, no sockets between decode
and device — the only cross-process traffic is the slab handoff.

Layout and lifecycle live in :mod:`gubernator_tpu.edge.shmring`; the
child process main (no jax import) in :mod:`gubernator_tpu.edge.worker`;
the owner-side drain/supervisor in :mod:`gubernator_tpu.edge.plane`.
See docs/edge.md for topology, crash semantics and backpressure.
"""

from gubernator_tpu.edge.plane import EdgeConfig, EdgePlane

__all__ = ["EdgeConfig", "EdgePlane"]
