"""Shared-memory slab rings: the edge plane's cross-process wire format.

One shm segment per edge worker, fully described by ``(max_batch,
slabs, depth)`` so the owner and the (jax-free) child process map
byte-identical views:

    control block   16 int64   magic/version/shape, generation, stop flag
    counter block   32 float64 worker-written telemetry (owner reads)
    request ring    ``slabs``  REQ32 decode slabs, worker → owner (SPSC)
    response ring   ``depth``  (5, max_batch) response slots, owner → worker

A request slab mirrors a :class:`~gubernator_tpu.ops.reqcols.ColumnArena`
slab exactly — ``(9, max_batch+1)`` int64 (row 8 = key-blob offsets), a
flags vector, and ``max_batch * BLOB_PER_ROW`` staging bytes — so
``fastwire.parse_req`` decodes straight into shared memory and the owner
rebuilds :class:`ReqColumns` as zero-copy views, key blob included.

SPSC discipline: each ring has exactly one producer and one consumer,
both advancing a private cursor and communicating only through the
per-slot ``state`` word.  The producer writes the payload first and
flips ``state`` last; the consumer reads ``state`` first.  CPython's
eval loop orders the stores and x86-TSO keeps them ordered across
cores; slabs are only reused after the consumer flips the state back,
so a torn window cannot be observed.  Crash recovery never relies on
ring state: the owner zeroes both rings and bumps ``generation`` before
respawning a worker, and stale-generation traffic is dropped on read.
"""

from __future__ import annotations

import gc
from typing import Optional, Tuple

import numpy as np

from gubernator_tpu.ops.reqcols import ColumnArena
from gubernator_tpu.utils import sanitize
from gubernator_tpu.utils.hotpath import hot_path

MAGIC = 0x45444745  # "EDGE"
LAYOUT_VERSION = 1

BLOB_PER_ROW = ColumnArena.BLOB_PER_ROW

# Control block words (16 int64).
CTRL_MAGIC = 0
CTRL_VERSION = 1
CTRL_MAX_BATCH = 2
CTRL_SLABS = 3
CTRL_DEPTH = 4
CTRL_GENERATION = 5
CTRL_STOP = 6
CTRL_WORKER_PID = 7
CTRL_READY = 8    # worker: attached + warmed, waiting for GO
CTRL_GO = 9       # owner: start the drive clock (bench start barrier)
CTRL_REQ_AT = 10  # respawn handoff: where the next publish must land
CTRL_RESP_AT = 11  # respawn handoff: where the next response will land
CTRL_WORDS = 16

# Worker-written counters (32 float64; the owner only ever reads, so no
# cross-process atomicity is needed — each index has a single writer).
C_DECODE_SECONDS = 0
C_DECODE_BATCHES = 1
C_ROWS_DECODED = 2
C_WIN_PUBLISHED = 3
C_ROWS_PUBLISHED = 4
C_HITS_PUBLISHED = 5
C_WIN_ACKED = 6
C_ROWS_ACKED = 7
C_HITS_ACKED = 8
C_ERR_ROWS = 9
C_DOUBLE_SERVED = 10
C_BACKPRESSURE_WAITS = 11
C_SHED_LOCAL = 12
C_WIRE_BYTES_IN = 13
C_WIRE_BYTES_OUT = 14
C_DRIVE_DONE = 15
N_COUNTERS = 32

# Request-slab header words (8 int64 per slab).
RQ_STATE = 0          # FREE / PUBLISHED
RQ_SEQNO = 1
RQ_ROWS = 2
RQ_BLOB_LEN = 3
RQ_DEADLINE_NS = 4    # absolute CLOCK_MONOTONIC ns (system-wide on Linux)
RQ_DECODE_NS = 5      # decode duration, stamped by the worker
RQ_GENERATION = 6
RQ_WORDS = 8

# Response-slot header words (8 int64 per slot).
RS_STATE = 0          # FREE / PUBLISHED
RS_SEQNO = 1
RS_ROWS = 2
RS_ERR_COUNT = 3
RS_ERR_LEN = 4
RS_GENERATION = 5
RS_STATUS = 6         # RESP_OK / RESP_SHED
RS_WORDS = 8

FREE = 0
PUBLISHED = 1
LEASED = 2  # request slabs only: popped by the owner, not yet released

RESP_OK = 0
RESP_SHED = 1         # window shed (retriable; every row carries an error)

# Per-row budget for encoded error records in a response slot: errors are
# the exception path (shed windows, table-full items), and records past
# the budget degrade to a truncated string, never a lost error.
ERR_RECORD_BYTES = 112


def _pad8(n: int) -> int:
    return (n + 7) & ~7


class EdgeSegment:
    """Typed numpy views over one worker's shm segment.

    The owner constructs with ``create=True`` (and owns unlink); the
    child attaches by name.  Attach in children goes through
    :func:`attach_segment`, which un-registers the mapping from the
    multiprocessing resource tracker so a worker exit (or SIGKILL — the
    chaos case) can never tear down a segment the owner still serves
    from.
    """

    def __init__(self, name: Optional[str], max_batch: int, slabs: int,
                 depth: int, create: bool, shm=None):
        from multiprocessing import shared_memory

        self.max_batch = int(max_batch)
        self.slabs = int(slabs)
        self.depth = int(depth)
        self.blob_cap = self.max_batch * BLOB_PER_ROW
        self.err_cap = _pad8(8 + self.max_batch * ERR_RECORD_BYTES)
        if shm is not None:
            self.shm = shm
        elif create:
            self.shm = shared_memory.SharedMemory(
                name=name, create=True, size=self.total_size()
            )
        else:
            self.shm = shared_memory.SharedMemory(name=name)
        buf = self.shm.buf
        at = 0

        def view(dtype, shape):
            nonlocal at
            count = int(np.prod(shape))
            a = np.frombuffer(buf, dtype, count=count, offset=at)
            at += a.nbytes
            at = _pad8(at)
            return a.reshape(shape)

        mb, sl, dp = self.max_batch, self.slabs, self.depth
        self.ctrl = view(np.int64, (CTRL_WORDS,))
        self.counters = view(np.float64, (N_COUNTERS,))
        self.req_hdr = view(np.int64, (sl, RQ_WORDS))
        self.req_ints = view(np.int64, (sl, 9, mb + 1))
        self.req_flags = view(np.uint8, (sl, _pad8(mb)))
        self.req_blob = view(np.uint8, (sl, self.blob_cap))
        self.resp_hdr = view(np.int64, (dp, RS_WORDS))
        self.resp_mat = view(np.int64, (dp, 5, mb))
        self.resp_err = view(np.uint8, (dp, self.err_cap))
        assert at <= self.shm.size
        if create:
            self.ctrl[CTRL_MAGIC] = MAGIC
            self.ctrl[CTRL_VERSION] = LAYOUT_VERSION
            self.ctrl[CTRL_MAX_BATCH] = mb
            self.ctrl[CTRL_SLABS] = sl
            self.ctrl[CTRL_DEPTH] = dp
            self.ctrl[CTRL_GENERATION] = 1
        else:
            if int(self.ctrl[CTRL_MAGIC]) != MAGIC or (
                int(self.ctrl[CTRL_MAX_BATCH]) != mb
                or int(self.ctrl[CTRL_SLABS]) != sl
                or int(self.ctrl[CTRL_DEPTH]) != dp
            ):
                raise ValueError(
                    f"edge segment {self.shm.name} layout mismatch"
                )

    def total_size(self) -> int:
        mb, sl, dp = self.max_batch, self.slabs, self.depth
        return (
            _pad8(CTRL_WORDS * 8)
            + _pad8(N_COUNTERS * 8)
            + sl * (RQ_WORDS * 8 + 9 * (mb + 1) * 8 + _pad8(mb)
                    + self.blob_cap)
            + dp * (RS_WORDS * 8 + 5 * mb * 8 + self.err_cap)
        )

    # Views hold exported pointers into shm.buf; drop them before close()
    # or BufferError ("cannot close exported pointers exist").
    def _drop_views(self) -> None:
        for f in ("ctrl", "counters", "req_hdr", "req_ints", "req_flags",
                  "req_blob", "resp_hdr", "resp_mat", "resp_err"):
            if hasattr(self, f):
                delattr(self, f)

    def close(self) -> None:
        self._drop_views()
        try:
            self.shm.close()
        except BufferError:
            # A ReqColumns view in an unreachable cycle (future ->
            # done-callback -> columns) can outlive its drop; collect,
            # then retry.  A genuinely live view still pins the mapping
            # — swallow, unlink below works regardless.
            gc.collect()
            try:
                self.shm.close()
            except BufferError:
                pass

    def unlink(self) -> None:
        try:
            self.shm.unlink()
        except FileNotFoundError:
            pass


def attach_segment(name: str, max_batch: int, slabs: int,
                   depth: int) -> EdgeSegment:
    """Child-side attach, un-registered from the resource tracker (the
    owner created the segment and owns its lifetime; without this, any
    worker death — including the deliberate SIGKILL chaos path — would
    let the tracker unlink a segment that is still serving).  The
    registration is suppressed around the attach rather than undone
    after it: the spawn child shares the owner's tracker process, whose
    name cache is a set, so a child-side unregister would erase the
    owner's own registration and turn the owner's unlink into a tracker
    KeyError."""
    from multiprocessing import resource_tracker, shared_memory

    orig_register = resource_tracker.register
    resource_tracker.register = lambda *a, **k: None
    try:
        shm = shared_memory.SharedMemory(name=name)
    finally:
        resource_tracker.register = orig_register
    return EdgeSegment(None, max_batch, slabs, depth, create=False, shm=shm)


class RequestRing:
    """The worker→owner slab ring of one segment (SPSC).

    Producer side (worker): :meth:`try_claim` a FREE slab at the write
    cursor, decode into its views, :meth:`publish`.  Consumer side
    (owner): :meth:`pop_published` in ring order marks the slab LEASED;
    it returns to FREE via :meth:`free` only after the tick loop has
    packed the window — exactly the ``TickLoop._flush``
    release-after-pack timing, carried by the :class:`ShmSlabLease`
    attached to the drained ``ReqColumns``.  Slab states move
    FREE → PUBLISHED (worker) → LEASED → FREE (owner), each transition
    single-writer.
    """

    def __init__(self, seg: EdgeSegment):
        self.seg = seg
        self.hdr = seg.req_hdr
        self.slabs = seg.slabs
        self.write_at = 0
        self.read_at = 0
        # None unless GUBER_SANITIZERS=1 (docs/concurrency.md): per-ring
        # single-writer checker, one attribute test on the off path.
        self._san = sanitize.ring_sanitizer(f"RequestRing[{seg.shm.name}]")

    # -- producer (worker process) -------------------------------------
    def try_claim(self) -> Optional[int]:
        """Index of the slab at the write cursor if FREE, else None
        (ring full — the worker's per-producer backpressure bound)."""
        idx = self.write_at
        if int(self.hdr[idx, RQ_STATE]) != FREE:
            return None
        return idx

    @hot_path
    def publish(self, idx: int, seqno: int, rows: int, blob_len: int,
                deadline_ns: int, decode_ns: int, generation: int) -> None:
        """Hand a decoded slab to the owner: header payload first, the
        state flip last (the SPSC ordering contract), cursor advance."""
        h = self.hdr[idx]
        h[RQ_SEQNO] = seqno
        h[RQ_ROWS] = rows
        h[RQ_BLOB_LEN] = blob_len
        h[RQ_DEADLINE_NS] = deadline_ns
        h[RQ_DECODE_NS] = decode_ns
        h[RQ_GENERATION] = generation
        if self._san is not None:
            self._san.note_publish(idx)
        h[RQ_STATE] = PUBLISHED
        self.write_at = (idx + 1) % self.slabs

    # -- consumer (owner process) --------------------------------------
    @hot_path
    def pop_published(self) -> Optional[Tuple[int, int, int, int, int, int, int]]:
        """The next published slab in ring order as ``(idx, seqno, rows,
        blob_len, deadline_ns, decode_ns, generation)``, or None when the
        ring is quiet.  The slab moves PUBLISHED → LEASED: still owned by
        the tick loop's zero-copy views, not claimable by the worker, and
        — critically — not poppable again when the read cursor wraps a
        full ring of in-flight slabs.  :meth:`free` returns it to FREE."""
        idx = self.read_at
        h = self.hdr[idx]
        if int(h[RQ_STATE]) != PUBLISHED:
            return None
        if self._san is not None:
            self._san.note_pop(idx)
        h[RQ_STATE] = LEASED
        self.read_at = (idx + 1) % self.slabs
        return (
            idx, int(h[RQ_SEQNO]), int(h[RQ_ROWS]), int(h[RQ_BLOB_LEN]),
            int(h[RQ_DEADLINE_NS]), int(h[RQ_DECODE_NS]),
            int(h[RQ_GENERATION]),
        )

    def free(self, idx: int) -> None:
        if self._san is not None:
            self._san.note_free(
                idx, int(self.hdr[idx, RQ_STATE]) == PUBLISHED
            )
        self.hdr[idx, RQ_STATE] = FREE

    def reset(self) -> None:
        """Crash recovery: drop every in-flight slab and rewind both
        cursors (the owner bumps the generation around this)."""
        if self._san is not None:
            self._san.note_reset()
        self.hdr[:] = 0
        self.write_at = 0
        self.read_at = 0

    def detach(self) -> None:
        """Drop the shm views so the segment's mmap can close."""
        self.hdr = None
        self.seg = None


class ShmSlabLease:
    """Release token carried by a drained window's ``ReqColumns.lease``
    slot — duck-typed to :class:`ops.reqcols.ArenaLease` so the tick
    loop's release-after-pack call returns the shm slab to the worker
    without knowing it crossed a process boundary.  Idempotent."""

    __slots__ = ("ring", "index")

    def __init__(self, ring: RequestRing, index: int):
        self.ring = ring
        self.index = index

    def release(self) -> None:
        ring, self.ring = self.ring, None
        if ring is not None:
            ring.free(self.index)


class ResponseRing:
    """The owner→worker response ring of one segment (SPSC at the slot
    level; the owner side serializes its writers — tick-resolver and
    shed paths both complete futures — behind the plane's per-worker
    lock)."""

    def __init__(self, seg: EdgeSegment):
        self.seg = seg
        self.hdr = seg.resp_hdr
        self.mat = seg.resp_mat
        self.err = seg.resp_err
        self.depth = seg.depth
        self.write_at = 0
        self.read_at = 0
        # Consumer-side pin only: the producer side is deliberately
        # multi-thread (tick-resolver and shed paths), serialized by
        # the plane's per-worker lock rather than a thread pin.
        self._san = sanitize.ring_sanitizer(f"ResponseRing[{seg.shm.name}]")

    # -- producer (owner process) --------------------------------------
    def try_publish(self, seqno: int, rows: int, mat: np.ndarray,
                    err_blob: bytes, err_count: int, generation: int,
                    status: int) -> bool:
        """Write one window's response; False when the slot at the write
        cursor is still unconsumed (only reachable when the worker died
        — the live worker bounds its outstanding windows to the ring
        depth — so the caller counts a dropped response and moves on)."""
        idx = self.write_at
        h = self.hdr[idx]
        if int(h[RS_STATE]) != FREE:
            return False
        self.mat[idx, :, :rows] = mat
        if err_blob:
            self.err[idx, : len(err_blob)] = np.frombuffer(err_blob, np.uint8)
        h[RS_SEQNO] = seqno
        h[RS_ROWS] = rows
        h[RS_ERR_COUNT] = err_count
        h[RS_ERR_LEN] = len(err_blob)
        h[RS_GENERATION] = generation
        h[RS_STATUS] = status
        h[RS_STATE] = PUBLISHED
        self.write_at = (idx + 1) % self.depth
        return True

    # -- consumer (worker process) -------------------------------------
    def poll(self):
        """The next response in ring order as ``(seqno, rows, mat_view,
        err_count, err_blob_bytes, generation, status)`` or None; the
        caller must finish with the views before :meth:`free_slot`."""
        idx = self.read_at
        h = self.hdr[idx]
        if int(h[RS_STATE]) != PUBLISHED:
            return None
        rows = int(h[RS_ROWS])
        err_len = int(h[RS_ERR_LEN])
        if self._san is not None:
            self._san.note_pop(idx)
        out = (
            int(h[RS_SEQNO]), rows, self.mat[idx, :, :rows],
            int(h[RS_ERR_COUNT]), bytes(self.err[idx, :err_len]),
            int(h[RS_GENERATION]), int(h[RS_STATUS]), idx,
        )
        self.read_at = (idx + 1) % self.depth
        return out

    def free_slot(self, idx: int) -> None:
        if self._san is not None:
            # A polled slot sits in the lease set; freeing a PUBLISHED
            # slot that was never polled drops a response on the floor.
            self._san.note_free(
                idx, int(self.hdr[idx, RS_STATE]) == PUBLISHED
            )
        self.hdr[idx, RS_STATE] = FREE

    def reset(self) -> None:
        if self._san is not None:
            self._san.note_reset()
        self.hdr[:] = 0
        self.write_at = 0
        self.read_at = 0

    def detach(self) -> None:
        """Drop the shm views so the segment's mmap can close."""
        self.hdr = None
        self.mat = None
        self.err = None
        self.seg = None


def encode_errors(errors: dict) -> Tuple[bytes, int]:
    """Pack a per-item error dict (``{row: message}``) into the response
    slot's record blob: ``count`` u32 little-endian records of
    ``(row u32, len u32, utf-8 bytes)``.  Messages survive byte-exact —
    the wire contract's per-item error strings (engine table-full, the
    PR 9 retriable shed messages) must not be lossy across the shm hop."""
    if not errors:
        return b"", 0
    parts = []
    for i, msg in errors.items():
        b = msg.encode()[: ERR_RECORD_BYTES - 8]
        parts.append(int(i).to_bytes(4, "little"))
        parts.append(len(b).to_bytes(4, "little"))
        parts.append(b)
    return b"".join(parts), len(errors)


def decode_errors(blob: bytes, count: int) -> dict:
    """Inverse of :func:`encode_errors`."""
    errors = {}
    at = 0
    for _ in range(count):
        row = int.from_bytes(blob[at : at + 4], "little")
        ln = int.from_bytes(blob[at + 4 : at + 8], "little")
        at += 8
        errors[row] = blob[at : at + ln].decode()
        at += ln
    return errors
