"""Tiered bucket state: the host-side cold tier between HBM and a Store.

The engine's device table (L1) is fixed-capacity; before this package,
LRU reclaim *destroyed* victim rows (the evict scatter zeroes them), so
any key cycling out and back in restarted with a full budget — a
rate-limit bypass under churn.  The cold tier is a bounded host-side
columnar store the engine demotes victims into (readback-then-evict)
and promotes misses out of (one batched restore scatter per tick), so
bucket continuity survives hot↔cold cycling.  Below it, the SSD tier
(ssd.py) absorbs the cold store's overflow into append-only mmap slab
files — billions of keys under bounded RAM.  See docs/tiering.md.
"""

from gubernator_tpu.tiering.coldstore import ColdStore
from gubernator_tpu.tiering.ssd import SsdStore

__all__ = ["ColdStore", "SsdStore"]
