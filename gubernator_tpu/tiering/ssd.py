"""SSD-backed third storage tier: append-only mmap slab store.

One tier below the host :class:`~gubernator_tpu.tiering.coldstore.ColdStore`
(docs/tiering.md): when the bounded cold tier sheds its LRU tail, the
victims land here instead of evaporating, so bucket continuity
(``remaining / remaining_f / created_at / status``) survives
hot↔cold↔SSD cycling with RAM bounded by the two upper tiers — the
long Zipf tail of billions of rarely-touched buckets lives on flash.

Layout — log-structured slabs, not a B-tree:

* A slab is an append-only file of CRC-framed records (the
  ``persistence/`` GSNP framing: ``MAGIC | crc32 | len | payload``), one
  record per **demote batch** — an npz-encoded columnar block of keys +
  ``COLD_FIELDS`` rows.  Batched records mean one ``write()`` per cold
  sweep, not per key.
* Reads go through a per-slab ``mmap``: a batch lookup touches only the
  pages holding the records it needs.  A record is decoded once per
  batch no matter how many of its rows hit.
* The only RAM per key is one index entry ``key → (slab, offset, row,
  expire_at)``; TTL is enforced drop-on-read from the index alone (no
  I/O for an expired key).

Write path — asynchronous, bounded, never unbounded RAM:

* ``put_columns`` stages the batch in a **bounded queue**; a supervised
  background thread (``resilience.spawn_supervised_thread``) drains it:
  encode → append → install index entries.  A full queue **blocks the
  demote sweep** (counted: ``backpressure``) rather than buffering
  without bound or dropping rows — continuity beats latency on the
  demote side, which already runs off the tick path.
* Staged-but-unwritten batches are visible to ``take_batch`` (served
  from RAM and tombstoned so the written row is born dead) — a key can
  never fall into a read/write gap.

Compaction and bounds (log-structured maintenance, writer-thread side):

* Overwrites and takes don't touch old records; they just decrement the
  owning slab's live count.  A sealed slab past ``compact_ratio``
  garbage gets its live rows appended to the active slab **and fsynced
  before the old file is unlinked** — the crash-safe retire ordering of
  ``SnapshotStore.write_base``; a crash between the two leaves both
  copies and index rebuild resolves last-wins by (slab, offset) order.
* ``capacity_bytes`` bounds total disk: past it the oldest sealed slab
  retires wholesale (cache semantics, like the tiers above).

Failure modes (documented, tested):

* A torn tail (kill -9 mid-append) is detected by the CRC framing:
  rebuild stops that slab at its last good record and counts the damage
  (``corrupt_records``); on reopen all existing slabs are sealed and
  appends go to a fresh slab, so a bad tail is never appended past.
* ``remove``/``take`` tombstones live only in RAM: after a crash the
  record is still on disk and the row resurrects with its pre-take
  state.  That is at worst *conservative* for admission (the stale copy
  has fewer tokens than a fresh bucket) and heals on the key's next
  demote (newer record wins).
"""

from __future__ import annotations

import io
import logging
import mmap
import os
import queue
import threading
import zlib
from typing import Dict, List, Optional, Tuple

import numpy as np

from gubernator_tpu.persistence.snapshot import (
    _HEADER, MAGIC, read_records, write_record,
)
from gubernator_tpu.resilience.supervisor import spawn_supervised_thread
from gubernator_tpu.tiering.coldstore import COLD_FIELDS, ZOO_COLD_FIELDS
from gubernator_tpu.utils.hotpath import hot_path
from gubernator_tpu.utils import sanitize

log = logging.getLogger("gubernator.tiering.ssd")

_SLAB_SUFFIX = ".slab"


def _slab_name(slab_id: int) -> str:
    return f"slab-{slab_id:08d}{_SLAB_SUFFIX}"


def _field_dtype(f: str):
    return np.float64 if f == "remaining_f" else np.int64


def _encode_batch(keys: List[bytes], cols: Dict[str, np.ndarray]) -> bytes:
    """Columnar demote batch → npz payload (key blob + offsets + fields;
    the persistence snapshot encoding, minus the engine-only fields)."""
    blob = b"".join(keys)
    offsets = np.zeros(len(keys) + 1, np.int64)
    np.cumsum([len(k) for k in keys], out=offsets[1:])
    enc = {
        "key_blob": np.frombuffer(blob, np.uint8),
        "key_offsets": offsets,
    }
    for f in COLD_FIELDS:
        enc[f] = np.ascontiguousarray(cols[f], _field_dtype(f))
    buf = io.BytesIO()
    np.savez(buf, **enc)
    return buf.getvalue()


def _decode_batch(payload: bytes) -> Tuple[List[bytes], Dict[str, np.ndarray]]:
    """Inverse of :func:`_encode_batch`.  Slabs written before the
    algorithm zoo lack the zoo columns: zero-fill them (fresh
    window/TAT) so old slab files keep loading."""
    with np.load(io.BytesIO(payload)) as z:
        blob = z["key_blob"].tobytes()
        offsets = z["key_offsets"]
        n = len(offsets) - 1
        cols = {
            f: (
                z[f] if f in z.files
                else np.zeros(n, _field_dtype(f))
            )
            for f in COLD_FIELDS
        }
    keys = [
        blob[int(offsets[i]): int(offsets[i + 1])]
        for i in range(len(offsets) - 1)
    ]
    return keys, cols


class _Slab:
    """One append-only slab file + its read map and liveness stats."""

    __slots__ = ("slab_id", "path", "file", "map", "tail", "total_rows",
                 "live_rows", "sealed", "keys")

    def __init__(self, slab_id: int, path: str):
        self.slab_id = slab_id
        self.path = path
        self.file = None            # write handle (active slab only)
        self.map: Optional[mmap.mmap] = None
        self.tail = 0               # bytes appended (== file size)
        self.total_rows = 0
        self.live_rows = 0
        self.sealed = False
        self.keys: set = set()      # keys whose index entry points here

    def garbage_ratio(self) -> float:
        if self.total_rows <= 0:
            return 0.0
        return 1.0 - self.live_rows / self.total_rows


class SsdStore:
    """Bounded SSD tier for cold-store overflow (see module doc).

    Implements the :class:`~gubernator_tpu.store.Store` protocol —
    including the batched ``put_batch``/``remove_batch`` extension and
    the columnar ``put_columns`` fast path — so it drops in as the
    ColdStore's write-behind sink unchanged.  Thread-safe: the engine's
    miss path (``take_batch``) runs concurrently with the background
    writer and the reclaimer's demote sweeps.
    """

    def __init__(
        self,
        directory: str,
        capacity_bytes: int = 1 << 30,
        compact_ratio: float = 0.5,
        queue_depth: int = 8,
        slab_bytes: int = 0,
        metrics=None,
    ):
        if capacity_bytes <= 0:
            raise ValueError("SsdStore capacity_bytes must be positive")
        if not (0.0 < compact_ratio <= 1.0):
            raise ValueError("SsdStore compact_ratio must be in (0, 1]")
        if queue_depth <= 0:
            raise ValueError("SsdStore queue_depth must be positive")
        self.dir = directory
        self.capacity_bytes = int(capacity_bytes)
        self.compact_ratio = float(compact_ratio)
        # Slab roll target: small enough that compaction/retire work in
        # slab-sized chunks, large enough to amortize the per-file cost.
        self.slab_bytes = int(slab_bytes) if slab_bytes > 0 else max(
            1 << 20, self.capacity_bytes // 8
        )
        os.makedirs(directory, exist_ok=True)
        self._lock = sanitize.lock("SsdStore._lock")
        # key → (slab_id, offset, row, expire_at).  Disjoint from
        # ``_staged`` by construction: staging a key pops its index
        # entry (the old disk row becomes garbage immediately).
        self._index: Dict[bytes, Tuple[int, int, int, int]] = {}
        self._slabs: Dict[int, _Slab] = {}
        # In-flight demote batches: bid → (keys, cols, dead-row set).
        # ``_staged`` maps key → (bid, row) so queued rows stay readable.
        self._pending: Dict[int, Tuple[List[bytes], Dict[str, np.ndarray],
                                       set]] = {}
        self._staged: Dict[bytes, Tuple[int, int]] = {}
        self._next_bid = 0
        self._queue: "queue.Queue[Optional[int]]" = queue.Queue(queue_depth)
        self._running = True
        # Counters (mirrored into Prometheus by the service layer).
        self.metric_demotions = 0
        self.metric_promotions = 0
        self.metric_hits = 0
        self.metric_misses = 0
        self.metric_expired = 0
        self.metric_lookup_calls = 0
        self.metric_write_batches = 0
        self.metric_backpressure = 0
        self.metric_compactions = 0
        self.metric_slab_evictions = 0
        self.metric_corrupt_records = 0
        self._rebuild()
        self._writer = spawn_supervised_thread(
            self._writer_loop,
            name="ssd-writer",
            should_restart=lambda: self._running,
            metrics=metrics,
            loop_label="ssd_writer",
        )

    # ------------------------------------------------------------------
    # Open-time index rebuild
    # ------------------------------------------------------------------
    def _rebuild(self) -> None:
        """Replay every slab's records in (slab, offset) order, last
        write wins.  All pre-existing slabs are sealed — appending past
        a possibly-torn tail would orphan the new record behind the
        first corrupt frame — and writes start a fresh slab."""
        try:
            names = sorted(
                n for n in os.listdir(self.dir)
                if n.startswith("slab-") and n.endswith(_SLAB_SUFFIX)
            )
        except OSError:
            names = []
        max_id = -1
        for name in names:
            try:
                slab_id = int(name[len("slab-"): -len(_SLAB_SUFFIX)])
            except ValueError:
                continue
            max_id = max(max_id, slab_id)
            slab = _Slab(slab_id, os.path.join(self.dir, name))
            slab.sealed = True
            # Registered before replay: a key superseded by a later
            # record in this same slab resolves its old entry here.
            self._slabs[slab_id] = slab
            payloads, corrupt = read_records(slab.path)
            self.metric_corrupt_records += corrupt
            offset = 0
            for payload in payloads:
                try:
                    keys, cols = _decode_batch(payload)
                except Exception:
                    self.metric_corrupt_records += 1
                    break
                expire = np.asarray(cols["expire_at"], np.int64)
                for row, key in enumerate(keys):
                    slab.total_rows += 1
                    old = self._index.pop(key, None)
                    if old is not None:
                        prev = self._slabs[old[0]]
                        prev.live_rows -= 1
                        prev.keys.discard(key)
                    self._index[key] = (
                        slab_id, offset, row, int(expire[row])
                    )
                    slab.live_rows += 1
                    slab.keys.add(key)
                offset += _HEADER.size + len(payload)
            slab.tail = offset
        self._active = self._new_slab(max_id + 1)

    def _new_slab(self, slab_id: int) -> _Slab:
        slab = _Slab(slab_id, os.path.join(self.dir, _slab_name(slab_id)))
        slab.file = open(slab.path, "ab")
        # The open stays outside the lock (G007); only the registry
        # install is guarded — take_batch walks _slabs under _lock while
        # the writer thread rolls slabs.
        with self._lock:
            # guber: allow-g009(post-start writes all hold _lock; the unguarded peers are _load, which runs in __init__ before the writer thread exists)
            self._slabs[slab_id] = slab
        return slab

    # ------------------------------------------------------------------
    # Read plumbing
    # ------------------------------------------------------------------
    def _map_slab(self, slab: _Slab, need: int) -> Optional[mmap.mmap]:
        """The slab's read map, remapped when appends outgrew it.  Kept
        out of the batch-lookup body: ``mmap`` is a syscall and remaps
        are rare (once per slab growth spurt, not per lookup)."""
        m = slab.map
        if m is not None and len(m) >= need:
            return m
        if m is not None:
            m.close()
            slab.map = None
        try:
            # guber: allow-G001(memoized remap - once per slab growth spurt, not per lookup; the mmap'd read path IS the SSD tier design) # guber: allow-G007(memoized remap - amortized to once per slab growth, briefly under the store lock by design)
            with open(slab.path, "rb") as f:
                # guber: allow-G001(memoized remap - see the open above) # guber: allow-G007(memoized remap - see the open above)
                slab.map = mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ)
        except (OSError, ValueError):
            return None  # empty or vanished file: caller counts a miss
        return slab.map if len(slab.map) >= need else None

    def _read_payload(self, slab: _Slab, offset: int) -> Optional[bytes]:
        """One CRC-checked record payload out of the slab map."""
        m = self._map_slab(slab, offset + _HEADER.size)
        if m is None:
            return None
        magic, crc, length = _HEADER.unpack(
            m[offset: offset + _HEADER.size]
        )
        if magic != MAGIC:
            self.metric_corrupt_records += 1
            return None
        end = offset + _HEADER.size + length
        if len(m) < end:
            m = self._map_slab(slab, end)
            if m is None:
                return None
        payload = m[offset + _HEADER.size: end]
        if zlib.crc32(payload) & 0xFFFFFFFF != crc:
            self.metric_corrupt_records += 1
            return None
        return payload

    # ------------------------------------------------------------------
    # Demote (cold overflow → SSD)
    # ------------------------------------------------------------------
    @hot_path
    def put_columns(
        self, keys: List[bytes], cols: Dict[str, np.ndarray], now: int
    ) -> int:
        """Stage one demote batch (COLD_FIELDS columns, one row per
        key) on the bounded writer queue; returns rows staged.  Already
        TTL-expired rows are dropped.  Blocks (counted) when the queue
        is full — backpressure, never unbounded RAM."""
        if not keys:
            return 0
        missing = [f for f in COLD_FIELDS if f not in cols]
        if missing:
            # Legacy callers omit the zoo columns; zero-fill (see
            # _decode_batch).
            zeros = np.zeros(len(keys), np.int64)
            cols = {**cols, **{f: zeros for f in missing}}
        expire = cols["expire_at"]
        keep = np.flatnonzero(expire >= now)
        if len(keep) == 0:
            return 0
        if len(keep) < len(keys):
            keys = [keys[int(j)] for j in keep]
            cols = {f: cols[f][keep] for f in COLD_FIELDS}
        with self._lock:
            bid = self._next_bid
            self._next_bid = bid + 1
            dead: set = set()
            for row, key in enumerate(keys):
                old = self._staged.get(key)
                if old is not None:
                    # Superseded while queued: the old row is born dead.
                    self._pending[old[0]][2].add(old[1])
                else:
                    ent = self._index.pop(key, None)
                    if ent is not None:
                        prev = self._slabs[ent[0]]
                        prev.live_rows -= 1
                        prev.keys.discard(key)
                self._staged[key] = (bid, row)
            self._pending[bid] = (keys, cols, dead)
            self.metric_demotions += len(keys)
        if self._queue.full():
            self.metric_backpressure += 1
        # guber: allow-G001(bounded demote-queue put IS the backpressure - blocks only when the writer thread is behind, counted above)
        self._queue.put(bid)
        return len(keys)

    # ------------------------------------------------------------------
    # Promote (SSD → cold/hot): the engine miss path's third hop
    # ------------------------------------------------------------------
    @hot_path
    def take_batch(
        self, keys: List[bytes], now: int
    ) -> Tuple[np.ndarray, Dict[str, np.ndarray]]:
        """Look up + REMOVE a batch of keys (promotion is a move, like
        ``ColdStore.take``: the upper tier becomes the owner).  Returns
        ``(hit_positions, cols)`` in hit order; expired entries are
        dropped from the index without touching disk."""
        empty = np.empty(0, np.int64)
        if not keys:
            return empty, {}
        with self._lock:
            self.metric_lookup_calls += 1
            pos: List[int] = []
            ram_rows: List[Tuple[int, int, int]] = []  # (out, bid, row)
            # (slab_id, offset) → [(out_row, record_row)]
            disk: Dict[Tuple[int, int], List[Tuple[int, int]]] = {}
            for j, key in enumerate(keys):
                staged = self._staged.get(key)
                if staged is not None:
                    bid, row = staged
                    batch = self._pending[bid]
                    if batch[1]["expire_at"][row] < now:
                        del self._staged[key]
                        batch[2].add(row)
                        self.metric_expired += 1
                        self.metric_misses += 1
                        continue
                    ram_rows.append((len(pos), bid, row))
                    pos.append(j)
                    del self._staged[key]
                    batch[2].add(row)  # written row will be born dead
                    continue
                ent = self._index.get(key)
                if ent is None:
                    self.metric_misses += 1
                    continue
                slab_id, offset, row, expire_at = ent
                slab = self._slabs[slab_id]
                del self._index[key]
                slab.live_rows -= 1
                slab.keys.discard(key)
                if expire_at < now:
                    self.metric_expired += 1
                    self.metric_misses += 1
                    continue
                disk.setdefault((slab_id, offset), []).append((len(pos), row))
                pos.append(j)
            n = len(pos)
            if n == 0:
                return empty, {}
            out = {f: np.empty(n, _field_dtype(f)) for f in COLD_FIELDS}
            lost: set = set()
            for (slab_id, offset), rows in disk.items():
                payload = self._read_payload(self._slabs[slab_id], offset)
                if payload is None:
                    lost.update(o for o, _ in rows)
                    continue
                _, rec_cols = _decode_batch(payload)
                dst = np.fromiter((o for o, _ in rows), np.int64, len(rows))
                src = np.fromiter((r for _, r in rows), np.int64, len(rows))
                for f in COLD_FIELDS:
                    out[f][dst] = rec_cols[f][src]
            for o, bid, row in ram_rows:
                batch_cols = self._pending[bid][1]
                for f in COLD_FIELDS:
                    out[f][o] = batch_cols[f][row]
            if lost:
                # Unreadable record (rot under a live index entry):
                # those rows are misses; compact the survivors out.
                keep = np.fromiter(
                    (o for o in range(n) if o not in lost),
                    np.int64, n - len(lost),
                )
                pos = [pos[int(o)] for o in keep]
                out = {f: out[f][keep] for f in COLD_FIELDS}
                self.metric_misses += len(lost)
                n = len(pos)
                if n == 0:
                    return empty, {}
            self.metric_hits += n
            self.metric_promotions += n
            return np.fromiter(pos, np.int64, n), out

    # ------------------------------------------------------------------
    # Background writer
    # ------------------------------------------------------------------
    def _writer_loop(self) -> None:
        """Drain the bounded queue: encode → append → install; then the
        log-structured maintenance (roll / compact / evict) that must
        never run on the demote or miss path."""
        while True:
            bid = self._queue.get()
            try:
                if bid is None:
                    return
                self._write_batch(bid)
                self._maintain()
            finally:
                self._queue.task_done()

    def _write_batch(self, bid: int) -> None:
        with self._lock:
            keys, cols, _dead = self._pending[bid]
        payload = _encode_batch(keys, cols)
        slab = self._active
        offset = slab.tail
        written = write_record(slab.file, payload)
        slab.file.flush()
        with self._lock:
            slab.tail = offset + written
            keys, cols, dead = self._pending.pop(bid)
            expire = cols["expire_at"]
            for row, key in enumerate(keys):
                slab.total_rows += 1
                if row in dead:
                    continue  # taken/removed/superseded while queued
                if self._staged.get(key) != (bid, row):
                    continue
                del self._staged[key]
                # guber: allow-g009(all post-start touches hold _lock; the unguarded peers are _load, which runs in __init__ before the writer thread exists)
                self._index[key] = (
                    slab.slab_id, offset, row, int(expire[row])
                )
                slab.live_rows += 1
                slab.keys.add(key)
            self.metric_write_batches += 1

    def _maintain(self) -> None:
        """Roll the active slab past its size target, compact sealed
        slabs past the garbage threshold, retire oldest slabs past the
        byte budget.  Writer-thread only."""
        slab = self._active
        if slab.tail >= self.slab_bytes:
            os.fsync(slab.file.fileno())
            slab.file.close()
            slab.file = None
            with self._lock:
                slab.sealed = True
            # guber: allow-g009(writer-thread-only rebind; the other write is _load, which runs in __init__ before the thread starts)
            self._active = self._new_slab(slab.slab_id + 1)
        for sid in sorted(self._slabs):
            s = self._slabs[sid]
            if (
                s.sealed and s.total_rows > 0
                and s.garbage_ratio() > self.compact_ratio
            ):
                self._compact(s)
        total = sum(s.tail for s in self._slabs.values())
        while total > self.capacity_bytes:
            sealed = sorted(
                sid for sid, s in self._slabs.items() if s.sealed
            )
            if not sealed:
                break
            total -= self._retire(self._slabs[sealed[0]], evict=True)

    def _compact(self, slab: _Slab) -> None:
        """Rewrite a sealed slab's live rows into the active slab, fsync
        the copy, THEN unlink the original (SnapshotStore retire
        ordering: a crash between leaves both copies; index rebuild is
        last-wins by slab order, and the copy lives in a newer slab)."""
        with self._lock:
            entries = [
                (key, ent) for key in list(slab.keys)
                if (ent := self._index.get(key)) is not None
            ]
        if entries:
            by_record: Dict[int, List[Tuple[bytes, int, int]]] = {}
            for key, (sid, offset, row, expire_at) in entries:
                if sid != slab.slab_id:
                    continue  # repointed while we looked
                by_record.setdefault(offset, []).append(
                    (key, row, expire_at)
                )
            live_keys: List[bytes] = []
            live_cols = {
                f: [] for f in COLD_FIELDS
            }  # type: Dict[str, list]
            for offset, rows in sorted(by_record.items()):
                payload = self._read_payload(slab, offset)
                if payload is None:
                    continue
                _, rec_cols = _decode_batch(payload)
                for key, row, _expire in rows:
                    live_keys.append(key)
                    for f in COLD_FIELDS:
                        live_cols[f].append(rec_cols[f][row])
            if live_keys:
                cols = {
                    f: np.asarray(live_cols[f], _field_dtype(f))
                    for f in COLD_FIELDS
                }
                dst = self._active
                offset = dst.tail
                written = write_record(dst.file, _encode_batch(
                    live_keys, cols
                ))
                dst.file.flush()
                os.fsync(dst.file.fileno())
                expire = cols["expire_at"]
                with self._lock:
                    dst.tail = offset + written
                    for row, key in enumerate(live_keys):
                        dst.total_rows += 1
                        ent = self._index.get(key)
                        if ent is None or ent[0] != slab.slab_id:
                            continue  # moved/removed during the copy
                        slab.live_rows -= 1
                        slab.keys.discard(key)
                        self._index[key] = (
                            dst.slab_id, offset, row, int(expire[row])
                        )
                        dst.live_rows += 1
                        dst.keys.add(key)
        self._retire(slab, evict=False)
        self.metric_compactions += 1

    def _retire(self, slab: _Slab, evict: bool) -> int:
        """Drop a sealed slab: index entries, read map, file.  Returns
        the bytes released."""
        with self._lock:
            for key in slab.keys:
                self._index.pop(key, None)
            if evict:
                self.metric_slab_evictions += 1
            slab.keys.clear()
            slab.live_rows = 0
            if slab.map is not None:
                slab.map.close()
                slab.map = None
            freed = slab.tail
            del self._slabs[slab.slab_id]
        try:
            os.unlink(slab.path)
        except OSError:
            pass
        return freed

    # ------------------------------------------------------------------
    # Store protocol (per-item fallback + batched extension)
    # ------------------------------------------------------------------
    def on_change(self, req, item: dict) -> None:
        """Store-protocol write(-behind): one item → a one-row batch."""
        self.put_batch([item])

    def put_batch(self, items: List[dict]) -> None:
        """Batched Store sink: one staged record per call."""
        if not items:
            return
        keys = [it["key"].encode() for it in items]
        cols = {
            f: np.asarray(
                [
                    it.get(f, 0) if f in ZOO_COLD_FIELDS else it[f]
                    for it in items
                ],
                _field_dtype(f),
            )
            for f in COLD_FIELDS
        }
        self.put_columns(keys, cols, now=0)

    def get(self, req) -> Optional[dict]:
        """Store-protocol read-through: peek one key (no removal)."""
        key = req.hash_key().encode()
        with self._lock:
            staged = self._staged.get(key)
            if staged is not None:
                bid, row = staged
                cols = self._pending[bid][1]
                return {
                    "key": key.decode(),
                    **{
                        f: (float if f == "remaining_f" else int)(
                            cols[f][row]
                        )
                        for f in COLD_FIELDS
                    },
                }
            ent = self._index.get(key)
            if ent is None:
                return None
            slab_id, offset, row, _expire = ent
            payload = self._read_payload(self._slabs[slab_id], offset)
        if payload is None:
            return None
        _, cols = _decode_batch(payload)
        return {
            "key": key.decode(),
            **{
                f: (float if f == "remaining_f" else int)(cols[f][row])
                for f in COLD_FIELDS
            },
        }

    def remove(self, key: str) -> None:
        self.remove_batch([key])

    def remove_batch(self, keys: List[str]) -> None:
        """Batched Store removal: tombstone index/staged entries (the
        on-disk rows become compactable garbage)."""
        with self._lock:
            for key_s in keys:
                key = key_s.encode()
                staged = self._staged.pop(key, None)
                if staged is not None:
                    self._pending[staged[0]][2].add(staged[1])
                    continue
                ent = self._index.pop(key, None)
                if ent is not None:
                    slab = self._slabs[ent[0]]
                    slab.live_rows -= 1
                    slab.keys.discard(key)

    # ------------------------------------------------------------------
    # Maintenance / introspection
    # ------------------------------------------------------------------
    def flush(self) -> None:
        """Block until every staged batch is on disk and indexed (test
        and shutdown barrier; serving never calls this)."""
        self._queue.join()

    def __len__(self) -> int:
        # _index and _staged are disjoint (staging pops the index entry).
        with self._lock:
            return len(self._index) + len(self._staged)

    def bytes_used(self) -> int:
        with self._lock:
            return sum(s.tail for s in self._slabs.values())

    def queue_depth(self) -> int:
        return self._queue.qsize()

    def stats(self) -> dict:
        with self._lock:
            return {
                "size": len(self._index) + len(self._staged),
                "bytes": sum(s.tail for s in self._slabs.values()),
                "slabs": len(self._slabs),
                "capacity_bytes": self.capacity_bytes,
                "demotions": self.metric_demotions,
                "promotions": self.metric_promotions,
                "hits": self.metric_hits,
                "misses": self.metric_misses,
                "expired": self.metric_expired,
                "lookup_calls": self.metric_lookup_calls,
                "write_batches": self.metric_write_batches,
                "backpressure": self.metric_backpressure,
                "compactions": self.metric_compactions,
                "slab_evictions": self.metric_slab_evictions,
                "corrupt_records": self.metric_corrupt_records,
                "queue_depth": self._queue.qsize(),
            }

    def close(self) -> None:
        """Stop the writer (draining the queue first), fsync, unmap."""
        if not self._running:
            return
        self._running = False
        self._queue.put(None)
        self._writer.join(timeout=10.0)
        for slab in list(self._slabs.values()):
            if slab.file is not None:
                slab.file.flush()
                try:
                    os.fsync(slab.file.fileno())
                except OSError:
                    pass
                slab.file.close()
                slab.file = None
            if slab.map is not None:
                slab.map.close()
                slab.map = None
