"""Host-side columnar cold store for demoted bucket state.

One tier below the device table: struct-of-arrays numpy columns (the
Loader v2 snapshot schema, ``engine.SNAP_FIELDS``) plus a host key map.
The engine demotes LRU victims here via the readback-then-evict path and
promotes misses back out in one batched restore scatter — so bucket
state (remaining / remaining_f / created_at / status) survives hot↔cold
cycling instead of evaporating with the evict scatter.

Bounds:

* **TTL** — entries whose ``expire_at`` has passed are dropped at
  lookup, at insert, and by :meth:`expire` sweeps (the reference's
  expired-on-read removal, lrucache.go:88-103, applied host-side).
* **Entry budget** — ``capacity`` caps live entries; inserting past it
  evicts the cold tier's own LRU tail (by a monotonic touch clock).
  Overflow victims optionally **write-behind** to the :class:`Store`
  protocol (``on_change`` with ``req=None`` — see store.py) so a third
  durability tier can absorb what the host tier sheds.

All operations are batched and vectorized over numpy columns; the only
per-key Python is the dict hop of the key map — the same cost profile
as the engine's host slot map.  Thread-safe: the engine's background
reclaimer demotes concurrently with serving-path promotes.
"""

from __future__ import annotations

import threading
from gubernator_tpu.utils import sanitize
from typing import Dict, List, Optional, Tuple

import numpy as np

# Field schema shared with the engine's columnar snapshot (the Loader v2
# wire format; engine.SNAP_FIELDS + engine.ZOO_SNAP_FIELDS).  Duplicated
# as a literal to keep this package importable without jax.  The trailing
# zoo columns (tat / prev_count, docs/algorithms.md) default to zero when
# a caller's column dict omits them (pre-zoo SSD slabs, legacy stores).
COLD_FIELDS = (
    "algorithm", "limit", "remaining", "remaining_f", "duration",
    "created_at", "updated_at", "burst", "status", "expire_at",
    "tat", "prev_count",
)

# The subset that legacy (pre-zoo) payloads may omit — decoders zero-fill
# these instead of failing (mirrors engine.ZOO_SNAP_FIELDS).
ZOO_COLD_FIELDS = ("tat", "prev_count")

_MIN_ALLOC = 256


class ColdStore:
    """Bounded host tier for evicted bucket rows (see module doc)."""

    def __init__(self, capacity: int, store=None):
        if capacity <= 0:
            raise ValueError("ColdStore capacity must be positive")
        self.capacity = int(capacity)
        # Optional write-behind sink (Store protocol): overflow evictions
        # flow to on_change(None, item); TTL-dropped entries to remove().
        self.store = store
        self._lock = sanitize.lock("ColdStore._lock")
        self._map: Dict[bytes, int] = {}
        self._keys: List[Optional[bytes]] = []
        self._free: List[int] = []
        self._alloc = 0
        self._cols: Dict[str, np.ndarray] = {}
        self._touch = np.zeros(0, np.int64)
        self._used = np.zeros(0, bool)
        self._clock = 0
        # Entries demoted since the last export — the cold half of the
        # engine's incremental-snapshot working set (export_columns
        # dirty_only).  Indices, not keys: released entries drop out.
        self._dirty: set = set()
        # Counters (mirrored into Prometheus by the service layer).
        self.metric_demotions = 0
        self.metric_promotions = 0
        self.metric_hits = 0
        self.metric_misses = 0
        self.metric_expired = 0
        self.metric_overflow_evictions = 0
        self.metric_write_behind = 0

    def __len__(self) -> int:
        return len(self._map)

    # ------------------------------------------------------------------
    # Storage management
    # ------------------------------------------------------------------
    def _grow(self, need: int) -> None:
        """Geometric array growth up to the entry budget (amortized O(1)
        per insert; a 10M-entry tier must not reallocate per demote)."""
        new_alloc = max(_MIN_ALLOC, self._alloc)
        while new_alloc < need:
            new_alloc *= 2
        new_alloc = min(new_alloc, max(self.capacity, _MIN_ALLOC))
        if new_alloc <= self._alloc:
            return
        for f in COLD_FIELDS:
            dt = np.float64 if f == "remaining_f" else np.int64
            col = np.zeros(new_alloc, dt)
            if self._alloc:
                col[: self._alloc] = self._cols[f]
            self._cols[f] = col
        for arr_name, fill in (("_touch", 0), ("_used", False)):
            old = getattr(self, arr_name)
            new = np.full(new_alloc, fill, old.dtype)
            new[: self._alloc] = old
            setattr(self, arr_name, new)
        self._keys.extend([None] * (new_alloc - self._alloc))
        self._free.extend(range(new_alloc - 1, self._alloc - 1, -1))
        self._alloc = new_alloc

    def _release(self, idx: np.ndarray) -> None:
        for i in idx:
            i = int(i)
            key = self._keys[i]
            if key is None:
                continue
            del self._map[key]
            self._keys[i] = None
            self._used[i] = False
            self._dirty.discard(i)
            self._free.append(i)

    @staticmethod
    def _cols_item(keys: List[bytes], cols: Dict[str, np.ndarray],
                   j: int) -> dict:
        return {
            "key": keys[j].decode(),
            **{
                f: (float if f == "remaining_f" else int)(cols[f][j])
                for f in COLD_FIELDS
            },
        }

    def _evict_overflow(
        self, want: int
    ) -> Tuple[List[bytes], Dict[str, np.ndarray]]:
        """Free ``want`` entries by the cold tier's own LRU (oldest touch
        clock).  Returns the victims as ``(keys, cols)`` copies when a
        write-behind sink is wired — the CALLER ships them to the sink
        after releasing ``self._lock``: sink I/O under the lock stalls
        every concurrent promote behind the sink's disk."""
        used = np.flatnonzero(self._used)
        n = min(want, len(used))
        if n <= 0:
            return [], {}
        if n >= len(used):
            victims = used
        else:
            # argpartition, not argsort: the tier can hold millions of
            # entries and overflow eviction rides the demote path.
            victims = used[np.argpartition(self._touch[used], n - 1)[:n]]
        self.metric_overflow_evictions += len(victims)
        keys: List[bytes] = []
        cols: Dict[str, np.ndarray] = {}
        if self.store is not None:
            keys = [self._keys[int(i)] for i in victims]
            cols = {f: self._cols[f][victims].copy() for f in COLD_FIELDS}
        self._release(victims)
        return keys, cols

    # ------------------------------------------------------------------
    # Write-behind sink dispatch (always OUTSIDE self._lock)
    # ------------------------------------------------------------------
    def _flush_shed(
        self,
        shed: List[Tuple[List[bytes], Dict[str, np.ndarray]]],
        now: int,
    ) -> None:
        """Ship overflow victims to the sink, one batched call per evict
        sweep: columnar ``put_columns`` (the SSD tier) > ``put_batch``
        (batched Store) > per-item ``on_change`` fallback."""
        if self.store is None:
            return
        for keys, cols in shed:
            if not keys:
                continue
            if hasattr(self.store, "put_columns"):
                self.store.put_columns(keys, cols, now)
            elif hasattr(self.store, "put_batch"):
                self.store.put_batch([
                    self._cols_item(keys, cols, j)
                    for j in range(len(keys))
                ])
            else:
                for j in range(len(keys)):
                    self.store.on_change(
                        None, self._cols_item(keys, cols, j)
                    )
            self.metric_write_behind += len(keys)

    def _sink_remove(self, keys: List[str]) -> None:
        """TTL-dropped keys leave the tiered cache entirely: batched
        sink removal (``remove_batch`` > per-key ``remove``)."""
        if self.store is None or not keys:
            return
        if hasattr(self.store, "remove_batch"):
            self.store.remove_batch(keys)
        else:
            for key in keys:
                self.store.remove(key)

    # ------------------------------------------------------------------
    # Demote (device → cold)
    # ------------------------------------------------------------------
    def put_columns(
        self, keys: List[bytes], cols: Dict[str, np.ndarray], now: int
    ) -> int:
        """Insert demoted rows (COLD_FIELDS columns, one row per key).

        Rows already TTL-expired are dropped (they're dead; resurrecting
        them would hand the next tenant stale state).  Existing keys are
        overwritten in place (the hot tier's copy is always newer).
        Returns the number of rows actually demoted."""
        if not keys:
            return 0
        missing = [f for f in COLD_FIELDS if f not in cols]
        if missing:
            # Legacy callers (pre-zoo slabs, old stores) omit the zoo
            # columns; zero is the safe restore (fresh window/TAT).
            zeros = np.zeros(len(keys), np.int64)
            cols = {**cols, **{f: zeros for f in missing}}
        expire = np.asarray(cols["expire_at"], np.int64)
        keep = expire >= now
        shed: List[Tuple[List[bytes], Dict[str, np.ndarray]]] = []
        with self._lock:
            self._clock += 1
            idx = np.empty(len(keys), np.int64)
            n_new = 0
            for j, key in enumerate(keys):
                if not keep[j]:
                    idx[j] = -1
                    continue
                i = self._map.get(key)
                if i is None:
                    n_new += 1
                    idx[j] = -2  # allocate below, after budget enforcement
                else:
                    idx[j] = i
            if n_new:
                shortfall = len(self._map) + n_new - self.capacity
                if shortfall > 0:
                    shed.append(self._evict_overflow(shortfall))
                self._grow(len(self._map) + n_new)
                for j, key in enumerate(keys):
                    if idx[j] != -2:
                        continue
                    if not self._free:
                        idx[j] = -1  # budget smaller than one demote batch
                        continue
                    i = self._free.pop()
                    self._map[key] = i
                    self._keys[i] = key
                    self._used[i] = True
                    idx[j] = i
            sel = np.flatnonzero(idx >= 0)
            if len(sel) > 0:
                dst = idx[sel]
                for f in COLD_FIELDS:
                    self._cols[f][dst] = np.asarray(cols[f])[sel]
                self._touch[dst] = self._clock
                self._dirty.update(int(i) for i in dst)
                self.metric_demotions += len(sel)
                # One demote batch can exceed the whole budget (a big
                # reclaim into a small tier): enforce it after the writes
                # too, so the excess write-behinds instead of silently
                # over-filling.
                over = len(self._map) - self.capacity
                if over > 0:
                    shed.append(self._evict_overflow(over))
        self._flush_shed(shed, now)
        return len(sel)

    # ------------------------------------------------------------------
    # Promote (cold → device)
    # ------------------------------------------------------------------
    def take(
        self, keys: List[bytes], now: int
    ) -> Tuple[np.ndarray, Dict[str, np.ndarray]]:
        """Look up + REMOVE a batch of keys (promotion is a move, not a
        copy: the hot tier becomes the owner; a stale cold copy would
        shadow newer state on the next demote).

        Returns ``(hit_positions, cols)``: positions into ``keys`` that
        hit, and the gathered COLD_FIELDS columns for exactly those
        positions (in hit order).  Expired entries count as misses and
        are dropped."""
        if not keys:
            return np.empty(0, np.int64), {}
        removed: List[str] = []
        with self._lock:
            self._clock += 1
            pos: List[int] = []
            idx: List[int] = []
            expired: List[int] = []
            for j, key in enumerate(keys):
                i = self._map.get(key)
                if i is None:
                    self.metric_misses += 1
                    continue
                if self._cols["expire_at"][i] < now:
                    expired.append(i)
                    self.metric_expired += 1
                    self.metric_misses += 1
                    continue
                pos.append(j)
                idx.append(i)
            if expired:
                # guber: allow-G001(host index build over python lists - the cold tier is host RAM, no device data anywhere in this method)
                exp = np.asarray(expired, np.int64)
                if self.store is not None:
                    removed = [self._keys[int(i)].decode() for i in exp]
                self._release(exp)
            if not idx:
                out_pos, out = np.empty(0, np.int64), {}
            else:
                # guber: allow-G001(host index build - see the expired branch above)
                src = np.asarray(idx, np.int64)
                out = {f: self._cols[f][src].copy() for f in COLD_FIELDS}
                self._release(src)
                self.metric_hits += len(idx)
                self.metric_promotions += len(idx)
                # guber: allow-G001(host index build - see the expired branch above)
                out_pos = np.asarray(pos, np.int64)
        self._sink_remove(removed)
        return out_pos, out

    # ------------------------------------------------------------------
    # Maintenance
    # ------------------------------------------------------------------
    def expire(self, now: int) -> int:
        """Vectorized TTL sweep: drop every entry whose ``expire_at`` has
        passed.  Cheap enough to ride the engine's reclaim cadence (one
        compare over the used columns, no per-key work until the rare
        release)."""
        removed: List[str] = []
        with self._lock:
            if self._alloc == 0:
                return 0
            dead = np.flatnonzero(self._used & (self._cols["expire_at"] < now))
            if len(dead) == 0:
                return 0
            self.metric_expired += len(dead)
            if self.store is not None:
                removed = [self._keys[int(i)].decode() for i in dead]
            self._release(dead)
        self._sink_remove(removed)
        return len(dead)

    def export_columns(
        self, dirty_only: bool = False
    ) -> Tuple[List[bytes], Dict[str, np.ndarray]]:
        """Snapshot the tier's (dirty) entries as (keys, COLD_FIELDS
        columns) — the cold half of the engine's columnar export: demoted
        state must survive a Loader save/restore cycle like hot state
        does.  Entries stay resident; the dirty set drains (like the
        engine's dirty-slot set, any export resets it)."""
        with self._lock:
            if self._alloc == 0:
                return [], {
                    f: np.zeros(
                        0, np.float64 if f == "remaining_f" else np.int64
                    )
                    for f in COLD_FIELDS
                }
            if dirty_only:
                idx = np.fromiter(self._dirty, np.int64, len(self._dirty))
                idx.sort()
            else:
                idx = np.flatnonzero(self._used)
            self._dirty.clear()
            keys = [self._keys[int(i)] for i in idx]
            return keys, {f: self._cols[f][idx].copy() for f in COLD_FIELDS}

    def stats(self) -> dict:
        with self._lock:
            return {
                "size": len(self._map),
                "capacity": self.capacity,
                "demotions": self.metric_demotions,
                "promotions": self.metric_promotions,
                "hits": self.metric_hits,
                "misses": self.metric_misses,
                "expired": self.metric_expired,
                "overflow_evictions": self.metric_overflow_evictions,
                "write_behind": self.metric_write_behind,
            }
