"""Decorrelated-jitter exponential backoff.

The "decorrelated jitter" variant from the AWS architecture blog
("Exponential Backoff And Jitter"): each delay is drawn uniformly from
``[base, prev * 3]`` and capped, so concurrent retriers spread out instead
of thundering in lockstep — the failure mode of both plain exponential
backoff (synchronized waves) and full jitter (too many immediate retries).
"""

from __future__ import annotations

import random
from typing import Optional


class DecorrelatedJitterBackoff:
    """``next()`` yields the next delay; ``reset()`` after a success."""

    def __init__(self, base: float, cap: float,
                 rng: Optional[random.Random] = None):
        if base <= 0:
            raise ValueError(f"backoff base must be > 0; got {base}")
        if cap < base:
            raise ValueError(f"backoff cap {cap} must be >= base {base}")
        self.base = base
        self.cap = cap
        self._rng = rng if rng is not None else random.Random()
        self._prev = base

    def next(self) -> float:
        d = min(self.cap, self._rng.uniform(self.base, self._prev * 3))
        self._prev = d
        return d

    def reset(self) -> None:
        self._prev = self.base
