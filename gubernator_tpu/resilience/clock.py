"""Virtual time for deterministic resilience tests.

The breaker, backoff, and fault injector all take an injectable ``clock``
(and, where they wait, an async ``sleep``); production passes
``time.monotonic``/``asyncio.sleep``, tests pass a :class:`ManualClock` so
open-duration expiry and injected delays advance instantly — the chaos
suite runs in tier-1 with no real sleeps.
"""

from __future__ import annotations

from typing import List


class ManualClock:
    """Monotonic clock that only moves when told to.

    Usable directly as a ``clock`` callable (``clock()`` → now) and as a
    ``sleep`` hook (``await clock.sleep(d)`` records ``d`` and advances
    time by it without ever yielding to the wall clock).
    """

    def __init__(self, start: float = 0.0):
        self._now = float(start)
        self.sleeps: List[float] = []  # every sleep duration, in order

    def __call__(self) -> float:
        return self._now

    def now(self) -> float:
        return self._now

    def advance(self, dt: float) -> None:
        self._now += dt

    async def sleep(self, dt: float) -> None:
        self.sleeps.append(dt)
        self._now += dt
