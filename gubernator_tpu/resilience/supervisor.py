"""Crash-proof wrapper for background loops.

A background loop that dies silently is worse than one that fails loudly:
a dead ``_hits_loop`` stops GLOBAL reconciliation forever while requests
keep being answered from increasingly stale local state.
:func:`spawn_supervised` wraps a loop coroutine so an unexpected exception
is logged, counted (``gubernator_loop_restarts``), and followed by a
restart after a short doubling delay — the loop is only ever *gone* when
it returns cleanly, is cancelled, or its owner says it should stop.
"""

from __future__ import annotations

import asyncio
import logging
import threading
import time
from typing import Awaitable, Callable, Optional

log = logging.getLogger("gubernator.resilience")


def spawn_supervised(
    factory: Callable[[], Awaitable[None]],
    *,
    name: str,
    should_restart: Callable[[], bool] = lambda: True,
    metrics=None,
    loop_label: Optional[str] = None,
    restart_delay: float = 0.01,
    max_delay: float = 1.0,
) -> asyncio.Task:
    """Run ``factory()`` as a task that restarts on crash.

    ``should_restart`` is consulted after every crash (owners pass their
    running/closed flag); ``metrics.loop_restarts`` (labeled
    ``loop=loop_label``) counts restarts when a registry is wired.
    """

    async def run() -> None:
        delay = restart_delay
        while True:
            try:
                await factory()
                return  # clean exit
            except asyncio.CancelledError:
                raise
            except Exception:
                if not should_restart():
                    return
                log.exception(
                    "background loop %r crashed; restarting in %.3fs",
                    name, delay,
                )
                if metrics is not None:
                    metrics.loop_restarts.labels(
                        loop=loop_label or name
                    ).inc()
                await asyncio.sleep(delay)
                delay = min(delay * 2, max_delay)

    return asyncio.create_task(run(), name=name)


def spawn_supervised_thread(
    target: Callable[[], None],
    *,
    name: str,
    should_restart: Callable[[], bool] = lambda: True,
    metrics=None,
    loop_label: Optional[str] = None,
    restart_delay: float = 0.01,
    max_delay: float = 1.0,
) -> threading.Thread:
    """Thread twin of :func:`spawn_supervised` for loops that must run
    off the event loop entirely (blocking file I/O: the SSD tier's slab
    writer).  Same contract: restart on crash with a doubling delay,
    gone only on clean return, ``should_restart()`` False, or process
    exit (the thread is a daemon).
    """

    def run() -> None:
        delay = restart_delay
        while True:
            try:
                target()
                return  # clean exit
            except Exception:
                if not should_restart():
                    return
                log.exception(
                    "background thread %r crashed; restarting in %.3fs",
                    name, delay,
                )
                if metrics is not None:
                    metrics.loop_restarts.labels(
                        loop=loop_label or name
                    ).inc()
                time.sleep(delay)
                delay = min(delay * 2, max_delay)

    thread = threading.Thread(target=run, name=name, daemon=True)
    thread.start()
    return thread
