"""Fault-tolerant peer path: breakers, backoff, redelivery, fault injection.

The peer path's failure story used to be "swallow and hope": failed GLOBAL
hit flushes and broadcasts were dropped, a crashed background loop stayed
dead, and forwarded requests retried in a tight fixed-count loop.  This
package holds the building blocks that replace that:

* :class:`CircuitBreaker` — per-peer closed/open/half-open breaker over a
  sliding failure window; an open breaker fails fast without dialing.
* :class:`DecorrelatedJitterBackoff` — AWS-style decorrelated jitter for
  forward retries and breaker open durations.
* :class:`FaultInjector` — seedable per-peer drop/delay/error/partition
  schedules for the chaos suite and staged game-days (``GUBER_FAULT_*``).
* :func:`spawn_supervised` — crash-proof wrapper for the background loops
  (GLOBAL hits, broadcast, peer batch): log, count, restart.
* :class:`ManualClock` — virtual time for tests (no real sleeps).

Wiring: ``PeerClient`` owns one breaker per peer and consults the injector
before every RPC; ``GlobalManager`` re-enqueues failed batches into its
bounded redelivery buffer; ``V1Instance._async_request`` retries with
backoff and degrades GLOBAL keys to the local non-owner answer when the
owner's breaker is open.  See docs/resilience.md.
"""

from __future__ import annotations

from dataclasses import dataclass

from gubernator_tpu.resilience.backoff import DecorrelatedJitterBackoff
from gubernator_tpu.resilience.breaker import (
    BreakerOpenError,
    BreakerState,
    CircuitBreaker,
)
from gubernator_tpu.resilience.clock import ManualClock
from gubernator_tpu.resilience.faults import FaultInjector, FaultSpec
from gubernator_tpu.resilience.supervisor import spawn_supervised


@dataclass
class ResilienceConfig:
    """Knobs for the fault-tolerant peer path (env surface ``GUBER_BREAKER_*``,
    ``GUBER_FORWARD_*``, ``GUBER_REDELIVERY_LIMIT``; see config.py)."""

    # Per-peer circuit breaker.
    breaker_enabled: bool = True
    breaker_failure_threshold: float = 0.5   # failure rate that trips
    breaker_min_requests: int = 5            # volume floor inside the window
    breaker_window: float = 10.0             # sliding window (seconds)
    breaker_open_for: float = 2.0            # base open duration (backoff base)
    breaker_open_cap: float = 30.0           # open-duration backoff cap
    breaker_half_open_probes: int = 1        # RPCs allowed through half-open

    # Forward retry loop (V1Instance._async_request).
    forward_max_attempts: int = 5
    forward_backoff_base: float = 0.005
    forward_backoff_cap: float = 0.1

    # GLOBAL redelivery buffer: max distinct keys held for re-flush after a
    # failed send/broadcast (beyond it, records drop and are counted).
    redelivery_limit: int = 10_000


__all__ = [
    "BreakerOpenError",
    "BreakerState",
    "CircuitBreaker",
    "DecorrelatedJitterBackoff",
    "FaultInjector",
    "FaultSpec",
    "ManualClock",
    "ResilienceConfig",
    "spawn_supervised",
]
