"""Per-peer circuit breaker: closed / open / half-open.

Standard three-state breaker over a sliding failure window:

* **CLOSED** — requests flow; outcomes are recorded into a time-bounded
  window.  When the window holds at least ``min_requests`` samples and the
  failure rate reaches ``failure_threshold``, the breaker trips.
* **OPEN** — requests fail fast (no dial) until the open duration elapses.
  The duration follows a decorrelated-jitter backoff (base ``open_for``,
  cap ``open_cap``) so a peer that keeps failing is probed progressively
  less often.
* **HALF_OPEN** — up to ``half_open_probes`` requests are allowed through
  as probes.  A probe success closes the breaker (window and backoff
  reset); a probe failure re-opens it with a longer duration.

The clock is injectable (tests pass :class:`ManualClock`), transitions
fire an optional callback (PeerClient exports them as Prometheus state /
transition families), and the window is a bounded deque so memory stays
O(1) per peer.
"""

from __future__ import annotations

import collections
import enum
import random
import time
from typing import Callable, Optional

from gubernator_tpu.resilience.backoff import DecorrelatedJitterBackoff

# Bound on the sliding window's sample count; failure *rate* needs only a
# representative recent sample, not every request ever made.
_MAX_WINDOW_SAMPLES = 256


class BreakerState(enum.IntEnum):
    # Gauge values for gubernator_breaker_state (docs/prometheus.md).
    CLOSED = 0
    HALF_OPEN = 1
    OPEN = 2


class BreakerOpenError(ConnectionError):
    """Raised without dialing when the peer's breaker is open."""


class CircuitBreaker:
    def __init__(
        self,
        *,
        failure_threshold: float = 0.5,
        min_requests: int = 5,
        window: float = 10.0,
        open_for: float = 2.0,
        open_cap: float = 30.0,
        half_open_probes: int = 1,
        enabled: bool = True,
        clock: Callable[[], float] = time.monotonic,
        rng: Optional[random.Random] = None,
        on_transition: Optional[
            Callable[[BreakerState, BreakerState], None]
        ] = None,
        name: str = "",
    ):
        if not 0.0 < failure_threshold <= 1.0:
            raise ValueError(
                f"failure_threshold must be in (0, 1]; got {failure_threshold}"
            )
        self.name = name
        self.enabled = enabled
        self.failure_threshold = failure_threshold
        self.min_requests = max(1, min_requests)
        self.window = window
        self.half_open_probes = max(1, half_open_probes)
        self._clock = clock
        self._backoff = DecorrelatedJitterBackoff(open_for, open_cap, rng=rng)
        self._on_transition = on_transition
        self._state = BreakerState.CLOSED
        self._open_until = 0.0
        self._probes = 0
        # (timestamp, ok) outcome samples inside the sliding window.
        self._events: collections.deque = collections.deque(
            maxlen=_MAX_WINDOW_SAMPLES
        )

    # ------------------------------------------------------------------
    @property
    def state(self) -> BreakerState:
        """Current state, promoting OPEN → HALF_OPEN when the open
        duration has elapsed (state reads drive the transition; there is
        no timer task)."""
        if (
            self._state is BreakerState.OPEN
            and self._clock() >= self._open_until
        ):
            self._transition(BreakerState.HALF_OPEN)
            self._probes = 0
        return self._state

    def is_open(self) -> bool:
        """Non-consuming fast-fail check (does not take a probe slot)."""
        return self.enabled and self.state is BreakerState.OPEN

    def allow(self) -> bool:
        """Whether one request may proceed right now.  In HALF_OPEN this
        *consumes* a probe slot — call it once per attempted RPC."""
        if not self.enabled:
            return True
        s = self.state
        if s is BreakerState.CLOSED:
            return True
        if s is BreakerState.OPEN:
            return False
        if self._probes < self.half_open_probes:
            self._probes += 1
            return True
        return False

    # ------------------------------------------------------------------
    def record_success(self) -> None:
        if not self.enabled:
            return
        if self._state is BreakerState.HALF_OPEN:
            # Probe succeeded: close and forget the failing past.
            self._events.clear()
            self._backoff.reset()
            self._transition(BreakerState.CLOSED)
            return
        self._events.append((self._clock(), True))
        self._prune()

    def record_failure(self) -> None:
        if not self.enabled:
            return
        if self._state is BreakerState.HALF_OPEN:
            self._trip()  # probe failed: back to OPEN, longer this time
            return
        if self._state is BreakerState.OPEN:
            return
        self._events.append((self._clock(), False))
        self._prune()
        total = len(self._events)
        if total < self.min_requests:
            return
        failures = sum(1 for _, ok in self._events if not ok)
        if failures / total >= self.failure_threshold:
            self._trip()

    def force_open(self, duration: Optional[float] = None) -> None:
        """Trip the breaker manually (tests, operator tooling)."""
        self._open_until = self._clock() + (
            duration if duration is not None else self._backoff.next()
        )
        self._events.clear()
        self._transition(BreakerState.OPEN)

    # ------------------------------------------------------------------
    def _trip(self) -> None:
        self._open_until = self._clock() + self._backoff.next()
        self._events.clear()
        self._transition(BreakerState.OPEN)

    def _prune(self) -> None:
        cutoff = self._clock() - self.window
        while self._events and self._events[0][0] < cutoff:
            self._events.popleft()

    def _transition(self, new: BreakerState) -> None:
        old, self._state = self._state, new
        if old is not new and self._on_transition is not None:
            self._on_transition(old, new)
