"""Seedable per-peer fault injection for the peer RPC path.

A :class:`FaultInjector` sits between ``PeerClient`` and its gRPC stub
(wired through ``InstanceConfig.fault_injector`` — a test/config hook, not
a hot-path feature): before every peer RPC the client awaits
``before_rpc(peer, method)``, which may delay the call, raise UNAVAILABLE
(``error``/``partition``), or raise DEADLINE_EXCEEDED (``drop`` — a
dropped RPC surfaces to the caller as its deadline expiring).  Faults are
keyed per peer address (``"*"`` matches every peer), draws come from a
seeded RNG so chaos runs replay exactly, and injected faults are counted
per (peer, kind) for test oracles.

WAN schedules (docs/federation.md): faults can additionally be keyed by
*direction* — ``set_fault(dest, from_peer=src, ...)`` applies only to
RPCs from ``src`` to ``dest``, leaving the reverse path clean (the
asymmetric-partition scenario where region A can reach B but B's acks
never come back).  A schedule can also *flap* (``flap_interval``):
it is active only during alternating windows of that length on the
injector clock, modelling a link that comes and goes.

The env surface (``GUBER_FAULT_*``, see :meth:`FaultInjector.from_env`)
lets an operator stage the same schedules in a real deployment.
"""

from __future__ import annotations

import asyncio
import collections
import random
import time
from dataclasses import dataclass
from typing import Callable, Dict, Optional, Tuple

import grpc
import grpc.aio


def rpc_error(code: grpc.StatusCode, details: str) -> grpc.aio.AioRpcError:
    """A real AioRpcError (so retry/breaker paths can't tell it from the
    wire) carrying the injected status."""
    return grpc.aio.AioRpcError(
        code,
        grpc.aio.Metadata(),
        grpc.aio.Metadata(),
        details=details,
        debug_error_string="fault-injected",
    )


@dataclass
class FaultSpec:
    """One peer's fault schedule.  Rates are probabilities per RPC."""

    error_rate: float = 0.0      # UNAVAILABLE with this probability
    drop_rate: float = 0.0       # DEADLINE_EXCEEDED with this probability
    delay: float = 0.0           # fixed latency added before the RPC
    partition: bool = False      # unconditional UNAVAILABLE (100% failure)
    methods: Tuple[str, ...] = ()  # restrict to these RPCs; empty = all
    # Link flap: 0 = always active; > 0 = active only during alternating
    # windows of this many (injector-clock) seconds, starting active at
    # install time.
    flap_interval: float = 0.0

    def matches(self, method: str) -> bool:
        return not self.methods or method in self.methods


class FaultInjector:
    """Per-peer fault schedules with a seeded RNG and virtual-clock hooks."""

    def __init__(
        self,
        seed: int = 0,
        clock: Callable[[], float] = time.monotonic,
        sleep=asyncio.sleep,
    ):
        self._rng = random.Random(seed)
        self._clock = clock
        self._sleep = sleep
        self._faults: Dict[str, FaultSpec] = {}
        # Directional schedules: (dest, src) → spec, consulted before the
        # per-dest and "*" entries so one direction of a pair can fail
        # while the reverse stays clean.
        self._directional: Dict[Tuple[str, str], FaultSpec] = {}
        # spec id → install time, for flap-window phase.
        self._installed_at: Dict[int, float] = {}
        # (peer, kind) → count; kind in {"error", "drop", "delay"}.
        self.injected: collections.Counter = collections.Counter()

    # ------------------------------------------------------------------
    def set_fault(self, peer: str = "*", from_peer: Optional[str] = None,
                  **spec) -> FaultSpec:
        """Install/replace the schedule for ``peer`` (``"*"`` = every peer);
        pass FaultSpec fields as kwargs, or a prebuilt ``spec=FaultSpec``.
        With ``from_peer`` the schedule is directional: it applies only to
        RPCs whose caller identifies as ``from_peer`` (PeerClient passes
        its own advertise address), leaving the reverse direction — and
        every other caller — untouched."""
        prebuilt = spec.pop("spec", None)
        built = prebuilt if prebuilt is not None else FaultSpec(**spec)
        if from_peer is not None:
            self._directional[(peer, from_peer)] = built
        else:
            self._faults[peer] = built
        self._installed_at[id(built)] = self._clock()
        return built

    def clear(self, peer: Optional[str] = None) -> None:
        if peer is None:
            self._faults.clear()
            self._directional.clear()
            self._installed_at.clear()
        else:
            self._faults.pop(peer, None)
            for k in [k for k in self._directional if k[0] == peer]:
                del self._directional[k]

    def spec_for(self, peer: str, from_peer: str = "") -> Optional[FaultSpec]:
        """The schedule governing an RPC to ``peer`` from ``from_peer``:
        directional match first, then per-dest, then the wildcard."""
        if from_peer:
            spec = self._directional.get((peer, from_peer))
            if spec is not None:
                return spec
        return self._faults.get(peer) or self._faults.get("*")

    def _flap_active(self, spec: FaultSpec) -> bool:
        """True when the schedule is currently live: always for
        non-flapping specs; for flapping ones, during even-numbered
        windows of ``flap_interval`` since install."""
        if spec.flap_interval <= 0:
            return True
        t0 = self._installed_at.get(id(spec), 0.0)
        elapsed = self._clock() - t0
        return int(elapsed / spec.flap_interval) % 2 == 0

    # ------------------------------------------------------------------
    async def before_rpc(self, peer: str, method: str,
                         from_peer: str = "") -> None:
        """Apply ``peer``'s schedule to one outgoing RPC: maybe delay,
        maybe raise.  A no-op when no schedule matches."""
        spec = self.spec_for(peer, from_peer)
        if spec is None or not spec.matches(method):
            return
        if not self._flap_active(spec):
            return
        if spec.delay > 0:
            self.injected[(peer, "delay")] += 1
            await self._sleep(spec.delay)
        if spec.partition or (
            spec.error_rate > 0 and self._rng.random() < spec.error_rate
        ):
            self.injected[(peer, "error")] += 1
            raise rpc_error(
                grpc.StatusCode.UNAVAILABLE,
                f"injected fault: peer {peer} unavailable",
            )
        if spec.drop_rate > 0 and self._rng.random() < spec.drop_rate:
            self.injected[(peer, "drop")] += 1
            raise rpc_error(
                grpc.StatusCode.DEADLINE_EXCEEDED,
                f"injected fault: RPC to peer {peer} dropped",
            )

    # ------------------------------------------------------------------
    @classmethod
    def from_env(cls, reader) -> Optional["FaultInjector"]:
        """Build an injector from ``GUBER_FAULT_*`` (config.py EnvReader);
        None unless ``GUBER_FAULT_PEERS`` names at least one target.

        GUBER_FAULT_PEERS       comma list of peer addresses, or "*"
        GUBER_FAULT_ERROR_RATE  probability of UNAVAILABLE per RPC
        GUBER_FAULT_DROP_RATE   probability of DEADLINE_EXCEEDED per RPC
        GUBER_FAULT_DELAY       added latency (Go-style duration)
        GUBER_FAULT_PARTITION   bool: 100% UNAVAILABLE
        GUBER_FAULT_SEED        RNG seed (default 0)
        """
        peers = reader.list_("GUBER_FAULT_PEERS")
        if not peers:
            return None
        inj = cls(seed=reader.int_("GUBER_FAULT_SEED", 0))
        spec = FaultSpec(
            error_rate=float(reader.str_("GUBER_FAULT_ERROR_RATE", "0") or 0),
            drop_rate=float(reader.str_("GUBER_FAULT_DROP_RATE", "0") or 0),
            delay=reader.float_seconds("GUBER_FAULT_DELAY", 0.0),
            partition=reader.bool_("GUBER_FAULT_PARTITION"),
        )
        for p in peers:
            inj._faults[p] = spec
        return inj
