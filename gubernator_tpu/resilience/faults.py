"""Seedable per-peer fault injection for the peer RPC path.

A :class:`FaultInjector` sits between ``PeerClient`` and its gRPC stub
(wired through ``InstanceConfig.fault_injector`` — a test/config hook, not
a hot-path feature): before every peer RPC the client awaits
``before_rpc(peer, method)``, which may delay the call, raise UNAVAILABLE
(``error``/``partition``), or raise DEADLINE_EXCEEDED (``drop`` — a
dropped RPC surfaces to the caller as its deadline expiring).  Faults are
keyed per peer address (``"*"`` matches every peer), draws come from a
seeded RNG so chaos runs replay exactly, and injected faults are counted
per (peer, kind) for test oracles.

The env surface (``GUBER_FAULT_*``, see :meth:`FaultInjector.from_env`)
lets an operator stage the same schedules in a real deployment.
"""

from __future__ import annotations

import asyncio
import collections
import random
import time
from dataclasses import dataclass
from typing import Callable, Dict, Optional, Tuple

import grpc
import grpc.aio


def rpc_error(code: grpc.StatusCode, details: str) -> grpc.aio.AioRpcError:
    """A real AioRpcError (so retry/breaker paths can't tell it from the
    wire) carrying the injected status."""
    return grpc.aio.AioRpcError(
        code,
        grpc.aio.Metadata(),
        grpc.aio.Metadata(),
        details=details,
        debug_error_string="fault-injected",
    )


@dataclass
class FaultSpec:
    """One peer's fault schedule.  Rates are probabilities per RPC."""

    error_rate: float = 0.0      # UNAVAILABLE with this probability
    drop_rate: float = 0.0       # DEADLINE_EXCEEDED with this probability
    delay: float = 0.0           # fixed latency added before the RPC
    partition: bool = False      # unconditional UNAVAILABLE (100% failure)
    methods: Tuple[str, ...] = ()  # restrict to these RPCs; empty = all

    def matches(self, method: str) -> bool:
        return not self.methods or method in self.methods


class FaultInjector:
    """Per-peer fault schedules with a seeded RNG and virtual-clock hooks."""

    def __init__(
        self,
        seed: int = 0,
        clock: Callable[[], float] = time.monotonic,
        sleep=asyncio.sleep,
    ):
        self._rng = random.Random(seed)
        self._clock = clock
        self._sleep = sleep
        self._faults: Dict[str, FaultSpec] = {}
        # (peer, kind) → count; kind in {"error", "drop", "delay"}.
        self.injected: collections.Counter = collections.Counter()

    # ------------------------------------------------------------------
    def set_fault(self, peer: str = "*", **spec) -> FaultSpec:
        """Install/replace the schedule for ``peer`` (``"*"`` = every peer);
        pass FaultSpec fields as kwargs, or a prebuilt ``spec=FaultSpec``."""
        prebuilt = spec.pop("spec", None)
        self._faults[peer] = prebuilt if prebuilt is not None else FaultSpec(**spec)
        return self._faults[peer]

    def clear(self, peer: Optional[str] = None) -> None:
        if peer is None:
            self._faults.clear()
        else:
            self._faults.pop(peer, None)

    def spec_for(self, peer: str) -> Optional[FaultSpec]:
        return self._faults.get(peer) or self._faults.get("*")

    # ------------------------------------------------------------------
    async def before_rpc(self, peer: str, method: str) -> None:
        """Apply ``peer``'s schedule to one outgoing RPC: maybe delay,
        maybe raise.  A no-op when no schedule matches."""
        spec = self.spec_for(peer)
        if spec is None or not spec.matches(method):
            return
        if spec.delay > 0:
            self.injected[(peer, "delay")] += 1
            await self._sleep(spec.delay)
        if spec.partition or (
            spec.error_rate > 0 and self._rng.random() < spec.error_rate
        ):
            self.injected[(peer, "error")] += 1
            raise rpc_error(
                grpc.StatusCode.UNAVAILABLE,
                f"injected fault: peer {peer} unavailable",
            )
        if spec.drop_rate > 0 and self._rng.random() < spec.drop_rate:
            self.injected[(peer, "drop")] += 1
            raise rpc_error(
                grpc.StatusCode.DEADLINE_EXCEEDED,
                f"injected fault: RPC to peer {peer} dropped",
            )

    # ------------------------------------------------------------------
    @classmethod
    def from_env(cls, reader) -> Optional["FaultInjector"]:
        """Build an injector from ``GUBER_FAULT_*`` (config.py EnvReader);
        None unless ``GUBER_FAULT_PEERS`` names at least one target.

        GUBER_FAULT_PEERS       comma list of peer addresses, or "*"
        GUBER_FAULT_ERROR_RATE  probability of UNAVAILABLE per RPC
        GUBER_FAULT_DROP_RATE   probability of DEADLINE_EXCEEDED per RPC
        GUBER_FAULT_DELAY       added latency (Go-style duration)
        GUBER_FAULT_PARTITION   bool: 100% UNAVAILABLE
        GUBER_FAULT_SEED        RNG seed (default 0)
        """
        peers = reader.list_("GUBER_FAULT_PEERS")
        if not peers:
            return None
        inj = cls(seed=reader.int_("GUBER_FAULT_SEED", 0))
        spec = FaultSpec(
            error_rate=float(reader.str_("GUBER_FAULT_ERROR_RATE", "0") or 0),
            drop_rate=float(reader.str_("GUBER_FAULT_DROP_RATE", "0") or 0),
            delay=reader.float_seconds("GUBER_FAULT_DELAY", 0.0),
            partition=reader.bool_("GUBER_FAULT_PARTITION"),
        )
        for p in peers:
            inj._faults[p] = spec
        return inj
