"""Controller inputs: one immutable sample of the telemetry plane.

Every field comes from a cheap public snapshot accessor — the PR 9
admission plane (:meth:`TickLoop.admission_snapshot`), the PR 8 flight
recorder (:meth:`FlightRecorder.snapshot`), the PR 2 tier occupancy
(:meth:`V1Instance.occupancy`), and the PR 14 reshard coordinator — not
from private fields and not from parsing ``/metrics``.  Sampling runs on
the controller's cadence (seconds), never on the tick path, so nothing
here is ``@hot_path``.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict


@dataclass(frozen=True)
class SignalSnapshot:
    """One controller observation window."""

    ts: float = 0.0                 # controller-clock sample time
    window_limit: int = 0           # AIMD admitted window width
    queue_depth: int = 0            # admission queue depth, in requests
    shed_total: int = 0             # cumulative admission sheds
    p50_ms: float = 0.0             # whole-window p50 (flight recorder)
    p99_ms: float = 0.0             # whole-window p99 (flight recorder)
    stage_p99_ms: Dict[str, float] = field(default_factory=dict)
    hot_occupancy: float = 0.0      # device-table fill fraction [0, 1]
    cold_size: int = 0              # cold-tier resident rows
    shards: int = 1                 # current mesh shard count
    breaker_open: bool = False      # any peer breaker open right now
    reshard_busy: bool = False      # a transition already holds the lock
    frozen: bool = False            # admission frozen (cutover window)


def instance_sampler(instance, clock) -> Callable[[], "SignalSnapshot"]:
    """Build the production sampler over a :class:`V1Instance`.

    The flight recorder is optional (installed only under
    ``GUBER_DEBUG_ENDPOINTS`` or the slow-window watchdog); without one
    the latency fields read 0.0 and the policy can still scale on queue
    depth and occupancy.  Tests bypass this entirely and hand the
    controller a fake sampler.
    """
    from gubernator_tpu.utils import flightrec

    def sample() -> SignalSnapshot:
        adm = instance.tick_loop.admission_snapshot()
        occ = instance.occupancy()
        rec = flightrec.get()
        p50 = p99 = 0.0
        stage_p99: Dict[str, float] = {}
        if rec is not None:
            fr = rec.snapshot()
            p50 = fr["total"]["p50_ms"]
            p99 = fr["total"]["p99_ms"]
            stage_p99 = {s: v["p99_ms"] for s, v in fr["stages"].items()}
        coord = instance.reshard_coord
        return SignalSnapshot(
            ts=clock(),
            window_limit=adm["limiter"]["window_limit"],
            queue_depth=adm["queue"]["requests"],
            shed_total=sum(adm["shed"].values()),
            p50_ms=p50,
            p99_ms=p99,
            stage_p99_ms=stage_p99,
            hot_occupancy=occ["hot_occupancy"],
            cold_size=occ["cold_size"],
            shards=int(coord.status()["shards"]),
            breaker_open=any(
                p.breaker.is_open() for p in instance.get_peer_list()),
            reshard_busy=coord.is_busy(),
            frozen=adm["frozen"],
        )

    return sample
