"""Guardrailed telemetry-driven autoscaler (docs/autoscaling.md).

Closes the loop over live resharding (docs/resharding.md): a supervised
controller samples the admission/latency/occupancy telemetry the system
already produces, feeds it through a sustained-window policy with
non-overlapping hysteresis bands, and drives ``Instance.reshard()``
through hard guardrails — per-direction cooldowns, a rolling-hour flap
suppressor, abort-on-open-breaker, abort-on-reshard-busy, and a dry-run
mode that records every decision without acting.  Every decision lands
in a bounded ring (``/debug/autoscaler``) and in the
``gubernator_tpu_autoscale_*`` counter families, so a misbehaving
controller is diagnosable from the outside.
"""

from __future__ import annotations

from gubernator_tpu.autoscale.controller import Autoscaler, Decision
from gubernator_tpu.autoscale.policy import AutoscalePolicy, PolicyConfig
from gubernator_tpu.autoscale.signals import SignalSnapshot, instance_sampler

__all__ = [
    "Autoscaler",
    "AutoscalePolicy",
    "Decision",
    "PolicyConfig",
    "SignalSnapshot",
    "instance_sampler",
]
