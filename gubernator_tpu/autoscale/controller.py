"""The controller: sample → policy → guardrails → actuate, supervised.

Separation of duties: :mod:`signals` observes, :mod:`policy` proposes a
direction, and this module *disposes* — every proposal runs a guardrail
chain before it may touch :meth:`Instance.reshard`:

``breaker_open``
    An open peer breaker means the cluster is already degraded; a
    freeze/cutover on top of that turns a brownout into an outage.
``reshard_busy``
    A transition is already holding the coordinator lock (checked from
    the sampled snapshot AND from the actuation result — the
    coordinator's ``BUSY_RESULT`` dict is the single source of truth,
    so the autoscaler and the admin endpoint can never double-freeze).
``cooldown_up`` / ``cooldown_down``
    Per-direction quiet period measured from the last actuation in
    either direction: scale-up re-arms fast (load is real), scale-down
    re-arms slow (giving back capacity is never urgent).
``flap_cap``
    Rolling-hour ceiling on actuations — a controller that wants to
    transition more than ``max_per_hour`` times is reacting to noise,
    and every transition costs a freeze window.

Every decision — act, hold, or veto with the guardrail that fired —
lands in a bounded ring (``/debug/autoscaler``) and increments
``gubernator_tpu_autoscale_{decisions,transitions,vetoes}``.  ``dry_run``
(the default) runs the full chain and records the act decision without
calling the executor: stare at the ring for a day before arming it.
"""

from __future__ import annotations

import asyncio
import inspect
import logging
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Optional

from gubernator_tpu.autoscale.policy import DOWN, UP, AutoscalePolicy
from gubernator_tpu.autoscale.signals import SignalSnapshot
from gubernator_tpu.resilience import spawn_supervised

log = logging.getLogger("gubernator.autoscale")

ACT = "act"
HOLD = "hold"
VETO = "veto"

FLAP_WINDOW_S = 3600.0  # the "rolling hour" of the flap suppressor


@dataclass
class Decision:
    """One ring entry: what the controller did and why."""

    ts: float
    action: str                     # act | hold | veto
    reason: str                     # guardrail / policy explanation
    direction: str = ""             # up | down | "" (hold with no signal)
    from_shards: int = 0
    to_shards: int = 0
    dry_run: bool = False
    outcome: str = ""               # committed | aborted | noop | ""
    signals: dict = field(default_factory=dict)

    def as_dict(self) -> dict:
        return {
            "ts": round(self.ts, 3),
            "action": self.action,
            "reason": self.reason,
            "direction": self.direction,
            "from_shards": self.from_shards,
            "to_shards": self.to_shards,
            "dry_run": self.dry_run,
            "outcome": self.outcome,
            "signals": dict(self.signals),
        }


class Autoscaler:
    """Supervised controller loop over a sampler and a reshard executor.

    * ``sample`` — zero-arg callable returning a
      :class:`SignalSnapshot` (production: :func:`instance_sampler`;
      tests: any fake).
    * ``reshard`` — callable taking the target shard count and
      returning the coordinator outcome dict (``{"result": "busy"}``
      for a concurrent transition).  May be sync or async; production
      passes ``Instance.reshard``.
    * ``clock``/``sleep`` — injectable time (tests pass a
      :class:`~gubernator_tpu.resilience.ManualClock`).
    """

    def __init__(
        self,
        sample: Callable[[], SignalSnapshot],
        reshard: Callable[[int], object],
        *,
        policy: Optional[AutoscalePolicy] = None,
        interval: float = 10.0,
        cooldown_up: float = 60.0,
        cooldown_down: float = 300.0,
        max_per_hour: int = 4,
        dry_run: bool = True,
        ring_size: int = 256,
        metrics=None,
        clock=time.monotonic,
        sleep=asyncio.sleep,
    ):
        self.sample = sample
        self.reshard = reshard
        self.policy = policy or AutoscalePolicy()
        self.interval = float(interval)
        self.cooldown = {UP: float(cooldown_up), DOWN: float(cooldown_down)}
        self.max_per_hour = int(max_per_hour)
        self.dry_run = bool(dry_run)
        self.metrics = metrics
        self._clock = clock
        self._sleep = sleep
        self.ring: deque = deque(maxlen=max(1, int(ring_size)))
        self._actuations: deque = deque()   # timestamps, rolling hour
        self._last_actuation: Optional[float] = None
        self._running = False
        self._task: Optional[asyncio.Task] = None

    # ------------------------------------------------------------------
    # Lifecycle
    # ------------------------------------------------------------------
    def start(self) -> None:
        """Spawn the supervised sampling loop on the running event loop."""
        self._running = True
        self._task = spawn_supervised(
            self._loop, name="autoscaler",
            should_restart=lambda: self._running,
            metrics=self.metrics, loop_label="autoscale",
        )

    async def stop(self) -> None:
        self._running = False
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            except Exception:
                pass
            self._task = None

    async def _loop(self) -> None:
        while self._running:
            await self._sleep(self.interval)
            if not self._running:
                return
            await self.step()

    # ------------------------------------------------------------------
    # One control decision
    # ------------------------------------------------------------------
    async def step(self) -> Decision:
        """Sample once, decide once.  Never raises: an executor failure
        is a recorded veto, not a dead control loop."""
        snap = self.sample()
        now = self._clock()
        direction = self.policy.observe(snap)
        if direction is None:
            return self._record(Decision(
                ts=now, action=HOLD, reason="no_sustained_pressure",
                from_shards=snap.shards, to_shards=snap.shards,
                signals=self._sig(snap),
            ))
        target = self.policy.target_shards(snap.shards, direction)
        if target == snap.shards:
            return self._record(Decision(
                ts=now, action=HOLD, reason="at_bound", direction=direction,
                from_shards=snap.shards, to_shards=target,
                signals=self._sig(snap),
            ))
        veto = self._guardrail(snap, direction, now)
        if veto is not None:
            return self._record(Decision(
                ts=now, action=VETO, reason=veto, direction=direction,
                from_shards=snap.shards, to_shards=target,
                signals=self._sig(snap),
            ))
        if self.dry_run:
            # The act decision is recorded (the rollout story: watch the
            # ring agree with your intuition for a day), nothing moves,
            # and no cooldown/flap state is consumed.
            return self._record(Decision(
                ts=now, action=ACT, reason="policy", direction=direction,
                from_shards=snap.shards, to_shards=target, dry_run=True,
                outcome="dry_run", signals=self._sig(snap),
            ))
        return await self._actuate(snap, direction, target, now)

    def _guardrail(self, snap: SignalSnapshot, direction: str,
                   now: float) -> Optional[str]:
        """First guardrail that objects wins; None means clear to act."""
        if snap.breaker_open:
            return "breaker_open"
        if snap.reshard_busy:
            return "reshard_busy"
        if self._last_actuation is not None and \
                now - self._last_actuation < self.cooldown[direction]:
            return f"cooldown_{direction}"
        while self._actuations and now - self._actuations[0] > FLAP_WINDOW_S:
            self._actuations.popleft()
        if len(self._actuations) >= self.max_per_hour:
            return "flap_cap"
        return None

    async def _actuate(self, snap: SignalSnapshot, direction: str,
                       target: int, now: float) -> Decision:
        try:
            res = self.reshard(target)
            if inspect.isawaitable(res):
                res = await res
        except Exception as e:
            log.warning("autoscale reshard %d -> %d failed: %s",
                        snap.shards, target, e)
            return self._record(Decision(
                ts=now, action=VETO, reason="reshard_error",
                direction=direction, from_shards=snap.shards,
                to_shards=target, signals=self._sig(snap),
            ))
        if isinstance(res, dict) and res.get("result") == "busy":
            # Lost the race to the admin endpoint between sample and
            # call — the coordinator's lock, not ours, is authoritative.
            return self._record(Decision(
                ts=now, action=VETO, reason="reshard_busy",
                direction=direction, from_shards=snap.shards,
                to_shards=target, signals=self._sig(snap),
            ))
        # Any real actuation — committed or aborted — consumed a freeze
        # window, so both charge the cooldowns and the flap budget.
        self._last_actuation = now
        self._actuations.append(now)
        self.policy.reset()
        outcome = res.get("outcome", "") if isinstance(res, dict) else ""
        if outcome == "committed" and self.metrics is not None:
            self.metrics.autoscale_transitions.labels(
                direction=direction).inc()
        log.info("autoscale %s: %d -> %d shards (%s)",
                 direction, snap.shards, target, outcome or "done")
        return self._record(Decision(
            ts=now, action=ACT, reason="policy", direction=direction,
            from_shards=snap.shards, to_shards=target, outcome=outcome,
            signals=self._sig(snap),
        ))

    # ------------------------------------------------------------------
    # Bookkeeping / introspection
    # ------------------------------------------------------------------
    def _record(self, d: Decision) -> Decision:
        self.ring.append(d)
        if self.metrics is not None:
            self.metrics.autoscale_decisions.labels(action=d.action).inc()
            if d.action == VETO:
                self.metrics.autoscale_vetoes.labels(reason=d.reason).inc()
        return d

    @staticmethod
    def _sig(snap: SignalSnapshot) -> dict:
        """The compact signal summary kept per ring entry."""
        return {
            "p99_ms": snap.p99_ms,
            "queue_depth": snap.queue_depth,
            "hot_occupancy": snap.hot_occupancy,
            "window_limit": snap.window_limit,
        }

    def transitions_last_hour(self, now: Optional[float] = None) -> int:
        now = self._clock() if now is None else now
        return sum(1 for t in self._actuations if now - t <= FLAP_WINDOW_S)

    def debug_state(self) -> dict:
        """The /debug/autoscaler body: config, streaks, and the ring
        (oldest first)."""
        c = self.policy.conf
        return {
            "running": self._running,
            "dry_run": self.dry_run,
            "interval_s": self.interval,
            "policy": {
                "windows": c.windows,
                "target_p99_ms": c.target_p99_ms,
                "queue_high": c.queue_high,
                "hysteresis": c.hysteresis,
                "occupancy_low": c.occupancy_low,
                "min_shards": c.min_shards,
                "max_shards": c.max_shards,
            },
            "cooldown_s": {"up": self.cooldown[UP], "down": self.cooldown[DOWN]},
            "max_per_hour": self.max_per_hour,
            "streaks": self.policy.streaks,
            "transitions_last_hour": self.transitions_last_hour(),
            "last_decision": self.ring[-1].as_dict() if self.ring else None,
            "decisions": [d.as_dict() for d in self.ring],
        }
