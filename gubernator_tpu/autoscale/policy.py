"""Sustained-window scaling policy with non-overlapping hysteresis bands.

The policy answers exactly one question per sample: does the evidence
*sustained over N consecutive windows* justify a direction?  Scale-up
pressure is p99 over target OR queue depth over high-water (either one
means the current layout is the bottleneck); scale-down pressure is low
hot-table occupancy AND p99 under ``target × hysteresis`` (capacity is
idle and there is latency headroom).  Because ``hysteresis < 1`` is
validated at config load, the up band (``p99 > target``) and the down
band (``p99 < target × hysteresis``) can never overlap — a p99 sitting
between them is a hold, which is what kills ping-pong at its source
(per the Pulsar playbook: react to the sustained bottleneck, not the
noise).  Targets move one power of two at a time (double up, halve
down), clamped to ``[min_shards, max_shards]``.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from gubernator_tpu.autoscale.signals import SignalSnapshot

UP = "up"
DOWN = "down"


@dataclass
class PolicyConfig:
    """Env surface ``GUBER_AUTOSCALE_*`` (config.py validates)."""

    windows: int = 3                # consecutive samples before acting
    target_p99_ms: float = 5.0      # scale-up latency threshold
    queue_high: int = 1000          # scale-up queue-depth high-water
    hysteresis: float = 0.5         # down band = target × this (< 1)
    occupancy_low: float = 0.3      # scale-down occupancy threshold
    min_shards: int = 1
    max_shards: int = 8


class AutoscalePolicy:
    """Streak-counting policy: one :meth:`observe` per sample."""

    def __init__(self, conf: Optional[PolicyConfig] = None):
        self.conf = conf or PolicyConfig()
        self._up_streak = 0
        self._down_streak = 0

    @property
    def streaks(self) -> dict:
        return {"up": self._up_streak, "down": self._down_streak}

    def observe(self, snap: SignalSnapshot) -> Optional[str]:
        """Feed one sample; returns ``UP``/``DOWN`` when the pressure
        has been sustained for ``windows`` consecutive samples, else
        None (a single spike is a hold by construction).  Samples taken
        while admission is frozen (a cutover in flight) are skipped
        entirely — a freeze inflates queue depth and p99 for reasons
        the controller itself caused."""
        c = self.conf
        if snap.frozen:
            return None
        up = (c.target_p99_ms > 0 and snap.p99_ms > c.target_p99_ms) or \
            snap.queue_depth > c.queue_high
        down = (
            snap.hot_occupancy < c.occupancy_low
            and snap.p99_ms < c.target_p99_ms * c.hysteresis
        )
        if up:
            self._up_streak += 1
            self._down_streak = 0
        elif down:
            self._down_streak += 1
            self._up_streak = 0
        else:
            self._up_streak = 0
            self._down_streak = 0
        if self._up_streak >= c.windows:
            return UP
        if self._down_streak >= c.windows:
            return DOWN
        return None

    def reset(self) -> None:
        """Clear both streaks (called after an actuated transition so
        the next decision re-earns its N windows on the new layout)."""
        self._up_streak = 0
        self._down_streak = 0

    def target_shards(self, current: int, direction: str) -> int:
        """Next shard count: double up / halve down, clamped."""
        c = self.conf
        cur = max(1, int(current))
        if direction == UP:
            return min(c.max_shards, cur * 2)
        return max(c.min_shards, cur // 2 or 1)
