"""guberlint framework: findings, rules, suppressions, baseline.

The shape is deliberately small: a :class:`Project` is every ``*.py``
file of one package parsed once (AST + real comment tokens), a
:class:`Rule` is a callable over the project returning :class:`Finding`
rows, and :func:`run_project` subtracts inline suppressions and the
checked-in baseline from the union of all rule output.  Everything is
stdlib — rules must never import the modules they inspect (the linter
has to run on hosts with no jax toolchain, and importing the serving
code would drag the device stack in).
"""

from __future__ import annotations

import ast
import hashlib
import io
import json
import os
import re
import tokenize
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterable, List, Optional, Tuple

# ``# guber: allow-G001(reason)`` — the reason is part of the syntax, not
# decoration: a suppression with an empty reason does not suppress.  The
# rule id is case-insensitive (allow-g009 == allow-G009).
SUPPRESS_RE = re.compile(r"#\s*guber:\s*allow-([Gg]\d{3})\(([^()]*)\)")


@dataclass(frozen=True)
class Finding:
    """One rule violation at one source location."""

    rule: str          # "G001".."G006"
    path: str          # project-root-relative, posix separators
    line: int          # 1-indexed
    message: str
    fix_hint: str = ""

    def fingerprint(self, source_line: str = "") -> str:
        """Line-drift-tolerant identity for baseline matching: the rule,
        the file, and the stripped text of the offending line — NOT the
        line number, so unrelated edits above don't invalidate the
        baseline."""
        h = hashlib.sha1()
        h.update(self.rule.encode())
        h.update(b"|")
        h.update(self.path.encode())
        h.update(b"|")
        h.update(source_line.strip().encode())
        return h.hexdigest()[:16]

    def render(self) -> str:
        loc = f"{self.path}:{self.line}"
        out = f"{loc}: {self.rule} {self.message}"
        if self.fix_hint:
            out += f"\n    fix: {self.fix_hint}"
        return out


class SourceFile:
    """One parsed python file: text, AST, and real comment suppressions."""

    def __init__(self, relpath: str, text: str):
        self.path = relpath.replace(os.sep, "/")
        self.text = text
        self.lines = text.splitlines()
        self.syntax_error: Optional[SyntaxError] = None
        try:
            self.tree: Optional[ast.AST] = ast.parse(text)
        except SyntaxError as e:
            self.tree = None
            self.syntax_error = e
        # line -> [(rule, reason)] from actual COMMENT tokens (a string
        # literal that merely contains the pattern must not suppress).
        self.suppressions: Dict[int, List[Tuple[str, str]]] = {}
        try:
            for tok in tokenize.generate_tokens(io.StringIO(text).readline):
                if tok.type != tokenize.COMMENT:
                    continue
                for m in SUPPRESS_RE.finditer(tok.string):
                    self.suppressions.setdefault(tok.start[0], []).append(
                        (m.group(1).upper(), m.group(2).strip())
                    )
        except (tokenize.TokenError, IndentationError):
            pass

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def suppressed(self, finding: Finding) -> bool:
        """True when an allow-comment with a NON-EMPTY reason for this
        rule sits on the finding's line or the line directly above."""
        for line in (finding.line, finding.line - 1):
            for rule, reason in self.suppressions.get(line, []):
                if rule == finding.rule and reason:
                    return True
        return False


class Project:
    """The lint unit: one package subtree under one project root."""

    def __init__(self, root: str, package: str = "gubernator_tpu"):
        self.root = os.path.abspath(root)
        self.package = package
        self.files: List[SourceFile] = []
        self.by_path: Dict[str, SourceFile] = {}

    def add_file(self, relpath: str, text: str) -> SourceFile:
        sf = SourceFile(relpath, text)
        self.files.append(sf)
        self.by_path[sf.path] = sf
        return sf

    def read_text(self, relpath: str) -> Optional[str]:
        """Non-python project file (example.conf, docs/*.md); None when
        absent so rules can report the absence themselves."""
        p = os.path.join(self.root, relpath)
        try:
            with open(p, encoding="utf-8") as f:
                return f.read()
        except OSError:
            return None

    # Well-known project paths rules key off (kept together so a repo
    # re-layout is a one-place change).
    @property
    def config_path(self) -> str:
        return f"{self.package}/config.py"

    @property
    def metrics_path(self) -> str:
        return f"{self.package}/utils/metrics.py"

    @property
    def example_conf_path(self) -> str:
        return "example.conf"

    @property
    def prometheus_doc_path(self) -> str:
        return "docs/prometheus.md"


def load_project(root: str, package: str = "gubernator_tpu") -> Project:
    proj = Project(root, package)
    pkg_dir = os.path.join(proj.root, package)
    for dirpath, dirnames, filenames in os.walk(pkg_dir):
        dirnames[:] = sorted(
            d for d in dirnames if d != "__pycache__" and not d.startswith(".")
        )
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            full = os.path.join(dirpath, fn)
            rel = os.path.relpath(full, proj.root)
            try:
                with open(full, encoding="utf-8") as f:
                    text = f.read()
            except OSError:
                continue
            proj.add_file(rel, text)
    return proj


# ----------------------------------------------------------------------
# Rule registry
# ----------------------------------------------------------------------
@dataclass
class Rule:
    id: str
    title: str
    description: str
    fix_hint: str
    check: Callable[[Project], Iterable[Finding]]


RULES: Dict[str, Rule] = {}


def register(rule: Rule) -> Rule:
    if rule.id in RULES:
        raise ValueError(f"duplicate rule id {rule.id}")
    RULES[rule.id] = rule
    return rule


# ----------------------------------------------------------------------
# Baseline: grandfathered findings, checked in, reason-annotated
# ----------------------------------------------------------------------
BASELINE_NAME = ".guberlint-baseline.json"


def load_baseline(path: str) -> Dict[Tuple[str, str, str], int]:
    """fingerprint-keyed allowance counts.  Key: (rule, path, fp)."""
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
    except OSError:
        return {}
    out: Dict[Tuple[str, str, str], int] = {}
    for row in data.get("findings", []):
        key = (row["rule"], row["path"], row["fingerprint"])
        out[key] = out.get(key, 0) + int(row.get("count", 1))
    return out


def write_baseline(path: str, project: Project,
                   findings: List[Finding]) -> None:
    """Write the given (still-unsuppressed) findings as the new baseline.
    Every entry carries a reason field the operator is expected to edit —
    'grandfathered' is a placeholder, not a justification."""
    rows = []
    counts: Dict[Tuple[str, str, str], int] = {}
    for f in findings:
        sf = project.by_path.get(f.path)
        fp = f.fingerprint(sf.line_text(f.line) if sf else "")
        key = (f.rule, f.path, fp)
        if key in counts:
            counts[key] += 1
            continue
        counts[key] = 1
        rows.append({
            "rule": f.rule, "path": f.path, "line": f.line,
            "fingerprint": fp, "message": f.message,
            "reason": "grandfathered — justify or fix",
        })
    for row in rows:
        key = (row["rule"], row["path"], row["fingerprint"])
        if counts[key] > 1:
            row["count"] = counts[key]
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"findings": rows}, f, indent=2, sort_keys=True)
        f.write("\n")


# ----------------------------------------------------------------------
# Runner
# ----------------------------------------------------------------------
@dataclass
class LintResult:
    findings: List[Finding] = field(default_factory=list)   # live
    suppressed: int = 0
    baselined: int = 0

    @property
    def ok(self) -> bool:
        return not self.findings


def run_project(
    project: Project,
    baseline: Optional[Dict[Tuple[str, str, str], int]] = None,
    rule_ids: Optional[Iterable[str]] = None,
) -> LintResult:
    result = LintResult()
    remaining = dict(baseline or {})
    ids = sorted(rule_ids) if rule_ids else sorted(RULES)
    all_findings: List[Finding] = []
    for rid in ids:
        all_findings.extend(RULES[rid].check(project))
    all_findings.sort(key=lambda f: (f.path, f.line, f.rule))
    for f in all_findings:
        sf = project.by_path.get(f.path)
        if sf is not None and sf.suppressed(f):
            result.suppressed += 1
            continue
        fp = f.fingerprint(sf.line_text(f.line) if sf else "")
        key = (f.rule, f.path, fp)
        if remaining.get(key, 0) > 0:
            remaining[key] -= 1
            result.baselined += 1
            continue
        result.findings.append(f)
    return result
