"""Package-wide AST call graph: the interprocedural half of guberlint.

One :class:`CallGraph` per :class:`~gubernator_tpu.analysis.core.Project`
indexes every module, class, method, and nested def into qualified
names, resolves imports (including aliases and one-hop re-exports), and
turns ``ast.Call`` nodes into edges.  Rules use it to make scope taint
transitive: ``@hot_path`` (G001), async context (G002), held locks
(G007/G008), and supervised-loop reachability (G009/G010) all propagate
through resolved callees.

Resolution is deliberately conservative — **best-effort on static
dispatch, silent on dynamic dispatch**:

* plain names resolve through nested-def scopes, module defs, and
  imports (``import a.b as c`` / ``from a.b import c as d``, re-exports
  followed up to a small depth);
* ``self.method()`` resolves in the enclosing class and its
  project-local bases;
* ``self.attr.method()`` resolves only when ``attr``'s type is inferable
  from ``__init__``-style assignments (``self.attr = ClassName(...)`` or
  ``self.attr = param`` with an annotated parameter);
* everything else — duck-typed receivers, callables passed as values,
  monkey-patched names — produces **no edge**.  A missed edge can hide a
  finding; an invented edge fabricates one.  The linter takes the miss.

External (non-project) names still resolve to a *canonical* dotted path
(``from time import sleep as zzz; zzz()`` → ``time.sleep``) so primitive
matching in rules survives aliasing.

Pure stdlib, and never imports the inspected modules.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from gubernator_tpu.analysis.core import Project, SourceFile

# Result kinds from resolve():  ("func", FuncInfo) | ("class", ClassInfo)
# | ("mod", ModuleInfo) | ("ext", "dotted.canonical.name") | None.
_MAX_REEXPORT_DEPTH = 6


def qual_parts(node: ast.AST) -> List[str]:
    """['os', 'environ', 'get'] for a Name/Attribute chain; [] otherwise."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        parts.reverse()
        return parts
    return []


def iter_stmts_skip_nested(body: Iterable[ast.stmt]) -> Iterable[ast.AST]:
    """Walk statements without entering nested def/lambda bodies — the
    callgraph gives every nested def its own node, so its statements
    must not leak into the parent's."""
    stack: List[ast.AST] = list(body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def direct_nested_defs(fn: ast.AST) -> List[ast.AST]:
    out: List[ast.AST] = []
    for node in iter_stmts_skip_nested(fn.body):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.append(node)
    return out


def decorator_names(fn: ast.AST) -> Set[str]:
    """Terminal decorator name segments: @utils.hot_path → {'hot_path'}."""
    names: Set[str] = set()
    for d in getattr(fn, "decorator_list", []):
        if isinstance(d, ast.Call):
            d = d.func
        parts = qual_parts(d)
        if parts:
            names.add(parts[-1])
    return names


class FuncInfo:
    """One def/method/nested def with enough context to resolve from."""

    __slots__ = ("qname", "node", "sf", "module", "cls", "parent",
                 "children", "is_async")

    def __init__(self, qname, node, sf, module, cls, parent):
        self.qname: str = qname
        self.node = node
        self.sf: SourceFile = sf
        self.module: "ModuleInfo" = module
        self.cls: Optional["ClassInfo"] = cls
        self.parent: Optional["FuncInfo"] = parent
        self.children: Dict[str, "FuncInfo"] = {}
        self.is_async = isinstance(node, ast.AsyncFunctionDef)

    @property
    def name(self) -> str:
        return self.node.name

    @property
    def short(self) -> str:
        """Human label: 'Class.method' or 'func'."""
        if self.cls is not None:
            return f"{self.cls.name}.{self.name}"
        return self.name

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"<FuncInfo {self.qname}>"


class ClassInfo:
    __slots__ = ("qname", "name", "node", "sf", "module", "base_names",
                 "methods", "attr_types")

    def __init__(self, qname, name, node, sf, module, base_names):
        self.qname: str = qname
        self.name: str = name
        self.node = node
        self.sf: SourceFile = sf
        self.module: "ModuleInfo" = module
        self.base_names: List[List[str]] = base_names  # raw dotted parts
        self.methods: Dict[str, FuncInfo] = {}
        # attr -> canonical type name: a project class qname, or an
        # external dotted name ("threading.RLock", "queue.Queue").
        # Conflicting inferences poison the entry (dropped).
        self.attr_types: Dict[str, str] = {}

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"<ClassInfo {self.qname}>"


class ModuleInfo:
    __slots__ = ("name", "sf", "is_pkg", "imports", "functions", "classes")

    def __init__(self, name: str, sf: SourceFile, is_pkg: bool):
        self.name = name
        self.sf = sf
        self.is_pkg = is_pkg
        # alias -> ("mod", dotted) | ("sym", dotted_module, symbol)
        self.imports: Dict[str, Tuple] = {}
        self.functions: Dict[str, FuncInfo] = {}
        self.classes: Dict[str, ClassInfo] = {}


def modname_of(path: str) -> Optional[str]:
    if not path.endswith(".py"):
        return None
    parts = path[:-3].split("/")
    if parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


class CallGraph:
    """Index + resolver + edge cache over one project."""

    def __init__(self, project: Project):
        self.project = project
        self.modules: Dict[str, ModuleInfo] = {}
        self.functions: Dict[str, FuncInfo] = {}
        self.classes: Dict[str, ClassInfo] = {}
        self._edge_cache: Dict[str, List[Tuple[FuncInfo, int]]] = {}
        self._bases_cache: Dict[str, List[ClassInfo]] = {}
        self._by_node: Dict[int, FuncInfo] = {}
        for sf in project.files:
            if sf.tree is None:
                continue
            name = modname_of(sf.path)
            if name is None:
                continue
            mod = ModuleInfo(name, sf, sf.path.endswith("/__init__.py"))
            self.modules[name] = mod
        for mod in self.modules.values():
            self._index_module(mod)
        for ci in list(self.classes.values()):
            self._infer_attr_types(ci)

    def func_of(self, node: ast.AST) -> Optional["FuncInfo"]:
        """The FuncInfo indexed for a given def node (None for defs the
        index skipped, e.g. methods of nested classes)."""
        return self._by_node.get(id(node))

    @classmethod
    def of(cls, project: Project) -> "CallGraph":
        """Build once per project; rules share the cached instance."""
        cg = getattr(project, "_guber_callgraph", None)
        if cg is None:
            cg = cls(project)
            project._guber_callgraph = cg
        return cg

    # ------------------------------------------------------------------
    # Indexing
    # ------------------------------------------------------------------
    def _index_module(self, mod: ModuleInfo) -> None:
        tree = mod.sf.tree
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.asname:
                        mod.imports[a.asname] = ("mod", a.name)
                    else:
                        head = a.name.split(".")[0]
                        mod.imports.setdefault(head, ("mod", head))
            elif isinstance(node, ast.ImportFrom):
                base = self._import_base(mod, node)
                if base is None:
                    continue
                for a in node.names:
                    if a.name == "*":
                        continue
                    mod.imports[a.asname or a.name] = ("sym", base, a.name)
        for stmt in tree.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._add_func(mod, stmt, cls=None, parent=None,
                               prefix=mod.name)
            elif isinstance(stmt, ast.ClassDef):
                self._add_class(mod, stmt)

    def _import_base(self, mod: ModuleInfo,
                     node: ast.ImportFrom) -> Optional[str]:
        if not node.level:
            return node.module or None
        parts = mod.name.split(".")
        drop = node.level if not mod.is_pkg else node.level - 1
        if drop > 0:
            parts = parts[:-drop] if drop < len(parts) else []
        if node.module:
            parts = parts + node.module.split(".")
        return ".".join(parts) if parts else None

    def _add_func(self, mod, node, cls, parent, prefix) -> None:
        qname = f"{prefix}.{node.name}"
        fi = FuncInfo(qname, node, mod.sf, mod, cls, parent)
        self.functions[qname] = fi
        self._by_node[id(node)] = fi
        if parent is not None:
            parent.children[node.name] = fi
        elif cls is not None:
            # First def wins on duplicates (@property getter vs setter).
            cls.methods.setdefault(node.name, fi)
        else:
            mod.functions.setdefault(node.name, fi)
        for child in direct_nested_defs(node):
            self._add_func(mod, child, cls=cls, parent=fi,
                           prefix=f"{qname}.<locals>")

    def _add_class(self, mod: ModuleInfo, node: ast.ClassDef) -> None:
        qname = f"{mod.name}.{node.name}"
        bases = [p for b in node.bases if (p := qual_parts(b))]
        ci = ClassInfo(qname, node.name, node, mod.sf, mod, bases)
        self.classes[qname] = ci
        mod.classes.setdefault(node.name, ci)
        for stmt in node.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._add_func(mod, stmt, cls=ci, parent=None, prefix=qname)

    # ------------------------------------------------------------------
    # Attribute type inference (self.attr = ...)
    # ------------------------------------------------------------------
    def _infer_attr_types(self, ci: ClassInfo) -> None:
        for m in ci.methods.values():
            ann: Dict[str, ast.AST] = {}
            a = m.node.args
            for arg in (a.posonlyargs + a.args + a.kwonlyargs):
                if arg.annotation is not None:
                    ann[arg.arg] = arg.annotation
            for node in ast.walk(m.node):
                if not (isinstance(node, ast.Assign)
                        and len(node.targets) == 1):
                    continue
                t = node.targets[0]
                if not (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"):
                    continue
                tq = self._value_type(node.value, m, ann)
                if tq is None:
                    continue
                prev = ci.attr_types.get(t.attr)
                if prev is None:
                    ci.attr_types[t.attr] = tq
                elif prev != tq:
                    ci.attr_types[t.attr] = "?"  # poisoned: conflicting
        for attr in [k for k, v in ci.attr_types.items() if v == "?"]:
            del ci.attr_types[attr]

    def _value_type(self, value: ast.AST, scope: FuncInfo,
                    ann: Dict[str, ast.AST]) -> Optional[str]:
        if isinstance(value, ast.Call):
            r = self.resolve(qual_parts(value.func), scope)
            if r is None:
                return None
            if r[0] == "class":
                return r[1].qname
            if r[0] == "ext":
                return r[1]
            return None
        if isinstance(value, ast.Name) and value.id in ann:
            return self._annotation_type(ann[value.id], scope)
        return None

    def _annotation_type(self, node: ast.AST,
                         scope: FuncInfo) -> Optional[str]:
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            try:
                node = ast.parse(node.value, mode="eval").body
            except SyntaxError:
                return None
        if isinstance(node, ast.Subscript):
            # Optional[X] / "X | None": take the concrete arm.
            base = qual_parts(node.value)
            if base and base[-1] == "Optional":
                node = node.slice
            else:
                return None
        if isinstance(node, ast.BinOp) and isinstance(node.op, ast.BitOr):
            for side in (node.left, node.right):
                if not (isinstance(side, ast.Constant)
                        and side.value is None):
                    node = side
                    break
        parts = qual_parts(node)
        if not parts:
            return None
        r = self.resolve(parts, scope)
        if r is None:
            return None
        if r[0] == "class":
            return r[1].qname
        if r[0] == "ext":
            return r[1]
        return None

    # ------------------------------------------------------------------
    # Resolution
    # ------------------------------------------------------------------
    def class_method(self, ci: ClassInfo, name: str) -> Optional[FuncInfo]:
        """Method lookup through project-local bases (cycle-safe)."""
        seen: Set[str] = set()
        stack = [ci]
        while stack:
            c = stack.pop(0)
            if c.qname in seen:
                continue
            seen.add(c.qname)
            m = c.methods.get(name)
            if m is not None:
                return m
            stack.extend(self._bases(c))
        return None

    def _bases(self, ci: ClassInfo) -> List[ClassInfo]:
        cached = self._bases_cache.get(ci.qname)
        if cached is None:
            cached = []
            for parts in ci.base_names:
                r = self._resolve_in_module(parts, ci.module)
                if r is not None and r[0] == "class":
                    cached.append(r[1])
            self._bases_cache[ci.qname] = cached
        return cached

    def _lookup_symbol(self, modname: str, name: str, depth: int = 0):
        sub = self.modules.get(f"{modname}.{name}")
        if sub is not None:
            return ("mod", sub)
        mi = self.modules.get(modname)
        if mi is None:
            return ("ext", f"{modname}.{name}" if modname else name)
        if name in mi.functions:
            return ("func", mi.functions[name])
        if name in mi.classes:
            return ("class", mi.classes[name])
        imp = mi.imports.get(name)
        if imp is not None and depth < _MAX_REEXPORT_DEPTH:
            return self._resolve_import(imp, depth + 1)
        return None  # defined some dynamic way — unknown, not external

    def _resolve_import(self, imp: Tuple, depth: int = 0):
        if imp[0] == "mod":
            mi = self.modules.get(imp[1])
            if mi is not None:
                return ("mod", mi)
            return ("ext", imp[1])
        _, base, name = imp
        return self._lookup_symbol(base, name, depth)

    def _resolve_self(self, rest: List[str], ci: ClassInfo):
        if len(rest) == 1:
            m = self.class_method(ci, rest[0])
            if m is not None:
                return ("func", m)
            return None
        if len(rest) == 2:
            t = ci.attr_types.get(rest[0])
            if t is None:
                return None
            target = self.classes.get(t)
            if target is not None:
                m = self.class_method(target, rest[1])
                return ("func", m) if m is not None else None
            return ("ext", f"{t}.{rest[1]}")
        return None

    def _resolve_in_module(self, parts: List[str], mod: ModuleInfo,
                           scope: Optional[FuncInfo] = None):
        head = parts[0]
        cur = None
        if scope is not None and len(parts) == 1:
            p = scope
            while p is not None:
                if head in p.children:
                    return ("func", p.children[head])
                p = p.parent
        if head in mod.functions:
            cur = ("func", mod.functions[head])
        elif head in mod.classes:
            cur = ("class", mod.classes[head])
        elif head in mod.imports:
            cur = self._resolve_import(mod.imports[head])
        if cur is None:
            # Unqualified builtin or module-global we didn't index: treat
            # the raw dotted name as its own canonical external form.
            return ("ext", ".".join(parts))
        for i, part in enumerate(parts[1:], 1):
            kind, val = cur
            if kind == "mod":
                cur = self._lookup_symbol(val.name, part)
                if cur is None:
                    return None
            elif kind == "ext":
                return ("ext", val + "." + ".".join(parts[i:]))
            elif kind == "class":
                m = self.class_method(val, part)
                if m is None:
                    return None
                cur = ("func", m)
            else:  # attribute access on a function object — unknown
                return None
        return cur

    def resolve(self, parts: List[str], scope: Optional[FuncInfo]):
        """Resolve a dotted name seen inside ``scope``.  Returns
        ("func", FuncInfo) | ("class", ClassInfo) | ("mod", ModuleInfo) |
        ("ext", canonical) | None (dynamic/unknown — no edge)."""
        if not parts:
            return None
        if parts[0] in ("self", "cls") and scope is not None \
                and scope.cls is not None:
            if len(parts) == 1:
                return None
            return self._resolve_self(parts[1:], scope.cls)
        if scope is not None:
            return self._resolve_in_module(parts, scope.module, scope)
        return None

    def resolve_expr(self, expr: ast.AST, scope: FuncInfo):
        return self.resolve(qual_parts(expr), scope)

    def canonical(self, expr: ast.AST, scope: FuncInfo) -> str:
        """Canonical external name of an expression ('' for project-local
        or unresolvable): survives ``from time import sleep as zzz``."""
        r = self.resolve_expr(expr, scope)
        if r is not None and r[0] == "ext":
            return r[1]
        return ""

    def callable_target(self, expr: ast.AST,
                        scope: FuncInfo) -> Optional[FuncInfo]:
        """A function *reference* (not call): spawn targets, callbacks."""
        r = self.resolve_expr(expr, scope)
        if r is not None and r[0] == "func":
            return r[1]
        if r is not None and r[0] == "class":
            return self.class_method(r[1], "__init__")
        return None

    # ------------------------------------------------------------------
    # Edges
    # ------------------------------------------------------------------
    def edges(self, fi: FuncInfo) -> List[Tuple[FuncInfo, int]]:
        """(callee, call lineno) for every resolvable direct call in
        ``fi``'s own body (nested defs excluded — they get their own
        node, and merely *defining* one runs nothing)."""
        cached = self._edge_cache.get(fi.qname)
        if cached is not None:
            return cached
        out: List[Tuple[FuncInfo, int]] = []
        for node in iter_stmts_skip_nested(fi.node.body):
            if not isinstance(node, ast.Call):
                continue
            r = self.resolve_expr(node.func, fi)
            if r is None:
                continue
            if r[0] == "func":
                out.append((r[1], node.lineno))
            elif r[0] == "class":
                init = self.class_method(r[1], "__init__")
                if init is not None:
                    out.append((init, node.lineno))
        out.sort(key=lambda e: e[1])
        self._edge_cache[fi.qname] = out
        return out


class PrimHit:
    """A primitive call reached from inside one function: the chain of
    functions walked (starting at the function itself), the function
    holding the primitive, and its location."""

    __slots__ = ("chain", "fi", "lineno", "label")

    def __init__(self, chain: Tuple[FuncInfo, ...], fi: FuncInfo,
                 lineno: int, label: str):
        self.chain = chain
        self.fi = fi
        self.lineno = lineno
        self.label = label

    def describe(self) -> str:
        path = " -> ".join(f.short for f in self.chain)
        return (f"{self.label} via {path} "
                f"({self.fi.sf.path}:{self.lineno})")


def first_primitive(cg: CallGraph, fi: FuncInfo, direct_fn, memo: Dict,
                    skip_fn=None) -> Optional[PrimHit]:
    """First primitive (per ``direct_fn``) reachable from inside ``fi``
    through resolved call edges — ``fi``'s own body first, then callees
    in call order.  ``direct_fn(fi) -> [(lineno, label)]`` scans one
    body; ``skip_fn(fi) -> bool`` prunes traversal (e.g. callees that
    carry their own ``@hot_path`` marker are checked directly).  ``memo``
    is a per-(rule, project) dict; cycles resolve to None."""
    key = fi.qname
    if key in memo:
        return memo[key]
    memo[key] = None  # in-progress marker: recursion terminates
    hit: Optional[PrimHit] = None
    hits = direct_fn(fi)
    if hits:
        lineno, label = min(hits)
        hit = PrimHit((fi,), fi, lineno, label)
    else:
        for callee, _ln in cg.edges(fi):
            if callee.qname == fi.qname:
                continue
            if skip_fn is not None and skip_fn(callee):
                continue
            sub = first_primitive(cg, callee, direct_fn, memo, skip_fn)
            if sub is not None:
                hit = PrimHit((fi,) + sub.chain, sub.fi, sub.lineno,
                              sub.label)
                break
    memo[key] = hit
    return hit
