"""``python -m gubernator_tpu.analysis`` — run guberlint over the repo.

Exit status: 0 when every finding is suppressed (inline allow-comment)
or baselined; 1 when any live finding remains; 2 on usage errors.

Usage:
    python -m gubernator_tpu.analysis [--root DIR] [--package NAME]
        [--baseline PATH | --no-baseline] [--update-baseline]
        [--rules G001,G004] [--json] [--sarif PATH] [--list-rules] [-q]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from gubernator_tpu.analysis.core import (
    BASELINE_NAME,
    RULES,
    load_baseline,
    load_project,
    run_project,
    write_baseline,
)
from gubernator_tpu.analysis import rules as _rules  # noqa: F401


def sarif_report(findings) -> dict:
    """SARIF 2.1.0 document for the given findings: one run, the full
    rule catalog under tool.driver.rules, one result per finding with
    a physical location (code-scanning upload shape)."""
    rules = [
        {
            "id": rid,
            "name": RULES[rid].title,
            "shortDescription": {"text": RULES[rid].title},
            "fullDescription": {"text": RULES[rid].description},
            "help": {"text": RULES[rid].fix_hint},
            "defaultConfiguration": {"level": "error"},
        }
        for rid in sorted(RULES)
    ]
    index = {rid: i for i, rid in enumerate(sorted(RULES))}
    results = [
        {
            "ruleId": f.rule,
            "ruleIndex": index[f.rule],
            "level": "error",
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {
                        "uri": f.path,
                        "uriBaseId": "%SRCROOT%",
                    },
                    "region": {"startLine": f.line},
                },
            }],
        }
        for f in findings
    ]
    return {
        "$schema": ("https://raw.githubusercontent.com/oasis-tcs/"
                    "sarif-spec/master/Schemata/sarif-schema-2.1.0.json"),
        "version": "2.1.0",
        "runs": [{
            "tool": {
                "driver": {
                    "name": "guberlint",
                    "informationUri": (
                        "https://github.com/gubernator-io/gubernator"),
                    "rules": rules,
                },
            },
            "results": results,
        }],
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m gubernator_tpu.analysis",
        description="guberlint: AST-based project invariant checker",
    )
    ap.add_argument("--root", default=None,
                    help="project root (default: auto-detected repo root)")
    ap.add_argument("--package", default="gubernator_tpu")
    ap.add_argument("--baseline", default=None,
                    help=f"baseline path (default: <root>/{BASELINE_NAME})")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline file")
    ap.add_argument("--update-baseline", action="store_true",
                    help="write current live findings as the new baseline "
                         "(then hand-edit the reason fields)")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule ids (default: all)")
    ap.add_argument("--json", action="store_true", dest="as_json")
    ap.add_argument("--sarif", default=None, metavar="PATH",
                    help="also write findings as SARIF 2.1.0 (for code "
                         "scanning upload); '-' for stdout")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("-q", "--quiet", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rid in sorted(RULES):
            r = RULES[rid]
            print(f"{rid}  {r.title}\n      {r.description}")
        return 0

    root = args.root
    if root is None:
        # The package dir's parent is the repo root when run in-tree.
        here = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        root = here if os.path.isdir(
            os.path.join(here, args.package)) else os.getcwd()
    if not os.path.isdir(os.path.join(root, args.package)):
        print(f"error: no package {args.package!r} under {root}",
              file=sys.stderr)
        return 2

    rule_ids = None
    if args.rules:
        rule_ids = [r.strip().upper() for r in args.rules.split(",") if r]
        unknown = [r for r in rule_ids if r not in RULES]
        if unknown:
            print(f"error: unknown rule(s) {', '.join(unknown)}",
                  file=sys.stderr)
            return 2

    project = load_project(root, args.package)
    baseline_path = args.baseline or os.path.join(root, BASELINE_NAME)
    baseline = {} if args.no_baseline else load_baseline(baseline_path)

    result = run_project(project, baseline, rule_ids)

    if args.update_baseline:
        write_baseline(baseline_path, project, result.findings)
        print(f"wrote {len(result.findings)} finding(s) to "
              f"{baseline_path} — edit each 'reason' to a real "
              "justification (or fix the code)")
        return 0

    if args.sarif:
        doc = sarif_report(result.findings)
        if args.sarif == "-":
            print(json.dumps(doc, indent=2))
        else:
            with open(args.sarif, "w") as fh:
                json.dump(doc, fh, indent=2)
                fh.write("\n")

    if args.as_json:
        print(json.dumps({
            "findings": [vars(f) for f in result.findings],
            "suppressed": result.suppressed,
            "baselined": result.baselined,
        }, indent=2))
    else:
        for f in result.findings:
            print(f.render())
        if not args.quiet:
            print(
                f"guberlint: {len(result.findings)} finding(s), "
                f"{result.suppressed} suppressed, "
                f"{result.baselined} baselined",
                file=sys.stderr,
            )
    return 1 if result.findings else 0


if __name__ == "__main__":
    sys.exit(main())
