"""The core guberlint rules (G001-G006), each grounded in a bug class
this repo has already shipped and hand-fixed at least once.  The
concurrency rules (G007-G010) live in analysis/concurrency.py.

All rules are pure AST walks — no imports of the inspected modules.
Since guberlint v2, G001 and G002 are *transitive*: the package call
graph (analysis/callgraph.py) propagates @hot_path and async-context
taint through resolved callees, so a primitive hidden one call deep in
a helper flags at the call site.  Where static truth is unreachable (is
this ``asarray`` argument a device buffer or host numpy?) the rules err
toward flagging inside an explicitly marked scope and let the author
answer with a reason-carrying ``# guber: allow-…`` comment; an
invariant you have to argue for in writing is the point.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

from gubernator_tpu.analysis.core import Finding, Project, Rule, register
from gubernator_tpu.analysis.callgraph import (
    CallGraph,
    FuncInfo,
    decorator_names,
    first_primitive,
    iter_stmts_skip_nested,
)
from gubernator_tpu.analysis.concurrency import (
    blocking_call_label,
    line_allowed,
)

# ----------------------------------------------------------------------
# Shared AST helpers
# ----------------------------------------------------------------------


def qual_name(node: ast.AST) -> str:
    """Dotted name of a Name/Attribute chain ('' when not a plain chain):
    ``os.environ.get`` → "os.environ.get"."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def walk_skip_nested(body: Iterable[ast.stmt]) -> Iterable[ast.AST]:
    """Walk statements without descending into nested function/lambda
    bodies: nested defs run at some other time, under some other
    discipline (a resolver callback, an executor thunk) — and every
    function gets its own visit from the enclosing rule's loop anyway."""
    stack: List[ast.AST] = list(body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def str_const(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def functions(tree: ast.AST):
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


# ----------------------------------------------------------------------
# G001 — device sync primitive in a @hot_path function
# ----------------------------------------------------------------------
# The per-tick serving path (dispatch threads: TickLoop._run/_flush,
# TickEngine submit/_build_cols, the mesh twin) must queue device work
# and NEVER materialize it — per-request D2H is the exact regression the
# fused-tick architecture exists to avoid (BASELINE.md; bench gates the
# dispatch counts, this rule gates the source).  Functions opt in with
# @hot_path (gubernator_tpu/utils/hotpath.py); the decorator is the
# documented contract, the rule is its enforcement.

_G001_CALLS = {
    "jax.device_get": "jax.device_get",
    "jax.block_until_ready": "jax.block_until_ready",
}
_G001_ASARRAY_BASES = {"np", "numpy", "onp"}
# Blocking file syscalls are the SSD-tier twin of a device sync: a
# per-tick open()/fsync()/mmap() stalls the dispatch thread on storage
# latency instead of PCIe.  Slab I/O belongs on the background writer
# (SsdStore._writer_loop) or in non-hot helpers (_map_slab).
_G001_FILE_CALLS = {"open", "os.open", "os.fsync", "mmap.mmap"}


def _g001_match(node: ast.Call, q: str,
                canonical: str) -> Optional[Tuple[str, bool]]:
    """(label, is_blocking_syscall) when this call is a G001 primitive:
    a device sync, or a thread-blocking syscall (file I/O, sleep,
    socket send/recv, blocking queue put/get, subprocess)."""
    if q in _G001_CALLS:
        return q, False
    if q in _G001_FILE_CALLS or canonical in _G001_FILE_CALLS:
        return f"{q or canonical}()", True
    if q.split(".")[-1] == "block_until_ready":
        return (q or ".block_until_ready()"), False
    if (
        q.split(".")[-1] in ("asarray", "array")
        and q.split(".")[0] in _G001_ASARRAY_BASES
    ):
        return q, False
    if (
        isinstance(node.func, ast.Attribute)
        and node.func.attr == "item"
        and not node.args
    ):
        return ".item()", False
    if (
        isinstance(node.func, ast.Name)
        and node.func.id in ("float", "bool")
        and len(node.args) == 1
        and not isinstance(node.args[0], ast.Constant)
    ):
        return f"{node.func.id}()", False
    # Blocking-syscall family (socket send/recv, blocking Queue.put/get,
    # subprocess, sleep): the edge drain path's gap — any of these on a
    # dispatch thread is a per-tick stall exactly like an fsync.
    label = blocking_call_label(node, q.split(".") if q else [], canonical)
    if label is not None:
        return label, True
    return None


def _is_hot(fi: FuncInfo) -> bool:
    return "hot_path" in decorator_names(fi.node)


def _g001(project: Project) -> Iterable[Finding]:
    hint = ("queue the device work and materialize it on the resolver "
            "side (TickHandle.result / resolve_ticks), or move this off "
            "the per-tick path")
    io_hint = ("blocking syscalls belong on the SSD tier's background "
               "writer (SsdStore._writer_loop) or in a non-hot helper, "
               "never inline on the dispatch thread")
    cg = CallGraph.of(project)
    memo: Dict[str, object] = {}

    def direct(fi: FuncInfo) -> List[Tuple[int, str]]:
        """Primitive sites in one body, minus inline-allowed ones (a
        G001 allow at the primitive line covers every transitive
        caller)."""
        hits: List[Tuple[int, str]] = []
        for node in iter_stmts_skip_nested(fi.node.body):
            if not isinstance(node, ast.Call):
                continue
            q = qual_name(node.func)
            m = _g001_match(node, q, cg.canonical(node.func, fi))
            if m is not None and not line_allowed(fi.sf, node.lineno,
                                                  "G001"):
                hits.append((node.lineno, m[0]))
        return hits

    def skip(fi: FuncInfo) -> bool:
        # Hot-marked callees get their own direct visit; async callees
        # aren't *run* by a sync call expression.
        return _is_hot(fi) or fi.is_async

    for qname in sorted(cg.functions):
        fi = cg.functions[qname]
        if not _is_hot(fi):
            continue
        for node in iter_stmts_skip_nested(fi.node.body):
            if not isinstance(node, ast.Call):
                continue
            q = qual_name(node.func)
            m = _g001_match(node, q, cg.canonical(node.func, fi))
            if m is not None:
                bad, blocking = m
                if blocking:
                    yield Finding(
                        "G001", fi.sf.path, node.lineno,
                        f"blocking syscall {bad} inside @hot_path "
                        f"function '{fi.name}' — a per-tick stall on "
                        "the dispatch thread", io_hint,
                    )
                else:
                    yield Finding(
                        "G001", fi.sf.path, node.lineno,
                        f"device-sync primitive {bad} inside @hot_path "
                        f"function '{fi.name}' — a per-tick host/device "
                        "round trip", hint,
                    )
                continue
            # Transitive: taint propagates through resolved callees, so
            # a primitive one call deep in an unmarked helper flags at
            # this call site.
            r = cg.resolve_expr(node.func, fi)
            callee: Optional[FuncInfo] = None
            if r is not None and r[0] == "func":
                callee = r[1]
            elif r is not None and r[0] == "class":
                callee = cg.class_method(r[1], "__init__")
            if callee is None or callee.qname == fi.qname or skip(callee):
                continue
            sub = first_primitive(cg, callee, direct, memo, skip)
            if sub is not None:
                yield Finding(
                    "G001", fi.sf.path, node.lineno,
                    f"@hot_path function '{fi.name}' reaches "
                    f"{sub.describe()} — the helper runs on the "
                    "dispatch thread and stalls it exactly like an "
                    "inline sync",
                    "mark the helper @hot_path and fix it, or move the "
                    "primitive off the per-tick path (an allow-comment "
                    "at the primitive's own line covers all callers)",
                )


register(Rule(
    "G001", "hot-path device sync / blocking syscall",
    "np.asarray / .item() / float()/bool() / block_until_ready / "
    "jax.device_get, or a thread-blocking syscall (open / os.fsync / "
    "mmap.mmap / time.sleep / socket send-recv / blocking Queue "
    "put-get / subprocess), inside — or transitively reachable from — "
    "a @hot_path serving function.",
    "Dispatch, don't materialize: syncs belong on the resolver side, "
    "blocking I/O on the SSD tier's background writer.",
    _g001,
))


# ----------------------------------------------------------------------
# G002 — blocking under a held lock / blocking in async
# ----------------------------------------------------------------------
_LOCKISH = re.compile(r"(^|_)(lock|cond|mutex|sem)[a-z0-9]*$", re.I)
_G002_BLOCKING = {"time.sleep", "os.fsync", "os.fdatasync"}


def _lockish_ctx(expr: ast.AST) -> bool:
    """Heuristic: the with-item looks like a threading lock/condition —
    terminal name segment lock/cond/mutex-ish, or a direct
    threading.Lock()/RLock()/Condition() call."""
    if isinstance(expr, ast.Call):
        q = qual_name(expr.func)
        if q.split(".")[-1] in ("Lock", "RLock", "Condition", "Semaphore",
                                "BoundedSemaphore"):
            return True
        expr = expr.func
    q = qual_name(expr)
    return bool(q) and bool(_LOCKISH.search(q.split(".")[-1]))


def _g002_blocking_q(q: str, canonical: str) -> bool:
    return (
        q in _G002_BLOCKING or canonical in _G002_BLOCKING
        or q in ("open", "io.open") or canonical in ("open", "io.open")
    )


def _g002(project: Project) -> Iterable[Finding]:
    cg = CallGraph.of(project)
    memo: Dict[str, object] = {}

    def direct(fi: FuncInfo) -> List[Tuple[int, str]]:
        hits: List[Tuple[int, str]] = []
        for node in iter_stmts_skip_nested(fi.node.body):
            if not isinstance(node, ast.Call):
                continue
            q = qual_name(node.func)
            if _g002_blocking_q(q, cg.canonical(node.func, fi)) and \
                    not line_allowed(fi.sf, node.lineno, "G002"):
                hits.append((node.lineno, q or "(call)"))
        return hits

    def skip(fi: FuncInfo) -> bool:
        return fi.is_async  # awaited callees carry their own async taint

    for sf in project.files:
        if sf.tree is None:
            continue
        for fn in functions(sf.tree):
            # (a) await while holding a (threading) lock: the event loop
            # parks this coroutine with the lock held; every thread that
            # then touches the lock — the tick loop, the reclaimer —
            # deadlocks behind a suspended coroutine.
            if isinstance(fn, ast.AsyncFunctionDef):
                for node in walk_skip_nested(fn.body):
                    if not isinstance(node, ast.With):
                        continue
                    if not any(
                        _lockish_ctx(it.context_expr) for it in node.items
                    ):
                        continue
                    for inner in walk_skip_nested(node.body):
                        if isinstance(inner, ast.Await):
                            yield Finding(
                                "G002", sf.path, inner.lineno,
                                f"await inside a held lock in "
                                f"'{fn.name}' — the coroutine parks "
                                "with the lock held and wedges every "
                                "thread behind it",
                                "release the lock before awaiting, or "
                                "make the critical section synchronous "
                                "and run it in an executor",
                            )
                # (b) blocking sync calls on the event loop: fsync and
                # friends stall EVERY coroutine (ticks, health probes,
                # peer RPCs) for the duration.  Transitive since v2: a
                # sync helper that opens/sleeps/fsyncs taints its async
                # callers through the call graph.
                for node in walk_skip_nested(fn.body):
                    if not isinstance(node, ast.Call):
                        continue
                    q = qual_name(node.func)
                    scope = cg.func_of(fn)
                    canonical = (cg.canonical(node.func, scope)
                                 if scope is not None else "")
                    if _g002_blocking_q(q, canonical):
                        yield Finding(
                            "G002", sf.path, node.lineno,
                            f"blocking call {q or '(call)'}() inside "
                            f"async def '{fn.name}' stalls the event "
                            "loop",
                            "await loop.run_in_executor(None, fn) or "
                            "asyncio.to_thread(fn) — see "
                            "persistence/writer.py",
                        )
                        continue
                    if scope is None:
                        continue
                    r = cg.resolve_expr(node.func, scope)
                    callee: Optional[FuncInfo] = None
                    if r is not None and r[0] == "func":
                        callee = r[1]
                    if callee is None or callee.is_async or \
                            callee.qname == scope.qname:
                        continue
                    sub = first_primitive(cg, callee, direct, memo, skip)
                    if sub is not None:
                        yield Finding(
                            "G002", sf.path, node.lineno,
                            f"async def '{fn.name}' reaches blocking "
                            f"{sub.describe()} — the helper runs on "
                            "the event loop and stalls every "
                            "coroutine",
                            "run the sync helper in an executor "
                            "(asyncio.to_thread), or move the blocking "
                            "primitive out of it",
                        )


register(Rule(
    "G002", "blocking under lock / blocking in async",
    "await while a threading lock is held, or time.sleep/os.fsync/raw "
    "file IO directly inside an async def.",
    "Blocking work belongs in an executor; locks release before awaits.",
    _g002,
))


# ----------------------------------------------------------------------
# G003 — fire-and-forget asyncio tasks
# ----------------------------------------------------------------------
_SPAWN_TAILS = ("create_task", "ensure_future")


def _g003(project: Project) -> Iterable[Finding]:
    hint = ("keep the handle: store it in a tracked set with an "
            "add_done_callback that logs exceptions (the "
            "V1Instance._peer_shutdown_tasks pattern), await it, or use "
            "resilience.spawn_supervised for loops")
    for sf in project.files:
        if sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            call: Optional[ast.Call] = None
            if isinstance(node, ast.Expr) and isinstance(node.value,
                                                         ast.Call):
                call = node.value
            elif (
                isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)
                and all(
                    isinstance(t, ast.Name) and t.id == "_"
                    for t in node.targets
                )
            ):
                call = node.value
            if call is None:
                continue
            q = qual_name(call.func)
            if q.split(".")[-1] not in _SPAWN_TAILS:
                continue
            yield Finding(
                "G003", sf.path, call.lineno,
                f"fire-and-forget task: {q}(...) discards its handle — "
                "the task can be GC'd mid-flight and its exception is "
                "silently swallowed", hint,
            )


register(Rule(
    "G003", "fire-and-forget tasks",
    "asyncio.create_task/ensure_future whose handle is discarded "
    "(bare statement or assigned to _).",
    "Track the task and log its exceptions on completion.",
    _g003,
))


# ----------------------------------------------------------------------
# G004 — GUBER_* env discipline
# ----------------------------------------------------------------------
_ENV_NAME = re.compile(r"^GUBER_[A-Z0-9]+(?:_[A-Z0-9]+)*$")


def _registry_names(project: Project) -> Optional[Set[str]]:
    """Keys of the ENV_REGISTRY dict literal in config.py (the single
    source of truth for the supported env surface)."""
    sf = project.by_path.get(project.config_path)
    if sf is None or sf.tree is None:
        return None
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = [node.target]
        else:
            continue
        if not any(
            isinstance(t, ast.Name) and t.id == "ENV_REGISTRY"
            for t in targets
        ):
            continue
        if isinstance(node.value, ast.Dict):
            return {
                s for k in node.value.keys
                if (s := str_const(k)) is not None
            }
    return None


def _env_read_literal(call: ast.Call) -> Optional[str]:
    """GUBER_* literal read directly from the process environment:
    os.environ.get("X") / os.getenv("X")."""
    q = qual_name(call.func)
    if q in ("os.environ.get", "os.getenv", "getenv") and call.args:
        s = str_const(call.args[0])
        if s and _ENV_NAME.match(s):
            return s
    return None


def _g004(project: Project) -> Iterable[Finding]:
    registry = _registry_names(project)
    if registry is None:
        yield Finding(
            "G004", project.config_path, 1,
            "config.py must define the ENV_REGISTRY dict literal — the "
            "single source of truth for the GUBER_* env surface",
            "declare ENV_REGISTRY: Dict[str, str] = {\"GUBER_…\": "
            "\"description\", …}",
        )
        return

    # (a) ad-hoc process-env reads outside config.py.  The registry's
    # typed accessors (env_knob / EnvReader) exist so every knob is
    # registered, validated, and documented in one place.
    for sf in project.files:
        if sf.tree is None or sf.path == project.config_path:
            continue
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Call):
                name = _env_read_literal(node)
                if name:
                    yield Finding(
                        "G004", sf.path, node.lineno,
                        f"direct os.environ read of {name} bypasses the "
                        "config registry",
                        "use gubernator_tpu.config.env_knob(name, "
                        "default, parse=…) — registered, validated, "
                        "documented",
                    )
            elif (
                isinstance(node, ast.Subscript)
                and isinstance(node.ctx, ast.Load)
                and qual_name(node.value) == "os.environ"
            ):
                s = str_const(node.slice)
                if s and _ENV_NAME.match(s):
                    yield Finding(
                        "G004", sf.path, node.lineno,
                        f"direct os.environ[{s!r}] read bypasses the "
                        "config registry",
                        "use gubernator_tpu.config.env_knob",
                    )

    # (b) every GUBER_* name mentioned in code must be registered —
    # names ending in '_' are prefix-family mentions (GUBER_FAULT_*) and
    # don't count.
    for sf in project.files:
        if sf.tree is None:
            continue
        seen_lines: Set[Tuple[str, int]] = set()
        for node in ast.walk(sf.tree):
            s = str_const(node)
            if not s or not _ENV_NAME.match(s) or s in registry:
                continue
            key = (s, node.lineno)
            if key in seen_lines:
                continue
            seen_lines.add(key)
            yield Finding(
                "G004", sf.path, node.lineno,
                f"unregistered env var name {s} — not a key of "
                "config.ENV_REGISTRY",
                "register it (name → one-line description) in "
                "config.ENV_REGISTRY and document it in example.conf",
            )

    # (c/d) registry ↔ example.conf, both directions.
    conf_text = project.read_text(project.example_conf_path)
    if conf_text is None:
        yield Finding(
            "G004", project.example_conf_path, 1,
            "example.conf is missing — every registered knob must be "
            "documented there",
            "restore example.conf",
        )
        return
    conf_names = {
        m for m in re.findall(r"GUBER_[A-Z0-9_]+", conf_text)
        if _ENV_NAME.match(m)
    }
    sf = project.by_path[project.config_path]
    reg_line = 1
    for i, ln in enumerate(sf.lines, 1):
        if "ENV_REGISTRY" in ln:
            reg_line = i
            break
    for name in sorted(registry - conf_names):
        yield Finding(
            "G004", project.config_path, reg_line,
            f"{name} is registered but not documented in example.conf",
            "add a commented example entry to example.conf",
        )
    for name in sorted(conf_names - registry):
        yield Finding(
            "G004", project.example_conf_path, 1,
            f"{name} appears in example.conf but is not registered in "
            "config.ENV_REGISTRY",
            "register it or remove the stale documentation",
        )


register(Rule(
    "G004", "env discipline",
    "Every GUBER_* env var is registered in config.ENV_REGISTRY, read "
    "through it, and documented in example.conf.",
    "One registry; no ad-hoc os.environ reads.",
    _g004,
))


# ----------------------------------------------------------------------
# G005 — metric catalog ↔ docs/prometheus.md sync
# ----------------------------------------------------------------------
_METRIC_CTORS = {"Counter", "Gauge", "Summary", "Histogram"}
_METRIC_NAME = re.compile(r"^gubernator[a-z0-9_]*$")


def _g005(project: Project) -> Iterable[Finding]:
    sf = project.by_path.get(project.metrics_path)
    if sf is None or sf.tree is None:
        return
    code_names: Dict[str, int] = {}
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call):
            continue
        if qual_name(node.func).split(".")[-1] not in _METRIC_CTORS:
            continue
        if not node.args:
            continue
        name = str_const(node.args[0])
        if not name or not _METRIC_NAME.match(name):
            continue
        if name in code_names:
            yield Finding(
                "G005", sf.path, node.lineno,
                f"duplicate metric family {name} (first defined on "
                f"line {code_names[name]})",
                "one family per name; reuse the existing attribute",
            )
            continue
        code_names[name] = node.lineno
    doc_text = project.read_text(project.prometheus_doc_path)
    if doc_text is None:
        yield Finding(
            "G005", project.prometheus_doc_path, 1,
            "docs/prometheus.md is missing — the metric catalog must be "
            "documented",
            "restore docs/prometheus.md",
        )
        return
    doc_names: Dict[str, int] = {}
    for i, ln in enumerate(doc_text.splitlines(), 1):
        if not ln.lstrip().startswith("|"):
            continue  # only catalog table rows count; prose may cite
            # derived series like _count/_sum
        for m in re.finditer(r"`(gubernator[a-z0-9_]*)`", ln):
            doc_names.setdefault(m.group(1), i)
    for name in sorted(set(code_names) - set(doc_names)):
        yield Finding(
            "G005", sf.path, code_names[name],
            f"metric {name} is registered in code but missing from "
            "docs/prometheus.md",
            "add a table row to docs/prometheus.md",
        )
    for name in sorted(set(doc_names) - set(code_names)):
        yield Finding(
            "G005", project.prometheus_doc_path, doc_names[name],
            f"metric {name} is documented but not registered in "
            f"{project.metrics_path}",
            "remove the stale row or register the family",
        )


register(Rule(
    "G005", "metric registry sync",
    "Prometheus family names in utils/metrics.py and docs/prometheus.md "
    "must match exactly, both directions, with no duplicates.",
    "The docs table IS the catalog; keep it generated from the code.",
    _g005,
))


# ----------------------------------------------------------------------
# G006 — trace purity inside jit / shard_map functions
# ----------------------------------------------------------------------
_G006_IMPURE = {
    "time.time", "time.monotonic", "time.perf_counter", "time.time_ns",
    "datetime.now", "datetime.utcnow", "datetime.datetime.now",
    "datetime.datetime.utcnow", "os.getenv", "print",
}
_G006_IMPURE_PREFIX = ("random.", "np.random.", "numpy.random.")
_STATIC_ATTRS = {"shape", "dtype", "ndim", "size", "sharding", "at"}


def _traced_functions(tree: ast.AST):
    """(function node, reason) for every function we can statically see
    being traced: decorated with @jit/@jax.jit (directly or via
    partial), or passed by name/lambda to jit()/shard_map()."""
    defs: Dict[str, List[ast.AST]] = {}
    for fn in functions(tree):
        defs.setdefault(fn.name, []).append(fn)

    def is_jit_name(node: ast.AST) -> bool:
        q = qual_name(node)
        return q in ("jit", "jax.jit", "pjit", "jax.pjit", "shard_map",
                     "jax.experimental.shard_map.shard_map")

    traced: List[Tuple[ast.AST, str]] = []
    for fn in functions(tree):
        for d in fn.decorator_list:
            if is_jit_name(d):
                traced.append((fn, qual_name(d)))
            elif isinstance(d, ast.Call):
                if is_jit_name(d.func):
                    traced.append((fn, qual_name(d.func)))
                elif (
                    qual_name(d.func).split(".")[-1] == "partial"
                    and d.args and is_jit_name(d.args[0])
                ):
                    traced.append((fn, qual_name(d.args[0])))
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or not is_jit_name(node.func):
            continue
        if not node.args:
            continue
        target = node.args[0]
        if isinstance(target, ast.Lambda):
            traced.append((target, qual_name(node.func)))
        elif isinstance(target, ast.Name):
            for fn in defs.get(target.id, []):
                traced.append((fn, qual_name(node.func)))
    return traced


def _value_dependent_param_use(test: ast.AST, params: Set[str]) -> bool:
    """True when the expression reads a traced parameter's VALUE (vs its
    static metadata: .shape/.dtype/len()/isinstance()/is-None)."""

    def visit(node: ast.AST) -> bool:
        if isinstance(node, ast.Attribute):
            if node.attr in _STATIC_ATTRS:
                return False
            return visit(node.value)
        if isinstance(node, ast.Call):
            q = qual_name(node.func)
            if q in ("len", "isinstance", "type", "id"):
                return False
            return any(visit(c) for c in ast.iter_child_nodes(node))
        if isinstance(node, ast.Compare) and all(
            isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops
        ):
            return False
        if isinstance(node, ast.Name):
            return node.id in params
        return any(visit(c) for c in ast.iter_child_nodes(node))

    return visit(test)


def _g006(project: Project) -> Iterable[Finding]:
    for sf in project.files:
        if sf.tree is None:
            continue
        seen: Set[Tuple[int, str]] = set()
        for fn, how in _traced_functions(sf.tree):
            if isinstance(fn, ast.Lambda):
                body: List[ast.AST] = [fn.body]
                name = "<lambda>"
                args = fn.args
            else:
                body = list(fn.body)
                name = fn.name
                args = fn.args
            params = {
                a.arg for a in (
                    args.posonlyargs + args.args + args.kwonlyargs
                )
            } - {"self", "cls"}
            # Traced bodies include nested defs: fori_loop/scan bodies
            # trace right along with their parent.
            stack = list(body)
            nodes: List[ast.AST] = []
            while stack:
                n = stack.pop()
                nodes.append(n)
                stack.extend(ast.iter_child_nodes(n))
            for node in nodes:
                if isinstance(node, ast.Call):
                    q = qual_name(node.func)
                    if q in _G006_IMPURE or any(
                        q.startswith(p) for p in _G006_IMPURE_PREFIX
                    ):
                        key = (node.lineno, q)
                        if key in seen:
                            continue
                        seen.add(key)
                        yield Finding(
                            "G006", sf.path, node.lineno,
                            f"impure call {q}() inside {how}-traced "
                            f"function '{name}' — evaluated once at "
                            "trace time, then frozen into the compiled "
                            "program",
                            "hoist it to the host caller and pass the "
                            "value in as an argument",
                        )
                elif (
                    isinstance(node, (ast.Attribute, ast.Subscript))
                    and qual_name(
                        node.value if isinstance(node, ast.Subscript)
                        else node
                    ) in ("os.environ",)
                ):
                    key = (node.lineno, "os.environ")
                    if key in seen:
                        continue
                    seen.add(key)
                    yield Finding(
                        "G006", sf.path, node.lineno,
                        f"os.environ access inside {how}-traced "
                        f"function '{name}' — read at trace time and "
                        "frozen",
                        "resolve the knob outside the traced function",
                    )
                elif isinstance(node, (ast.If, ast.While)):
                    if _value_dependent_param_use(node.test, params):
                        key = (node.lineno, "branch")
                        if key in seen:
                            continue
                        seen.add(key)
                        yield Finding(
                            "G006", sf.path, node.lineno,
                            f"Python-level branch on a traced value in "
                            f"{how}-traced function '{name}' — this "
                            "either fails to trace or silently "
                            "specializes on one concrete value",
                            "use jnp.where / jax.lax.cond / "
                            "jax.lax.select on device values",
                        )


register(Rule(
    "G006", "trace purity",
    "No time.time()/os.environ/random/print or Python-level branching "
    "on traced values inside functions passed to jit/shard_map.",
    "Traced functions see abstract values; host state must be an input.",
    _g006,
))
