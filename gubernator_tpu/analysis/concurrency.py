"""Concurrency rules G007-G010: the interprocedural bug classes.

Each rule is grounded in a bug this repo has already shipped and
root-caused:

* **G007** — blocking call reachable while a ``threading`` lock is held
  (the PR 12 ColdStore sink-under-lock class, enforced everywhere and
  made transitive through the call graph).
* **G008** — lock-order cycles: two locks acquired in opposite nesting
  orders *anywhere* in the package, including through calls (the static
  half of the runtime lock-order sanitizer in utils/sanitize.py).
* **G009** — cross-thread shared mutable state: attributes written from
  a ``spawn_supervised_thread``/``threading.Thread`` target and touched
  elsewhere in the class with no lock on either side (the ring
  double-serve / PR 13 class).  ``# guber: allow-g009(reason)`` marks
  single-writer-by-design fields.
* **G010** — background-task deadline taint: an object carrying an
  admission ``deadline`` stored into a container drained by a supervised
  loop (the exact PR 17 federation bug, generalized).

Known resolution limits (see docs/static-analysis.md): dynamic dispatch
produces no edge, so a blocking call behind an un-inferable attribute
does not flag — the runtime sanitizers (GUBER_SANITIZERS=1) cover that
half.  G009 deliberately scopes to *thread* targets: ``spawn_supervised``
(asyncio) loop state is event-loop-confined by construction, and
flagging it would drown the signal.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

from gubernator_tpu.analysis.core import Finding, Project, Rule, register
from gubernator_tpu.analysis.callgraph import (
    CallGraph,
    ClassInfo,
    FuncInfo,
    first_primitive,
    iter_stmts_skip_nested,
    qual_parts,
)

# ----------------------------------------------------------------------
# Shared: what blocks a thread, what looks like a lock
# ----------------------------------------------------------------------
_LOCKISH = re.compile(r"(^|_)(lock|cond|mutex|sem)[a-z0-9]*$", re.I)
_QUEUEISH = re.compile(r"(^|_)(q|queue)\d*$", re.I)
_SOCKISH = re.compile(r"(sock|conn)", re.I)

_BLOCKING_EXACT = {
    "time.sleep", "os.fsync", "os.fdatasync", "open", "io.open", "os.open",
    "mmap.mmap", "select.select", "socket.create_connection",
}
_QUEUE_TYPES = ("queue.Queue", "queue.LifoQueue", "queue.PriorityQueue")
_QUEUE_BLOCK_METHODS = {"put", "get", "join"}
_SOCK_METHODS = {"send", "sendall", "sendto", "recv", "recv_into",
                 "recvfrom", "accept", "connect"}
# Operations *on a lock object* are the lock-order rule's domain (G008),
# and Condition.wait releases the lock it waits on — never G007 material.
_LOCK_METHODS = {"acquire", "release", "wait", "wait_for", "notify",
                 "notify_all", "locked", "set", "is_set"}


def _const_eq(node: ast.AST, value) -> bool:
    return isinstance(node, ast.Constant) and node.value == value


def _nonblocking_queue_call(call: ast.Call) -> bool:
    """put/get with block=False or timeout=0 doesn't block."""
    for kw in call.keywords:
        if kw.arg == "block" and _const_eq(kw.value, False):
            return True
        if kw.arg == "timeout" and _const_eq(kw.value, 0):
            return True
    # Queue.put(item, block) / Queue.get(block) positional forms.
    attr = call.func.attr if isinstance(call.func, ast.Attribute) else ""
    pos = 1 if attr == "put" else 0
    if len(call.args) > pos and _const_eq(call.args[pos], False):
        return True
    return False


def blocking_call_label(call: ast.Call, parts: List[str],
                        canonical: str) -> Optional[str]:
    """Canonical label when this call blocks the calling thread (sleep,
    fsync, open, socket I/O, subprocess, blocking queue put/get), else
    None.  ``canonical`` is the callgraph-resolved external name ('' when
    project-local/unknown); ``parts`` the raw dotted chain."""
    attr = parts[-1] if parts else ""
    recv_term = parts[-2] if len(parts) >= 2 else ""
    if attr in ("put_nowait", "get_nowait"):
        return None
    if attr in _LOCK_METHODS and _LOCKISH.search(recv_term):
        return None
    if canonical in _BLOCKING_EXACT:
        return canonical
    if canonical.startswith("subprocess."):
        return canonical
    for qt in _QUEUE_TYPES:
        if canonical.startswith(qt + "."):
            if attr in _QUEUE_BLOCK_METHODS and \
                    not _nonblocking_queue_call(call):
                return canonical
            return None
    if canonical.startswith("socket.") and attr in _SOCK_METHODS:
        return canonical
    # Untyped receivers: name-shape heuristics (the _resolve_q.put /
    # sock.recv idiom).  Receiver-less bare names never match here.
    if attr in _QUEUE_BLOCK_METHODS and _QUEUEISH.search(recv_term) and \
            not _nonblocking_queue_call(call):
        return ".".join(parts)
    if attr in _SOCK_METHODS and _SOCKISH.search(recv_term):
        return ".".join(parts)
    return None


def line_allowed(sf, lineno: int, rule: str) -> bool:
    """Inline allow-comment (with a non-empty reason) at a *primitive's*
    own line — lets one suppression in a shared helper cover every
    transitive caller, mirroring SourceFile.suppressed placement."""
    for ln in (lineno, lineno - 1):
        for rid, reason in sf.suppressions.get(ln, []):
            if rid == rule and reason:
                return True
    return False


def lockish_expr(expr: ast.AST) -> bool:
    if isinstance(expr, ast.Call):
        q = qual_parts(expr.func)
        if q and q[-1] in ("Lock", "RLock", "Condition", "Semaphore",
                           "BoundedSemaphore"):
            return True
        expr = expr.func
    q = qual_parts(expr)
    return bool(q) and bool(_LOCKISH.search(q[-1]))


def lock_identity(expr: ast.AST, fi: FuncInfo) -> Optional[Tuple[str, str]]:
    """(lock id, kind) for a lock-ish with-item.  Identity is
    class-scoped (``TickLoop._cond``) so every instance of a class maps
    to one graph node — the package-wide ordering discipline is per
    class attribute, not per object.  kind is the canonical ctor
    ('threading.RLock', ...) when __init__ inference knows it."""
    if isinstance(expr, ast.Call):
        return None  # inline Lock(): no cross-function identity
    parts = qual_parts(expr)
    if not parts or not _LOCKISH.search(parts[-1]):
        return None
    kind = ""
    if parts[0] in ("self", "cls") and fi.cls is not None:
        lid = f"{fi.cls.name}.{'.'.join(parts[1:])}"
        if len(parts) == 2:
            kind = fi.cls.attr_types.get(parts[1], "")
    else:
        lid = f"{fi.module.name}:{'.'.join(parts)}"
    return lid, kind


def lock_regions(fi: FuncInfo) -> List[Tuple[ast.With, str, str]]:
    """(with-node, lock id, kind) for every ``with <lock>:`` region in
    fi's own body, outermost first, in source order."""
    out: List[Tuple[ast.With, str, str]] = []
    for node in ast.walk(fi.node):
        if not isinstance(node, ast.With):
            continue
        for it in node.items:
            if not lockish_expr(it.context_expr):
                continue
            ident = lock_identity(it.context_expr, fi)
            if ident is None:
                q = qual_parts(it.context_expr)
                ident = (".".join(q) if q else "<lock>", "")
            out.append((node, ident[0], ident[1]))
            break
    out.sort(key=lambda r: (r[0].lineno, r[0].col_offset))
    return out


# ----------------------------------------------------------------------
# G007 — blocking call reachable while a lock is held
# ----------------------------------------------------------------------
def _resolved_callee(cg: CallGraph, call: ast.Call,
                     fi: FuncInfo) -> Optional[FuncInfo]:
    r = cg.resolve_expr(call.func, fi)
    if r is None:
        return None
    if r[0] == "func":
        return r[1]
    if r[0] == "class":
        return cg.class_method(r[1], "__init__")
    return None


def _g007(project: Project) -> Iterable[Finding]:
    cg = CallGraph.of(project)
    memo: Dict[str, object] = {}

    def direct(fi: FuncInfo) -> List[Tuple[int, str]]:
        hits: List[Tuple[int, str]] = []
        for node in iter_stmts_skip_nested(fi.node.body):
            if not isinstance(node, ast.Call):
                continue
            parts = qual_parts(node.func)
            canonical = cg.canonical(node.func, fi) if parts else ""
            label = blocking_call_label(node, parts, canonical)
            if label and not line_allowed(fi.sf, node.lineno, "G007"):
                hits.append((node.lineno, label))
        return hits

    def skip(fi: FuncInfo) -> bool:
        return fi.is_async  # sync code can't *run* an async callee

    hint = ("ship the blocking work outside the critical section: "
            "collect under the lock, act after release (the PR 12 "
            "ColdStore fix), or hand it to the background writer")
    seen_sites: Set[Tuple[str, int]] = set()
    for qname in sorted(cg.functions):
        fi = cg.functions[qname]
        for withnode, lid, _kind in lock_regions(fi):
            for node in iter_stmts_skip_nested(withnode.body):
                if not isinstance(node, ast.Call):
                    continue
                site = (fi.sf.path, node.lineno)
                if site in seen_sites:
                    continue
                parts = qual_parts(node.func)
                canonical = cg.canonical(node.func, fi) if parts else ""
                label = blocking_call_label(node, parts, canonical)
                if label:
                    seen_sites.add(site)
                    yield Finding(
                        "G007", fi.sf.path, node.lineno,
                        f"blocking call {label} while holding {lid} in "
                        f"'{fi.short}' — every thread contending on the "
                        "lock stalls behind it", hint,
                    )
                    continue
                callee = _resolved_callee(cg, node, fi)
                if callee is None or skip(callee) or \
                        callee.qname == fi.qname:
                    continue
                sub = first_primitive(cg, callee, direct, memo, skip)
                if sub is not None:
                    seen_sites.add(site)
                    yield Finding(
                        "G007", fi.sf.path, node.lineno,
                        f"call to '{callee.short}' while holding {lid} "
                        f"in '{fi.short}' reaches blocking "
                        f"{sub.describe()}", hint,
                    )


register(Rule(
    "G007", "blocking call under a held lock",
    "sleep / fsync / open / socket send-recv / subprocess / blocking "
    "queue put-get reachable (transitively, through resolved calls) "
    "while a threading.Lock/RLock/Condition is held.",
    "Collect under the lock, act after release; blocking work never "
    "shares a critical section with the serving path.",
    _g007,
))


# ----------------------------------------------------------------------
# G008 — lock-order cycles in the static acquisition graph
# ----------------------------------------------------------------------
def _g008(project: Project) -> Iterable[Finding]:
    cg = CallGraph.of(project)
    acq_memo: Dict[str, Set[str]] = {}

    def acquired(fi: FuncInfo) -> Set[str]:
        """Transitive set of lock ids this function may acquire."""
        cached = acq_memo.get(fi.qname)
        if cached is not None:
            return cached
        acq_memo[fi.qname] = set()  # cycle guard
        out: Set[str] = set()
        for _w, lid, _k in lock_regions(fi):
            out.add(lid)
        for callee, _ln in cg.edges(fi):
            out |= acquired(callee)
        acq_memo[fi.qname] = out
        return out

    # edge (outer, inner) -> (path, line, description)
    edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}

    def add_edge(a: str, b: str, fi: FuncInfo, lineno: int,
                 via: str = "") -> None:
        if a == b:
            # Same class-scoped id: either a reentrant RLock or two
            # *instances* of one class — neither is an ordering fact the
            # static graph can decide.  The runtime sanitizer owns it.
            return
        key = (a, b)
        if key not in edges:
            note = f" via call to {via}" if via else ""
            edges[key] = (fi.sf.path, lineno,
                          f"{a} -> {b} ({fi.sf.path}:{lineno}{note})")

    def scan_expr(fi: FuncInfo, expr: ast.AST,
                  held: List[str]) -> None:
        stack = [expr]
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.Lambda, ast.FunctionDef,
                              ast.AsyncFunctionDef)):
                continue
            if isinstance(n, ast.Call) and held:
                callee = _resolved_callee(cg, n, fi)
                if callee is not None and callee.qname != fi.qname:
                    for m in sorted(acquired(callee)):
                        for h in held:
                            add_edge(h, m, fi, n.lineno, callee.short)
            stack.extend(ast.iter_child_nodes(n))

    def scan_stmt(fi: FuncInfo, node: ast.AST, held: List[str]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return
        if isinstance(node, ast.With):
            ids: List[str] = []
            for it in node.items:
                scan_expr(fi, it.context_expr, held)
                if lockish_expr(it.context_expr):
                    ident = lock_identity(it.context_expr, fi)
                    if ident is not None:
                        ids.append(ident[0])
            for h in held:
                for lid in ids:
                    add_edge(h, lid, fi, node.lineno)
            for i in range(len(ids)):
                for j in range(i + 1, len(ids)):
                    add_edge(ids[i], ids[j], fi, node.lineno)
            for stmt in node.body:
                scan_stmt(fi, stmt, held + ids)
            return
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.stmt):
                scan_stmt(fi, child, held)
            else:
                scan_expr(fi, child, held)

    for qname in sorted(cg.functions):
        fi = cg.functions[qname]
        for stmt in fi.node.body:
            scan_stmt(fi, stmt, [])

    # Strongly connected components of the acquisition digraph: any SCC
    # with >= 2 locks means two opposite-order paths exist somewhere.
    adj: Dict[str, List[str]] = {}
    for (a, b) in edges:
        adj.setdefault(a, []).append(b)
        adj.setdefault(b, [])
    for k in adj:
        adj[k].sort()
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    sccs: List[List[str]] = []
    counter = [0]

    def strongconnect(v: str) -> None:  # iterative Tarjan
        work = [(v, 0)]
        while work:
            node, pi = work[-1]
            if pi == 0:
                index[node] = low[node] = counter[0]
                counter[0] += 1
                stack.append(node)
                on_stack.add(node)
            advanced = False
            for i in range(pi, len(adj[node])):
                w = adj[node][i]
                if w not in index:
                    work[-1] = (node, i + 1)
                    work.append((w, 0))
                    advanced = True
                    break
                if w in on_stack:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                if len(comp) > 1:
                    sccs.append(sorted(comp))
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])

    for v in sorted(adj):
        if v not in index:
            strongconnect(v)

    for comp in sorted(sccs):
        members = set(comp)
        internal = sorted(
            edges[k][2] for k in edges
            if k[0] in members and k[1] in members
        )
        locs = sorted(
            (edges[k][0], edges[k][1]) for k in edges
            if k[0] in members and k[1] in members
        )
        path, line = locs[0]
        shown = "; ".join(internal[:6])
        if len(internal) > 6:
            shown += f"; ... {len(internal) - 6} more"
        yield Finding(
            "G008", path, line,
            f"lock-order cycle among {{{', '.join(comp)}}}: {shown} — "
            "two threads taking these locks in opposite orders can "
            "deadlock",
            "pick one global order (docs/concurrency.md) and release "
            "the outer lock before any path that re-enters the other; "
            "GUBER_SANITIZERS=1 catches the dynamic counterpart with "
            "both stacks",
        )


register(Rule(
    "G008", "lock-order cycle",
    "The package-wide static lock acquisition graph (nested with-blocks "
    "plus lock sets of resolved callees) contains a cycle: two locks "
    "are taken in opposite nesting orders somewhere.",
    "One global lock order per docs/concurrency.md; never call back "
    "into another locked subsystem while holding your own lock.",
    _g008,
))


# ----------------------------------------------------------------------
# G009 — cross-thread shared mutable state without a lock
# ----------------------------------------------------------------------
_MUTATOR_METHODS = {"append", "appendleft", "add", "remove", "discard",
                    "pop", "popleft", "clear", "update", "extend",
                    "insert", "setdefault"}
_THREADSAFE_TYPES = ("queue.", "threading.", "collections.deque",
                     "multiprocessing.")


def _thread_targets(cg: CallGraph, ci: ClassInfo,
                    tails: Tuple[str, ...]) -> List[FuncInfo]:
    """Entry points of background loops this class spawns, resolved from
    spawn call sites in any of its methods."""
    out: List[FuncInfo] = []
    for m in ci.methods.values():
        for node in ast.walk(m.node):
            if not isinstance(node, ast.Call):
                continue
            parts = qual_parts(node.func)
            if not parts or parts[-1] not in tails:
                continue
            target_expr: Optional[ast.AST] = None
            if parts[-1] == "Thread":
                canonical = cg.canonical(node.func, m)
                if canonical != "threading.Thread":
                    continue
                for kw in node.keywords:
                    if kw.arg == "target":
                        target_expr = kw.value
                if target_expr is None and len(node.args) > 1:
                    target_expr = node.args[1]
            else:
                if node.args:
                    target_expr = node.args[0]
                for kw in node.keywords:
                    if kw.arg in ("target", "factory"):
                        target_expr = kw.value
            if target_expr is None:
                continue
            fi = cg.callable_target(target_expr, m)
            if fi is not None and fi.cls is ci:
                out.append(fi)
    return out


def _same_class_closure(cg: CallGraph, ci: ClassInfo,
                        roots: List[FuncInfo]) -> Set[str]:
    seen: Set[str] = set()
    stack = list(roots)
    while stack:
        fi = stack.pop()
        if fi.qname in seen:
            continue
        seen.add(fi.qname)
        for callee, _ln in cg.edges(fi):
            if callee.cls is ci and callee.qname not in seen:
                stack.append(callee)
    return seen


class _Access:
    __slots__ = ("attr", "write", "lineno", "guarded", "const_write",
                 "fi")

    def __init__(self, attr, write, lineno, guarded, const_write, fi):
        self.attr = attr
        self.write = write
        self.lineno = lineno
        self.guarded = guarded
        self.const_write = const_write
        self.fi = fi


def _attr_accesses(fi: FuncInfo) -> List[_Access]:
    """Every ``self.X`` touch in fi (nested defs included — closures run
    on the same thread as their caller), tagged with whether it sits
    lexically inside a ``with <lock>:`` region."""
    out: List[_Access] = []

    def self_attr(node: ast.AST) -> Optional[str]:
        if isinstance(node, ast.Attribute) and \
                isinstance(node.value, ast.Name) and \
                node.value.id == "self":
            return node.attr
        return None

    def is_const(v: ast.AST) -> bool:
        if isinstance(v, ast.UnaryOp):
            v = v.operand
        return isinstance(v, ast.Constant)

    def scan(node: ast.AST, guarded: bool) -> None:
        if isinstance(node, ast.Lambda):
            return
        if isinstance(node, ast.With):
            g2 = guarded or any(
                lockish_expr(it.context_expr) for it in node.items
            )
            for it in node.items:
                scan(it.context_expr, guarded)
            for stmt in node.body:
                scan(stmt, g2)
            return
        if isinstance(node, ast.Assign):
            for t in node.targets:
                a = self_attr(t)
                if a is not None:
                    out.append(_Access(a, True, node.lineno, guarded,
                                       is_const(node.value), fi))
                elif isinstance(t, ast.Subscript):
                    a = self_attr(t.value)
                    if a is not None:
                        out.append(_Access(a, True, node.lineno, guarded,
                                           False, fi))
            scan(node.value, guarded)
            for t in node.targets:
                if not (self_attr(t) or isinstance(t, ast.Subscript)):
                    scan(t, guarded)
            return
        if isinstance(node, ast.AugAssign):
            a = self_attr(node.target)
            if a is None and isinstance(node.target, ast.Subscript):
                a = self_attr(node.target.value)
            if a is not None:
                out.append(_Access(a, True, node.lineno, guarded, False,
                                   fi))
            scan(node.value, guarded)
            return
        if isinstance(node, ast.Delete):
            for t in node.targets:
                a = self_attr(t)
                if a is None and isinstance(t, ast.Subscript):
                    a = self_attr(t.value)
                if a is not None:
                    out.append(_Access(a, True, node.lineno, guarded,
                                       False, fi))
            return
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in _MUTATOR_METHODS:
            a = self_attr(node.func.value)
            if a is not None:
                out.append(_Access(a, True, node.lineno, guarded, False,
                                   fi))
            for c in list(node.args) + [kw.value for kw in node.keywords]:
                scan(c, guarded)
            return
        a = self_attr(node)
        if a is not None:
            out.append(_Access(a, False, node.lineno, guarded, False, fi))
            return
        for child in ast.iter_child_nodes(node):
            scan(child, guarded)

    for stmt in fi.node.body:
        scan(stmt, False)
    return out


def _g009(project: Project) -> Iterable[Finding]:
    cg = CallGraph.of(project)
    for qname in sorted(cg.classes):
        ci = cg.classes[qname]
        targets = _thread_targets(
            cg, ci, ("spawn_supervised_thread", "Thread"))
        if not targets:
            continue
        loop_set = _same_class_closure(cg, ci, targets)
        inside: Dict[str, List[_Access]] = {}
        outside: Dict[str, List[_Access]] = {}
        for m in ci.methods.values():
            fis = [m] + [c for c in m.children.values()]
            in_loop = m.qname in loop_set
            for f in fis:
                for acc in _attr_accesses(f):
                    if in_loop or f.qname in loop_set:
                        inside.setdefault(acc.attr, []).append(acc)
                    elif m.name not in ("__init__", "__post_init__"):
                        outside.setdefault(acc.attr, []).append(acc)
        loop_names = ", ".join(sorted({t.short for t in targets}))
        for attr in sorted(set(inside) & set(outside)):
            if attr.startswith("metric_"):
                continue  # documented single-writer telemetry convention
            t = ci.attr_types.get(attr, "")
            if t.startswith(_THREADSAFE_TYPES):
                continue
            in_writes = [a for a in inside[attr] if a.write]
            if not in_writes:
                continue
            all_writes = in_writes + [a for a in outside[attr] if a.write]
            if all_writes and all(a.const_write for a in all_writes):
                continue  # monotonic flag publication (_running = False)
            in_unguarded = [a for a in in_writes if not a.guarded]
            out_unguarded = [a for a in outside[attr] if not a.guarded]
            if not in_unguarded and not out_unguarded:
                continue  # both sides lock-guarded
            racy = min(in_unguarded or in_writes,
                       key=lambda a: a.lineno)
            others = sorted({a.lineno for a in outside[attr]})[:4]
            yield Finding(
                "G009", ci.sf.path, racy.lineno,
                f"self.{attr} written from background-thread target "
                f"'{loop_names}' and touched from other methods of "
                f"{ci.name} (lines {', '.join(map(str, others))}) with "
                "no lock on at least one side — a cross-thread data "
                "race",
                "guard both sides with the owning lock, or mark the "
                "field single-writer-by-design with "
                "# guber: allow-g009(reason)",
            )


register(Rule(
    "G009", "unguarded cross-thread shared state",
    "An attribute written inside a spawn_supervised_thread / "
    "threading.Thread target (or its same-class callees) and touched "
    "from other methods, with no lock on at least one side.",
    "Every field shared with a background thread is lock-guarded or "
    "explicitly declared single-writer with allow-g009(reason).",
    _g009,
))


# ----------------------------------------------------------------------
# G010 — deadline taint into supervised background queues
# ----------------------------------------------------------------------
_STORE_METHODS = {"append", "appendleft", "add", "put", "put_nowait",
                  "insert", "setdefault"}


def _deadline_classes(cg: CallGraph) -> Set[str]:
    out: Set[str] = set()
    for ci in cg.classes.values():
        for stmt in ci.node.body:
            name = None
            if isinstance(stmt, ast.AnnAssign) and \
                    isinstance(stmt.target, ast.Name):
                name = stmt.target.id
            elif isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name):
                name = stmt.targets[0].id
            if name == "deadline":
                out.add(ci.qname)
                out.add(ci.name)
                break
    return out


def _g010(project: Project) -> Iterable[Finding]:
    cg = CallGraph.of(project)
    dl_classes = _deadline_classes(cg)
    if not dl_classes:
        return
    for qname in sorted(cg.classes):
        ci = cg.classes[qname]
        targets = _thread_targets(
            cg, ci, ("spawn_supervised", "spawn_supervised_thread"))
        if not targets:
            continue
        loop_set = _same_class_closure(cg, ci, targets)
        loop_names = ", ".join(sorted({t.short for t in targets}))
        # Containers the background loop actually drains.
        loop_attrs: Set[str] = set()
        for t_qname in loop_set:
            fi = cg.functions.get(t_qname)
            if fi is None:
                continue
            for acc in _attr_accesses(fi):
                loop_attrs.add(acc.attr)
        if not loop_attrs:
            continue
        for m in sorted(ci.methods.values(), key=lambda f: f.qname):
            if m.qname in loop_set or m.name == "__init__":
                continue
            yield from _g010_scan_method(cg, ci, m, dl_classes,
                                         loop_attrs, loop_names)


def _g010_scan_method(cg, ci, m, dl_classes, loop_attrs,
                      loop_names) -> Iterable[Finding]:
    tainted: Set[str] = set()
    ann_of: Dict[str, Optional[str]] = {}
    a = m.node.args
    for arg in (a.posonlyargs + a.args + a.kwonlyargs):
        if arg.annotation is None:
            continue
        t = cg._annotation_type(arg.annotation, m)
        if t is not None and (t in dl_classes
                              or t.split(".")[-1] in dl_classes):
            tainted.add(arg.arg)
            ann_of[arg.arg] = t

    def clones_tainted(value: ast.AST) -> Optional[str]:
        """Name of the tainted source when value is a clone of it:
        Cls(**vars(x)) / replace(x, ...) with no deadline= override."""
        if not isinstance(value, ast.Call):
            return None
        for kw in value.keywords:
            if kw.arg == "deadline":
                return None  # explicit deadline: author decided
            if kw.arg is None and isinstance(kw.value, ast.Call):
                inner = kw.value
                if qual_parts(inner.func)[-1:] == ["vars"] and \
                        inner.args and \
                        isinstance(inner.args[0], ast.Name) and \
                        inner.args[0].id in tainted:
                    return inner.args[0].id
        parts = qual_parts(value.func)
        if parts and parts[-1] == "replace" and value.args and \
                isinstance(value.args[0], ast.Name) and \
                value.args[0].id in tainted:
            return value.args[0].id
        return None

    # Events in source order: a linear pass is exact enough for the
    # stamp-then-store idiom this rule encodes (queue_hit's fix).
    events: List[Tuple[int, int, str, object]] = []
    for node in ast.walk(m.node):
        lineno = getattr(node, "lineno", None)
        if lineno is None:
            continue
        col = getattr(node, "col_offset", 0)
        if isinstance(node, ast.Assign):
            t = node.targets[0] if len(node.targets) == 1 else None
            if isinstance(t, ast.Name):
                events.append((lineno, col, "assign", (t.id, node.value)))
            elif isinstance(t, ast.Attribute) and t.attr == "deadline" \
                    and isinstance(t.value, ast.Name):
                events.append((lineno, col, "clear", t.value.id))
            elif isinstance(t, ast.Subscript):
                sa = t.value
                if isinstance(sa, ast.Attribute) and \
                        isinstance(sa.value, ast.Name) and \
                        sa.value.id == "self" and \
                        isinstance(node.value, ast.Name):
                    events.append((lineno, col, "store",
                                   (sa.attr, node.value.id)))
        elif isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in _STORE_METHODS:
            recv = node.func.value
            if isinstance(recv, ast.Attribute) and \
                    isinstance(recv.value, ast.Name) and \
                    recv.value.id == "self":
                for argv in node.args:
                    if isinstance(argv, ast.Name):
                        events.append((lineno, node.col_offset, "store",
                                       (recv.attr, argv.id)))
    events.sort(key=lambda e: (e[0], e[1]))
    for lineno, _col, kind, payload in events:
        if kind == "clear":
            tainted.discard(payload)
        elif kind == "assign":
            name, value = payload
            if isinstance(value, ast.Name) and value.id in tainted:
                tainted.add(name)
            else:
                src = clones_tainted(value)
                if src is not None:
                    tainted.add(name)
                    ann_of[name] = ann_of.get(src)
                else:
                    tainted.discard(name)
        elif kind == "store":
            attr, name = payload
            if name in tainted and attr in loop_attrs:
                t = ann_of.get(name) or "a deadline-carrying type"
                yield Finding(
                    "G010", m.sf.path, lineno,
                    f"'{name}' ({t} — carries the caller's admission "
                    f"deadline) stored into self.{attr}, which the "
                    f"supervised loop '{loop_names}' drains: the "
                    "background path inherits a serving-path deadline "
                    "and sheds or expires asynchronously (the PR 17 "
                    "federation bug class)",
                    "clear it first (obj.deadline = None) or store a "
                    "deadline-free clone before enqueueing "
                    "(service/global_manager.queue_hit shows the "
                    "pattern)",
                )


register(Rule(
    "G010", "deadline taint into background queues",
    "An object whose type carries an admission `deadline` field is "
    "stored, deadline intact, into a container drained by a "
    "spawn_supervised(_thread) loop.",
    "Background work never inherits a serving-path deadline: clear it "
    "or clone without it before enqueueing.",
    _g010,
))
