"""guberlint — the project's AST-based invariant checker.

Six bug classes this repo has already shipped (and hand-fixed, one PR at
a time) are statically detectable properties of the source tree.  This
package locks them down:

==== =============================================================
G001 device-sync primitive inside a ``@hot_path`` serving function
G002 blocking call in ``async def`` / ``await`` under a held lock
G003 fire-and-forget asyncio task (handle discarded)
G004 ``GUBER_*`` env read outside the config registry / undocumented
G005 Prometheus metric names drifting from ``docs/prometheus.md``
G006 impure host calls inside jit/shard_map-traced functions
==== =============================================================

Pure stdlib on purpose: ``python -m gubernator_tpu.analysis`` and the
tier-1 test that wraps it never import jax (or any third-party module),
so the gate runs anywhere in well under a second.

Suppression: ``# guber: allow-G003(reason)`` on the finding's line or
the line above.  The reason is mandatory — an empty one leaves the
finding live.  Grandfathered findings live in a checked-in baseline
(``.guberlint-baseline.json``); see docs/static-analysis.md.
"""

from gubernator_tpu.analysis.core import (
    Finding,
    LintResult,
    Project,
    Rule,
    RULES,
    load_baseline,
    load_project,
    run_project,
    write_baseline,
)
from gubernator_tpu.analysis import rules as _rules  # noqa: F401  (registers)

__all__ = [
    "Finding",
    "LintResult",
    "Project",
    "Rule",
    "RULES",
    "load_baseline",
    "load_project",
    "run_project",
    "write_baseline",
]
