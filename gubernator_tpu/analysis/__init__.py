"""guberlint — the project's AST-based invariant checker.

Ten bug classes this repo has already shipped (and hand-fixed, one PR
at a time) are statically detectable properties of the source tree.
This package locks them down:

==== =============================================================
G001 device-sync / blocking syscall in (or reachable from) @hot_path
G002 blocking call in ``async def`` / ``await`` under a held lock
G003 fire-and-forget asyncio task (handle discarded)
G004 ``GUBER_*`` env read outside the config registry / undocumented
G005 Prometheus metric names drifting from ``docs/prometheus.md``
G006 impure host calls inside jit/shard_map-traced functions
G007 blocking call reachable while a threading lock is held
G008 lock-order cycle in the package-wide acquisition graph
G009 unguarded cross-thread shared state (background-thread targets)
G010 admission-deadline taint into supervised background queues
==== =============================================================

Since v2 the checker is *interprocedural*: analysis/callgraph.py builds
a package-wide call graph (module-qualified def/method resolution,
best-effort on dynamic dispatch, no edge when unresolvable), and G001,
G002, G007, and G008 propagate their scope taint through resolved
callees.  The runtime twin — lock-order and SPSC single-writer
sanitizers behind ``GUBER_SANITIZERS=1`` (utils/sanitize.py) — covers
the dynamic-dispatch half the static graph cannot see.

Pure stdlib on purpose: ``python -m gubernator_tpu.analysis`` and the
tier-1 test that wraps it never import jax (or any third-party module),
so the gate runs anywhere in well under a second.

Suppression: ``# guber: allow-G003(reason)`` on the finding's line or
the line above (rule id case-insensitive).  The reason is mandatory —
an empty one leaves the finding live.  Grandfathered findings live in a
checked-in baseline (``.guberlint-baseline.json``); see
docs/static-analysis.md.
"""

from gubernator_tpu.analysis.core import (
    Finding,
    LintResult,
    Project,
    Rule,
    RULES,
    load_baseline,
    load_project,
    run_project,
    write_baseline,
)
from gubernator_tpu.analysis import rules as _rules  # noqa: F401  (registers)
from gubernator_tpu.analysis import concurrency as _conc  # noqa: F401

__all__ = [
    "Finding",
    "LintResult",
    "Project",
    "Rule",
    "RULES",
    "load_baseline",
    "load_project",
    "run_project",
    "write_baseline",
]
