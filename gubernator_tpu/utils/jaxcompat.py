"""JAX cross-version shims.

The toolchain pin floats between container builds: newer JAX exposes
``jax.shard_map`` (with the ``check_vma`` replication-check kwarg) while
the 0.4.x line ships it as ``jax.experimental.shard_map.shard_map``
(kwarg ``check_rep``).  The mesh data planes call through here so one
source tree runs on both.
"""

from __future__ import annotations

import gubernator_tpu.jaxinit  # noqa: F401  (x64 + compile cache before jax use)
import jax

_NEW = getattr(jax, "shard_map", None)
if _NEW is None:
    from jax.experimental.shard_map import shard_map as _OLD
else:
    _OLD = None


def pallas_tpu_compiler_params(**kwargs):
    """Mosaic compiler params under either name: ``pltpu.CompilerParams``
    (new) or ``pltpu.TPUCompilerParams`` (0.4.x line)."""
    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None) or getattr(
        pltpu, "TPUCompilerParams"
    )
    return cls(**kwargs)


def enable_x64(enabled: bool = True):
    """Context manager toggling x64 for traces inside it: newer JAX has
    ``jax.enable_x64(bool)``, the 0.4.x line only the
    ``jax.experimental.enable_x64/disable_x64`` pair."""
    if hasattr(jax, "enable_x64"):
        return jax.enable_x64(enabled)
    from jax.experimental import disable_x64 as _dis
    from jax.experimental import enable_x64 as _en

    return _en() if enabled else _dis()


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = False):
    """``jax.shard_map`` under either API generation (see module doc)."""
    if _NEW is not None:
        return _NEW(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
    return _OLD(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check_vma,
    )
