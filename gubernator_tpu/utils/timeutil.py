"""Millisecond clocks and Gregorian calendar interval math.

Host-side equivalent of the reference's ``interval.go:74-148``
(``GregorianDuration`` / ``GregorianExpiration``).  Device kernels never
read clocks — time is always an input (see SURVEY.md §7 "Hard parts").

Like the reference, calendar math uses the process-local timezone and the
"end of interval" is the last representable millisecond of the interval
(interval start of the *next* interval minus 1 ms).

Deviation (conscious fix, documented per SURVEY.md §2.4 guidance): the
reference's month/year ``GregorianDuration`` mixes nanosecond and
millisecond units (``interval.go:99,105`` — ``end.UnixNano() -
begin.UnixNano()/1000000``). We return the intended value: the interval
length in milliseconds.
"""

from __future__ import annotations

import time
from datetime import datetime, timedelta

from gubernator_tpu.types import (
    GREGORIAN_DAYS,
    GREGORIAN_HOURS,
    GREGORIAN_MINUTES,
    GREGORIAN_MONTHS,
    GREGORIAN_WEEKS,
    GREGORIAN_YEARS,
)


class GregorianError(ValueError):
    pass


def now_ms() -> int:
    """Wall clock in epoch milliseconds (reference lrucache.go:106-108)."""
    return time.time_ns() // 1_000_000


def _interval_bounds(now_ms_: int, d: int) -> tuple[int, int]:
    """(start_ms, next_start_ms) of the Gregorian interval containing now."""
    dt = datetime.fromtimestamp(now_ms_ / 1000.0)  # local time, like Go's now.Location()
    if d == GREGORIAN_MINUTES:
        start = dt.replace(second=0, microsecond=0)
        nxt = start + timedelta(minutes=1)
    elif d == GREGORIAN_HOURS:
        start = dt.replace(minute=0, second=0, microsecond=0)
        nxt = start + timedelta(hours=1)
    elif d == GREGORIAN_DAYS:
        start = dt.replace(hour=0, minute=0, second=0, microsecond=0)
        nxt = start + timedelta(days=1)
    elif d == GREGORIAN_WEEKS:
        # The reference left weeks as a TODO ("consider making a PR!",
        # interval.go:132); implemented here as ISO-8601 weeks — the
        # interval runs Monday 00:00:00.000 through Sunday 23:59:59.999.
        # DELIBERATE wire-visible divergence (documented in README
        # "Features"): the reference answers GregorianWeeks with a
        # calendar error, this implementation rate-limits.
        start = dt.replace(hour=0, minute=0, second=0, microsecond=0)
        start -= timedelta(days=dt.weekday())
        nxt = start + timedelta(days=7)
    elif d == GREGORIAN_MONTHS:
        start = dt.replace(day=1, hour=0, minute=0, second=0, microsecond=0)
        if start.month == 12:
            nxt = start.replace(year=start.year + 1, month=1)
        else:
            nxt = start.replace(month=start.month + 1)
    elif d == GREGORIAN_YEARS:
        start = dt.replace(month=1, day=1, hour=0, minute=0, second=0, microsecond=0)
        nxt = start.replace(year=start.year + 1)
    else:
        raise GregorianError(
            "behavior DURATION_IS_GREGORIAN is set; but `duration` is not a "
            "valid gregorian interval"
        )
    return int(start.timestamp() * 1000), int(nxt.timestamp() * 1000)


def gregorian_duration(now_ms_: int, d: int) -> int:
    """Entire duration of the Gregorian interval in ms (interval.go:84-109)."""
    if d == GREGORIAN_MINUTES:
        return 60_000
    if d == GREGORIAN_HOURS:
        return 3_600_000
    if d == GREGORIAN_DAYS:
        return 86_400_000
    start, nxt = _interval_bounds(now_ms_, d)  # raises for invalid d;
    # weeks/months/years computed from the interval bounds
    return nxt - start


def gregorian_expiration(now_ms_: int, d: int) -> int:
    """End of the current Gregorian interval in epoch ms (interval.go:117-148).

    E.g. for minutes at 11:20:10 → 11:20:59.999 as epoch ms.
    """
    _, nxt = _interval_bounds(now_ms_, d)
    return nxt - 1
