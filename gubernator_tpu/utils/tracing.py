"""Distributed tracing: spans, W3C TraceContext propagation, profiler hooks.

The reference instruments every layer with OpenTelemetry (holster
``tracing.StartNamedScope`` wrappers — gubernator.go:315,396,589,
peer_client.go:351-362 — plus the otelgrpc server/client stats handlers,
daemon.go:109-125) and piggybacks W3C TraceContext across peers inside
``RateLimitReq.Metadata`` via ``MetadataCarrier``
(metadata_carrier.go:19-38, peer_client.go:140-141,359-360, extracted
owner-side at gubernator.go:502-504).

This build ships its own lightweight tracer rather than depending on the
OpenTelemetry SDK (only the API package exists in the image): spans are
plain objects threaded through ``contextvars`` (correct across asyncio
tasks), exporters are pluggable, and the wire format is the standard W3C
``traceparent`` header so traces interoperate with any OTEL-instrumented
reference peer.  When the OpenTelemetry SDK *is* importable, installing
:class:`OtelBridgeExporter` re-emits finished spans through it.

TPU twist: :func:`profile_annotation` wraps device work in
``jax.profiler.TraceAnnotation`` so engine ticks show up as named ranges
in TensorBoard/XProf captures alongside the service-level spans.
"""

from __future__ import annotations

import contextlib
import contextvars
import os
import random
import re
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional

from gubernator_tpu.utils import sanitize

TRACEPARENT = "traceparent"
# W3C trace-context: version 00 is exactly 4 fields; a higher version may
# append fields after the flags, and receivers must parse the first four
# and ignore the rest (the spec's forward-compatibility rule).
_TP_RE = re.compile(
    r"^([0-9a-f]{2})-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})($|-)"
)

FLAG_SAMPLED = 0x01


@dataclass(frozen=True)
class SpanContext:
    """Identity of one span: what crosses process boundaries."""

    trace_id: str  # 32 lowercase hex chars, non-zero
    span_id: str   # 16 lowercase hex chars, non-zero
    flags: int = FLAG_SAMPLED

    @property
    def sampled(self) -> bool:
        return bool(self.flags & FLAG_SAMPLED)

    def to_traceparent(self) -> str:
        return f"00-{self.trace_id}-{self.span_id}-{self.flags:02x}"


@dataclass
class Span:
    """One timed operation; finished spans go to the tracer's exporters."""

    name: str
    context: SpanContext
    parent_span_id: Optional[str] = None
    start_ns: int = 0
    end_ns: int = 0
    attributes: Dict[str, object] = field(default_factory=dict)
    events: List[tuple] = field(default_factory=list)
    error: Optional[str] = None

    @property
    def trace_id(self) -> str:
        return self.context.trace_id

    @property
    def span_id(self) -> str:
        return self.context.span_id

    @property
    def duration_ms(self) -> float:
        return (self.end_ns - self.start_ns) / 1e6

    def set_attribute(self, key: str, value: object) -> None:
        self.attributes[key] = value

    def add_event(self, name: str, attributes: Optional[Dict] = None) -> None:
        """Annotate a point in time (the reference's span.AddEvent calls on
        algorithm branches, algorithms.go:57-66,163-174)."""
        self.events.append((time.time_ns(), name, attributes or {}))

    def record_exception(self, exc: BaseException) -> None:
        self.error = f"{type(exc).__name__}: {exc}"


def _rand_hex(n_bytes: int) -> str:
    # random.getrandbits is ~20× cheaper than os.urandom per span and trace
    # ids need uniqueness, not cryptographic strength.
    return format(random.getrandbits(n_bytes * 8), f"0{n_bytes * 2}x")


class SpanExporter:
    """Exporter interface: receives each finished span."""

    def export(self, span: Span) -> None:  # pragma: no cover - interface
        raise NotImplementedError


class InMemoryExporter(SpanExporter):
    """Ring buffer of finished spans (tests + /debug introspection)."""

    def __init__(self, cap: int = 4096):
        self.spans: deque = deque(maxlen=cap)
        self._lock = sanitize.lock("InMemoryExporter._lock")

    def export(self, span: Span) -> None:
        with self._lock:
            self.spans.append(span)

    def by_trace(self, trace_id: str) -> List[Span]:
        with self._lock:
            return [s for s in self.spans if s.trace_id == trace_id]

    def by_name(self, name: str) -> List[Span]:
        with self._lock:
            return [s for s in self.spans if s.name == name]

    def clear(self) -> None:
        with self._lock:
            self.spans.clear()


class OtelBridgeExporter(SpanExporter):
    """Re-emit finished spans through an OpenTelemetry *SDK span exporter*
    (OTLP, Jaeger, console, …) when the host has the SDK installed (the
    image ships only the API package, which records nothing).

    Spans are rebuilt as ``ReadableSpan``s carrying the ORIGINAL trace id,
    span id, and parent link, so the exported trace tree is identical to
    the in-process one and interleaves correctly with spans emitted by
    OTEL-instrumented reference peers sharing the trace."""

    def __init__(self, otel_span_exporter):
        # Import here: constructing the bridge without the SDK should fail
        # loudly at install time, not silently per span.
        from opentelemetry.sdk.trace import ReadableSpan  # noqa: F401
        from opentelemetry.sdk.resources import Resource
        from opentelemetry.sdk.util.instrumentation import InstrumentationScope

        self._exporter = otel_span_exporter
        # Real SDK encoders dereference resource/scope attributes — they
        # must be concrete objects, not None; both are per-process constants.
        self._resource = Resource.create({"service.name": "gubernator-tpu"})
        self._scope = InstrumentationScope("gubernator_tpu")

    def export(self, span: Span) -> None:
        from opentelemetry import trace as ot
        from opentelemetry.sdk.trace import ReadableSpan

        ctx = ot.SpanContext(
            int(span.trace_id, 16),
            int(span.span_id, 16),
            is_remote=False,
            trace_flags=ot.TraceFlags(span.context.flags),
        )
        parent = (
            ot.SpanContext(
                int(span.trace_id, 16),
                int(span.parent_span_id, 16),
                is_remote=False,
            )
            if span.parent_span_id
            else None
        )
        rs = ReadableSpan(
            name=span.name,
            context=ctx,
            parent=parent,
            resource=self._resource,
            instrumentation_scope=self._scope,
            attributes=dict(span.attributes),
            start_time=span.start_ns,
            end_time=span.end_ns,
        )
        self._exporter.export([rs])


class Tracer:
    """Span factory + context manager + sampler.

    Sampling follows the OTEL env convention (``OTEL_TRACES_SAMPLER``:
    always_on / always_off / traceidratio with ``OTEL_TRACES_SAMPLER_ARG``),
    the same surface the reference's tracing.InitTracing reads.  Unsampled
    flows still *propagate* context (flags=00) but record nothing.
    """

    def __init__(self, ratio: Optional[float] = None):
        if ratio is None:
            sampler = os.environ.get("OTEL_TRACES_SAMPLER", "always_on")
            if sampler == "always_off":
                ratio = 0.0
            elif sampler == "traceidratio":
                try:
                    ratio = float(os.environ.get("OTEL_TRACES_SAMPLER_ARG", "1"))
                except ValueError:
                    ratio = 1.0
            else:
                ratio = 1.0
        self.ratio = ratio
        self.exporters: List[SpanExporter] = []
        self._current: contextvars.ContextVar[Optional[Span]] = (
            contextvars.ContextVar("guber_span", default=None)
        )

    # -- context ------------------------------------------------------
    def current_span(self) -> Optional[Span]:
        return self._current.get()

    def current_context(self) -> Optional[SpanContext]:
        s = self._current.get()
        return s.context if s is not None else None

    # -- span lifecycle ----------------------------------------------
    def _sample(self) -> bool:
        if self.ratio >= 1.0:
            return True
        if self.ratio <= 0.0:
            return False
        return random.random() < self.ratio

    @contextlib.contextmanager
    def span(
        self,
        name: str,
        attributes: Optional[Dict[str, object]] = None,
        parent: Optional[SpanContext] = None,
        root: bool = False,
    ) -> Iterator[Span]:
        """Start a span as the current one; ends (and exports) on exit.

        ``parent`` overrides the ambient parent — pass the context extracted
        from an incoming request's metadata to continue a remote trace.
        ``root=True`` ignores the ambient parent and starts a fresh trace —
        for long-lived background tasks (batch loops, sync windows) that
        inherited an arbitrary caller's contextvars at task creation.
        """
        if parent is None and not root:
            parent = self.current_context()
        if parent is not None:
            trace_id = parent.trace_id
            flags = parent.flags
            parent_id: Optional[str] = parent.span_id
        else:
            trace_id = _rand_hex(16)
            flags = FLAG_SAMPLED if self._sample() else 0
            parent_id = None
        span = Span(
            name=name,
            context=SpanContext(trace_id, _rand_hex(8), flags),
            parent_span_id=parent_id,
            start_ns=time.time_ns(),
            attributes=dict(attributes or {}),
        )
        token = self._current.set(span)
        try:
            yield span
        except BaseException as exc:
            span.record_exception(exc)
            raise
        finally:
            self._current.reset(token)
            span.end_ns = time.time_ns()
            if span.context.sampled:
                for e in self.exporters:
                    e.export(span)

    def start_detached(
        self,
        name: str,
        attributes: Optional[Dict[str, object]] = None,
        parent: Optional[SpanContext] = None,
    ) -> Span:
        """Start a span WITHOUT making it current — for batch fan-in points
        where many remote parents land in one handler call.  Finish with
        :meth:`finish`."""
        if parent is None:
            parent = self.current_context()
        if parent is not None:
            ctx = SpanContext(parent.trace_id, _rand_hex(8), parent.flags)
            parent_id: Optional[str] = parent.span_id
        else:
            flags = FLAG_SAMPLED if self._sample() else 0
            ctx = SpanContext(_rand_hex(16), _rand_hex(8), flags)
            parent_id = None
        return Span(
            name=name,
            context=ctx,
            parent_span_id=parent_id,
            start_ns=time.time_ns(),
            attributes=dict(attributes or {}),
        )

    def finish(self, span: Span) -> None:
        span.end_ns = time.time_ns()
        if span.context.sampled:
            for e in self.exporters:
                e.export(span)

    # -- propagation (W3C TraceContext over RateLimitReq.metadata) ----
    def inject(self, metadata: Dict[str, str]) -> None:
        """Write the current context as a ``traceparent`` entry
        (peer_client.go:140-141: carried per request so peers continue the
        trace)."""
        ctx = self.current_context()
        if ctx is not None:
            metadata[TRACEPARENT] = ctx.to_traceparent()

    @staticmethod
    def extract(metadata: Optional[Dict[str, str]]) -> Optional[SpanContext]:
        """Parse a ``traceparent`` entry; None on absence or malformation
        (malformed context starts a fresh trace, per the W3C spec)."""
        if not metadata:
            return None
        m = _TP_RE.match(metadata.get(TRACEPARENT, ""))
        if not m:
            return None
        version, trace_id, span_id, flags, tail = m.groups()
        if version == "ff" or int(trace_id, 16) == 0 or int(span_id, 16) == 0:
            return None
        if version == "00" and tail:
            return None  # version 00 allows no trailing fields
        return SpanContext(trace_id, span_id, int(flags, 16))


# ---------------------------------------------------------------------
# Process-global tracer (the reference uses the otel global provider).
# ---------------------------------------------------------------------
_tracer = Tracer()


def get_tracer() -> Tracer:
    return _tracer


def span(name, attributes=None, parent=None, root=False):
    return _tracer.span(name, attributes, parent, root)


def current_span() -> Optional[Span]:
    return _tracer.current_span()


def inject(metadata: Dict[str, str]) -> None:
    _tracer.inject(metadata)


def extract(metadata: Optional[Dict[str, str]]) -> Optional[SpanContext]:
    return Tracer.extract(metadata)


def add_exporter(exporter: SpanExporter) -> None:
    _tracer.exporters.append(exporter)


def remove_exporter(exporter: SpanExporter) -> None:
    if exporter in _tracer.exporters:
        _tracer.exporters.remove(exporter)


def enabled() -> bool:
    """Whether any exporter is installed.  Service hot paths gate their
    instrumentation on this so an untraced daemon pays nothing per request
    (the reference's no-op global otel provider has the same effect)."""
    return bool(_tracer.exporters)


def maybe_span(name, attributes=None, parent=None, root=False):
    """``span(...)`` when tracing is enabled, else a free null context."""
    if not _tracer.exporters:
        return contextlib.nullcontext()
    return _tracer.span(name, attributes, parent, root)


def profile_annotation(name: str):
    """A ``jax.profiler.TraceAnnotation`` naming device work in XProf
    captures; degrades to a no-op when the profiler is unavailable."""
    try:
        import gubernator_tpu.jaxinit  # noqa: F401  (x64 + cache before jax use)
        import jax.profiler

        return jax.profiler.TraceAnnotation(name)
    except Exception:  # pragma: no cover - profiler always present with jax
        return contextlib.nullcontext()
